"""Health observatory: metrics time-series + saturation attribution.

Everything ROADMAP item 3 needs for capacity tuning exists as declared
registries (channels, timeout budgets, the task supervisor, the jit
contracts, the race recorder) — but until this module the node could
only answer "what is happening right now": `/metrics` and
`node.metrics` are point-in-time snapshots, and the histograms are
cumulative-forever (a p99 from minute 1 pollutes hour 2). The
observatory closes both gaps:

- **Sampler.** A supervised task (`tasks.spawn`, owner ``node/health``,
  interval `SDTPU_HEALTH_INTERVAL_S`) spools DELTA-snapshots of every
  registered metric family into bounded per-series rings — counters
  become windowed rates, gauges become samples, histograms become
  windowed p50/p95/p99 via bucket-delta interpolation
  (`telemetry.Histogram.snapshot_delta`; the cumulative families are
  never reset, so `/metrics` keeps its meaning). The rings are
  declared `health.series` registry channels, so depth discipline
  applies to the observer itself.
- **Saturation engine.** On top of the freshest window it cross-reads
  the declared registries — channel depth/high-water vs declared
  capacity plus shed rate (channels.py), timeout firing rates
  (timeouts.py), store write-lock wait and commit latency, the task
  census vs the supervisor's ownership tree (tasks.py), the pipeline
  stage/retire stall split plus the flight recorder's per-batch bound
  attribution (`sd_pipeline_*`, flight.py), and the sanitizer/race
  violation counters — and emits a per-subsystem state
  (``ok | degraded | saturated``) with **bottleneck attribution**: the
  top-k resources driving the state, named by their declared registry
  name/owner/doc, with the evidence series inline.
- **Surfaces.** The `node.health` rspc query + ws subscription
  (coalesced newest-wins in the ws pump), periodic ``HealthSnapshot``
  events on the node event bus, the `sd_health_state{subsystem}`
  gauge family on `/metrics`, and the `tools/sd_top.py` live operator
  top.

Design constraints: stdlib + the registry modules only
(flags/telemetry/timeouts/channels/tasks/flight) — importable from
every layer without cycles and without jax. The engine reads metric
families ONLY through the `READS` table at the bottom of this module;
sdlint's telemetry pass fails the build on a `sd_*` literal here that
is not in `READS` (or not centrally registered), the same
static↔runtime parity discipline the span and channel registries get.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import channels, flags, tasks, telemetry, timeouts
from .telemetry import HEALTH_SAMPLES, HEALTH_STATE

__all__ = [
    "HealthMonitor", "READS", "STATES", "windowed_quantile",
    "validate_health_snapshot",
]

STATES = ("ok", "degraded", "saturated")

# -- state thresholds (documented in docs/architecture.md §Health) ----------
# Channel depth as a fraction of declared capacity that marks a
# consumer as falling behind (full = saturated outright).
DEPTH_DEGRADED_FRAC = 0.75
# Blocked-producer wait (windowed p99 of sd_chan_put_block_seconds) as
# a fraction of the channel's declared put_budget.
BLOCK_WAIT_DEGRADED_FRAC = 0.1
BLOCK_WAIT_SATURATED_FRAC = 0.5
# Store write-lock wait, windowed p99 seconds.
LOCK_WAIT_DEGRADED_S = 0.05
LOCK_WAIT_SATURATED_S = 0.5
# Store COMMIT latency, windowed p99 seconds.
COMMIT_DEGRADED_S = 1.0
# Group-commit end-to-end wait (enqueue → group COMMIT), windowed p99
# seconds. Healthy groups resolve in a few ms (one fsync shared across
# the group); tens of ms means the queue is deep or a batch body is
# slow inside the group — and the score carries the fraction of the
# declared store.actor.write budget burned per write.
GROUP_WAIT_DEGRADED_S = 0.25
GROUP_WAIT_SATURATED_S = 2.0
# Declared network budgets firing: any firing degrades; a sustained
# rate saturates (the peer/path is effectively down).
TIMEOUT_SATURATED_PER_S = 0.5
# Pipeline stall seconds accumulated per wall second (a dispatcher or
# retirer parked more than this fraction of the window).
PIPELINE_STALL_DEGRADED = 0.2
PIPELINE_STALL_SATURATED = 0.6
# Ring tail included per attribution entry ("evidence series inline").
EVIDENCE_POINTS = 32

# Subsystems that always carry a state, even when nothing is observed
# (operators diff states across polls; a key that appears only under
# load would read as a new failure mode).
BASE_SUBSYSTEMS = ("api", "jobs", "media", "ops", "p2p", "sanitize",
                   "store", "sync", "tasks")


def windowed_quantile(buckets: Sequence[float],
                      delta_counts: Sequence[int],
                      q: float) -> Optional[float]:
    """Prometheus-style histogram_quantile over NON-cumulative bucket
    deltas: find the bucket where the cumulative windowed count
    crosses q*total and interpolate linearly inside it (lower bound 0
    for the first bucket). Observations above the top finite bound
    clamp to it — the honest answer a fixed-bucket histogram can
    give. None when the window saw nothing."""
    total = sum(delta_counts)
    if total <= 0:
        return None
    rank = q * total
    lo, cum = 0.0, 0.0
    for le, c in zip(buckets, delta_counts):
        cum += c
        if c > 0 and cum >= rank:
            frac = (rank - (cum - c)) / c
            return lo + (le - lo) * frac
        lo = le
    return float(buckets[-1])


def _series_key(family: str, labels: Optional[Dict[str, Any]]) -> str:
    if not labels:
        return family
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{family}{{{inner}}}"


def _round(v: Any, nd: int = 6) -> Any:
    return round(v, nd) if isinstance(v, float) else v


def _finding(resource: str, subsystem: str, severity: int, score: float,
             reason: str, owner: str = "", doc: str = "",
             evidence: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    return {
        "resource": resource, "subsystem": subsystem,
        "severity": int(severity), "score": _round(float(score), 3),
        "reason": reason, "owner": owner, "doc": doc,
        "evidence": {k: _round(v) for k, v in (evidence or {}).items()},
    }


def _family_doc(family: str) -> str:
    m = telemetry.REGISTRY.get(family)
    return m.help if m is not None else ""


class HealthMonitor:
    """The sampler + saturation engine, one per node (constructed at
    bootstrap, started with the node, reaped under ``node/health``).
    Bench CLIs construct throwaway instances around a run to embed a
    whole-run health section in their artifacts — `sample()` works
    loop-less, exactly like the channels it builds on."""

    def __init__(self, events=None, interval_s: Optional[float] = None,
                 owner: str = "health", node_id: str = "",
                 node_name: str = ""):
        self._lock = threading.Lock()
        self.events = events
        # Node identity riding every snapshot (fleet federation needs
        # labeled rows; skew needs the sampled-at wall clock, which
        # `ts` has always carried). Empty strings for loose monitors
        # (bench CLIs, tests) — the key is present either way so the
        # schema is one shape.
        self.node_identity = {"id": str(node_id), "name": str(node_name)}
        if interval_s is None:
            interval_s = float(flags.get("SDTPU_HEALTH_INTERVAL_S"))
        self.interval_s = max(0.05, interval_s)
        self.topk = max(1, int(flags.get("SDTPU_HEALTH_TOPK")))
        self._owner = owner
        self._task: Optional[asyncio.Task] = None
        # Series state, all under _lock (contract in threadctx.py).
        # Both maps are bounded by the metric registry's family×label
        # cardinality — the same import-time contract as the
        # declaration registries the engine reads.
        self._cursors: Dict[str, Any] = {}  # sdlint: ok[unbounded-growth]
        self._series: Dict[str, channels.Channel] = {}  # sdlint: ok[unbounded-growth]
        self._snapshots = channels.channel("health.snapshots")
        self._prev_t: Optional[float] = None
        self._last: Optional[Dict[str, Any]] = None
        # Establish cursors immediately: the first periodic tick then
        # has a real window instead of a meaningless since-forever one.
        self.sample()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            with self._lock:
                self._task = tasks.spawn(
                    "health-sampler", self._loop(), owner=self._owner)

    def stop(self) -> None:
        with self._lock:
            task, self._task = self._task, None
        if task is not None:
            task.cancel()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            if telemetry.enabled():
                self._emit(self.sample())

    def _emit(self, snap: Dict[str, Any]) -> None:
        if self.events is not None:
            self.events.emit({"type": "HealthSnapshot",
                              "ts": snap["ts"], "health": snap})

    def emit_snapshot(self) -> None:
        """Push one HealthSnapshot now (the subscription's immediate
        first frame)."""
        self._emit(self.snapshot())

    # -- the sampler -------------------------------------------------------

    def snapshot(self, max_age_s: Optional[float] = None
                 ) -> Dict[str, Any]:
        """The latest computed snapshot; samples fresh when none
        exists or the last one is older than `max_age_s` (default
        2× interval — covers loop-less embedders and sync tests where
        the periodic sampler never runs)."""
        limit = 2.0 * self.interval_s if max_age_s is None else max_age_s
        with self._lock:
            last = self._last
        if last is not None and (time.time() - last["ts"]) <= limit:
            return last
        return self.sample()

    def sample(self) -> Dict[str, Any]:
        """One observation: delta-spool every registered family into
        the per-series rings, evaluate saturation, publish the
        sd_health_state gauges, and return the HealthSnapshot dict."""
        with self._lock:
            t, wall = time.perf_counter(), time.time()
            dt = (t - self._prev_t) if self._prev_t is not None else None
            window: Dict[str, Dict[str, Any]] = {}
            for name, metric in sorted(telemetry.REGISTRY.families()
                                       .items()):
                for labels, m in metric.samples():
                    key = _series_key(name, labels)
                    rec: Dict[str, Any] = {
                        "family": name, "labels": labels or {},
                        "kind": metric.kind,
                    }
                    point: Optional[float] = None
                    if metric.kind == "histogram":
                        d = m.snapshot_delta(self._cursors.get(key))
                        self._cursors[key] = d["cursor"]
                        rec["count"] = d["count"]
                        rec["sum"] = _round(d["sum"])
                        rec["rate"] = _round(
                            d["count"] / dt) if dt else 0.0
                        for q, lbl in ((0.5, "p50"), (0.95, "p95"),
                                       (0.99, "p99")):
                            rec[lbl] = _round(windowed_quantile(
                                m.buckets, d["counts"], q))
                        point = rec["p99"]
                    elif metric.kind == "gauge":
                        rec["value"] = _round(m.value)
                        point = rec["value"]
                    else:  # counter
                        d = m.snapshot_delta(self._cursors.get(key))
                        self._cursors[key] = d["cursor"]
                        rec["delta"] = _round(d["value"])
                        rec["rate"] = _round(
                            d["value"] / dt) if dt else 0.0
                        point = rec["rate"]
                    window[key] = rec
                    if point is not None:
                        ring = self._series.get(key)
                        if ring is None:
                            ring = self._series[key] = channels.channel(
                                "health.series")
                        ring.put_nowait([round(wall, 3), _round(point)])

            findings = _evaluate(window, dt, wall)
            census: Dict[str, int] = {}
            for r in tasks.live():
                root = tasks.owner_label(r.owner).split("/")[0]
                census[root] = census.get(root, 0) + 1

            states: Dict[str, str] = {s: "ok" for s in BASE_SUBSYSTEMS}
            by_sub: Dict[str, List[Dict[str, Any]]] = {}
            for f in findings:
                sub = f["subsystem"]
                by_sub.setdefault(sub, []).append(f)
                cur = states.get(sub, "ok")
                if f["severity"] > STATES.index(cur):
                    states[sub] = STATES[f["severity"]]
                else:
                    states.setdefault(sub, cur)
            attribution: Dict[str, List[Dict[str, Any]]] = {}
            for sub, fs in sorted(by_sub.items()):
                fs.sort(key=lambda f: (-f["severity"], -f["score"],
                                       f["resource"]))
                top = fs[:self.topk]
                for f in top:
                    # Evidence series inline: the ring tails behind
                    # each windowed number the engine judged by.
                    pts = {}
                    for key in list(f["evidence"])[:2]:
                        ring = self._series.get(key)
                        if ring is not None:
                            pts[key] = list(ring)[-EVIDENCE_POINTS:]
                    f["points"] = pts
                attribution[sub] = top

            snap: Dict[str, Any] = {
                "ts": round(wall, 3),
                "node": dict(self.node_identity),
                "window_s": _round(dt) if dt is not None else None,
                "interval_s": self.interval_s,
                "states": states,
                "attribution": attribution,
                "tasks": {"live": sum(census.values()),
                          "census": census},
                "window": window,
            }
            self._prev_t = t
            self._last = snap
            self._snapshots.put_nowait(snap)
        HEALTH_SAMPLES.inc()
        for sub, st in states.items():
            HEALTH_STATE.labels(subsystem=sub).set(STATES.index(st))
        # Incident observatory last, OUTSIDE the lock: the observer
        # snapshot-freezes evidence bundles (disk writes, counter
        # stages) and must never extend the sampler's critical
        # section — or break the sample on its own failure.
        observer = _incident_observer
        if observer is not None:
            try:
                observer(snap)
            except Exception:
                pass
        return snap


# Incident-observatory hook (incidents.py set_incident_observer):
# called with every computed snapshot so saturated/degraded states
# become durable evidence bundles.
_incident_observer: Optional[Callable[[Dict[str, Any]], None]] = None


def set_incident_observer(
        cb: Optional[Callable[[Dict[str, Any]], None]]) -> None:
    global _incident_observer
    _incident_observer = cb


# -- the saturation engine ---------------------------------------------------

def _win(window: Dict[str, Dict], family: str, **labels) -> Optional[Dict]:
    return window.get(_series_key(family, labels or None))


def _by_family(window: Dict[str, Dict], family: str
               ) -> List[Tuple[str, Dict]]:
    return [(k, rec) for k, rec in window.items()
            if rec["family"] == family]


def _evaluate(window: Dict[str, Dict], dt: Optional[float],
              wall: float) -> List[Dict[str, Any]]:
    """Cross-read the declared registries over the freshest window and
    name what is saturated and what it is blocked on. Rates need a
    window: the very first sample (dt None) judges gauges/depths
    only."""
    finds: List[Dict[str, Any]] = []
    finds.extend(_channel_findings(window, dt))
    finds.extend(_timeout_findings(window, dt))
    finds.extend(_store_findings(window))
    finds.extend(_task_findings(window, dt))
    finds.extend(_pipeline_findings(window, dt, wall))
    finds.extend(_sanitize_findings(window, dt))
    finds.extend(_incident_findings(window, dt))
    return finds


def _channel_findings(window, dt) -> List[Dict[str, Any]]:
    finds = []
    for name, c in channels.CHANNELS.items():
        if c.sheds_expected:
            continue  # aging out IS this channel's design
        depth_rec = _win(window, "sd_chan_depth", name=name)
        if depth_rec is None:
            continue  # never constructed in this process
        cap = channels.capacity(name)
        depth = depth_rec.get("value") or 0.0
        shed_rec = _win(window, "sd_chan_shed_total", name=name)
        shed_rate = (shed_rec or {}).get("rate") or 0.0
        hw_rec = _win(window, "sd_chan_high_water", name=name)
        evidence = {
            _series_key("sd_chan_depth", {"name": name}): depth,
            _series_key("sd_chan_shed_total", {"name": name}): shed_rate,
            "capacity": cap,
        }
        if hw_rec is not None:
            evidence[_series_key("sd_chan_high_water",
                                 {"name": name})] = hw_rec.get("value")
        sev, reason = 0, ""
        if c.policy == "block":
            wait_rec = _win(window, "sd_chan_put_block_seconds",
                            name=name)
            p99 = (wait_rec or {}).get("p99")
            budget_s = timeouts.budget(c.put_budget) \
                if c.put_budget else None
            if p99 is not None and budget_s:
                evidence["put_block_p99_s"] = p99
                evidence["put_budget_s"] = budget_s
                if p99 >= BLOCK_WAIT_SATURATED_FRAC * budget_s:
                    sev, reason = 2, (
                        f"producers wait p99 {p99:.3g}s of the "
                        f"{budget_s:g}s {c.put_budget} budget")
                elif p99 >= BLOCK_WAIT_DEGRADED_FRAC * budget_s:
                    sev, reason = 1, (
                        f"producers feel backpressure (put p99 "
                        f"{p99:.3g}s vs {budget_s:g}s budget)")
        else:
            shedding = shed_rate > 0 and (
                c.policy in ("shed_new", "shed_oldest") or depth >= cap)
            if shedding:
                sev, reason = 2, (
                    f"{c.policy} policy dropping work "
                    f"({shed_rate:.3g}/s, depth {depth:g}/{cap})")
            elif depth >= cap:
                sev, reason = 2, (
                    f"buffer full ({depth:g}/{cap}) — consumer wedged")
            elif depth >= DEPTH_DEGRADED_FRAC * cap:
                sev, reason = 1, (
                    f"consumer falling behind (depth {depth:g}/{cap})")
        if sev:
            finds.append(_finding(
                name, name.split(".")[0], sev,
                shed_rate + (depth / cap if cap else 0.0),
                reason, owner=c.owner, doc=c.doc, evidence=evidence))
    return finds


def _timeout_findings(window, dt) -> List[Dict[str, Any]]:
    finds = []
    if dt is None:
        return finds
    for name, c in timeouts.TIMEOUTS.items():
        rec = _win(window, "sd_timeout_fired_total", name=name)
        rate = (rec or {}).get("rate") or 0.0
        if rate <= 0:
            continue
        sev = 2 if rate >= TIMEOUT_SATURATED_PER_S else 1
        finds.append(_finding(
            name, name.split(".")[0], sev, rate,
            f"declared budget firing {rate:.3g}/s "
            f"(default {c.default_s:g}s)",
            owner=name.split(".")[0], doc=c.doc,
            evidence={_series_key("sd_timeout_fired_total",
                                  {"name": name}): rate}))
    return finds


def _hot_statements(window, top: int = 3) -> List[Dict[str, Any]]:
    """Per-statement attribution over the declared inventory (round
    16): the window's hottest statements by execution and row rate —
    so a saturated write lock names WHICH statement is hammering it,
    not just that the store hurts."""
    from .store import statements as _stmts

    hot = []
    for name in list(_stmts.STATEMENTS) + list(_stmts.SHAPES):
        rec = _win(window, "sd_sql_statements_total", name=name)
        rate = (rec or {}).get("rate") or 0.0
        if rate <= 0:
            continue
        rows = _win(window, "sd_sql_rows_total", name=name)
        hot.append({"statement": name, "rate": rate,
                    "rows_rate": (rows or {}).get("rate") or 0.0})
    hot.sort(key=lambda h: (-h["rows_rate"], -h["rate"]))
    return hot[:top]


def _store_findings(window) -> List[Dict[str, Any]]:
    finds = []
    lock_rec = _win(window, "sd_store_write_lock_wait_seconds")
    p99 = (lock_rec or {}).get("p99")
    if p99 is not None:
        sev = 2 if p99 >= LOCK_WAIT_SATURATED_S else \
            1 if p99 >= LOCK_WAIT_DEGRADED_S else 0
        if sev:
            finds.append(_finding(
                "store.db.write_lock", "store", sev, p99,
                f"write-lock wait p99 {p99:.3g}s in window — writers "
                "serializing behind the per-database lock",
                owner="store",
                doc=_family_doc("sd_store_write_lock_wait_seconds"),
                evidence={
                    "sd_store_write_lock_wait_seconds": p99,
                    "tx_rate": (_win(window, "sd_store_tx_total")
                                or {}).get("rate"),
                    "hottest_statements": _hot_statements(window),
                }))
    commit_rec = _win(window, "sd_store_commit_seconds")
    cp99 = (commit_rec or {}).get("p99")
    if cp99 is not None and cp99 >= COMMIT_DEGRADED_S:
        finds.append(_finding(
            "store.db.commit", "store", 1, cp99,
            f"COMMIT latency p99 {cp99:.3g}s in window",
            owner="store", doc=_family_doc("sd_store_commit_seconds"),
            evidence={"sd_store_commit_seconds": cp99,
                      "hottest_statements": _hot_statements(window)}))
    wait_rec = _win(window, "sd_store_group_wait_seconds")
    wp99 = (wait_rec or {}).get("p99")
    if wp99 is not None:
        sev = 2 if wp99 >= GROUP_WAIT_SATURATED_S else \
            1 if wp99 >= GROUP_WAIT_DEGRADED_S else 0
        if sev:
            budget = timeouts.budget("store.actor.write")
            size_rec = _win(window, "sd_store_group_size")
            finds.append(_finding(
                "store.actor.group", "store", sev, wp99,
                f"group-commit wait p99 {wp99:.3g}s in window "
                f"({wp99 / budget:.1%} of the store.actor.write "
                "budget) — the writer queue is deep or a batch body "
                "is slow inside the group",
                owner="store",
                doc=_family_doc("sd_store_group_wait_seconds"),
                evidence={
                    "sd_store_group_wait_seconds": wp99,
                    "sd_store_group_size": (size_rec or {}).get("p99"),
                    "group_rate": (_win(
                        window, "sd_store_group_commits_total")
                        or {}).get("rate"),
                    "shutdown_drains": (_win(
                        window, "sd_store_group_shutdown_drains_total")
                        or {}).get("delta"),
                    "hottest_statements": _hot_statements(window),
                }))
    return finds


def _task_findings(window, dt) -> List[Dict[str, Any]]:
    finds = []
    if dt is None:
        return finds
    orphan_rec = _win(window, "sd_task_orphaned_total")
    orphans = (orphan_rec or {}).get("delta") or 0.0
    if orphans > 0:
        finds.append(_finding(
            "tasks.orphans", "tasks", 2, orphans,
            f"{orphans:g} task(s) survived a shutdown reap grace "
            "period in this window",
            owner="tasks", doc=_family_doc("sd_task_orphaned_total"),
            evidence={"sd_task_orphaned_total": orphans}))
    exc_rec = _win(window, "sd_sanitize_violations_total",
                   kind="task_exception")
    exc = (exc_rec or {}).get("delta") or 0.0
    if exc > 0:
        finds.append(_finding(
            "tasks.exceptions", "tasks", 1, exc,
            f"{exc:g} supervised task(s) died with unhandled "
            "exceptions in this window",
            owner="tasks", doc=_family_doc("sd_task_spawned_total"),
            evidence={_series_key("sd_sanitize_violations_total",
                                  {"kind": "task_exception"}): exc}))
    return finds


def _pipeline_findings(window, dt, wall) -> List[Dict[str, Any]]:
    if dt is None:
        return []
    stage_r = (_win(window, "sd_pipeline_stage_stall_seconds_total")
               or {}).get("rate") or 0.0
    retire_r = (_win(window, "sd_pipeline_retire_stall_seconds_total")
                or {}).get("rate") or 0.0
    h2d_r = (_win(window, "sd_pipeline_h2d_seconds_total")
             or {}).get("rate") or 0.0
    busy = max(stage_r, retire_r)
    if busy < PIPELINE_STALL_DEGRADED:
        return []
    sev = 2 if busy >= PIPELINE_STALL_SATURATED else 1
    evidence = {
        "sd_pipeline_stage_stall_seconds_total": stage_r,
        "sd_pipeline_retire_stall_seconds_total": retire_r,
        "sd_pipeline_h2d_seconds_total": h2d_r,
    }
    if stage_r >= retire_r:
        resource = "ops.pipeline.stage"
        reason = (f"dispatchers starved {stage_r:.2f} stall-s/s "
                  "waiting on staged batches — the pipeline is "
                  "stage-bound")
        doc = _family_doc("sd_pipeline_stage_stall_seconds_total")
    else:
        binding = _flight_binding(wall, dt) or (
            "h2d" if h2d_r >= 0.5 * retire_r else "kernel")
        resource = f"ops.pipeline.{binding}"
        reason = (f"retirer starved {retire_r:.2f} stall-s/s; recent "
                  f"batch windows attribute the bound to {binding}")
        doc = _family_doc("sd_pipeline_h2d_seconds_total") \
            if binding == "h2d" else \
            _family_doc("sd_pipeline_retire_stall_seconds_total")
    return [_finding(resource, "ops", sev, busy, reason,
                     owner="ops", doc=doc, evidence=evidence)]


def _flight_binding(wall: float, dt: float) -> Optional[str]:
    """The dominant bound (stage|h2d|kernel) named by the flight
    recorder's per-batch window events inside the sampling window —
    the forensic half of the pipeline attribution."""
    from . import flight

    t0_us = int((wall - dt) * 1e6)
    counts: Dict[str, int] = {}
    for ev in flight.RECORDER.snapshot():
        if ev.get("lane") == "window" and ev.get("ts_us", 0) >= t0_us:
            b = ev.get("binding")
            if b:
                counts[b] = counts.get(b, 0) + 1
    if not counts:
        return None
    return max(counts.items(), key=lambda kv: (kv[1], kv[0]))[0]


def _sanitize_findings(window, dt) -> List[Dict[str, Any]]:
    finds = []
    if dt is None:
        return finds
    for key, rec in _by_family(window, "sd_sanitize_violations_total"):
        kind = rec["labels"].get("kind", "?")
        if kind in ("task_exception", "task_orphaned"):
            continue  # attributed under the tasks subsystem
        delta = rec.get("delta") or 0.0
        if delta <= 0:
            continue
        sev = 2 if kind == "data_race" else 1
        finds.append(_finding(
            f"sanitize.{kind}", "sanitize", sev, delta,
            f"{delta:g} {kind} violation(s) recorded in this window",
            owner="sanitize",
            doc=_family_doc("sd_sanitize_violations_total"),
            evidence={key: delta}))
    for key, rec in _by_family(window, "sd_race_candidates_total"):
        delta = rec.get("delta") or 0.0
        if delta <= 0:
            continue
        cls_attr = rec["labels"].get("cls_attr", "?")
        finds.append(_finding(
            f"sanitize.race.{cls_attr}", "sanitize", 1, delta,
            f"{delta:g} ownership-contract breach(es) on {cls_attr} "
            "in this window",
            owner="sanitize",
            doc=_family_doc("sd_race_candidates_total"),
            evidence={key: delta}))
    return finds


def _incident_findings(window, dt) -> List[Dict[str, Any]]:
    """The observatory observes itself: evidence lost to the store
    bound, and an untriaged backlog against the declared capacity.
    Both land under the dynamic `incidents` subsystem — which the
    observatory explicitly refuses to open bundles about (a black box
    recording its own pressure forever would be the feedback loop)."""
    finds = []
    if dt is not None:
        rec = _win(window, "sd_incident_dropped_total")
        delta = (rec or {}).get("delta") or 0.0
        if delta > 0:
            finds.append(_finding(
                "incidents.store", "incidents", 1, delta,
                f"{delta:g} evidence bundle(s) evicted by the store "
                "bound in this window — postmortems are being lost; "
                "raise SDTPU_INCIDENT_STORE_MB or triage faster",
                owner="incidents",
                doc=_family_doc("sd_incident_dropped_total"),
                evidence={"sd_incident_dropped_total": delta}))
    rec = _win(window, "sd_incident_open")
    open_n = (rec or {}).get("value") or 0.0
    cap = channels.capacity("incidents.store")
    if open_n >= 0.8 * cap:
        finds.append(_finding(
            "incidents.open", "incidents", 1, open_n / max(cap, 1),
            f"{open_n:g} unacknowledged bundle(s) vs store capacity "
            f"{cap} — the untriaged backlog is about to evict "
            "evidence (incidents.ack drains it)",
            owner="incidents",
            doc=_family_doc("sd_incident_open"),
            evidence={"sd_incident_open": open_n}))
    return finds


# -- artifact schema ---------------------------------------------------------

def validate_health_snapshot(doc: Any) -> List[str]:
    """Schema gate for a HealthSnapshot (the node.health payload and
    the `sd_top --json` artifact body). Returns problem strings
    (empty = valid) — the contract tools/sd_top.py self-checks in
    tier-1, same pattern as flight.validate_chrome_trace."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["health snapshot must be a dict"]
    if not isinstance(doc.get("ts"), (int, float)):
        problems.append("ts must be a number")
    node = doc.get("node")
    if node is not None:
        # Node identity is OPTIONAL (pre-fleet snapshots validate
        # unchanged — backward-compatible shape) but typed when
        # present: the fleet merger labels rows by it.
        if not isinstance(node, dict) or \
                not isinstance(node.get("id"), str) or \
                not isinstance(node.get("name"), str):
            problems.append(
                "node must be {id: str, name: str} when present")
    if doc.get("window_s") is not None and \
            not isinstance(doc["window_s"], (int, float)):
        problems.append("window_s must be a number or null")
    states = doc.get("states")
    if not isinstance(states, dict) or not states:
        return problems + ["states must be a non-empty dict"]
    for sub, st in states.items():
        if st not in STATES:
            problems.append(f"states[{sub}]: unknown state {st!r}")
    attribution = doc.get("attribution")
    if not isinstance(attribution, dict):
        return problems + ["attribution must be a dict"]
    for sub, entries in attribution.items():
        where = f"attribution[{sub}]"
        if sub not in states:
            problems.append(f"{where}: subsystem has no state")
            continue
        if not isinstance(entries, list) or not entries:
            problems.append(f"{where}: must be a non-empty list")
            continue
        worst = 0
        for i, e in enumerate(entries):
            ew = f"{where}[{i}]"
            if not isinstance(e, dict):
                problems.append(f"{ew}: not an object")
                continue
            for k, t in (("resource", str), ("reason", str),
                         ("owner", str), ("doc", str)):
                if not isinstance(e.get(k), t):
                    problems.append(f"{ew}: {k} must be a {t.__name__}")
            if e.get("subsystem") != sub:
                problems.append(f"{ew}: subsystem mismatch")
            sev = e.get("severity")
            if sev not in (1, 2):
                problems.append(f"{ew}: severity must be 1 or 2")
            else:
                worst = max(worst, sev)
            if not isinstance(e.get("evidence"), dict):
                problems.append(f"{ew}: evidence must be a dict")
            pts = e.get("points")
            if pts is not None:
                if not isinstance(pts, dict):
                    problems.append(f"{ew}: points must be a dict")
                else:
                    for series, tail in pts.items():
                        if not isinstance(tail, list) or any(
                                not isinstance(p, (list, tuple))
                                or len(p) != 2 for p in tail):
                            problems.append(
                                f"{ew}: points[{series}] must be "
                                "[ts, value] pairs")
        if worst and states.get(sub) != STATES[worst]:
            problems.append(
                f"{where}: state {states.get(sub)!r} inconsistent "
                f"with worst attributed severity {worst}")
    window = doc.get("window")
    if window is not None:
        if not isinstance(window, dict):
            problems.append("window must be a dict")
        else:
            for key, rec in window.items():
                if not isinstance(rec, dict) or rec.get("kind") not in (
                        "counter", "gauge", "histogram"):
                    problems.append(
                        f"window[{key}]: needs a kind of "
                        "counter|gauge|histogram")
                    break  # one structural problem is enough signal
    return problems


# ---------------------------------------------------------------------------
# THE families the saturation engine cross-reads, each with why. Every
# key must be registered in spacedrive_tpu/telemetry.py, and every
# `sd_*` literal in this module must appear here — enforced statically
# by sdlint's telemetry pass (codes health-read-undeclared /
# health-read-unlisted) and at runtime by the parity test in
# tests/test_sdlint.py, the same shape as the span-family and channel
# drift checks.
# ---------------------------------------------------------------------------

READS: Dict[str, str] = {
    "sd_chan_depth": "instantaneous channel depth vs declared capacity",
    "sd_chan_high_water": "deepest observed depth per channel",
    "sd_chan_shed_total": "overflow-policy drop rate per channel",
    "sd_chan_put_block_seconds":
        "blocked-producer wait vs the channel's declared put budget",
    "sd_timeout_fired_total":
        "declared network-await budgets firing, per contract",
    "sd_store_write_lock_wait_seconds":
        "writer serialization behind the per-database write lock",
    "sd_store_commit_seconds": "COMMIT latency of write transactions",
    "sd_store_tx_total": "write-transaction rate (lock-wait context)",
    "sd_store_group_wait_seconds":
        "enqueue→COMMIT wait of group-committed writes vs the "
        "store.actor.write budget",
    "sd_store_group_size":
        "batches coalesced per group commit (fat-commit evidence)",
    "sd_store_group_commits_total":
        "group-commit rate of the per-library write actor",
    "sd_store_group_shutdown_drains_total":
        "write batches failed by actor shutdown (never silently "
        "dropped)",
    "sd_sql_statements_total":
        "per-statement execution rate (hottest-statement attribution "
        "for store findings)",
    "sd_sql_rows_total":
        "per-statement row throughput (hottest-statement attribution)",
    "sd_task_spawned_total": "supervisor spawn rate (census context)",
    "sd_task_orphaned_total": "tasks surviving the shutdown reap",
    "sd_pipeline_stage_stall_seconds_total":
        "identify-pipeline dispatcher starvation (stage-bound)",
    "sd_pipeline_retire_stall_seconds_total":
        "identify-pipeline retirer starvation (device-bound)",
    "sd_pipeline_h2d_seconds_total":
        "host→device transfer occupancy of the pipeline",
    "sd_sanitize_violations_total":
        "runtime-sanitizer detections by kind",
    "sd_race_candidates_total":
        "ownership-contract breaches recorded by the race recorder",
    "sd_incident_dropped_total":
        "evidence bundles evicted by the incident store's declared "
        "bound (postmortems lost)",
    "sd_incident_open":
        "untriaged incident-bundle backlog vs the incidents.store "
        "capacity",
}
