"""XLA environment bootstrap helpers (no jax import — must be callable
before the first ``import jax`` takes effect).

The virtual host-device count used for CPU-mesh testing and the driver's
multichip dry-run is carried in ``XLA_FLAGS`` and is only read once, when
the CPU backend initializes; these helpers centralize the mutation so the
test conftest and ``__graft_entry__`` can't drift apart.
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def ensure_host_device_count(n: int) -> None:
    """Ensure ``XLA_FLAGS`` requests at least ``n`` virtual CPU devices.

    Appends the flag when absent; raises an existing smaller value to
    ``n`` (never lowers a larger one). Takes effect only if the CPU
    backend has not yet initialized in this process.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"--{_FLAG}=(\d+)", flags)
    if m is None:
        flags = f"{flags} --{_FLAG}={n}".strip()
    elif int(m.group(1)) < n:
        flags = flags[: m.start(1)] + str(n) + flags[m.end(1):]
    else:
        return
    os.environ["XLA_FLAGS"] = flags
