"""Incident observatory: always-on black-box capture + evidence bundles.

The observability plane before this module was live-poll only: sd_top,
node.health, and the flight recorder answer "what is wrong NOW", but
the bounded rings they read age out in minutes (health.series sheds at
~10 min, the span ring at 512 records) — a storm, give-up, sanitizer
violation, or crash that happens while nobody is watching leaves no
postmortem. This module is the flight-data-recorder half: every
detection surface the registries already expose notifies the
observatory, and each distinct trouble fingerprint snapshot-freezes a
causal evidence BUNDLE — durably, rate-limited, and federable.

Triggers (the declared ``TRIGGERS`` table; the static↔runtime drift
test in tests/test_incidents.py pins that every declared kind has a
fire site and every fire site names a declared kind):

- ``health.saturated``  — a health subsystem entered ``saturated``
  (health.py sample() notifies after every evaluation, outside its
  lock);
- ``health.degraded``   — a subsystem held ``degraded`` for >=
  SDTPU_INCIDENT_DEGRADED_WINDOWS consecutive samples (brief wobbles
  don't open incidents; persistent ones do);
- ``backoff.give_up``   — a declared retry ladder exhausted
  (timeouts.Backoff.next_delay notifies once per exhausted ladder,
  exactly when sd_backoff_gave_up_total increments);
- ``sanitize.violation`` / ``task.exception`` / ``task.orphaned`` —
  a sanitizer detection in COUNT mode (raise mode already hands the
  evidence to the raiser; counting mode is production, where the
  violation would otherwise be one counter tick nobody saw);
- ``crash``             — the previous process died without running
  close(): a ``.running`` marker left in the store directory is
  noticed at next boot, and any partially-written bundle is recovered
  WAL-style (a torn ``.json.tmp`` is discarded, a complete one is
  promoted — never a torn final file).

A bundle carries the triggering attribution with its windowed
evidence, the relevant health-snapshot tails, the flight-recorder
timeline slice and span ring filtered to the implicated trace ids,
the chaos/backoff/timeout/shed counter families, the SQL
top-statements stage, a bounded log-ring tail (tracing.LogRing,
trace-id-stamped), and node identity / non-default flags / capacity
profile — enough to triage without the process that produced it.

Bundles are fingerprinted (subsystem + resource + trigger kind) for
dedup: repeat firings inside SDTPU_INCIDENT_WINDOW_S collapse into
sd_incident_deduped_total instead of new files. The on-disk store has
declared-channel semantics (``incidents.store``, shed_oldest): the
header index IS a registry channel whose eviction hook deletes the
evicted bundle's file, and a byte cap (SDTPU_INCIDENT_STORE_MB)
evicts oldest-first below the count cap — the store never grows past
its declared bounds. Surfaces: rspc ``incidents.list/get/ack`` + the
``incidents`` ws subscription (api/procedures.py), fleet federation
(``obs.incidents`` in p2p/obs.py; FleetMonitor pulls peers' bundle
headers, ``sd_top --fleet`` shows the INC column), and the
tools/sd_incidents.py CLI (list/show/diff/validate/self-check).
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import channels, chaos, flags, persist, telemetry, tracing
from .telemetry import (
    INCIDENTS_DEDUPED,
    INCIDENTS_DROPPED,
    INCIDENTS_OPENED,
    INCIDENTS_RECOVERED,
    INCIDENT_OPEN,
    INCIDENT_STORE_BYTES,
)

__all__ = [
    "TRIGGERS", "BUNDLE_SCHEMA", "IncidentObservatory",
    "validate_incident_bundle", "validate_incident_header",
    "install", "current", "uninstall",
]

BUNDLE_SCHEMA = 1

# Evidence bounds per bundle: a bundle is a postmortem slice, not a
# full dump — each section is capped so a storm of incidents cannot
# turn the store cap into a handful of giant files.
SPAN_LIMIT = 128
TIMELINE_LIMIT = 256
LOG_LIMIT = 128
TRACE_ID_LIMIT = 8          # implicated traces folded into one bundle
SQL_TOP = 3

# Counter families frozen into every bundle: the injected-cause /
# observed-effect reconciliation set (chaos, backoff, timeout, shed)
# plus the observatory's own families so a bundle shows the dedup and
# eviction pressure it was born under.
COUNTER_FAMILY_PREFIXES = (
    "sd_chaos_", "sd_backoff_", "sd_timeout_", "sd_chan_shed",
    "sd_sanitize_", "sd_task_", "sd_incident_",
)

# ---------------------------------------------------------------------------
# THE trigger namespace. Keep alphabetical; every entry must be fired
# by a `_fire("<kind>", ...)` literal (or the sanitizer kind map below)
# somewhere in the tree, and every fire site must name a declared kind
# — tests/test_incidents.py walks the AST both ways, the same drift
# gate the chaos fault points get.
# ---------------------------------------------------------------------------

TRIGGERS: Dict[str, str] = {
    "backoff.give_up":
        "A declared retry ladder exhausted its max_tries "
        "(timeouts.Backoff) — the operation stopped retrying and "
        "degraded; the bundle names the policy as its resource.",
    "crash":
        "The previous process exited without close(): the .running "
        "marker survived in the store directory. Fired once at "
        "next-boot recovery, after promoting or discarding any "
        "partially-written bundle.",
    "health.degraded":
        "A health subsystem held `degraded` for >= "
        "SDTPU_INCIDENT_DEGRADED_WINDOWS consecutive samples; the "
        "bundle's resource is the subsystem's top attributed finding.",
    "health.saturated":
        "A health subsystem entered `saturated`; the bundle's "
        "resource is the subsystem's top attributed finding.",
    "sanitize.violation":
        "A runtime-sanitizer detection recorded in COUNT mode "
        "(chan_overflow, data_race, loop_stall, sql_undeclared, ...) "
        "— production's only record of a contract breach.",
    "task.exception":
        "A supervised task died with an unhandled exception "
        "(tasks.py supervisor, routed through sanitize.record).",
    "task.orphaned":
        "A supervised task survived the shutdown reap's grace period "
        "(tasks.py supervisor, routed through sanitize.record).",
}

# Sanitizer violation kind → trigger kind. Task lifecycle kinds get
# their own trigger (they attribute under the tasks subsystem); every
# other sanitizer kind folds into the generic violation trigger.
_SANITIZE_TRIGGERS: Dict[str, str] = {
    "task_exception": "task.exception",
    "task_orphaned": "task.orphaned",
}

_MARKER = ".running"


def _fingerprint(kind: str, subsystem: str, resource: str) -> str:
    h = hashlib.sha256(
        f"{subsystem}|{resource}|{kind}".encode()).hexdigest()
    return h[:12]


def _subsystem_of(resource: str) -> str:
    """Dotted resource name → owning subsystem, the same first-segment
    convention the health engine's channel/timeout findings use."""
    return resource.split(".", 1)[0] if resource else "node"


class IncidentObservatory:
    """The capture engine: observers feed `_fire`, `_fire` dedups,
    assembles, and durably writes. One per process in production
    (module global, installed at Node bootstrap); bench CLIs and the
    sd_incidents self-check construct loose instances around a run,
    exactly like HealthMonitor."""

    def __init__(self, dir_path: Optional[str] = None, monitor=None,
                 events=None, node_id: str = "", node_name: str = ""):
        self._lock = threading.Lock()
        self.dir = os.path.abspath(dir_path) if dir_path else None
        self.monitor = monitor          # HealthMonitor or None
        self.events = events            # EventBus or None
        self.node_identity = {"id": str(node_id), "name": str(node_name)}
        self.window_s = float(flags.get("SDTPU_INCIDENT_WINDOW_S"))
        self.degraded_windows = max(
            1, int(flags.get("SDTPU_INCIDENT_DEGRADED_WINDOWS")))
        self.store_bytes_cap = int(
            float(flags.get("SDTPU_INCIDENT_STORE_MB")) * 1e6)
        # Header index with declared-channel semantics: count-capped by
        # the registry, shed_oldest, and the eviction hook deletes the
        # evicted bundle's file — the disk store can never outgrow the
        # index that names it.
        self._index = channels.channel(
            "incidents.store", on_evict=self._on_index_evict)
        self._last_fired: Dict[str, float] = {}   # fingerprint → ts
        self._dedup: Dict[str, int] = {}          # fingerprint → count
        self._degraded_streak: Dict[str, int] = {}
        self._store_bytes = 0
        self._closed = False
        if self.dir is not None:
            os.makedirs(self.dir, exist_ok=True)
            self._recover()
            self._write_marker()

    # -- observers (the detection surfaces call these) ----------------------

    def observe_health(self, snap: Dict[str, Any]) -> None:
        """Called after every health sample (health.py, outside its
        lock). Saturated fires immediately; degraded fires only after
        a streak — and the observatory never observes its own
        `incidents` subsystem (a bundle about bundle pressure would
        recurse forever)."""
        if self._closed:
            return
        states = snap.get("states") or {}
        attribution = snap.get("attribution") or {}
        # Streak bookkeeping under _lock (concurrent samplers exist in
        # embedder tests); the fires themselves run after release —
        # _fire re-acquires for its dedup window.
        fire: List[Tuple[str, str]] = []
        with self._lock:
            for sub, state in sorted(states.items()):
                if sub == "incidents":
                    continue
                if state == "saturated":
                    self._degraded_streak.pop(sub, None)
                    fire.append(("health.saturated", sub))
                elif state == "degraded":
                    streak = self._degraded_streak.get(sub, 0) + 1
                    self._degraded_streak[sub] = streak
                    if streak >= self.degraded_windows:
                        fire.append(("health.degraded", sub))
                else:
                    self._degraded_streak.pop(sub, None)
        for kind, sub in fire:
            self._fire_health(kind, sub, attribution)

    def _fire_health(self, kind: str, subsystem: str,
                     attribution: Dict[str, Any]) -> None:
        top = (attribution.get(subsystem) or [{}])[0]
        resource = top.get("resource") or subsystem
        self._fire(
            kind, subsystem, resource,
            top.get("reason") or f"subsystem {subsystem} {kind}",
            severity=int(top.get("severity") or 2),
            evidence=dict(top.get("evidence") or {}))

    def observe_give_up(self, name: str, tries: int) -> None:
        """Called once per exhausted backoff ladder (timeouts.py),
        exactly when sd_backoff_gave_up_total counts it."""
        if self._closed:
            return
        self._fire(
            "backoff.give_up", _subsystem_of(name), name,
            f"backoff ladder {name} exhausted after {tries} tries",
            severity=2, evidence={"tries": tries})

    def observe_violation(self, kind: str, detail: str) -> None:
        """Called per sanitizer violation recorded WITHOUT raising
        (sanitize.py _record): count mode, and the task lifecycle
        kinds that never raise. Raise mode already delivers the
        evidence to the raiser, and tier-1's per-test violation gate
        must not drown in bundles."""
        if self._closed:
            return
        trigger = _SANITIZE_TRIGGERS.get(kind, "sanitize.violation")
        sub = "tasks" if kind.startswith("task_") else "sanitize"
        self._fire(
            trigger, sub, f"sanitize.{kind}",
            detail[:500] or f"{kind} violation recorded",
            severity=1, evidence={"kind": kind})

    # -- the capture path ---------------------------------------------------

    def _fire(self, kind: str, subsystem: str, resource: str,
              reason: str, severity: int,
              evidence: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Dedup-gate, assemble, persist, announce. Returns the new
        bundle's header, or None when the fingerprint was rate-limited
        (counted into sd_incident_deduped_total)."""
        if kind not in TRIGGERS:
            raise ValueError(f"undeclared incident trigger {kind!r} "
                             "(declare it in spacedrive_tpu/"
                             "incidents.py TRIGGERS)")
        now = time.time()
        fp = _fingerprint(kind, subsystem, resource)
        with self._lock:
            if self._closed:
                return None
            last = self._last_fired.get(fp)
            if last is not None and (now - last) < self.window_s:
                self._dedup[fp] = self._dedup.get(fp, 0) + 1
                INCIDENTS_DEDUPED.inc()
                return None
            self._last_fired[fp] = now
        bundle = self._assemble(kind, subsystem, resource, reason,
                                severity, evidence, fp, now)
        header = bundle_header(bundle)
        with self._lock:
            entry = {"header": header, "path": None, "bundle": None,
                     "bytes": 0}
            if self.dir is not None:
                entry["path"], entry["bytes"] = self._write(bundle)
                self._store_bytes += entry["bytes"]
            else:
                entry["bundle"] = bundle
            self._index.put_nowait(entry)
            self._enforce_bytes_cap()
            self._publish_gauges()
        INCIDENTS_OPENED.labels(kind=kind).inc()
        if self.events is not None:
            try:
                self.events.emit({"type": "Incident", "ts": now,
                                  "incident": dict(header)})
            except Exception:
                pass
        return header

    def _assemble(self, kind: str, subsystem: str, resource: str,
                  reason: str, severity: int, evidence: Dict[str, Any],
                  fp: str, now: float) -> Dict[str, Any]:
        """Snapshot-freeze the evidence. Every section is best-effort
        and bounded: a capture failure degrades that section to empty,
        never loses the trigger attribution itself."""
        from . import flight

        bundle: Dict[str, Any] = {
            "bundle": "incident", "schema": BUNDLE_SCHEMA,
            "id": f"{int(now * 1000):x}-{fp}",
            "ts": round(now, 3),
            "fingerprint": fp,
            "trigger": {
                "kind": kind, "subsystem": subsystem,
                "resource": resource, "reason": reason,
                "severity": 2 if severity not in (1, 2) else severity,
                "evidence": {k: v for k, v in evidence.items()},
            },
            "node": dict(self.node_identity),
            "ack": False,
        }
        try:
            timeline = flight.RECORDER.snapshot()[-TIMELINE_LIMIT:]
        except Exception:
            timeline = []
        bundle["timeline"] = timeline
        # Implicated traces: whatever the recent timeline touched. The
        # span slice follows those ids when any exist — the bundle
        # then reads as a causal story, not 128 unrelated spans.
        traces = []
        for ev in reversed(timeline):
            t = ev.get("trace")
            if t and t not in traces:
                traces.append(t)
            if len(traces) >= TRACE_ID_LIMIT:
                break
        try:
            if traces:
                spans: List[Dict[str, Any]] = []
                for t in traces:
                    spans.extend(tracing.recent_spans(
                        limit=SPAN_LIMIT, trace_id=t))
                spans.sort(key=lambda s: s.get("ts") or 0)
                bundle["spans"] = spans[-SPAN_LIMIT:]
            else:
                bundle["spans"] = tracing.recent_spans(limit=SPAN_LIMIT)
        except Exception:
            bundle["spans"] = []
        bundle["traces"] = traces
        try:
            bundle["logs"] = tracing.log_ring_tail(LOG_LIMIT)
        except Exception:
            bundle["logs"] = []
        bundle["counters"] = self._counter_stage()
        bundle["sql_top"] = self._sql_top()
        bundle["health"] = self._health_tail()
        try:
            bundle["flags"] = {
                name: flags.raw(name) for name in sorted(flags.FLAGS)
                if flags.raw(name) not in (None, "")
            }
        except Exception:
            bundle["flags"] = {}
        try:
            bundle["capacity"] = {
                name: channels.capacity(name)
                for name in sorted(channels.CHANNELS)
            }
        except Exception:
            bundle["capacity"] = {}
        return bundle

    def _counter_stage(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        try:
            for name, m in sorted(telemetry.REGISTRY.families().items()):
                if name.startswith(COUNTER_FAMILY_PREFIXES):
                    out[name] = m.snapshot_value()
        except Exception:
            pass
        return out

    def _sql_top(self) -> List[Dict[str, Any]]:
        """Hottest statements by cumulative executions — which SQL was
        hammering the store when the incident froze."""
        hot: List[Dict[str, Any]] = []
        try:
            fam = telemetry.REGISTRY.get("sd_sql_statements_total")
            if fam is not None:
                for labels, child in fam.samples():
                    v = getattr(child, "value", 0.0)
                    if labels and v > 0:
                        hot.append({"statement": labels.get("name", "?"),
                                    "total": v})
                hot.sort(key=lambda h: -h["total"])
        except Exception:
            pass
        return hot[:SQL_TOP]

    def _health_tail(self) -> Optional[Dict[str, Any]]:
        """States + attribution (with their inline evidence-series
        tails) of the freshest health snapshot — NOT the full window
        (bundle size discipline; the attribution carries the ring
        tails that matter)."""
        if self.monitor is None:
            return None
        try:
            snap = self.monitor.snapshot()
            return {"ts": snap.get("ts"),
                    "window_s": snap.get("window_s"),
                    "states": snap.get("states"),
                    "attribution": snap.get("attribution"),
                    "tasks": snap.get("tasks")}
        except Exception:
            return None

    # -- the durable store --------------------------------------------------

    @staticmethod
    def _chaos_window(edge: str) -> None:
        """The declared incidents.write chaos seam, hooked into the
        shared persist writer's edges: `tmp-partial` (half the body
        flushed) is the torn-tmp window, `pre-rename` (complete,
        fsynced, unrenamed) the complete-tmp window — a delay widens
        either so the kill -9 test can land inside it."""
        if edge in ("tmp-partial", "pre-rename"):
            fault = chaos.hit("incidents.write", only=("delay",))
            if fault is not None:
                chaos.apply_sync(fault)

    def _write(self, bundle: Dict[str, Any]) -> Tuple[str, int]:
        """WAL-style bundle write through the declared persist seam
        (artifact `incidents.bundle`): full body into `<id>.json.tmp`,
        fsync, then one atomic rename. A crash mid-write leaves a torn
        tmp (discarded at recovery) or a complete tmp (promoted) —
        never a torn `<id>.json`."""
        path = os.path.join(self.dir, f"{bundle['id']}.json")
        data = json.dumps(bundle, indent=1)
        with persist.wal_writer("incidents.bundle") as write:
            write(path, data, chaos_point=self._chaos_window)
        return path, len(data)

    def _on_index_evict(self, entry: Dict[str, Any]) -> None:
        """Channel shed_oldest eviction hook: the index slot is gone,
        so the file goes too (the store's declared-bound discipline).
        Runs under whatever context put_nowait sheds in — file unlink
        only, no locks taken."""
        INCIDENTS_DROPPED.inc()
        path = entry.get("path")
        if path:
            try:
                # Every caller holds _lock: the index put that sheds
                # (inside _fire's locked section), _enforce_bytes_cap,
                # and recovery — the hook itself takes none so the
                # locked put path never double-acquires.
                self._store_bytes -= entry.get("bytes", 0)  # sdlint: ok[shared-mutation]
                os.unlink(path)
            except OSError:
                pass

    def _enforce_bytes_cap(self) -> None:
        """Oldest-first eviction below the count cap when the byte cap
        is crossed (callers hold _lock)."""
        while (self._store_bytes > self.store_bytes_cap
               and len(self._index) > 1):
            try:
                self._on_index_evict(self._index.get_nowait())
            except Exception:
                break

    def _publish_gauges(self) -> None:
        open_n = sum(1 for e in self._index
                     if not e["header"].get("ack"))
        INCIDENT_OPEN.set(open_n)
        INCIDENT_STORE_BYTES.set(max(0, self._store_bytes))

    # -- crash marker + WAL recovery ----------------------------------------

    def _marker_path(self) -> str:
        return os.path.join(self.dir, _MARKER)

    def _write_marker(self) -> None:
        persist.atomic_write(
            "incidents.marker", self._marker_path(),
            json.dumps({"pid": os.getpid(), "ts": round(time.time(), 3),
                        "node": dict(self.node_identity)}))
        atexit.register(self._atexit)

    def _atexit(self) -> None:
        try:
            self.close()
        except Exception:
            pass

    def _recover(self) -> None:
        """Next-boot recovery: promote complete `.json.tmp` bundles,
        discard torn ones, rebuild the index from surviving files, and
        turn a surviving crash marker into a `crash` bundle."""
        crashed: Optional[Dict[str, Any]] = None
        marker = self._marker_path()
        if os.path.exists(marker):
            try:
                with open(marker) as f:
                    crashed = json.load(f)
            except (OSError, ValueError):
                crashed = {}
            try:
                os.unlink(marker)
            except OSError:
                pass
        def _complete(raw: bytes) -> bool:
            # A tmp is promotable only when it parses AND passes the
            # full bundle schema — a torn body fails either way.
            return not validate_incident_bundle(json.loads(raw))

        for path, outcome in persist.recover(
                "incidents.bundle", self.dir, validate=_complete):
            if outcome == "promoted" or path.endswith(".json.tmp"):
                INCIDENTS_RECOVERED.labels(outcome=outcome).inc()
        entries = []
        for fn in sorted(os.listdir(self.dir)):
            path = os.path.join(self.dir, fn)
            if fn.endswith(".json"):
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    continue
                if not validate_incident_header(bundle_header(doc)):
                    entries.append((doc, path))
        entries.sort(key=lambda e: e[0].get("ts") or 0)
        with self._lock:
            for doc, path in entries:
                try:
                    size = os.path.getsize(path)
                except OSError:
                    size = 0
                self._store_bytes += size
                self._index.put_nowait({"header": bundle_header(doc),
                                        "path": path, "bundle": None,
                                        "bytes": size})
            self._enforce_bytes_cap()
            self._publish_gauges()
        if crashed is not None:
            prev = (crashed or {}).get("node") or {}
            self._fire(
                "crash", "node", "node.process",
                "previous process exited without close() "
                f"(pid {(crashed or {}).get('pid', '?')}, node "
                f"{prev.get('name') or 'unknown'!s})",
                severity=2,
                evidence={"marker": crashed or {}})

    # -- read/triage surface ------------------------------------------------

    def list(self, limit: int = 0) -> List[Dict[str, Any]]:
        """Bundle headers, newest-first."""
        with self._lock:
            headers = [dict(e["header"]) for e in self._index]
        headers.reverse()
        return headers[:limit] if limit and limit > 0 else headers

    def get(self, bundle_id: str) -> Optional[Dict[str, Any]]:
        """One full bundle by id (disk is authoritative)."""
        with self._lock:
            entry = next((e for e in self._index
                          if e["header"]["id"] == bundle_id), None)
        if entry is None:
            return None
        if entry["path"] is not None:
            try:
                with open(entry["path"]) as f:
                    return json.load(f)
            except (OSError, ValueError):
                return None
        return entry["bundle"]

    def ack(self, bundle_id: str) -> bool:
        """Mark a bundle triaged: flips the header (and the file) so
        sd_incident_open tracks the untriaged backlog only."""
        with self._lock:
            entry = next((e for e in self._index
                          if e["header"]["id"] == bundle_id), None)
            if entry is None:
                return False
            entry["header"]["ack"] = True
            if entry["bundle"] is not None:
                entry["bundle"]["ack"] = True
            path = entry["path"]
            self._publish_gauges()
        if path is not None:
            try:
                with open(path) as f:
                    doc = json.load(f)
                doc["ack"] = True
                # Read-modify-write outside _lock: the index header
                # flip above (under _lock) is the authoritative state;
                # this file rewrite is its durable shadow, and ack is
                # idempotent per bundle id.
                # sdlint: ok[crash-atomicity]
                persist.atomic_write("incidents.bundle", path,
                                     json.dumps(doc, indent=1))
            except (OSError, ValueError):
                pass
        return True

    def deduped(self) -> Dict[str, int]:
        """Per-fingerprint dedup counts since construction (what the
        bench harnesses embed next to the headers)."""
        with self._lock:
            return dict(self._dedup)

    def close(self) -> None:
        """Orderly shutdown: remove the crash marker (an exit after
        close() is not a crash). Idempotent; bundles stay on disk."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self.dir is not None:
            try:
                os.unlink(self._marker_path())
            except OSError:
                pass


# -- bundle schema -----------------------------------------------------------

def bundle_header(bundle: Dict[str, Any]) -> Dict[str, Any]:
    """The federable subset of a bundle: what incidents.list serves,
    obs.incidents ships to peers, and BENCH artifacts embed."""
    return {
        "id": bundle.get("id"), "ts": bundle.get("ts"),
        "schema": bundle.get("schema"),
        "fingerprint": bundle.get("fingerprint"),
        "trigger": dict(bundle.get("trigger") or {}),
        "node": dict(bundle.get("node") or {}),
        "ack": bool(bundle.get("ack")),
    }


def validate_incident_header(doc: Any) -> List[str]:
    """Schema gate for a bundle header (the federated shape). Returns
    problem strings, empty = valid — same contract as
    health.validate_health_snapshot."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["incident header must be a dict"]
    if not isinstance(doc.get("id"), str) or not doc.get("id"):
        problems.append("id must be a non-empty string")
    if not isinstance(doc.get("ts"), (int, float)):
        problems.append("ts must be a number")
    if not isinstance(doc.get("fingerprint"), str) \
            or not doc.get("fingerprint"):
        problems.append("fingerprint must be a non-empty string")
    trig = doc.get("trigger")
    if not isinstance(trig, dict):
        return problems + ["trigger must be a dict"]
    if trig.get("kind") not in TRIGGERS:
        problems.append(
            f"trigger.kind {trig.get('kind')!r} is not a declared "
            "trigger (incidents.TRIGGERS)")
    for k in ("subsystem", "resource", "reason"):
        if not isinstance(trig.get(k), str) or not trig.get(k):
            problems.append(f"trigger.{k} must be a non-empty string")
    if trig.get("severity") not in (1, 2):
        problems.append("trigger.severity must be 1 or 2")
    if not isinstance(trig.get("evidence"), dict):
        problems.append("trigger.evidence must be a dict")
    node = doc.get("node")
    if not isinstance(node, dict) or \
            not isinstance(node.get("id"), str) or \
            not isinstance(node.get("name"), str):
        problems.append("node must be {id: str, name: str}")
    if not isinstance(doc.get("ack"), bool):
        problems.append("ack must be a bool")
    expected = _fingerprint(trig.get("kind") or "",
                            trig.get("subsystem") or "",
                            trig.get("resource") or "")
    if isinstance(doc.get("fingerprint"), str) and \
            doc["fingerprint"] != expected and not problems:
        problems.append(
            "fingerprint does not match sha256(subsystem|resource|"
            "kind) — dedup identity is broken")
    return problems


def validate_incident_bundle(doc: Any) -> List[str]:
    """Schema gate for a FULL bundle (the on-disk file and the
    incidents.get payload) — what `sd_incidents --input` checks and
    the WAL recovery uses to tell a complete tmp from a torn one."""
    if not isinstance(doc, dict):
        return ["incident bundle must be a dict"]
    problems = validate_incident_header(doc)
    if doc.get("bundle") != "incident":
        problems.append("bundle must be 'incident'")
    if doc.get("schema") != BUNDLE_SCHEMA:
        problems.append(f"schema must be {BUNDLE_SCHEMA}")
    for k in ("timeline", "spans", "logs", "traces"):
        if not isinstance(doc.get(k), list):
            problems.append(f"{k} must be a list")
    for k in ("counters", "flags", "capacity"):
        if not isinstance(doc.get(k), dict):
            problems.append(f"{k} must be a dict")
    if not isinstance(doc.get("sql_top"), list):
        problems.append("sql_top must be a list")
    health = doc.get("health")
    if health is not None and not isinstance(health, dict):
        problems.append("health must be a dict or null")
    return problems


# -- process-global wiring ----------------------------------------------------

_OBSERVATORY: Optional[IncidentObservatory] = None
_wire_lock = threading.Lock()


def current() -> Optional[IncidentObservatory]:
    return _OBSERVATORY


def install(dir_path: Optional[str] = None, monitor=None, events=None,
            node_id: str = "", node_name: str = ""
            ) -> Optional[IncidentObservatory]:
    """Construct the process-global observatory and wire every
    detection surface's observer hook to it. Idempotent — the first
    install wins (one black box per process; a second node in the same
    process shares it, exactly like the sanitizer). Returns the active
    observatory, or None when SDTPU_INCIDENTS is off."""
    global _OBSERVATORY
    if not flags.get("SDTPU_INCIDENTS"):
        return None
    with _wire_lock:
        if _OBSERVATORY is not None:
            return _OBSERVATORY
        obs = IncidentObservatory(
            dir_path=dir_path, monitor=monitor, events=events,
            node_id=node_id, node_name=node_name)
        _OBSERVATORY = obs
    _wire(obs)
    return obs


def _wire(obs: IncidentObservatory) -> None:
    from . import health, sanitize, timeouts

    health.set_incident_observer(obs.observe_health)
    timeouts.set_give_up_observer(obs.observe_give_up)
    sanitize.set_violation_observer(obs.observe_violation)


def uninstall() -> None:
    """Test/embedder hook: close the global observatory and detach
    every observer."""
    global _OBSERVATORY
    from . import health, sanitize, timeouts

    with _wire_lock:
        obs, _OBSERVATORY = _OBSERVATORY, None
    health.set_incident_observer(None)
    timeouts.set_give_up_observer(None)
    sanitize.set_violation_observer(None)
    if obs is not None:
        obs.close()
