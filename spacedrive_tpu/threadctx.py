"""Shared-state ownership registry — the thread-safety twin of
channels.py's capacity table and timeouts.py's budget table.

Every class whose methods are reachable from more than one THREAD
CONTEXT (the event loop, `asyncio.to_thread`/executor submit targets,
the ops/staging.py pool workers, the per-device dispatch streams in
ops/overlap.py, atexit/signal shutdown hooks) DECLARES a contract per
mutable attribute here — which thread(s) may write it and under which
lock. The depth-N pipeline review (PR 8) burned both rounds hand-fixing
exactly this bug class: `PipelineStats` plain `+=` lost updates at more
than one device stream, and the stage-pool gauge clobbered across a
concurrent pool swap. With the contracts machine-readable, tools/sdlint
checks them statically (shared-mutation / thread-boundary /
guard-consistency passes) and this module checks them dynamically (the
`__setattr__`/container write recorder armed by `sanitize.install()`).

Contract kinds (one per attribute):

- ``loop_only``             — written only from the event-loop thread
  (ws pumps, channel internals, sync-net bookkeeping).
- ``single_thread``         — written from exactly one thread, whichever
  thread first writes it (bench stats finalized by their driver).
- ``guarded_by("<lock>")``  — every post-init write holds the named
  lock attribute of the same instance (the store/telemetry idiom).
- ``atomic_counter``        — a statistics counter deliberately updated
  with bare `+=` from multiple threads: the declaration is a VISIBLE
  waiver that a lost update only skews a statistic, never corrupts
  state. The static pass allows only augmented numeric updates; the
  runtime twin counts its writes but never raises.
- ``immutable_after_init``  — bound during construction, then frozen
  (config snapshots, contract records).

Runtime twin (armed by `sanitize.install()` unless
`SDTPU_RACE_GUARD=off`): each declared class's `__setattr__` is wrapped
to record (thread id, held tracked-lock set) per post-init write, and
declared list/dict/set attributes are wrapped so in-place container
mutation records too. Writes to one attribute from two or more threads
with an EMPTY lockset intersection — or any second-thread write to a
`loop_only`/`single_thread` attribute, or any post-init write to an
`immutable_after_init` one — raise a ``data_race`` sanitizer violation
in tier-1 (`raise` mode) and count into
`sd_race_candidates_total{cls_attr}` in production (`count` mode);
every tracked write counts into `sd_race_tracked_writes_total`.
Lockset membership comes from two sources: the sanitizer's tracked-lock
stack (store locks), and — for `guarded_by` attrs — the named guard
object itself reporting `locked()` at the write, so plain
`threading.Lock` guards participate without migrating to tracked locks.

Disarmed cost is ZERO: no class is wrapped until `arm()` runs, so
production default (`SDTPU_SANITIZE` unset) never sees the recorder.

This module also owns the ONE sanctioned cross-thread loop hand-off,
`call_threadsafe(loop, cb, *args)`: the raw
`loop.call_soon_threadsafe(...)` idiom crashes the posting executor
thread with `RuntimeError: Event loop is closed` when shutdown wins the
race; the helper swallows exactly that shape (counting it into
`sd_race_handoff_closed_total`) and re-raises everything else. The
thread-boundary pass treats this helper — and the raw
`call_soon_threadsafe`/`run_coroutine_threadsafe` primitives — as the
sanctioned shapes for loop-affine calls from executor threads.

Design constraints (same as flags.py / timeouts.py / channels.py):
stdlib + flags/telemetry only, importable from every layer without
cycles. The classes a contract points at are imported lazily at arm
time, never at module import.
"""

from __future__ import annotations

import importlib
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from . import flags
from .telemetry import (
    RACE_CANDIDATES,
    RACE_HANDOFF_CLOSED,
    RACE_TRACKED_WRITES,
)

__all__ = [
    "AttrContract", "OwnerContract", "CONTRACTS", "declare_owner",
    "loop_only", "single_thread", "guarded_by", "atomic_counter",
    "immutable_after_init", "arm", "disarm", "armed", "armed_classes",
    "call_threadsafe", "temporary_owner", "owner_table_markdown",
]

KINDS = ("loop_only", "single_thread", "guarded_by", "atomic_counter",
         "immutable_after_init")


@dataclass(frozen=True)
class AttrContract:
    kind: str
    lock: Optional[str] = None  # guard attr name for guarded_by


def loop_only() -> AttrContract:
    return AttrContract("loop_only")


def single_thread() -> AttrContract:
    return AttrContract("single_thread")


def guarded_by(lock: str) -> AttrContract:
    if not lock:
        raise ValueError("guarded_by needs a lock attribute name")
    return AttrContract("guarded_by", lock)


def atomic_counter() -> AttrContract:
    return AttrContract("atomic_counter")


def immutable_after_init() -> AttrContract:
    return AttrContract("immutable_after_init")


@dataclass(frozen=True)
class OwnerContract:
    name: str                       # dotted id: "<module>.<Class>"
    site: str                       # "path/to/file.py::ClassName"
    attrs: Mapping[str, AttrContract]
    doc: str


CONTRACTS: Dict[str, OwnerContract] = {}


def declare_owner(name: str, site: str,
                  attrs: Mapping[str, AttrContract],
                  doc: str = "") -> OwnerContract:
    if name in CONTRACTS:
        raise ValueError(f"owner {name!r} declared twice")
    if "::" not in site:
        raise ValueError(f"owner {name!r}: site must be "
                         "'path/to/file.py::ClassName'")
    cls_name = site.split("::", 1)[1]
    for other in CONTRACTS.values():
        if other.site.split("::", 1)[1] == cls_name:
            raise ValueError(
                f"owner {name!r}: class name {cls_name!r} already "
                f"claimed by {other.name!r} — the static pass resolves "
                "receivers by class name, which must stay unique")
    for attr, c in attrs.items():
        if c.kind not in KINDS:
            raise ValueError(f"owner {name!r}.{attr}: unknown contract "
                             f"kind {c.kind!r}")
    oc = OwnerContract(name, site, dict(attrs), doc)
    CONTRACTS[name] = oc
    return oc


# -- runtime twin -----------------------------------------------------------

_armed = False
_record: Optional[Callable[[str, str, bool], None]] = None
_held_fn: Optional[Callable[[], List[str]]] = None
# cls → (had_own_setattr, orig_setattr, merged_attr_contracts)
_wrapped: Dict[type, Tuple[bool, Any, Dict[str, AttrContract]]] = {}
_tls = threading.local()

_STATE_ATTR = "_sdtpu_write_state"


def armed() -> bool:
    return _armed


def armed_classes() -> List[type]:
    return list(_wrapped)


def _resolve_site(site: str) -> type:
    path, cls_name = site.split("::", 1)
    module = path[:-3].replace("/", ".") if path.endswith(".py") else path
    mod = importlib.import_module(module)
    cls = getattr(mod, cls_name)
    if not isinstance(cls, type):
        raise TypeError(f"site {site!r} resolves to {cls!r}, not a class")
    return cls


class _WriteState:
    """Per-(instance, attr) write history: writer thread ids and the
    running intersection of locksets held at each write. Mutated
    lock-free — set.add and slot rebinds are effectively atomic under
    the GIL, and a lost lockset narrowing only makes the detector
    miss, never false-positive harder."""

    __slots__ = ("threads", "common")

    def __init__(self, tid: int, locks: frozenset):
        self.threads = {tid}
        self.common = locks


def _locks_now(obj: Any, c: AttrContract) -> frozenset:
    held = frozenset(_held_fn()) if _held_fn is not None else frozenset()
    if c.kind == "guarded_by":
        guard: Any = obj
        for part in c.lock.split("."):  # "db._write_lock" chains
            guard = getattr(guard, part, None)
            if guard is None:
                break
        locked = getattr(guard, "locked", None)
        if locked is not None:
            try:
                is_held = bool(locked())
            except Exception:  # RLock.locked absent on older runtimes
                is_held = False
            if is_held:
                # The named guard participates even when it is a plain
                # threading.Lock: locked() at the write means this
                # (or, rarely, a racing) thread holds it — good
                # enough for a sanitizer whose static half pins the
                # bare-write shape.
                held |= {f"{c.lock}#{id(guard)}"}
    return held


def _note_write(obj: Any, cls_name: str, attr: str,
                c: AttrContract) -> None:
    if getattr(_tls, "busy", False):
        return  # the recorder's own metrics must not re-enter it
    _tls.busy = True
    try:
        RACE_TRACKED_WRITES.inc()
        state = obj.__dict__.get(_STATE_ATTR)
        if state is None:
            state = {}
            object.__setattr__(obj, _STATE_ATTR, state)
        tid = threading.get_ident()
        locks = _locks_now(obj, c)
        rec = state.get(attr)
        if rec is None:
            state[attr] = _WriteState(tid, locks)
            if c.kind != "immutable_after_init":
                return
            rec = state[attr]
        else:
            rec.threads.add(tid)
            rec.common = rec.common & locks
        racy = False
        if c.kind == "immutable_after_init":
            racy = True  # any post-init write mutates a frozen attr
        elif len(rec.threads) >= 2:
            if c.kind in ("loop_only", "single_thread"):
                racy = True
            elif c.kind == "guarded_by" and not rec.common:
                racy = True
            # atomic_counter: multi-thread bare increments are the
            # declared, visible waiver — counted, never raised.
        if racy:
            RACE_CANDIDATES.labels(cls_attr=f"{cls_name}.{attr}").inc()
            if _record is not None:
                _record(
                    "data_race",
                    f"{cls_name}.{attr} ({c.kind}"
                    + (f" {c.lock!r}" if c.lock else "")
                    + f") written from {len(rec.threads)} thread(s) "
                    f"with lockset intersection "
                    f"{sorted(rec.common) or '{}'}",
                    True)
    finally:
        _tls.busy = False


# -- tracked containers -----------------------------------------------------
# Declared list/dict/set attributes are replaced (at assignment time,
# while armed) with subclasses whose mutators record like __setattr__
# does — `self._counts[i] += 1` and `stats.samples.append(...)` are
# writes too. deque/custom containers are NOT wrapped (the registry
# channels already meter themselves); the static pass still sees their
# mutation sites.
#
# CONSTRAINT: the wrap is a tracked COPY, so assigning a container to
# a declared attr transfers ownership — a caller that keeps mutating
# its own reference afterwards (`rows = []; stats.samples = rows;
# rows.append(x)`) diverges from the attribute under an armed
# sanitizer. No declared site aliases this way (they assign literals
# or field defaults); keep it that way when declaring new container
# attrs.

def _tracking(cls_name: str, attr: str, c: AttrContract):
    def note(self) -> None:
        owner = self._sdtpu_owner
        if owner is not None and _armed:
            _note_write(owner, cls_name, attr, c)
    return note


def _wrap_container(value: Any, owner: Any, cls_name: str, attr: str,
                    c: AttrContract) -> Any:
    base = None
    if type(value) is list:
        base = _TrackedList
    elif type(value) is dict:
        base = _TrackedDict
    elif type(value) is set:
        base = _TrackedSet
    if base is None:
        return value
    wrapped = base(value)
    wrapped._sdtpu_owner = owner
    wrapped._sdtpu_note = _tracking(cls_name, attr, c).__get__(wrapped)
    return wrapped


class _TrackedList(list):
    _sdtpu_owner: Any = None

    def _sdtpu_note(self):  # replaced per-instance
        pass

    def append(self, *a):
        self._sdtpu_note()
        return list.append(self, *a)

    def extend(self, *a):
        self._sdtpu_note()
        return list.extend(self, *a)

    def insert(self, *a):
        self._sdtpu_note()
        return list.insert(self, *a)

    def pop(self, *a):
        self._sdtpu_note()
        return list.pop(self, *a)

    def remove(self, *a):
        self._sdtpu_note()
        return list.remove(self, *a)

    def clear(self):
        self._sdtpu_note()
        return list.clear(self)

    def __setitem__(self, *a):
        self._sdtpu_note()
        return list.__setitem__(self, *a)

    def __delitem__(self, *a):
        self._sdtpu_note()
        return list.__delitem__(self, *a)

    def __iadd__(self, other):
        self._sdtpu_note()
        list.extend(self, other)
        return self


class _TrackedDict(dict):
    _sdtpu_owner: Any = None

    def _sdtpu_note(self):
        pass

    def __setitem__(self, *a):
        self._sdtpu_note()
        return dict.__setitem__(self, *a)

    def __delitem__(self, *a):
        self._sdtpu_note()
        return dict.__delitem__(self, *a)

    def pop(self, *a):
        self._sdtpu_note()
        return dict.pop(self, *a)

    def popitem(self):
        self._sdtpu_note()
        return dict.popitem(self)

    def setdefault(self, *a):
        self._sdtpu_note()
        return dict.setdefault(self, *a)

    def update(self, *a, **kw):
        self._sdtpu_note()
        return dict.update(self, *a, **kw)

    def clear(self):
        self._sdtpu_note()
        return dict.clear(self)


class _TrackedSet(set):
    _sdtpu_owner: Any = None

    def _sdtpu_note(self):
        pass

    def add(self, *a):
        self._sdtpu_note()
        return set.add(self, *a)

    def discard(self, *a):
        self._sdtpu_note()
        return set.discard(self, *a)

    def remove(self, *a):
        self._sdtpu_note()
        return set.remove(self, *a)

    def pop(self):
        self._sdtpu_note()
        return set.pop(self)

    def clear(self):
        self._sdtpu_note()
        return set.clear(self)

    def update(self, *a):
        self._sdtpu_note()
        return set.update(self, *a)


# -- class wrapping ---------------------------------------------------------

def _make_setattr(cls: type, merged: Dict[str, AttrContract], orig):
    cls_name = cls.__name__

    def __setattr__(self, name, value):
        c = merged.get(name)
        if c is None or not _armed:
            orig(self, name, value)
            return
        first = name not in self.__dict__
        if not isinstance(value, (_TrackedList, _TrackedDict,
                                  _TrackedSet)):
            value = _wrap_container(value, self, cls_name, name, c)
        orig(self, name, value)
        if first:
            # The initializing write establishes the attr (dataclass
            # field defaults, __init__ bodies) — ownership tracking
            # starts at the first REBIND.
            return
        _note_write(self, cls_name, name, c)

    return __setattr__


def _wrap_class(cls: type, merged: Dict[str, AttrContract]) -> None:
    if cls in _wrapped:
        return
    had_own = "__setattr__" in cls.__dict__
    orig = cls.__setattr__
    _wrapped[cls] = (had_own, orig, merged)
    cls.__setattr__ = _make_setattr(cls, merged, orig)


def arm(mode: str,
        record: Callable[[str, str, bool], None],
        held_fn: Optional[Callable[[], List[str]]] = None) -> None:
    """Arm the write recorder over every declared class (called by
    sanitize.install; `SDTPU_RACE_GUARD=off` disables, `auto` follows
    the sanitizer). `record(kind, detail, may_raise)` is
    sanitize._record — the raise/count split is its decision; `held_fn`
    returns the calling thread's tracked-lock graph ids."""
    global _armed, _record, _held_fn
    del mode  # the record callback owns the raise/count split
    if flags.get("SDTPU_RACE_GUARD") == "off":
        return
    _record = record
    _held_fn = held_fn
    resolved: Dict[type, OwnerContract] = {}
    for oc in CONTRACTS.values():
        resolved[_resolve_site(oc.site)] = oc
    for cls in resolved:
        # Contracts compose down the MRO: a subclass of a declared base
        # (Gauge under Counter) inherits the base's attr contracts and
        # may add its own.
        merged: Dict[str, AttrContract] = {}
        for base in reversed(cls.__mro__):
            if base in resolved:
                merged.update(resolved[base].attrs)
        _wrap_class(cls, merged)
    _armed = True


def disarm() -> None:
    """Restore every wrapped class (tests). Instances keep any tracked
    containers already installed; with _armed False they record
    nothing."""
    global _armed, _record, _held_fn
    _armed = False
    _record = None
    _held_fn = None
    for cls, (had_own, orig, _merged) in _wrapped.items():
        if had_own:
            cls.__setattr__ = orig
        else:
            try:
                del cls.__setattr__
            except AttributeError:
                pass
    _wrapped.clear()


class temporary_owner:
    """Test scaffold: declare + wrap one class for the duration of a
    with-block (the seeded-race tests arm throwaway classes without
    touching the real registry)."""

    def __init__(self, cls: type, **attrs: AttrContract):
        self.cls = cls
        self.attrs = attrs

    def __enter__(self):
        if self.cls in _wrapped:
            # Silently no-opping here would test NOTHING, and __exit__
            # would then strip the REGISTRY's wrap for the rest of the
            # process — a quietly disarmed recorder is the worst
            # outcome a test scaffold can produce.
            raise RuntimeError(
                f"{self.cls.__name__} is already wrapped (declared in "
                "the central registry?) — temporary_owner is for "
                "throwaway test classes only")
        _wrap_class(self.cls, dict(self.attrs))
        return self.cls

    def __exit__(self, *exc):
        had_own, orig, _merged = _wrapped.pop(self.cls)
        if had_own:
            self.cls.__setattr__ = orig
        else:
            try:
                del self.cls.__setattr__
            except AttributeError:
                pass
        return False


# -- the sanctioned cross-thread loop hand-off ------------------------------

def call_threadsafe(loop, callback: Callable, *args) -> bool:
    """Post `callback(*args)` onto `loop` from any thread, tolerating a
    loop torn down mid-shutdown: the raw
    `loop.call_soon_threadsafe(...)` raises `RuntimeError: Event loop
    is closed` into the posting executor thread when shutdown wins the
    race (the old p2p/sync_net + api/server crash shape). Returns True
    when the callback was scheduled; a closed/absent loop returns False
    and counts into `sd_race_handoff_closed_total` (the work is
    shutdown-moot by definition: peers re-pull on reconnect, ws
    subscribers are gone). Any other RuntimeError re-raises — this
    helper swallows exactly the closed-loop shape, nothing else."""
    if loop is None or loop.is_closed():
        RACE_HANDOFF_CLOSED.inc()
        return False
    try:
        loop.call_soon_threadsafe(callback, *args)
    except RuntimeError as e:
        if "closed" not in str(e).lower():
            raise
        RACE_HANDOFF_CLOSED.inc()
        return False
    return True


def owner_table_markdown() -> str:
    """docs table: one row per declared owner class."""
    out = ["| Owner | Site | Attr contracts |", "| --- | --- | --- |"]
    for name in sorted(CONTRACTS):
        oc = CONTRACTS[name]
        kinds = ", ".join(
            f"`{a}`: {c.kind}" + (f"({c.lock})" if c.lock else "")
            for a, c in sorted(oc.attrs.items()))
        out.append(f"| `{name}` | `{oc.site}` | {kinds} |")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# THE ownership namespace. Keep alphabetical by name; every entry is
# enforced statically by the sdlint shared-mutation pass (an undeclared
# multi-context class, an undeclared mutable attribute, or a write that
# breaks its contract fails the build) and dynamically by the armed
# write recorder above. Sites must resolve (tests/test_threadctx.py
# pins static↔runtime parity and that every declared class is
# constructed somewhere in the tree).
# ---------------------------------------------------------------------------

declare_owner(
    "channels.Metered", "spacedrive_tpu/channels.py::_Metered",
    {
        "high_water": guarded_by("_hw_lock"),
    },
    "Depth/high-water accounting shared by Channel/Window/BoundedDict: "
    "instances are loop-affine, but the per-NAME high-water compare-"
    "and-set must stay monotone even under the threaded stress test, "
    "so it runs under the module-wide _hw_lock.")

declare_owner(
    "channels.BoundedDict", "spacedrive_tpu/channels.py::BoundedDict",
    {
        "_d": loop_only(),
    },
    "Registry LRU caches (p2p.route_cache): resolved and invalidated "
    "by loop-side p2p code only.")

declare_owner(
    "channels.Channel", "spacedrive_tpu/channels.py::Channel",
    {
        "_slots": loop_only(),
        "_keys": loop_only(),
        "_getters": loop_only(),
        "_space": loop_only(),
    },
    "Bounded channel internals: waiter futures are loop-affine by "
    "construction (the thread-boundary pass routes cross-thread "
    "producers through call_threadsafe). The nowait slot surface "
    "additionally tolerates GIL-atomic use from worker threads with "
    "no parked waiters — the jobs run-queue construction path and the "
    "threaded shed stress test — which the deque keeps exact.")

declare_owner(
    "channels.Window", "spacedrive_tpu/channels.py::Window",
    {
        "_depth": guarded_by("_depth_lock"),
    },
    "External-buffer depth tracker: the tunnel send_nowait window "
    "notes from its owning loop, while the staging buffer pool's "
    "window is noted from stage and retire executor threads — every "
    "depth mutation serializes on the window's internal _depth_lock leaf.")

declare_owner(
    "staging.StagePool", "spacedrive_tpu/ops/staging.py::StagePool",
    {
        "_free": guarded_by("_lock"),
        "_total": guarded_by("_lock"),
        "_high_water": guarded_by("_lock"),
    },
    "Native staging buffer pool: leases are acquired on the stage "
    "executor threads and released on the retirer, so the free list "
    "and allocation accounting all move under the pool's _lock leaf; "
    "occupancy is metered through the declared ops.stage.pool "
    "window.")

declare_owner(
    "timeouts.Backoff", "spacedrive_tpu/timeouts.py::Backoff",
    {
        "tries": single_thread(),
        "_gave_up_counted": single_thread(),
    },
    "One failing operation's retry-ladder state (timeouts.py "
    "declare_backoff registry): instances are strictly per-use-site — "
    "a commit retry lives inside one tx() call's thread, a "
    "RetrySchedule ladder belongs to its owning loop — so the ladder "
    "counter is single-thread by construction; distinct sites get "
    "distinct instances, never a shared one.")

declare_owner(
    "fleet.FleetMonitor", "spacedrive_tpu/fleet.py::FleetMonitor",
    {
        "_peers": guarded_by("_lock"),
        "_last": guarded_by("_lock"),
        "_task": guarded_by("_lock"),
    },
    "Fleet observatory poller: the supervised poll loop mutates the "
    "peer records and the cached merged view, while rspc handlers, "
    "the sd_top CLI, and bench embedders read them on demand — the "
    "peer map, last view, and task handle all move under the "
    "monitor's _lock leaf.")

declare_owner(
    "flight.FlightRecorder", "spacedrive_tpu/flight.py::FlightRecorder",
    {
        "ring": immutable_after_init(),
        "_open": guarded_by("_lock"),
    },
    "Flight-recorder timeline ring: the per-device dispatch executor "
    "threads, the retire thread, and the pipeline coroutines all "
    "record phases — every ring put and open-window mutation runs "
    "under the recorder's _lock; the ring channel itself is bound at "
    "construction and never rebound.")

declare_owner(
    "health.HealthMonitor", "spacedrive_tpu/health.py::HealthMonitor",
    {
        "_cursors": guarded_by("_lock"),
        "_series": guarded_by("_lock"),
        "_prev_t": guarded_by("_lock"),
        "_last": guarded_by("_lock"),
        "_task": guarded_by("_lock"),
    },
    "Health observatory sampler: ticked by its supervised loop task, "
    "sampled on demand by rspc handlers and bench CLIs — per-series "
    "cursors, rings, and the cached snapshot all mutate under the "
    "monitor's _lock leaf.")

declare_owner(
    "incidents.IncidentObservatory",
    "spacedrive_tpu/incidents.py::IncidentObservatory",
    {
        "_index": guarded_by("_lock"),
        "_last_fired": guarded_by("_lock"),
        "_dedup": guarded_by("_lock"),
        "_store_bytes": guarded_by("_lock"),
        "_closed": guarded_by("_lock"),
        "_degraded_streak": guarded_by("_lock"),
    },
    "Incident observatory capture engine: triggers fire from the "
    "health sampler loop, backoff ladders on arbitrary threads, and "
    "the sanitizer's recording sites — the bundle index, dedup "
    "windows, store accounting, and degraded-streak map all move "
    "under the observatory's _lock leaf (health samples arrive from "
    "whichever thread asked the monitor to sample).")

declare_owner(
    "overlap.PipelineStats",
    "spacedrive_tpu/ops/overlap.py::PipelineStats",
    {
        "h2d_bytes": guarded_by("_lock"),
        "h2d_s": guarded_by("_lock"),
        "donated_reuse": guarded_by("_lock"),
        "buffer_samples": guarded_by("_lock"),
        "stage_s": guarded_by("_lock"),
        "retire_stall_s": guarded_by("_lock"),
        "calibration_s": guarded_by("_lock"),
        "samples": guarded_by("_lock"),
        "depth_high_water": guarded_by("_lock"),
        "per_device_batches": guarded_by("_lock"),
        "stage_native_batches": guarded_by("_lock"),
        "stage_python_batches": guarded_by("_lock"),
        "files": single_thread(),
        "wall_s": single_thread(),
        "batches": single_thread(),
        "batch_files": single_thread(),
        "t_stage_1": single_thread(),
        "t_h2d_1": single_thread(),
        "t_kernel_1": single_thread(),
        "t_stage_2": single_thread(),
        "t_h2d_2": single_thread(),
        "t_kernel_2": single_thread(),
    },
    "Depth-N pipeline stats: the per-device executor streams AND the "
    "pipeline coroutines mutate the accounting fields (the PR 8 "
    "lost-update class), so everything multi-writer sits under _lock; "
    "the run-shape and bracket fields are finalized by the one thread "
    "driving run_overlapped.")

declare_owner(
    "store.Database", "spacedrive_tpu/store/db.py::Database",
    {
        "_all_conns": guarded_by("_conns_lock"),
        "_closed": guarded_by("_conns_lock"),
        "_local": guarded_by("_conns_lock"),
        "_read_pool": guarded_by("_conns_lock"),
        "_commits": guarded_by("_write_lock"),
    },
    "The store: every job thread and the loop share one Database per "
    "library. Connection registration/teardown — and the read-only "
    "pool's borrow/release free-list — serialize on the _conns_lock "
    "leaf (the PR 1 deadlock fix); the WAL-check commit counter only "
    "moves inside a tx, which holds _write_lock.")

declare_owner(
    "store.WriteActor", "spacedrive_tpu/store/actor.py::WriteActor",
    {
        "_stopping": guarded_by("_lock"),
        "_thread": guarded_by("_lock"),
        "_q": guarded_by("_lock"),
        "groups": single_thread(),
        "batches": single_thread(),
    },
    "Per-library single-writer group-commit actor: every product "
    "writer enqueues tickets (producers + the stop path mutate the "
    "lifecycle flags under the actor's _lock/condition leaf), while "
    "the shard tallies are the writer thread's alone — group "
    "formation state itself lives in _run_group locals, and the "
    "ticket handshake events are the cross-thread edges.")

declare_owner(
    "sync.HLC", "spacedrive_tpu/sync/hlc.py::HLC",
    {
        "_last": guarded_by("_lock"),
    },
    "Hybrid logical clock: ticked from every op-writing thread; "
    "monotonicity IS the CRDT ordering guarantee, so _last only moves "
    "under its lock.")

declare_owner(
    "sync.SyncManager", "spacedrive_tpu/sync/manager.py::SyncManager",
    {
        "_instance_ids": guarded_by("_meta_lock"),
        "timestamps": guarded_by("_meta_lock"),
        "_solo": guarded_by("_meta_lock"),
        "_sync_indexes_ready": guarded_by("_meta_lock"),
        "_op_log_high": guarded_by("_meta_lock"),
        "_has_shared_tombstones": guarded_by("_meta_lock"),
        "_on_created": loop_only(),
    },
    "Per-library sync engine: the in-memory caches (watermark vector, "
    "instance map, solo flag, clone fast-path facts) are mutated from "
    "to_thread job steps, loop-side ingest, and pairing — all under "
    "the _meta_lock leaf. The created-callback list is loop-side "
    "component wiring.")

declare_owner(
    "telemetry.Counter", "spacedrive_tpu/telemetry.py::Counter",
    {
        "_value": guarded_by("_lock"),
    },
    "Counter/Gauge sample cell: inc/set from any thread (jobs workers, "
    "device streams, the loop) under the per-metric leaf lock.")

declare_owner(
    "telemetry.Histogram", "spacedrive_tpu/telemetry.py::Histogram",
    {
        "_counts": guarded_by("_lock"),
        "_sum": guarded_by("_lock"),
        "_count": guarded_by("_lock"),
    },
    "Histogram cells: observe() is one bisect + three adds under the "
    "metric lock, from any thread.")

declare_owner(
    "telemetry.Metric", "spacedrive_tpu/telemetry.py::_Metric",
    {
        "_children": guarded_by("_lock"),
    },
    "Label-child map: double-checked read, creation under the parent "
    "lock — child creation races resolve to one cached child.")

declare_owner(
    "telemetry.MetricsRegistry",
    "spacedrive_tpu/telemetry.py::MetricsRegistry",
    {
        "_metrics": guarded_by("_lock"),
    },
    "The process-global name → metric map: registration happens at "
    "import time from any importing thread.")
