"""Node bootstrap: construct every service in order, own the event bus.

Mirrors `Node::new` (/root/reference/core/src/lib.rs:58-144): config
manager → libraries → job manager → (p2p later), with the library-load
hook wiring cold-resume, exactly the ordering the reference marks
ordering-sensitive (lib.rs:134-138). The event bus is the CoreEvent
channel (api/mod.rs:17-23) as a plain callback fan-out.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
import uuid as uuidlib
from typing import Any, Callable, Dict, List, Optional

from . import flags, persist, tasks, telemetry, tracing
from .fleet import FleetMonitor
from .health import HealthMonitor
from .jobs.manager import JobManager
from .library import Libraries, Library
from .store.db import uuid_bytes

NODE_CONFIG_VERSION = 1
NODE_CONFIG_NAME = "node_state.sdconfig"


class EventBus:
    """CoreEvent fan-out: JobProgress / JobUpdate / InvalidateOperation.

    Delivery discipline (round 12): in-process subscribers are
    SYNCHRONOUS callbacks on purpose — every registered callback is a
    cheap filter (the api procedures' on_event closures), so the emit
    loop holds no buffer at all and cannot grow one. The moment
    delivery crosses to a consumer that can stall — every websocket
    subscription — it goes through a bounded registry channel instead
    (api/server.py WsSubscriptionPump, channels.py `api.ws`):
    per-subscriber depth capped, TelemetrySnapshot frames coalesced to
    the newest, slow consumers shed into sd_chan_shed_total{api.ws}.
    A callback that does heavy work inline would show up as a
    loop_stall sanitizer violation, which is the enforcement half of
    this contract."""

    def __init__(self):
        self._subs: List[Callable[[dict], None]] = []

    def subscribe(self, cb: Callable[[dict], None]) -> Callable[[], None]:
        self._subs.append(cb)
        return lambda: self._subs.remove(cb)

    def emit(self, event: dict) -> None:
        for cb in list(self._subs):
            try:
                cb(event)
            except Exception:
                pass

    def invalidate_query(self, library_id, key: str) -> None:
        """invalidate_query! macro semantics (api/utils/invalidate.rs:131)."""
        self.emit({"type": "InvalidateOperation",
                   "library_id": str(library_id), "key": key})


def migrate_node_config(raw: dict) -> dict:
    """Versioned config migrator (util/migrator.rs:33-41 semantics):
    upgrade step by step from raw['version'] to NODE_CONFIG_VERSION."""
    version = raw.get("version", 0)
    if version > NODE_CONFIG_VERSION:
        raise ValueError(
            f"config version {version} is newer than supported "
            f"{NODE_CONFIG_VERSION} (time traveling backwards?)")
    while version < NODE_CONFIG_VERSION:
        if version == 0:
            raw.setdefault("id", uuidlib.uuid4().hex)
            raw.setdefault("name", "spacedrive-tpu-node")
            raw.setdefault("features", [])
        version += 1
        raw["version"] = version
    return raw


class NodeConfig:
    """node_state.sdconfig (node/config.rs:22-43)."""

    def __init__(self, path: str):
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                raw = json.load(f)
        else:
            raw = {}
        raw = migrate_node_config(raw)
        self.raw = raw
        self.save()

    @property
    def id(self) -> bytes:
        return bytes.fromhex(self.raw["id"])

    @property
    def name(self) -> str:
        return self.raw["name"]

    @property
    def features(self) -> List[str]:
        return list(self.raw.get("features", []))

    def toggle_feature(self, feature: str) -> bool:
        """BackendFeature toggle (api/mod.rs:28-48); returns new state."""
        feats = set(self.raw.get("features", []))
        if feature in feats:
            feats.remove(feature)
            enabled = False
        else:
            feats.add(feature)
            enabled = True
        self.raw["features"] = sorted(feats)
        self.save()
        return enabled

    def save(self) -> None:
        persist.atomic_write("node.config", self.path,
                             json.dumps(self.raw, indent=2))


class OrphanRemover:
    """Deletes objects with zero file_paths; 1-minute tick or on demand
    (core/src/object/orphan_remover.rs:17-40)."""

    TICK_S = 60

    def __init__(self, library: Library, owner: str = "orphan-remover"):
        self.library = library
        self._owner = owner
        self._task: Optional[asyncio.Task] = None

    def invoke(self) -> int:
        db = self.library.db
        rows = db.run("node.orphan_objects")
        if not rows:
            return 0
        sync = self.library.sync
        from .sync.manager import cascade_local_fks

        ops = [sync.shared_delete("object", r["pub_id"]) for r in rows]
        with sync.write_ops(ops) as conn:
            for r in rows:
                # membership rows (tags/labels/albums/spaces) have no
                # DDL ON DELETE — a raw delete would FK-fail and abort
                # the whole batch (round-5 review finding)
                cascade_local_fks(conn, "object", r["id"])
                db.run("node.object_delete", (r["id"],), conn=conn)
        return len(rows)

    def start(self) -> None:
        async def loop():
            while True:
                await asyncio.sleep(self.TICK_S)
                await asyncio.to_thread(self.invoke)
        self._task = tasks.spawn(
            f"orphan/{self.library.id.hex[:8]}", loop(), owner=self._owner)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


class TelemetryReporter:
    """Periodic TelemetrySnapshot events on the node event bus: the
    webui's (and any subscriber's) push-based view of the metrics
    registry — the same snapshot `node.metrics` serves on demand.
    Interval from SDTPU_TELEMETRY_INTERVAL seconds (default 15); the
    loop skips emission entirely while telemetry is disabled."""

    DEFAULT_INTERVAL_S = 15.0

    def __init__(self, events: EventBus,
                 interval_s: Optional[float] = None,
                 owner: str = "telemetry-reporter"):
        self.events = events
        if interval_s is None:
            interval_s = flags.get("SDTPU_TELEMETRY_INTERVAL")
        self.interval_s = max(0.05, interval_s)
        self._owner = owner
        self._task: Optional[asyncio.Task] = None

    def emit_snapshot(self) -> None:
        self.events.emit({
            "type": "TelemetrySnapshot",
            "ts": time.time(),
            "metrics": telemetry.snapshot(),
        })

    def start(self) -> None:
        async def loop():
            while True:
                await asyncio.sleep(self.interval_s)
                if telemetry.enabled():
                    self.emit_snapshot()
        if self._task is None:
            self._task = tasks.spawn(
                "telemetry-reporter", loop(), owner=self._owner)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


class Node:
    def __init__(self, data_dir: str):
        # Production nodes honor SDTPU_SANITIZE=1 too: violations count
        # into sd_sanitize_* telemetry (mode `count`) instead of
        # raising. No-op (and zero overhead) when the flag is unset.
        from . import sanitize
        sanitize.install()
        # SDTPU_LOG_JSON: trace-correlated structured logging — a
        # no-op when the flag is off, one handler per process when on.
        tracing.install_json_logging()
        # SDTPU_LOG_RING (default on): bounded in-memory log ring so
        # incident bundles can freeze a trace-stamped log tail.
        tracing.install_log_ring()
        self.data_dir = os.path.abspath(data_dir)
        os.makedirs(self.data_dir, exist_ok=True)
        self.config = NodeConfig(os.path.join(self.data_dir, NODE_CONFIG_NAME))
        # Root of this node's supervisor ownership tree (tasks.py):
        # every long-lived component spawns under it, shutdown() reaps
        # it. Process-unique so two nodes in one test never cross-reap.
        self.task_owner = tasks.unique_owner("node")
        self.events = EventBus()
        self.libraries = Libraries(self.data_dir)
        self.jobs = JobManager(
            on_event=self.events.emit,
            services={"data_dir": self.data_dir, "node": self},
            owner=f"{self.task_owner}/jobs",
        )
        self.orphan_removers: Dict[uuidlib.UUID, OrphanRemover] = {}
        self.telemetry_reporter = TelemetryReporter(
            self.events, owner=f"{self.task_owner}/reporter")
        # Health observatory (health.py): delta-samples every metric
        # family into bounded rings and attributes saturation; serves
        # node.health and the sd_health_state{subsystem} gauges.
        self.health = HealthMonitor(
            self.events, owner=f"{self.task_owner}/health",
            node_id=self.config.id.hex(), node_name=self.config.name)
        # Fleet observatory (fleet.py): polls paired peers' obs.health
        # snapshots into bounded rings and merges the per-(node,
        # subsystem) fleet view; serves fleet.health / fleet.metrics /
        # fleet.trace.export.
        self.fleet = FleetMonitor(self, owner=f"{self.task_owner}/fleet")
        # Incident observatory (incidents.py): the always-on black
        # box. install() is process-global and idempotent (first node
        # wins, like the sanitizer); recovery of a prior crash's
        # partially-written bundle happens here, before any trigger
        # can fire. SDTPU_INCIDENTS=off → None.
        from . import incidents
        self.incidents = incidents.install(
            dir_path=os.path.join(self.data_dir, "incidents"),
            monitor=self.health, events=self.events,
            node_id=self.config.id.hex(), node_name=self.config.name)
        self.p2p = None  # created by start_p2p (P2PManager)
        # Thumbnailer actor (lib.rs:116 Thumbnailer::new): constructed at
        # bootstrap (cache version migration runs here), loop starts with
        # the node.
        from .media.actor import Thumbnailer
        self.thumbnailer = Thumbnailer(self)
        self._started = False
        self.libraries.on_event(self._on_library_event)
        # Warm the native I/O plane at bootstrap (may compile libsdio.so
        # once) so watcher-triggered hot paths never hit a cold build.
        from . import native as _native
        _native.available()

    # -- lifecycle (ordering-sensitive: lib.rs:134-138) --------------------

    async def start(self) -> None:
        """Load libraries, cold-resume their interrupted jobs, start
        actors."""
        self._started = True
        self.thumbnailer.start()
        try:
            self.telemetry_reporter.start()
            self.health.start()
            self.fleet.start()
        except RuntimeError:
            pass  # no running loop (sync tests); node.metrics and the
            # on-demand node.health / fleet.health samples still work
        self.libraries.init()
        # Dev seed (util/debug_initializer.rs): data-dir init.json.
        # BEFORE cold_resume so reset_on_startup never deletes a library
        # whose interrupted jobs were just re-dispatched; errors are
        # contained — a bad seed file must not become a boot loop.
        from .debug_init import apply_init_file

        try:
            await apply_init_file(self)
        except Exception as e:
            self.events.emit({"type": "DebugInitError", "error": str(e)})
        for lib in self.libraries.list():
            # one resume sweep per LIBRARY — each is its own database
            await self.jobs.cold_resume(lib)  # sdlint: ok[tx-shape]
            self._ensure_actors(lib)

    def _on_library_event(self, kind: str, library: Library) -> None:
        if kind == "load":
            self._ensure_actors(library)
        elif kind == "delete":
            remover = self.orphan_removers.pop(library.id, None)
            if remover:
                remover.stop()
        # query invalidation for the frontend
        self.events.invalidate_query(library.id, "library.list")

    def _ensure_actors(self, library: Library) -> None:
        if library.id not in self.orphan_removers:
            remover = OrphanRemover(
                library, owner=f"{self.task_owner}/orphan-remover")
            try:
                remover.start()
            except RuntimeError:
                pass  # no running loop (sync tests); invoke() still works
            self.orphan_removers[library.id] = remover

    async def start_p2p(self, host: str = "0.0.0.0", port: int = 0,
                        enable_discovery: bool = True) -> int:
        """Bring up the p2p plane: listener + discovery + the
        NetworkedLibraries sync fan-out (lib.rs:102 P2PManager::new +
        p2p.start at :138). Returns the bound port."""
        from .p2p.manager import P2PManager
        from .p2p.sync_net import NetworkedLibraries

        if self.p2p is None:
            self.p2p = P2PManager(self, enable_discovery=enable_discovery)
            NetworkedLibraries(self, self.p2p)
        if self.p2p.server is not None:
            return self.p2p.port  # already listening; don't double-bind
        return await self.p2p.start(host, port)

    async def shutdown(self) -> None:
        """Node::shutdown (lib.rs:205): pause jobs, stop actors, then
        reap the supervisor subtree as the backstop — anything a
        component forgot (a mid-flight origin fan-out, a watcher scan,
        an auth poll whose subscriber vanished) is cancelled-and-
        gathered by ownership tree BEFORE the library DBs close, so
        cancellation cleanup can still write. A task that survives the
        reap grace is an orphan: counted in sd_task_orphaned_total and
        raised as a sanitizer violation in tier-1."""
        await self.jobs.shutdown()
        self.telemetry_reporter.stop()
        self.health.stop()
        self.fleet.stop()
        await self.thumbnailer.stop()
        if self.p2p is not None:
            await self.p2p.stop()
        for remover in self.orphan_removers.values():
            remover.stop()
        try:
            await tasks.reap(self.task_owner)
        finally:
            # The DBs close even when the reap raises on an orphan
            # (raise mode): an aborted shutdown must not leak open
            # library handles on top of the orphaned task.
            for lib in self.libraries.list():
                lib.db.close()
            # The shared staging executor (ops/staging.py) is module-
            # global — threads the supervisor reap cannot see. Close it
            # explicitly (off-loop: the close waits for in-flight
            # reads; shielded so a cancelled shutdown still completes
            # the pool close instead of abandoning it half-torn-down);
            # a later identify in this process just re-creates it, so
            # multi-node tests stay correct.
            from .ops import staging as _staging
            await asyncio.shield(
                asyncio.to_thread(_staging.shutdown_stage_pool))

    async def close(self) -> None:
        """Alias for shutdown() — the supervisor docs' name for the
        reap edge."""
        await self.shutdown()

    # -- convenience -------------------------------------------------------

    def create_library(self, name: str, lib_id=None) -> Library:
        lib = self.libraries.create(
            name, node_name=self.config.name, node_pub_id=self.config.id,
            lib_id=lib_id)
        return lib
