"""Mesh construction and sharding helpers for multi-chip execution."""

from .mesh import batch_mesh, pad_to_multiple, tile_mesh

__all__ = ["batch_mesh", "tile_mesh", "pad_to_multiple"]
