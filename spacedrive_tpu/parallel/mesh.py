"""Device-mesh construction for the identification and dedup pipelines.

The framework's parallelism axes (the TPU-native analog of the reference's
job/step concurrency, SURVEY.md §2.5-2.6):

- ``data``: files are independent → batch dim sharded, no collectives
  (hashing, pHash, EXIF tensors).
- ``rows``/``cols`` 2-D tile mesh: Hamming all-pairs over N digests is an
  N×N tile grid; each device owns a row-block and all-gathers column
  blocks over ICI (see ops/hamming.py).

On this machine there is one real TPU chip; multi-chip layouts are
exercised on a virtual CPU mesh (tests) and by the driver's
``dryrun_multichip``.
"""

from __future__ import annotations

import functools
import math

import jax
import numpy as np
from jax.sharding import Mesh


@functools.lru_cache(maxsize=16)
def _batch_mesh_cached(devices: tuple) -> Mesh:
    return Mesh(np.array(devices), axis_names=("data",))


def batch_mesh(devices=None) -> Mesh:
    """1-D mesh over all devices for data-parallel batch work.

    Cached per device tuple (round-10 retrace hygiene): callers like
    the validator build a mesh per STEP, and jit entry points that take
    the mesh as a static argument (ops/seqhash._sharded_reduce) key
    their trace cache on it — returning the same Mesh object for the
    same device set keeps those at one compiled program per mesh
    instead of risking one per step."""
    devices = tuple(jax.devices()) if devices is None else tuple(devices)
    return _batch_mesh_cached(devices)


@functools.lru_cache(maxsize=16)
def _tile_mesh_cached(devices: tuple) -> Mesh:
    n = len(devices)
    rows = 1
    for r in range(int(math.isqrt(n)), 0, -1):
        if n % r == 0:
            rows = r
            break
    cols = n // rows
    return Mesh(np.array(devices).reshape(rows, cols),
                axis_names=("rows", "cols"))


def tile_mesh(devices=None) -> Mesh:
    """2-D (rows, cols) mesh for all-pairs tiles; rows*cols = n_devices.

    Prefers the squarest factorization so tile all-gathers move the
    least data per device. Cached per device tuple (see batch_mesh)."""
    devices = tuple(jax.devices()) if devices is None else tuple(devices)
    return _tile_mesh_cached(devices)


def device_ring(limit: int = 0, devices=None) -> tuple:
    """The local devices the depth-N identify pipeline round-robins
    in-flight batches across (ops/overlap.py): the batch_mesh device
    tuple, optionally capped at `limit` (> 0).

    Returning the SAME tuple the cached batch mesh is built from keeps
    one code path covering 1→8 chips: single-chip hosts get a ring of
    one, pod slices get per-device staging streams, and the mesh-cached
    sharded kernels (blake3 sharded, seqhash reduce) see an identical
    device ordering when a caller composes both."""
    devices = tuple(jax.devices()) if devices is None else tuple(devices)
    if limit and limit > 0:
        devices = devices[:limit]
    return devices or tuple(jax.devices())[:1]


def pad_to_multiple(n: int, m: int) -> int:
    return -(-n // m) * m
