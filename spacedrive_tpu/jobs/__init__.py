from .job import (
    EarlyFinish,
    JOB_REGISTRY,
    JobContext,
    JobError,
    JobState,
    StatefulJob,
    StepOutcome,
    register_job,
)
from .manager import AlreadyRunning, JobBuilder, JobManager, MAX_WORKERS
from .report import JobReport, JobStatus

__all__ = [
    "AlreadyRunning", "EarlyFinish", "JOB_REGISTRY", "JobBuilder",
    "JobContext", "JobError", "JobManager", "JobReport", "JobState",
    "JobStatus", "MAX_WORKERS", "StatefulJob", "StepOutcome", "register_job",
]
