"""Job reports: the DB-persisted record of every job run.

Mirrors the semantics of /root/reference/core/src/job/report.rs:41-257 —
status enum values are kept numerically identical so dashboards and
tests can compare against the reference's conventions.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import msgpack

from ..store import Database
from ..telemetry import (
    JOBS_COMPLETED,
    JOBS_ITEMS_PER_SEC,
    JOBS_ITEMS_PROCESSED,
    JOB_DURATION_SECONDS,
)

# Record separator for the errors_text TEXT column: tracebacks contain
# blank lines, so a plain "\n\n" join would split one error into many.
_ERR_SEP = "\n\x1e\n"


class JobStatus(enum.IntEnum):
    QUEUED = 0
    RUNNING = 1
    COMPLETED = 2
    CANCELED = 3
    FAILED = 4
    PAUSED = 5
    COMPLETED_WITH_ERRORS = 6

    @property
    def is_final(self) -> bool:
        return self in (
            JobStatus.COMPLETED,
            JobStatus.CANCELED,
            JobStatus.FAILED,
            JobStatus.COMPLETED_WITH_ERRORS,
        )


@dataclass
class JobReport:
    id: bytes
    name: str
    status: JobStatus = JobStatus.QUEUED
    action: Optional[str] = None
    errors_text: list = field(default_factory=list)
    data: Optional[bytes] = None  # serialized JobState for resume
    metadata: Dict[str, Any] = field(default_factory=dict)
    parent_id: Optional[bytes] = None
    task_count: int = 0
    completed_task_count: int = 0
    date_created: Optional[int] = None
    date_started: Optional[int] = None
    date_completed: Optional[int] = None
    date_estimated_completion: Optional[int] = None

    # -- telemetry --------------------------------------------------------

    def record_metrics(self, duration_s: Optional[float] = None) -> None:
        """Publish this report's terminal facts to the node registry:
        completion counters by status, run duration, items processed
        and the derived items/s of the finished run. Called once per
        worker run from _emit_final (paused runs count too — their
        status label says so)."""
        JOBS_COMPLETED.labels(status=self.status.name.lower()).inc()
        if duration_s is not None and duration_s >= 0:
            JOB_DURATION_SECONDS.labels(name=self.name).observe(duration_s)
            if self.completed_task_count and duration_s > 0:
                JOBS_ITEMS_PER_SEC.labels(name=self.name).set(
                    self.completed_task_count / duration_s)
        if self.completed_task_count:
            JOBS_ITEMS_PROCESSED.labels(name=self.name).inc(
                self.completed_task_count)

    # -- persistence ------------------------------------------------------

    def create(self, db: Database) -> None:
        self.date_created = int(time.time())
        db.insert("job", self._row())

    def update(self, db: Database) -> None:
        db.update("job", self.id, self._row(exclude_id=True))

    def _row(self, exclude_id: bool = False) -> Dict[str, Any]:
        row = {
            "name": self.name,
            "action": self.action,
            "status": int(self.status),
            "errors_text": _ERR_SEP.join(self.errors_text) or None,
            "data": self.data,
            "metadata": msgpack.packb(self.metadata, use_bin_type=True)
            if self.metadata else None,
            "parent_id": self.parent_id,
            "task_count": self.task_count,
            "completed_task_count": self.completed_task_count,
            "date_estimated_completion": self.date_estimated_completion,
            "date_created": self.date_created,
            "date_started": self.date_started,
            "date_completed": self.date_completed,
        }
        if not exclude_id:
            row = {"id": self.id, **row}
        return row

    @classmethod
    def from_row(cls, row) -> "JobReport":
        meta = row["metadata"]
        return cls(
            id=row["id"],
            name=row["name"],
            status=JobStatus(row["status"] or 0),
            action=row["action"],
            errors_text=row["errors_text"].split(_ERR_SEP)
            if row["errors_text"] else [],
            data=row["data"],
            metadata=msgpack.unpackb(meta, raw=False) if meta else {},
            parent_id=row["parent_id"],
            task_count=row["task_count"] or 0,
            completed_task_count=row["completed_task_count"] or 0,
            date_created=row["date_created"],
            date_started=row["date_started"],
            date_completed=row["date_completed"],
            date_estimated_completion=row["date_estimated_completion"],
        )
