"""The job driver loop: one asyncio task per running job.

Multiplexes the in-flight step against worker commands the way the
reference's driver does with tokio::select!
(/root/reference/core/src/job/mod.rs:494-901): commands win, and on
Pause/Shutdown the remaining steps — including the interrupted one, which
is cancelled and pushed back — are serialized into the job report
(mod.rs:694-775). Steps are therefore contractually idempotent.

Progress reporting matches worker.rs:228-292: events are throttled to
500 ms, carry task counts and an ETA extrapolated from elapsed/completed,
and every status transition is persisted.
"""

from __future__ import annotations

import asyncio
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .. import channels, tasks
from ..telemetry import JOBS_EARLY_FINISH, JOBS_STEP_ERRORS, JOB_STEP_SECONDS
from ..tracing import current_trace_id
from ..tracing import span as trace_span
from .job import (
    EarlyFinish,
    JobContext,
    JobError,
    JobState,
    StatefulJob,
    StepOutcome,
)
from .report import JobReport, JobStatus

PROGRESS_THROTTLE_S = 0.5  # worker.rs:273
# Periodic crash checkpoint: the reference serializes JobState only on
# pause/shutdown, so a SIGKILL replays the whole job from step 0 (saved
# only by step idempotency). Persisting the state every few seconds
# bounds the replay window to the last interval.
CHECKPOINT_INTERVAL_S = 3.0


class WorkerCommand:
    PAUSE = "pause"
    RESUME = "resume"
    CANCEL = "cancel"
    SHUTDOWN = "shutdown"


class Worker:
    """Drives one job to completion, pause, cancellation, or failure."""

    def __init__(
        self,
        job: StatefulJob,
        report: JobReport,
        library: Any,
        on_event: Callable[[dict], None],
        services: Optional[dict] = None,
        resume_state: Optional[JobState] = None,
    ):
        self.job = job
        self.report = report
        self.library = library
        self.on_event = on_event
        self.services = services or {}
        self.resume_state = resume_state
        # Bounded command inbox (channels.py registry): the drain is
        # latest-wins, so shed_oldest under a command flood preserves
        # semantics exactly while capping depth.
        self.commands = channels.channel("jobs.worker.commands")
        self._last_progress_emit = 0.0
        self._last_checkpoint = time.monotonic()
        self._started_at = 0.0

    # -- control ----------------------------------------------------------

    def command(self, cmd: str) -> None:
        self.commands.put_nowait(cmd)

    # -- progress ---------------------------------------------------------

    def _progress(self, task_count=None, completed=None, message=None) -> None:
        r = self.report
        if task_count is not None:
            r.task_count = task_count
        if completed is not None:
            r.completed_task_count = completed
        now = time.monotonic()
        if r.completed_task_count and r.task_count:
            per_task = (now - self._started_at) / r.completed_task_count
            remaining = per_task * (r.task_count - r.completed_task_count)
            r.date_estimated_completion = int(time.time() + remaining)
        if now - self._last_progress_emit >= PROGRESS_THROTTLE_S:
            self._last_progress_emit = now
            self.on_event({
                "type": "JobProgress",
                "id": r.id.hex(),  # JSON-safe: ids cross the ws boundary
                "name": r.name,
                "task_count": r.task_count,
                "completed_task_count": r.completed_task_count,
                "message": message,
                "estimated_completion": r.date_estimated_completion,
            })

    # -- driver -----------------------------------------------------------

    async def run(self) -> JobStatus:
        # Root span of this run's trace: every job.step span (and any
        # span opened inside step bodies — contextvars survive
        # ensure_future and asyncio.to_thread) nests under it.
        with trace_span(f"job/{self.report.name}",
                        job_id=self.report.id.hex()):
            # Stamp the run's trace id into the persisted report so an
            # operator can jump from a job row to its spans
            # (node.spans {trace: ...}) and its flight-recorder
            # timeline (node.trace.export) after the fact.
            self.report.metadata["trace"] = current_trace_id()
            try:
                status = await self._run_inner()
            except asyncio.CancelledError:
                status = await self._persist_paused_or_fail(
                    "worker task cancelled")
            except Exception as e:  # noqa: BLE001 — job-level catch-all
                await self._cleanup_quietly(None)
                self.report.status = JobStatus.FAILED
                self.report.errors_text.append(
                    "".join(traceback.format_exception(e)).strip()
                )
                self.report.date_completed = int(time.time())
                self.report.data = None
                await asyncio.to_thread(self.report.update, self.library.db)
            else:
                self.report.status = status
        self._emit_final()
        return self.report.status

    def _emit_final(self) -> None:
        self.report.record_metrics(
            duration_s=(time.monotonic() - self._started_at)
            if self._started_at else None)
        self.on_event({
            "type": "JobUpdate",
            "id": self.report.id.hex(),
            "name": self.report.name,
            "status": int(self.report.status),
        })

    async def _run_inner(self) -> JobStatus:
        r = self.report
        ctx = JobContext(self.library, report_progress=self._progress,
                         services=self.services, job_id=r.id)
        self._started_at = time.monotonic()
        r.status = JobStatus.RUNNING
        r.date_started = int(time.time())
        await asyncio.to_thread(r.update, self.library.db)

        errors: List[str] = []
        if self.resume_state is not None and (
            self.resume_state.steps or self.resume_state.step_number
        ):
            state = self.resume_state
            errors = list(r.errors_text)
        else:
            # Fresh run — including a QUEUED job resumed from the DB whose
            # state blob was written at ingest, before init ever ran.
            try:
                data, steps = await self.job.init(ctx)
            except EarlyFinish:
                JOBS_EARLY_FINISH.inc()
                r.status = JobStatus.COMPLETED
                r.data = None  # clear the at-ingest state blob
                r.date_completed = int(time.time())
                await asyncio.to_thread(r.update, self.library.db)
                return JobStatus.COMPLETED
            next_chain = (
                self.resume_state.next_chain if self.resume_state else []
            )
            state = JobState(
                init_args=self.job.persistable_init_args(),
                data=data,
                steps=deque(steps),
                step_number=0,
                run_metadata={},
                next_chain=next_chain,
            )
        if not r.task_count:
            r.task_count = len(state.steps)

        while state.steps:
            # Commands take priority over starting the next step.
            cmd = self._drain_commands()
            if cmd == WorkerCommand.CANCEL:
                return await self._finish_cancel(state)
            if cmd in (WorkerCommand.PAUSE, WorkerCommand.SHUTDOWN):
                return await self._persist_paused(state, errors)

            step_task = asyncio.ensure_future(
                self._spanned_step(ctx, state)
            )
            cmd_task = asyncio.ensure_future(self.commands.get())
            await asyncio.wait(
                {step_task, cmd_task}, return_when=asyncio.FIRST_COMPLETED
            )
            if not step_task.done():
                # A command arrived mid-step.
                cmd = cmd_task.result()
                if cmd == WorkerCommand.RESUME:
                    # Spurious (job is running): let the step finish and
                    # fall through to normal outcome handling below.
                    await asyncio.wait({step_task})
                else:
                    # Interrupted-step reap: swallows the step's own
                    # cancellation (and captures a racing step error —
                    # the step replays from its persisted front), but
                    # OUR cancellation mid-gather still propagates.
                    await tasks.cancel_and_gather(step_task)
                    if cmd == WorkerCommand.CANCEL:
                        return await self._finish_cancel(state)
                    # interrupted step stays at the front for idempotent replay
                    return await self._persist_paused(state, errors)
            elif cmd_task.done():
                # Command landed in the same tick the step finished;
                # cancel() would silently drop it. Re-queue so the next
                # loop iteration's drain handles it.
                self.commands.put_nowait(cmd_task.result())
            else:
                cmd_task.cancel()
            try:
                outcome = step_task.result()
            except JobError:
                raise
            except Exception as e:  # noqa: BLE001 — non-fatal step error
                JOBS_STEP_ERRORS.inc()
                errors.append(
                    f"step {state.step_number}: "
                    + "".join(traceback.format_exception(e)).strip()
                )
                outcome = None
            if isinstance(outcome, StepOutcome):
                state.steps.extend(outcome.more_steps)
                r.task_count += len(outcome.more_steps)
                JOBS_STEP_ERRORS.inc(len(outcome.errors))
                errors.extend(outcome.errors)
                for k, v in outcome.metadata.items():
                    state.run_metadata[k] = v
            state.steps.popleft()
            state.step_number += 1
            self._progress(completed=state.step_number)
            now = time.monotonic()
            if now - self._last_checkpoint >= CHECKPOINT_INTERVAL_S:
                self._last_checkpoint = now
                # Crash checkpoint: status stays RUNNING; cold_resume
                # rehydrates from this blob after a hard kill. Strictly
                # best-effort — an optimization write must never kill a
                # healthy job — and off the event loop (the blob is
                # O(remaining steps) for batch jobs).
                try:
                    await asyncio.to_thread(
                        self._persist_state, state, errors)
                except Exception:  # noqa: BLE001 — retry next interval
                    pass

        # A command that landed in the same tick the FINAL step finished was
        # re-queued above and would otherwise be dropped. CANCEL is still
        # honored (finalize hasn't run); PAUSE on a finished job is moot.
        if self._drain_commands() == WorkerCommand.CANCEL:
            return await self._finish_cancel(state)

        meta = await self.job.finalize(ctx, state.data, state.run_metadata)
        # Bulk jobs run with wal_autocheckpoint off (store/db.py); fold
        # the accumulated WAL back now, off the event loop and without
        # blocking concurrent writers.
        await asyncio.to_thread(self.library.db.checkpoint_passive)
        if meta:
            r.metadata.update(meta)
        r.errors_text = errors
        r.completed_task_count = state.step_number
        r.data = None
        r.date_completed = int(time.time())
        r.status = (
            JobStatus.COMPLETED_WITH_ERRORS if errors else JobStatus.COMPLETED
        )
        await asyncio.to_thread(r.update, self.library.db)
        return r.status

    async def _spanned_step(self, ctx: JobContext, state: JobState):
        """One step under a child span of the job's root trace (plus the
        per-step latency histogram). Reads the step from the deque head
        so the interrupted-step push-back contract is untouched."""
        t0 = time.perf_counter()
        try:
            with trace_span("job.step", job=self.report.name,
                            step=state.step_number):
                return await self.job.execute_step(
                    ctx, state.data, state.steps[0], state.step_number)
        finally:
            JOB_STEP_SECONDS.labels(name=self.report.name).observe(
                time.perf_counter() - t0)

    def _drain_commands(self) -> Optional[str]:
        """Pop the latest pending command (latest wins: a RESUME sent after
        a not-yet-actioned PAUSE cancels it)."""
        cmd = None
        while not self.commands.empty():
            cmd = self.commands.get_nowait()
        return cmd

    def _persist_state(self, state: JobState, errors: List[str]) -> None:
        """Serialize + write the resumable state blob (shared by the
        pause path and the periodic crash checkpoint)."""
        self.report.data = state.serialize()
        self.report.errors_text = list(errors)
        self.report.completed_task_count = state.step_number
        self.report.update(self.library.db)

    async def _persist_paused(self, state: JobState,
                              errors: List[str]) -> JobStatus:
        self.report.status = JobStatus.PAUSED
        await asyncio.to_thread(self._persist_state, state, errors)
        return JobStatus.PAUSED

    async def _persist_paused_or_fail(self, why: str) -> JobStatus:
        # Hard cancellation of the worker task (process shutdown): we have
        # no state object in scope — report as paused if a checkpoint was
        # already written, else failed.
        if self.report.data is not None:
            self.report.status = JobStatus.PAUSED
        else:
            self.report.status = JobStatus.FAILED
            self.report.errors_text.append(why)
        await asyncio.to_thread(self.report.update, self.library.db)
        return self.report.status

    async def _cleanup_quietly(self, data) -> None:
        """Run the job's no-finalize teardown hook; never raises."""
        try:
            await self.job.cleanup(
                JobContext(self.library, services=self.services,
                           job_id=self.report.id), data)
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass

    async def _finish_cancel(self, state: JobState) -> JobStatus:
        await self._cleanup_quietly(state.data)
        self.report.status = JobStatus.CANCELED
        self.report.data = None
        self.report.completed_task_count = state.step_number
        self.report.date_completed = int(time.time())
        await asyncio.to_thread(self.report.update, self.library.db)
        return JobStatus.CANCELED
