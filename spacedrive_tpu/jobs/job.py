"""StatefulJob contract: resumable, checkpointable units of work.

This is the framework's stable workload boundary, mirroring the semantics
of the reference's `StatefulJob` trait
(/root/reference/core/src/job/mod.rs:68-110): `init` produces work steps,
`execute_step` runs one step (and may append more), `finalize` reports
metadata. The whole job state — init args, working data, remaining steps,
step number, run metadata — is msgpack-serializable, so jobs pause,
survive process death, and cold-resume (mod.rs:694-775 semantics).

Differences from the reference, chosen for the TPU design rather than
ported: jobs are asyncio-native (the driver loop lives in
jobs/worker.py), steps must be *idempotent* (an interrupted step replays
on resume — required because a device batch in flight cannot be
serialized mid-kernel, SURVEY.md §7 hard-part 3), and device work runs on
an executor thread so the event loop stays responsive while XLA blocks.
"""

from __future__ import annotations

import hashlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Type

import msgpack


def _canonical(v: Any) -> Any:
    """Recursively sort dict keys so msgpack bytes are order-stable."""
    if isinstance(v, dict):
        return {k: _canonical(v[k]) for k in sorted(v)}
    if isinstance(v, (list, tuple)):
        return [_canonical(x) for x in v]
    return v


class JobError(Exception):
    pass


class EarlyFinish(JobError):
    """Job has nothing to do; complete cleanly (file_identifier_job.rs:131)."""


@dataclass
class StepOutcome:
    """Result of one execute_step call.

    more_steps are appended to the back of the queue (the indexer defers
    directory walks this way); errors are non-fatal and accumulate into
    the report (JobRunErrors semantics, job/mod.rs:31).
    """

    more_steps: List[Any] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)
    metadata: Dict[str, Any] = field(default_factory=dict)


class StatefulJob:
    """Base class for every workload job.

    Subclasses set NAME (stable, used for DB dispatch on resume) and
    implement init/execute_step/finalize. `init_args` must be a
    msgpack-serializable dict: it is both the checkpoint identity and the
    dedup hash input (job/manager.rs:107-122 semantics).
    """

    NAME: str = ""
    IS_BATCHED: bool = False  # task_count counts batches, not items
    # Init-arg names that must never touch the DB (secrets like
    # passwords). They serialize as None; a cold-resumed job gets None
    # back and must degrade gracefully (step error, not crash).
    TRANSIENT_ARGS: frozenset = frozenset()

    def __init__(self, **init_args: Any):
        self.init_args = init_args

    def persistable_init_args(self) -> Dict[str, Any]:
        """init_args with TRANSIENT_ARGS values redacted to None — the
        only form that may be written to the job table."""
        if not self.TRANSIENT_ARGS:
            return self.init_args
        return {k: (None if k in self.TRANSIENT_ARGS else v)
                for k, v in self.init_args.items()}

    # -- identity ---------------------------------------------------------

    def hash(self) -> str:
        """Dedup hash over (NAME, init args), insensitive to kwarg order."""
        payload = msgpack.packb(
            {"name": self.NAME, "init": _canonical(self.init_args)},
            use_bin_type=True,
        )
        return hashlib.blake2b(payload, digest_size=16).hexdigest()

    # -- lifecycle (override) --------------------------------------------

    async def init(self, ctx: "JobContext") -> tuple[Dict[str, Any], List[Any]]:
        """Return (data, steps). Raise EarlyFinish when there is no work.

        Jobs whose init is pure sync work (queries + step building —
        the common batch-job shape) define `_init_sync(ctx)` instead of
        overriding this: the base runs it off the event loop, so the
        blocking-in-async discipline (tools/sdlint, sanitize.py) holds
        by construction for every such job."""
        sync_init = getattr(self, "_init_sync", None)
        if sync_init is not None:
            import asyncio

            return await asyncio.to_thread(sync_init, ctx)
        raise NotImplementedError

    async def execute_step(
        self, ctx: "JobContext", data: Dict[str, Any], step: Any, step_number: int
    ) -> Optional[StepOutcome]:
        raise NotImplementedError

    async def cleanup(self, ctx: "JobContext",
                      data: Optional[Dict[str, Any]]) -> None:
        """Best-effort teardown when the job ends WITHOUT finalize
        (cancellation or a job-level failure). Jobs that alter
        library-wide state for the duration of a run (the identifier's
        bulk index drop) restore it here. Must be idempotent; the
        worker swallows exceptions. `data` may be None when the job
        died before any state existed."""
        return None

    async def finalize(
        self, ctx: "JobContext", data: Dict[str, Any], metadata: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        return metadata or None


@dataclass
class JobState:
    """Everything needed to resume a job after pause or process death.

    `next_chain` persists queued follow-up jobs as (name, init_args)
    pairs so a paused indexer still triggers its identifier after a
    process restart (the reference keeps next_jobs inside the serialized
    JobState too, core/src/job/mod.rs:248-254).
    """

    init_args: Dict[str, Any]
    data: Dict[str, Any]
    steps: Deque[Any]
    step_number: int
    run_metadata: Dict[str, Any]
    next_chain: List[Any] = field(default_factory=list)

    def serialize(self) -> bytes:
        return msgpack.packb(
            {
                "init": self.init_args,
                "data": self.data,
                "steps": list(self.steps),
                "step_number": self.step_number,
                "run_metadata": self.run_metadata,
                "next_chain": [
                    [name, init] for name, init in self.next_chain
                ],
            },
            use_bin_type=True,
        )

    @classmethod
    def deserialize(cls, blob: bytes) -> "JobState":
        raw = msgpack.unpackb(blob, raw=False, strict_map_key=False)
        return cls(
            init_args=raw["init"],
            data=raw["data"],
            steps=deque(raw["steps"]),
            step_number=raw["step_number"],
            run_metadata=raw["run_metadata"],
            next_chain=[tuple(p) for p in raw.get("next_chain", [])],
        )

    @classmethod
    def fresh(cls, init_args: Dict[str, Any],
              next_chain: Optional[List[Any]] = None) -> "JobState":
        """Pre-init state written at ingest so QUEUED jobs survive restarts."""
        return cls(
            init_args=init_args, data={}, steps=deque(), step_number=0,
            run_metadata={}, next_chain=list(next_chain or []),
        )


class JobContext:
    """Services visible to a running job: the library, progress, events.

    `library` duck-types {db, sync, ...}; `services` carries node-level
    actors (thumbnailer, staging pool) without jobs importing the node.
    """

    def __init__(self, library: Any, report_progress=None,
                 services: Optional[dict] = None,
                 job_id: Optional[bytes] = None):
        self.library = library
        self.services = services or {}
        # The running job's report id — keys job_scratch rows (spooled
        # step payloads) so sweeps can target one job's leftovers.
        self.job_id = job_id
        self._report_progress = report_progress or (lambda **kw: None)

    @property
    def db(self):
        return self.library.db

    def progress(self, *, task_count: Optional[int] = None,
                 completed: Optional[int] = None,
                 message: Optional[str] = None) -> None:
        """Report progress; the worker throttles and adds ETA."""
        self._report_progress(
            task_count=task_count, completed=completed, message=message
        )


# -- registry: NAME → class, for cold-resume dispatch ----------------------
# (the reference does this with a macro over its 8 job types,
#  core/src/job/manager.rs:362-399)

JOB_REGISTRY: Dict[str, Type[StatefulJob]] = {}  # sdlint: ok[unbounded-growth] import-time job-class registry: one entry per @register_job class, not per event


def register_job(cls: Type[StatefulJob]) -> Type[StatefulJob]:
    assert cls.NAME, cls
    JOB_REGISTRY[cls.NAME] = cls
    return cls


def job_from_state(name: str, state: JobState) -> StatefulJob:
    cls = JOB_REGISTRY[name]
    job = cls(**state.init_args)
    return job
