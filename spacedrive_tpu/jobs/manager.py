"""Job manager: worker pool, dedup, FIFO queue, chaining, cold resume.

Semantics mirrored from /root/reference/core/src/job/manager.rs — at most
MAX_WORKERS jobs run concurrently (manager.rs:32), a job whose
(name, init) hash matches a running or queued job is rejected
(manager.rs:107-122), completed jobs trigger their queued `next_jobs`
chain, and `cold_resume` re-hydrates Paused/Running/Queued reports from
the DB at startup, failing those without a state blob
(manager.rs:269-319).
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Dict, List, Optional

from .. import channels, tasks
from ..store import uuid_bytes as new_job_id
from ..telemetry import (
    JOBS_DUPLICATE_REJECTED,
    JOBS_INGESTED,
    JOBS_QUEUED,
    JOBS_RESUMED,
    JOBS_RUNNING,
)
from .job import JOB_REGISTRY, JobState, StatefulJob
from .report import JobReport, JobStatus
from .worker import Worker, WorkerCommand

MAX_WORKERS = 5  # manager.rs:32


class JobManagerError(Exception):
    pass


class AlreadyRunning(JobManagerError):
    pass


class JobBuilder:
    """Compose a job with chained next-jobs, then dispatch it.

    Mirrors scan_location's JobBuilder chain (core/src/location/mod.rs:429-445):
    `JobBuilder(a).queue_next(b).queue_next(c).spawn(manager, library)`.
    """

    def __init__(self, job: StatefulJob, action: Optional[str] = None):
        self.job = job
        self.action = action
        self.next_jobs: List[StatefulJob] = []

    def queue_next(self, job: StatefulJob) -> "JobBuilder":
        self.next_jobs.append(job)
        return self

    async def spawn(self, manager: "JobManager", library: Any) -> bytes:
        return await manager.ingest(
            library, self.job, next_jobs=self.next_jobs, action=self.action
        )


class _Entry:
    def __init__(self, job, report, library, next_jobs, resume_state=None):
        self.job = job
        self.report = report
        self.library = library
        self.next_jobs: List[StatefulJob] = next_jobs
        self.resume_state = resume_state


class JobManager:
    def __init__(self, on_event: Optional[Callable[[dict], None]] = None,
                 services: Optional[dict] = None,
                 max_workers: int = MAX_WORKERS,
                 owner: str = "jobs"):
        self.max_workers = max_workers
        self.on_event = on_event or (lambda e: None)
        self.services = services or {}
        self._owner = owner
        self.running: Dict[bytes, Worker] = {}
        self._tasks: Dict[bytes, asyncio.Task] = {}
        self._entries: Dict[bytes, _Entry] = {}
        # Bounded admission run-queue (channels.py registry): shed_new
        # IS the admission control — a job past capacity is refused
        # loudly in _admit, the queue never balloons.
        self.queue = channels.channel("jobs.manager.queue")
        self._hashes: Dict[str, bytes] = {}  # job.hash() → job id
        self._final_status: Dict[bytes, JobStatus] = {}
        self._paused: Dict[bytes, _Entry] = {}  # paused this session
        self._resuming: set = set()  # job ids mid-await in resume()
        self._shutting_down = False

    # -- ingestion --------------------------------------------------------

    async def ingest(self, library: Any, job: StatefulJob,
                     next_jobs: Optional[List[StatefulJob]] = None,
                     action: Optional[str] = None) -> bytes:
        h = job.hash()
        if h in self._hashes:
            JOBS_DUPLICATE_REJECTED.inc()
            raise AlreadyRunning(f"{job.NAME} already running/queued")
        JOBS_INGESTED.inc()
        next_jobs = list(next_jobs or [])
        # Persist a pre-init state blob so a job that dies while QUEUED
        # (or is shut down before starting) cold-resumes instead of
        # failing with "lost state" — the blob also carries the chain.
        state = JobState.fresh(
            job.persistable_init_args(),
            [(j.NAME, j.persistable_init_args()) for j in next_jobs],
        )
        report = JobReport(
            id=new_job_id(), name=job.NAME, action=action,
            data=state.serialize(),
        )
        # Reserve the dedup hash BEFORE suspending: with the report
        # write off-loop, a second identical ingest could otherwise
        # pass the AlreadyRunning check during our await and run the
        # same job twice. Released on failure (only if still ours).
        self._hashes[h] = report.id
        try:
            await asyncio.to_thread(report.create, library.db)
        except BaseException:
            if self._hashes.get(h) == report.id:
                del self._hashes[h]
            raise
        entry = _Entry(job, report, library, next_jobs, resume_state=state)
        # _admit must stay sync (the task done-callback path admits
        # chained jobs); its QUEUED-status write is one tiny UPDATE.
        self._admit(entry)  # sdlint: ok[blocking-async]
        return report.id

    def _admit(self, entry: _Entry) -> None:
        self._entries[entry.report.id] = entry
        if len(self.running) < self.max_workers and not self._shutting_down:
            self._start(entry)
            return
        if not self.queue.put_nowait(entry):
            # Admission shed (jobs.manager.queue policy shed_new): the
            # run-queue is at declared capacity — refuse the job loudly
            # instead of growing without bound. Counted into
            # sd_chan_shed_total{jobs.manager.queue}.
            self._finalize_entry(
                entry, JobStatus.FAILED,
                "admission refused: jobs.manager.queue at capacity "
                f"({self.queue.capacity})")
            self.on_event({
                "type": "JobError",
                "id": entry.report.id.hex(),
                "message": "job queue full: admission refused",
            })
            return
        entry.report.status = JobStatus.QUEUED
        entry.report.update(entry.library.db)
        JOBS_QUEUED.set(len(self.queue))

    def _finalize_entry(self, entry: _Entry, status: JobStatus,
                        message: Optional[str] = None) -> None:
        """Terminal bookkeeping for a job that never reached a worker
        (admission refusal, queued/paused cancel): drop it from the
        indexes, persist the terminal report, and sweep any spooled
        step payloads — the worker's own cleanup path never runs for
        these."""
        job_id = entry.report.id
        self._entries.pop(job_id, None)
        h = entry.job.hash()
        if self._hashes.get(h) == job_id:
            del self._hashes[h]
        self._final_status[job_id] = status
        entry.report.status = status
        entry.report.data = None
        if message is not None:
            entry.report.errors_text.append(message)
        entry.report.update(entry.library.db)
        with entry.library.db.write_tx() as conn:
            entry.library.db.run("jobs.scratch.delete_for_job",
                                 (job_id,), conn=conn)

    def _start(self, entry: _Entry) -> None:
        worker = Worker(
            entry.job, entry.report, entry.library,
            on_event=self.on_event, services=self.services,
            resume_state=entry.resume_state,
        )
        self.running[entry.report.id] = worker
        JOBS_RUNNING.set(len(self.running))
        task = tasks.spawn(
            f"job/{entry.report.name}", worker.run(), owner=self._owner)
        self._tasks[entry.report.id] = task
        task.add_done_callback(
            lambda t, jid=entry.report.id: self._on_done(jid, t)
        )

    def _on_done(self, job_id: bytes, task: asyncio.Task) -> None:
        self.running.pop(job_id, None)
        self._tasks.pop(job_id, None)
        entry = self._entries.pop(job_id, None)
        status = entry.report.status if entry else JobStatus.FAILED
        self._final_status[job_id] = status
        if entry is not None:
            if status == JobStatus.PAUSED:
                self._paused[job_id] = entry
            if status != JobStatus.PAUSED:
                # Paused jobs keep their dedup hash so an identical ingest
                # still collides with the paused run until it is resumed
                # or cancelled.
                self._hashes.pop(entry.job.hash(), None)
            if status in (JobStatus.COMPLETED,
                          JobStatus.COMPLETED_WITH_ERRORS) and \
                    entry.next_jobs and not self._shutting_down:
                head, *rest = entry.next_jobs
                if head.hash() in self._hashes:
                    self.on_event({
                        "type": "JobError",
                        "id": entry.report.id.hex(),
                        "message": f"chained job {head.NAME} skipped: "
                                   "identical job already running/queued",
                    })
                else:
                    nxt_state = JobState.fresh(
                        head.persistable_init_args(),
                        [(j.NAME, j.persistable_init_args()) for j in rest],
                    )
                    nxt_report = JobReport(
                        id=new_job_id(), name=head.NAME,
                        parent_id=entry.report.id,
                        data=nxt_state.serialize(),
                    )
                    nxt_report.create(entry.library.db)
                    nxt = _Entry(head, nxt_report, entry.library, rest,
                                 resume_state=nxt_state)
                    self._hashes[head.hash()] = nxt_report.id
                    self._admit(nxt)
        while (self.queue and len(self.running) < self.max_workers
               and not self._shutting_down):
            # one report tx per STARTED job — the admission unit
            self._start(self.queue.popleft())  # sdlint: ok[tx-shape]
        JOBS_RUNNING.set(len(self.running))
        JOBS_QUEUED.set(len(self.queue))

    # -- control ----------------------------------------------------------

    def pause(self, job_id: bytes) -> None:
        self._worker(job_id).command(WorkerCommand.PAUSE)

    async def resume(self, library: Any, job_id: bytes) -> None:
        """Resume a paused job, re-hydrating from the DB if needed."""
        if job_id in self.running:
            # Cancels a pending not-yet-actioned pause (latest command wins).
            self.running[job_id].command(WorkerCommand.RESUME)
            return
        if job_id in self._entries or job_id in self._resuming:
            return  # already re-admitted / mid-resume (double resume)
        self._resuming.add(job_id)
        try:
            paused_entry = self._paused.pop(job_id, None)
            row = await asyncio.to_thread(
                library.db.query_one,
                "SELECT * FROM job WHERE id = ?", (job_id,))
            if row is None:
                raise JobManagerError("no such job")
            report = JobReport.from_row(row)
            if report.status != JobStatus.PAUSED or not report.data:
                raise JobManagerError("job is not resumable")
            live_job = paused_entry.job if paused_entry is not None else None
            JOBS_RESUMED.inc()
            # sync by design (done-callback path); tiny status UPDATE
            self._admit_from_state(  # sdlint: ok[blocking-async]
                library, report, live_job=live_job)
        finally:
            self._resuming.discard(job_id)

    def _admit_from_state(self, library: Any, report: JobReport,
                          live_job: Any = None) -> None:
        state = JobState.deserialize(report.data)
        # Same-session resume keeps the live job object: the DB blob has
        # TRANSIENT_ARGS (passwords) redacted to None, but the in-memory
        # instance still holds them.
        job = live_job or JOB_REGISTRY[report.name](**state.init_args)
        next_jobs = [
            JOB_REGISTRY[name](**init) for name, init in state.next_chain
            if name in JOB_REGISTRY
        ]
        entry = _Entry(job, report, library, next_jobs, resume_state=state)
        self._hashes.setdefault(job.hash(), report.id)
        self._final_status.pop(report.id, None)
        self._admit(entry)

    def cancel(self, job_id: bytes) -> None:
        if job_id in self.running:
            self._worker(job_id).command(WorkerCommand.CANCEL)
            return
        for entry in list(self.queue):
            if entry.report.id == job_id:
                self.queue.remove(entry)
                break
        else:
            entry = self._paused.pop(job_id, None)
            if entry is None:
                raise JobManagerError("no such running/queued/paused job")
        self._finalize_entry(entry, JobStatus.CANCELED)

    def _worker(self, job_id: bytes) -> Worker:
        if job_id not in self.running:
            raise JobManagerError("no such running job")
        return self.running[job_id]

    async def wait(self, job_id: bytes) -> JobStatus:
        """Await a job reaching a terminal-or-paused state."""
        task = self._tasks.get(job_id)
        if task is not None:
            return await asyncio.shield(task)
        for entry in self.queue:
            if entry.report.id == job_id:
                # queued and no worker yet: poll admission
                while job_id not in self._tasks and \
                        job_id not in self._final_status:
                    await asyncio.sleep(0.01)
                return await self.wait(job_id)
        if job_id in self._final_status:
            return self._final_status[job_id]
        raise JobManagerError("unknown job")

    async def wait_idle(self) -> None:
        # During shutdown queued entries intentionally stay QUEUED in the
        # DB (cold_resume picks them up), so only running tasks gate exit.
        while self._tasks or (self.queue and not self._shutting_down):
            await asyncio.gather(*list(self._tasks.values()),
                                 return_exceptions=True)
            # Awaiting already-done tasks returns without yielding to the
            # loop, so the call_soon-scheduled _on_done that pops _tasks
            # (and admits chained jobs) would never run — always yield.
            await asyncio.sleep(0)

    # -- lifecycle --------------------------------------------------------

    async def shutdown(self) -> None:
        """Pause everything running; queued jobs stay QUEUED in the DB."""
        self._shutting_down = True
        for w in list(self.running.values()):
            w.command(WorkerCommand.SHUTDOWN)
        await self.wait_idle()

    async def cold_resume(self, library: Any) -> List[bytes]:
        """Re-hydrate interrupted jobs from the DB (manager.rs:269-319).

        Paused/Running/Queued reports with a state blob are resumed;
        those without are marked Failed.
        """
        resumed = []
        rows = await asyncio.to_thread(
            library.db.query,
            "SELECT * FROM job WHERE status IN (?, ?, ?)",
            (int(JobStatus.PAUSED), int(JobStatus.RUNNING),
             int(JobStatus.QUEUED)),
        )
        for row in rows:
            report = JobReport.from_row(row)
            if not report.data or report.name not in JOB_REGISTRY:
                report.status = JobStatus.FAILED
                report.errors_text.append("job lost state; cannot resume")
                await asyncio.to_thread(report.update, library.db)
                continue
            state = JobState.deserialize(report.data)
            job = JOB_REGISTRY[report.name](**state.init_args)
            if job.hash() in self._hashes:
                continue
            # sync by design (done-callback path); tiny status UPDATE
            self._admit_from_state(library,  # sdlint: ok[blocking-async,tx-shape]
                                   report)
            JOBS_RESUMED.inc()
            resumed.append(report.id)
        return resumed
