"""Declared wire contracts: the registry of every cross-node message.

Every frame kind that crosses a tunnel — the p2p control headers
(ping/pair/spacedrop/file), the obs federation plane, the sync pull
loop, the clone fast path, and the spaceblock block layer — is
DECLARED here with its schema, direction, size cap, proto-version
group, and the timeout budget its exchange runs under. The registry is
the single source of truth three consumers share:

- **Runtime**: `pack(name, **fields)` builds a frame that cannot drift
  from its declaration (const discriminators filled automatically,
  unknown/missing/mistyped fields refused); `unpack(name, frame)`
  validates an inbound frame (unknown fields TOLERATED for forward
  compatibility, version consts rejected on mismatch, declared
  `size_cap` enforced when the transport supplies the frame size).
  Proto-version constants (`SYNC_PROTO`, the clone stream's shared
  version, `OBS_PROTO`) and slice caps (`TRACE_SLICE_LIMIT`) are
  registry reads via `proto(group)` / `slice_cap(name)`.
- **The sanitizer twin** (`arm`, via sanitize.install): `audit_frame`
  at the tunnel seam classifies every inbound AND outbound frame by
  its declared discriminators and validates it — an undeclared kind,
  a schema mismatch, a size-cap breach, or a version skew is a
  `wire_violation` (raised in tier-1, counted in production;
  sd_wire_frames_total{name,dir} / sd_wire_violations_total{kind} /
  sd_wire_bytes_total{name}).
- **Static analysis**: the sdlint passes wire-discipline /
  schema-drift / proto-compat (tools/sdlint/passes/_wire.py) parse
  the literal `declare_message` calls below cross-AST, so send/recv
  sites naming undeclared kinds, payload drift, and schema changes
  without a version bump fail the build; tools/wire_grid.py mutates
  every declared kind at the real decode sites and asserts
  reject-without-crash.

Schema grammar (`{field: token}`):

- ``"str" | "int" | "bytes" | "bool" | "float" | "list" | "dict" |
  "any"`` — required field of that msgpack type; append ``"?"`` for
  optional (absent or None both tolerated).
- ``"=<literal>"`` — const discriminator (e.g. ``"t": "=ping"``):
  pack fills it, unpack requires it. Classification keys on these.
- ``"=proto"`` — version const: must equal the message's group
  version in PROTO_VERSIONS; a mismatch is WireVersionError (the
  polite-refusal paths catch it). ``"=proto?"`` tolerates an ABSENT
  field (the in-process loopback transports omit it) but still
  rejects a present mismatch.

Bare-string frames (spacedrop verdicts, spaceblock block acks) are
declared with ``values=(...)``; raw binary frames (spaceblock chunks)
with ``binary=True`` — both still carry a size cap and a budget.

Design constraint: imports WITHOUT the `cryptography` package (stdlib
plus the registry modules only) — the stub-transport fleets
(tools/load_bench.py) and crypto-less tier-1 containers drive the same
contracts through pack/unpack. proto.py reads MAX_FRAME from here.

Compat rules (enforced by the proto-compat pass against the committed
tools/sdlint/wire_baseline.json snapshot): changing a declared schema,
size cap, or values tuple without bumping the group's version in
PROTO_VERSIONS fails the build; regenerate the snapshot with
`python -m tools.sdlint --write-wire-baseline` as part of the same
change so the bump is a reviewed diff.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

from .. import flags, timeouts
from ..telemetry import WIRE_BYTES, WIRE_FRAMES, WIRE_VIOLATIONS

__all__ = [
    "MAX_FRAME", "PROTO_VERSIONS", "Field", "Message", "MESSAGES",
    "WireError", "WireSchemaError", "WireSizeError", "WireVersionError",
    "declare_message", "proto", "slice_cap", "message",
    "pack", "unpack", "classify", "audit_frame",
    "arm", "disarm", "armed", "wire_table_markdown", "baseline_snapshot",
]

# Transport sanity cap on one frame's payload: read_frame refuses
# anything larger before buffering it. Every declared size_cap sits at
# or below this; proto.py imports it from here so the transport bound
# and the contract bounds cannot drift.
MAX_FRAME = 64 * 1024 * 1024

_KIB, _MIB = 1024, 1024 * 1024

# One version per protocol GROUP (a message's name prefix): bump the
# group when any of its schemas changes shape. sync and clone share a
# number deliberately — the clone fast path is a sync-stream answer
# (a v2 sync peer would not understand v3's blob_stream frames), so
# they version together.
PROTO_VERSIONS: Dict[str, int] = {
    "p2p": 1,
    "obs": 1,
    "sync": 3,
    "clone": 3,
    "spaceblock": 1,
}

_TYPES: Dict[str, tuple] = {
    "str": (str,),
    "int": (int,),
    "bytes": (bytes, bytearray),
    "bool": (bool,),
    "float": (int, float),
    "list": (list, tuple),
    "dict": (dict,),
    "any": (object,),
}

_DIRECTIONS = ("dialer", "listener", "both")


class WireError(ValueError):
    """A frame broke its declared contract (or named no contract).
    A ValueError subclass: pre-registry decode sites raised plain
    ValueError for malformed frames, and their callers' handling
    still applies."""


class WireSchemaError(WireError):
    """Declared kind, payload drifted from its schema."""


class WireSizeError(WireError):
    """Frame larger than its declared size_cap."""


class WireVersionError(WireError):
    """Version const mismatch — the peer speaks another proto rev."""


@dataclass(frozen=True)
class Field:
    name: str
    type: str                      # key into _TYPES ("int" for consts)
    optional: bool = False
    const: Any = None              # literal value, or None
    is_proto: bool = False         # "=proto" version const


@dataclass(frozen=True)
class Message:
    name: str                      # dotted, first segment == group
    group: str                     # PROTO_VERSIONS key
    version: int
    direction: str                 # which tunnel side sends it
    fields: Tuple[Field, ...]      # empty for values/binary frames
    values: Optional[Tuple[str, ...]]   # bare-string frames
    binary: bool                   # raw-bytes frames (send_raw)
    size_cap: int                  # payload bytes, <= MAX_FRAME
    slice_cap: Optional[int]       # per-reply item cap (obs slices)
    timeout_budget: str            # timeouts.py registry name
    doc: str

    def field(self, name: str) -> Optional[Field]:
        for f in self.fields:
            if f.name == name:
                return f
        return None

    def schema_tokens(self) -> Dict[str, str]:
        """The declaration's schema dict, re-rendered token-for-token
        (what wire_baseline.json snapshots)."""
        out: Dict[str, str] = {}
        for f in self.fields:
            if f.is_proto:
                tok = "=proto?" if f.optional else "=proto"
            elif f.const is not None:
                tok = f"={f.const}"
            else:
                tok = f.type + ("?" if f.optional else "")
            out[f.name] = tok
        return out


# name -> Message. Grow-only by design: the registry IS the protocol
# inventory; messages retire via an explicit declaration removal plus
# a baseline regeneration, never at runtime.
MESSAGES: Dict[str, Message] = {}  # sdlint: ok[unbounded-growth]

# (discriminator field, value) -> message name, for classification.
_CONST_INDEX: Dict[Tuple[str, Any], str] = {}  # sdlint: ok[unbounded-growth]
# bare-string value -> message name.
_VALUE_INDEX: Dict[str, str] = {}  # sdlint: ok[unbounded-growth]


def _parse_field(name: str, token: Any) -> Field:
    if not isinstance(name, str) or not name:
        raise ValueError(f"wire schema field name {name!r} invalid")
    if not isinstance(token, str) or not token:
        raise ValueError(
            f"wire schema token for {name!r} must be a non-empty str, "
            f"got {token!r}")
    if token in ("=proto", "=proto?"):
        return Field(name, "int", optional=token.endswith("?"),
                     is_proto=True)
    if token.startswith("="):
        lit = token[1:]
        if not lit:
            raise ValueError(f"empty const token for field {name!r}")
        return Field(name, "str", const=lit)
    optional = token.endswith("?")
    base = token[:-1] if optional else token
    if base not in _TYPES:
        raise ValueError(
            f"unknown wire schema type {base!r} for field {name!r} "
            f"(one of {sorted(_TYPES)})")
    return Field(name, base, optional=optional)


def declare_message(name: str, proto: str, direction: str,
                    schema: Optional[Dict[str, str]] = None, *,
                    size_cap: int, timeout_budget: str, doc: str,
                    values: Optional[Tuple[str, ...]] = None,
                    binary: bool = False,
                    slice_cap: Optional[int] = None) -> Message:
    """Declare one cross-node message kind. Called at import time from
    the bottom of THIS module only (the wire-discipline pass holds the
    declarations literal and central)."""
    segments = name.split(".")
    if len(segments) < 2 or not all(
            s and s.replace("_", "a").isalnum() and s == s.lower()
            for s in segments):
        raise ValueError(
            f"wire message name {name!r} must be dotted lower_snake "
            "with at least two segments")
    if name in MESSAGES:
        raise ValueError(f"wire message {name!r} declared twice")
    if proto not in PROTO_VERSIONS:
        raise ValueError(
            f"{name}: unknown proto group {proto!r} "
            f"(one of {sorted(PROTO_VERSIONS)})")
    if segments[0] != proto:
        raise ValueError(
            f"{name}: name prefix must equal its proto group {proto!r}")
    if direction not in _DIRECTIONS:
        raise ValueError(
            f"{name}: direction {direction!r} not in {_DIRECTIONS}")
    if sum((schema is not None, values is not None, bool(binary))) != 1:
        raise ValueError(
            f"{name}: exactly one of schema/values/binary required")
    if not isinstance(size_cap, int) or not 0 < size_cap <= MAX_FRAME:
        raise ValueError(
            f"{name}: size_cap must be an int in (0, {MAX_FRAME}]")
    if slice_cap is not None and (
            not isinstance(slice_cap, int) or slice_cap <= 0):
        raise ValueError(f"{name}: slice_cap must be a positive int")
    if timeout_budget not in timeouts.TIMEOUTS:
        raise ValueError(
            f"{name}: timeout_budget {timeout_budget!r} is not a "
            "declared budget (timeouts.py)")
    if not doc:
        raise ValueError(f"{name}: doc required")

    fields: Tuple[Field, ...] = ()
    if schema is not None:
        fields = tuple(_parse_field(k, v) for k, v in schema.items())
    if values is not None:
        if not values or not all(
                isinstance(v, str) and v for v in values):
            raise ValueError(
                f"{name}: values must be a non-empty tuple of strings")
        for v in values:
            if v in _VALUE_INDEX:
                raise ValueError(
                    f"{name}: bare-string value {v!r} already claimed "
                    f"by {_VALUE_INDEX[v]}")

    msg = Message(name=name, group=proto, version=PROTO_VERSIONS[proto],
                  direction=direction, fields=fields,
                  values=tuple(values) if values else None,
                  binary=bool(binary), size_cap=size_cap,
                  slice_cap=slice_cap, timeout_budget=timeout_budget,
                  doc=doc)
    MESSAGES[name] = msg
    for f in fields:
        if f.const is not None and f.name in ("t", "kind"):
            key = (f.name, f.const)
            if key in _CONST_INDEX:
                raise ValueError(
                    f"{name}: discriminator {key!r} already claimed "
                    f"by {_CONST_INDEX[key]}")
            _CONST_INDEX[key] = name
    if values:
        for v in values:
            _VALUE_INDEX[v] = name
    return msg


def message(name: str) -> Message:
    try:
        return MESSAGES[name]
    except KeyError:
        raise WireError(
            f"undeclared wire message {name!r} (declare it in "
            "p2p/wire.py)") from None


def proto(group: str) -> int:
    """The group's wire version — the one source SYNC_PROTO, the clone
    stream, and the obs envelopes all read."""
    try:
        return PROTO_VERSIONS[group]
    except KeyError:
        raise KeyError(
            f"unknown wire proto group {group!r} "
            f"(one of {sorted(PROTO_VERSIONS)})") from None


def slice_cap(name: str) -> int:
    """A declared message's per-reply item cap (obs slice limits)."""
    cap = message(name).slice_cap
    if cap is None:
        raise KeyError(f"wire message {name!r} declares no slice_cap")
    return cap


def _type_ok(f: Field, value: Any) -> bool:
    if f.type == "any":
        return True
    if f.type in ("int", "float") and isinstance(value, bool):
        return False
    return isinstance(value, _TYPES[f.type])


def pack(name: str, /, **fields: Any) -> Any:
    """Build a frame that cannot drift from its declaration: const
    discriminators (including version fields) are filled in, unknown /
    missing / mistyped fields are refused. Returns the msgpack-ready
    value (dict for schema frames, str for values frames, bytes for
    binary frames — values/binary take a single `value=` kwarg).
    The message name is positional-only: a schema may legitimately
    declare a field called `name` (spaceblock.request does)."""
    msg = message(name)
    if msg.values is not None or msg.binary:
        if set(fields) != {"value"}:
            raise WireSchemaError(
                f"{name}: pack takes exactly one kwarg `value`")
        value = fields["value"]
        _check_scalar(msg, value)
        return value
    out: Dict[str, Any] = {}
    declared = {f.name for f in msg.fields}
    for k in fields:
        if k not in declared:
            raise WireSchemaError(
                f"{name}: field {k!r} not in the declared schema")
    for f in msg.fields:
        if f.is_proto:
            out[f.name] = msg.version
            continue
        if f.const is not None:
            given = fields.get(f.name, f.const)
            if given != f.const:
                raise WireSchemaError(
                    f"{name}: const field {f.name!r} must be "
                    f"{f.const!r}, got {given!r}")
            out[f.name] = f.const
            continue
        if f.name not in fields or fields[f.name] is None:
            if not f.optional:
                raise WireSchemaError(
                    f"{name}: required field {f.name!r} missing")
            if f.name in fields:
                out[f.name] = None  # explicit optional None rides along
            continue
        value = fields[f.name]
        if not _type_ok(f, value):
            raise WireSchemaError(
                f"{name}: field {f.name!r} must be {f.type}, got "
                f"{type(value).__name__}")
        out[f.name] = value
    return out


def _check_scalar(msg: Message, frame: Any) -> None:
    """Validate a values/binary frame's payload."""
    if msg.values is not None:
        if not isinstance(frame, str):
            raise WireSchemaError(
                f"{msg.name}: expected a bare string, got "
                f"{type(frame).__name__}")
        if frame not in msg.values:
            raise WireSchemaError(
                f"{msg.name}: value {frame!r} not in declared "
                f"{msg.values}")
        if len(frame.encode()) > msg.size_cap:
            raise WireSizeError(
                f"{msg.name}: value over the declared "
                f"{msg.size_cap}-byte cap")
        return
    # binary
    if not isinstance(frame, (bytes, bytearray)):
        raise WireSchemaError(
            f"{msg.name}: expected raw bytes, got "
            f"{type(frame).__name__}")
    if not frame:
        raise WireSchemaError(f"{msg.name}: empty binary frame")
    if len(frame) > msg.size_cap:
        raise WireSizeError(
            f"{msg.name}: {len(frame)} bytes over the declared "
            f"{msg.size_cap}-byte cap")


def unpack(name: str, frame: Any, *, size: Optional[int] = None) -> Any:
    """Validate an inbound frame against its declared contract and
    return it. Unknown fields are TOLERATED (forward compatibility: a
    newer peer may send more than we know); missing required fields,
    type drift, and const mismatches are refused; a version const from
    another rev raises WireVersionError (the polite-refusal idiom
    catches exactly that); `size` (the transport's payload byte count)
    enforces the declared size_cap."""
    msg = message(name)
    if size is not None and size > msg.size_cap:
        raise WireSizeError(
            f"{name}: {size}-byte frame over the declared "
            f"{msg.size_cap}-byte cap")
    if msg.values is not None or msg.binary:
        _check_scalar(msg, frame)
        return frame
    if not isinstance(frame, dict):
        raise WireSchemaError(
            f"{name}: expected a map frame, got "
            f"{type(frame).__name__}")
    for f in msg.fields:
        if f.name not in frame:
            if f.optional or (f.is_proto and f.optional):
                continue
            if f.is_proto:
                raise WireVersionError(
                    f"{name}: version field {f.name!r} missing")
            raise WireSchemaError(
                f"{name}: required field {f.name!r} missing")
        value = frame[f.name]
        if f.is_proto:
            if not isinstance(value, int) or isinstance(value, bool) \
                    or value != msg.version:
                raise WireVersionError(
                    f"{name}: peer wire proto {value!r} != ours "
                    f"{msg.version}")
            continue
        if f.const is not None:
            if value != f.const:
                raise WireSchemaError(
                    f"{name}: const field {f.name!r} is {value!r}, "
                    f"declared {f.const!r}")
            continue
        if value is None:
            if f.optional:
                continue
            raise WireSchemaError(
                f"{name}: required field {f.name!r} is None")
        if not _type_ok(f, value):
            raise WireSchemaError(
                f"{name}: field {f.name!r} must be {f.type}, got "
                f"{type(value).__name__}")
    return frame


def classify(frame: Any) -> Tuple[str, ...]:
    """Candidate declared names for an arbitrary frame, best-first.

    Dict frames match on their const discriminators (`t` / `kind`),
    most-specific first; dict frames with NO declared discriminator
    (response envelopes) fall back to structural matching on required
    fields. Bare strings match the values index; bytes match binary
    messages. Empty tuple = undeclared."""
    if isinstance(frame, str):
        name = _VALUE_INDEX.get(frame)
        return (name,) if name else ()
    if isinstance(frame, (bytes, bytearray)):
        return tuple(n for n, m in MESSAGES.items() if m.binary)
    if not isinstance(frame, dict):
        return ()
    scored = []
    for name, msg in MESSAGES.items():
        if msg.values is not None or msg.binary:
            continue
        consts = [f for f in msg.fields
                  if f.const is not None and f.name in ("t", "kind")]
        if consts:
            if all(frame.get(f.name) == f.const for f in consts):
                scored.append((len(consts), name))
            continue
        required = [f for f in msg.fields
                    if not f.optional and f.const is None
                    and not f.is_proto]
        if required and all(f.name in frame for f in required):
            scored.append((0, name))
    scored.sort(key=lambda t: (-t[0], t[1]))
    best = [n for s, n in scored if s > 0]
    return tuple(best) if best else tuple(n for _, n in scored)


# -- runtime twin (armed by sanitize.install) --------------------------------

_armed = False
_mode = "count"
_recorder: Optional[Callable[[str, str, bool], None]] = None


def armed() -> bool:
    return _armed


def arm(mode: str, record: Callable[[str, str, bool], None]) -> None:
    """Arm the frame auditor (sanitize.install). `record(kind, detail,
    may_raise)` is the sanitizer's violation sink. SDTPU_WIRE_AUDIT=off
    skips arming entirely (pack/unpack still validate)."""
    global _armed, _mode, _recorder
    if flags.get("SDTPU_WIRE_AUDIT") == "off":
        return
    _armed = True
    _mode = mode
    _recorder = record


def disarm() -> None:
    global _armed, _recorder
    _armed = False
    _recorder = None


def _report(kind: str, detail: str) -> None:
    WIRE_VIOLATIONS.labels(kind=kind).inc()
    rec = _recorder
    if rec is not None:
        rec("wire_violation", detail, True)


def audit_frame(frame: Any, direction: str,
                nbytes: Optional[int] = None) -> Optional[str]:
    """The tunnel-seam auditor: classify + validate one frame in
    either direction. Returns the matched declared name (for the
    frame census) or None when disarmed / in violation. Violations
    raise in tier-1 (sanitizer raise mode) and only count in
    production — production traffic is never torn by its own
    observer."""
    if not _armed:
        return None
    names = classify(frame)
    if not names:
        _report("undeclared",
                f"wire: undeclared {direction} frame {_clip(frame)}")
        return None
    errors = []
    for name in names:
        try:
            unpack(name, frame, size=nbytes)
        except WireError as e:
            errors.append(e)
            continue
        WIRE_FRAMES.labels(name=name, dir=direction).inc()
        if nbytes:
            WIRE_BYTES.labels(name=name).inc(nbytes)
        return name
    if any(isinstance(e, WireVersionError) for e in errors):
        kind = "proto_skew"
    elif any(isinstance(e, WireSizeError) for e in errors):
        kind = "size_cap"
    else:
        kind = "schema"
    _report(kind, f"wire: {direction} frame failed "
                  f"{'/'.join(names)}: {errors[0]}")
    return None


def _clip(frame: Any, limit: int = 160) -> str:
    s = repr(frame)
    return s if len(s) <= limit else s[:limit] + "…"


# -- generated docs / snapshots ----------------------------------------------

def wire_table_markdown() -> str:
    """README's generated wire-contract inventory (one row per
    declared message)."""
    lines = [
        "| message | proto | sender | payload | size cap | budget |",
        "|---|---|---|---|---|---|",
    ]
    for name in sorted(MESSAGES):
        m = MESSAGES[name]
        if m.values is not None:
            payload = "one of " + " / ".join(
                f"`{v}`" for v in m.values)
        elif m.binary:
            payload = "raw bytes"
        else:
            payload = ", ".join(
                f"`{f}={tok}`" for f, tok in
                m.schema_tokens().items())
        if m.slice_cap is not None:
            payload += f" (slice cap {m.slice_cap})"
        cap = (f"{m.size_cap // _MIB} MiB" if m.size_cap >= _MIB
               else f"{m.size_cap // _KIB} KiB")
        lines.append(
            f"| `{name}` | {m.group} v{m.version} | {m.direction} "
            f"| {payload} | {cap} | `{m.timeout_budget}` |")
    return "\n".join(lines)


def baseline_snapshot() -> Dict[str, Dict[str, Any]]:
    """The proto-compat pass's committed snapshot shape
    (tools/sdlint/wire_baseline.json): schema + caps per version, so
    a shape change without a version bump is a build failure."""
    out: Dict[str, Dict[str, Any]] = {}
    for name in sorted(MESSAGES):
        m = MESSAGES[name]
        if m.values is not None:
            payload: Any = {"values": list(m.values)}
        elif m.binary:
            payload = {"binary": True}
        else:
            payload = {"schema": m.schema_tokens()}
        out[name] = {"proto": m.group, "version": m.version,
                     "size_cap": m.size_cap, **payload}
        if m.slice_cap is not None:
            out[name]["slice_cap"] = m.slice_cap
    return out


# ---------------------------------------------------------------------------
# The inventory. Every cross-node frame kind, declared once, literal
# args only (the sdlint passes parse these calls cross-AST; a computed
# declaration is invisible to them and fails wire-discipline).
# ---------------------------------------------------------------------------

declare_message(
    "p2p.handshake.hello", "p2p", "both",
    {"identity": "bytes", "ephemeral": "bytes", "nonce": "bytes",
     "sig": "bytes"},
    size_cap=4096, timeout_budget="p2p.handshake",
    doc="Signed ephemeral key exchange, one per side, BEFORE the "
        "tunnel exists — the only frame that crosses in the clear "
        "(proto.tunnel_handshake verifies the signature).")

declare_message(
    "p2p.ping", "p2p", "dialer",
    {"t": "=ping", "tp": "str?"},
    size_cap=4096, timeout_budget="p2p.ping",
    doc="Liveness probe; the whole exchange runs under the p2p.ping "
        "budget (manager.ping).")

declare_message(
    "p2p.pong", "p2p", "listener",
    {"t": "=pong"},
    size_cap=4096, timeout_budget="p2p.ping",
    doc="Ping answer.")

declare_message(
    "p2p.pair.request", "p2p", "dialer",
    {"t": "=pair", "tp": "str?", "library_id": "str",
     "library_name": "str", "listen_port": "int", "instance": "dict"},
    size_cap=64 * 1024, timeout_budget="p2p.pair",
    doc="Pairing offer: signed instance row + the dialer's LISTENING "
        "port (the TCP source port is ephemeral) so the responder "
        "derives a route back.")

declare_message(
    "p2p.pair.response", "p2p", "listener",
    {"status": "str", "instance": "dict?"},
    size_cap=64 * 1024, timeout_budget="p2p.pair",
    doc="Pairing verdict: status accepted (with the responder's "
        "instance row) or rejected.")

declare_message(
    "p2p.spacedrop.offer", "p2p", "dialer",
    {"t": "=spacedrop", "req": "dict", "tp": "str?"},
    size_cap=64 * 1024, timeout_budget="p2p.spacedrop.verdict",
    doc="File-drop offer carrying an embedded spaceblock.request; the "
        "receiver's interactive decision runs under "
        "p2p.spacedrop.decide, the offerer waits under "
        "p2p.spacedrop.verdict.")

declare_message(
    "p2p.spacedrop.verdict", "p2p", "listener",
    values=("accept", "reject"),
    size_cap=4096, timeout_budget="p2p.spacedrop.verdict",
    doc="Bare-string spacedrop verdict; `accept` is followed by "
        "spaceblock chunks.")

declare_message(
    "p2p.file.request", "p2p", "dialer",
    {"t": "=file", "library_id": "str", "location_pub_id": "bytes",
     "file_path_pub_id": "bytes", "range_start": "int?",
     "range_end": "int?", "tp": "str?"},
    size_cap=64 * 1024, timeout_budget="p2p.file.response",
    doc="Files-over-p2p fetch, rows addressed by synced pub_ids "
        "(local autoincrement ids never cross the wire).")

declare_message(
    "p2p.file.response", "p2p", "listener",
    {"status": "str", "req": "dict?"},
    size_cap=64 * 1024, timeout_budget="p2p.file.response",
    doc="File-request answer: status ok (with the embedded "
        "spaceblock.request the chunk stream will follow) or "
        "not_found.")

declare_message(
    "obs.metrics", "obs", "dialer",
    {"t": "=obs.metrics", "proto": "=proto?", "tp": "str?",
     "limit": "int?"},
    size_cap=4096, timeout_budget="p2p.obs",
    doc="Fleet-plane request for the whole telemetry registry "
        "snapshot. The version const is optional-on-the-wire: the "
        "in-process loopback transports omit it.")

declare_message(
    "obs.health", "obs", "dialer",
    {"t": "=obs.health", "proto": "=proto?", "tp": "str?",
     "limit": "int?"},
    size_cap=4096, timeout_budget="p2p.obs",
    doc="Fleet-plane request for the latest HealthSnapshot.")

declare_message(
    "obs.trace", "obs", "dialer",
    {"t": "=obs.trace", "proto": "=proto?", "tp": "str?",
     "limit": "int?", "trace": "str?"},
    size_cap=4096, timeout_budget="p2p.obs", slice_cap=8192,
    doc="Fleet-plane request for a span-ring + flight-timeline slice, "
        "optionally filtered to one trace id; the responder clamps "
        "`limit` to the declared slice cap (the old "
        "TRACE_SLICE_LIMIT, now a registry read).")

declare_message(
    "obs.incidents", "obs", "dialer",
    {"t": "=obs.incidents", "proto": "=proto?", "tp": "str?",
     "limit": "int?"},
    size_cap=4096, timeout_budget="p2p.obs", slice_cap=256,
    doc="Fleet-plane request for incident-bundle HEADERS "
        "(newest-first, clamped to the declared slice cap — full "
        "bundles never cross the fleet plane unsolicited).")

declare_message(
    "obs.response", "obs", "listener",
    {"status": "str", "proto": "=proto", "what": "str?", "node": "dict?",
     "ts": "float?", "error": "str?", "metrics": "dict?",
     "health": "dict?", "incidents": "list?", "spans": "list?",
     "timeline": "list?"},
    size_cap=16 * 1024 * 1024, timeout_budget="p2p.obs",
    doc="Every obs answer: one envelope (status/proto/what/node/ts) "
        "plus the payload key its request kind declares — metrics | "
        "health | incidents | spans+timeline — or status=error with "
        "`error`. The version const is REQUIRED here: a stale-proto "
        "peer must degrade to a labeled stale row, never corrupt the "
        "merged fleet view.")

declare_message(
    "sync.announce", "sync", "dialer",
    {"t": "=sync", "kind": "=new_ops", "library_id": "str",
     "proto": "=proto", "tp": "str?"},
    size_cap=4096, timeout_budget="p2p.frame_send",
    doc="NewOperations: the originator has ops for this library; the "
        "responder drives the pull loop back over the same tunnel. "
        "Version checked in BOTH directions (see sync_net.py).")

declare_message(
    "sync.pull.request", "sync", "listener",
    {"kind": "=messages", "clocks": "list", "count": "int",
     "proto": "=proto", "tp": "str?"},
    size_cap=1024 * 1024, timeout_budget="sync.pull.request",
    doc="GetOperations: the puller's watermark clock vector + page "
        "size; the originator refuses to SERVE a version skew (a "
        "stale decoder would corrupt its replica's op log).")

declare_message(
    "sync.pull.page", "sync", "dialer",
    {"ops": "list", "has_more": "bool"},
    size_cap=32 * 1024 * 1024, timeout_budget="sync.pull.page",
    doc="One page of row-format CRDT ops answering a pull request; "
        "has_more drives the puller's next request.")

declare_message(
    "sync.done", "sync", "both",
    {"kind": "=done"},
    size_cap=4096, timeout_budget="p2p.frame_send",
    doc="Stream close: the puller finished ingesting, or the "
        "responder refuses the announce (unknown library / version "
        "skew).")

declare_message(
    "clone.stream", "clone", "dialer",
    {"kind": "=blob_stream", "window": "int"},
    size_cap=4096, timeout_budget="sync.clone.frame",
    doc="Clone fast-path opener answering a fresh peer's pull "
        "request: the windowed blob-page stream follows, `window` "
        "pages in flight per watermark ack.")

declare_message(
    "clone.ops", "clone", "dialer",
    {"kind": "=clone_ops", "ops": "list"},
    size_cap=32 * 1024 * 1024, timeout_budget="sync.clone.frame",
    doc="Interleaved row-format ops that must precede a page's "
        "watermark advance (ingested per-op on the receiver).")

declare_message(
    "clone.page", "clone", "dialer",
    {"kind": "=blob_page", "model": "str", "instance": "bytes",
     "min_ts": "int", "max_ts": "int", "n_ops": "int", "data": "bytes"},
    size_cap=48 * 1024 * 1024, timeout_budget="sync.clone.frame",
    doc="One stored blob page relayed VERBATIM (no per-op "
        "materialization); the receiver's batched apply commits it "
        "in one transaction, or falls back per-op on proof failure.")

declare_message(
    "clone.ack", "clone", "listener",
    {"kind": "=ack", "ts": "int", "fast": "bool"},
    size_cap=4096, timeout_budget="sync.clone.ack",
    doc="Per-page watermark ack: `ts` is the receiver's DURABLY "
        "committed watermark (a torn stream resumes exactly there); "
        "`fast` reports whether the batched apply held.")

declare_message(
    "clone.done", "clone", "dialer",
    {"kind": "=blob_done"},
    size_cap=4096, timeout_budget="p2p.frame_send",
    doc="Clean end of the blob phase; the puller re-requests with "
        "advanced clocks and the per-op loop serves the row tail.")

declare_message(
    "spaceblock.request", "spaceblock", "both",
    {"name": "str", "size": "int", "range_start": "int?",
     "range_end": "int?"},
    size_cap=64 * 1024, timeout_budget="p2p.transfer.chunk",
    doc="Block-transfer descriptor (BEP-style), embedded in "
        "spacedrop offers and file responses; block size derives "
        "from `size`.")

declare_message(
    "spaceblock.verdict", "spaceblock", "both",
    values=("ok", "cancel"),
    size_cap=4096, timeout_budget="p2p.transfer.chunk",
    doc="Bare-string per-block ack from the receiving side: `ok` "
        "releases the next block, `cancel` tears the transfer down "
        "mid-stream.")

declare_message(
    "spaceblock.chunk", "spaceblock", "both",
    binary=True,
    size_cap=4 * 1024 * 1024, timeout_budget="p2p.transfer.chunk",
    doc="Raw file block (send_raw/recv_raw, no msgpack): at most one "
        "4 MiB block (block_size_from_file_size's ceiling), each "
        "acked before the next.")
