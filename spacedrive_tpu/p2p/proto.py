"""Wire framing + authenticated-encrypted tunnel.

Covers the roles of the reference's `proto.rs` (length-prefixed
encode/decode helpers, /root/reference/crates/p2p/src/proto.rs) and
`spacetunnel/tunnel.rs` (encrypted peer tunnel — a placeholder in the
reference, real here): frames are u32-length-prefixed msgpack values; the
tunnel runs an authenticated X25519 handshake (each side signs its
ephemeral key with its ed25519 identity), derives directional
ChaCha20-Poly1305 keys via HKDF, and seals every frame with a counter
nonce. The reference's QUIC transport maps to asyncio TCP streams — the
control plane stays host-side (SURVEY.md §2.6), ICI/DCN is only for
device collectives.
"""

from __future__ import annotations

import asyncio
import os
import struct
from typing import Any, Optional, Tuple

import msgpack
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

from .. import channels, chaos
from ..telemetry import (
    P2P_TUNNEL_BYTES_RECV,
    P2P_TUNNEL_BYTES_SENT,
    P2P_TUNNELS_OPENED,
)
from ..timeouts import deadline
from .identity import Identity, RemoteIdentity
# Observability request kinds (obs.metrics / obs.health / obs.trace)
# ride the same header discriminator as ping/pair/spacedrop/file/sync;
# re-exported here because this module IS the wire-format surface —
# the payload builders live crypto-free in p2p/obs.py so loopback
# transports share them. An obs response is one ordinary msgpack frame
# under MAX_FRAME (the registry snapshot and the capped trace slice
# both sit far below it).
from .obs import OBS_KINDS, OBS_PROTO  # noqa: F401  (protocol surface)
# The declared wire contracts (p2p/wire.py): the tunnel is the audit
# seam — every frame crossing it in either direction is classified
# and validated against its declaration when the sanitizer's wire
# auditor is armed, and the transport's frame cap IS the registry's
# MAX_FRAME (re-exported here for compatibility).
from . import wire
from .wire import MAX_FRAME, audit_frame  # noqa: F401

# Timeout discipline (tools/sdlint timeout-discipline pass): this
# module is the TRANSPORT PRIMITIVE layer — read_frame/send/recv are
# what every budget wraps, so their internal socket awaits carry
# suppression markers ("the budget lives at the call site") and the
# pass enforces that every caller in p2p/api/sync actually provides
# one (with_timeout / deadline). The handshake is the exception: it is
# a self-contained exchange, so it owns its own `p2p.handshake` block.

class ProtoError(Exception):
    pass


async def read_frame(reader: asyncio.StreamReader) -> bytes:
    hdr = await reader.readexactly(4)  # sdlint: ok[timeout-discipline]
    (length,) = struct.unpack(">I", hdr)
    if length > MAX_FRAME:
        raise ProtoError(f"frame too large: {length}")
    return await reader.readexactly(length)  # sdlint: ok[timeout-discipline]


def write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(struct.pack(">I", len(payload)) + payload)


async def read_msg(reader: asyncio.StreamReader) -> Any:
    return msgpack.unpackb(
        await read_frame(reader),  # sdlint: ok[timeout-discipline]
        raw=False, strict_map_key=False)


def write_msg(writer: asyncio.StreamWriter, msg: Any) -> None:
    write_frame(writer, msgpack.packb(msg, use_bin_type=True))


class Tunnel:
    """Encrypted, identity-authenticated frame stream over TCP."""

    def __init__(self, reader, writer, send_key: bytes, recv_key: bytes,
                 remote: RemoteIdentity):
        self.reader = reader
        self.writer = writer
        self.remote = remote
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_ctr = 0
        self._recv_ctr = 0
        # Declared frame window (channels.py p2p.tunnel.frames): the
        # send_nowait buffer lives in the transport, so this tracks
        # its depth — a burst past the declared window without a
        # drain is a chan_overflow sanitizer violation, which is how
        # a wedged peer's memory cost stays bounded at the cap
        # instead of growing with the stream.
        self._frames = channels.window("p2p.tunnel.frames")
        P2P_TUNNELS_OPENED.inc()

    @staticmethod
    def _nonce(counter: int) -> bytes:
        return counter.to_bytes(12, "big")

    def _seal(self, plain: bytes, tamper: bool = False) -> bytes:
        """Encrypt + frame + count: every outbound path goes through
        here so the tunnel byte counters see ciphertext (what actually
        crosses the wire, 4-byte length header excluded). `tamper`
        (chaos `corrupt` fault only) flips one ciphertext bit AFTER
        sealing, so the peer's AEAD decrypt fails loudly — the
        injected symptom of a flaky link past the checksum layer."""
        sealed = self._send.encrypt(self._nonce(self._send_ctr), plain, None)
        self._send_ctr += 1
        if tamper:
            sealed = bytes([sealed[0] ^ 0x01]) + sealed[1:]
        P2P_TUNNEL_BYTES_SENT.inc(len(sealed))
        write_frame(self.writer, sealed)
        return sealed

    async def send(self, msg: Any) -> None:
        # Chaos seam (send half): drop = the frame is lost on the wire
        # (never sealed, counter untouched — the peer's recv budget is
        # what notices); corrupt = sealed then tampered (AEAD failure
        # on the peer); delay/wedge/disconnect via the generic effects,
        # all bounded by the caller's declared frame budget.
        f = chaos.hit("p2p.tunnel.frame")
        if f is not None:
            if await chaos.apply_async(f):
                return  # dropped
        payload = msgpack.packb(msg, use_bin_type=True)
        audit_frame(msg, "out", len(payload))
        self._seal(payload, tamper=f is not None and f.kind == "corrupt")
        await self.writer.drain()  # sdlint: ok[timeout-discipline]
        self._frames.note_drain()  # drain flushes queued frames too

    async def recv(self) -> Any:
        # Chaos seam (recv half): delay/wedge/disconnect only —
        # dropping a RECEIVED frame would desync the counter nonce,
        # which is a different bug than the one being injected.
        f = chaos.hit("p2p.tunnel.frame",
                      only=("delay", "disconnect", "wedge"))
        if f is not None:
            await chaos.apply_async(f)
        sealed = await read_frame(self.reader)  # sdlint: ok[timeout-discipline]
        P2P_TUNNEL_BYTES_RECV.inc(len(sealed))
        plain = self._recv.decrypt(self._nonce(self._recv_ctr), sealed, None)
        self._recv_ctr += 1
        msg = msgpack.unpackb(plain, raw=False, strict_map_key=False)
        audit_frame(msg, "in", len(plain))
        return msg

    def send_nowait(self, msg: Any) -> None:
        """Seal and queue a frame WITHOUT awaiting the socket drain —
        the windowed blob-page sender (sync_net clone stream) pipelines
        up to its window of pages into the transport buffer and then
        awaits drain() once, instead of a per-frame drain round-trip.
        Counter-nonce ordering is unaffected: frames are sealed in call
        order on the single writer. Each queued frame counts into the
        declared p2p.tunnel.frames window; bursting past its capacity
        without a drain is a sanitizer violation (the cap that bounds
        a wedged peer's memory)."""
        payload = msgpack.packb(msg, use_bin_type=True)
        audit_frame(msg, "out", len(payload))
        self._seal(payload)
        self._frames.note_put()

    async def drain(self) -> None:
        """Flush frames queued by send_nowait to the socket. The
        budget lives at the call site (sync.clone.drain), which is the
        window's drain deadline."""
        await self.writer.drain()  # sdlint: ok[timeout-discipline]
        self._frames.note_drain()

    async def send_raw(self, data: bytes) -> None:
        audit_frame(data, "out", len(data))
        self._seal(data)
        await self.writer.drain()  # sdlint: ok[timeout-discipline]
        self._frames.note_drain()

    async def recv_raw(self) -> bytes:
        sealed = await read_frame(self.reader)  # sdlint: ok[timeout-discipline]
        P2P_TUNNEL_BYTES_RECV.inc(len(sealed))
        plain = self._recv.decrypt(self._nonce(self._recv_ctr), sealed, None)
        self._recv_ctr += 1
        audit_frame(plain, "in", len(plain))
        return plain

    def close(self) -> None:
        try:
            self.writer.close()
        except Exception:
            pass


def _x25519_pub_bytes(key: X25519PrivateKey) -> bytes:
    return key.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw)


def _derive_keys(shared: bytes, salt: bytes) -> Tuple[bytes, bytes]:
    okm = HKDF(algorithm=hashes.SHA256(), length=64, salt=salt,
               info=b"spacedrive-tpu-tunnel-v1").derive(shared)
    return okm[:32], okm[32:]


async def tunnel_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    identity: Identity,
    initiator: bool,
    expected: Optional[RemoteIdentity] = None,
) -> Tunnel:
    """Authenticated key exchange → Tunnel.

    Each side sends (identity_pub, ephemeral_pub, sig(ephemeral_pub ‖
    transcript-nonce)) and verifies the peer's signature — a signed
    ephemeral Diffie-Hellman, the real version of spacetunnel's
    placeholder (tunnel.rs:17-42).
    """
    eph = X25519PrivateKey.generate()
    my_pub = identity.to_remote_identity().to_bytes()
    nonce = os.urandom(16)
    write_msg(writer, wire.pack(
        "p2p.handshake.hello",
        identity=my_pub,
        ephemeral=_x25519_pub_bytes(eph),
        nonce=nonce,
        sig=identity.sign(_x25519_pub_bytes(eph) + nonce)))
    async with deadline("p2p.handshake"):
        await writer.drain()
        # The rawest decode site of all: the peer is unauthenticated
        # until the signature check below, so the frame is held to its
        # declared contract before any field is touched.
        hello = wire.unpack("p2p.handshake.hello",
                            await read_msg(reader))
    remote = RemoteIdentity(hello["identity"])
    if expected is not None and remote != expected:
        raise ProtoError("peer identity mismatch")
    if not remote.verify(hello["sig"], hello["ephemeral"] + hello["nonce"]):
        raise ProtoError("peer handshake signature invalid")
    shared = eph.exchange(X25519PublicKey.from_public_bytes(
        hello["ephemeral"]))
    # Both sides derive the same salt; key order flips by role.
    salt_material = sorted([nonce, hello["nonce"]])
    salt = salt_material[0] + salt_material[1]
    k1, k2 = _derive_keys(shared, salt)
    send_key, recv_key = (k1, k2) if initiator else (k2, k1)
    return Tunnel(reader, writer, send_key, recv_key, remote)
