"""P2p observability protocol: serve a node's telemetry, health, and
trace slices to paired peers.

The fleet observatory (spacedrive_tpu/fleet.py) cannot be built blind
either: PR 10's traceparent already makes one logical operation span
nodes, but each node's span ring, flight-recorder timeline, and health
snapshots were stranded in-process. This module is the serving half of
the federation plane — three request kinds riding the same
authenticated tunnels as the data plane (manager.py dispatches them
next to ping/pair/sync):

- ``obs.metrics`` — the whole telemetry registry snapshot (the rspc
  node.metrics payload) wrapped in a node-identity envelope;
- ``obs.health``  — the health observatory's latest HealthSnapshot
  (which itself now carries node identity + sampled-at wall clock);
- ``obs.trace``   — a span-ring + flight-timeline slice, filterable
  by trace id, capped at TRACE_SLICE_LIMIT entries per reply — the
  raw material distributed trace assembly merges into one
  Chrome-trace document;
- ``obs.incidents`` — the incident observatory's bundle HEADERS
  (newest-first, capped): enough for a fleet operator to see which
  node froze what postmortem and pull the full bundle from its rspc
  incidents.get — full bundles never cross the fleet plane
  unsolicited.

Every response is an envelope ``{status, proto, what, node, ts, ...}``
so the poller can reject a malformed or stale-proto peer without
poisoning its fleet view; every served request counts into
``sd_obs_requests_total{what}``.

Design constraints: this module must import WITHOUT the `cryptography`
package (stdlib + the registry modules only) — the in-process
loopback client (fleet.py) and the rspc obs.* queries serve the same
snapshots through `serve_obs` in containers where the tunnel's crypto
dependency is absent. Only `P2PObsClient` touches the tunnel layer,
and only at call time.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from .. import flight, telemetry, tracing
from ..telemetry import OBS_REQUESTS
from ..timeouts import with_timeout
from . import wire

__all__ = [
    "OBS_PROTO", "OBS_KINDS", "TRACE_SLICE_LIMIT",
    "INCIDENT_SLICE_LIMIT", "node_identity", "serve_obs",
    "P2PObsClient",
]

# Observability wire version, echoed in every response envelope — a
# REGISTRY READ (p2p/wire.py PROTO_VERSIONS), bumped there on any
# payload-shape change: the poller refuses a mismatched peer (one
# stale-proto node must degrade to a labeled stale row, never corrupt
# the merged fleet view).
OBS_PROTO = wire.proto("obs")

# The request kinds manager.py dispatches on (the `t` header field,
# same discriminator scheme as ping/pair/spacedrop/file/sync). Each
# IS a declared wire message name — dispatch keys and contracts
# cannot drift.
OBS_KINDS = ("obs.metrics", "obs.health", "obs.trace",
             "obs.incidents")

# Per-reply cap on bundle headers in an obs.incidents response —
# headers are small, and the store itself is capped well below this.
# The declared slice_cap of the obs.incidents contract.
INCIDENT_SLICE_LIMIT = wire.slice_cap("obs.incidents")

# Per-reply cap on spans and timeline events in an obs.trace slice:
# bounded well above the default rings (512 spans / 4096 timeline
# events) so a whole ring ships in one reply, while a hostile `limit`
# cannot make the responder build an unbounded copy. The declared
# slice_cap of the obs.trace contract.
TRACE_SLICE_LIMIT = wire.slice_cap("obs.trace")


def node_identity(node) -> Dict[str, str]:
    """The identity envelope every obs response carries: the node's
    config pub id + device name (the labels fleet rows render under)."""
    try:
        return {"id": node.config.id.hex(), "name": node.config.name}
    except Exception:
        return {"id": "", "name": ""}


def _trace_slice(trace: Optional[str], limit: int) -> Dict[str, Any]:
    """Span-ring + flight-timeline copies, newest-last, optionally
    filtered to one trace id, each side capped at `limit`."""
    limit = max(1, min(int(limit), TRACE_SLICE_LIMIT))
    spans = tracing.recent_spans(limit=limit, trace_id=trace)
    timeline = flight.RECORDER.snapshot()
    if trace is not None:
        timeline = [ev for ev in timeline if ev.get("trace") == trace]
    return {"spans": spans, "timeline": timeline[-limit:]}


def serve_obs(node, header: Dict[str, Any]) -> Dict[str, Any]:
    """One obs request → one JSON-safe response envelope. The SINGLE
    dispatch every transport goes through — the p2p handler
    (manager.py), the rspc obs.* queries, and the in-process loopback
    client (fleet.py) — so request validation and payload shape cannot
    drift between transports. Never raises on a malformed header: a
    hostile peer gets a status=error envelope, not a torn tunnel."""
    what = header.get("t") if isinstance(header, dict) else None
    if what not in OBS_KINDS:
        OBS_REQUESTS.labels(what="error").inc()
        return wire.pack("obs.response", status="error",
                         error=f"unknown obs kind {what!r}")
    try:
        # The request kind IS its declared message name; holding the
        # header to that contract here covers every transport (p2p
        # handler, rspc, loopback) with one validation site. The
        # version const is optional-on-the-wire, so proto-less
        # loopback headers pass; a PRESENT skew is refused.
        wire.unpack(what, header)  # sdlint: ok[wire-discipline]
    except wire.WireError as e:
        OBS_REQUESTS.labels(what="error").inc()
        return wire.pack("obs.response", status="error", error=str(e))
    extra: Dict[str, Any] = {}
    if what == "obs.metrics":
        extra["metrics"] = telemetry.snapshot()
    elif what == "obs.health":
        extra["health"] = node.health.snapshot()
    elif what == "obs.incidents":
        from .. import incidents as _incidents

        obs = getattr(node, "incidents", None) or _incidents.current()
        try:
            limit = int(header.get("limit", INCIDENT_SLICE_LIMIT))
        except (TypeError, ValueError):
            limit = INCIDENT_SLICE_LIMIT
        limit = max(1, min(limit, INCIDENT_SLICE_LIMIT))
        extra["incidents"] = obs.list(limit=limit) if obs else []
    else:  # obs.trace
        trace = header.get("trace")
        trace = str(trace) if trace else None
        try:
            limit = int(header.get("limit", TRACE_SLICE_LIMIT))
        except (TypeError, ValueError):
            limit = TRACE_SLICE_LIMIT
        extra.update(_trace_slice(trace, limit))
    OBS_REQUESTS.labels(what=what.split(".", 1)[1]).inc()
    return wire.pack("obs.response", status="ok", what=what,
                     node=node_identity(node),
                     ts=round(time.time(), 6), **extra)


class P2PObsClient:
    """Fetch one peer's obs snapshots over an authenticated tunnel —
    the production transport of the fleet poller. One short-lived
    tunnel per fetch (the obs cadence is seconds, not frames; route
    reuse belongs to the sync plane's cache): dial + handshake run
    under the manager's p2p.connect budget, the request/response
    exchange under p2p.obs."""

    def __init__(self, p2p, addr: str, port: int, expected=None):
        self.p2p = p2p
        self.addr = addr
        self.port = int(port)
        self.expected = expected

    async def fetch(self, what: str,
                    trace: Optional[str] = None) -> Any:
        tunnel = await self.p2p.open_stream(
            self.addr, self.port, expected=self.expected)
        try:
            extra = {"trace": str(trace)} if trace else {}
            # The fetch kind is data (one client, four request
            # contracts): the sanctioned dynamic pack call.
            req: Dict[str, Any] = wire.pack(  # sdlint: ok[wire-discipline]
                what, tp=tracing.traceparent(), **extra)
            await with_timeout("p2p.obs", tunnel.send(req))
            return await with_timeout("p2p.obs", tunnel.recv())
        finally:
            tunnel.close()
