"""NetworkedLibraries: CRDT sync over the p2p mesh.

The instance↔peer plane from the reference
(/root/reference/core/src/p2p/sync/mod.rs:31-446): each library knows the
remote instances it is paired with; when local writes create CRDT ops the
**originator** opens a sync stream to every reachable peer and announces
`NewOperations`; the remote **responder** then drives a pull loop —
repeated `GetOperations{clocks, count=1000}` requests answered from the
originator's op log — feeding each page through the library's ingest
state machine until drained (OPS_PER_REQUEST at p2p/sync/mod.rs:403).

Peer addressing goes through discovery (mdns in the reference, UDP
beacons here); tests can inject (addr, port) routes directly, mirroring
the reference's in-process transport fake
(core/crates/sync/tests/lib.rs:109-163).
"""

from __future__ import annotations

import asyncio
import uuid as uuidlib
from typing import Dict, Optional, Tuple

from .. import channels, flags, tasks, threadctx, timeouts, tracing
from ..sync.ingest import Ingester, MessagesEvent, ReqKind, \
    pump_clone_stream
from ..timeouts import with_timeout
from ..sync.clone_serve import CLONE_WINDOW, serve_clone_stream, \
    serve_gate
from ..sync.manager import GetOpsArgs
from ..sync.crdt import CRDTOperation
from ..telemetry import (
    P2P_RECONNECTS,
    P2P_ROUTE_CACHE_HITS,
    P2P_ROUTE_CACHE_MISSES,
)
from ..tracing import logger
from . import wire
from .identity import RemoteIdentity

OPS_PER_REQUEST = 1000

# CLONE_WINDOW and the windowed serving loop moved to the crypto-free
# sync/clone_serve.py (round 19) so stub-transport fleets — tier-1 and
# tools/load_bench.py — drive the REAL flow control; re-exported here
# because this module remains the wire-facing surface.

# Sync wire-format version, checked in BOTH directions: the originator
# announces it in the new_ops header (responder refuses a mismatch), and
# the responder echoes it in every pull-request frame (originator refuses
# to SERVE a mismatch — the direction that matters: a stale decoder
# pulling v2 ops would silently read multi-field update ops, "u:a+b"
# kinds, as creates and corrupt its replica's op log; a v2 peer would
# likewise not understand v3's blob_stream clone frames). A REGISTRY
# READ (p2p/wire.py PROTO_VERSIONS) since round 20 — the version the
# announce/pull contracts' `=proto` consts enforce is by construction
# the one this module serves.
SYNC_PROTO = wire.proto("sync")


class NetworkedLibraries:
    def __init__(self, node, p2p):
        self.node = node
        self.p2p = p2p
        p2p.networked = self
        # Captured so originate_soon works from worker threads — most
        # sync writes happen inside asyncio.to_thread job steps, where
        # get_running_loop() raises and the announcement would be lost.
        try:
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            self._loop = None
        # library_id → {instance pub_id → RemoteIdentity}; evicted on
        # library delete (bounded by loaded libraries, not history).
        self._instances: Dict[uuidlib.UUID, Dict[bytes, RemoteIdentity]] = {}
        # identity bytes → (addr, port) route override (set_route /
        # pairing-time learn): authoritative config keyed by PAIRED
        # instances, not a recomputable cache — evicting an entry
        # silently strands a non-discoverable peer, so grow-only is
        # the correctness contract (same shape as SyncManager's
        # watermark vector).
        self._routes: Dict[bytes, Tuple[str, int]] = {}  # sdlint: ok[unbounded-growth]
        # identity bytes → last route that carried a healthy tunnel:
        # discovery results are cached for the life of the tunnel and
        # invalidated on send failure, so a steady announce stream does
        # not re-scan the discovery peer table per round.
        self._route_cache = channels.bounded_dict("p2p.route_cache")
        # Declared reconnect discipline (timeouts.py registry): a peer
        # that failed its last announce round is retried up the
        # p2p.announce.reconnect ladder instead of being hammered on
        # every local write; schedule state is evicted on success, so
        # the maps are bounded by currently-flapping peers.
        self._announce_backoff = timeouts.RetrySchedule(
            "p2p.announce.reconnect")
        # Peers already handed to the fleet observatory as stale
        # (cleared on the next successful announce) — the hand-off
        # happens once per outage, not once per capped retry.
        # Bounded by currently-flapping peers.
        self._gave_up: set = set()
        # Fair-share page-fetch gate shared by this node's concurrent
        # clone streams (sync/clone_serve.py).
        self._clone_gate = serve_gate()
        self._ingest_locks: Dict[uuidlib.UUID, asyncio.Lock] = {}
        # Supervisor subtree for announce fan-outs + per-pull ingest
        # actors: Node.shutdown reaps any still in flight.
        self._owner = f"{getattr(node, 'task_owner', 'proc')}/sync"
        self._origin_pending: set = set()
        self._origin_redo: set = set()
        for lib in node.libraries.list():
            self.watch_library(lib)
        node.libraries.on_event(self._on_library_event)

    # -- wiring ------------------------------------------------------------

    def _on_library_event(self, kind: str, library) -> None:
        if kind == "load":
            self.watch_library(library)
        elif kind == "delete":
            # Eviction path for the per-library maps: without it a
            # node cycling through libraries grows them forever
            # (sdlint unbounded-growth). The announce ladders evict
            # with their peers — a peer no longer iterated by any
            # announce round can never reach the success() eviction,
            # so a flapping-then-unpaired peer would otherwise park
            # its Backoff state forever. (An identity shared with
            # another library rebuilds its ladder on the next
            # failure — resetting is harmless; leaking is not.)
            for identity in self._instances.get(library.id,
                                                {}).values():
                key = identity.to_bytes()
                self._announce_backoff.evict(key)
                self._gave_up.discard(key)
            self._instances.pop(library.id, None)
            self._ingest_locks.pop(library.id, None)

    def watch_library(self, library) -> None:
        self._instances.setdefault(library.id, {})
        self._load_known_instances(library)
        library.sync.on_created(
            lambda lib=library: self.originate_soon(lib))

    def _load_known_instances(self, library) -> None:
        """Paired instances persist in the instance table; identities
        recorded at pairing time re-arm routes after restart."""
        me = library.sync.instance
        for row in library.db.run("sync.instances.rows"):
            if row["pub_id"] == me:
                continue
            identity = row["identity"]
            if identity and len(identity) == 32:
                self._instances[library.id][row["pub_id"]] = (
                    RemoteIdentity(identity))

    def learn_instance(self, library_id, pub_id: bytes,
                       identity: RemoteIdentity,
                       route: Optional[Tuple[str, int]] = None) -> None:
        self._instances.setdefault(library_id, {})[pub_id] = identity
        if route is not None:
            self._routes[identity.to_bytes()] = route

    def set_route(self, identity: RemoteIdentity, addr: str,
                  port: int) -> None:
        self._routes[identity.to_bytes()] = (addr, port)

    def known_routes(self) -> Dict[bytes, Tuple[str, int]]:
        """Copy of the paired identity → (addr, port) table — the
        peer set the fleet observatory polls (fleet.py adopts every
        entry as an obs peer)."""
        return dict(self._routes)

    def _resolve(self, identity: RemoteIdentity
                 ) -> Optional[Tuple[str, int]]:
        key = identity.to_bytes()
        if key in self._routes:
            # explicit overrides (set_route / pairing) always win, so a
            # healed partition takes effect immediately even with a
            # stale cache entry present
            return self._routes[key]
        cached = self._route_cache.get(key)
        if cached is not None:
            P2P_ROUTE_CACHE_HITS.inc()
            return cached
        P2P_ROUTE_CACHE_MISSES.inc()
        disc = self.p2p.discovery
        if disc is not None:
            for peer in disc.peers.values():
                if peer.identity.to_bytes() == key:
                    return (peer.addr, peer.port)
        return None

    # -- originator (p2p/sync/mod.rs:256-325) ------------------------------

    def originate_soon(self, library) -> None:
        """Local write hook: fan NewOperations out in the background.

        Thread-safe: write_ops fires this from to_thread job steps."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = self._loop

        def spawn() -> None:
            # Coalesce bursts: while an announcement round is in flight
            # for this library, a redo mark replaces extra rounds — the
            # peers' pull loop drains the op log regardless of how many
            # times it is poked (the reference's ingest actor drops
            # redundant notifications the same way, ingest.rs wait!).
            if library.id in self._origin_pending:
                self._origin_redo.add(library.id)
                return
            self._origin_pending.add(library.id)

            async def run() -> None:
                try:
                    while True:
                        self._origin_redo.discard(library.id)
                        await self.originate(library)
                        if library.id not in self._origin_redo:
                            break
                finally:
                    self._origin_pending.discard(library.id)
                    self._origin_redo.discard(library.id)

            # Supervised: the registry keeps the strong reference
            # (no GC-cancel), observes a failed fan-out's exception,
            # and Node.shutdown reaps a round still in flight.
            tasks.spawn(f"origin/{library.id.hex[:8]}", run(),
                        owner=self._owner)

        # Absent loop (sync unit tests) or loop closed mid-shutdown:
        # dropped and counted — peers poll on reconnect either way.
        threadctx.call_threadsafe(loop, spawn)

    async def originate(self, library) -> None:
        peers = list(self._instances.get(library.id, {}).items())
        for pub_id, identity in peers:
            route = self._resolve(identity)
            if route is None:
                continue
            key = identity.to_bytes()
            if not self._announce_backoff.allowed(key):
                # Backing off after a failed round: skipping is safe —
                # the peer's pull loop drains our whole op log whenever
                # any later announce (or its own reconnect) lands.
                continue
            try:
                await self._originate_one(library, identity, route)
                self._route_cache[key] = route  # healthy: keep for next round
                self._announce_backoff.success(key)
                self._gave_up.discard(key)
            except (ConnectionError, OSError, asyncio.IncompleteReadError,
                    asyncio.TimeoutError) as e:
                self._route_cache.pop(key, None)  # stale: re-resolve next time
                P2P_RECONNECTS.inc()
                # Declared backoff instead of the old bare `continue`
                # (which re-dialed a flapping peer on EVERY announce):
                # each failure climbs the p2p.announce.reconnect
                # ladder; exhaustion hands the peer to the fleet
                # observatory as a stale row (operators see WHY sync
                # stopped reaching it) and parks retries at the cap.
                if self._announce_backoff.failure(key) is None and \
                        key not in self._gave_up:
                    self._note_gave_up(key, e)
                continue  # peer offline; it will pull on reconnect

    def _note_gave_up(self, key: bytes, err: BaseException) -> None:
        self._gave_up.add(key)
        fleet = getattr(self.node, "fleet", None)
        if fleet is not None:
            c = timeouts.BACKOFFS["p2p.announce.reconnect"]
            fleet.note_peer_gave_up(
                key.hex(),
                f"sync announce gave up after {c.max_tries} tries "
                f"({type(err).__name__}: {err}); retrying at the "
                f"{c.cap_s:g}s cap")

    async def _originate_one(self, library, identity: RemoteIdentity,
                             route: Tuple[str, int]) -> None:
        # The serving half of the cross-node sync trace: this span
        # roots it (or continues the caller's — a backfill triggered
        # inside an rpc/* span rides that trace), and its traceparent
        # travels in the new_ops header so the responder's sync.pull
        # span lands in the SAME trace — one id covers the request
        # end-to-end across both nodes.
        with tracing.span("sync.serve", library=str(library.id)):
            await self._serve_pull_loop(library, identity, route)

    async def _serve_pull_loop(self, library, identity: RemoteIdentity,
                               route: Tuple[str, int]) -> None:
        tunnel = await self.p2p.open_stream(*route, expected=identity)
        try:
            # pack() fills the t/kind discriminators and the proto
            # const from the sync.announce declaration — the header
            # cannot drift from what handle_sync_stream validates.
            await with_timeout(
                "p2p.frame_send",
                tunnel.send(wire.pack(
                    "sync.announce", library_id=str(library.id),
                    tp=tracing.traceparent())))
            # Serve the responder's pull loop from our op log. The
            # clone fast path runs at most once per tunnel: a receiver
            # whose watermark stays frozen (persistent per-op failure)
            # must degrade to the per-op loop, not re-pull the whole
            # blob stream forever.
            clone_served = False
            while True:
                # The responder ingests the previous page (one tx per
                # page) before its next pull request lands here.
                req = await with_timeout("sync.pull.request",
                                         tunnel.recv())
                if not isinstance(req, dict) or req.get("kind") == "done":
                    break
                try:
                    req = wire.unpack("sync.pull.request", req)
                except wire.WireVersionError as e:
                    # A stale peer would misparse our ops (see SYNC_PROTO)
                    # — refuse to serve it rather than corrupt its log.
                    logger.warning("not serving sync pull: %s", e)
                    await with_timeout(
                        "p2p.frame_send",
                        tunnel.send(wire.pack("sync.pull.page",
                                              ops=[], has_more=False)))
                    break
                # Any OTHER contract breach propagates: the finally
                # closes the tunnel — the declared teardown path for a
                # peer speaking off-schema (the auditor already counted
                # the frame when armed).
                clocks = [(bytes(i), int(t)) for i, t in req["clocks"]]
                # Clone fast path: a fresh peer (zero watermark for the
                # blob-authoring instances) gets the stored blob pages
                # VERBATIM — no per-op materialization, no re-encode —
                # under windowed flow control. After the stream the
                # peer re-requests with advanced clocks and the normal
                # per-op loop finishes the row tail.
                if not clone_served and flags.get(
                        "SDTPU_CLONE_PASSTHROUGH"):
                    # The windowed originator lives crypto-free in
                    # sync/clone_serve.py (shared with the load
                    # harness's stub transports); this node's streams
                    # share one fair-share page-fetch gate.
                    clone_served = await serve_clone_stream(
                        library.sync, tunnel, clocks,
                        gate=self._clone_gate)
                    if clone_served:
                        continue
                ops = await asyncio.to_thread(
                    library.sync.get_ops, GetOpsArgs(
                        clocks=clocks,
                        count=min(int(req.get("count", OPS_PER_REQUEST)),
                                  OPS_PER_REQUEST)))
                await with_timeout("p2p.frame_send", tunnel.send(
                    wire.pack("sync.pull.page",
                              ops=[op.to_wire() for op in ops],
                              has_more=len(ops) >= OPS_PER_REQUEST)))
        finally:
            tunnel.close()

    # -- responder (p2p/sync/mod.rs:379-446) -------------------------------

    async def handle_sync_stream(self, tunnel, header: dict) -> None:
        try:
            header = wire.unpack("sync.announce", header)
        except wire.WireVersionError as e:
            # Version skew gets the POLITE refusal (a real v2 peer
            # deserves a clean done, not a torn tunnel) …
            logger.warning("refusing sync stream: %s", e)
            await with_timeout("p2p.frame_send",
                               tunnel.send(wire.pack("sync.done")))
            return
        # … while any other contract breach propagates to manager.py's
        # generic handler: P2PError event + tunnel close, the declared
        # disconnect path for an off-schema peer.
        lib = self.node.libraries.get(
            uuidlib.UUID(str(header["library_id"])))
        if lib is None:
            await with_timeout("p2p.frame_send",
                               tunnel.send(wire.pack("sync.done")))
            return
        # Continue the originator's trace (the header's tp field):
        # this node's pull spans — and the ingester task spawned under
        # them, which inherits the context through tasks.spawn — join
        # the serving node's trace instead of rooting a fresh one.
        with tracing.continue_trace(header.get("tp")), \
                tracing.span("sync.pull", library=str(lib.id)):
            lock = self._ingest_locks.setdefault(lib.id, asyncio.Lock())
            async with lock:
                await self._pull(lib, tunnel)
        self.node.events.invalidate_query(lib.id, "search.paths")

    async def _pull(self, library, tunnel) -> None:
        """Bridge the ingest actor's request queue to the wire: its
        MESSAGES requests become GetOperations frames, pages come back as
        MessagesEvents, FINISHED closes the stream.

        When a pull APPLIED anything, re-announce to our own peers:
        ingested ops land in our op log (including relayed, other-
        instance-authored ones), so in an A↔B↔C line B forwards A's
        writes to C. Announcing only on applied>0 terminates — a node
        with nothing new never re-fans."""
        ingester = Ingester(library.sync,
                            owner=f"{self._owner}/ingest")
        ingester.start()
        applied = 0
        try:
            ingester.notify()
            while True:
                req = await ingester.requests.get()
                if req.kind == ReqKind.INGESTED:
                    applied += req.count
                    continue
                if req.kind == ReqKind.FINISHED:
                    await with_timeout("p2p.frame_send",
                                       tunnel.send(wire.pack("sync.done")))
                    return
                if req.kind != ReqKind.MESSAGES:
                    continue
                # Trace continuity in the reverse direction too: the
                # pull-request frame carries this node's span (a child
                # of the originator's, once continued above) so wire
                # captures show one id everywhere. pack() supplies the
                # kind/proto consts from the declaration.
                await with_timeout("p2p.frame_send", tunnel.send(
                    wire.pack("sync.pull.request",
                              clocks=[[i, t] for i, t in req.timestamps],
                              count=OPS_PER_REQUEST,
                              tp=tracing.traceparent())))
                # The originator runs get_ops off-loop over bulk op
                # logs before this page arrives.
                page = await with_timeout("sync.pull.page", tunnel.recv())
                if isinstance(page, dict) and \
                        page.get("kind") == "blob_stream":
                    # Clone fast path: the originator answered our pull
                    # request with a verbatim blob-page stream. Hold
                    # the stream header to its contract, drain the
                    # stream here (batched apply + per-page acks), then
                    # hand the ingester an empty has_more page so its
                    # loop re-requests with the advanced clocks and the
                    # normal per-op path serves the row tail.
                    wire.unpack("clone.stream", page)
                    n, _fast, _fb = await pump_clone_stream(
                        library.sync, tunnel.recv, tunnel.send,
                        ingester.errors)
                    applied += n
                    ingester.deliver(MessagesEvent(
                        instance=library.sync.instance, messages=[],
                        has_more=True))
                    continue
                page = wire.unpack("sync.pull.page", page)
                ops = [CRDTOperation.from_wire(raw)
                       for raw in page.get("ops", [])]
                ingester.deliver(MessagesEvent(
                    instance=library.sync.instance, messages=ops,
                    has_more=bool(page.get("has_more"))))
        finally:
            # Shielded: when _pull itself is being cancelled (node
            # shutdown dropping a connection mid-pull), the ingester
            # reap must still run to completion — unshielded it would
            # die on the first await and orphan the actor task.
            await asyncio.shield(ingester.stop())
            while not ingester.requests.empty():  # unread tail counts
                req = ingester.requests.get_nowait()
                if req.kind == ReqKind.INGESTED:
                    applied += req.count
            if applied:
                # Fire the relay fan-out even when the stream ended
                # abnormally (peer drop mid-pull): whatever DID apply
                # is durably in our log and must still reach our peers.
                self.originate_soon(library)
