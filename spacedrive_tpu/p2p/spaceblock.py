"""Spaceblock: block-based file transfer over a tunnel.

Mirrors the reference's spaceblock protocol
(/root/reference/crates/p2p/src/spaceblock/mod.rs:1-70 — modeled on
Syncthing's BEP): a `SpaceblockRequest` (name, size, optional range)
followed by fixed-size blocks, each acknowledged so the receiver can
cancel mid-transfer. Block size scales with file size like the
reference's `BlockSize::from_size`.
"""

from __future__ import annotations

import os
from typing import BinaryIO, Callable, Optional

from ..timeouts import with_timeout
from . import wire
from .proto import Tunnel

KIB, MIB = 1024, 1024 * 1024


def block_size_from_file_size(size: int) -> int:
    """BlockSize::from_size heuristic (spaceblock/mod.rs)."""
    if size > 500 * MIB:
        return 4 * MIB
    if size > 100 * MIB:
        return 1 * MIB
    if size > 10 * MIB:
        return 512 * KIB
    return 128 * KIB


class SpaceblockRequest:
    def __init__(self, name: str, size: int,
                 range_start: Optional[int] = None,
                 range_end: Optional[int] = None):
        self.name = name
        self.size = size
        self.range_start = range_start
        self.range_end = range_end

    def to_wire(self) -> dict:
        return wire.pack("spaceblock.request", name=self.name,
                         size=self.size, range_start=self.range_start,
                         range_end=self.range_end)

    @classmethod
    def from_wire(cls, raw: dict) -> "SpaceblockRequest":
        raw = wire.unpack("spaceblock.request", raw)
        return cls(raw["name"], raw["size"], raw.get("range_start"),
                   raw.get("range_end"))

    @property
    def effective_range(self) -> tuple:
        start = self.range_start or 0
        end = self.range_end if self.range_end is not None else self.size
        return start, min(end, self.size)


async def send_file(tunnel: Tunnel, req: SpaceblockRequest, f: BinaryIO,
                    on_progress: Optional[Callable[[int], None]] = None,
                    ) -> bool:
    """Stream a file's (ranged) blocks; the receiver acks each block with
    continue/cancel. Returns False if cancelled."""
    start, end = req.effective_range
    block = block_size_from_file_size(req.size)
    f.seek(start)
    sent = 0
    total = end - start
    while sent < total:
        chunk = f.read(min(block, total - sent))
        if not chunk:
            break
        # Per-BLOCK budget: a transfer of any size stays alive as long
        # as block-level progress continues; a stalled receiver frees
        # the sender within one p2p.transfer.chunk window.
        await with_timeout("p2p.transfer.chunk", tunnel.send_raw(chunk))
        sent += len(chunk)
        if on_progress:
            on_progress(sent)
        ack = await with_timeout("p2p.transfer.chunk", tunnel.recv())
        try:
            ack = wire.unpack("spaceblock.verdict", ack)
        except wire.WireError:
            # An off-contract ack is no consent: stop streaming.
            return False
        if ack != "ok":
            return False
    return True


async def receive_file(tunnel: Tunnel, req: SpaceblockRequest, out: BinaryIO,
                       on_progress: Optional[Callable[[int], None]] = None,
                       should_cancel: Optional[Callable[[], bool]] = None,
                       ) -> bool:
    start, end = req.effective_range
    total = end - start
    got = 0
    while got < total:
        chunk = wire.unpack(
            "spaceblock.chunk",
            await with_timeout("p2p.transfer.chunk",
                               tunnel.recv_raw()))
        out.write(chunk)
        got += len(chunk)
        if on_progress:
            on_progress(got)
        if should_cancel and should_cancel():
            await with_timeout(
                "p2p.transfer.chunk",
                tunnel.send(wire.pack("spaceblock.verdict",
                                      value="cancel")))
            return False
        await with_timeout(
            "p2p.transfer.chunk",
            tunnel.send(wire.pack("spaceblock.verdict", value="ok")))
    return True
