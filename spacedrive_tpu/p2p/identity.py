"""Ed25519 node/instance identities.

Mirrors `spacetunnel`'s identity types
(/root/reference/crates/p2p/src/spacetunnel/identity.rs:19-60): an
`Identity` is an ed25519 keypair whose public half (`RemoteIdentity`) is
how peers and library instances are addressed and verified.
"""

from __future__ import annotations

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.exceptions import InvalidSignature


class RemoteIdentity:
    """A peer's public identity (32 raw bytes)."""

    def __init__(self, public_bytes: bytes):
        assert len(public_bytes) == 32, "ed25519 public key is 32 bytes"
        self._raw = public_bytes
        self._key = Ed25519PublicKey.from_public_bytes(public_bytes)

    def to_bytes(self) -> bytes:
        return self._raw

    def verify(self, signature: bytes, message: bytes) -> bool:
        try:
            self._key.verify(signature, message)
            return True
        except InvalidSignature:
            return False

    def __eq__(self, other) -> bool:
        return isinstance(other, RemoteIdentity) and self._raw == other._raw

    def __hash__(self) -> int:
        return hash(self._raw)

    def __repr__(self) -> str:
        return f"RemoteIdentity({self._raw.hex()[:12]}…)"


class Identity:
    def __init__(self, private_bytes: bytes | None = None):
        if private_bytes is None:
            self._key = Ed25519PrivateKey.generate()
        else:
            self._key = Ed25519PrivateKey.from_private_bytes(private_bytes)

    def to_bytes(self) -> bytes:
        return self._key.private_bytes(
            serialization.Encoding.Raw,
            serialization.PrivateFormat.Raw,
            serialization.NoEncryption())

    def to_remote_identity(self) -> RemoteIdentity:
        return RemoteIdentity(self._key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw))

    def sign(self, message: bytes) -> bytes:
        return self._key.sign(message)
