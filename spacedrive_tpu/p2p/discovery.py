"""Peer discovery: UDP multicast beacons.

Covers the role of the reference's mDNS discovery
(/root/reference/crates/p2p/src/discovery/mdns.rs): each node
periodically multicasts a signed beacon (node identity, TCP port,
metadata incl. owned instance identities); listeners maintain a
peer table with expiry. Multicast on 239.255.41.42:41420 replaces the
mdns-sd service since this environment has no zeroconf stack.
"""

from __future__ import annotations

import asyncio
import socket
import struct
import time
from typing import Callable, Dict, Optional

import msgpack

from .. import tasks
from .identity import Identity, RemoteIdentity

MCAST_GRP = "239.255.41.42"
MCAST_PORT = 41420
BEACON_INTERVAL_S = 2.0
PEER_EXPIRY_S = 10.0


class DiscoveredPeer:
    def __init__(self, identity: RemoteIdentity, addr: str, port: int,
                 metadata: dict):
        self.identity = identity
        self.addr = addr
        self.port = port
        self.metadata = metadata
        self.last_seen = time.monotonic()

    def __repr__(self) -> str:
        return f"Peer({self.identity!r} @ {self.addr}:{self.port})"


class Discovery:
    """Multicast beacon sender + listener with a peer table."""

    def __init__(self, identity: Identity, service_port: int,
                 metadata: Optional[dict] = None,
                 group: str = MCAST_GRP, port: int = MCAST_PORT,
                 owner: str = "p2p/discovery"):
        self._owner = owner
        self.identity = identity
        self.service_port = service_port
        self.metadata = metadata or {}
        self.group = group
        self.port = port
        self.peers: Dict[RemoteIdentity, DiscoveredPeer] = {}
        self.on_discovered: Optional[Callable[[DiscoveredPeer], None]] = None
        self.on_expired: Optional[Callable[[RemoteIdentity], None]] = None
        self._transport = None
        self._tasks: list = []

    def _beacon(self) -> bytes:
        body = msgpack.packb({
            "identity": self.identity.to_remote_identity().to_bytes(),
            "port": self.service_port,
            "metadata": self.metadata,
            "ts": time.time(),
        }, use_bin_type=True)
        return msgpack.packb(
            {"body": body, "sig": self.identity.sign(body)},
            use_bin_type=True)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM,
                             socket.IPPROTO_UDP)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        except (AttributeError, OSError):
            pass
        sock.bind(("", self.port))
        mreq = struct.pack("4sl", socket.inet_aton(self.group),
                           socket.INADDR_ANY)
        sock.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq)
        sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_TTL, 1)
        sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_LOOP, 1)
        sock.setblocking(False)

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(proto_self, data, addr):
                self._on_datagram(data, addr)

        self._transport, _ = await loop.create_datagram_endpoint(
            Proto, sock=sock)
        self._tasks = [
            tasks.spawn("beacon", self._beacon_loop(), owner=self._owner),
            tasks.spawn("expire", self._expire_loop(), owner=self._owner),
        ]

    def _on_datagram(self, data: bytes, addr) -> None:
        try:
            # The UDP beacon plane is its own signed envelope format,
            # pre-tunnel — no size cap / frame auditor applies here, so
            # the registry's caging doesn't either.
            outer = msgpack.unpackb(data, raw=False)  # sdlint: ok[proto-compat]
            body = msgpack.unpackb(outer["body"], raw=False)  # sdlint: ok[proto-compat]
            remote = RemoteIdentity(body["identity"])
            if remote == self.identity.to_remote_identity():
                return  # our own beacon
            if not remote.verify(outer["sig"], outer["body"]):
                return
        except Exception:
            return
        # Beacon payloads are peer-controlled even when signed (any LAN
        # host signs with its own key): validate shape before the peer
        # record reaches API consumers (the web UI renders it).
        port = body.get("port")
        if not isinstance(port, int) or not (0 < port < 65536):
            return
        is_new = remote not in self.peers
        peer = DiscoveredPeer(remote, addr[0], port,
                              body.get("metadata") or {})
        self.peers[remote] = peer
        if is_new and self.on_discovered:
            self.on_discovered(peer)

    async def _beacon_loop(self) -> None:
        while True:
            self._transport.sendto(
                self._beacon(), (self.group, self.port))
            await asyncio.sleep(BEACON_INTERVAL_S)

    async def _expire_loop(self) -> None:
        while True:
            await asyncio.sleep(PEER_EXPIRY_S / 2)
            now = time.monotonic()
            for key in [k for k, p in self.peers.items()
                        if now - p.last_seen > PEER_EXPIRY_S]:
                self.peers.pop(key, None)
                if self.on_expired:
                    self.on_expired(key)

    async def stop(self) -> None:
        await tasks.cancel_and_gather(*self._tasks)
        self._tasks = []
        if self._transport is not None:
            self._transport.close()
            self._transport = None
