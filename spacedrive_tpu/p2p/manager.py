"""P2PManager: the node's peer-to-peer service.

Covers the reference's p2p glue
(/root/reference/core/src/p2p/p2p_manager.rs:88-340 and
crates/p2p/src/manager.rs): a TCP listener whose accepted streams run the
authenticated tunnel handshake and then dispatch on a `Header`
discriminator (protocol.rs:13-27: Ping / Spacedrop / Pair / Sync / File),
plus discovery wiring and outbound stream helpers. QUIC→TCP is the one
transport substitution (see proto.py).

Spacedrop (p2p_manager.rs:88: send/accept/reject), file requests
(request_file), pairing, and the library sync plane (sync_net.py) all
ride these streams.
"""

from __future__ import annotations

import asyncio
import os
import time
import uuid as uuidlib
from typing import Any, Callable, Dict, Optional

from .. import chaos, tracing
from ..timeouts import deadline, with_timeout
from . import wire
from .discovery import Discovery, DiscoveredPeer
from .identity import Identity, RemoteIdentity
from .obs import OBS_KINDS, serve_obs
from .proto import Tunnel, tunnel_handshake
from .spaceblock import (
    SpaceblockRequest,
    receive_file,
    send_file,
)

class P2PManager:
    def __init__(self, node, identity: Optional[Identity] = None,
                 enable_discovery: bool = True):
        self.node = node
        self.identity = identity or Identity()
        self.enable_discovery = enable_discovery
        self.discovery: Optional[Discovery] = None
        self.mdns = None  # standards mDNS responder/browser (optional)
        self.server: Optional[asyncio.base_events.Server] = None
        self.port: Optional[int] = None
        # Spacedrop decision hook: (peer, request) -> save-path | None.
        self.on_spacedrop: Callable[
            [RemoteIdentity, SpaceblockRequest],
            Optional[str]] = lambda peer, req: None
        # Pairing decision hook: (peer, library_info) -> bool.
        self.on_pairing_request: Callable[
            [RemoteIdentity, dict], bool] = lambda peer, info: False
        self._spacedrop_cancel: Dict[str, bool] = {}
        # Interactive mode (api/p2p.rs acceptSpacedrop flow): when the
        # sync hook declines, park the offer in _pending_drops, emit a
        # SpacedropRequest event, and wait for accept_/reject_spacedrop.
        self.interactive_spacedrop = False
        self._pending_drops: Dict[str, asyncio.Future] = {}
        self.networked = None  # set by sync_net.NetworkedLibraries

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "0.0.0.0", port: int = 0) -> int:
        self.server = await asyncio.start_server(
            self._on_connection, host, port)
        self.port = self.server.sockets[0].getsockname()[1]
        if self.enable_discovery:
            self.discovery = Discovery(
                self.identity, self.port,
                metadata={"name": self.node.config.name,
                          "node_id": self.node.config.id.hex()},
                owner=f"{self.node.task_owner}/p2p/discovery")
            await self.discovery.start()
            # Standards-interoperable mDNS/DNS-SD alongside the signed
            # beacons (the reference's _sd-spacedrive._udp service,
            # discovery/mdns.rs): visible to any zeroconf browser.
            # Unauthenticated hints only — pairing still verifies.
            from .mdns import MdnsService

            self.mdns = MdnsService(
                instance=self.node.config.id.hex()[:12],
                service_port=self.port,
                txt={"name": self.node.config.name,
                     "id": self.node.config.id.hex(),
                     "identity":
                         self.identity.to_remote_identity()
                         .to_bytes().hex()},
                owner=f"{self.node.task_owner}/p2p/mdns")
            try:
                await self.mdns.start()
            except OSError:
                self.mdns = None  # 5353 unavailable: beacons only
        return self.port

    async def stop(self) -> None:
        if self.discovery is not None:
            await self.discovery.stop()
        if self.mdns is not None:
            await self.mdns.stop()
            self.mdns = None
        if self.server is not None:
            self.server.close()
            await self.server.wait_closed()
            self.server = None

    # -- outbound ----------------------------------------------------------

    async def open_stream(self, addr: str, port: int,
                          expected: Optional[RemoteIdentity] = None
                          ) -> Tunnel:
        async with deadline("p2p.connect"):
            # Chaos seam: error = unreachable peer (the announce
            # loop's declared backoff path), wedge parks the dial
            # until THIS deadline frees it.
            f = chaos.hit("p2p.tunnel.open",
                          only=("delay", "error", "wedge"))
            if f is not None:
                await chaos.apply_async(f)
            reader, writer = await asyncio.open_connection(addr, port)
            try:
                return await tunnel_handshake(
                    reader, writer, self.identity, initiator=True,
                    expected=expected)
            except BaseException:
                # Handshake death (timeout, bad signature, cancel):
                # the connected socket must not outlive the attempt —
                # every announce round against a half-open peer would
                # otherwise leak one fd.
                writer.close()
                raise

    async def ping(self, addr: str, port: int) -> float:
        t0 = time.monotonic()
        with tracing.span("p2p/ping", peer=f"{addr}:{port}"):
            tunnel = await self.open_stream(addr, port)
            try:
                async with deadline("p2p.ping"):
                    await tunnel.send(wire.pack(
                        "p2p.ping", tp=tracing.traceparent()))
                    wire.unpack("p2p.pong", await tunnel.recv())
            finally:
                tunnel.close()
        return time.monotonic() - t0

    def _progress_emitter(self, drop_id: str, total: int, direction: str):
        """Throttled SpacedropProgress events — same cadence as the job
        plane (jobs/worker.PROGRESS_THROTTLE_S, worker.rs:273)."""
        from ..jobs.worker import PROGRESS_THROTTLE_S

        last = [0.0]

        def emit(done: int) -> None:
            now = time.monotonic()
            if now - last[0] >= PROGRESS_THROTTLE_S or done >= total:
                last[0] = now
                self.node.events.emit({
                    "type": "SpacedropProgress", "id": drop_id,
                    "direction": direction, "bytes": done, "total": total})
        return emit

    async def spacedrop(self, addr: str, port: int, file_path: str,
                        on_progress=None) -> str:
        """Send a file to a peer; returns 'sent'|'rejected'|'cancelled'
        (p2p_manager.rs spacedrop flow). A SpacedropStarted event with
        direction='send' announces the id its progress events carry."""
        size = os.path.getsize(file_path)
        req = SpaceblockRequest(os.path.basename(file_path), size)
        drop_id = uuidlib.uuid4().hex
        on_progress = on_progress or self._progress_emitter(
            drop_id, size, "send")
        with tracing.span("p2p/spacedrop", peer=f"{addr}:{port}",
                          bytes=size):
            return await self._spacedrop_send(
                addr, port, file_path, req, drop_id, on_progress, size)

    async def _spacedrop_send(self, addr, port, file_path, req, drop_id,
                              on_progress, size) -> str:
        tunnel = await self.open_stream(addr, port)
        try:
            await with_timeout(
                "p2p.frame_send",
                tunnel.send(wire.pack(
                    "p2p.spacedrop.offer", req=req.to_wire(),
                    tp=tracing.traceparent())))
            # The verdict budget brackets the receiver's whole
            # interactive p2p.spacedrop.decide window (timeouts.py).
            try:
                verdict = wire.unpack(
                    "p2p.spacedrop.verdict",
                    await with_timeout("p2p.spacedrop.verdict",
                                       tunnel.recv()))
            except wire.WireError:
                return "rejected"  # off-contract verdict = no consent
            if verdict != "accept":
                return "rejected"
            self.node.events.emit({
                "type": "SpacedropStarted", "id": drop_id,
                "direction": "send", "name": req.name, "size": size,
                "peer": f"{addr}:{port}"})
            with await asyncio.to_thread(open, file_path, "rb") as f:
                ok = await send_file(tunnel, req, f, on_progress)
            return "sent" if ok else "cancelled"
        finally:
            tunnel.close()

    async def request_file(self, addr: str, port: int, library_id: str,
                           location_pub_id: bytes, file_path_pub_id: bytes,
                           out_path: str,
                           range_start: Optional[int] = None,
                           range_end: Optional[int] = None) -> bool:
        """Fetch a file from a remote node's library
        (files-over-p2p, custom_uri proxy path).

        Rows are addressed by their synced pub_ids — local autoincrement
        ids diverge between nodes and must never cross the wire."""
        with tracing.span("p2p/file", peer=f"{addr}:{port}"):
            tunnel = await self.open_stream(addr, port)
            try:
                await with_timeout("p2p.frame_send", tunnel.send(
                    wire.pack(
                        "p2p.file.request", library_id=library_id,
                        location_pub_id=location_pub_id,
                        file_path_pub_id=file_path_pub_id,
                        range_start=range_start, range_end=range_end,
                        tp=tracing.traceparent())))
                try:
                    resp = wire.unpack(
                        "p2p.file.response",
                        await with_timeout("p2p.file.response",
                                           tunnel.recv()))
                except wire.WireError:
                    return False
                if resp.get("status") != "ok":
                    return False
                req = SpaceblockRequest.from_wire(resp["req"])
                with await asyncio.to_thread(open, out_path, "wb") as out:
                    return await receive_file(tunnel, req, out)
            finally:
                tunnel.close()

    async def pair(self, addr: str, port: int, library) -> bool:
        """Pair a library with a peer: exchange instance rows so sync can
        flow (core/src/p2p/pairing/mod.rs protocol v1, simplified to one
        round-trip of signed instance info)."""
        with tracing.span("p2p/pair", peer=f"{addr}:{port}",
                          library=str(library.id)):
            return await self._pair(addr, port, library)

    async def _pair(self, addr: str, port: int, library) -> bool:
        sync = library.sync
        tunnel = await self.open_stream(addr, port)
        try:
            # One budget over the whole round-trip: the responder's
            # decision hook + instance-row DB writes included.
            async with deadline("p2p.pair"):
                me = await asyncio.to_thread(
                    library.db.query_one,
                    "SELECT * FROM instance WHERE pub_id = ?",
                    (sync.instance,))
                await tunnel.send(wire.pack(
                    "p2p.pair.request",
                    tp=tracing.traceparent(),
                    library_id=str(library.id),
                    library_name=library.config.name,
                    # Our LISTENING port (the TCP source port is
                    # ephemeral): the responder derives a route back to
                    # us from it.
                    listen_port=self.port,
                    instance={
                        "pub_id": me["pub_id"], "identity":
                            self.identity.to_remote_identity().to_bytes(),
                        "node_id": self.node.config.id,
                        "node_name": self.node.config.name,
                    }))
                try:
                    resp = wire.unpack("p2p.pair.response",
                                       await tunnel.recv())
                except wire.WireError:
                    return False
                if resp.get("status") != "accepted":
                    return False
                inst = resp["instance"]
                await asyncio.to_thread(
                    library.sync.register_instance,
                    inst["pub_id"], identity=inst["identity"],
                    node_id=inst["node_id"], node_name=inst["node_name"])
            if self.networked is not None:
                self.networked.learn_instance(
                    library.id, inst["pub_id"],
                    RemoteIdentity(inst["identity"]),
                    route=(addr, port))
                # Backfill: announce immediately so the fresh peer pulls
                # the library's existing op log — without this, a paired
                # library stays empty until the NEXT local write.
                self.networked.originate_soon(library)
            return True
        finally:
            tunnel.close()

    # -- inbound dispatch (p2p_manager.rs event loop match Header) ---------

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        try:
            tunnel = await tunnel_handshake(
                reader, writer, self.identity, initiator=False)
        except Exception:
            writer.close()
            return
        try:
            header = await with_timeout("p2p.header_recv", tunnel.recv())
            t = header.get("t") if isinstance(header, dict) else None
            tp = header.get("tp") if isinstance(header, dict) else None
            # Continue the dialer's trace across the wire: every
            # handler span below (and sync.pull, which re-anchors to
            # the same header) lands in the caller's trace — a
            # request is one trace id end-to-end over the mesh.
            # Each branch holds the header to its declared contract
            # BEFORE any field is read; a WireError lands in the
            # generic handler below — P2PError event + tunnel close,
            # the declared disconnect path a malformed peer gets.
            with tracing.continue_trace(tp):
                if t == "ping":
                    with tracing.span("p2p/ping"):
                        wire.unpack("p2p.ping", header)
                        await with_timeout(
                            "p2p.frame_send",
                            tunnel.send(wire.pack("p2p.pong")))
                elif t == "spacedrop":
                    with tracing.span("p2p/spacedrop"):
                        await self._handle_spacedrop(
                            tunnel,
                            wire.unpack("p2p.spacedrop.offer", header))
                elif t == "pair":
                    with tracing.span("p2p/pair"):
                        await self._handle_pair(
                            tunnel,
                            wire.unpack("p2p.pair.request", header))
                elif t == "file":
                    with tracing.span("p2p/file"):
                        await self._handle_file(
                            tunnel,
                            wire.unpack("p2p.file.request", header))
                elif t in OBS_KINDS:
                    # Fleet observatory pull: serve the local
                    # telemetry/health/trace snapshot. Built off-loop
                    # (a snapshot walks the whole registry or copies
                    # the span ring); the deadline brackets the
                    # snapshot build AND the response send — the whole
                    # exchange the p2p.obs contract declares, so a
                    # wedged registry walk cannot hold a server slot
                    # unbudgeted.
                    with tracing.span("p2p/obs", what=t):
                        async with deadline("p2p.obs"):
                            resp = await asyncio.to_thread(
                                serve_obs, self.node, header)
                            await tunnel.send(resp)
                elif t == "sync":
                    # handle_sync_stream opens its own continued
                    # sync.pull span parented directly on the
                    # originator's sync.serve span.
                    if self.networked is not None:
                        await self.networked.handle_sync_stream(
                            tunnel, header)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception as e:
            self.node.events.emit({"type": "P2PError", "error": str(e)})
        finally:
            tunnel.close()

    async def _decide_spacedrop(self, peer: RemoteIdentity,
                                req: SpaceblockRequest,
                                drop_id: str) -> Optional[str]:
        save_path = self.on_spacedrop(peer, req)
        if save_path is not None or not self.interactive_spacedrop:
            return save_path
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending_drops[drop_id] = fut
        # Defense in depth: the peer-chosen name is untrusted input —
        # every consumer gets a path-free basename (a hostile
        # "../../x" must never reach a save-path prompt).
        safe_name = os.path.basename(req.name).lstrip(".") or \
            "spacedrop.bin"
        self.node.events.emit({
            "type": "SpacedropRequest", "id": drop_id, "name": safe_name,
            "size": req.size, "peer": peer.to_bytes().hex()})
        try:
            return await with_timeout("p2p.spacedrop.decide", fut)
        except asyncio.TimeoutError:
            self.node.events.emit(
                {"type": "SpacedropTimedout", "id": drop_id})
            return None
        finally:
            self._pending_drops.pop(drop_id, None)

    def accept_spacedrop(self, drop_id: str, save_path: str) -> bool:
        fut = self._pending_drops.get(drop_id)
        if fut is None or fut.done():
            return False
        fut.set_result(save_path)
        return True

    def reject_spacedrop(self, drop_id: str) -> bool:
        fut = self._pending_drops.get(drop_id)
        if fut is None or fut.done():
            return False
        fut.set_result(None)
        self.node.events.emit({"type": "SpacedropRejected", "id": drop_id})
        return True

    async def _handle_spacedrop(self, tunnel: Tunnel, header: dict) -> None:
        req = SpaceblockRequest.from_wire(header["req"])
        # Receiver-minted id: the sender's header id is untrusted input —
        # colliding/replayed ids must not cross-wire pending offers.
        drop_id = uuidlib.uuid4().hex
        save_path = await self._decide_spacedrop(tunnel.remote, req, drop_id)
        if save_path is None:
            await with_timeout(
                "p2p.frame_send",
                tunnel.send(wire.pack("p2p.spacedrop.verdict",
                                      value="reject")))
            return
        await with_timeout(
            "p2p.frame_send",
            tunnel.send(wire.pack("p2p.spacedrop.verdict",
                                  value="accept")))
        self._spacedrop_cancel[drop_id] = False
        # Announce the receive (with its cancellation id) in BOTH modes —
        # p2p.cancelSpacedrop needs an id even when a sync hook accepted.
        self.node.events.emit({
            "type": "SpacedropStarted", "id": drop_id, "name": req.name,
            "direction": "receive", "size": req.size, "path": save_path,
            "peer": tunnel.remote.to_bytes().hex()})
        try:
            with await asyncio.to_thread(open, save_path, "wb") as out:
                await receive_file(
                    tunnel, req, out,
                    on_progress=self._progress_emitter(
                        drop_id, req.size, "receive"),
                    should_cancel=lambda: self._spacedrop_cancel.get(
                        drop_id, False))
        finally:
            self._spacedrop_cancel.pop(drop_id, None)
        self.node.events.emit({
            "type": "SpacedropReceived", "name": req.name,
            "path": save_path, "from": tunnel.remote.to_bytes().hex()})

    def cancel_spacedrop(self, drop_id: str) -> None:
        if drop_id in self._spacedrop_cancel:
            self._spacedrop_cancel[drop_id] = True

    async def _handle_pair(self, tunnel: Tunnel, header: dict) -> None:
        if not self.on_pairing_request(tunnel.remote, header):
            await with_timeout(
                "p2p.frame_send",
                tunnel.send(wire.pack("p2p.pair.response",
                                      status="rejected")))
            return
        lib = None
        for candidate in self.node.libraries.list():
            if str(candidate.id) == header["library_id"]:
                lib = candidate
                break
        if lib is None:
            # Pairing into a library we don't have yet: create it locally
            # UNDER THE ORIGINATOR'S UUID — sync streams address
            # libraries by id, so both sides must agree on it.
            lib = self.node.create_library(
                header.get("library_name", "paired library"),
                lib_id=uuidlib.UUID(str(header["library_id"])))
        inst = header["instance"]
        await asyncio.to_thread(
            lib.sync.register_instance,
            inst["pub_id"], identity=inst["identity"],
            node_id=inst["node_id"], node_name=inst["node_name"])
        if self.networked is not None:
            # Route back to the initiator: its socket IP + the listening
            # port it sent (NOT the connection's ephemeral source port).
            route = None
            peer = tunnel.writer.get_extra_info("peername")
            if peer and header.get("listen_port"):
                route = (peer[0], int(header["listen_port"]))
            self.networked.learn_instance(
                lib.id, inst["pub_id"], RemoteIdentity(inst["identity"]),
                route=route)
        me = await asyncio.to_thread(
            lib.db.query_one,
            "SELECT * FROM instance WHERE pub_id = ?",
            (lib.sync.instance,))
        await with_timeout("p2p.frame_send", tunnel.send(wire.pack(
            "p2p.pair.response", status="accepted", instance={
                "pub_id": me["pub_id"],
                "identity":
                    self.identity.to_remote_identity().to_bytes(),
                "node_id": self.node.config.id,
                "node_name": self.node.config.name,
            })))
        if self.networked is not None:
            # Symmetric backfill: OUR pre-existing ops (re-pairing case)
            # flow to the initiator without waiting for a local write.
            self.networked.originate_soon(lib)

    async def _handle_file(self, tunnel: Tunnel, header: dict) -> None:
        from ..locations.paths import IsolatedPath
        lib = self.node.libraries.get(
            uuidlib.UUID(str(header["library_id"])))
        if lib is None:
            await with_timeout(
                "p2p.frame_send",
                tunnel.send(wire.pack("p2p.file.response",
                                      status="not_found")))
            return
        loc = await asyncio.to_thread(
            lib.db.query_one,
            "SELECT * FROM location WHERE pub_id = ?",
            (bytes(header["location_pub_id"]),))
        row = (await asyncio.to_thread(
            lib.db.query_one,
            "SELECT * FROM file_path WHERE pub_id = ?",
            (bytes(header["file_path_pub_id"]),))) if loc else None
        if (row is None or loc is None or not loc["path"]
                or row["location_id"] != loc["id"]):
            await with_timeout(
                "p2p.frame_send",
                tunnel.send(wire.pack("p2p.file.response",
                                      status="not_found")))
            return
        iso = IsolatedPath.from_db_row(
            loc["id"], bool(row["is_dir"]),
            row["materialized_path"], row["name"] or "",
            row["extension"] or "")
        full = iso.join_on(loc["path"])
        if not os.path.isfile(full):
            await with_timeout(
                "p2p.frame_send",
                tunnel.send(wire.pack("p2p.file.response",
                                      status="not_found")))
            return
        req = SpaceblockRequest(
            os.path.basename(full), os.path.getsize(full),
            header.get("range_start"), header.get("range_end"))
        await with_timeout(
            "p2p.frame_send",
            tunnel.send(wire.pack("p2p.file.response", status="ok",
                                  req=req.to_wire())))
        with await asyncio.to_thread(open, full, "rb") as f:
            await send_file(tunnel, req, f)
