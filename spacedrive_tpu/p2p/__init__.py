try:
    from .identity import Identity, RemoteIdentity
    from .manager import P2PManager
    __all__ = ["Identity", "RemoteIdentity", "P2PManager"]
except ModuleNotFoundError as e:  # pragma: no cover - environmental
    # The tunnel layer needs the `cryptography` package; containers
    # without it still import the package so the crypto-free
    # observability submodule (p2p/obs.py: serve_obs + the fleet
    # poller's payload shapes) stays usable. Touching P2PManager in
    # such a runtime raises at the point of use, exactly as before.
    # ONLY that one dependency is gated — any other missing module
    # (msgpack, a typo'd import) must surface loudly, not read as
    # "no crypto".
    if (e.name or "").split(".")[0] != "cryptography":
        raise
    __all__ = []
