from .identity import Identity, RemoteIdentity
from .manager import P2PManager

__all__ = ["Identity", "RemoteIdentity", "P2PManager"]
