"""Standards-interoperable mDNS / DNS-SD discovery.

The reference advertises over real mDNS (`_sd-spacedrive._udp.local`
service with TXT metadata, /root/reference/crates/p2p/src/discovery/
mdns.rs) so third-party zeroconf browsers can see nodes. The signed
UDP-beacon plane (p2p/discovery.py) remains this framework's default —
it is authenticated, which mDNS is not — and this module adds the
standard-protocol responder/browser on 224.0.0.251:5353 for
interoperability: announcements any `avahi-browse`/`dns-sd` client can
resolve, and a browser that discovers peers advertising the same
service type.

Wire format is hand-rolled RFC 1035/6762/6763 (no zeroconf package in
this image): header + name compression decode (encode is
compression-free, which is always legal), A / PTR / SRV / TXT records.
Like the reference's mDNS, records are UNAUTHENTICATED hints — pairing
performs the real identity verification before any data flows.

Service shape (RFC 6763):
  PTR  _spacedrive._udp.local            -> <inst>._spacedrive._udp.local
  SRV  <inst>._spacedrive._udp.local     -> <host>.local : service_port
  TXT  <inst>._spacedrive._udp.local     -> id=<hex peer id>, name=...
  A    <host>.local                      -> local address
"""

from __future__ import annotations

import asyncio
import socket
import struct
import time
from typing import Callable, Dict, List, Optional, Tuple

from .. import tasks

MDNS_GRP = "224.0.0.251"
MDNS_PORT = 5353
SERVICE = "_spacedrive._udp.local"
TTL = 120
ANNOUNCE_INTERVAL_S = 30.0
QUERY_INTERVAL_S = 15.0

TYPE_A = 1
TYPE_PTR = 12
TYPE_TXT = 16
TYPE_SRV = 33
CLASS_IN = 1
CACHE_FLUSH = 0x8001  # class IN + cache-flush bit on records we own


# -- DNS wire codec ---------------------------------------------------------

def encode_name(name: str) -> bytes:
    out = b""
    for label in name.strip(".").split("."):
        raw = label.encode()
        if not 0 < len(raw) < 64:
            raise ValueError(f"bad label {label!r}")
        out += bytes([len(raw)]) + raw
    return out + b"\x00"


def decode_name(buf: bytes, off: int) -> Tuple[str, int]:
    """Decodes a (possibly compression-pointer) name; returns
    (name, next offset). Guards pointer loops."""
    labels: List[str] = []
    jumps = 0
    end = None
    while True:
        if off >= len(buf):
            raise ValueError("truncated name")
        ln = buf[off]
        if ln == 0:
            off += 1
            break
        if ln & 0xC0 == 0xC0:  # compression pointer
            if off + 1 >= len(buf):
                raise ValueError("truncated pointer")
            ptr = ((ln & 0x3F) << 8) | buf[off + 1]
            if end is None:
                end = off + 2
            off = ptr
            jumps += 1
            if jumps > 32:
                raise ValueError("pointer loop")
            continue
        if ln & 0xC0:
            raise ValueError("bad label type")
        labels.append(buf[off + 1:off + 1 + ln].decode(errors="replace"))
        off += 1 + ln
    return ".".join(labels), (end if end is not None else off)


def _record(name: str, rtype: int, rdata: bytes,
            rclass: int = CACHE_FLUSH, ttl: int = TTL) -> bytes:
    return (encode_name(name) + struct.pack(">HHIH", rtype, rclass, ttl,
                                            len(rdata)) + rdata)


def txt_rdata(kv: Dict[str, str]) -> bytes:
    out = b""
    for k, v in kv.items():
        pair = f"{k}={v}".encode()[:255]
        out += bytes([len(pair)]) + pair
    return out or b"\x00"


def parse_txt(rdata: bytes) -> Dict[str, str]:
    out: Dict[str, str] = {}
    off = 0
    while off < len(rdata):
        ln = rdata[off]
        body = rdata[off + 1:off + 1 + ln]
        off += 1 + ln
        if b"=" in body:
            k, _, v = body.partition(b"=")
            out[k.decode(errors="replace")] = v.decode(errors="replace")
    return out


def parse_packet(buf: bytes):
    """-> (is_response, questions [(name, type)], answers
    [(name, type, ttl, rdata, full_buf, rdata_off)]) — rdata offsets
    kept so SRV/PTR targets can chase compression pointers."""
    if len(buf) < 12:
        raise ValueError("short packet")
    (_tid, flags, qd, an, ns, ar) = struct.unpack(">HHHHHH", buf[:12])
    off = 12
    questions = []
    for _ in range(qd):
        name, off = decode_name(buf, off)
        qtype, _qclass = struct.unpack(">HH", buf[off:off + 4])
        off += 4
        questions.append((name, qtype))
    answers = []
    for _ in range(an + ns + ar):
        name, off = decode_name(buf, off)
        rtype, _rclass, ttl, rdlen = struct.unpack(">HHIH",
                                                   buf[off:off + 10])
        off += 10
        answers.append((name, rtype, ttl, buf[off:off + rdlen], buf, off))
        off += rdlen
    return bool(flags & 0x8000), questions, answers


# -- service ---------------------------------------------------------------

class MdnsPeer:
    def __init__(self, instance: str, addr: str, port: int,
                 txt: Dict[str, str]):
        self.instance = instance
        self.addr = addr
        self.port = port
        self.txt = txt
        self.last_seen = time.monotonic()

    def __repr__(self) -> str:
        return f"MdnsPeer({self.instance!r} @ {self.addr}:{self.port})"


class MdnsService:
    """mDNS responder + browser for the spacedrive service type."""

    def __init__(self, instance: str, service_port: int,
                 txt: Optional[Dict[str, str]] = None,
                 group: str = MDNS_GRP, port: int = MDNS_PORT,
                 owner: str = "p2p/mdns"):
        self._owner = owner
        # instance/host labels must be DNS-safe
        safe = "".join(c if c.isalnum() or c == "-" else "-"
                       for c in instance)[:32] or "node"
        self.instance = f"{safe}.{SERVICE}"
        self.host = f"{safe}.local"
        self.service_port = service_port
        self.txt = dict(txt or {})
        self.group = group
        self.port = port
        self.peers: Dict[str, MdnsPeer] = {}
        self.on_discovered: Optional[Callable[[MdnsPeer], None]] = None
        self._transport = None
        self._tasks: list = []
        # SRV/TXT arrive in separate packets from some stacks: hold
        # partial info until both halves exist.
        self._partial: Dict[str, dict] = {}

    # -- record building --

    def _local_ip(self) -> str:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((self.group, self.port))
            return s.getsockname()[0]
        except OSError:
            return "127.0.0.1"
        finally:
            s.close()

    def _announcement(self, ttl: int = TTL) -> bytes:
        ip = self._local_ip()
        answers = [
            _record(SERVICE, TYPE_PTR, encode_name(self.instance),
                    rclass=CLASS_IN, ttl=ttl),  # shared record: no flush
            _record(self.instance, TYPE_SRV,
                    struct.pack(">HHH", 0, 0, self.service_port)
                    + encode_name(self.host), ttl=ttl),
            _record(self.instance, TYPE_TXT, txt_rdata(self.txt),
                    ttl=ttl),
            _record(self.host, TYPE_A, socket.inet_aton(ip), ttl=ttl),
        ]
        header = struct.pack(">HHHHHH", 0, 0x8400, 0, len(answers), 0, 0)
        return header + b"".join(answers)

    def _query(self) -> bytes:
        q = encode_name(SERVICE) + struct.pack(">HH", TYPE_PTR, CLASS_IN)
        return struct.pack(">HHHHHH", 0, 0, 1, 0, 0, 0) + q

    # -- lifecycle --

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM,
                             socket.IPPROTO_UDP)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            except (AttributeError, OSError):
                pass
            sock.bind(("", self.port))
            mreq = struct.pack("4sl", socket.inet_aton(self.group),
                               socket.INADDR_ANY)
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP,
                            mreq)
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_TTL, 1)
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_LOOP, 1)
            sock.setblocking(False)
        except OSError:
            sock.close()  # 5353 taken / membership denied: no fd leak
            raise

        svc = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(proto_self, data, addr):
                svc._on_datagram(data, addr)

        self._transport, _ = await loop.create_datagram_endpoint(
            Proto, sock=sock)
        self._tasks = [
            tasks.spawn("announce", self._announce_loop(),
                        owner=self._owner),
            tasks.spawn("query", self._query_loop(), owner=self._owner),
            tasks.spawn("expire", self._expire_loop(), owner=self._owner),
        ]

    async def stop(self) -> None:
        # goodbye packet: TTL 0 clears remote caches (RFC 6762 §10.1)
        if self._transport is not None:
            try:
                self._transport.sendto(self._announcement(ttl=0),
                                       (self.group, self.port))
            except Exception:
                pass
        await tasks.cancel_and_gather(*self._tasks)
        self._tasks = []
        if self._transport is not None:
            self._transport.close()
            self._transport = None

    # -- protocol --

    def _on_datagram(self, data: bytes, addr) -> None:
        try:
            is_resp, questions, answers = parse_packet(data)
        except Exception:
            return
        if not is_resp:
            # respond to PTR queries for our service type (and direct
            # SRV/TXT questions for our instance)
            for name, qtype in questions:
                if (name.lower() == SERVICE and qtype == TYPE_PTR) or \
                        name.lower() == self.instance.lower():
                    self._transport.sendto(self._announcement(),
                                           (self.group, self.port))
                    break
            return
        self._ingest_answers(answers, addr)

    MAX_PARTIAL = 512  # hostile-LAN bound on half-resolved entries

    def _ingest_answers(self, answers, addr) -> None:
        touched = set()

        def partial(lname: str, name: str) -> Optional[dict]:
            p = self._partial.get(lname)
            if p is None:
                if len(self._partial) >= self.MAX_PARTIAL:
                    return None  # bound the table on a hostile LAN
                p = self._partial[lname] = {"inst": name}
            # address follows the answering packet for THIS entry only
            p["addr"] = addr[0]
            touched.add(lname)
            return p

        for name, rtype, ttl, rdata, buf, roff in answers:
            lname = name.lower()
            if rtype == TYPE_PTR and lname == SERVICE:
                try:
                    inst, _ = decode_name(buf, roff)
                except ValueError:
                    continue
                if inst.lower() == self.instance.lower():
                    continue  # ourselves
                partial(inst.lower(), inst)
            elif rtype == TYPE_SRV:
                if lname == self.instance.lower():
                    continue
                try:
                    port = struct.unpack(">H", rdata[4:6])[0]
                except struct.error:
                    continue
                if ttl == 0:
                    self.peers.pop(lname, None)
                    self._partial.pop(lname, None)
                    touched.discard(lname)
                    continue
                p = partial(lname, name)
                if p is not None:
                    p["port"] = port
            elif rtype == TYPE_TXT:
                if lname == self.instance.lower():
                    continue
                p = partial(lname, name)
                if p is not None:
                    p["txt"] = parse_txt(rdata)
        # Graduate ONLY entries this packet touched — re-graduating the
        # whole table stamped every known peer with THIS packet's
        # source address (round-5 review finding). Partial state stays
        # until SRV+TXT both arrive; complete entries are dropped from
        # the table once peers holds them.
        for key in touched:
            p = self._partial.get(key)
            if not p or "port" not in p or not key.endswith(SERVICE):
                continue
            peer = MdnsPeer(p["inst"], p.get("addr", addr[0]),
                            p["port"], p.get("txt", {}))
            is_new = key not in self.peers
            self.peers[key] = peer
            if "txt" in p:
                self._partial.pop(key, None)
            if is_new and self.on_discovered:
                self.on_discovered(peer)

    async def _announce_loop(self) -> None:
        # RFC 6762 §8.3: a couple of quick startup announcements, then
        # periodic refresh well inside TTL
        for delay in (0.1, 1.0):
            await asyncio.sleep(delay)
            self._transport.sendto(self._announcement(),
                                   (self.group, self.port))
        while True:
            await asyncio.sleep(ANNOUNCE_INTERVAL_S)
            self._transport.sendto(self._announcement(),
                                   (self.group, self.port))

    async def _query_loop(self) -> None:
        while True:
            self._transport.sendto(self._query(), (self.group, self.port))
            await asyncio.sleep(QUERY_INTERVAL_S)

    async def _expire_loop(self) -> None:
        while True:
            await asyncio.sleep(TTL / 2)
            now = time.monotonic()
            for key in [k for k, p in self.peers.items()
                        if now - p.last_seen > TTL]:
                self.peers.pop(key, None)
