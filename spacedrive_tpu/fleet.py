"""Fleet observatory: cross-node metrics/health federation and
distributed trace assembly.

ROADMAP items 2 and 3 (multi-host sharded fleet; million-user serving)
cannot be debugged blind: PR 10's traceparent makes one logical
operation span nodes, and PR 11's health observatory attributes
saturation — but only for its OWN process. This module federates the
observability planes over the same p2p layer the data plane uses
(PAPER.md L2c: locations live on nodes behind the mesh):

- **Poller.** A supervised task (owner ``node/fleet``, interval
  `SDTPU_FLEET_INTERVAL_S`) pulls every registered peer's
  ``obs.health`` snapshot (p2p/obs.py protocol; the production
  transport is `P2PObsClient` over authenticated tunnels, with
  loopback and rspc-HTTP clients for in-process fleets and
  crypto-less containers) into a bounded per-peer ring
  (`fleet.peer.snapshots`). Every fetch runs under the declared
  ``fleet.poll`` budget; outcomes count into
  ``sd_fleet_polls_total{outcome}``. A malformed snapshot is rejected
  by the schema gate WITHOUT touching the ring — one poisoned peer
  cannot corrupt the fleet view. Each good round also pulls the
  peer's ``obs.incidents`` bundle HEADERS best-effort, so every
  fleet row carries an incident digest (open/total + newest
  headers) even after the peer goes unreachable.
- **Merger.** The fleet health view reuses PR 11's
  saturation-attribution rules — each node's own engine already
  named its bottlenecks — and re-keys them per ``(node, subsystem)``.
  A peer that is unreachable or whose last good snapshot is older
  than 2x the poll interval is marked ``degraded`` under its ``peer``
  pseudo-subsystem with last-seen evidence inline.
- **Trace assembly.** `assemble_trace(trace_id)` fetches every peer's
  span-ring + flight-timeline slice for one trace id
  (``obs.trace``, budget ``fleet.trace.fetch``) and merges them with
  the local slice into ONE validated Chrome-trace document
  (flight.fleet_chrome_trace): per-node pid lanes, each remote
  node's clock aligned by the skew estimated from obs-poll RTT
  midpoints (skew = peer_sampled_at - poll_midpoint), the offsets
  recorded in the document's metadata.
- **Surfaces.** The ``fleet.health`` / ``fleet.metrics`` rspc queries,
  the ``fleet.health`` subscription (FleetHealthSnapshot events,
  coalesced newest-wins in the ws pump), ``fleet.trace.export``, and
  the `tools/sd_top.py --fleet` / `tools/trace_export.py --fleet`
  operator CLIs.

Design constraints: stdlib + the registry modules + health/flight
only — importable without jax AND without the tunnel stack's
`cryptography` dependency (the p2p obs submodule it leans on is
deliberately crypto-free; p2p/__init__ gates the rest).
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Dict, List, Optional

from . import channels, chaos, flags, flight, incidents, tasks, \
    telemetry, timeouts, tracing
from .health import STATES, validate_health_snapshot
from .p2p.obs import OBS_PROTO
from .telemetry import FLEET_PEERS, FLEET_PEERS_STALE, FLEET_POLLS
from .timeouts import with_backoff, with_timeout

__all__ = [
    "FleetMonitor", "LoopbackObsClient", "HttpObsClient",
    "validate_obs_response", "validate_fleet_snapshot",
]

# A peer whose freshest good snapshot is older than this many poll
# intervals is a stale row (documented with the flag declaration).
STALE_INTERVALS = 2.0

# Newest bundle headers carried inline per fleet row — enough to see
# WHAT froze on each node; the full bundle stays on the owning node
# (pulled via its rspc incidents.get, never pushed over the fleet
# plane).
INCIDENT_RECENT = 3


# -- obs response schema gate ------------------------------------------------

def validate_obs_response(what: str, resp: Any) -> List[str]:
    """Problems with one obs response envelope (empty = valid). The
    poller's poisoning gate: a peer answering garbage — wrong proto,
    missing identity, a health payload that fails PR 11's snapshot
    schema — is rejected here and its row goes stale-degraded; the
    merged fleet view never sees the bytes."""
    problems: List[str] = []
    if not isinstance(resp, dict):
        return [f"{what}: response must be a dict"]
    if resp.get("status") != "ok":
        return [f"{what}: status {resp.get('status')!r} "
                f"({resp.get('error', 'no error detail')})"]
    if resp.get("proto") != OBS_PROTO:
        problems.append(f"{what}: obs proto {resp.get('proto')!r} != "
                        f"ours {OBS_PROTO}")
    if resp.get("what") != what:
        problems.append(f"{what}: answered for {resp.get('what')!r}")
    node = resp.get("node")
    if not isinstance(node, dict) or \
            not isinstance(node.get("id"), str) or \
            not isinstance(node.get("name"), str):
        problems.append(f"{what}: node identity must be "
                        "{id: str, name: str}")
    if not isinstance(resp.get("ts"), (int, float)):
        problems.append(f"{what}: ts must be a number")
    if what == "obs.health":
        health = resp.get("health")
        if not isinstance(health, dict):
            problems.append("obs.health: health payload missing")
        else:
            problems.extend(
                f"obs.health: {p}"
                for p in validate_health_snapshot(health))
    elif what == "obs.metrics":
        if not isinstance(resp.get("metrics"), dict):
            problems.append("obs.metrics: metrics payload missing")
    elif what == "obs.incidents":
        headers = resp.get("incidents")
        if not isinstance(headers, list):
            problems.append(
                "obs.incidents: incidents must be a list")
        else:
            # Every header must pass the incident schema gate — one
            # peer serving malformed headers is rejected whole, same
            # poisoning rule as a malformed health snapshot.
            for i, h in enumerate(headers):
                sub = incidents.validate_incident_header(h)
                if sub:
                    problems.append(
                        f"obs.incidents: incidents[{i}]: {sub[0]}")
                    break
    elif what == "obs.trace":
        for key in ("spans", "timeline"):
            seq = resp.get(key)
            if not isinstance(seq, list) or \
                    any(not isinstance(e, dict) for e in seq):
                problems.append(
                    f"obs.trace: {key} must be a list of objects")
                continue
            # The fields the merger arithmetics over must be numeric
            # when present — one peer's {"ts_us": null} entry must be
            # rejected HERE, not crash the whole assembled trace.
            for i, e in enumerate(seq):
                bad = next((f for f in ("ts_us", "dur_us", "ms")
                            if f in e and not isinstance(
                                e[f], (int, float))), None)
                if bad is not None:
                    problems.append(
                        f"obs.trace: {key}[{i}].{bad} must be a "
                        "number")
                    break
    else:
        problems.append(f"unknown obs kind {what!r}")
    return problems


# -- transports --------------------------------------------------------------
# Every client is one async `fetch(what, trace=None) -> response
# envelope`; the poller wraps each call in the declared fleet.* budget
# regardless of transport. The production transport (P2PObsClient,
# authenticated tunnels) lives in p2p/obs.py next to the serving side.

class LoopbackObsClient:
    """In-process transport fake for the obs plane (the reference's
    in-process sync transport, core/crates/sync/tests/lib.rs:109-163):
    serves another node object in the SAME process through the same
    `serve_obs` dispatch the p2p handler uses — protocol semantics
    without the tunnel. What the unit tests and crypto-less containers
    drive."""

    def __init__(self, node):
        self.node = node

    async def fetch(self, what: str,
                    trace: Optional[str] = None) -> Any:
        from .p2p.obs import serve_obs

        header: Dict[str, Any] = {"t": what}
        if trace:
            header["trace"] = str(trace)
        return await asyncio.to_thread(serve_obs, self.node, header)


class HttpObsClient:
    """Fetch obs snapshots from a peer's rspc HTTP host
    (GET /rspc/obs.*, api/procedures.py) — the transport for fleets
    whose tunnel stack is unavailable, and what the sd_top --fleet
    self-check drives across two real processes in-container."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")

    def _get(self, what: str, trace: Optional[str]) -> Any:
        import json
        import urllib.parse
        import urllib.request

        from . import timeouts

        q = ""
        if trace:
            q = "?input=" + urllib.parse.quote(
                json.dumps({"trace": str(trace)}))
        endpoint = f"{self.url}/rspc/{what}{q}"
        # The socket timeout mirrors the caller's declared budget:
        # trace slices run under the bigger fleet.trace.fetch, the
        # health/metrics polls under fleet.poll.
        budget = timeouts.budget(
            "fleet.trace.fetch" if what == "obs.trace" else "fleet.poll")
        with urllib.request.urlopen(endpoint, timeout=budget) as resp:
            payload = json.load(resp)
        return payload.get("result") if isinstance(payload, dict) \
            else None

    async def fetch(self, what: str,
                    trace: Optional[str] = None) -> Any:
        # Declared obs.http backoff: a transient connect failure
        # against a restarting peer retries inside the caller's
        # fleet.poll budget instead of failing the round outright;
        # exhaustion surfaces the final error to the poller, which
        # marks the row unreachable. URLError (and every socket-level
        # refusal) is an OSError.
        return await with_backoff(
            "obs.http",
            lambda: asyncio.to_thread(self._get, what, trace),
            retry_on=(OSError,))


# -- the federation engine ---------------------------------------------------

class FleetMonitor:
    """Poller + merger + trace assembler, one per node (constructed at
    bootstrap next to the HealthMonitor, reaped under ``node/fleet``).
    Also constructible loose (node=None + explicit identity/health)
    for CLIs building throwaway fleets around a run."""

    def __init__(self, node=None, interval_s: Optional[float] = None,
                 owner: str = "fleet", node_id: str = "",
                 node_name: str = "", health=None):
        self._lock = threading.Lock()
        self.node = node
        if node is not None:
            node_id = node_id or node.config.id.hex()
            node_name = node_name or node.config.name
            health = health if health is not None else node.health
        self.node_identity = {"id": str(node_id),
                              "name": str(node_name)}
        self.health = health
        self.events = getattr(node, "events", None)
        if interval_s is None:
            interval_s = float(flags.get("SDTPU_FLEET_INTERVAL_S"))
        self.interval_s = max(0.05, interval_s)
        self._owner = owner
        self._task: Optional[asyncio.Task] = None
        # peer_id -> record (client, per-peer snapshot ring, liveness
        # facts), all under _lock (contract in threadctx.py). Bounded
        # by registered peers — paired routes plus explicit add_peer
        # calls — not by history.
        self._peers: Dict[str, Dict[str, Any]] = {}  # sdlint: ok[unbounded-growth]
        self._snapshots = channels.channel("fleet.snapshots")
        self._last: Optional[Dict[str, Any]] = None
        # Declared poll discipline for UNREACHABLE peers (timeouts.py
        # fleet.peer.poll): a failed fetch parks the peer's next poll
        # up the ladder instead of burning a fleet.poll budget every
        # round; state evicts on success, so it is bounded by
        # currently-unreachable peers. Never gives up — the row is
        # already stale-degraded, and cap-cadence probes see the heal.
        self._poll_backoff = timeouts.RetrySchedule("fleet.peer.poll")

    # -- peer registry -----------------------------------------------------

    def add_peer(self, peer_id: str, client, name: str = "") -> None:
        """Register one peer (idempotent per id; the client object is
        refreshed so a re-pair with a new route takes effect)."""
        with self._lock:
            rec = self._peers.get(peer_id)
            if rec is None:
                rec = {
                    "peer_id": peer_id, "name": name or peer_id[:12],
                    "client": client,
                    "ring": channels.channel("fleet.peer.snapshots"),
                    "last_ok": None, "rtt_s": None, "skew_s": None,
                    "error": "", "incidents": [],
                }
                self._peers[peer_id] = rec
            else:
                rec["client"] = client
                if name:
                    rec["name"] = name
            n = len(self._peers)
        # A (re-)registered client is an affirmative route signal
        # (fresh pair, route moved): probe it next round instead of
        # waiting out a dead ladder from the old address.
        self._poll_backoff.evict(peer_id)
        FLEET_PEERS.set(n)

    def remove_peer(self, peer_id: str) -> None:
        with self._lock:
            self._peers.pop(peer_id, None)
            n = len(self._peers)
        self._poll_backoff.evict(peer_id)
        FLEET_PEERS.set(n)

    def note_peer_gave_up(self, peer_id: str, reason: str,
                          name: str = "") -> None:
        """Hand-off from a data-plane retry ladder that exhausted
        itself (the sync announcer's p2p.announce.reconnect give-up):
        the peer renders as a stale-degraded row carrying the
        give-up reason even if the observatory itself has not failed
        a poll yet — operators see WHY sync stopped reaching it.
        Registers an observability-less row (client None: the poller
        skips it) when the peer was never an obs peer."""
        with self._lock:
            rec = self._peers.get(peer_id)
            if rec is None:
                rec = {
                    "peer_id": peer_id, "name": name or peer_id[:12],
                    "client": None,
                    "ring": channels.channel("fleet.peer.snapshots"),
                    "last_ok": None, "rtt_s": None, "skew_s": None,
                    "error": "", "incidents": [],
                }
                self._peers[peer_id] = rec
            rec["error"] = str(reason)[:200]
            n = len(self._peers)
        FLEET_PEERS.set(n)

    def peer_ids(self) -> List[str]:
        with self._lock:
            return list(self._peers)

    def refresh_p2p_peers(self) -> None:
        """Adopt every paired p2p route as an obs peer (production
        wiring: the same identity->route table the sync originator
        fans out over). No-op without a p2p plane or without the
        tunnel stack's crypto dependency."""
        networked = getattr(getattr(self.node, "p2p", None),
                            "networked", None)
        if networked is None:
            return
        try:
            from .p2p.identity import RemoteIdentity
            from .p2p.obs import P2PObsClient
        except ModuleNotFoundError:  # no cryptography: HTTP/loopback only
            return
        for key, route in networked.known_routes().items():
            peer_id = key.hex()
            with self._lock:
                rec = self._peers.get(peer_id)
                client = rec["client"] if rec else None
            # Register new peers AND refresh a known peer whose route
            # moved (re-pair after a restart on a new addr/port): the
            # poller must follow the route table, not pin the client
            # it first built.
            if client is not None and (
                    getattr(client, "addr", None),
                    getattr(client, "port", None)) == route:
                continue
            self.add_peer(peer_id, P2PObsClient(
                self.node.p2p, route[0], route[1],
                expected=RemoteIdentity(key)))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            with self._lock:
                self._task = tasks.spawn(
                    "fleet-poller", self._loop(), owner=self._owner)

    def stop(self) -> None:
        with self._lock:
            task, self._task = self._task, None
        if task is not None:
            task.cancel()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.poll_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # a bad round must not kill the poller
                tracing.logger.warning("fleet poll round failed: %s", e)

    # -- the poller --------------------------------------------------------

    async def _fetch_health(self, client) -> Any:
        # Chaos seam, INSIDE the fleet.poll budget: wedge parks the
        # fetch until the budget fires and the row goes stale-degraded
        # (disarming must let it recover — pinned by test_chaos).
        f = chaos.hit("fleet.poll", only=("delay", "error", "wedge"))
        if f is not None:
            await chaos.apply_async(f)
        return await client.fetch("obs.health")

    async def _poll_peer(self, peer_id: str) -> None:
        with self._lock:
            rec = self._peers.get(peer_id)
            client = rec["client"] if rec else None
        if client is None:
            return
        t0 = time.time()
        try:
            resp = await with_timeout("fleet.poll",
                                      self._fetch_health(client))
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # ANY transport/protocol failure is "unreachable" — a
            # handshake ProtoError, a torn frame, a JSON decode error
            # — one bad peer must only ever cost its own row, never
            # abort the round's gather (the healthy peers' snapshots
            # still merge and _publish still runs).
            FLEET_POLLS.labels(outcome="unreachable").inc()
            # Declared backoff instead of re-burning a fleet.poll
            # budget on the dead peer every round: its next attempt
            # waits the fleet.peer.poll ladder (the row is stale
            # either way; healed peers are found at cap cadence).
            self._poll_backoff.failure(peer_id)
            with self._lock:
                rec = self._peers.get(peer_id)
                if rec is not None:
                    rec["error"] = f"{type(e).__name__}: {e}"[:200]
            return
        t1 = time.time()
        problems = validate_obs_response("obs.health", resp)
        if problems:
            # Rejected WITHOUT touching the ring: the fleet view keeps
            # serving the last good snapshot (or a stale row) instead
            # of whatever this peer just made up.
            FLEET_POLLS.labels(outcome="malformed").inc()
            with self._lock:
                rec = self._peers.get(peer_id)
                if rec is not None:
                    rec["error"] = f"malformed snapshot: {problems[0]}"
            return
        # Clock skew from the poll's RTT midpoint: the peer sampled
        # its wall clock roughly mid-exchange, so (peer_ts - midpoint)
        # estimates how far ahead its clock runs — what trace assembly
        # subtracts to land both nodes' events on one axis.
        rtt = t1 - t0
        skew = float(resp["ts"]) - (t0 + t1) / 2.0
        FLEET_POLLS.labels(outcome="ok").inc()
        self._poll_backoff.success(peer_id)
        with self._lock:
            rec = self._peers.get(peer_id)
            if rec is None:
                return
            rec["ring"].put_nowait({
                "ts": round(t1, 3), "rtt_s": round(rtt, 6),
                "skew_s": round(skew, 6), "node": resp["node"],
                "health": resp["health"],
            })
            rec["last_ok"] = t1
            rec["rtt_s"] = rtt
            rec["skew_s"] = skew
            rec["error"] = ""
            if resp["node"].get("name"):
                rec["name"] = resp["node"]["name"]
        # Incident headers ride the same round, best-effort AFTER the
        # health poll succeeded (a peer that can answer obs.health has
        # a live transport): a failed or malformed header fetch keeps
        # the last known list — headers are evidence pointers, and a
        # transient fetch failure must not erase them from the view.
        try:
            iresp = await with_timeout("fleet.poll",
                                       client.fetch("obs.incidents"))
        except asyncio.CancelledError:
            raise
        except Exception:
            return
        if validate_obs_response("obs.incidents", iresp):
            return
        with self._lock:
            rec = self._peers.get(peer_id)
            if rec is not None:
                rec["incidents"] = list(iresp["incidents"])

    async def poll_once(self) -> Dict[str, Any]:
        """One poll round: refresh the peer set from the p2p plane,
        pull every peer concurrently, merge, publish."""
        with tracing.span("fleet/poll"):
            self.refresh_p2p_peers()
            with self._lock:
                ids = list(self._peers)
            # Unreachable peers inside their backoff window are
            # skipped this round (their rows render stale regardless);
            # everyone else polls concurrently.
            due = [pid for pid in ids
                   if self._poll_backoff.allowed(pid)]
            if due:
                await asyncio.gather(
                    *(self._poll_peer(pid) for pid in due))
            return self._publish()

    def _publish(self) -> Dict[str, Any]:
        view = self.merge_view()
        stale = sum(1 for row in view["nodes"].values()
                    if not row["local"] and row["stale"])
        with self._lock:
            self._last = view
            self._snapshots.put_nowait(view)
            FLEET_PEERS.set(len(self._peers))
        FLEET_PEERS_STALE.set(stale)
        if self.events is not None:
            self.events.emit({"type": "FleetHealthSnapshot",
                              "ts": view["ts"], "fleet": view})
        return view

    # -- the merger --------------------------------------------------------

    @staticmethod
    def _incident_summary(headers: Any) -> Dict[str, Any]:
        """The per-row incident digest: open (unacked) / total counts
        plus the newest INCIDENT_RECENT headers, from a newest-first
        header list (obs.incidents payload or the local list())."""
        rows = [dict(h) for h in headers
                if isinstance(h, dict)] \
            if isinstance(headers, list) else []
        return {
            "open": sum(1 for h in rows if not h.get("ack")),
            "total": len(rows),
            "recent": rows[:INCIDENT_RECENT],
        }

    def _local_row(self) -> Optional[Dict[str, Any]]:
        if self.health is None:
            return None
        snap = self.health.snapshot()
        ident = dict(self.node_identity)
        if not ident.get("id") and isinstance(snap.get("node"), dict):
            ident = dict(snap["node"])
        obs = getattr(self.node, "incidents", None) \
            or incidents.current()
        return {
            "node": ident, "local": True, "reachable": True,
            "stale": False, "last_seen": snap["ts"], "rtt_s": 0.0,
            "skew_s": 0.0, "error": None,
            "states": dict(snap["states"]),
            "attribution": dict(snap["attribution"]),
            "incidents": self._incident_summary(
                obs.list() if obs is not None else []),
        }

    @staticmethod
    def _stale_row(rec: Dict[str, Any], age: Optional[float],
                   stale_after: float) -> Dict[str, Any]:
        """The degraded row an unreachable/stale peer renders as —
        with last-seen evidence, per the poller's staleness rule."""
        name = rec["name"]
        if age is not None:
            reason = (f"no good obs.health snapshot for {age:.1f}s "
                      f"(stale after {stale_after:g}s)")
        else:
            reason = "peer never answered an obs.health poll"
        if rec["error"]:
            reason += f" — last error: {rec['error']}"
        evidence: Dict[str, Any] = {
            "last_seen": round(rec["last_ok"], 3)
            if rec["last_ok"] else None,
            "age_s": round(age, 3) if age is not None else None,
            "stale_after_s": round(stale_after, 3),
        }
        return {
            "node": {"id": rec["peer_id"], "name": name},
            "local": False, "reachable": False, "stale": True,
            "last_seen": rec["last_ok"], "rtt_s": rec["rtt_s"],
            "skew_s": rec["skew_s"], "error": rec["error"] or None,
            # Last-known headers survive unreachability on purpose: a
            # node that crashed AFTER freezing a bundle is exactly the
            # row whose incidents an operator needs to see.
            "incidents": FleetMonitor._incident_summary(
                rec.get("incidents")),
            "states": {"peer": "degraded"},
            "attribution": {"peer": [{
                "resource": f"fleet.peer.{name}", "subsystem": "peer",
                "severity": 1,
                "score": round(age, 3) if age is not None else 0.0,
                "reason": reason, "owner": "fleet",
                "doc": "fleet.py staleness rule: a peer without a "
                       "good snapshot inside 2x the poll interval "
                       "is degraded, last-seen evidence inline",
                "evidence": evidence,
            }]},
        }

    def merge_view(self) -> Dict[str, Any]:
        """The merged fleet health view: one row per node (local row
        first), states/attribution re-keyed per `<node>/<subsystem>`."""
        wall = time.time()
        stale_after = STALE_INTERVALS * self.interval_s
        nodes: Dict[str, Dict[str, Any]] = {}

        def row_key(name: str, fallback: str) -> str:
            key = name or fallback
            if key in nodes:  # name collision: disambiguate by id
                key = f"{key}#{fallback[:6]}"
            return key

        local = self._local_row()
        if local is not None:
            nodes[row_key(local["node"]["name"], "local")] = local
        with self._lock:
            peers = [(pid, dict(rec), list(rec["ring"]))
                     for pid, rec in self._peers.items()]
        for pid, rec, ring in peers:
            age = (wall - rec["last_ok"]) if rec["last_ok"] else None
            if not ring or age is None or age > stale_after:
                row = self._stale_row(rec, age, stale_after)
            else:
                latest = ring[-1]
                health = latest["health"]
                row = {
                    "node": dict(latest["node"]), "local": False,
                    "reachable": True, "stale": False,
                    "last_seen": rec["last_ok"],
                    "rtt_s": round(rec["rtt_s"], 6)
                    if rec["rtt_s"] is not None else None,
                    "skew_s": round(rec["skew_s"], 6)
                    if rec["skew_s"] is not None else None,
                    "error": None,
                    "states": dict(health["states"]),
                    "attribution": dict(health["attribution"]),
                    "incidents": self._incident_summary(
                        rec.get("incidents")),
                }
            nodes[row_key(row["node"]["name"], pid)] = row

        states: Dict[str, str] = {}
        attribution: Dict[str, List[Dict[str, Any]]] = {}
        for node_name, row in nodes.items():
            for sub, st in row["states"].items():
                states[f"{node_name}/{sub}"] = st
            for sub, entries in row["attribution"].items():
                attribution[f"{node_name}/{sub}"] = entries
        return {
            "ts": round(wall, 3),
            "interval_s": self.interval_s,
            "stale_after_s": stale_after,
            "node": dict(self.node_identity),
            "nodes": nodes,
            "states": states,
            "attribution": attribution,
        }

    async def snapshot(self, max_age_s: Optional[float] = None
                       ) -> Dict[str, Any]:
        """The latest merged view; polls fresh when none exists or the
        last one is older than `max_age_s` (default 2x interval) —
        covers loop-less embedders exactly like HealthMonitor."""
        limit = STALE_INTERVALS * self.interval_s \
            if max_age_s is None else max_age_s
        with self._lock:
            last = self._last
        if last is not None and (time.time() - last["ts"]) <= limit:
            return last
        return await self.poll_once()

    def last_view(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._last

    # -- fleet metrics -----------------------------------------------------

    async def metrics(self) -> Dict[str, Any]:
        """Per-node cumulative metrics snapshots: the local registry
        plus every reachable peer's obs.metrics, fetched on demand
        (cumulative families are big; nothing here is cached)."""
        rows: Dict[str, Dict[str, Any]] = {}
        local_name = self.node_identity["name"] or "local"
        rows[local_name] = {
            "node": dict(self.node_identity), "local": True,
            "error": None,
            # Off-loop like every other obs snapshot build: the walk
            # visits every registered family.
            "metrics": await asyncio.to_thread(telemetry.snapshot),
        }
        with self._lock:
            # client None = a give-up hand-off row with no obs
            # transport (note_peer_gave_up): it renders in the health
            # view but cannot be fetched from — same skip as the
            # poller's.
            peers = [(pid, rec["name"], rec["client"])
                     for pid, rec in self._peers.items()
                     if rec["client"] is not None]

        async def one(pid, name, client):
            try:
                resp = await with_timeout("fleet.poll",
                                          client.fetch("obs.metrics"))
            except asyncio.CancelledError:
                raise
            except Exception as e:
                return {"node": {"id": pid, "name": name},
                        "local": False,
                        "error": f"{type(e).__name__}: {e}"[:200],
                        "metrics": None}
            problems = validate_obs_response("obs.metrics", resp)
            if problems:
                return {"node": {"id": pid, "name": name},
                        "local": False, "error": problems[0],
                        "metrics": None}
            return {"node": dict(resp["node"]), "local": False,
                    "error": None, "metrics": resp["metrics"]}

        fetched = await asyncio.gather(
            *(one(pid, name, client) for pid, name, client in peers))
        for (pid, name, _client), row in zip(peers, fetched):
            key = name if name not in rows else f"{name}#{pid[:6]}"
            rows[key] = row
        return {"ts": round(time.time(), 3),
                "node": dict(self.node_identity), "nodes": rows}

    # -- distributed trace assembly ----------------------------------------

    async def assemble_trace(self, trace: str) -> Dict[str, Any]:
        """Fetch every paired peer's spans+timeline for `trace` and
        merge them with the local slice into one Chrome-trace doc with
        per-node pid lanes and skew-aligned clocks (the skew each
        peer's poll round estimated; a peer polled never gets 0)."""
        trace = str(trace)
        with tracing.span("fleet/trace", trace=trace):
            local_name = self.node_identity["name"] or "local"
            spans = tracing.recent_spans(
                limit=tracing.span_ring_capacity(), trace_id=trace)
            timeline = [ev for ev in flight.RECORDER.snapshot()
                        if ev.get("trace") == trace]
            rows: List[Dict[str, Any]] = [{
                "node": local_name, "spans": spans,
                "timeline": timeline, "skew_s": 0.0,
            }]
            with self._lock:
                # Same client-None skip as the poller: a give-up
                # hand-off row has no transport to fetch a trace
                # slice from (and must not count an "unreachable"
                # outcome for a peer that was never an obs peer).
                peers = [(pid, rec["name"], rec["client"],
                          rec["skew_s"])
                         for pid, rec in self._peers.items()
                         if rec["client"] is not None]

            async def one(name, client, skew):
                try:
                    resp = await with_timeout(
                        "fleet.trace.fetch",
                        client.fetch("obs.trace", trace=trace))
                except asyncio.CancelledError:
                    raise
                except Exception:
                    FLEET_POLLS.labels(outcome="unreachable").inc()
                    return None  # assembled from who answered
                if validate_obs_response("obs.trace", resp):
                    FLEET_POLLS.labels(outcome="malformed").inc()
                    return None
                return {
                    "node": resp["node"].get("name") or name,
                    "spans": resp["spans"],
                    "timeline": resp["timeline"],
                    "skew_s": skew or 0.0,
                }
            fetched = await asyncio.gather(
                *(one(name, client, skew)
                  for _pid, name, client, skew in peers))
            rows.extend(r for r in fetched if r is not None)
            return flight.fleet_chrome_trace(
                rows, trace=trace,
                fleet_name=f"fleet via {local_name}")


# -- fleet snapshot schema gate ----------------------------------------------

def validate_fleet_snapshot(doc: Any) -> List[str]:
    """Schema gate for a merged fleet view (the fleet.health payload
    and the `sd_top --fleet --json` artifact body). Returns problem
    strings (empty = valid) — the same contract shape as
    health.validate_health_snapshot, extended per-node."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["fleet snapshot must be a dict"]
    if not isinstance(doc.get("ts"), (int, float)):
        problems.append("ts must be a number")
    if not isinstance(doc.get("node"), dict):
        problems.append("node (the assembling node) must be a dict")
    nodes = doc.get("nodes")
    if not isinstance(nodes, dict) or not nodes:
        return problems + ["nodes must be a non-empty dict"]
    for name, row in nodes.items():
        where = f"nodes[{name}]"
        if not isinstance(row, dict):
            problems.append(f"{where}: not an object")
            continue
        ident = row.get("node")
        if not isinstance(ident, dict) or \
                not isinstance(ident.get("id"), str) or \
                not isinstance(ident.get("name"), str):
            problems.append(f"{where}: node must be "
                            "{id: str, name: str}")
        for key in ("local", "reachable", "stale"):
            if not isinstance(row.get(key), bool):
                problems.append(f"{where}: {key} must be a bool")
        inc = row.get("incidents")
        if inc is not None:  # optional: pre-observatory rows omit it
            if not isinstance(inc, dict) \
                    or not isinstance(inc.get("open"), int) \
                    or not isinstance(inc.get("total"), int) \
                    or not isinstance(inc.get("recent"), list):
                problems.append(
                    f"{where}: incidents must be "
                    "{open: int, total: int, recent: list}")
        states = row.get("states")
        if not isinstance(states, dict) or not states:
            problems.append(f"{where}: states must be a non-empty "
                            "dict")
            continue
        for sub, st in states.items():
            if st not in STATES:
                problems.append(
                    f"{where}.states[{sub}]: unknown state {st!r}")
        if row.get("reachable") is False and \
                states.get("peer") != "degraded":
            problems.append(
                f"{where}: unreachable/stale peer must carry "
                "peer=degraded")
        attribution = row.get("attribution")
        if not isinstance(attribution, dict):
            problems.append(f"{where}: attribution must be a dict")
            continue
        for sub, entries in attribution.items():
            ew = f"{where}.attribution[{sub}]"
            if sub not in states:
                problems.append(f"{ew}: subsystem has no state")
                continue
            if not isinstance(entries, list) or not entries:
                problems.append(f"{ew}: must be a non-empty list")
                continue
            worst = 0
            for i, e in enumerate(entries):
                if not isinstance(e, dict):
                    problems.append(f"{ew}[{i}]: not an object")
                    continue
                for k in ("resource", "reason", "owner", "doc"):
                    if not isinstance(e.get(k), str):
                        problems.append(
                            f"{ew}[{i}]: {k} must be a str")
                if e.get("subsystem") != sub:
                    problems.append(f"{ew}[{i}]: subsystem mismatch")
                sev = e.get("severity")
                if sev not in (1, 2):
                    problems.append(
                        f"{ew}[{i}]: severity must be 1 or 2")
                else:
                    worst = max(worst, sev)
                if not isinstance(e.get("evidence"), dict):
                    problems.append(
                        f"{ew}[{i}]: evidence must be a dict")
            if worst and states.get(sub) != STATES[worst]:
                problems.append(
                    f"{ew}: state {states.get(sub)!r} inconsistent "
                    f"with worst attributed severity {worst}")
    flat = doc.get("states")
    if not isinstance(flat, dict):
        problems.append("states must be a dict keyed node/subsystem")
    else:
        want = {f"{n}/{sub}": st
                for n, row in nodes.items()
                if isinstance(row, dict)
                and isinstance(row.get("states"), dict)
                for sub, st in row["states"].items()}
        if flat != want:
            problems.append(
                "flattened states drifted from the per-node rows")
    flat_attr = doc.get("attribution")
    if not isinstance(flat_attr, dict):
        problems.append(
            "attribution must be a dict keyed node/subsystem")
    else:
        want_attr = {f"{n}/{sub}": entries
                     for n, row in nodes.items()
                     if isinstance(row, dict)
                     and isinstance(row.get("attribution"), dict)
                     for sub, entries in row["attribution"].items()}
        if flat_attr != want_attr:
            problems.append(
                "flattened attribution drifted from the per-node rows")
    return problems
