"""Device-side duplicate analytics: exact-dup grouping and Hamming all-pairs.

The reference detects duplicates only by exact CAS-ID equality
(/root/reference/core/src/object/file_identifier/mod.rs:167-225); there is
no perceptual near-dup search anywhere in it. This module supplies both:

- `exact_dup_groups`: batch grouping of equal digests (the device analog
  of the identifier's cas_id matching, used by the dedup pass over 100k+
  libraries).
- Hamming all-pairs over bit-digests (pHash near-dup search — net-new
  capability per BASELINE.json): XOR + popcount, tiled so the N×N
  comparison streams through fixed-size blocks, with a shard_map layout
  that puts row-blocks on one mesh axis and column-blocks on the other so
  each device computes an [N/r, N/c] tile with no replication of the
  full matrix.

Digests are [N, W] uint32 grids (W=2 for 64-bit pHash / CAS prefixes,
W=8 for full 256-bit BLAKE3).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


@jax.jit
def hamming_tile(x, y):
    """[n, W] × [m, W] uint32 → [n, m] int32 Hamming distances."""
    xors = x[:, None, :] ^ y[None, :, :]
    return jnp.sum(jax.lax.population_count(xors), axis=-1).astype(jnp.int32)


def make_sharded_hamming(mesh):
    """All-pairs Hamming over a 2-D (rows, cols) mesh.

    The same digest array is passed twice — once sharded by rows, once by
    cols — so each device holds two 1/r- and 1/c-sized slices and emits
    its tile of the distance matrix; no device ever sees the full N×N.
    """

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("rows", None), P("cols", None)),
        out_specs=P("rows", "cols"),
    )
    def sharded(x_rows, y_cols):
        xors = x_rows[:, None, :] ^ y_cols[None, :, :]
        return jnp.sum(
            jax.lax.population_count(xors), axis=-1
        ).astype(jnp.int32)

    return sharded


@functools.partial(jax.jit, static_argnames=("threshold",))
def _near_mask_tile(x, y, threshold: int):
    return hamming_tile(x, y) <= threshold


def near_dup_pairs(
    digests: np.ndarray,
    threshold: int,
    tile: int = 4096,
) -> List[Tuple[int, int]]:
    """All (i < j) index pairs with Hamming distance ≤ threshold. Exact.

    One-tile batches run as a single masked call; anything larger
    delegates to the two-pass tiled sweep (`near_dup_pairs_device`),
    which keeps the whole tile grid inside one jit — per-tile dispatch
    through the tunneled bench TPU costs ~2 s of RPC latency per tile,
    which at 100k digests (325 tiles) measured ~700 s of pure overhead.
    """
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    N = digests.shape[0]
    if N <= tile:
        mask = np.triu(np.asarray(
            _near_mask_tile(digests, digests, threshold)), k=1)
        ii, jj = np.nonzero(mask)
        return list(zip(ii.tolist(), jj.tolist()))
    return near_dup_pairs_device(digests, threshold, tile=tile)


def exact_dup_groups(ids: List[str]) -> Dict[str, List[int]]:
    """Group indexes by identical id; only groups with >1 member.

    The host-side exact pass (id strings are 16-hex CAS IDs). For large
    batches the heavy lifting — the digests themselves — already happened
    on device; grouping N short strings is O(N) dict work.
    """
    groups: Dict[str, List[int]] = {}
    for i, cid in enumerate(ids):
        groups.setdefault(cid, []).append(i)
    return {k: v for k, v in groups.items() if len(v) > 1}


def phash_bands(digests: np.ndarray, n_bands: int = 4) -> Dict[tuple, List[int]]:
    """LSH banding: split each digest into bands; near-dups (small Hamming
    distance) collide in at least one band with high probability.

    Fully vectorized (VERDICT r1 item 6): per band, the byte-slice is
    zero-extended into a uint64 key, grouped with one argsort + boundary
    scan — no per-row Python. Returns {(band, key): [indexes]} for
    buckets with > 1 member.
    """
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    N, W = digests.shape
    bits = digests.view(np.uint8).reshape(N, W * 4)
    per = max(1, (W * 4) // n_bands)
    assert per <= 8, "band wider than a uint64 key; raise n_bands"
    buckets: Dict[tuple, List[int]] = {}
    for b in range(n_bands):
        band = bits[:, b * per : (b + 1) * per]
        keys = np.zeros((N, 8), dtype=np.uint8)
        keys[:, : band.shape[1]] = band
        keys = keys.view("<u8").ravel()
        order = np.argsort(keys, kind="stable")
        sk = keys[order]
        # Boundaries of equal-key runs; keep runs of length > 1.
        starts = np.flatnonzero(np.r_[True, sk[1:] != sk[:-1]])
        ends = np.r_[starts[1:], N]
        for s, e in zip(starts, ends):
            if e - s > 1:
                buckets[(b, int(sk[s]))] = order[s:e].tolist()
    return buckets


def lsh_candidate_pairs(digests: np.ndarray, n_bands: int = 4,
                        max_bucket: int = 4096) -> np.ndarray:
    """Unique candidate (i < j) pairs from LSH banding, as an [P, 2] array.

    Buckets larger than `max_bucket` (degenerate keys — e.g. thousands of
    identical digests) are truncated to their first `max_bucket` members
    to bound P at O(sum k²); truncated members still pair with the kept
    ones, and identical digests are exact dups the CAS pass already
    catches.
    """
    out = []
    for (_, _), idxs in phash_bands(digests, n_bands).items():
        k = min(len(idxs), max_bucket)
        a = np.asarray(idxs[:k], dtype=np.int64)
        ii, jj = np.triu_indices(k, k=1)
        lo = np.minimum(a[ii], a[jj])
        hi = np.maximum(a[ii], a[jj])
        out.append(np.stack([lo, hi], axis=1))
    if not out:
        return np.zeros((0, 2), dtype=np.int64)
    pairs = np.concatenate(out, axis=0)
    # Dedup across bands: pack to one uint64 key per pair.
    packed = (pairs[:, 0].astype(np.uint64) << np.uint64(32)) \
        | pairs[:, 1].astype(np.uint64)
    packed = np.unique(packed)
    return np.stack([(packed >> np.uint64(32)).astype(np.int64),
                     (packed & np.uint64(0xFFFFFFFF)).astype(np.int64)],
                    axis=1)


def pair_distances(digests: np.ndarray, pairs: np.ndarray,
                   chunk: int = 1 << 20) -> np.ndarray:
    """Hamming distance for each (i, j) row of `pairs` — vectorized
    XOR + popcount in bounded chunks, [P] int32."""
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    out = np.zeros((len(pairs),), dtype=np.int32)
    for s in range(0, len(pairs), chunk):
        p = pairs[s : s + chunk]
        x = digests[p[:, 0]] ^ digests[p[:, 1]]
        out[s : s + chunk] = np.bitwise_count(x).sum(axis=1)
    return out


def near_dup_pairs_lsh(digests: np.ndarray, threshold: int,
                       n_bands: int = 4) -> List[Tuple[int, int]]:
    """CPU fallback for beyond-all-pairs scale: LSH candidates + one
    vectorized distance pass. Probabilistic recall — a pair at distance
    d ≤ threshold is found iff some 16-bit band matches exactly, which
    for uniformly-spread d=10 flips is only ~25% per pair (measured:
    0.66 planted recall at 1M with the 0..10 flip mixture —
    tools/near_dup_scale.py records it per run). The device path
    (`near_dup_pairs_device`) is EXACT at the same scale and is what the
    near-dup job uses whenever a TPU is present; this survives only as
    the no-device fallback."""
    pairs = lsh_candidate_pairs(digests, n_bands)
    if not len(pairs):
        return []
    d = pair_distances(digests, pairs)
    keep = pairs[d <= threshold]
    return [(int(i), int(j)) for i, j in keep]


# ---------------------------------------------------------------------------
# Exact all-pairs at 1M: two single-dispatch device passes on the MXU.
#
# Two ideas make this exact search feasible where the naive loop dies:
#
# 1. One dispatch per pass, not per tile. Per-tile jit calls pay a
#    host→device round trip each — 325 calls for 100k digests measured
#    ~700 s of pure RPC latency through the tunneled bench TPU. Both
#    passes here sweep their whole tile grid INSIDE one jit.
#
# 2. Hamming distance as a matmul. With each bit mapped to ±1,
#    dot(s_x, s_y) = BITS - 2·hamming(x, y), so the [T, T] distance
#    tile is one [T, BITS] @ [BITS, T] product — MXU work at int-exact
#    bf16/f32, ~100× the VPU XOR+popcount formulation. The sum of 64
#    ±1 terms is exact in f32, so thresholding is still exact.
#
#   pass 1: full tile grid → per-tile count of (i < j) pairs ≤
#           threshold ([NT, NT] int32, a few KB out).
#   pass 2: only the flagged tiles (host-chosen list, static shape) →
#           per-tile pair coordinates, padded to the max count.
# The N×N distance matrix never exists (O(tile²) working set).


def _bit_planes(digests) -> jnp.ndarray:
    """[N, W] uint32 → [N, W*32] bf16 of ±1 (bit b of word w at column
    w*32+b)."""
    n, w = digests.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (digests[:, :, None] >> shifts[None, None, :]) & jnp.uint32(1)
    return (bits.astype(jnp.bfloat16) * 2 - 1).reshape(n, w * 32)


def _pair_mask(dots, i, j, T, bits: int, threshold: int, n: int):
    """dots [T, T] f32 → boolean mask of in-range (global i < j) pairs."""
    gi = i * T + jnp.arange(T, dtype=jnp.int32)
    gj = j * T + jnp.arange(T, dtype=jnp.int32)
    return ((dots >= bits - 2 * threshold)
            & (gi[:, None] < gj[None, :])
            & (gi[:, None] < n) & (gj[None, :] < n))


@functools.partial(jax.jit, static_argnames=("block",))
def _tile_counts_block(planes, row0, threshold, n, block: int):
    """Pair counts for `block` consecutive row-tiles starting at row0.

    planes: [NT, T, BITS] ±1 bf16 → [block, NT] int32. `threshold`/`n`/
    `row0` are traced scalars so one compilation serves every dataset of
    the same tile grid (a fresh compile per library size measured
    ~100 s through the tunnel — the matmul sweep itself is ~0.1 s warm
    for 100k digests). The sweep is dispatched in row blocks because
    the tunneled TPU worker kills single programs that run for minutes
    (a full 1M sweep is ~60k matmuls — one program crashed the worker);
    rows past NT clamp to the last tile and are discarded by the host.
    """
    NT, T, BITS = planes.shape

    def row(k):
        i = jnp.minimum(row0 + k, NT - 1)
        x = jax.lax.dynamic_index_in_dim(planes, i, keepdims=False)

        def col(j):
            dots = jax.lax.dot_general(
                x, planes[j], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            return jnp.sum(_pair_mask(dots, i, j, T, BITS, threshold, n),
                           dtype=jnp.int32)

        return jax.lax.map(col, jnp.arange(NT))

    return jax.lax.map(row, jnp.arange(block))


@functools.partial(jax.jit, static_argnames=("cap",))
def _tile_extract(planes, flagged, threshold, n, cap: int):
    """flagged: [F, 2] int32 tile coords → ([F, cap, 2] global pair
    indexes, [F] counts); unused slots are (-1, -1). Only `cap` (the
    nonzero-extraction size) must be static — callers round it up to a
    power of two so compilations stay bucketed."""
    NT, T, BITS = planes.shape

    def one(ij):
        i, j = ij[0], ij[1]
        x = jax.lax.dynamic_index_in_dim(planes, i, keepdims=False)
        y = jax.lax.dynamic_index_in_dim(planes, j, keepdims=False)
        dots = jax.lax.dot_general(
            x, y, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ok = _pair_mask(dots, i, j, T, BITS, threshold, n)
        ii, jj = jnp.nonzero(ok.reshape(T, T), size=cap, fill_value=-1)
        valid = ii >= 0
        pi = jnp.where(valid, i * T + ii, -1)
        pj = jnp.where(valid, j * T + jj, -1)
        return jnp.stack([pi, pj], axis=1), jnp.sum(ok, dtype=jnp.int32)

    return jax.lax.map(one, flagged)


# Row-tiles per counts dispatch and flagged tiles per extract dispatch:
# sized so one dispatch stays well under the tunnel worker's runtime
# tolerance (~a few thousand [T,T] matmul tiles).
COUNT_ROWS_PER_DISPATCH = 16
EXTRACT_TILES_PER_DISPATCH = 256
# Extraction output budget per dispatch (int32 pairs) and the per-tile
# truncation bound. One tile of m identical digests holds ~m²/2 pairs
# (m=4096 → 8M) — a degenerate cluster the CAS exact-dup pass already
# covers; capping mirrors lsh_candidate_pairs' max_bucket truncation.
EXTRACT_BUDGET_ELEMS = 32 << 20
MAX_PAIRS_PER_TILE = 1 << 20


def _pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


def near_dup_pairs_device(digests: np.ndarray, threshold: int,
                          tile: int = 4096) -> List[Tuple[int, int]]:
    """Exact all-pairs (i < j, distance ≤ threshold) at large N on the
    device — a bounded number of jit dispatches, each sweeping thousands
    of tiles (see block comment above). Returns the same pairs as
    `near_dup_pairs`, validated at 1M by tools/near_dup_scale.py.

    Exactness caveat: a single tile holding more than MAX_PAIRS_PER_TILE
    (1M) qualifying pairs — a ≥ ~1450-wide cluster of near-identical
    digests — has its extraction truncated to the cap; such clusters are
    degenerate for near-dup reporting (the UI shows pairs) and their
    exact-equality core is already collapsed by the CAS dedup pass."""
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    N, W = digests.shape
    if N < 2:
        return []
    NT = -(-N // tile)
    padded = np.zeros((NT * tile, W), dtype=np.uint32)
    padded[:N] = digests
    planes = _bit_planes(jnp.asarray(padded)).reshape(NT, tile, W * 32)

    thr = jnp.int32(threshold)
    nn = jnp.int32(N)
    blocks = []
    for r0 in range(0, NT, COUNT_ROWS_PER_DISPATCH):
        blk = np.asarray(_tile_counts_block(
            planes, jnp.int32(r0), thr, nn, COUNT_ROWS_PER_DISPATCH))
        blocks.append(blk[: NT - r0])
    counts = np.concatenate(blocks, axis=0)

    flagged = np.argwhere(counts > 0).astype(np.int32)
    if len(flagged) == 0:
        return []
    # Extract densest tiles first with a per-chunk cap: a single global
    # cap sized to the worst tile would allocate [chunk, cap, 2] for
    # every dispatch (a 4096-wide identical-digest cluster → 17 GB).
    tile_counts = counts[flagged[:, 0], flagged[:, 1]]
    order = np.argsort(-tile_counts)
    flagged = flagged[order]
    tile_counts = tile_counts[order]
    out = []
    f0 = 0
    while f0 < len(flagged):
        cap = _pow2(min(int(tile_counts[f0]), MAX_PAIRS_PER_TILE))
        width = min(EXTRACT_TILES_PER_DISPATCH,
                    max(1, EXTRACT_BUDGET_ELEMS // cap),
                    len(flagged) - f0)
        fpad = _pow2(width)  # pad tile list: (F, cap) compile buckets
        chunk = np.zeros((fpad, 2), dtype=np.int32)
        chunk[:width] = flagged[f0 : f0 + width]
        pairs_dev, _ = _tile_extract(planes, jnp.asarray(chunk),
                                     thr, nn, cap)
        out.append(np.asarray(pairs_dev[:width]).reshape(-1, 2))
        f0 += width
    pairs = np.concatenate(out, axis=0)
    pairs = pairs[pairs[:, 0] >= 0]
    return [(int(i), int(j)) for i, j in pairs]
