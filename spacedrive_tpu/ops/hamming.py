"""Device-side duplicate analytics: exact-dup grouping and Hamming all-pairs.

The reference detects duplicates only by exact CAS-ID equality
(/root/reference/core/src/object/file_identifier/mod.rs:167-225); there is
no perceptual near-dup search anywhere in it. This module supplies both:

- `exact_dup_groups`: batch grouping of equal digests (the device analog
  of the identifier's cas_id matching, used by the dedup pass over 100k+
  libraries).
- Hamming all-pairs over bit-digests (pHash near-dup search — net-new
  capability per BASELINE.json): XOR + popcount, tiled so the N×N
  comparison streams through fixed-size blocks, with a shard_map layout
  that puts row-blocks on one mesh axis and column-blocks on the other so
  each device computes an [N/r, N/c] tile with no replication of the
  full matrix.

Digests are [N, W] uint32 grids (W=2 for 64-bit pHash / CAS prefixes,
W=8 for full 256-bit BLAKE3).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from . import jit_registry


@jit_registry.tracked("hamming.tile")
@jax.jit
def hamming_tile(x, y):
    """[n, W] × [m, W] uint32 → [n, m] int32 Hamming distances."""
    xors = x[:, None, :] ^ y[None, :, :]
    return jnp.sum(jax.lax.population_count(xors), axis=-1).astype(jnp.int32)


def make_sharded_hamming(mesh):
    """All-pairs Hamming over a 2-D (rows, cols) mesh.

    The same digest array is passed twice — once sharded by rows, once by
    cols — so each device holds two 1/r- and 1/c-sized slices and emits
    its tile of the distance matrix; no device ever sees the full N×N.
    """

    @jit_registry.tracked("hamming.sharded")
    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("rows", None), P("cols", None)),
        out_specs=P("rows", "cols"),
    )
    def sharded(x_rows, y_cols):
        xors = x_rows[:, None, :] ^ y_cols[None, :, :]
        return jnp.sum(
            jax.lax.population_count(xors), axis=-1
        ).astype(jnp.int32)

    return sharded


@jit_registry.tracked("hamming.near_mask")
@functools.partial(jax.jit, static_argnames=("threshold",))
def _near_mask_tile(x, y, threshold: int):
    return hamming_tile(x, y) <= threshold


def near_dup_pairs(
    digests: np.ndarray,
    threshold: int,
    tile: int = 4096,
    stats: Optional[Dict[str, int]] = None,
) -> List[Tuple[int, int]]:
    """All (i < j) index pairs with Hamming distance ≤ threshold.

    Exact up to the MAX_TOTAL_PAIRS output budget: degenerate
    near-identical clusters past ~4M qualifying pairs are truncated by
    the multi-tile sweep, and `stats["truncated_pairs"]` (when a dict is
    passed) records how many were dropped so job reports can surface it.

    One-tile batches run as a single masked call; anything larger
    delegates to the two-pass tiled sweep (`near_dup_pairs_device`),
    which keeps the whole tile grid inside one jit — per-tile dispatch
    through the tunneled bench TPU costs ~2 s of RPC latency per tile,
    which at 100k digests (325 tiles) measured ~700 s of pure overhead.
    """
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    N = digests.shape[0]
    if N <= tile:
        with jit_registry.device_scope("hamming.pairs"):
            dev_mask = _near_mask_tile(digests, digests, threshold)
            with jit_registry.io("hamming.pairs"):
                mask = np.triu(np.asarray(dev_mask), k=1)
        ii, jj = np.nonzero(mask)
        return list(zip(ii.tolist(), jj.tolist()))
    return near_dup_pairs_device(digests, threshold, tile=tile, stats=stats)


def exact_dup_groups(ids: List[str]) -> Dict[str, List[int]]:
    """Group indexes by identical id; only groups with >1 member.

    The host-side exact pass (id strings are 16-hex CAS IDs). For large
    batches the heavy lifting — the digests themselves — already happened
    on device; grouping N short strings is O(N) dict work.
    """
    groups: Dict[str, List[int]] = {}
    for i, cid in enumerate(ids):
        groups.setdefault(cid, []).append(i)
    return {k: v for k, v in groups.items() if len(v) > 1}


def phash_bands(digests: np.ndarray, n_bands: int = 4) -> Dict[tuple, List[int]]:
    """LSH banding: split each digest into bands; near-dups (small Hamming
    distance) collide in at least one band with high probability.

    Fully vectorized (VERDICT r1 item 6): per band, the byte-slice is
    zero-extended into a uint64 key, grouped with one argsort + boundary
    scan — no per-row Python. Returns {(band, key): [indexes]} for
    buckets with > 1 member.
    """
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    N, W = digests.shape
    bits = digests.view(np.uint8).reshape(N, W * 4)
    per = max(1, (W * 4) // n_bands)
    assert per <= 8, "band wider than a uint64 key; raise n_bands"
    buckets: Dict[tuple, List[int]] = {}
    for b in range(n_bands):
        band = bits[:, b * per : (b + 1) * per]
        keys = np.zeros((N, 8), dtype=np.uint8)
        keys[:, : band.shape[1]] = band
        keys = keys.view("<u8").ravel()
        order = np.argsort(keys, kind="stable")
        sk = keys[order]
        # Boundaries of equal-key runs; keep runs of length > 1.
        starts = np.flatnonzero(np.r_[True, sk[1:] != sk[:-1]])
        ends = np.r_[starts[1:], N]
        for s, e in zip(starts, ends):
            if e - s > 1:
                buckets[(b, int(sk[s]))] = order[s:e].tolist()
    return buckets


def lsh_candidate_pairs(digests: np.ndarray, n_bands: int = 4,
                        max_bucket: int = 4096) -> np.ndarray:
    """Unique candidate (i < j) pairs from LSH banding, as an [P, 2] array.

    Buckets larger than `max_bucket` (degenerate keys — e.g. thousands of
    identical digests) are truncated to their first `max_bucket` members
    to bound P at O(sum k²); truncated members still pair with the kept
    ones, and identical digests are exact dups the CAS pass already
    catches.
    """
    out = []
    for (_, _), idxs in phash_bands(digests, n_bands).items():
        k = min(len(idxs), max_bucket)
        a = np.asarray(idxs[:k], dtype=np.int64)
        ii, jj = np.triu_indices(k, k=1)
        lo = np.minimum(a[ii], a[jj])
        hi = np.maximum(a[ii], a[jj])
        out.append(np.stack([lo, hi], axis=1))
    if not out:
        return np.zeros((0, 2), dtype=np.int64)
    pairs = np.concatenate(out, axis=0)
    # Dedup across bands: pack to one uint64 key per pair.
    packed = (pairs[:, 0].astype(np.uint64) << np.uint64(32)) \
        | pairs[:, 1].astype(np.uint64)
    packed = np.unique(packed)
    return np.stack([(packed >> np.uint64(32)).astype(np.int64),
                     (packed & np.uint64(0xFFFFFFFF)).astype(np.int64)],
                    axis=1)


def pair_distances(digests: np.ndarray, pairs: np.ndarray,
                   chunk: int = 1 << 20) -> np.ndarray:
    """Hamming distance for each (i, j) row of `pairs` — vectorized
    XOR + popcount in bounded chunks, [P] int32."""
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    out = np.zeros((len(pairs),), dtype=np.int32)
    for s in range(0, len(pairs), chunk):
        p = pairs[s : s + chunk]
        x = digests[p[:, 0]] ^ digests[p[:, 1]]
        out[s : s + chunk] = np.bitwise_count(x).sum(axis=1)
    return out


def near_dup_pairs_lsh(digests: np.ndarray, threshold: int,
                       n_bands: int = 4) -> List[Tuple[int, int]]:
    """CPU fallback for beyond-all-pairs scale: LSH candidates + one
    vectorized distance pass. Probabilistic recall — a pair at distance
    d ≤ threshold is found iff some 16-bit band matches exactly, which
    for uniformly-spread d=10 flips is only ~25% per pair (measured:
    0.43 recall vs the exact device pass at 1M with a 0..10 flip
    mixture — tools/near_dup_scale.py records it per run). The device path
    (`near_dup_pairs_device`) is EXACT at the same scale and is what the
    near-dup job uses whenever a TPU is present; this survives only as
    the no-device fallback."""
    pairs = lsh_candidate_pairs(digests, n_bands)
    if not len(pairs):
        return []
    d = pair_distances(digests, pairs)
    keep = pairs[d <= threshold]
    return [(int(i), int(j)) for i, j in keep]


# ---------------------------------------------------------------------------
# Exact all-pairs at 1M: two single-dispatch device passes on the MXU.
#
# Two ideas make this exact search feasible where the naive loop dies:
#
# 1. One dispatch per pass, not per tile. Per-tile jit calls pay a
#    host→device round trip each — 325 calls for 100k digests measured
#    ~700 s of pure RPC latency through the tunneled bench TPU. Both
#    passes here sweep their whole tile grid INSIDE one jit.
#
# 2. Hamming distance as a matmul. With each bit mapped to ±1,
#    dot(s_x, s_y) = BITS - 2·hamming(x, y), so the [T, T] distance
#    tile is one [T, BITS] @ [BITS, T] product — MXU work at int-exact
#    bf16/f32, ~100× the VPU XOR+popcount formulation. The sum of 64
#    ±1 terms is exact in f32, so thresholding is still exact.
#
#   pass 1: full tile grid → per-tile count of (i < j) pairs ≤
#           threshold ([NT, NT] int32, a few KB out).
#   pass 2: only the flagged tiles (host-chosen list, static shape) →
#           per-tile pair coordinates, padded to the max count.
# The N×N distance matrix never exists (O(tile²) working set).


def _bit_planes(digests) -> jnp.ndarray:
    """[N, W] uint32 → [N, W*32] bf16 of ±1 (bit b of word w at column
    w*32+b)."""
    n, w = digests.shape
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (digests[:, :, None] >> shifts[None, None, :]) & jnp.uint32(1)
    return (bits.astype(jnp.bfloat16) * 2 - 1).reshape(n, w * 32)


def _origin_pair_mask(dots, oi, oj, size, bits, threshold, n):
    """dots [size, size] f32 → in-range (global i < j) mask for a block
    whose rows start at global index oi and columns at oj."""
    gi = oi + jnp.arange(size, dtype=jnp.int32)
    gj = oj + jnp.arange(size, dtype=jnp.int32)
    return ((dots >= bits - 2 * threshold)
            & (gi[:, None] < gj[None, :])
            & (gi[:, None] < n) & (gj[None, :] < n))


def _pair_mask(dots, i, j, T, bits, threshold, n):
    """dots [T, T] f32 → boolean mask of in-range (global i < j) pairs
    for whole-tile coords (the oi=i·T, oj=j·T case of the origin form)."""
    return _origin_pair_mask(dots, i * T, j * T, T, bits, threshold, n)


@jit_registry.tracked("hamming.tile_counts")
@functools.partial(jax.jit, static_argnames=("block",))
def _tile_counts_block(planes, row0, threshold, n, block: int):
    """Pair counts for `block` consecutive row-tiles starting at row0.

    planes: [NT, T, BITS] ±1 bf16 → [block, NT] int32. `threshold`/`n`/
    `row0` are traced scalars so one compilation serves every dataset of
    the same tile grid (a fresh compile per library size measured
    ~100 s through the tunnel — the matmul sweep itself is ~0.1 s warm
    for 100k digests). The sweep is dispatched in row blocks because
    the tunneled TPU worker kills single programs that run for minutes
    (a full 1M sweep is ~60k matmuls — one program crashed the worker);
    rows past NT clamp to the last tile and are discarded by the host.
    """
    NT, T, BITS = planes.shape

    def row(k):
        i = jnp.minimum(row0 + k, NT - 1)
        x = jax.lax.dynamic_index_in_dim(planes, i, keepdims=False)

        def col(j):
            dots = jax.lax.dot_general(
                x, planes[j], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            return jnp.sum(_pair_mask(dots, i, j, T, BITS, threshold, n),
                           dtype=jnp.int32)

        return jax.lax.map(col, jnp.arange(NT, dtype=jnp.int32))

    return jax.lax.map(row, jnp.arange(block, dtype=jnp.int32))


def _refine_body(flat, coords, threshold, n, size: int, sub: int):
    """Core of the refinement step, shared by the single-device jit and
    the shard_map multi-device layout."""
    NP, BITS = flat.shape

    def one(rc):
        oi = rc[0] * size
        oj = rc[1] * size
        x = jax.lax.dynamic_slice_in_dim(flat, oi, size)
        y = jax.lax.dynamic_slice_in_dim(flat, oj, size)
        dots = jax.lax.dot_general(
            x, y, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ok = _origin_pair_mask(dots, oi, oj, size, BITS, threshold, n)
        k = size // sub
        return jnp.sum(ok.reshape(sub, k, sub, k), axis=(1, 3),
                       dtype=jnp.int32)

    return jax.lax.map(one, coords)


@jit_registry.tracked("hamming.refine")
@functools.partial(jax.jit, static_argnames=("size", "sub"))
def _refine_counts(flat, coords, threshold, n, size: int, sub: int):
    """Subdivide count blocks: for each (row0, col0) block origin pair
    in `coords` (units of `size` rows/cols of the flat plane array),
    return [F, sub, sub] int32 pair counts of its sub-blocks.

    Pure matmul + reshape-reduce — the extraction pyramid never runs
    nonzero/cumsum on device (a [4096,4096] nonzero measured ~150 ms
    per tile; this refinement is ~2 ms per tile).
    """
    return _refine_body(flat, coords, threshold, n, size, sub)


def make_sharded_pyramid(mesh):
    """The near-dup pyramid's counts + refine stages laid out for a
    1-D device mesh — the multi-chip form of `near_dup_pairs_device`.

    counts: the tile-row axis is sharded (each device owns NT/D row
    tiles); column tiles arrive by `all_gather` over the mesh axis, so
    the full [NT, NT] count grid is produced with each device doing an
    equal slice of the matmul sweep.

    refine: the flagged-block axis is sharded — each device refines its
    own block set against a replicated plane array (blocks are
    independent, zero collectives).

    Returns (counts_fn, refine_fn):
      counts_fn(planes [NT, T, BITS], threshold, n) -> [NT, NT] int32
      refine_fn(flat [NP, BITS], coords [F, 2], threshold, n) ->
          [F, sub, sub] int32   (size/sub fixed at tile → REFINE_SUB)
    """

    @jit_registry.tracked("hamming.pyramid")
    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("data", None, None), P(), P()),
        out_specs=P("data", None))
    def counts_fn(planes_shard, threshold, n):
        local_nt, T, BITS = planes_shard.shape
        base = jax.lax.axis_index("data") * local_nt
        planes_all = jax.lax.all_gather(
            planes_shard, "data", tiled=True)
        NT = planes_all.shape[0]

        def row(k):
            x = planes_shard[k]

            def col(j):
                dots = jax.lax.dot_general(
                    x, planes_all[j], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return jnp.sum(
                    _pair_mask(dots, base + k, j, T, BITS, threshold, n),
                    dtype=jnp.int32)

            return jax.lax.map(col, jnp.arange(NT, dtype=jnp.int32))

        return jax.lax.map(row, jnp.arange(local_nt, dtype=jnp.int32))

    def make_refine(size: int, sub: int):
        @jit_registry.tracked("hamming.pyramid")
        @jax.jit
        @functools.partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(None, None), P("data", None), P(), P()),
            out_specs=P("data", None, None))
        def refine_fn(flat, coords_shard, threshold, n):
            return _refine_body(flat, coords_shard, threshold, n,
                                size, sub)

        return refine_fn

    return counts_fn, make_refine


@jit_registry.tracked("hamming.leaf_masks")
@functools.partial(jax.jit, static_argnames=("size",))
def _leaf_masks(flat, coords, threshold, n, size: int):
    """[F, size, size] uint8 pair masks for leaf blocks — tiny enough
    to ship to the host, where numpy nonzero finishes the job."""
    NP, BITS = flat.shape

    def one(rc):
        oi = rc[0] * size
        oj = rc[1] * size
        x = jax.lax.dynamic_slice_in_dim(flat, oi, size)
        y = jax.lax.dynamic_slice_in_dim(flat, oj, size)
        dots = jax.lax.dot_general(
            x, y, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return _origin_pair_mask(dots, oi, oj, size, BITS,
                                 threshold, n).astype(jnp.uint8)

    return jax.lax.map(one, coords)


# Row-tiles per counts dispatch and refinement blocks per extract
# dispatch: sized so one dispatch stays well under the tunnel worker's
# runtime tolerance (~a few thousand [T,T] matmul tiles).
COUNT_ROWS_PER_DISPATCH = 16
REFINE_BLOCKS_PER_DISPATCH = 1024
REFINE_SUB = 16  # 4096 → 256 → 16-wide leaf blocks
# Host-side pair-list budget; denser output is degenerate (see
# near_dup_pairs_device docstring).
MAX_TOTAL_PAIRS = 4 << 20


def _pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


def near_dup_pairs_device(digests: np.ndarray, threshold: int,
                          tile: int = 4096,
                          stats: Optional[Dict[str, int]] = None,
                          ) -> List[Tuple[int, int]]:
    """Exact all-pairs (i < j, distance ≤ threshold) at large N on the
    device — a bounded number of jit dispatches, each sweeping thousands
    of tiles (see block comment above). Returns the same pairs as
    `near_dup_pairs`, validated at 1M by tools/near_dup_scale.py.

    Output is bounded at MAX_TOTAL_PAIRS: a degenerate near-identical
    cluster of m digests holds ~m²/2 qualifying pairs (50k burst photos
    → 1.25e9 pairs → ~100 GB of host tuples); past the budget the
    densest tiles are dropped with a warning — their exact-equality
    core is already collapsed by the CAS dedup pass, and a pair list
    that size is noise for any consumer. When truncation happens,
    `stats["truncated_pairs"]` carries the dropped-pair estimate (in
    addition to the RuntimeWarning) so callers can record it."""
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    N, W = digests.shape
    if N < 2:
        return []
    if tile & (tile - 1):
        raise ValueError(f"tile must be a power of two, got {tile} "
                         "(the refinement pyramid subdivides by "
                         f"{REFINE_SUB})")
    with jit_registry.device_scope("hamming.pairs"):
        return _near_dup_pairs_device_guarded(digests, threshold, tile,
                                              stats)


def _near_dup_pairs_device_guarded(digests, threshold, tile, stats):
    """Body of near_dup_pairs_device, run inside its device scope."""
    N, W = digests.shape
    NT = -(-N // tile)
    padded = np.zeros((NT * tile, W), dtype=np.uint32)
    padded[:N] = digests
    flat = _bit_planes(jnp.asarray(padded))
    planes = flat.reshape(NT, tile, W * 32)

    thr = jnp.int32(threshold)
    nn = jnp.int32(N)
    blocks = []
    for r0 in range(0, NT, COUNT_ROWS_PER_DISPATCH):
        dev_blk = _tile_counts_block(
            planes, jnp.int32(r0), thr, nn, COUNT_ROWS_PER_DISPATCH)
        with jit_registry.io("hamming.pairs"):
            blk = np.asarray(dev_blk)
        blocks.append(blk[: NT - r0])
    counts = np.concatenate(blocks, axis=0)

    coords = np.argwhere(counts > 0).astype(np.int32)
    if len(coords) == 0:
        return []
    tile_totals = counts[coords[:, 0], coords[:, 1]]
    if int(tile_totals.sum()) > MAX_TOTAL_PAIRS:
        # Keep sparsest tiles first until the pair budget is spent.
        import warnings

        order = np.argsort(tile_totals)
        keep = np.cumsum(tile_totals[order]) <= MAX_TOTAL_PAIRS
        dropped = int(tile_totals.sum()
                      - tile_totals[order][keep].sum())
        warnings.warn(
            f"near_dup_pairs_device: truncating ~{dropped} pairs in "
            "degenerate near-identical clusters (MAX_TOTAL_PAIRS "
            f"= {MAX_TOTAL_PAIRS})", RuntimeWarning)
        if stats is not None:
            stats["truncated_pairs"] = (
                stats.get("truncated_pairs", 0) + dropped)
        coords = coords[order][keep]
        if len(coords) == 0:
            return []

    def run_level(fn, coords, *args):
        """Dispatch a refinement level in pow2-padded chunks."""
        outs = []
        for f0 in range(0, len(coords), REFINE_BLOCKS_PER_DISPATCH):
            chunk = coords[f0 : f0 + REFINE_BLOCKS_PER_DISPATCH]
            fpad = _pow2(len(chunk))
            padded_c = np.zeros((fpad, 2), dtype=np.int32)
            padded_c[: len(chunk)] = chunk
            dev_res = fn(flat, jnp.asarray(padded_c), thr, nn, *args)
            with jit_registry.io("hamming.pairs"):
                res = np.asarray(dev_res)
            outs.append(res[: len(chunk)])
        return np.concatenate(outs, axis=0)

    # Refinement pyramid: tile → tile/16 → tile/256 leaf blocks; each
    # level keeps only sub-blocks whose count is nonzero, so the work
    # set stays O(pairs), and the leaves ship as tiny host-side masks.
    size = tile
    while size > REFINE_SUB:
        sub_counts = run_level(_refine_counts, coords, size, REFINE_SUB)
        f, a, b = np.nonzero(sub_counts)
        coords = np.stack([coords[f, 0] * REFINE_SUB + a,
                           coords[f, 1] * REFINE_SUB + b],
                          axis=1).astype(np.int32)
        size //= REFINE_SUB
        if len(coords) == 0:
            return []

    masks = run_level(_leaf_masks, coords, size)
    f, ii, jj = np.nonzero(masks)
    pi = coords[f, 0].astype(np.int64) * size + ii
    pj = coords[f, 1].astype(np.int64) * size + jj
    order = np.lexsort((pj, pi))
    return [(int(a), int(b)) for a, b in zip(pi[order], pj[order])]
