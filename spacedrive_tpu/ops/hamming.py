"""Device-side duplicate analytics: exact-dup grouping and Hamming all-pairs.

The reference detects duplicates only by exact CAS-ID equality
(/root/reference/core/src/object/file_identifier/mod.rs:167-225); there is
no perceptual near-dup search anywhere in it. This module supplies both:

- `exact_dup_groups`: batch grouping of equal digests (the device analog
  of the identifier's cas_id matching, used by the dedup pass over 100k+
  libraries).
- Hamming all-pairs over bit-digests (pHash near-dup search — net-new
  capability per BASELINE.json): XOR + popcount, tiled so the N×N
  comparison streams through fixed-size blocks, with a shard_map layout
  that puts row-blocks on one mesh axis and column-blocks on the other so
  each device computes an [N/r, N/c] tile with no replication of the
  full matrix.

Digests are [N, W] uint32 grids (W=2 for 64-bit pHash / CAS prefixes,
W=8 for full 256-bit BLAKE3).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


@jax.jit
def hamming_tile(x, y):
    """[n, W] × [m, W] uint32 → [n, m] int32 Hamming distances."""
    xors = x[:, None, :] ^ y[None, :, :]
    return jnp.sum(jax.lax.population_count(xors), axis=-1).astype(jnp.int32)


def make_sharded_hamming(mesh):
    """All-pairs Hamming over a 2-D (rows, cols) mesh.

    The same digest array is passed twice — once sharded by rows, once by
    cols — so each device holds two 1/r- and 1/c-sized slices and emits
    its tile of the distance matrix; no device ever sees the full N×N.
    """

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P("rows", None), P("cols", None)),
        out_specs=P("rows", "cols"),
    )
    def sharded(x_rows, y_cols):
        xors = x_rows[:, None, :] ^ y_cols[None, :, :]
        return jnp.sum(
            jax.lax.population_count(xors), axis=-1
        ).astype(jnp.int32)

    return sharded


@functools.partial(jax.jit, static_argnames=("threshold",))
def _near_mask_tile(x, y, threshold: int):
    return hamming_tile(x, y) <= threshold


def near_dup_pairs(
    digests: np.ndarray,
    threshold: int,
    tile: int = 4096,
) -> List[Tuple[int, int]]:
    """All (i < j) index pairs with Hamming distance ≤ threshold.

    Streams the upper triangle through [tile, tile] device blocks so N is
    bounded by O(N·W) HBM, not N². Exact all-pairs — fine to ~100k
    digests (≈ 300 tiles of 16M comparisons each at 4096); beyond that,
    bucket with `phash_bands` first (SURVEY.md §7 hard-part 4).
    """
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    N = digests.shape[0]
    pairs: List[Tuple[int, int]] = []
    for i0 in range(0, N, tile):
        xi = digests[i0 : i0 + tile]
        for j0 in range(i0, N, tile):
            yj = digests[j0 : j0 + tile]
            mask = np.asarray(_near_mask_tile(xi, yj, threshold))
            if i0 == j0:
                mask = np.triu(mask, k=1)
            ii, jj = np.nonzero(mask)
            pairs.extend(zip((ii + i0).tolist(), (jj + j0).tolist()))
    return pairs


def exact_dup_groups(ids: List[str]) -> Dict[str, List[int]]:
    """Group indexes by identical id; only groups with >1 member.

    The host-side exact pass (id strings are 16-hex CAS IDs). For large
    batches the heavy lifting — the digests themselves — already happened
    on device; grouping N short strings is O(N) dict work.
    """
    groups: Dict[str, List[int]] = {}
    for i, cid in enumerate(ids):
        groups.setdefault(cid, []).append(i)
    return {k: v for k, v in groups.items() if len(v) > 1}


def phash_bands(digests: np.ndarray, n_bands: int = 4) -> Dict[tuple, List[int]]:
    """LSH banding: split each digest into bands; near-dups (small Hamming
    distance) collide in at least one band with high probability. Use to
    bucket >100k sets, then run exact near_dup_pairs per bucket."""
    digests = np.ascontiguousarray(digests, dtype=np.uint32)
    N, W = digests.shape
    bits = digests.view(np.uint8).reshape(N, W * 4)
    per = max(1, (W * 4) // n_bands)
    buckets: Dict[tuple, List[int]] = {}
    for b in range(n_bands):
        band = bits[:, b * per : (b + 1) * per]
        for i in range(N):
            buckets.setdefault((b, band[i].tobytes()), []).append(i)
    return {k: v for k, v in buckets.items() if len(v) > 1}
