"""Central jax.jit contract registry — the device twin of flags.py.

Every `jax.jit` entry point in the tree DECLARES its contract here:
how many traces it is allowed (shape buckets × static-arg combos),
which argnames are static, which dtypes cross the boundary, and
whether its results are expected to transfer back to the host. The
promise `ops/staging.py` makes in prose ("two compiled shapes only")
becomes machine-checked in two halves:

- **statically** — tools/sdlint's `jit-stability` / `dtype-discipline`
  / `host-transfer` passes parse the `declare_jit(...)` calls below
  (AST, same as the flag-registry pass) and fail the build on
  undeclared jit sites, call-time `jax.jit(fn)` construction outside a
  declared factory, static-arg drift, and stray D2H transfers outside
  a declared `io(...)` scope;
- **at runtime** — `tracked(name)` wraps the jitted callable and
  counts retraces (jit cache growth) against the declared budget into
  `sd_jit_retraces_total{fn}` / `sd_jit_cache_size{fn}`, and
  `device_scope()` / `io(name)` arm JAX's device-to-host transfer
  guard (raise mode in tier-1, log mode in production — the same
  split as sanitize.py, which arms this module at install()).

Design constraints (same as flags.py / telemetry.py): pure stdlib at
import time — `jax` is imported lazily and ONLY when a guard scope is
actually armed, so every layer (including jax-free hosts running the
numpy backends) can import this module.
"""

from __future__ import annotations

import functools
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, Optional, Tuple

from .. import flags
from ..telemetry import JIT_CACHE_SIZE, JIT_DECLARED_TRANSFERS, JIT_RETRACES

__all__ = [
    "JitContract", "CONTRACTS", "declare_jit", "tracked", "io",
    "device_scope", "arm", "disarm", "armed", "trace_counts",
    "temporary_contract",
]


@dataclass(frozen=True)
class JitContract:
    """One jit entry point's declared behavior.

    `site` is the `relpath::qualname` of the definition — sdlint uses
    it to associate factory functions (which construct their jit at
    call time) with their declaration. `max_traces` is a PROCESS-WIDE
    budget across every instance the site ever creates: exceeding it
    means the canonical-shape promise broke (a silent retrace storm),
    which is a sanitizer violation in raise mode.
    """

    name: str                      # short dotted id ("blake3.jnp")
    site: str                      # "spacedrive_tpu/ops/x.py::qual"
    kind: str = "entry"            # "entry" | "factory" | "wrapper"
    max_traces: int = 8
    static_argnames: Tuple[str, ...] = ()
    in_dtypes: Tuple[str, ...] = ()
    out_dtypes: Tuple[str, ...] = ()
    shape_buckets: str = ""        # the canonical-grid policy, prose
    host_transfer: bool = False    # results fetched via io(name)
    # Positional args this entry point may CONSUME (jax donate_argnums):
    # a donated buffer is invalid after the call — callers must treat
    # it as moved, which is why donation is part of the declared
    # contract surface (the sdlint jit-stability pass fails the build
    # on a jit site donating argnums its contract does not declare).
    # Declaring donation does not force it: sites may bind undonated
    # variants of the same contract (SDTPU_DONATE_BUFFERS=off).
    donate_argnums: Tuple[int, ...] = ()


CONTRACTS: Dict[str, JitContract] = {}


def declare_jit(name: str, site: str, *, kind: str = "entry",
                max_traces: int = 8,
                static_argnames: Tuple[str, ...] = (),
                in_dtypes: Tuple[str, ...] = (),
                out_dtypes: Tuple[str, ...] = (),
                shape_buckets: str,
                host_transfer: bool = False,
                donate_argnums: Tuple[int, ...] = ()) -> JitContract:
    if name in CONTRACTS:
        raise ValueError(f"jit contract {name!r} declared twice")
    if kind not in ("entry", "factory", "wrapper"):
        raise ValueError(f"{name}: unknown contract kind {kind!r}")
    if not shape_buckets.strip():
        raise ValueError(
            f"{name}: every contract must state its shape-bucket "
            f"policy (what keeps the compiled-program count bounded)")
    c = JitContract(name, site, kind, max_traces,
                    tuple(static_argnames), tuple(in_dtypes),
                    tuple(out_dtypes), shape_buckets, host_transfer,
                    tuple(donate_argnums))
    CONTRACTS[name] = c
    return c


# -- runtime arming ---------------------------------------------------------
# sanitize.install() arms this module with its mode and its violation
# recorder; the callback indirection keeps the import graph acyclic
# (ops code imports this module, this module never imports sanitize).

_armed = False
_mode = "count"
_record: Optional[Callable[[str, str, bool], None]] = None
_trace_lock = threading.Lock()
_traces: Dict[str, int] = {}


def arm(mode: str, record: Callable[[str, str, bool], None]) -> None:
    global _armed, _mode, _record
    _mode = mode
    _record = record
    _armed = True


def disarm() -> None:
    global _armed, _record
    _armed = False
    _record = None


def armed() -> bool:
    return _armed


def trace_counts() -> Dict[str, int]:
    """Process-wide trace counts per contract name (diagnostics; the
    same numbers live in sd_jit_cache_size{fn}). There is deliberately
    no reset: counts mirror the live jit caches, and a reset would
    desync them from the per-wrapper cache-size watermarks — benches
    that want per-run deltas snapshot this dict and subtract."""
    with _trace_lock:
        return dict(_traces)


def _retrace_guard_on() -> bool:
    return _armed and flags.get("SDTPU_RETRACE_GUARD") != "off"


def _transfer_guard_level() -> Optional[str]:
    """jax transfer-guard level for device scopes, or None when off.
    `auto` follows the sanitizer mode: disallow under raise (tier-1),
    log under count (production)."""
    if not _armed:
        return None
    mode = flags.get("SDTPU_TRANSFER_GUARD")
    if mode == "off":
        return None
    if mode == "auto":
        return "disallow" if _mode == "raise" else "log"
    return {"raise": "disallow", "log": "log"}.get(mode)


# -- retrace counting -------------------------------------------------------

def _note_traces(contract: JitContract, state: dict, jitted) -> None:
    size_fn = getattr(jitted, "_cache_size", None)
    if size_fn is None:
        return
    try:
        size = size_fn()
    except Exception:
        return
    # One lock covers the wrapper's cache-size watermark AND the
    # global count: two threads observing the same compile must
    # account it once, not once each (the sanitizer cannot afford its
    # own data race — a double-counted delta is a spurious budget
    # violation in raise mode).
    with _trace_lock:
        delta = size - state["last"]
        if delta <= 0:
            return
        state["last"] = size
        _traces[contract.name] = _traces.get(contract.name, 0) + delta
        total = _traces[contract.name]
    JIT_RETRACES.labels(fn=contract.name).inc(delta)
    JIT_CACHE_SIZE.labels(fn=contract.name).set(total)
    if total > contract.max_traces and _record is not None:
        _record(
            "jit_retrace_budget",
            f"{contract.name}: {total} traces exceed the declared "
            f"budget of {contract.max_traces} (site {contract.site}; "
            f"a shape/static-arg reached the boundary outside the "
            f"canonical buckets: {contract.shape_buckets})",
            True)


def tracked(name: str):
    """Decorator binding a jitted callable to its declared contract.

    Wraps the function so every call, when the sanitizer armed this
    module, diffs the jit cache size and accounts new traces against
    the contract's budget. Disarmed cost: one module-global check per
    call — noise next to a device dispatch. The raw jitted callable
    stays reachable as `.__wrapped__` (functools.wraps)."""
    contract = CONTRACTS.get(name)
    if contract is None:
        raise KeyError(
            f"undeclared jit contract {name!r} (declare it in "
            f"spacedrive_tpu/ops/jit_registry.py)")

    def deco(fn):
        state = {"last": 0}

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            out = fn(*args, **kwargs)
            if _armed and _retrace_guard_on():
                _note_traces(contract, state, fn)
            return out

        wrapper._sdtpu_jit_contract = contract
        return wrapper

    return deco


# -- transfer guard scopes --------------------------------------------------

@contextmanager
def device_scope(label: str = "") -> Iterator[None]:
    """Guarded region around a device pipeline: inside it, an
    UNDECLARED device-to-host transfer (np.asarray on a live device
    value, implicit bool/float, .item()) is a sanitizer violation —
    raise mode raises at the transfer point (JAX's guard error), count
    mode logs. Declared fetches open an `io(name)` scope inside.

    Host-to-device stays unguarded: inputs are expected to stream in
    (device_put or implicit) — the discipline this enforces is about
    RESULTS leaking back mid-pipeline.

    Mode split: `disallow` under raise mode records + raises at the
    transfer point; count mode can only arm JAX's `log` level — the
    guard has no hook short of raising, so production detections
    surface as JAX transfer-guard log lines, not counters (the
    sd_sanitize host_transfer counter increments only on the raising
    path). Retrace budgets, by contrast, count in BOTH modes."""
    level = _transfer_guard_level()
    if level is None:
        yield
        return
    try:
        import jax
    except ImportError:
        # jax-free host running the numpy backends: nothing to guard.
        yield
        return

    try:
        with jax.transfer_guard_device_to_host(level):
            yield
    except Exception as e:
        msg = str(e).lower()
        # Match the guard's own error shape ("Disallowed host-to-device
        # transfer: ..."), not any app error that mentions transfers.
        if "disallow" in msg and "transfer" in msg and _record is not None:
            # Record for telemetry/violations(), then let the original
            # error surface — in raise mode the test sees the real
            # guard error with the offending line in its traceback.
            _record(
                "host_transfer",
                f"undeclared D2H transfer in device scope "
                f"{label or '?'}: {e}",
                False)
        raise


@contextmanager
def io(name: str) -> Iterator[None]:
    """A DECLARED host-transfer point: the contract `name` must exist
    with host_transfer=True. Inside, the D2H guard is lifted (the
    fetch is part of the entry point's declared surface) and the
    transfer is counted into sd_jit_declared_transfers_total{fn}.
    Opening an io scope for an undeclared contract is itself a
    violation — the registry stays authoritative."""
    contract = CONTRACTS.get(name)
    if contract is None or not contract.host_transfer:
        if _armed and _record is not None:
            _record(
                "host_transfer",
                f"io({name!r}): not a declared host-transfer contract "
                f"(declare it with host_transfer=True in "
                f"spacedrive_tpu/ops/jit_registry.py)",
                True)
        yield
        return
    if _armed:
        JIT_DECLARED_TRANSFERS.labels(fn=name).inc()
    if _transfer_guard_level() is None:
        yield
        return
    try:
        import jax
    except ImportError:
        yield
        return

    with jax.transfer_guard_device_to_host("allow"):
        yield


@contextmanager
def temporary_contract(name: str, **kwargs) -> Iterator[JitContract]:
    """Declare a contract for the duration of a with-block (tests)."""
    kwargs.setdefault("shape_buckets", "test-local")
    c = declare_jit(name, kwargs.pop("site", f"test::{name}"), **kwargs)
    try:
        yield c
    finally:
        CONTRACTS.pop(name, None)
        with _trace_lock:
            _traces.pop(name, None)


# ---------------------------------------------------------------------------
# THE jit namespace. Keep grouped by module; every entry is enforced by
# the sdlint jit-stability pass (undeclared jit sites fail the build)
# and, when the sanitizer is armed, by the retrace counter at runtime.
# max_traces budgets are process-wide ceilings sized from a full
# sanitized tier-1 run (which exercises far more shapes than any
# production pipeline) plus headroom; the canonical production shape
# count per entry is what `shape_buckets` documents.
# ---------------------------------------------------------------------------

declare_jit(
    "blake3.jnp", "spacedrive_tpu/ops/blake3_jax.py::_blake3_jnp_jit",
    max_traces=96, in_dtypes=("uint32", "int32"), out_dtypes=("uint32",),
    shape_buckets="canonical CAS grids [B,57,256] / [B,101,256] with B "
                  "pow2-bucketed by _bucket_b; checksum grids pad C to "
                  "pow2 (tests add oracle-parity odd shapes)")

declare_jit(
    "blake3.sharded", "spacedrive_tpu/ops/blake3_jax.py::make_sharded_blake3",
    kind="factory", max_traces=16,
    in_dtypes=("uint32", "int32"), out_dtypes=("uint32",),
    shape_buckets="one mesh per process (sharded_hasher caches); same "
                  "pow2 B buckets as blake3.jnp, shards = devices")

declare_jit(
    "blake3.donated", "spacedrive_tpu/ops/blake3_jax.py::_donated_best",
    max_traces=96, in_dtypes=("uint32", "int32"), out_dtypes=("uint32",),
    donate_argnums=(0, 1),
    shape_buckets="same canonical CAS grids as blake3.jnp; the donated "
                  "twin cas_ids_jax dispatches when SDTPU_DONATE_BUFFERS "
                  "is on — inputs are consumed (identity pass-through "
                  "outputs alias them), so each CAS batch's staged "
                  "device copy is recycled at kernel completion instead "
                  "of surviving until the digest fetch")

declare_jit(
    "cas.ids", "spacedrive_tpu/ops/blake3_jax.py::cas_ids_jax",
    kind="wrapper", host_transfer=True,
    out_dtypes=("str",),
    shape_buckets="delegates to blake3.jnp buckets; CAS IDs are host "
                  "strings — the D2H fetch is this wrapper's contract")

declare_jit(
    "cas.checksums",
    "spacedrive_tpu/ops/blake3_jax.py::checksums_words_batched",
    kind="wrapper", host_transfer=True,
    out_dtypes=("str",),
    shape_buckets="pow2 chunk grids, B pow2-bucketed; hex digests are "
                  "host strings — the D2H fetch is this wrapper's "
                  "contract")

declare_jit(
    "blake3.pallas.chunk_fast",
    "spacedrive_tpu/ops/blake3_pallas.py::_chunk_cvs_pallas_fast",
    max_traces=96, static_argnames=("interpret",),
    in_dtypes=("uint32", "int32"), out_dtypes=("uint32",),
    shape_buckets="same canonical CAS grids as blake3.jnp (TPU-only "
                  "fast path; interpret=True only in tests)")

declare_jit(
    "blake3.pallas.chunk",
    "spacedrive_tpu/ops/blake3_pallas.py::_chunk_cvs_pallas",
    max_traces=96, static_argnames=("interpret",),
    in_dtypes=("uint32", "int32", "bool"), out_dtypes=("uint32",),
    shape_buckets="counter-base variant of blake3.pallas.chunk_fast "
                  "(seqhash windows: one fixed window grid per mesh)")

declare_jit(
    "blake3.pallas.words",
    "spacedrive_tpu/ops/blake3_pallas.py::blake3_words_pallas",
    max_traces=96, static_argnames=("interpret",),
    in_dtypes=("uint32", "int32"), out_dtypes=("uint32",),
    shape_buckets="same canonical CAS grids as blake3.jnp (chunk stage "
                  "+ tree reduce fused in one program)")

declare_jit(
    "hamming.tile", "spacedrive_tpu/ops/hamming.py::hamming_tile",
    max_traces=32, in_dtypes=("uint32",), out_dtypes=("int32",),
    shape_buckets="[n,W]x[m,W] probe tiles; production uses the fixed "
                  "4096 tile, tests add small parity shapes")

declare_jit(
    "hamming.near_mask", "spacedrive_tpu/ops/hamming.py::_near_mask_tile",
    max_traces=32, static_argnames=("threshold",),
    in_dtypes=("uint32",), out_dtypes=("bool",),
    shape_buckets="one-tile batches (N <= tile); threshold static by "
                  "design (tiny int domain)")

declare_jit(
    "hamming.tile_counts",
    "spacedrive_tpu/ops/hamming.py::_tile_counts_block",
    max_traces=16, static_argnames=("block",),
    in_dtypes=("bfloat16", "int32"), out_dtypes=("int32",),
    shape_buckets="row0/threshold/n are traced scalars — one program "
                  "per (tile grid, block) pair, block fixed at "
                  "COUNT_ROWS_PER_DISPATCH")

declare_jit(
    "hamming.refine", "spacedrive_tpu/ops/hamming.py::_refine_counts",
    max_traces=16, static_argnames=("size", "sub"),
    in_dtypes=("bfloat16", "int32"), out_dtypes=("int32",),
    shape_buckets="coords padded to pow2 per dispatch (run_level), "
                  "size walks tile -> REFINE_SUB in fixed /16 steps")

declare_jit(
    "hamming.leaf_masks", "spacedrive_tpu/ops/hamming.py::_leaf_masks",
    max_traces=16, static_argnames=("size",),
    in_dtypes=("bfloat16", "int32"), out_dtypes=("uint8",),
    shape_buckets="coords padded to pow2 per dispatch, size fixed at "
                  "REFINE_SUB by the pyramid walk")

declare_jit(
    "hamming.sharded", "spacedrive_tpu/ops/hamming.py::make_sharded_hamming",
    kind="factory", max_traces=16,
    in_dtypes=("uint32",), out_dtypes=("int32",),
    shape_buckets="one program per (mesh, digest grid); callers build "
                  "one sharded fn per mesh and reuse it")

declare_jit(
    "hamming.pyramid", "spacedrive_tpu/ops/hamming.py::make_sharded_pyramid",
    kind="factory", max_traces=16,
    in_dtypes=("bfloat16", "int32"), out_dtypes=("int32",),
    shape_buckets="counts + refine stages per mesh; same pow2 coord "
                  "padding as the single-device pyramid")

declare_jit(
    "hamming.pairs", "spacedrive_tpu/ops/hamming.py::near_dup_pairs_device",
    kind="wrapper", host_transfer=True,
    out_dtypes=("int64",),
    shape_buckets="bounded dispatch count (pyramid levels), pair "
                  "coordinates are host output — D2H declared here")

declare_jit(
    "seqhash.reduce", "spacedrive_tpu/ops/seqhash.py::_sharded_reduce",
    max_traces=32, static_argnames=("mesh", "shard_chunks", "root"),
    in_dtypes=("uint32", "int32"), out_dtypes=("uint32",),
    shape_buckets="one fixed window grid per (mesh, shard_chunks); "
                  "root True/False doubles it; meshes cached in "
                  "parallel/mesh.py so equal device sets reuse one "
                  "program")

declare_jit(
    "seqhash.window", "spacedrive_tpu/ops/seqhash.py::StreamingShardedChecksum",
    kind="wrapper", host_transfer=True,
    out_dtypes=("uint32",),
    shape_buckets="window tops and digests are 8-word fetches — the "
                  "D2H per window is this wrapper's contract")

declare_jit(
    "phash.batch", "spacedrive_tpu/ops/phash.py::phash_jax",
    kind="factory", max_traces=16, host_transfer=True,
    in_dtypes=("float32",), out_dtypes=("bool",),
    shape_buckets="[B,32,32] grids, one trace per distinct B (callers "
                  "batch whole decode sets); bit fetch declared")

declare_jit(
    "overlap.kernel", "spacedrive_tpu/ops/overlap.py::_jitted",
    kind="factory", max_traces=64,
    in_dtypes=("uint32", "int32"), out_dtypes=("uint32",),
    donate_argnums=(0, 1),
    shape_buckets="lru-cached jit per (kernel fn, donate) pair (the "
                  "round-10 fix for the per-call jax.jit(fn) "
                  "recompile); one large-class batch grid per run, "
                  "times the round-robin device count (committed "
                  "inputs compile one program per device). The "
                  "donated variant consumes its (words, lengths) "
                  "inputs — the depth-N ring's recycled H2D buffers")

declare_jit(
    "overlap.retire", "spacedrive_tpu/ops/overlap.py::run_overlapped",
    kind="wrapper", host_transfer=True,
    out_dtypes=("uint32",),
    shape_buckets="digest retirement + calibration sync markers are "
                  "the pipeline's declared D2H points")

declare_jit(
    "staging.h2d_probe", "spacedrive_tpu/ops/staging.py::h2d_gbps",
    kind="wrapper", host_transfer=True,
    shape_buckets="one 8 MiB probe buffer, once per process (disk "
                  "cached); the round-trip fetch IS the measurement")
