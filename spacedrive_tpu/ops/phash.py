"""Batched perceptual hashing (pHash) — DCT-as-matmul for the MXU.

Net-new capability vs the reference (SURVEY.md §2.1 "Duplicate
detection": pHash near-dup is not in Spacedrive). The classic pHash
recipe, restructured for TPU batching:

1. decode + downsample each image to a 32×32 grayscale grid (CPU/PIL —
   decode stays host-side like the reference's thumbnailer);
2. 2-D DCT-II of the whole batch as two matmuls `D @ X @ Dᵀ` — one
   [B,32,32] einsum pair that XLA maps straight onto the MXU, instead of
   the per-image scipy calls a port would make;
3. keep the top-left 8×8 low-frequency block, drop the DC term, threshold
   against the per-image median → a 64-bit hash, packed [B, 2] uint32 for
   ops/hamming.py's all-pairs XOR+popcount.

Backends mirror ops/staging: numpy (always available) and jax (jitted).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

HASH_EDGE = 8            # 8×8 low-frequency block → 64 bits
INPUT_EDGE = 32          # downsampled grid edge


def dct_matrix(n: int = INPUT_EDGE) -> np.ndarray:
    """Orthonormal DCT-II matrix [n, n] (float32)."""
    k = np.arange(n)[:, None].astype(np.float64)
    i = np.arange(n)[None, :].astype(np.float64)
    m = np.cos(np.pi / n * (i + 0.5) * k)
    m[0] *= 1.0 / math.sqrt(2.0)
    return (m * math.sqrt(2.0 / n)).astype(np.float32)


_DCT32 = dct_matrix(INPUT_EDGE)


def _phash_core(xp, grids, dct):
    """[B, 32, 32] float grids → [B, 64] bool bits. Backend-generic."""
    coeffs = xp.einsum("ij,bjk,lk->bil", dct, grids, dct)
    low = coeffs[:, :HASH_EDGE, :HASH_EDGE].reshape(
        grids.shape[0], HASH_EDGE * HASH_EDGE)
    # Median over the AC terms (DC dominates brightness, excluded).
    ac = low[:, 1:]
    med = xp.median(ac, axis=1, keepdims=True)
    return ac > med


def _bits_to_words(bits: np.ndarray) -> np.ndarray:
    """[B, 63] bool → [B, 2] uint32 (63 AC bits + 1 zero pad)."""
    B = bits.shape[0]
    padded = np.zeros((B, 64), dtype=np.uint8)
    padded[:, :bits.shape[1]] = bits.astype(np.uint8)
    packed = np.packbits(padded, axis=1)  # [B, 8] bytes
    return packed.view(">u4").astype(np.uint32).reshape(B, 2)


def phash_numpy(grids: np.ndarray) -> np.ndarray:
    """[B, 32, 32] float32 → [B, 2] uint32 pHashes."""
    bits = _phash_core(np, grids.astype(np.float32), _DCT32)
    return _bits_to_words(np.asarray(bits))


_jax_phash = None


def phash_jax(grids: np.ndarray) -> np.ndarray:
    """Declared jit factory (contract phash.batch): the jitted DCT body
    is built once per process and cached in the module global; the bit
    fetch is the wrapper's declared host transfer."""
    global _jax_phash
    import jax
    import jax.numpy as jnp

    from . import jit_registry
    if _jax_phash is None:
        dct = jnp.asarray(_DCT32)

        @jit_registry.tracked("phash.batch")
        @jax.jit
        def run(g):
            coeffs = jnp.einsum("ij,bjk,lk->bil", dct, g, dct)
            low = coeffs[:, :HASH_EDGE, :HASH_EDGE].reshape(
                g.shape[0], HASH_EDGE * HASH_EDGE)
            ac = low[:, 1:]
            med = jnp.median(ac, axis=1, keepdims=True)
            return ac > med
        _jax_phash = run
    with jit_registry.device_scope("phash.batch"):
        out = _jax_phash(np.asarray(grids, dtype=np.float32))
        with jit_registry.io("phash.batch"):
            bits = np.asarray(out)
    return _bits_to_words(bits)


def image_to_grid(path: str) -> Optional[np.ndarray]:
    """Decode + grayscale + resize to [32, 32] float32; None on failure."""
    try:
        from PIL import Image
        with Image.open(path) as im:
            g = im.convert("L").resize(
                (INPUT_EDGE, INPUT_EDGE), Image.LANCZOS)
            return np.asarray(g, dtype=np.float32)
    except Exception:
        return None


def phash_files(paths: Sequence[str], backend: str = "auto",
                ) -> Tuple[dict, List[str]]:
    """paths → ({index: [2] uint32 hash}, errors). Batched decode + hash."""
    grids, idxs, errors = [], [], []
    for i, p in enumerate(paths):
        g = image_to_grid(p)
        if g is None:
            errors.append(f"phash decode failed: {p}")
        else:
            grids.append(g)
            idxs.append(i)
    if not grids:
        return {}, errors
    batch = np.stack(grids)
    if backend == "auto":
        from .staging import default_backend
        backend = default_backend(len(grids))
    words = phash_jax(batch) if backend == "jax" else phash_numpy(batch)
    return {i: words[row] for row, i in enumerate(idxs)}, errors


def phash_to_bytes(words: np.ndarray) -> bytes:
    return np.asarray(words, dtype=">u4").tobytes()


def phash_from_bytes(blob: bytes) -> np.ndarray:
    return np.frombuffer(blob, dtype=">u4").astype(np.uint32)
