"""Sequence-parallel BLAKE3: one huge file sharded across the mesh.

The long-context analog in this framework (SURVEY.md §5 "long-context /
sequence parallelism"): where an LLM shards one sequence's tokens across
devices, the validator shards one file's chunk chain. BLAKE3's tree mode
makes this exact — the tree over chunk CVs is adjacent pairing with
odd-promote, so any power-of-two-aligned span of chunks reduces to an
independent subtree top:

  stage 1 (local, zero collectives): each device hashes its contiguous
      span of chunks (counter base = global chunk index) and folds them
      to one subtree top with a no-ROOT tree reduction;
  stage 2 (one all-gather over ICI): the D shard tops are gathered and
      the top-of-tree reduction (log2 D tiny parent compressions) runs
      replicated on every device.

Semantics match the streaming oracle bit-for-bit
(/root/reference/core/src/object/validation/hash.rs full-file checksum,
here computed without any single device ever holding the whole file).

Shard capacity must be a power of two chunks so shard boundaries land on
subtree boundaries; files that fit in a single shard take the ordinary
batched path instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import jit_registry
from .blake3_batch import CHUNK_LEN, WORDS_PER_CHUNK, tree_reduce
from .blake3_jax import _chunk_cvs_scan

DEFAULT_SHARD_CHUNKS = 64  # 64 KiB per device-shard in tests; tune up on TPU


def _shard_fn(words_local, length, shard_chunks: int,
              base_lo=None, base_hi=None):
    """Per-device stage: [cps, 256] chunk words → 8-word subtree top.

    `length` is the byte count within THIS window (int32 — one window is
    bounded at 2 GiB); `base_lo`/`base_hi` is the uint32 pair for the
    window's first global chunk index (0 for a single-call hash). The
    shard's chunk counters are window base + shard offset, so repeated
    windowed calls see exactly the chunk counters the streaming oracle
    would use.
    """
    idx = jax.lax.axis_index("data")
    start = (idx * shard_chunks * CHUNK_LEN).astype(jnp.int32)
    local_len = jnp.clip(length - start, 0, shard_chunks * CHUNK_LEN)
    # Chunk counter base: global chunk index of this shard's first chunk,
    # carried as a (lo, hi) uint32 pair with explicit carry.
    off = (idx * shard_chunks).astype(jnp.uint32)
    if base_lo is None:
        lo = off
        hi = jnp.zeros((), jnp.uint32)
    else:
        lo = base_lo + off
        hi = base_hi + jnp.where(lo < off, jnp.uint32(1), jnp.uint32(0))
    cvs, n = _chunk_cvs_scan(words_local[None], local_len[None],
                             counter_base=(lo, hi), whole=False)
    top = tree_reduce(jnp, cvs, n, root=False)  # 8 × [1]
    return jnp.stack([w[0] for w in top])  # [8]


@jit_registry.tracked("seqhash.reduce")
@functools.partial(jax.jit,
                   static_argnames=("mesh", "shard_chunks", "root"))
def _sharded_reduce(words, length, n_tops, base_lo, base_hi, *,
                    mesh: Mesh, shard_chunks: int, root: bool):
    """Shared device body: shard chunk stage + all-gather + top tree.

    words: [D*cps, 256] uint32 sharded on the chunk axis; length: int32
    bytes in this window; n_tops: int32 shards holding real chunks;
    base_lo/base_hi: uint32 pair, global chunk index of the window start
    (0 for a single-call hash). root=True ROOT-finalizes the top merge
    (single-call digest); root=False yields a streaming window's
    subtree-top CV.
    """
    def inner(words_local):
        top = _shard_fn(words_local, length, shard_chunks,
                        base_lo, base_hi)
        return jax.lax.all_gather(top, "data")  # [D, 8] replicated

    tops = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(P("data", None),),
        out_specs=P(None, None),
        check_vma=False,
    )(words)
    # Top-of-tree: adjacent pairing over shard tops.
    cvs = [tops[:, i][None, :] for i in range(8)]  # 8 × [1, D]
    out = tree_reduce(jnp, cvs, n_tops[None], root=root)
    return jnp.stack([w[0] for w in out])  # [8]


def make_sharded_checksum(mesh: Mesh,
                          shard_chunks: int = DEFAULT_SHARD_CHUNKS):
    """Returns fn(data: bytes) -> 32-byte BLAKE3 digest, computed with
    the file's chunk chain sharded across `mesh`'s devices."""
    if shard_chunks & (shard_chunks - 1):
        raise ValueError("shard_chunks must be a power of two")
    D = int(np.prod(mesh.devices.shape))
    capacity = D * shard_chunks * CHUNK_LEN

    def fn(data: bytes) -> bytes:
        n_chunks = max(1, -(-len(data) // CHUNK_LEN))
        if n_chunks <= shard_chunks:
            # Fits one shard: the top stage would need ROOT handling the
            # sharded path deliberately never applies — use the batched
            # single-lane path.
            from .blake3_batch import blake3_batch_np

            return blake3_batch_np([data])[0]
        if len(data) > capacity:
            raise ValueError(
                f"data ({len(data)} B) exceeds mesh capacity "
                f"({capacity} B); raise shard_chunks")
        if len(data) > 2**31 - 1:
            raise ValueError(
                "single-call path is int32-bounded at 2 GiB; use "
                "StreamingShardedChecksum")
        buf = np.zeros(capacity, dtype=np.uint8)
        buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        words = buf.view("<u4").reshape(D * shard_chunks, WORDS_PER_CHUNK)
        sharding = NamedSharding(mesh, P("data", None))
        with jit_registry.device_scope("seqhash.reduce"):
            words_dev = jax.device_put(jnp.asarray(words), sharding)
            n_tops = np.int32(-(-n_chunks // shard_chunks))
            zero = jnp.zeros((), jnp.uint32)
            digest = _sharded_reduce(
                words_dev, jnp.asarray(len(data), jnp.int32),
                jnp.asarray(n_tops), zero, zero,
                mesh=mesh, shard_chunks=shard_chunks, root=True)
            with jit_registry.io("seqhash.window"):
                return np.asarray(digest).astype("<u4").tobytes()

    return fn


class StreamingShardedChecksum:
    """Streaming BLAKE3 over repeated sequence-sharded windows.

    Solves the "one file larger than mesh capacity / RAM" case the
    single-call path refuses: feed bytes in any increments; each time a
    full window (D · shard_chunks chunks) has accumulated AND more data
    follows, the window is hashed on-device (chunk counters offset by the
    window's global chunk base, no ROOT) into one subtree-top CV, and the
    host folds window tops with the standard incremental-BLAKE3 stack
    rule (merge-on-trailing-zeros of the window count — one 64-byte
    parent compression per merge, negligible host work). Memory is
    bounded at one window regardless of total stream length.

    The tail window (whatever remains at digest() time) reduces with the
    same adjacent-pairing/odd-promote tree, which equals the spec tree
    for any trailing span starting on a window boundary; the stack then
    merges right-to-left with ROOT on the last parent — exactly the
    finalize walk of the streaming oracle (blake3_ref.Blake3.digest).

    Semantics: /root/reference/core/src/object/validation/hash.rs:10-24
    (1 MiB streaming blocks into one hasher), recomputed here without any
    device ever holding more than one window.
    """

    def __init__(self, mesh: Mesh,
                 shard_chunks: int = DEFAULT_SHARD_CHUNKS):
        if shard_chunks & (shard_chunks - 1):
            raise ValueError("shard_chunks must be a power of two")
        D = int(np.prod(mesh.devices.shape))
        if D & (D - 1):
            raise ValueError("streaming windows need a power-of-two mesh")
        self._mesh = mesh
        self._shard_chunks = shard_chunks
        self._window_chunks = D * shard_chunks
        self._window_bytes = self._window_chunks * CHUNK_LEN
        if self._window_bytes > 2**31 - 1:
            # Window byte offsets are int32 on device (x64 off).
            raise ValueError(
                f"window ({self._window_bytes} B) exceeds the 2 GiB "
                "int32 device bound; lower shard_chunks")
        self._buf = bytearray()
        self._windows_done = 0     # full windows already folded (see
        # the `windows_folded` property)
        self._stack: list = []     # subtree CVs, shallowest first
        self._sharding = NamedSharding(mesh, P("data", None))

    @property
    def windows_folded(self) -> int:
        """Full windows already reduced on-device (diagnostics)."""
        return self._windows_done

    def update(self, data: bytes) -> "StreamingShardedChecksum":
        self._buf += data
        # Keep at least one byte buffered: the final window must be the
        # ROOT path in digest(), so a window is only folded when data
        # strictly beyond it has arrived.
        while len(self._buf) > self._window_bytes:
            window = bytes(self._buf[:self._window_bytes])
            del self._buf[:self._window_bytes]
            self._push_window_cv(self._window_top(window))
        return self

    def _window_top(self, data: bytes) -> list:
        """Device-reduce one window to its 8-word subtree-top CV."""
        buf = np.zeros(self._window_bytes, dtype=np.uint8)
        buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        words = buf.view("<u4").reshape(
            self._window_chunks, WORDS_PER_CHUNK)
        with jit_registry.device_scope("seqhash.reduce"):
            words_dev = jax.device_put(jnp.asarray(words), self._sharding)
            n_chunks = max(1, -(-len(data) // CHUNK_LEN))
            n_tops = np.int32(-(-n_chunks // self._shard_chunks))
            base = self._windows_done * self._window_chunks
            top = _sharded_reduce(
                words_dev, jnp.asarray(len(data), jnp.int32),
                jnp.asarray(n_tops),
                jnp.asarray(base & 0xFFFFFFFF, jnp.uint32),
                jnp.asarray(base >> 32, jnp.uint32),
                mesh=self._mesh, shard_chunks=self._shard_chunks,
                root=False)
            with jit_registry.io("seqhash.window"):
                return [int(w) for w in np.asarray(top)]

    def _push_window_cv(self, cv: list) -> None:
        from .blake3_ref import BLOCK_LEN as B3_BLOCK, IV, PARENT, compress

        self._windows_done += 1
        # Incremental-stack rule: after w windows, merge one level per
        # trailing zero bit of w.
        w = self._windows_done
        while w % 2 == 0:
            left = self._stack.pop()
            cv = compress(list(IV), left + cv, 0, B3_BLOCK, PARENT)[:8]
            w //= 2
        self._stack.append(cv)

    def digest(self) -> bytes:
        from .blake3_ref import BLOCK_LEN as B3_BLOCK, IV, PARENT, ROOT, compress

        if not self._stack:
            # Whole stream fit in one window: single-call ROOT path.
            return make_sharded_checksum(
                self._mesh, self._shard_chunks)(bytes(self._buf))
        tail = bytes(self._buf)
        cv = self._window_top(tail)
        # Finalize: fold the stack right-to-left; ROOT on the last parent.
        for i, left in enumerate(reversed(self._stack)):
            flags = PARENT | (ROOT if i == len(self._stack) - 1 else 0)
            cv = compress(list(IV), left + cv, 0, B3_BLOCK, flags)[:8]
        return b"".join(int(w).to_bytes(4, "little") for w in cv)

    def hexdigest(self) -> str:
        return self.digest().hex()


def make_streaming_checksum(mesh: Mesh,
                            shard_chunks: int = DEFAULT_SHARD_CHUNKS):
    """Returns a fresh StreamingShardedChecksum factory bound to `mesh`."""
    return lambda: StreamingShardedChecksum(mesh, shard_chunks)


def sharded_file_checksum(mesh: Mesh, path: str,
                          shard_chunks: int = DEFAULT_SHARD_CHUNKS) -> str:
    """Full-file checksum (validator semantics, hash.rs:10-24) with the
    chunk chain sequence-sharded across the mesh. Returns 64-hex digest.

    Files larger than one mesh window stream through repeated sharded
    window calls with bounded memory (one window buffered at a time).
    """
    D = int(np.prod(mesh.devices.shape))
    window = D * shard_chunks * CHUNK_LEN
    h = StreamingShardedChecksum(mesh, shard_chunks)
    with open(path, "rb") as f:
        while True:
            block = f.read(window)
            if not block:
                break
            h.update(block)
    return h.hexdigest()
