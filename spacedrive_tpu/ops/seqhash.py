"""Sequence-parallel BLAKE3: one huge file sharded across the mesh.

The long-context analog in this framework (SURVEY.md §5 "long-context /
sequence parallelism"): where an LLM shards one sequence's tokens across
devices, the validator shards one file's chunk chain. BLAKE3's tree mode
makes this exact — the tree over chunk CVs is adjacent pairing with
odd-promote, so any power-of-two-aligned span of chunks reduces to an
independent subtree top:

  stage 1 (local, zero collectives): each device hashes its contiguous
      span of chunks (counter base = global chunk index) and folds them
      to one subtree top with a no-ROOT tree reduction;
  stage 2 (one all-gather over ICI): the D shard tops are gathered and
      the top-of-tree reduction (log2 D tiny parent compressions) runs
      replicated on every device.

Semantics match the streaming oracle bit-for-bit
(/root/reference/core/src/object/validation/hash.rs full-file checksum,
here computed without any single device ever holding the whole file).

Shard capacity must be a power of two chunks so shard boundaries land on
subtree boundaries; files that fit in a single shard take the ordinary
batched path instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .blake3_batch import CHUNK_LEN, WORDS_PER_CHUNK, tree_reduce
from .blake3_jax import _chunk_cvs_scan

DEFAULT_SHARD_CHUNKS = 64  # 64 KiB per device-shard in tests; tune up on TPU


def _shard_fn(words_local, length, shard_chunks: int):
    """Per-device stage: [cps, 256] chunk words → 8-word subtree top.

    Byte offsets are int32 (x64 stays off): one sharded *call* is bounded
    at 2 GiB; the validator streams larger files through this in 2 GiB
    windows via the counter_base plumbing.
    """
    idx = jax.lax.axis_index("data")
    start = (idx * shard_chunks * CHUNK_LEN).astype(jnp.int32)
    local_len = jnp.clip(length - start, 0, shard_chunks * CHUNK_LEN)
    # Chunk counter base: global chunk index of this shard's first chunk.
    # Carried as (lo, hi) uint32; hi=0 bounds files at 2^32 chunks (4 TiB).
    base_lo = (idx * shard_chunks).astype(jnp.uint32)
    base_hi = jnp.zeros((), jnp.uint32)
    cvs, n = _chunk_cvs_scan(words_local[None], local_len[None],
                             counter_base=(base_lo, base_hi), whole=False)
    top = tree_reduce(jnp, cvs, n, root=False)  # 8 × [1]
    return jnp.stack([w[0] for w in top])  # [8]


@functools.partial(jax.jit, static_argnames=("mesh", "shard_chunks"))
def _sharded_blake3(words, length, n_tops, *, mesh: Mesh,
                    shard_chunks: int):
    """words: [D*cps, 256] uint32 sharded on chunk axis; length: scalar
    int64; n_tops: scalar int32 (shards holding real chunks)."""
    from jax.experimental.shard_map import shard_map

    def inner(words_local):
        top = _shard_fn(words_local, length, shard_chunks)
        tops = jax.lax.all_gather(top, "data")  # [D, 8] replicated
        return tops

    tops = shard_map(
        inner, mesh=mesh,
        in_specs=(P("data", None),),
        out_specs=P(None, None),
        check_rep=False,
    )(words)
    # Top-of-tree: adjacent pairing over shard tops; final merge is ROOT.
    cvs = [tops[:, i][None, :] for i in range(8)]  # 8 × [1, D]
    digest = tree_reduce(jnp, cvs, n_tops[None], root=True)
    return jnp.stack([w[0] for w in digest])  # [8]


def make_sharded_checksum(mesh: Mesh,
                          shard_chunks: int = DEFAULT_SHARD_CHUNKS):
    """Returns fn(data: bytes) -> 32-byte BLAKE3 digest, computed with
    the file's chunk chain sharded across `mesh`'s devices."""
    if shard_chunks & (shard_chunks - 1):
        raise ValueError("shard_chunks must be a power of two")
    D = int(np.prod(mesh.devices.shape))
    capacity = D * shard_chunks * CHUNK_LEN

    def fn(data: bytes) -> bytes:
        n_chunks = max(1, -(-len(data) // CHUNK_LEN))
        if n_chunks <= shard_chunks:
            # Fits one shard: the top stage would need ROOT handling the
            # sharded path deliberately never applies — use the batched
            # single-lane path.
            from .blake3_batch import blake3_batch_np

            return blake3_batch_np([data])[0]
        if len(data) > capacity:
            raise ValueError(
                f"data ({len(data)} B) exceeds mesh capacity "
                f"({capacity} B); raise shard_chunks")
        buf = np.zeros(capacity, dtype=np.uint8)
        buf[:len(data)] = np.frombuffer(data, dtype=np.uint8)
        words = buf.view("<u4").reshape(D * shard_chunks, WORDS_PER_CHUNK)
        sharding = NamedSharding(mesh, P("data", None))
        words_dev = jax.device_put(jnp.asarray(words), sharding)
        n_tops = np.int32(-(-n_chunks // shard_chunks))
        digest = _sharded_blake3(
            words_dev, jnp.asarray(len(data), jnp.int32),
            jnp.asarray(n_tops), mesh=mesh, shard_chunks=shard_chunks)
        return np.asarray(digest).astype("<u4").tobytes()

    return fn


def sharded_file_checksum(mesh: Mesh, path: str,
                          shard_chunks: int = DEFAULT_SHARD_CHUNKS) -> str:
    """Full-file checksum (validator semantics, hash.rs:10-24) with the
    chunk chain sequence-sharded across the mesh. Returns 64-hex digest."""
    with open(path, "rb") as f:
        data = f.read()
    return make_sharded_checksum(mesh, shard_chunks)(data).hex()
