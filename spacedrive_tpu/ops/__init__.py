"""Host and device kernels: BLAKE3 (reference, numpy, JAX, Pallas), CAS
sampling, perceptual hashing, Hamming all-pairs."""
