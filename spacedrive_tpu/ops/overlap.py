"""Double-buffered staging → H2D → kernel pipeline, measured for real.

SURVEY.md §7 hard-part 2 ("feeding the beast"): overlap C++ staging,
host→device copies, and kernel execution so the end-to-end rate is set
by the slowest stage, not their sum. Round 2 reported the steady-state
number as a *formula* (`B / max(t_kernel, t_h2d)`); this module is the
machinery itself, and bench.py now reports its measured rate.

Shape of the pipeline (two batches in flight):

    stager thread:   stage(i+1)          stage(i+2)         ...
    main thread:     put+dispatch(i) ->  put+dispatch(i+1)  ...
    retire:          fetch(i-1) while kernel(i) runs

- staging runs on ONE worker thread calling the native C++ plane
  (pooled pread, GIL released), so it overlaps the device round trip;
- `jax.device_put` + the jitted kernel dispatch are asynchronous — the
  only true sync on the axon platform is the D2H fetch, which is
  deferred one batch so transfer/compute of batch i+1 can proceed
  while batch i's digests stream back.

On a host whose device link is slower than the native plane, the
pipeline's measured rate approaches the link bound (that is the honest
steady state this machinery can deliver there); on a fast-PCIe host the
same code approaches the kernel bound.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class PipelineStats:
    files: int = 0
    wall_s: float = 0.0
    stage_s: float = 0.0      # stall time waiting on the stager thread
    batches: int = 0
    batch_files: int = 0
    # serial reference components, measured on one calibration batch
    # BEFORE the run and once more AFTER it — the tunneled link's
    # weather drifts minute to minute, and round 4's single pre-run
    # calibration produced a "bound" BELOW the measured rate when the
    # link improved mid-run (t_kernel_1 includes the small digest D2H):
    t_stage_1: float = 0.0
    t_h2d_1: float = 0.0
    t_kernel_1: float = 0.0
    t_stage_2: float = 0.0
    t_h2d_2: float = 0.0
    t_kernel_2: float = 0.0

    @property
    def files_per_sec(self) -> float:
        return self.files / self.wall_s if self.wall_s else 0.0

    @property
    def bound_files_per_sec(self) -> float:
        """The max(stage, transfer, kernel+fetch) steady-state bound —
        what a perfect pipeline would sustain under the BEST link
        conditions observed in the bracketing calibrations (per-
        component minimum of the pre/post measurements), so
        bound >= measured holds unless the link beat both brackets
        mid-run."""
        def best(a, b):
            return min(x for x in (a, b) if x > 0) \
                if (a > 0 or b > 0) else 0.0
        denom = max(best(self.t_stage_1, self.t_stage_2),
                    best(self.t_h2d_1, self.t_h2d_2),
                    best(self.t_kernel_1, self.t_kernel_2))
        return self.batch_files / denom if denom else 0.0


def _stage_batch(paths: Sequence[str], sizes: np.ndarray):
    """Native-plane staging of one large-class batch → (words, lengths).

    Falls back to the Python reader when the C++ plane is absent."""
    from . import blake3_jax as bj
    from . import staging

    large, _small, _empty, errors = staging.stage_files(
        list(zip(paths, sizes.tolist())))
    if errors:
        raise OSError(f"staging errors: {list(errors.values())[:3]}")
    return bj.build_cas_messages(large.payloads, large.sizes)


def run_overlapped(
    batches: Sequence[Tuple[Sequence[str], np.ndarray]],
    kernel: Optional[Callable] = None,
) -> Tuple[List[np.ndarray], PipelineStats]:
    """Run the staged pipeline over pre-split file batches.

    batches: [(paths, sizes_u64)] — all large-class (> 100 KiB) files.
    kernel: (words, lengths) -> [B, 8] digests; defaults to the best
        device implementation (Pallas on TPU).
    Returns ([per-batch digests], stats). The returned digests are
    row-aligned with each batch's path order.
    """
    import jax

    from . import blake3_jax as bj

    fn = kernel or (lambda w, l: bj._blake3_impl_best(w, l))
    jfn = jax.jit(fn)
    stats = PipelineStats(batches=len(batches),
                          batch_files=len(batches[0][0]))

    # calibration: one serial batch, component-timed (and the compile).
    # Syncs are FULL fetches of small arrays — a sliced fetch would
    # compile a second program remotely (~tens of seconds through a
    # tunneled device); a tiny marker device_put queued after the big
    # transfer rides the same ordered stream, so fetching it back
    # bounds the transfer.
    def _sync_marker() -> None:
        np.asarray(jax.device_put(np.zeros(16, np.uint8)))

    paths0, sizes0 = batches[0]
    t0 = time.perf_counter()
    words, lengths = _stage_batch(paths0, sizes0)
    stats.t_stage_1 = time.perf_counter() - t0
    w = jax.device_put(words); l = jax.device_put(lengths)
    np.asarray(jfn(w, l))  # compile + warm
    t0 = time.perf_counter()
    w = jax.device_put(words); l = jax.device_put(lengths)
    _sync_marker()
    stats.t_h2d_1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = jfn(w, l)
    res0 = np.asarray(out)  # kernel + the (small) digest D2H
    stats.t_kernel_1 = time.perf_counter() - t0

    pool = ThreadPoolExecutor(1, thread_name_prefix="overlap-stage")
    results: List[Optional[np.ndarray]] = [None] * len(batches)
    results[0] = res0

    t_wall = time.perf_counter()
    fut = None
    if len(batches) > 1:
        fut = pool.submit(_stage_batch, *batches[1])
    inflight: List[Tuple[int, object]] = []
    for i in range(1, len(batches)):
        ts = time.perf_counter()
        words, lengths = fut.result()
        stats.stage_s += time.perf_counter() - ts
        if i + 1 < len(batches):
            fut = pool.submit(_stage_batch, *batches[i + 1])
        w = jax.device_put(words)
        l = jax.device_put(lengths)
        out = jfn(w, l)          # async dispatch
        inflight.append((i, out))
        if len(inflight) > 1:    # retire with one-batch lag
            j, prev = inflight.pop(0)
            results[j] = np.asarray(prev)
    for j, prev in inflight:
        results[j] = np.asarray(prev)
    stats.wall_s = time.perf_counter() - t_wall
    stats.files = sum(len(p) for p, _ in batches[1:])
    pool.shutdown()

    # Post-run calibration bracket: same components, same batch-0 data,
    # measured the moment the pipeline drains — bound_files_per_sec
    # takes the per-component best of the two brackets.
    t0 = time.perf_counter()
    words, lengths = _stage_batch(paths0, sizes0)
    stats.t_stage_2 = time.perf_counter() - t0
    t0 = time.perf_counter()
    w = jax.device_put(words); l = jax.device_put(lengths)
    _sync_marker()
    stats.t_h2d_2 = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.asarray(jfn(w, l))
    stats.t_kernel_2 = time.perf_counter() - t0
    return results, stats


def make_sparse_corpus(root: str, n_files: int, file_size: int,
                       batch: int) -> List[Tuple[List[str], np.ndarray]]:
    """n_files sparse files of `file_size` bytes, split into batches.

    Sparse (truncate-created) files exercise the exact staging path —
    open/pread through the C++ plane — at memory speed, so the pipeline
    measurement reflects staging/transfer/kernel overlap rather than
    the benchmark host's disk. Real-corpus numbers come from
    tools/perf_smoke.py."""
    import os

    os.makedirs(root, exist_ok=True)
    batches = []
    for b0 in range(0, n_files, batch):
        paths = []
        for i in range(b0, min(b0 + batch, n_files)):
            p = os.path.join(root, f"f{i:07d}.bin")
            if not os.path.exists(p):
                with open(p, "wb") as f:
                    f.truncate(file_size)
            paths.append(p)
        sizes = np.full(len(paths), file_size, dtype=np.uint64)
        batches.append((paths, sizes))
    return batches
