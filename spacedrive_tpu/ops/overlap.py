"""Depth-N staging → H2D → kernel → fetch pipeline, measured for real.

SURVEY.md §7 hard-part 2 ("feeding the beast"): overlap C++ staging,
host→device copies, and kernel execution so the end-to-end rate is set
by the slowest stage, not their sum. Rounds 2-12 ran a TWO-batch
double buffer (one stager thread, one batch deferred for retirement);
this is the depth-N generalization that kills the H2D wall head-on:

    stagers (k):      stage(i+1) .. stage(i+k)          concurrent
    device streams:   h2d+dispatch(i) per device,       round-robin
    retire thread:    fetch(i-k+1..)                    one-batch lag

- **depth** (`SDTPU_PIPELINE_DEPTH`, default 3) is the ring-slot
  count: at most `depth` batches are simultaneously in flight from
  stage start to digest retirement. Depth 1 is the fully serial
  reference; depth ≥ 3 hides staging and the kernel under the H2D
  transfer (or vice versa — whichever stage binds).
- **staging** runs `depth` concurrent workers on the shared
  `ops/staging.py` pool (native C++ plane, GIL released), not the old
  single stager thread.
- **hand-off** between stages goes through the PR 12 bounded-channel
  registry: `ops.pipeline.staged` (stagers → dispatchers) and
  `ops.pipeline.inflight` (dispatchers → retirer), block policy under
  the `ops.pipeline.*.put` budgets, each instance narrowed to the
  configured depth — so pipeline backpressure and depth are live
  `sd_chan_*` metrics, and `sd_pipeline_*` adds the stall/bytes/ring
  accounting.
- **donated ring** (`SDTPU_DONATE_BUFFERS`, default on): the kernel
  binds with `donate_argnums=(0, 1)` through the `overlap.kernel`
  contract and passes its inputs through as aliased outputs, so each
  batch's staged device buffers are CONSUMED at dispatch — the
  allocator recycles them for a later batch's H2D instead of pinning
  them until retirement. The undonated path keeps each batch's device
  inputs alive in its in-flight record until its digests retire (the
  conservative re-dispatchable shape), which is exactly the footprint
  difference the donation test pins.
- **devices**: when more than one local device exists (and
  `SDTPU_PIPELINE_DEVICES` does not cap it), in-flight batches
  round-robin across per-device dispatch streams — one committed
  `device_put` + kernel stream per chip, the local half of the
  multi-chip pipeline (the sharded blake3/mesh machinery provides the
  device ring; see parallel/mesh.device_ring).
- **sim-link mode** (`SDTPU_SIM_LINK_GBPS`): every H2D additionally
  sleeps nbytes/rate per device stream, so CPU-only hosts pin the
  overlap math deterministically — measured rate vs the
  max(stage, h2d, kernel) bound at any depth — without TPU hardware.

On a host whose device link is slower than the native plane, the
pipeline's measured rate approaches the link bound (that is the honest
steady state this machinery can deliver there); on a fast-PCIe host the
same code approaches the kernel bound.
"""

from __future__ import annotations

import asyncio
import functools
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import jit_registry
from .. import channels, flags, tracing
from ..flight import RECORDER, new_run_token
from ..telemetry import (
    PIPELINE_DEPTH_HIGH_WATER,
    PIPELINE_DEVICE_BATCHES,
    PIPELINE_DONATED_REUSE,
    PIPELINE_H2D_BYTES,
    PIPELINE_H2D_SECONDS,
    PIPELINE_RETIRE_STALL_SECONDS,
    PIPELINE_STAGE_STALL_SECONDS,
    STAGE_BATCHES,
)

# Must match the declared capacity of the ops.pipeline.* channels —
# depth is clamped here so a run can never exceed the registry ceiling.
MAX_PIPELINE_DEPTH = 8

_DONE = object()  # dispatcher sentinel on the staged channel

# Process-lifetime depth peak backing the sd_pipeline_depth_high_water
# gauge: a later shallow run must not regress it below an earlier deep
# run's peak (same contract as channels._NAME_HIGH_WATER).
_DEPTH_HW = 0


def pipeline_depth() -> int:
    """The configured in-flight batch count, clamped to the declared
    ops.pipeline.* channel capacity. Both pipeline flags are declared
    strict — a malformed value raises out of flags.get rather than
    silently running at a default shape."""
    return max(1, min(int(flags.get("SDTPU_PIPELINE_DEPTH")),
                      MAX_PIPELINE_DEPTH))


def _pipeline_devices() -> tuple:
    from ..parallel.mesh import device_ring

    return device_ring(int(flags.get("SDTPU_PIPELINE_DEVICES")))


@dataclass
class PipelineStats:
    files: int = 0
    wall_s: float = 0.0       # measured loop time, calibration EXCLUDED
    stage_s: float = 0.0      # dispatcher stall waiting on staged batches
    retire_stall_s: float = 0.0  # retirer stall waiting on dispatches
    calibration_s: float = 0.0  # time spent in mid-run calibration pauses
    batches: int = 0
    batch_files: int = 0
    # Pipeline shape of this run (the bound below depends on it).
    depth: int = 2
    n_devices: int = 1
    donate: bool = False
    sim_link_gbps: float = 0.0
    # Transfer + ring accounting (mirrors the sd_pipeline_* families).
    h2d_bytes: int = 0
    h2d_s: float = 0.0
    donated_reuse: int = 0
    depth_high_water: int = 0
    per_device_batches: Dict[str, int] = field(default_factory=dict)
    # Staging backend mix for this run (warmup + calibration +
    # measured batches): packed zero-copy C plane vs the classic
    # stage_files + build_cas_messages pass.
    stage_native_batches: int = 0
    stage_python_batches: int = 0
    # (live device arrays, words consumed, lengths consumed) sampled
    # after each dispatch when run_overlapped(track_buffers=True) —
    # the donation footprint test's probe, off by default.
    buffer_samples: List[Tuple[int, bool, bool]] = field(
        default_factory=list)
    # Serial reference components, measured on calibration batches
    # INTERLEAVED with the run: one before, one after, and one every
    # few batches in between (the stagers pause at a milestone, the
    # pipeline drains productively, the components get timed, the
    # pipeline resumes). Rounds 4 and 5 calibrated outside the
    # measurement window and the tunnel's minute-to-minute weather
    # flipped measured/bound to opposite sides in consecutive
    # artifacts; same-window samples are what make the bound
    # comparable to the measurement at all.
    samples: List[Tuple[float, float, float]] = field(default_factory=list)
    # first/last sample components, kept as flat fields for artifact
    # compatibility (bench JSON, tests).
    t_stage_1: float = 0.0
    t_h2d_1: float = 0.0
    t_kernel_1: float = 0.0
    t_stage_2: float = 0.0
    t_h2d_2: float = 0.0
    t_kernel_2: float = 0.0
    # Guards every multi-writer field (declared guarded_by("_lock") in
    # the threadctx ownership registry): the per-device executor
    # threads mutate h2d_bytes/h2d_s/donated_reuse/buffer_samples —
    # with >1 device stream a plain += is a lost-update race (the PR 8
    # review bug, now the shared-mutation pass's encoded positive) —
    # and the pipeline coroutines mutate the stall/calibration/sample
    # accounting. Critical sections are a few arithmetic ops; no await
    # ever runs under it.
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    @property
    def files_per_sec(self) -> float:
        return self.files / self.wall_s if self.wall_s else 0.0

    @property
    def staging_backend(self) -> str:
        """'native' / 'python' / 'mixed' — which staging plane fed this
        run (mixed means the packed path degraded mid-run: pool
        exhaustion or per-batch flag flips)."""
        if self.stage_native_batches and self.stage_python_batches:
            return "mixed"
        return "native" if self.stage_native_batches else "python"

    def _component_bests(self) -> Tuple[float, float, float]:
        def best(idx: int) -> float:
            vals = [s[idx] for s in self.samples if s[idx] > 0]
            return min(vals) if vals else 0.0
        return best(0), best(1), best(2)

    @property
    def bound_files_per_sec(self) -> float:
        """The depth/device-aware steady-state bound — what a perfect
        pipeline of THIS run's shape would sustain under the BEST
        conditions observed across the same-run interleaved
        calibrations. Staging parallelizes across the `depth`
        concurrent stagers; H2D and the kernel serialize per device
        stream; and the total ring depth caps overall concurrency
        (at depth 1 the bound degenerates to the serial sum — no
        overlap to promise). bound >= measured holds unless the link
        beat every sample between two pauses."""
        t_s, t_h, t_k = self._component_bests()
        n_dev = max(self.n_devices, 1)
        depth = max(self.depth, 1)
        denom = max(t_h / n_dev, t_k / n_dev, t_s / depth,
                    (t_s + t_h + t_k) / depth)
        return self.batch_files / denom if denom else 0.0

    @property
    def bound_spread(self) -> float:
        """max/min ratio of the binding component across calibration
        samples — the same-run measure of how much the link weather
        moved underneath the pipeline (1.0 = perfectly stable)."""
        if not self.samples:
            return 1.0
        which = max(range(3), key=lambda i: self._component_bests()[i])
        vals = [s[which] for s in self.samples if s[which] > 0]
        return max(vals) / min(vals) if vals else 1.0

    def bound_report(self) -> dict:
        """Same-run bound accounting for the bench artifact: measured
        rate, bound, their ratio, and — when measured < 0.9 × bound — a
        printed reason derived from THIS run's calibration spread (the
        round-5 demand: the artifact must meet its bound or explain
        itself from the same run, never from another weather window)."""
        bound = self.bound_files_per_sec
        measured = self.files_per_sec
        ratio = measured / bound if bound else 0.0
        reason = None
        if bound and ratio < 0.9:
            names = ("stage", "h2d", "kernel")
            which = max(range(3),
                        key=lambda i: self._component_bests()[i])
            mid = max(0, len(self.samples) - 2)
            reason = (
                f"bound uses the best of {len(self.samples)} same-run "
                f"calibrations of the binding '{names[which]}' stage, "
                f"which varied {self.bound_spread:.2f}x within this "
                f"run; the measured rate averages over the troughs "
                f"the best sample missed"
                + (f", and {mid} mid-run pause(s) each leave one "
                   f"pipeline refill un-overlapped in the measured wall"
                   if mid else ""))
        return {"measured_files_per_sec": round(measured, 1),
                "bound_files_per_sec": round(bound, 1),
                "ratio": round(ratio, 3),
                "depth": self.depth,
                "devices": self.n_devices,
                "calibrations": len(self.samples),
                "binding_component_spread": round(self.bound_spread, 2),
                "reason": reason}


def _default_kernel(words, lengths):
    """Best device BLAKE3 body (Pallas on TPU, jnp scan elsewhere) — a
    module-level def, not a per-call lambda, so `_jitted` caches ONE
    compiled program across run_overlapped invocations."""
    from . import blake3_jax as bj

    return bj._blake3_impl_best(words, lengths)


@functools.lru_cache(maxsize=16)
def _jitted(fn: Callable, donate: bool = False):
    """Module-cached jit per (kernel fn, donate) — the round-10
    jit-stability fix (the old call-time `jax.jit(fn)` paid a fresh
    trace per invocation) plus the ring binding: the donated variant
    consumes its (words, lengths) inputs under the `overlap.kernel`
    contract's declared donate_argnums and passes them through as
    aliased outputs, so the staged device buffers are recycled at
    kernel completion instead of surviving until digest retirement."""
    import jax

    if donate:
        def _donating(words, lengths):
            return fn(words, lengths), words, lengths

        jf = jax.jit(_donating, donate_argnums=(0, 1))
    else:
        jf = jax.jit(fn)
    return jit_registry.tracked("overlap.kernel")(jf)


def _retire(x) -> np.ndarray:
    """Declared D2H fetch: digest retirement / calibration sync (the
    pipeline's sanctioned host-transfer points, contract
    overlap.retire)."""
    with jit_registry.io("overlap.retire"):
        return np.asarray(x)


def _stage_batch(paths: Sequence[str], sizes: np.ndarray,
                 stats: Optional[PipelineStats] = None):
    """Stage one large-class batch → (words, lengths, lease).

    Preferred path: packed zero-copy staging (staging.stage_batch_native
    writes the kernel's message layout straight into a pooled page; the
    returned lease IS that page and the caller must release it at batch
    retirement — the ring-recycling point). Falls back to the classic
    stage_files + build_cas_messages copy pass (lease None) whenever
    the packed path declines: SDTPU_STAGE_NATIVE off, libsdio.so
    absent, pool exhausted, or off-contract rows (empty files) in the
    batch."""
    from . import blake3_jax as bj
    from . import staging

    files = list(zip(paths, sizes.tolist()))
    staged = staging.stage_batch_native(files)
    if staged is not None:
        if staged.errors:
            errs = list(staged.errors.values())[:3]
            staged.release()
            raise OSError(f"staging errors: {errs}")
        if not staged.empty_rows:
            if stats is not None:
                with stats._lock:
                    stats.stage_native_batches += 1
            return staged.words, staged.lengths, staged.lease
        staged.release()  # empty rows: the classic path's class split
    large, _small, _empty, errors = staging.stage_files(files)
    if errors:
        raise OSError(f"staging errors: {list(errors.values())[:3]}")
    STAGE_BATCHES.labels(backend="python").inc()
    if stats is not None:
        with stats._lock:
            stats.stage_python_batches += 1
    words, lengths = bj.build_cas_messages(large.payloads, large.sizes)
    return words, lengths, None


def _h2d(words, lengths, dev, stats: Optional[PipelineStats] = None):
    """One batch's host→device transfer onto `dev`, plus the simulated
    per-stream link delay when SDTPU_SIM_LINK_GBPS pins a rate. Runs
    on the per-device dispatch thread (or the calibration thread) —
    never on the pipeline's event loop."""
    import jax

    nbytes = int(words.nbytes + lengths.nbytes)
    t0 = time.perf_counter()
    w = jax.device_put(words, dev)
    l = jax.device_put(lengths, dev)
    gbps = flags.get("SDTPU_SIM_LINK_GBPS")
    if gbps:
        time.sleep(nbytes / (gbps * 1e9))
    dt = time.perf_counter() - t0
    PIPELINE_H2D_BYTES.inc(nbytes)
    PIPELINE_H2D_SECONDS.inc(dt)
    if stats is not None:
        with stats._lock:
            stats.h2d_bytes += nbytes
            stats.h2d_s += dt
    return w, l


def _dispatch_kernel(jfn, w, l, donate: bool,
                     stats: Optional[PipelineStats] = None):
    """Dispatch one batch; returns (digests, keepalive).

    Donated path: the kernel CONSUMES w/l (they are invalid after this
    call) and the pass-through aliases are dropped on the floor, so the
    buffers return to the allocator the moment the execution finishes —
    recycled ring slots for a later batch's H2D. Undonated path: w/l
    ride in the in-flight record until the digests retire (the batch
    stays re-dispatchable, at the cost of depth × batch-bytes of pinned
    device memory — the footprint donation removes)."""
    if donate:
        out, _ring_w, _ring_l = jfn(w, l)
        PIPELINE_DONATED_REUSE.inc(2)
        if stats is not None:
            with stats._lock:
                stats.donated_reuse += 2
        return out, ()
    return jfn(w, l), (w, l)


def _transfer_and_dispatch(jfn, words, lengths, dev, donate: bool,
                           stats: PipelineStats, track_buffers: bool,
                           batch_idx: Optional[int] = None,
                           stream: int = 0, label: str = "",
                           trace: Optional[str] = None,
                           run: Optional[int] = None):
    """Per-device stream body (executor thread): H2D + kernel dispatch.

    With a batch_idx (the measured pipeline loop; calibration passes
    None) the flight recorder gets one `h2d` and one `kernel` timeline
    event per batch. The kernel lane times the DISPATCH wall — on an
    async backend completion lands in the batch's `retire` lane; on
    the CPU/sim-link paths tier-1 pins, dispatch is effectively the
    execution."""
    t0 = time.perf_counter()
    w, l = _h2d(words, lengths, dev, stats)
    t1 = time.perf_counter()
    out, keep = _dispatch_kernel(jfn, w, l, donate, stats)
    if batch_idx is not None:
        t2 = time.perf_counter()
        RECORDER.record("h2d", batch=batch_idx, t0=t0, t1=t1,
                        device=label, stream=stream, trace=trace,
                        run=run)
        RECORDER.record("kernel", batch=batch_idx, t0=t1, t1=t2,
                        device=label, stream=stream, trace=trace,
                        run=run)
    if track_buffers:
        import gc

        import jax

        # Debug-only probe: collect first so the count reflects buffers
        # the PIPELINE holds (ring slots, in-flight records), not
        # asyncio future/frame cycles awaiting generational GC. Only
        # staging-CLASS buffers count (nbytes >= this batch's words
        # array): the [B, 8] digests legitimately accumulate — on CPU
        # the retired numpy views share their device buffers — while
        # the staged words/lengths are exactly what donation recycles.
        gc.collect()
        threshold = words.nbytes
        live = sum(1 for a in jax.live_arrays()
                   if a.nbytes >= threshold)
        with stats._lock:
            stats.buffer_samples.append((
                live, bool(w.is_deleted()), bool(l.is_deleted())))
    return out, keep


def run_overlapped(
    batches: Sequence[Tuple[Sequence[str], np.ndarray]],
    kernel: Optional[Callable] = None,
    calibrate_every: Optional[int] = None,
    *,
    depth: Optional[int] = None,
    devices: Optional[Sequence] = None,
    donate: Optional[bool] = None,
    track_buffers: bool = False,
) -> Tuple[List[np.ndarray], PipelineStats]:
    """Run the depth-N pipeline over pre-split file batches.

    batches: [(paths, sizes_u64)] — all large-class (> 100 KiB) files.
    kernel: (words, lengths) -> [B, 8] digests; defaults to the best
        device implementation (Pallas on TPU).
    calibrate_every: pause staging and re-time the serial components
        every this many measured batches (default: ~2 mid-run pauses),
        so the steady-state bound is computed from the SAME weather
        window as the measurement. The pause is DEPTH-AWARE: the
        stagers stop at the milestone, the in-flight batches drain
        productively (their retirement stays in the measured wall —
        it is real throughput), and only the serial component timing
        itself is excluded from wall_s, so a pause costs the same at
        depth 8 as at depth 1.
    depth / devices / donate: override the SDTPU_PIPELINE_DEPTH /
        SDTPU_PIPELINE_DEVICES / SDTPU_DONATE_BUFFERS flags for this
        run (tests, benches).
    track_buffers: sample (live device arrays, inputs consumed) after
        every dispatch into stats.buffer_samples — the donation
        footprint probe.
    Returns ([per-batch digests], stats). The returned digests are
    row-aligned with each batch's path order.

    The whole run executes inside a `pipeline.run` span, and every
    measured batch's stage/H2D/kernel/retire phases land in the flight
    recorder (spacedrive_tpu/flight.py) stamped with that span's trace
    id — a caller already inside a trace (the identifier job) gets the
    pipeline timeline attached to its own trace.
    """
    with tracing.span("pipeline.run", batches=len(batches)):
        return _run_overlapped_impl(
            batches, kernel, calibrate_every, depth=depth,
            devices=devices, donate=donate, track_buffers=track_buffers)


def _run_overlapped_impl(
    batches: Sequence[Tuple[Sequence[str], np.ndarray]],
    kernel: Optional[Callable] = None,
    calibrate_every: Optional[int] = None,
    *,
    depth: Optional[int] = None,
    devices: Optional[Sequence] = None,
    donate: Optional[bool] = None,
    track_buffers: bool = False,
) -> Tuple[List[np.ndarray], PipelineStats]:
    import jax

    if donate is None:
        donate = bool(flags.get("SDTPU_DONATE_BUFFERS"))
    if depth is None:
        depth = pipeline_depth()
    depth = max(1, min(int(depth), MAX_PIPELINE_DEPTH))
    devs = tuple(devices) if devices else _pipeline_devices()
    try:
        sim_gbps = float(flags.get("SDTPU_SIM_LINK_GBPS") or 0.0)
    except (TypeError, ValueError):
        sim_gbps = 0.0

    jfn = _jitted(kernel or _default_kernel, bool(donate))
    stats = PipelineStats(batches=len(batches),
                          batch_files=len(batches[0][0]),
                          depth=depth, n_devices=len(devs),
                          donate=bool(donate), sim_link_gbps=sim_gbps)
    if calibrate_every is None:
        calibrate_every = max(2, (len(batches) - 1) // 3)

    # calibration: one serial batch, component-timed (and the compile).
    # Syncs are FULL fetches of small arrays — a sliced fetch would
    # compile a second program remotely (~tens of seconds through a
    # tunneled device); a tiny marker device_put queued after the big
    # transfer rides the same ordered stream, so fetching it back
    # bounds the transfer.
    def _sync_marker() -> None:
        _retire(jax.device_put(np.zeros(16, np.uint8), devs[0]))

    paths0, sizes0 = batches[0]

    def _calibrate() -> Tuple[float, float, float, np.ndarray]:
        t0 = time.perf_counter()
        words, lengths, lease = _stage_batch(paths0, sizes0, stats)
        t_stage = time.perf_counter() - t0
        t0 = time.perf_counter()
        w, l = _h2d(words, lengths, devs[0])
        _sync_marker()
        t_h2d = time.perf_counter() - t0
        t0 = time.perf_counter()
        out, _keep = _dispatch_kernel(jfn, w, l, donate)
        res = _retire(out)  # kernel + the (small) digest D2H
        t_kernel = time.perf_counter() - t0
        if lease is not None:
            lease.release()  # retire point: the kernel consumed it
        return t_stage, t_h2d, t_kernel, res

    # Warm the compile on batch 0 before the first timed sample.
    words, lengths, lease = _stage_batch(paths0, sizes0, stats)
    out, _keep = _dispatch_kernel(jfn, *_h2d(words, lengths, devs[0]),
                                  donate)
    _retire(out)
    if lease is not None:
        lease.release()
    s0 = _calibrate()
    with stats._lock:
        stats.samples.append(s0[:3])
    results: List[Optional[np.ndarray]] = [None] * len(batches)
    results[0] = s0[3]

    if len(batches) > 1:
        _run_pipeline(batches, jfn, devs, depth, bool(donate), stats,
                      results, calibrate_every, _calibrate,
                      track_buffers, tracing.current_trace_id())
    stats.files = sum(len(p) for p, _ in batches[1:])

    # Post-run sample: same components, same batch-0 data, measured the
    # moment the pipeline drains — the closing bracket of the same-run
    # series.
    closing = _calibrate()[:3]
    with stats._lock:
        stats.samples.append(closing)
    (stats.t_stage_1, stats.t_h2d_1, stats.t_kernel_1) = stats.samples[0]
    (stats.t_stage_2, stats.t_h2d_2, stats.t_kernel_2) = stats.samples[-1]
    return results, stats


def _run_pipeline(batches, jfn, devs, depth: int, donate: bool,
                  stats: PipelineStats, results,
                  calibrate_every: int, calibrate: Callable,
                  track_buffers: bool,
                  trace: Optional[str] = None) -> None:
    """The measured depth-N loop over batches[1:]. Runs a private event
    loop (run_overlapped is a synchronous API called from benches and
    job worker threads) whose coroutines only coordinate — staging,
    H2D+dispatch, and the D2H fetch all run on dedicated executor
    threads, so nothing blocks the loop and the sanitizer's stall
    detector stays quiet."""
    from . import staging

    n = len(batches)
    n_stagers = min(depth, n - 1)
    # Disambiguates THIS run's batch windows in the process recorder:
    # two runs (concurrent jobs, or back-to-back in one trace) both
    # dispatch a "batch 3", and the bound attribution must never mix
    # their phases.
    run_token = new_run_token()
    # Calibration milestones: after retiring batch m (1-indexed count),
    # pause staging and re-time the serial components — same cadence as
    # the old double-buffer ((i-1) % calibrate_every == 0 with room for
    # at least one post-pause batch).
    milestones = [m for m in range(calibrate_every + 1, n - 1,
                                   calibrate_every)]
    clock = {"start": 0.0}

    async def main() -> None:
        loop = asyncio.get_running_loop()
        staged = channels.channel("ops.pipeline.staged",
                                  capacity_cap=depth)
        inflight = channels.channel("ops.pipeline.inflight",
                                    capacity_cap=depth)
        # depth tickets bound TOTAL in-flight batches (stage start →
        # digest retired); the two channels bound (and meter) each
        # hand-off edge within that.
        tickets = asyncio.Semaphore(depth)
        state = {"next": 1, "in_flight": 0, "retired": 0,
                 "limit": milestones[0] if milestones else n,
                 "pending": list(milestones)}
        resume = asyncio.Event()
        resume.set()

        stage_pool = staging.stage_pool()
        dev_pools = [
            ThreadPoolExecutor(1, thread_name_prefix=f"sdtpu-pipe-dev{d}")
            for d in range(len(devs))]
        retire_pool = ThreadPoolExecutor(
            1, thread_name_prefix="sdtpu-pipe-retire")

        async def stager(w: int) -> None:
            while True:
                i = state["next"]
                if i >= n:
                    return
                if i > state["limit"]:
                    # A calibration is pending at the limit: hold this
                    # slot until the retirer finishes it. Re-check on
                    # wake — the limit may still be behind i.
                    resume.clear()
                    await resume.wait()
                    continue
                state["next"] = i + 1
                await tickets.acquire()
                state["in_flight"] += 1
                if state["in_flight"] > stats.depth_high_water:
                    with stats._lock:
                        stats.depth_high_water = max(
                            stats.depth_high_water, state["in_flight"])
                    global _DEPTH_HW
                    if stats.depth_high_water > _DEPTH_HW:
                        _DEPTH_HW = stats.depth_high_water
                        PIPELINE_DEPTH_HIGH_WATER.set(_DEPTH_HW)
                t0 = time.perf_counter()
                words, lengths, lease = await loop.run_in_executor(
                    stage_pool, _stage_batch, batches[i][0],
                    batches[i][1], stats)
                # Stage lane: this batch's staging wall as the
                # pipeline saw it (executor queue wait included — that
                # wait IS stage-side contention).
                RECORDER.record("stage", batch=i, t0=t0,
                                t1=time.perf_counter(), stream=w,
                                trace=trace, run=run_token)
                await staged.put((i, words, lengths, lease))

        async def feed() -> None:
            await asyncio.gather(*(stager(w) for w in range(n_stagers)))
            for _ in devs:
                await staged.put((_DONE, None, None, None))

        async def dispatcher(d: int) -> None:
            dev = devs[d]
            label = str(getattr(dev, "id", d))
            while True:
                t0 = time.perf_counter()
                c0 = stats.calibration_s
                i, words, lengths, lease = await staged.get()
                # Subtract any calibration pause that completed during
                # this wait: at a milestone every dispatcher idles in
                # staged.get() BY DESIGN (stagers hold, pipeline
                # drains) — that time is already calibration_s, and
                # counting it here too would misattribute the pause to
                # a staging bottleneck in the stall breakdown.
                # calibration_s only mutates in the retirer coroutine
                # on this same loop thread, so the delta is race-free.
                wait = max(0.0, time.perf_counter() - t0
                           - (stats.calibration_s - c0))
                with stats._lock:
                    stats.stage_s += wait
                PIPELINE_STAGE_STALL_SECONDS.inc(wait)
                if i is _DONE:
                    return
                out, keep = await loop.run_in_executor(
                    dev_pools[d], _transfer_and_dispatch, jfn, words,
                    lengths, dev, donate, stats, track_buffers,
                    i, d, label, trace, run_token)
                with stats._lock:
                    stats.per_device_batches[label] = (
                        stats.per_device_batches.get(label, 0) + 1)
                PIPELINE_DEVICE_BATCHES.labels(device=label).inc()
                await inflight.put((i, out, keep, lease))

        async def retirer() -> None:
            while state["retired"] < n - 1:
                t0 = time.perf_counter()
                i, out, keep, lease = await inflight.get()
                wait = time.perf_counter() - t0
                with stats._lock:
                    stats.retire_stall_s += wait
                PIPELINE_RETIRE_STALL_SECONDS.inc(wait)
                t0r = time.perf_counter()
                results[i] = await loop.run_in_executor(
                    retire_pool, _retire, out)
                # Retire lane; the recorder closes batch i's window
                # here and emits its bound-attribution event.
                RECORDER.record("retire", batch=i, t0=t0r,
                                t1=time.perf_counter(), trace=trace,
                                run=run_token)
                del keep  # undonated: device inputs released at retire
                if lease is not None:
                    # Pool recycling point: retirement guarantees the
                    # kernel consumed this batch's staged page (even on
                    # backends where device_put aliases host memory),
                    # so the page may be rewritten by a later batch.
                    lease.release()
                state["retired"] += 1
                state["in_flight"] -= 1
                tickets.release()
                if state["pending"] \
                        and state["retired"] == state["pending"][0]:
                    # Depth-aware calibration pause: the stagers already
                    # stopped at the limit, the drain above was ordinary
                    # (in-wall, productive) retirement — only the serial
                    # component timing itself is excluded from the
                    # measured wall, so the pause cost does not scale
                    # with depth. Residual bias: the post-pause refill
                    # (one pipeline fill with nothing in flight to hide
                    # under) stays in the wall — a small conservative
                    # tax surfaced via `calibrations` in the report.
                    state["pending"].pop(0)
                    t_pause = time.perf_counter()
                    sample = await loop.run_in_executor(
                        retire_pool, calibrate)
                    pause = time.perf_counter() - t_pause
                    with stats._lock:
                        stats.samples.append(sample[:3])
                        stats.calibration_s += pause
                    clock["start"] += pause  # shift the wall past it
                    state["limit"] = (state["pending"][0]
                                      if state["pending"] else n)
                    resume.set()

        try:
            await asyncio.gather(
                feed(), *(dispatcher(d) for d in range(len(devs))),
                retirer())
        finally:
            for pool in dev_pools:
                pool.shutdown(wait=True)
            retire_pool.shutdown(wait=True)

    clock["start"] = time.perf_counter()
    asyncio.run(main())
    stats.wall_s = time.perf_counter() - clock["start"]


def make_sparse_corpus(root: str, n_files: int, file_size: int,
                       batch: int) -> List[Tuple[List[str], np.ndarray]]:
    """n_files sparse files of `file_size` bytes, split into batches.

    Sparse (truncate-created) files exercise the exact staging path —
    open/pread through the C++ plane — at memory speed, so the pipeline
    measurement reflects staging/transfer/kernel overlap rather than
    the benchmark host's disk. Real-corpus numbers come from
    tools/perf_smoke.py."""
    import os

    os.makedirs(root, exist_ok=True)
    batches = []
    for b0 in range(0, n_files, batch):
        paths = []
        for i in range(b0, min(b0 + batch, n_files)):
            p = os.path.join(root, f"f{i:07d}.bin")
            if not os.path.exists(p):
                # Bench corpus filler (sparse truncate, no payload):
                # scratch content, regenerated on demand.
                # sdlint: ok[io-durability]
                with open(p, "wb") as f:
                    f.truncate(file_size)
            paths.append(p)
        sizes = np.full(len(paths), file_size, dtype=np.uint64)
        batches.append((paths, sizes))
    return batches
