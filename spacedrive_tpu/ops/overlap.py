"""Double-buffered staging → H2D → kernel pipeline, measured for real.

SURVEY.md §7 hard-part 2 ("feeding the beast"): overlap C++ staging,
host→device copies, and kernel execution so the end-to-end rate is set
by the slowest stage, not their sum. Round 2 reported the steady-state
number as a *formula* (`B / max(t_kernel, t_h2d)`); this module is the
machinery itself, and bench.py now reports its measured rate.

Shape of the pipeline (two batches in flight):

    stager thread:   stage(i+1)          stage(i+2)         ...
    main thread:     put+dispatch(i) ->  put+dispatch(i+1)  ...
    retire:          fetch(i-1) while kernel(i) runs

- staging runs on ONE worker thread calling the native C++ plane
  (pooled pread, GIL released), so it overlaps the device round trip;
- `jax.device_put` + the jitted kernel dispatch are asynchronous — the
  only true sync on the axon platform is the D2H fetch, which is
  deferred one batch so transfer/compute of batch i+1 can proceed
  while batch i's digests stream back.

On a host whose device link is slower than the native plane, the
pipeline's measured rate approaches the link bound (that is the honest
steady state this machinery can deliver there); on a fast-PCIe host the
same code approaches the kernel bound.
"""

from __future__ import annotations

import functools
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from . import jit_registry


@dataclass
class PipelineStats:
    files: int = 0
    wall_s: float = 0.0       # measured loop time, calibration EXCLUDED
    stage_s: float = 0.0      # stall time waiting on the stager thread
    calibration_s: float = 0.0  # time spent in mid-run calibration pauses
    batches: int = 0
    batch_files: int = 0
    # Serial reference components, measured on calibration batches
    # INTERLEAVED with the run: one before, one after, and one every
    # few batches in between (the pipeline drains, the components get
    # timed, the pipeline resumes). Rounds 4 and 5 calibrated outside
    # the measurement window and the tunnel's minute-to-minute weather
    # flipped measured/bound to opposite sides in consecutive
    # artifacts; same-window samples are what make the bound
    # comparable to the measurement at all.
    samples: List[Tuple[float, float, float]] = field(default_factory=list)
    # first/last sample components, kept as flat fields for artifact
    # compatibility (bench JSON, tests).
    t_stage_1: float = 0.0
    t_h2d_1: float = 0.0
    t_kernel_1: float = 0.0
    t_stage_2: float = 0.0
    t_h2d_2: float = 0.0
    t_kernel_2: float = 0.0

    @property
    def files_per_sec(self) -> float:
        return self.files / self.wall_s if self.wall_s else 0.0

    def _component_bests(self) -> Tuple[float, float, float]:
        def best(idx: int) -> float:
            vals = [s[idx] for s in self.samples if s[idx] > 0]
            return min(vals) if vals else 0.0
        return best(0), best(1), best(2)

    @property
    def bound_files_per_sec(self) -> float:
        """The max(stage, transfer, kernel+fetch) steady-state bound —
        what a perfect pipeline would sustain under the BEST link
        conditions observed across the same-run interleaved
        calibrations, so bound >= measured holds unless the link beat
        every sample between two pauses."""
        denom = max(self._component_bests())
        return self.batch_files / denom if denom else 0.0

    @property
    def bound_spread(self) -> float:
        """max/min ratio of the binding component across calibration
        samples — the same-run measure of how much the link weather
        moved underneath the pipeline (1.0 = perfectly stable)."""
        if not self.samples:
            return 1.0
        which = max(range(3), key=lambda i: self._component_bests()[i])
        vals = [s[which] for s in self.samples if s[which] > 0]
        return max(vals) / min(vals) if vals else 1.0

    def bound_report(self) -> dict:
        """Same-run bound accounting for the bench artifact: measured
        rate, bound, their ratio, and — when measured < 0.9 × bound — a
        printed reason derived from THIS run's calibration spread (the
        round-5 demand: the artifact must meet its bound or explain
        itself from the same run, never from another weather window)."""
        bound = self.bound_files_per_sec
        measured = self.files_per_sec
        ratio = measured / bound if bound else 0.0
        reason = None
        if bound and ratio < 0.9:
            names = ("stage", "h2d", "kernel")
            which = max(range(3),
                        key=lambda i: self._component_bests()[i])
            mid = max(0, len(self.samples) - 2)
            reason = (
                f"bound uses the best of {len(self.samples)} same-run "
                f"calibrations of the binding '{names[which]}' stage, "
                f"which varied {self.bound_spread:.2f}x within this "
                f"run; the measured rate averages over the troughs "
                f"the best sample missed"
                + (f", and {mid} mid-run pause(s) each leave up to one "
                   f"un-overlapped batch refill in the measured wall"
                   if mid else ""))
        return {"measured_files_per_sec": round(measured, 1),
                "bound_files_per_sec": round(bound, 1),
                "ratio": round(ratio, 3),
                "calibrations": len(self.samples),
                "binding_component_spread": round(self.bound_spread, 2),
                "reason": reason}


def _default_kernel(words, lengths):
    """Best device BLAKE3 body (Pallas on TPU, jnp scan elsewhere) — a
    module-level def, not a per-call lambda, so `_jitted` caches ONE
    compiled program across run_overlapped invocations."""
    from . import blake3_jax as bj

    return bj._blake3_impl_best(words, lengths)


@functools.lru_cache(maxsize=8)
def _jitted(fn: Callable):
    """Module-cached jit per kernel fn — the round-10 jit-stability
    fix: the old call-time `jax.jit(fn)` inside run_overlapped built a
    fresh jit wrapper (and paid a fresh trace, ~10 s on TPU) on every
    invocation, so each calibration pause recompiled a program the
    previous run already owned."""
    import jax

    return jit_registry.tracked("overlap.kernel")(jax.jit(fn))


def _retire(x) -> np.ndarray:
    """Declared D2H fetch: digest retirement / calibration sync (the
    pipeline's sanctioned host-transfer points, contract
    overlap.retire)."""
    with jit_registry.io("overlap.retire"):
        return np.asarray(x)


def _stage_batch(paths: Sequence[str], sizes: np.ndarray):
    """Native-plane staging of one large-class batch → (words, lengths).

    Falls back to the Python reader when the C++ plane is absent."""
    from . import blake3_jax as bj
    from . import staging

    large, _small, _empty, errors = staging.stage_files(
        list(zip(paths, sizes.tolist())))
    if errors:
        raise OSError(f"staging errors: {list(errors.values())[:3]}")
    return bj.build_cas_messages(large.payloads, large.sizes)


def run_overlapped(
    batches: Sequence[Tuple[Sequence[str], np.ndarray]],
    kernel: Optional[Callable] = None,
    calibrate_every: Optional[int] = None,
) -> Tuple[List[np.ndarray], PipelineStats]:
    """Run the staged pipeline over pre-split file batches.

    batches: [(paths, sizes_u64)] — all large-class (> 100 KiB) files.
    kernel: (words, lengths) -> [B, 8] digests; defaults to the best
        device implementation (Pallas on TPU).
    calibrate_every: drain the pipeline and re-time the serial
        components every this many measured batches (default: ~2 mid-
        run pauses), so the steady-state bound is computed from the
        SAME weather window as the measurement — calibrating only
        outside the run let the tunnel's drift flip measured/bound to
        opposite sides in consecutive round artifacts. Calibration
        pauses are excluded from the measured wall time.
    Returns ([per-batch digests], stats). The returned digests are
    row-aligned with each batch's path order.
    """
    import jax

    jfn = _jitted(kernel or _default_kernel)
    stats = PipelineStats(batches=len(batches),
                          batch_files=len(batches[0][0]))
    if calibrate_every is None:
        calibrate_every = max(2, (len(batches) - 1) // 3)

    # calibration: one serial batch, component-timed (and the compile).
    # Syncs are FULL fetches of small arrays — a sliced fetch would
    # compile a second program remotely (~tens of seconds through a
    # tunneled device); a tiny marker device_put queued after the big
    # transfer rides the same ordered stream, so fetching it back
    # bounds the transfer.
    def _sync_marker() -> None:
        _retire(jax.device_put(np.zeros(16, np.uint8)))

    paths0, sizes0 = batches[0]

    def _calibrate() -> Tuple[float, float, float, np.ndarray]:
        t0 = time.perf_counter()
        words, lengths = _stage_batch(paths0, sizes0)
        t_stage = time.perf_counter() - t0
        t0 = time.perf_counter()
        w = jax.device_put(words); l = jax.device_put(lengths)
        _sync_marker()
        t_h2d = time.perf_counter() - t0
        t0 = time.perf_counter()
        res = _retire(jfn(w, l))  # kernel + the (small) digest D2H
        t_kernel = time.perf_counter() - t0
        return t_stage, t_h2d, t_kernel, res

    # Warm the compile on batch 0 before the first timed sample.
    words, lengths = _stage_batch(paths0, sizes0)
    _retire(jfn(jax.device_put(words), jax.device_put(lengths)))
    s0 = _calibrate()
    stats.samples.append(s0[:3])
    res0 = s0[3]

    pool = ThreadPoolExecutor(1, thread_name_prefix="overlap-stage")
    results: List[Optional[np.ndarray]] = [None] * len(batches)
    results[0] = res0

    t_wall = time.perf_counter()
    fut = None
    if len(batches) > 1:
        fut = pool.submit(_stage_batch, *batches[1])
    inflight: List[Tuple[int, object]] = []
    for i in range(1, len(batches)):
        ts = time.perf_counter()
        words, lengths = fut.result()
        stats.stage_s += time.perf_counter() - ts
        if (i - 1) and (i - 1) % calibrate_every == 0 \
                and i + 1 < len(batches):
            # Mid-run calibration: the stager is idle (its result is in
            # hand, the next submit hasn't happened), so drain the
            # in-flight dispatches and time the serial components in
            # the exact weather the pipeline is running through. The
            # whole pause window — drain INCLUDED, since the forced
            # early retire is overlap the pipeline loses to the pause —
            # is excluded from the measured wall. Residual bias: the
            # post-pause refill (one batch staged/dispatched with
            # nothing in flight to hide under) stays in the wall, so
            # each pause costs up to ~one un-overlapped batch; with the
            # default ~2 pauses that is a small conservative tax on the
            # measured rate, surfaced via `calibrations` in the report.
            t_pause = time.perf_counter()
            for j, prev in inflight:
                results[j] = _retire(prev)
            inflight.clear()
            stats.samples.append(_calibrate()[:3])
            pause = time.perf_counter() - t_pause
            stats.calibration_s += pause
            t_wall += pause  # shift the wall clock past the pause
        if i + 1 < len(batches):
            fut = pool.submit(_stage_batch, *batches[i + 1])
        w = jax.device_put(words)
        l = jax.device_put(lengths)
        out = jfn(w, l)          # async dispatch
        inflight.append((i, out))
        if len(inflight) > 1:    # retire with one-batch lag
            j, prev = inflight.pop(0)
            results[j] = _retire(prev)
    for j, prev in inflight:
        results[j] = _retire(prev)
    stats.wall_s = time.perf_counter() - t_wall
    stats.files = sum(len(p) for p, _ in batches[1:])
    pool.shutdown()

    # Post-run sample: same components, same batch-0 data, measured the
    # moment the pipeline drains — the closing bracket of the same-run
    # series.
    stats.samples.append(_calibrate()[:3])
    (stats.t_stage_1, stats.t_h2d_1, stats.t_kernel_1) = stats.samples[0]
    (stats.t_stage_2, stats.t_h2d_2, stats.t_kernel_2) = stats.samples[-1]
    return results, stats


def make_sparse_corpus(root: str, n_files: int, file_size: int,
                       batch: int) -> List[Tuple[List[str], np.ndarray]]:
    """n_files sparse files of `file_size` bytes, split into batches.

    Sparse (truncate-created) files exercise the exact staging path —
    open/pread through the C++ plane — at memory speed, so the pipeline
    measurement reflects staging/transfer/kernel overlap rather than
    the benchmark host's disk. Real-corpus numbers come from
    tools/perf_smoke.py."""
    import os

    os.makedirs(root, exist_ok=True)
    batches = []
    for b0 in range(0, n_files, batch):
        paths = []
        for i in range(b0, min(b0 + batch, n_files)):
            p = os.path.join(root, f"f{i:07d}.bin")
            if not os.path.exists(p):
                with open(p, "wb") as f:
                    f.truncate(file_size)
            paths.append(p)
        sizes = np.full(len(paths), file_size, dtype=np.uint64)
        batches.append((paths, sizes))
    return batches
