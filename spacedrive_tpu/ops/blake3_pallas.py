"""Pallas TPU kernel for the BLAKE3 chunk stage.

Layout story (what round 1 got wrong and round 2 fixed): a "lane" is one
chunk of one file, and the compression function wants message word j of
block k as a contiguous [S, 128] vector — i.e. word-major data. Round 1
transposed the whole [B·C, 256] grid to word-major OUTSIDE the kernel
with an XLA transpose; that composition forced a 119 MB relayout through
HBM on every call and lost to the jnp path. This version streams the
natural chunk-major layout into VMEM (one contiguous 1 MiB block per
grid step) and transposes each tile IN the kernel — the [1024, 256]
transpose happens in VMEM at register speed, overlapped with the next
tile's DMA by the Pallas grid pipeline.

Inside a tile the whole 16-word state lives in vector registers over a
[8, 128] lane tile; the 16 block compressions × 7 rounds are fully
unrolled with a static message-index schedule, so there is zero data
movement per round. Two kernels share that body:

- `_chunk_kernel_meta` (the hot path, whole messages from counter 0 —
  every CAS call): per-lane chunk metadata is derived IN-KERNEL from
  two int32 planes (file length, chunk index); per-block metadata
  comes from the shared `block_meta` helper the numpy/jnp backends
  use. Two planes instead of six measured ~1.5× the six-plane kernel.
- `_chunk_kernel` (streaming windows: counter_base ≠ 0 / whole=False):
  all six per-lane planes precomputed by the shared `chunk_prelude`.

Measured on the (shared) bench v5e-1 chip with executions chained
inside one jit (tools/perf_probe.py — per-call timing measures tunnel
RPC latency): the chip adds ~7-10 ms of per-dispatch overhead under
load, so throughput scales with batch: ~0.3-0.5M files/s at 2048
files/batch, ~1.25M files/s (71.7 GB/s hashed) at 16384 — against
~61k files/s (3.5 GB/s) for the repo's AVX2 C++ plane on the host CPU.
Production (ops/staging.py "jax" backend) routes through
blake3_jax.blake3_words, which dispatches here whenever the default
backend is a TPU.

The tree reduction stays in jnp (blake3_batch.tree_reduce): it is
≤ 1/16th of the chunk-stage work, and folding it in-kernel measured
slower (padding C to a power of two costs more than the jnp tree).

Reference semantics: the blake3 crate as driven by
/root/reference/core/src/object/cas.rs:23-62 and
core/src/object/validation/hash.rs:10-24.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .blake3_ref import (
    BLOCK_LEN,
    CHUNK_END,
    CHUNK_START,
    IV,
    MSG_PERMUTATION,
    ROOT,
)
from .blake3_batch import BLOCKS_PER_CHUNK, WORDS_PER_BLOCK, chunk_prelude
from . import jit_registry

# Lane tile: 8 sublanes × 128 lanes of uint32 (one native VREG of
# chunks). Each grid step stages one [1024, 256] word block (1 MiB) into
# VMEM; larger tiles measured slower (4D/TILE_S=16/32 variants all lost).
TILE_S = 8
TILE_LANES = TILE_S * 128

# Static message-index schedule: round r reads word m[_SCHEDULE[r][i]].
_SCHEDULE = [list(range(16))]
for _ in range(6):
    _SCHEDULE.append([_SCHEDULE[-1][p] for p in MSG_PERMUTATION])


def _rotr(x, n: int):
    return (x >> n) | (x << (32 - n))


def _compress_tile(cv, m, counter_lo, counter_hi, block_len, flags):
    """One BLAKE3 compression over a lane tile, fully in registers.

    cv: list of 8 [S,128] uint32; m: list of 16; scalars-per-lane for
    counter/len/flags. Returns the 8-word output CV.
    """
    v = list(cv) + [
        jnp.full_like(cv[0], IV[0]),
        jnp.full_like(cv[0], IV[1]),
        jnp.full_like(cv[0], IV[2]),
        jnp.full_like(cv[0], IV[3]),
        counter_lo, counter_hi, block_len, flags,
    ]

    def g(a, b, c, d, mx, my):
        v[a] = v[a] + v[b] + mx
        v[d] = _rotr(v[d] ^ v[a], 16)
        v[c] = v[c] + v[d]
        v[b] = _rotr(v[b] ^ v[c], 12)
        v[a] = v[a] + v[b] + my
        v[d] = _rotr(v[d] ^ v[a], 8)
        v[c] = v[c] + v[d]
        v[b] = _rotr(v[b] ^ v[c], 7)

    for r in range(7):
        s = _SCHEDULE[r]
        g(0, 4, 8, 12, m[s[0]], m[s[1]])
        g(1, 5, 9, 13, m[s[2]], m[s[3]])
        g(2, 6, 10, 14, m[s[4]], m[s[5]])
        g(3, 7, 11, 15, m[s[6]], m[s[7]])
        g(0, 5, 10, 15, m[s[8]], m[s[9]])
        g(1, 6, 11, 12, m[s[10]], m[s[11]])
        g(2, 7, 8, 13, m[s[12]], m[s[13]])
        g(3, 4, 9, 14, m[s[14]], m[s[15]])

    return [v[i] ^ v[i + 8] for i in range(8)]


def _chunk_kernel_meta(words_ref, len_ref, cidx_ref, out_ref):
    """Chunk stage for one lane tile, metadata derived in-kernel.

    The hot CAS path always hashes whole messages from chunk counter 0,
    so per-lane metadata is a pure function of (file length, chunk
    index) — two int32 planes instead of six, with the byte/flag
    arithmetic done in registers. Measured ~1.5× the six-plane kernel
    (3.8 ms vs 5.7-7.3 ms per 2048-file batch) and less run-to-run
    spread; the six-plane kernel remains for streaming windows
    (counter_base ≠ 0 / whole=False).

    words_ref: [1, 1024, 256]; len_ref/cidx_ref: [1, S, 128] int32;
    out_ref: [8, 1, S, 128].
    """
    from .blake3_ref import CHUNK_LEN

    w = words_ref[0]
    wt = w.T.reshape(WORDS_PER_BLOCK * BLOCKS_PER_CHUNK, TILE_S, 128)
    length = len_ref[0]
    cidx = cidx_ref[0]
    u32 = lambda x: jnp.asarray(x, dtype=jnp.uint32)  # noqa: E731
    from .blake3_batch import block_meta

    chunk_bytes = jnp.clip(length - cidx * CHUNK_LEN, 0, CHUNK_LEN)
    n_chunks = jnp.maximum((length + CHUNK_LEN - 1) // CHUNK_LEN, 1)
    single = n_chunks == 1
    k_last = jnp.maximum(
        (chunk_bytes + BLOCK_LEN - 1) // BLOCK_LEN - 1, 0)
    counter_lo = cidx.astype(jnp.uint32)
    counter_hi = jnp.zeros_like(counter_lo)
    empty0 = (length == 0) & (cidx == 0)
    cv = [jnp.full_like(counter_lo, IV[i]) for i in range(8)]
    for k in range(BLOCKS_PER_CHUNK):
        block_len, active, flags = block_meta(
            jnp, chunk_bytes, k_last, single, empty0, k)
        m = [wt[k * WORDS_PER_BLOCK + j] for j in range(WORDS_PER_BLOCK)]
        new_cv = _compress_tile(
            cv, m, counter_lo, counter_hi,
            block_len.astype(jnp.uint32), flags)
        cv = [jnp.where(active, n, c) for n, c in zip(new_cv, cv)]
    for i in range(8):
        out_ref[i, 0] = cv[i]


@jit_registry.tracked("blake3.pallas.chunk_fast")
@functools.partial(jax.jit, static_argnames=("interpret",))
def _chunk_cvs_pallas_fast(words, lengths, interpret: bool = False):
    """Whole-message, counter-0 chunk stage (the CAS hot path):
    [B, C, 256] words → (8 × [B, C] CVs, [B] n_chunks)."""
    from .blake3_ref import CHUNK_LEN

    B, C, W = words.shape
    lengths = jnp.asarray(lengths, jnp.int32)
    L = B * C
    NT = -(-L // TILE_LANES)
    pad = NT * TILE_LANES - L

    def lanes(a):
        flat = jnp.broadcast_to(a, (B, C)).astype(jnp.int32).reshape(L)
        flat = jnp.pad(flat, (0, pad))
        return flat.reshape(NT, TILE_S, 128)

    words_n = jnp.pad(words.reshape(L, W), ((0, pad), (0, 0)))
    words_n = words_n.reshape(NT, TILE_LANES, W)
    out = pl.pallas_call(
        _chunk_kernel_meta,
        grid=(NT,),
        in_specs=[
            pl.BlockSpec((1, TILE_LANES, W), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_S, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_S, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, 1, TILE_S, 128), lambda i: (0, i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, NT, TILE_S, 128), jnp.uint32),
        interpret=interpret,
    )(
        words_n,
        lanes(lengths[:, None]),
        lanes(jnp.arange(C, dtype=jnp.int32)[None, :]),
    )
    n_chunks = jnp.maximum((lengths + CHUNK_LEN - 1) // CHUNK_LEN, 1)
    cvs = out.reshape(8, NT * TILE_LANES)[:, :L].reshape(8, B, C)
    return [cvs[i] for i in range(8)], n_chunks


def _chunk_kernel(words_ref, cb_ref, klast_ref, single_ref, empty0_ref,
                  clo_ref, chi_ref, out_ref):
    """Chunk stage for one lane tile.

    words_ref:  [1, 1024, 256] — message words, natural chunk-major
                layout (one contiguous HBM block); transposed to
                word-major in VMEM here.
    cb/klast/clo/chi: [1, S, 128] int32/uint32 per-lane metadata.
    single/empty0:    [1, S, 128] int32 (0/1) flags.
    out_ref:    [8, 1, S, 128] — the per-chunk chaining value.
    """
    w = words_ref[0]                         # [1024, 256]
    wt = w.T.reshape(WORDS_PER_BLOCK * BLOCKS_PER_CHUNK, TILE_S, 128)
    chunk_bytes = cb_ref[0]
    k_last = klast_ref[0]
    single = single_ref[0] != 0
    empty0 = empty0_ref[0] != 0
    counter_lo = clo_ref[0]
    counter_hi = chi_ref[0]

    u32 = lambda x: jnp.asarray(x, dtype=jnp.uint32)  # noqa: E731
    cv = [jnp.full_like(counter_lo, IV[i]) for i in range(8)]

    for k in range(BLOCKS_PER_CHUNK):
        block_len = jnp.clip(chunk_bytes - k * BLOCK_LEN, 0, BLOCK_LEN)
        is_last = k_last == k
        active = (block_len > 0) | (empty0 if k == 0 else False)
        flags = (
            (u32(CHUNK_START) if k == 0 else u32(0))
            + jnp.where(is_last, u32(CHUNK_END), u32(0))
            + jnp.where(is_last & single, u32(ROOT), u32(0))
        )
        m = [wt[k * WORDS_PER_BLOCK + j] for j in range(WORDS_PER_BLOCK)]
        new_cv = _compress_tile(
            cv, m, counter_lo, counter_hi,
            block_len.astype(jnp.uint32), flags)
        cv = [jnp.where(active, n, c) for n, c in zip(new_cv, cv)]

    for i in range(8):
        out_ref[i, 0] = cv[i]


@jit_registry.tracked("blake3.pallas.chunk")
@functools.partial(jax.jit, static_argnames=("interpret",))
def _chunk_cvs_pallas(words, lengths, clo, chi, whole_mask,
                      interpret: bool = False):
    """[B, C, 256] words → per-chunk CVs, list of 8 [B, C] uint32.

    clo/chi: [B] uint32 counter base per file; whole_mask: [B] bool.
    """
    B, C, W = words.shape
    (chunk_bytes, n_chunks, single, k_last, counter_lo, counter_hi,
     empty0) = chunk_prelude(jnp, lengths, C, (clo, chi),
                             whole_mask[:, None])

    L = B * C
    NT = -(-L // TILE_LANES)
    pad = NT * TILE_LANES - L

    def lanes(a, dtype):
        flat = jnp.broadcast_to(a, (B, C)).astype(dtype).reshape(L)
        flat = jnp.pad(flat, (0, pad))
        return flat.reshape(NT, TILE_S, 128)

    words_n = jnp.pad(words.reshape(L, W), ((0, pad), (0, 0)))
    words_n = words_n.reshape(NT, TILE_LANES, W)

    out = pl.pallas_call(
        _chunk_kernel,
        grid=(NT,),
        in_specs=[
            pl.BlockSpec((1, TILE_LANES, W), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ] + [
            pl.BlockSpec((1, TILE_S, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
            for _ in range(6)
        ],
        out_specs=pl.BlockSpec((8, 1, TILE_S, 128), lambda i: (0, i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, NT, TILE_S, 128), jnp.uint32),
        interpret=interpret,
    )(
        words_n,
        lanes(chunk_bytes, jnp.int32),
        lanes(k_last, jnp.int32),
        lanes(single, jnp.int32),
        lanes(empty0, jnp.int32),
        lanes(counter_lo, jnp.uint32),
        lanes(counter_hi, jnp.uint32),
    )

    cvs = out.reshape(8, NT * TILE_LANES)[:, :L].reshape(8, B, C)
    return [cvs[i] for i in range(8)], n_chunks


def chunk_cvs_pallas(words, lengths, counter_base=0, whole=True,
                     interpret: bool = False):
    """Drop-in device replacement for blake3_batch.chunk_cvs semantics."""
    from .blake3_batch import split_counter_base

    B = words.shape[0]
    lo, hi = split_counter_base(counter_base)
    lo = jnp.broadcast_to(jnp.asarray(lo, jnp.uint32), (B,))
    hi = jnp.broadcast_to(jnp.asarray(hi, jnp.uint32), (B,))
    whole_mask = jnp.broadcast_to(jnp.asarray(whole, jnp.bool_), (B,))
    return _chunk_cvs_pallas(words, jnp.asarray(lengths, jnp.int32),
                             lo, hi, whole_mask, interpret=interpret)


@jit_registry.tracked("blake3.pallas.words")
@functools.partial(jax.jit, static_argnames=("interpret",))
def blake3_words_pallas(words, lengths, interpret: bool = False):
    """[B, C, 256] words + [B] lengths → [B, 8] digests (fast-path
    Pallas chunk stage + jnp tree reduction).

    The WHOLE pipeline is one jitted program: the chunk stage alone was
    jitted before, which left the ~log2(C) tree-reduce levels running
    EAGERLY — locally that's a few extra dispatches, but through the
    tunneled bench chip every eager jnp op is its own RPC round-trip
    (+compile), turning one batched validator dispatch into ~47 s."""
    from .blake3_batch import tree_reduce

    cvs, n_chunks = _chunk_cvs_pallas_fast(words, lengths,
                                           interpret=interpret)
    return jnp.stack(tree_reduce(jnp, cvs, n_chunks), axis=1)


def supported() -> bool:
    """True when the default JAX backend can compile this kernel."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False
