"""CAS-ID generation and full-file checksums (CPU oracle path).

Matches the reference byte-for-byte:
- `generate_cas_id`: /root/reference/core/src/object/cas.rs:23-62 —
  blake3(size.to_le_bytes() ‖ payload), hex-truncated to 16 chars, where
  payload is the whole file when size ≤ 100 KiB, else 8 KiB header +
  4 × 10 KiB samples at offsets 8192 + k·((size − 16384) // 4) + 8 KiB footer.
- `file_checksum`: /root/reference/core/src/object/validation/hash.rs:10-24 —
  full-file blake3 read in 1 MiB blocks, 64-char hex.

`sample_spec` is the single source of truth for which byte ranges are hashed;
the C++ stager and the TPU batch builder consume the same spec so every
backend hashes identical payloads.
"""

from __future__ import annotations

import os
import struct
from typing import BinaryIO, List, Tuple

from .blake3_ref import Blake3

SAMPLE_COUNT = 4
SAMPLE_SIZE = 1024 * 10
HEADER_OR_FOOTER_SIZE = 1024 * 8
MINIMUM_FILE_SIZE = 1024 * 100  # ≤ this: hash the whole file
BLOCK_LEN = 1048576  # validator read block

# Fixed payload size for every large file: header + samples + footer.
LARGE_PAYLOAD_SIZE = 2 * HEADER_OR_FOOTER_SIZE + SAMPLE_COUNT * SAMPLE_SIZE  # 57344
# Plus the 8-byte little-endian size prefix that is hashed first.
SIZE_PREFIX_LEN = 8

assert (HEADER_OR_FOOTER_SIZE * 2 + SAMPLE_COUNT * SAMPLE_SIZE) < MINIMUM_FILE_SIZE
assert SAMPLE_SIZE > HEADER_OR_FOOTER_SIZE


def sample_spec(size: int) -> List[Tuple[int, int]]:
    """(offset, length) ranges whose concatenation is the hashed payload."""
    if size <= MINIMUM_FILE_SIZE:
        return [(0, size)]
    jump = (size - 2 * HEADER_OR_FOOTER_SIZE) // SAMPLE_COUNT
    ranges = [(0, HEADER_OR_FOOTER_SIZE)]
    ranges += [
        (HEADER_OR_FOOTER_SIZE + k * jump, SAMPLE_SIZE)
        for k in range(SAMPLE_COUNT)
    ]
    ranges.append((size - HEADER_OR_FOOTER_SIZE, HEADER_OR_FOOTER_SIZE))
    return ranges


def read_sampled_payload(f: BinaryIO, size: int) -> bytes:
    """Read the hashed payload exactly as the reference does.

    Matches cas.rs even when `size` disagrees with the file's true length:
    the small-file path reads the whole file (`fs::read`), and the footer
    seeks relative to the real end (`SeekFrom::End(-8192)`), not to
    `size - 8192`. Header/sample offsets come from the declared size.
    """
    if size <= MINIMUM_FILE_SIZE:
        return f.read()
    parts = []
    for offset, length in sample_spec(size)[:-1]:
        f.seek(offset)
        part = f.read(length)
        if len(part) != length:
            raise EOFError(
                f"short read at {offset}: wanted {length}, got {len(part)}"
            )
        parts.append(part)
    f.seek(-HEADER_OR_FOOTER_SIZE, os.SEEK_END)
    footer = f.read(HEADER_OR_FOOTER_SIZE)
    if len(footer) != HEADER_OR_FOOTER_SIZE:
        raise EOFError("short footer read")
    parts.append(footer)
    return b"".join(parts)


def cas_id_of_payload(size: int, payload: bytes) -> str:
    h = Blake3()
    h.update(struct.pack("<Q", size))
    h.update(payload)
    return h.hexdigest()[:16]


def generate_cas_id(path: str | os.PathLike, size: int | None = None) -> str:
    if size is None:
        size = os.stat(path).st_size
    with open(path, "rb") as f:
        payload = read_sampled_payload(f, size)
    return cas_id_of_payload(size, payload)


def file_checksum(path: str | os.PathLike) -> str:
    h = Blake3()
    with open(path, "rb") as f:
        while True:
            block = f.read(BLOCK_LEN)
            h.update(block)
            if len(block) != BLOCK_LEN:
                break
    return h.hexdigest()
