"""Batched pad-and-mask BLAKE3 over arrays — backend-generic core.

One algorithm, two array backends (numpy for the CPU baseline, jax.numpy
for the TPU path). The structure is chosen for SIMD/VPU execution rather
than translated from the reference's streaming Rust (cas.rs drives the
`blake3` crate per file; here whole batches hash at once):

- Every 32-bit state/message word is its own ``[B, C]`` array (B files,
  C chunks), so the compression function is pure elementwise arithmetic
  with zero gathers — ideal for the TPU VPU and for numpy vectorization.
- Chunk stage: all chunks of all files compress in parallel; only the 16
  blocks within a chunk are sequential (a real data dependency).
- Tree stage: BLAKE3's "left subtree = largest power of two" rule is
  equivalent to repeated adjacent pairing with odd-tail promotion, so the
  merge is ceil(log2(C)) vectorized parent compressions with per-lane
  ROOT flags (different files can root at different levels).

Inputs are zero-padded ``uint32`` little-endian word grids plus per-file
byte lengths; inactive blocks/chunks are masked with ``where`` selects.
Chunk counters are 32-bit here: single-call messages are bounded by the
grid size, and the streaming validator path passes an explicit
``counter_base`` (supports files up to 2^32 chunks = 4 TiB).
"""

from __future__ import annotations

import numpy as np

from . import cas as _cas

from .blake3_ref import (
    BLOCK_LEN,
    CHUNK_END,
    CHUNK_LEN,
    CHUNK_START,
    IV,
    MSG_PERMUTATION,
    PARENT,
    ROOT,
)

BLOCKS_PER_CHUNK = CHUNK_LEN // BLOCK_LEN  # 16
WORDS_PER_BLOCK = BLOCK_LEN // 4  # 16
WORDS_PER_CHUNK = CHUNK_LEN // 4  # 256


def _rotr(x, n: int):
    # uint32 rotate right by a static amount.
    return (x >> n) | (x << (32 - n))


def _ground(R0, R1, R2, R3, MX, MY):
    """The G mixing function applied to all four columns at once.

    R0..R3 are the four rows of the 4×4 state matrix, shape [4, ...] with
    axis 0 = column index. This is the standard SIMD formulation of
    BLAKE-family compression: 2 vector G calls per round instead of 8
    scalar ones, which keeps both numpy op count and XLA graph size small
    (the naive 16-scalar-word DAG sends XLA-CPU's optimizer into
    exponential territory).
    """
    R0 = R0 + R1 + MX
    R3 = _rotr(R3 ^ R0, 16)
    R2 = R2 + R3
    R1 = _rotr(R1 ^ R2, 12)
    R0 = R0 + R1 + MY
    R3 = _rotr(R3 ^ R0, 8)
    R2 = R2 + R3
    R1 = _rotr(R1 ^ R2, 7)
    return R0, R1, R2, R3


def compress_cv(xp, cv, m, counter_lo, counter_hi, block_len, flags):
    """Vectorized BLAKE3 compression returning the 8-word chaining value.

    cv: list of 8 arrays; m: list of 16 arrays; counter/block_len/flags:
    arrays (or scalars) broadcastable against them. All uint32.
    """
    u32 = lambda v: xp.asarray(v, dtype=xp.uint32)  # noqa: E731
    parts = (
        list(cv)
        + list(m)
        + [u32(counter_lo), u32(counter_hi), u32(block_len), u32(flags)]
    )
    shape = np.broadcast_shapes(*[getattr(a, "shape", ()) for a in parts])
    bc = lambda a: xp.broadcast_to(xp.asarray(a, dtype=xp.uint32), shape)  # noqa: E731

    cv = [bc(w) for w in cv]
    m = [bc(w) for w in m]
    R0 = xp.stack(cv[0:4])
    R1 = xp.stack(cv[4:8])
    R2 = xp.stack([bc(IV[0]), bc(IV[1]), bc(IV[2]), bc(IV[3])])
    R3 = xp.stack([bc(counter_lo), bc(counter_hi), bc(block_len), bc(flags)])

    for r in range(7):
        MXc = xp.stack([m[0], m[2], m[4], m[6]])
        MYc = xp.stack([m[1], m[3], m[5], m[7]])
        MXd = xp.stack([m[8], m[10], m[12], m[14]])
        MYd = xp.stack([m[9], m[11], m[13], m[15]])
        # column step
        R0, R1, R2, R3 = _ground(R0, R1, R2, R3, MXc, MYc)
        # diagonal step: rotate rows so diagonals become columns
        R1 = xp.roll(R1, -1, axis=0)
        R2 = xp.roll(R2, -2, axis=0)
        R3 = xp.roll(R3, -3, axis=0)
        R0, R1, R2, R3 = _ground(R0, R1, R2, R3, MXd, MYd)
        R1 = xp.roll(R1, 1, axis=0)
        R2 = xp.roll(R2, 2, axis=0)
        R3 = xp.roll(R3, 3, axis=0)
        if r < 6:
            m = [m[p] for p in MSG_PERMUTATION]

    lo = R0 ^ R2  # out[i] = s[i] ^ s[i+8]
    hi = R1 ^ R3
    return [lo[0], lo[1], lo[2], lo[3], hi[0], hi[1], hi[2], hi[3]]


def _select(xp, cond, a, b):
    """where() over parallel lists of word arrays."""
    return [xp.where(cond, x, y) for x, y in zip(a, b)]


def split_counter_base(counter_base):
    """Normalize a chunk-counter base to a (lo, hi) uint32 pair.

    Accepts a python int, a numpy uint64 array, or an already-split pair.
    Chunk counters are 64-bit in BLAKE3; device code carries them as two
    uint32 words since TPU jax runs without x64.
    """
    if isinstance(counter_base, tuple):
        return counter_base
    base = np.asarray(counter_base, dtype=np.uint64)
    lo = (base & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (base >> np.uint64(32)).astype(np.uint32)
    return lo, hi


def chunk_prelude(xp, lengths, C, counter_base=0, whole=True):
    """Shared per-chunk metadata for the chunk stage (numpy and JAX paths).

    Returns (chunk_bytes [B,C], n_chunks [B], single [B,1],
    k_last [B,C], counter_lo [B,C], counter_hi [B,C], empty0 [B,C]).
    `single` is true only for a complete one-chunk message hashed from
    counter 0. A streaming window that happens to hold one chunk but is a
    prefix of a longer message must NOT be root-finalized: such callers
    pass ``whole=False`` (counter_base==0 alone cannot distinguish the
    first window of a long stream from a genuine one-chunk message).
    """
    u32 = lambda v: xp.asarray(v, dtype=xp.uint32)  # noqa: E731
    lengths = xp.asarray(lengths, dtype=xp.int32)
    len_b = lengths[:, None]
    chunk_index = xp.arange(C, dtype=xp.int32)[None, :]
    chunk_bytes = xp.clip(len_b - chunk_index * CHUNK_LEN, 0, CHUNK_LEN)
    n_chunks = xp.maximum((lengths + (CHUNK_LEN - 1)) // CHUNK_LEN, 1)
    base_lo, base_hi = split_counter_base(counter_base)
    base_lo = u32(base_lo)
    base_hi = u32(base_hi)
    if getattr(base_lo, "ndim", 0) == 1:  # per-file bases: [B] → [B, 1]
        base_lo = base_lo[:, None]
        base_hi = base_hi[:, None]
    at_zero = (base_lo == 0) & (base_hi == 0)  # scalar or [B, 1]
    single = (n_chunks[:, None] == 1) & at_zero & whole  # [B, 1]
    k_last = xp.maximum((chunk_bytes + (BLOCK_LEN - 1)) // BLOCK_LEN - 1, 0)
    idx_u32 = u32(chunk_index)
    counter_lo = (base_lo + idx_u32) * xp.ones_like(chunk_bytes, dtype=xp.uint32)
    carry = xp.where(counter_lo < idx_u32, u32(1), u32(0))
    counter_hi = (base_hi + carry) * xp.ones_like(chunk_bytes, dtype=xp.uint32)
    empty0 = (len_b == 0) & (chunk_index == 0)
    return chunk_bytes, n_chunks, single, k_last, counter_lo, counter_hi, empty0


def block_meta(xp, chunk_bytes, k_last, single, empty0, k):
    """(block_len, active, flags) for block index k of every chunk.

    `k` may be a python int (unrolled numpy path) or a traced scalar
    (lax.scan path) — the arithmetic is identical, which keeps the two
    backends incapable of diverging on masking/flag semantics.
    """
    u32 = lambda v: xp.asarray(v, dtype=xp.uint32)  # noqa: E731
    block_len = xp.clip(chunk_bytes - k * BLOCK_LEN, 0, BLOCK_LEN)
    is_last = k_last == k
    is_first = k == 0  # bool or traced bool
    # A block participates if it holds real bytes, or it is the all-zero
    # block 0 of chunk 0 for an empty message.
    active = (block_len > 0) | (is_first & empty0)
    flags = (
        xp.where(is_first, u32(CHUNK_START), u32(0))
        + xp.where(is_last, u32(CHUNK_END), u32(0))
        + xp.where(is_last & single, u32(ROOT), u32(0))
    )
    return block_len, active, flags


def chunk_cvs(xp, words, lengths, counter_base=0, whole=True):
    """Compute per-chunk chaining values for a batch.

    words:   [B, C, 256] uint32, little-endian packed, zero padded.
    lengths: [B] int32 — true message byte length of each file.
    counter_base: absolute index of chunk 0 (int, uint64 array, or
        pre-split (lo, hi) uint32 pair) for streaming windows.
    whole: False when this grid is a window of a longer stream, so a
        one-chunk window at counter 0 is not root-finalized.

    Returns (cvs, n_chunks): cvs is a list of 8 [B, C] uint32 arrays,
    n_chunks is [B]. If the whole message is a single chunk hashed from
    counter 0, that chunk's final block was compressed WITH the ROOT
    flag, so cvs[:, 0] is already the final digest for those lanes (and
    tree_reduce passes it through untouched).
    """
    B, C, W = words.shape
    assert W == WORDS_PER_CHUNK
    u32 = lambda v: xp.asarray(v, dtype=xp.uint32)  # noqa: E731
    (
        chunk_bytes, n_chunks, single, k_last,
        counter_lo, counter_hi, empty0,
    ) = chunk_prelude(xp, lengths, C, counter_base, whole)

    cv = [u32(IV[i]) * xp.ones((B, C), dtype=xp.uint32) for i in range(8)]
    for k in range(BLOCKS_PER_CHUNK):
        block_len, active, flags = block_meta(
            xp, chunk_bytes, k_last, single, empty0, k
        )
        m = [words[:, :, k * WORDS_PER_BLOCK + j] for j in range(WORDS_PER_BLOCK)]
        new_cv = compress_cv(
            xp, cv, m, counter_lo, counter_hi, u32(block_len), flags
        )
        cv = _select(xp, active, new_cv, cv)
    return cv, n_chunks


def tree_reduce(xp, cvs, n_chunks, root=True):
    """Fold per-chunk CVs into root digests via adjacent pairing.

    cvs: list of 8 [B, C] arrays; n_chunks: [B]. Returns list of 8 [B]
    arrays — the first 32 bytes of each file's BLAKE3 digest. Lanes with
    n_chunks == 1 pass through untouched (their ROOT compression already
    happened in the chunk stage).

    root=False computes interior-subtree tops instead: no merge ever
    carries the ROOT flag, so the result can keep merging upward — the
    local stage of the sequence-parallel (sharded single-file) reduction
    in ops/seqhash.py.
    """
    B, C = cvs[0].shape
    u32 = lambda v: xp.asarray(v, dtype=xp.uint32)  # noqa: E731
    n = xp.asarray(n_chunks, dtype=xp.int32)
    while C > 1:
        half = (C + 1) // 2
        pad = half * 2 - C
        if pad:
            cvs = [
                xp.concatenate([w, xp.zeros((B, pad), dtype=xp.uint32)], axis=1)
                for w in cvs
            ]
        left = [w[:, 0::2] for w in cvs]  # [B, half]
        right = [w[:, 1::2] for w in cvs]
        pair_index = xp.arange(half, dtype=xp.int32)[None, :]
        merged_real = (pair_index * 2 + 1) < n[:, None]
        is_root = (n[:, None] == 2) & (pair_index == 0) & root
        flags = u32(PARENT) + xp.where(is_root, u32(ROOT), u32(0))
        iv_cv = [u32(IV[i]) * xp.ones((B, half), dtype=xp.uint32) for i in range(8)]
        parent = compress_cv(
            xp,
            iv_cv,
            left + right,  # parent block = left_cv ‖ right_cv
            xp.zeros((B, half), dtype=xp.uint32),
            xp.zeros((B, half), dtype=xp.uint32),
            u32(BLOCK_LEN),
            flags,
        )
        cvs = _select(xp, merged_real, parent, left)
        n = xp.maximum((n + 1) // 2, 1)
        C = half
    return [w[:, 0] for w in cvs]


def blake3_batch(xp, words, lengths):
    """Full batched BLAKE3: [B, C, 256] words + [B] lengths → 8×[B] words."""
    cvs, n_chunks = chunk_cvs(xp, words, lengths)
    return tree_reduce(xp, cvs, n_chunks)


# ---------------------------------------------------------------------------
# Host-side batch packing (always numpy).


def pack_messages(messages, max_chunks=None):
    """Pack variable-length byte strings into a padded word grid.

    Returns (words [B, C, 256] uint32, lengths [B] int32).
    """
    B = len(messages)
    longest = max((len(m) for m in messages), default=0)
    C = max(1, -(-longest // CHUNK_LEN))
    if max_chunks is not None:
        assert C <= max_chunks, (C, max_chunks)
        C = max_chunks
    buf = np.zeros((B, C * CHUNK_LEN), dtype=np.uint8)
    lengths = np.zeros((B,), dtype=np.int32)
    for i, m in enumerate(messages):
        buf[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        lengths[i] = len(m)
    words = buf.view("<u4").reshape(B, C, WORDS_PER_CHUNK)
    return words, lengths


def digest_words_to_bytes(word_lists) -> list:
    """8×[B] uint32 word arrays → list of 32-byte digests."""
    stacked = np.stack([np.asarray(w) for w in word_lists], axis=1)  # [B, 8]
    le = stacked.astype("<u4")
    return [le[i].tobytes() for i in range(le.shape[0])]


def blake3_batch_np(messages) -> list:
    """CPU batched BLAKE3 of a list of byte strings → 32-byte digests."""
    words, lengths = pack_messages(messages)
    out = blake3_batch(np, words, lengths)
    return digest_words_to_bytes(out)


# ---------------------------------------------------------------------------
# Host-side message building for the CAS pipeline (jax-free: the
# numpy fallback backend must work without jax importable).


def build_cas_messages(payloads: np.ndarray, sizes: np.ndarray, payload_lens=None):
    """Prefix payload rows with the 8-byte LE file size and pack to words.

    payloads: [B, P] uint8, zero-padded past each row's payload length.
    sizes:    [B] uint64 — true file sizes (hashed as the prefix).
    payload_lens: [B] — bytes of real payload per row (default: P).

    Returns (words [B, C, 256] uint32, lengths [B] int32) where C is the
    grid for P (57 for the large-file mode, 101 for small).
    """
    payloads = np.ascontiguousarray(payloads, dtype=np.uint8)
    B, P = payloads.shape
    if payload_lens is None:
        payload_lens = np.full((B,), P, dtype=np.int32)
    else:
        # Zero stale bytes past each row's payload: the compression always
        # consumes full 16-word blocks (block_len only clips the count), so
        # a reused buffer with residue would silently change the digest.
        payload_lens = np.asarray(payload_lens, dtype=np.int32)
        mask = np.arange(P, dtype=np.int32)[None, :] < payload_lens[:, None]
        payloads = np.where(mask, payloads, 0).astype(np.uint8)
    msg_len = _cas.SIZE_PREFIX_LEN + P
    C = max(1, -(-msg_len // CHUNK_LEN))
    buf = np.zeros((B, C * CHUNK_LEN), dtype=np.uint8)
    buf[:, : _cas.SIZE_PREFIX_LEN] = (
        np.asarray(sizes, dtype="<u8").reshape(B, 1).view(np.uint8)
    )
    buf[:, _cas.SIZE_PREFIX_LEN : _cas.SIZE_PREFIX_LEN + P] = payloads
    lengths = (_cas.SIZE_PREFIX_LEN + np.asarray(payload_lens, dtype=np.int32))
    return buf.view("<u4").reshape(B, C, WORDS_PER_CHUNK), lengths


def digests_to_cas_ids(digests) -> list:
    """[B, 8] uint32 digests → 16-hex-char CAS IDs (cas.rs:61)."""
    le = np.asarray(digests).astype("<u4")
    return [le[i].tobytes()[:8].hex() for i in range(le.shape[0])]


def digests_to_hex(digests) -> list:
    le = np.asarray(digests).astype("<u4")
    return [le[i].tobytes().hex() for i in range(le.shape[0])]
