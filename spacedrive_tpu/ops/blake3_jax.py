"""JAX device backend for batched BLAKE3 and CAS-ID generation.

The device computation is the shared pad-and-mask algorithm from
``blake3_batch`` instantiated with ``jax.numpy`` and jitted; jit
shape-specializes per (B, C) grid, and the CAS pipeline deliberately uses
a small set of canonical grids so compilation is amortized:

- large-file mode: every payload is exactly 57,344 sampled bytes
  (+ 8-byte size prefix) → a fixed [B, 57, 256] grid (cas.rs:23-62
  semantics; see ops/cas.py for the sampling spec),
- small-file mode: whole files ≤ 100 KiB → a fixed [B, 101, 256] grid.

Digest/CAS formatting (hex truncation to 16 chars) matches
/root/reference/core/src/object/cas.rs:61.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import cas, jit_registry
from .. import flags
from .blake3_batch import (  # noqa: F401 — re-exported for callers
    CHUNK_LEN,
    WORDS_PER_CHUNK,
    build_cas_messages,
    digests_to_cas_ids,
    digests_to_hex,
)

# Canonical chunk-grid sizes for the two CAS modes.
LARGE_MSG_LEN = cas.SIZE_PREFIX_LEN + cas.LARGE_PAYLOAD_SIZE  # 57352
LARGE_CHUNKS = -(-LARGE_MSG_LEN // CHUNK_LEN)  # 57
SMALL_MSG_MAX = cas.SIZE_PREFIX_LEN + cas.MINIMUM_FILE_SIZE  # 102408
SMALL_CHUNKS = -(-SMALL_MSG_MAX // CHUNK_LEN)  # 101


def _chunk_cvs_scan(words, lengths, counter_base=0, whole=True):
    """JAX-shaped chunk stage: lax.scan over the 16 blocks of every chunk.

    Same math as blake3_batch.chunk_cvs (the numpy oracle path) — the
    per-block metadata comes from the shared chunk_prelude/block_meta
    helpers so the backends cannot diverge. Only the loop strategy
    differs: a scan keeps one compression body in the compiled graph
    instead of sixteen, cutting compile time ~an order of magnitude
    while XLA keeps the carry in registers/VMEM.
    """
    from .blake3_batch import (
        BLOCKS_PER_CHUNK,
        WORDS_PER_BLOCK,
        _select,
        block_meta,
        chunk_prelude,
        compress_cv,
    )
    from .blake3_ref import IV

    B, C, W = words.shape
    u32 = lambda v: jnp.asarray(v, dtype=jnp.uint32)  # noqa: E731
    (
        chunk_bytes, n_chunks, single, k_last,
        counter_lo, counter_hi, empty0,
    ) = chunk_prelude(jnp, lengths, C, counter_base, whole)

    blocks = jnp.moveaxis(
        words.reshape(B, C, BLOCKS_PER_CHUNK, WORDS_PER_BLOCK), 2, 0
    )  # [16, B, C, 16]
    ks = jnp.arange(BLOCKS_PER_CHUNK, dtype=jnp.int32)

    # Derive the IV carry from the input so its sharding "varying axes"
    # match the scan outputs under shard_map.
    zeros = jnp.zeros_like(words[:, :, 0])
    cv0 = tuple(u32(IV[i]) + zeros for i in range(8))

    def body(cv, xs):
        k, blk = xs
        block_len, active, flags = block_meta(
            jnp, chunk_bytes, k_last, single, empty0, k
        )
        m = [blk[:, :, j] for j in range(WORDS_PER_BLOCK)]
        new_cv = compress_cv(
            jnp, list(cv), m, counter_lo, counter_hi, u32(block_len), flags
        )
        return tuple(_select(jnp, active, new_cv, list(cv))), None

    cv, _ = jax.lax.scan(body, cv0, (ks, blocks))
    return list(cv), n_chunks


def _blake3_impl(words, lengths):
    """Shared body of the jitted and shard_mapped entry points."""
    from .blake3_batch import tree_reduce

    cvs, n_chunks = _chunk_cvs_scan(words, lengths)
    return jnp.stack(tree_reduce(jnp, cvs, n_chunks), axis=1)


@jit_registry.tracked("blake3.jnp")
@jax.jit
def _blake3_jnp_jit(words, lengths):
    return _blake3_impl(words, lengths)


def _blake3_impl_best(words, lengths):
    """Traceable best-backend body: pallas kernel on TPU, jnp scan
    elsewhere. Usable inside an enclosing jit (bench harness loops)."""
    from . import blake3_pallas

    if blake3_pallas.supported():
        return blake3_pallas.blake3_words_pallas(words, lengths)
    return _blake3_impl(words, lengths)


def _donated_best(words, lengths):
    """Donated twin of the best-backend body: identity pass-through
    outputs alias the donated inputs (same shape/dtype, so XLA's
    input-output aliasing engages on every backend, CPU included),
    meaning the staged device copies are CONSUMED at dispatch and
    recycled by the allocator at kernel completion — instead of
    surviving until the digest fetch like the undonated entry's."""
    return _blake3_impl_best(words, lengths), words, lengths


_blake3_best_donated = jit_registry.tracked("blake3.donated")(
    jax.jit(_donated_best, donate_argnums=(0, 1)))


def _donated_local(words, lengths):
    """Local (single-device) CAS hasher over the donated entry: the
    ring aliases are dropped on the floor — the identify pipeline only
    wants the digests, the recycled buffers belong to the allocator."""
    digests, _ring_w, _ring_l = _blake3_best_donated(words, lengths)
    return digests


def blake3_words(words, lengths):
    """[B, C, 256] uint32 words + [B] int32 lengths → [B, 8] uint32 digests.

    Dispatches to the Pallas chunk-stage kernel on TPU (measured ~2×
    the jnp scan path and ~8.5× the AVX2 C++ plane at batch 2048; see
    ops/blake3_pallas.py) and to the jnp scan path elsewhere (CPU mesh
    tests, hosts without a TPU). Digests are bit-identical across
    backends — parity is pinned by tests/test_blake3_pallas.py and the
    oracle vectors.
    """
    from . import blake3_pallas

    if blake3_pallas.supported():
        return blake3_pallas.blake3_words_pallas(words, lengths)
    return _blake3_jnp_jit(words, lengths)


def make_sharded_blake3(mesh, axis: str = "data"):
    """Data-parallel batched BLAKE3 over a device mesh.

    Hashing is embarrassingly parallel across files, so the batch dim is
    sharded over `axis` and no collectives are needed; the result lands
    fully replicated only when gathered by the caller. The per-shard
    body is the best-backend one — the Pallas chunk-stage kernel on TPU
    meshes (~2× the jnp scan per chip), the jnp scan elsewhere — so
    sharding never trades away the single-chip kernel.
    """
    P = jax.sharding.PartitionSpec

    return jit_registry.tracked("blake3.sharded")(jax.jit(
        functools.partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=P(axis),
        )(_blake3_impl_best)
    ))


def sharded_hasher():
    """The production multi-device hasher: data-parallel BLAKE3 over ALL
    local devices, or None on a single-device host.

    This is how the identifier's flagship pipeline uses a pod slice
    (SURVEY §2.6 mapping): batch dim sharded over the mesh's data axis,
    zero collectives (hashing is embarrassingly parallel per file).
    Cached so the mesh + compiled program build once per process.

    SDTPU_SHARDED_CAS=off forces the single-device program — the test
    suite sets it because its 8-VIRTUAL-device CPU mesh would pay a
    fresh ~50 s shard_map compile per batch grid for zero coverage
    gain (the sharded dispatch has its own dedicated test and the
    driver's dryrun_multichip stage 6)."""
    global _SHARDED
    if _SHARDED is None:
        devs = jax.devices()
        if (len(devs) < 2
                or flags.get("SDTPU_SHARDED_CAS") == "off"):
            _SHARDED = (None, 1)
        else:
            from ..parallel.mesh import batch_mesh

            _SHARDED = (make_sharded_blake3(batch_mesh(devs)), len(devs))
    return _SHARDED


_SHARDED = None


# jit shape-specializes per (B, C); C is canonical per CAS mode, but the
# identifier's per-step large/small split makes B arbitrary. Padding B up
# to a bucket keeps the number of compiled programs tiny — without this,
# a scan over mixed batches recompiles (~10 s on TPU) nearly every step.
_B_BUCKETS = (8, 32, 64, 128, 256, 512, 1024, 2048)


def _bucket_b(B: int) -> int:
    for b in _B_BUCKETS:
        if B <= b:
            return b
    return -(-B // _B_BUCKETS[-1]) * _B_BUCKETS[-1]


def checksums_words_batched(blobs) -> list:
    """Full BLAKE3 digests (64-hex) of B byte strings in ONE device
    dispatch: rows padded to a shared power-of-two chunk grid, hashed by
    the batch machinery (sharded over the mesh when >1 device).

    This is the validator's RPC amortizer (VERDICT r4 item 4): the
    tunneled bench chip costs ~28 ms per dispatch, so hashing one file
    per call capped the device validator at ~36 files/s regardless of
    kernel speed — packing a page of small files into one batched grid
    pays that latency once per page. Callers group similar sizes per
    call (validator sorts by size) so the shared C pads little.
    """
    B = len(blobs)
    if B == 0:
        return []
    from .blake3_batch import CHUNK_LEN, WORDS_PER_CHUNK, digests_to_hex

    maxlen = max(len(b) for b in blobs)
    C = max(1, -(-max(maxlen, 1) // CHUNK_LEN))
    C = 1 << (C - 1).bit_length()   # pow2 → few compiled grids
    hasher, n_dev = sharded_hasher()
    if hasher is None:
        hasher = blake3_words
    Bp = _bucket_b(B)
    if n_dev > 1:
        from ..parallel.mesh import pad_to_multiple

        Bp = pad_to_multiple(Bp, n_dev)
    buf = np.zeros((Bp, C * CHUNK_LEN), dtype=np.uint8)
    lengths = np.zeros((Bp,), dtype=np.int32)
    for i, b in enumerate(blobs):
        buf[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        lengths[i] = len(b)
    words = buf.view("<u4").reshape(Bp, C, WORDS_PER_CHUNK)
    if flags.get("SDTPU_DISPATCH_LOG"):
        DISPATCH_LOG.append({"B": B, "Bp": Bp, "n_dev": n_dev, "C": C,
                             "kind": "checksum"})
    with jit_registry.device_scope("cas.checksums"):
        digests = hasher(words, lengths)[:B]
        with jit_registry.io("cas.checksums"):
            return digests_to_hex(digests)


# Dispatch observability: when SDTPU_DISPATCH_LOG=1, every cas_ids_jax
# call appends {"B", "Bp", "n_dev"} here — per-device shard balance is
# Bp/n_dev by construction (batch padded to a devices-multiple), and
# the dryrun/driver artifacts record it from this log.
DISPATCH_LOG: list = []  # sdlint: ok[unbounded-growth] flag-gated diagnostic (SDTPU_DISPATCH_LOG=1): dryrun artifacts read the whole log, so it must not self-truncate


def cas_ids_jax(payloads, sizes, payload_lens=None, hasher=None) -> list:
    """End-to-end device CAS: payload rows + sizes → 16-hex CAS IDs.

    With no explicit `hasher`, a multi-device host dispatches through
    the mesh-sharded program (batch padded to a devices-multiple so
    every shard gets equal rows); single-device hosts use the local
    jit/Pallas path."""
    n_dev = 1
    if hasher is None:
        hasher, n_dev = sharded_hasher()
        if hasher is None:
            # Single-device dispatch goes through the donated entry by
            # default (SDTPU_DONATE_BUFFERS): the batch's staged device
            # copy is recycled at kernel completion, not pinned until
            # the CAS-ID fetch below. The words/lengths built here are
            # per-call temporaries, so consuming them is always safe.
            hasher = (_donated_local
                      if flags.get("SDTPU_DONATE_BUFFERS")
                      else blake3_words)
    words, lengths = build_cas_messages(payloads, sizes, payload_lens)
    B = words.shape[0]
    Bp = _bucket_b(B)
    if n_dev > 1:
        from ..parallel.mesh import pad_to_multiple

        Bp = pad_to_multiple(Bp, n_dev)  # equal per-shard rows
    if Bp != B:
        words = np.concatenate(
            [words, np.zeros((Bp - B,) + words.shape[1:], words.dtype)])
        lengths = np.concatenate(
            [lengths, np.zeros((Bp - B,), lengths.dtype)])
    if flags.get("SDTPU_DISPATCH_LOG"):
        DISPATCH_LOG.append({"B": B, "Bp": Bp, "n_dev": n_dev})
    with jit_registry.device_scope("cas.ids"):
        digests = hasher(words, lengths)[:B]
        with jit_registry.io("cas.ids"):
            return digests_to_cas_ids(digests)
