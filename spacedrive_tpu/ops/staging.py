"""CAS staging: files → dense payload batches → CAS IDs, per backend.

This is the feeding layer between the filesystem and the batched BLAKE3
backends (SURVEY.md §7 phase 5 / hard-part 2 "feeding the beast"): the
reference hashes one file at a time inside per-file async tasks
(/root/reference/core/src/object/file_identifier/mod.rs:107-134 →
core/src/object/cas.rs:23-62); here whole batches are staged into dense
arrays and hashed at once.

Size classes keep device grids canonical (two compiled shapes only):
- LARGE (> 100 KiB): exactly 57,344 sampled bytes per row → [B, 57344].
- SMALL (≤ 100 KiB): whole file, zero-padded → [B, 102400] with lens.
Empty files get no CAS ID (cas_id = None), matching FileMetadata::new
(mod.rs:80-88).

Backends:
- "oracle": streaming pure-Python blake3 per file (the parity oracle).
- "numpy":  batched pad-and-mask blake3 on CPU.
- "jax":    the jitted device path (TPU when available).
"""

from __future__ import annotations

import atexit
import concurrent.futures
import itertools
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import cas, jit_registry
from .. import channels, chaos, flags, persist, tracing
from ..flight import RECORDER
from ..telemetry import (
    STAGE_BATCHES,
    STAGE_FALLBACK_FILES,
    STAGE_NATIVE_BYTES,
    STAGE_POOL_BUFFERS,
    STAGE_POOL_HIGH_WATER,
    STAGE_POOL_WORKERS,
)

# Monotone hashing-chunk ordinal for the flight recorder's "identify"
# scope: host-plane chunks get timeline lanes too, so the export shows
# the hash-ahead cadence next to the device pipeline's per-batch ring.
_CHUNK_SEQ = itertools.count(1).__next__

_STAGE_POOL: Optional[concurrent.futures.ThreadPoolExecutor] = None
_ATEXIT_REGISTERED = False
# Serializes pool-identity transitions (create / swap-out) WITH their
# gauge updates, so sd_stage_pool_workers always describes the current
# _STAGE_POOL — a shutdown racing a re-create cannot clobber the fresh
# pool's gauge with a late 0. The long shutdown(wait=True) itself runs
# outside the lock.
_POOL_LOCK = threading.Lock()


def _pool() -> concurrent.futures.ThreadPoolExecutor:
    """The shared staging executor, created lazily and visible to the
    lifecycle machinery: `sd_stage_pool_workers` reports its size (0
    when down), `shutdown_stage_pool()` is the explicit close hook
    `Node.shutdown()` drives (with an atexit backstop for bench CLIs
    that never build a Node), and the next submit after a shutdown
    simply re-creates the pool — multiple Nodes in one process share
    it safely."""
    global _STAGE_POOL, _ATEXIT_REGISTERED
    with _POOL_LOCK:
        if _STAGE_POOL is None:
            workers = min(32, (os.cpu_count() or 4) * 2)
            _STAGE_POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=workers,
                thread_name_prefix="cas-stage",
            )
            STAGE_POOL_WORKERS.set(workers)
            if not _ATEXIT_REGISTERED:
                _ATEXIT_REGISTERED = True
                atexit.register(shutdown_stage_pool)
        return _STAGE_POOL


def stage_pool() -> concurrent.futures.ThreadPoolExecutor:
    """Public spelling of the shared staging executor (the depth-N
    overlap pipeline submits its concurrent stage(i+1..i+k) here)."""
    return _pool()


def _submit(fn, *args) -> concurrent.futures.Future:
    """Submit to the shared pool, surviving a concurrent
    shutdown_stage_pool(): with two Nodes in one process, node A's
    shutdown can close the pool between node B's `_pool()` lookup and
    its `.submit()` — the RuntimeError retry clears the dead executor
    (only if nobody re-created it yet) and lands on a fresh one."""
    global _STAGE_POOL
    pool = _pool()
    try:
        return pool.submit(fn, *args)
    except RuntimeError:
        with _POOL_LOCK:
            if _STAGE_POOL is pool:
                _STAGE_POOL = None
                STAGE_POOL_WORKERS.set(0)
        return _pool().submit(fn, *args)


def shutdown_stage_pool(wait: bool = True) -> None:
    """Tear down the shared staging executor. Idempotent; in-flight
    reads finish when `wait` (the default — a half-staged batch must
    not observe freed numpy views). Wired into `Node.shutdown()` so
    the pool's threads no longer outlive the supervisor reap
    invisibly, and registered atexit as the backstop. The gauge zeroes
    AT the swap, under the lock: a pool re-created while this thread
    still drains the old one keeps its own (non-zero) gauge."""
    global _STAGE_POOL
    with _POOL_LOCK:
        pool, _STAGE_POOL = _STAGE_POOL, None
        if pool is not None:
            STAGE_POOL_WORKERS.set(0)
    if pool is not None:
        pool.shutdown(wait=wait)


@dataclass
class StagedBatch:
    """Dense payload arrays for one size class."""

    indexes: List[int]          # positions in the caller's file list
    payloads: np.ndarray        # [B, P] uint8, zero-padded
    sizes: np.ndarray           # [B] uint64 declared file sizes
    payload_lens: np.ndarray    # [B] int32 real payload bytes per row


def _read_large(path: str, size: int, out: np.ndarray) -> None:
    """Sampled read into a 57,344-byte row (cas.rs:23-59 spec)."""
    with open(path, "rb") as f:
        pos = 0
        spec = cas.sample_spec(size)
        for offset, length in spec[:-1]:
            f.seek(offset)
            chunk = f.read(length)
            if len(chunk) != length:
                raise EOFError(f"{path}: short read at {offset}")
            out[pos:pos + length] = np.frombuffer(chunk, dtype=np.uint8)
            pos += length
        f.seek(-cas.HEADER_OR_FOOTER_SIZE, os.SEEK_END)
        chunk = f.read(cas.HEADER_OR_FOOTER_SIZE)
        if len(chunk) != cas.HEADER_OR_FOOTER_SIZE:
            raise EOFError(f"{path}: short footer read")
        out[pos:pos + len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)


def _stage_files_native(
    files, large_idx, small_idx, empty_idx,
) -> Tuple[StagedBatch, StagedBatch, List[int], Dict[int, str]]:
    """Native plane staging (native/sdio.cpp): pooled pread into dense
    rows, no Python in the per-file loop."""
    from .. import native

    errors: Dict[int, str] = {}
    sizes = np.array([s for _, s in files], dtype=np.uint64)

    lpaths = [files[i][0] for i in large_idx]
    large, lstatus = native.stage_large(
        lpaths, sizes[large_idx] if large_idx else np.zeros(0, np.uint64))
    spaths = [files[i][0] for i in small_idx]
    small_wide, slens, sstatus = native.stage_small(
        spaths, cap=cas.MINIMUM_FILE_SIZE)
    small = small_wide[:, :cas.MINIMUM_FILE_SIZE]

    def filter_ok(idx_list, payloads, status, lens=None):
        bad_rows = np.nonzero(status != native.OK)[0]
        for row in bad_rows:
            errors[idx_list[row]] = (
                f"{files[idx_list[row]][0]}: "
                f"{native.STATUS_MESSAGES.get(int(status[row]), 'error')}")
        if len(bad_rows) == 0:
            return idx_list, payloads, lens
        ok = np.nonzero(status == native.OK)[0]
        return ([idx_list[r] for r in ok], payloads[ok],
                lens[ok] if lens is not None else None)

    large_idx, large, _ = filter_ok(large_idx, large, lstatus)
    small_idx, small, slens = filter_ok(small_idx, small, sstatus, slens)

    large_batch = StagedBatch(
        large_idx, large,
        sizes[large_idx] if large_idx else np.zeros(0, np.uint64),
        np.full((len(large_idx),), cas.LARGE_PAYLOAD_SIZE, dtype=np.int32))
    small_batch = StagedBatch(
        small_idx, small,
        sizes[small_idx] if small_idx else np.zeros(0, np.uint64),
        slens if slens is not None else np.zeros(0, np.int32))
    return large_batch, small_batch, empty_idx, errors


def stage_files(
    files: Sequence[Tuple[str, int]],
) -> Tuple[StagedBatch, StagedBatch, List[int], Dict[int, str]]:
    """Stage (path, size) pairs into dense per-class batches.

    Returns (large_batch, small_batch, empty_indexes, errors) where
    errors maps file index → message (unreadable files are skipped, the
    caller records them as non-fatal job errors — JobRunErrors semantics).
    """
    large_idx = [i for i, (_, s) in enumerate(files)
                 if s > cas.MINIMUM_FILE_SIZE]
    small_idx = [i for i, (_, s) in enumerate(files)
                 if 0 < s <= cas.MINIMUM_FILE_SIZE]
    empty_idx = [i for i, (_, s) in enumerate(files) if s == 0]
    errors: Dict[int, str] = {}

    from .. import native as _native
    # SDTPU_STAGE_NATIVE=off is the WHOLE native-staging escape hatch:
    # it pins not just the packed path (stage_batch_native) but these
    # classic native reads too, so "off" really means the pure-Python
    # readers — the baseline tools/overlap_bench.py --staging python
    # measures against.
    mode = str(flags.get("SDTPU_STAGE_NATIVE") or "auto")
    if mode not in ("off", "0", "no", "false") and _native.available():
        return _stage_files_native(files, large_idx, small_idx, empty_idx)

    large = np.zeros((len(large_idx), cas.LARGE_PAYLOAD_SIZE), dtype=np.uint8)
    small = np.zeros((len(small_idx), cas.MINIMUM_FILE_SIZE), dtype=np.uint8)
    small_lens = np.zeros((len(small_idx),), dtype=np.int32)

    def read_one(kind: str, row: int, idx: int) -> None:
        path, size = files[idx]
        try:
            if kind == "large":
                _read_large(path, size, large[row])
            else:
                with open(path, "rb") as f:
                    data = f.read(cas.MINIMUM_FILE_SIZE + 1)
                if len(data) > cas.MINIMUM_FILE_SIZE:
                    raise EOFError(
                        f"{path}: grew past declared size {size}")
                small[row, :len(data)] = np.frombuffer(data, dtype=np.uint8)
                small_lens[row] = len(data)
        except OSError as e:
            errors[idx] = f"{path}: {e}"
        except EOFError as e:
            errors[idx] = str(e)

    jobs = [("large", row, idx)
            for row, idx in enumerate(large_idx)] + \
           [("small", row, idx)
            for row, idx in enumerate(small_idx)]
    if threading.current_thread().name.startswith("cas-stage"):
        # Already ON a stage-pool worker (the depth-N pipeline stages
        # whole batches through the same executor): submitting the
        # per-file reads back into the pool and blocking on them can
        # starve — depth >= workers pins every worker on a batch whose
        # inner reads never get a thread. Nested staging reads inline;
        # batches still parallelize across the outer workers.
        for job in jobs:
            read_one(*job)
    else:
        futures = [_submit(read_one, *job) for job in jobs]
        for fut in futures:
            fut.result()

    sizes = np.array([s for _, s in files], dtype=np.uint64)
    large_batch = StagedBatch(
        large_idx, large, sizes[large_idx] if large_idx else
        np.zeros((0,), np.uint64),
        np.full((len(large_idx),), cas.LARGE_PAYLOAD_SIZE, dtype=np.int32))
    small_batch = StagedBatch(
        small_idx, small, sizes[small_idx] if small_idx else
        np.zeros((0,), np.uint64), small_lens)
    return large_batch, small_batch, empty_idx, errors


# -- native packed staging (zero-copy ring feed) ---------------------------
#
# The classic path above stages per-class payload matrices and then
# pays a full build_cas_messages pass — allocate a fresh [B, C*1024]
# buffer, write prefixes, copy every payload — before each H2D. The
# packed path below hands the C plane (native/sdio.cpp sd_stage_batch)
# a POOLED, page-aligned buffer and has it write the kernel's message
# layout directly: le64(size) ‖ payload ‖ zeros per row, per-row status
# for file-by-file degradation. The pooled pages are the H2D sources
# (np.frombuffer views, no copy) and recycle at batch RETIREMENT, so
# the pool is a declared bounded resource (ops.stage.pool window).


@dataclass
class StageLease:
    """One checked-out pooled page: `arr` is the [rows, stride] uint8
    zero-copy view over the anonymous mapping `buf`. Release returns
    the PAGE to the pool — the numpy views die with the lease holder,
    and the mapping itself is only reclaimed by GC once no view (or
    jax host alias) can reach it."""

    buf: "object"            # mmap.mmap backing pages
    nbytes: int              # pooled capacity of buf (>= rows*stride)
    arr: np.ndarray          # [rows, stride] uint8 view for this batch
    _pool: "StagePool"
    _released: bool = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._pool.release(self)


class StagePool:
    """Bounded pool of page-aligned staging pages (the donation ring's
    H2D sources). Anonymous mmap allocations are page-aligned by
    construction; a free page is reused for any batch whose rows fit
    its capacity. Checkouts are metered through the declared
    ops.stage.pool window — the capacity there (narrowable via
    SDTPU_STAGE_POOL_BUFFERS, never raisable) IS the bound: an
    exhausted pool returns None and the caller degrades to the Python
    staging path rather than allocating past it."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._free: List[Tuple[object, int]] = []  # [(mmap, nbytes)]
        self._total = 0
        self._high_water = 0
        self._win = channels.window("ops.stage.pool")

    def _cap(self) -> int:
        cap = self._win.capacity
        narrowed = int(flags.get("SDTPU_STAGE_POOL_BUFFERS") or 0)
        if narrowed > 0:
            cap = min(cap, narrowed)
        return max(1, cap)

    def acquire(self, rows: int, stride: int) -> Optional[StageLease]:
        import mmap as _mmap

        need = rows * stride
        with self._lock:
            mm = None
            # Smallest free page that fits; an all-too-small free list
            # drops one page (GC reclaims it once unreferenced) and
            # allocates at the new size — total never passes the cap.
            fits = [i for i, (_, cap) in enumerate(self._free)
                    if cap >= need]
            if fits:
                mm, nbytes = self._free.pop(
                    min(fits, key=lambda i: self._free[i][1]))
            elif self._total < self._cap():
                self._total += 1
            elif self._free:
                self._free.pop(0)
            else:
                return None  # every page checked out: degrade, not grow
            if mm is None:
                nbytes = need
                mm = _mmap.mmap(-1, need)
            in_use = self._total - len(self._free)
            if in_use > self._high_water:
                self._high_water = in_use
                STAGE_POOL_HIGH_WATER.set(in_use)
            STAGE_POOL_BUFFERS.set(in_use)
            self._win.note_put()
        arr = np.frombuffer(mm, dtype=np.uint8,
                            count=need).reshape(rows, stride)
        return StageLease(mm, nbytes, arr, self)

    def release(self, lease: StageLease) -> None:
        with self._lock:
            self._free.append((lease.buf, lease.nbytes))
            self._win.note_pop()
            STAGE_POOL_BUFFERS.set(self._total - len(self._free))


_BUF_POOL_LOCK = threading.Lock()
_STAGE_BUF_POOL: Optional[StagePool] = None


def stage_buffer_pool() -> StagePool:
    """The process-wide staging page pool (one declared window meters
    every ring)."""
    global _STAGE_BUF_POOL
    with _BUF_POOL_LOCK:
        if _STAGE_BUF_POOL is None:
            _STAGE_BUF_POOL = StagePool()
        return _STAGE_BUF_POOL


@dataclass
class NativeStaged:
    """A natively staged packed batch: row i corresponds to files[i].
    `words`/`lengths` are the kernel operands ([B, C, 256] uint32 view
    over the pooled page + [B] int32 message lengths); rows listed in
    `errors` failed BOTH the native reader and the per-file Python
    retry (their rows are scrubbed to the 8-byte prefix; ignore their
    digests), `empty_rows` are declared-empty files (no CAS ID)."""

    words: np.ndarray
    lengths: np.ndarray
    lease: StageLease
    errors: Dict[int, str]
    empty_rows: List[int]
    fallback_files: int = 0

    def release(self) -> None:
        self.lease.release()


def _grid_for(payload_cap: int) -> Tuple[int, int]:
    """(chunk grid C, row stride) for a payload class — the exact
    build_cas_messages shape."""
    c = max(1, -(-(cas.SIZE_PREFIX_LEN + payload_cap) // 1024))
    return c, c * 1024


def stage_batch_native(
    files: Sequence[Tuple[str, int]],
    pool: Optional[StagePool] = None,
) -> Optional[NativeStaged]:
    """Stage a batch straight into a pooled packed buffer via the C
    plane, or None to degrade the WHOLE batch to the Python path
    (flag off, libsdio.so missing — the fail-closed ladder — or pool
    exhausted). Individual bad rows (vanished file, permission, short
    read, injected EIO) degrade PER FILE: the Python reader retries
    into the same packed row, and only a row failing both lands in
    `errors`. Byte parity with stage_files + build_cas_messages is
    pinned by tests/test_staging_native.py."""
    mode = str(flags.get("SDTPU_STAGE_NATIVE") or "auto")
    if mode in ("off", "0", "no", "false"):
        return None
    from .. import native
    if not native.available():
        return None  # fail closed: the classic Python path
    n = len(files)
    if n == 0:
        return None
    sizes = np.array([s for _, s in files], dtype=np.uint64)
    any_small = bool(np.any((sizes > 0) & (sizes <= cas.MINIMUM_FILE_SIZE)))
    payload_cap = cas.MINIMUM_FILE_SIZE if any_small \
        else cas.LARGE_PAYLOAD_SIZE
    grid_c, stride = _grid_for(payload_cap)
    lease = (pool or stage_buffer_pool()).acquire(n, stride)
    if lease is None:
        return None  # bounded resource: degrade instead of growing
    try:
        msg_lens, status = native.stage_batch(
            [p for p, _ in files], sizes, lease.arr, payload_cap)
        if chaos.armed_point("stage.native.read"):
            f = chaos.hit("stage.native.read", only=("delay",))
            if f is not None:
                chaos.apply_sync(f)
            # Per-row draws so a probability storm speckles the batch
            # (file-by-file degradation) instead of all-or-nothing.
            for r in range(n):
                f = chaos.hit("stage.native.read",
                              only=("error", "corrupt"))
                if f is not None:
                    status[r] = (native.ERR_IO if f.kind == "error"
                                 else native.ERR_SHORT_READ)
        errors: Dict[int, str] = {}
        empty_rows: List[int] = []
        fallback = 0
        for r in np.nonzero(status != native.OK)[0]:
            r = int(r)
            if int(status[r]) == native.ERR_EMPTY:
                empty_rows.append(r)
                continue
            # Per-file fallback ladder: the Python oracle reader, into
            # the SAME packed row (zero-copy invariants hold — only
            # the bytes of this row change).
            path, size = files[r]
            row = lease.arr[r]
            try:
                if size > cas.MINIMUM_FILE_SIZE:
                    _read_large(path, size,
                                row[8:8 + cas.LARGE_PAYLOAD_SIZE])
                    plen = cas.LARGE_PAYLOAD_SIZE
                else:
                    with open(path, "rb") as fobj:
                        data = fobj.read(cas.MINIMUM_FILE_SIZE + 1)
                    if len(data) > cas.MINIMUM_FILE_SIZE:
                        raise EOFError(
                            f"{path}: grew past declared size {size}")
                    row[8:8 + len(data)] = np.frombuffer(data,
                                                         dtype=np.uint8)
                    plen = len(data)
                row[8 + plen:] = 0  # pooled page: scrub stale residue
                msg_lens[r] = 8 + plen
                status[r] = native.OK
                fallback += 1
            except (OSError, EOFError) as e:
                errors[r] = f"{path}: {e}"
                row[8:] = 0
                msg_lens[r] = 8
        words = lease.arr.view("<u4").reshape(n, grid_c, 256)
        STAGE_BATCHES.labels(backend="native").inc()
        STAGE_NATIVE_BYTES.inc(int(msg_lens.sum()))
        if fallback:
            STAGE_FALLBACK_FILES.inc(fallback)
        return NativeStaged(words, msg_lens, lease, errors, empty_rows,
                            fallback)
    except BaseException:
        lease.release()
        raise


# -- backends --------------------------------------------------------------


def _cas_ids_oracle(files, large, small) -> Dict[int, str]:
    out: Dict[int, str] = {}
    for batch in (large, small):
        for row, idx in enumerate(batch.indexes):
            payload = batch.payloads[row, :batch.payload_lens[row]].tobytes()
            out[idx] = cas.cas_id_of_payload(int(batch.sizes[row]), payload)
    return out


def _cas_ids_numpy(files, large, small) -> Dict[int, str]:
    # Deliberately jax-free: this is the fallback when jax is unavailable.
    from . import blake3_batch as bb
    out: Dict[int, str] = {}
    for batch in (large, small):
        if not batch.indexes:
            continue
        words, lengths = bb.build_cas_messages(
            batch.payloads, batch.sizes, batch.payload_lens)
        cvs = bb.blake3_batch(np, words, lengths)
        digests = np.stack(cvs, axis=1)
        for row, cid in enumerate(bb.digests_to_cas_ids(digests)):
            out[batch.indexes[row]] = cid
    return out


def _cas_ids_jax(files, large, small) -> Dict[int, str]:
    from .blake3_jax import cas_ids_jax
    out: Dict[int, str] = {}
    for batch in (large, small):
        if not batch.indexes:
            continue
        ids = cas_ids_jax(batch.payloads, batch.sizes, batch.payload_lens)
        out.update(zip(batch.indexes, ids))
    return out


_BACKENDS = {
    "oracle": _cas_ids_oracle,
    "numpy": _cas_ids_numpy,
    "jax": _cas_ids_jax,
}


# Below this batch size the device round-trip (H2D over the host link +
# dispatch + possible first compile) costs more than the fused native
# path; watcher-triggered single-file updates must never block on
# accelerator init. Identifier steps (100/step, reference parity) stay
# native — the device backend engages for the large analytics batches
# (dedup/pHash/bench) or when a job pins backend="jax".
JAX_MIN_BATCH = 256

# Auto device engagement for the identifier (VERDICT r1 item 3): scans
# with at least this many orphans consider the device pipeline, stepping
# in AUTO_DEVICE_BATCH-file chunks so each step is one device call.
AUTO_DEVICE_MIN_ORPHANS = 4096
AUTO_DEVICE_BATCH = 16384  # amortizes ~7-10 ms per-dispatch overhead

# When the device pipeline is NOT engaged, big scans still step in large
# chunks so the per-chunk Python/SQL orchestration (orphan page fetch,
# op building, transaction commit) amortizes over thousands of files
# instead of the reference's 100 (file_identifier/mod.rs:36). The native
# C++ plane streams per file, so chunk size costs no extra memory.
# Sized by interleaved A/B on the 1M corpus (the bench host's IO
# weather swings 2x between windows, so only same-window pairs count):
# 4096 beat 16384 in both interleaved pairs (53/58 s vs 69/80 s);
# sequential runs had earlier suggested the opposite, confounded by
# weather. Bigger chunks also grow the crash-replay window 4x.
AUTO_NATIVE_BATCH = 4096

# The CAS pipeline is H2D-bound end-to-end (the pallas kernel sustains
# ~30 GB/s, the AVX2 native plane ~3.5 GB/s): shipping bytes to the
# device only pays when the host→device link is faster than the native
# plane hashes. Probed once per process; SDTPU_DEVICE_PIPELINE=force/off
# overrides (the bench host's tunnel link fluctuates 0.02-1.2 GB/s, a
# real v5e PCIe host measures 10+ GB/s).
NATIVE_PLANE_GBPS = 3.5
_H2D_GBPS: Optional[float] = None


_H2D_PROBE_TTL = 3600.0


def _h2d_cache_path() -> Optional[str]:
    """Probe-cache file inside a private 0700 per-user dir (a fixed name
    directly in world-writable /tmp could be pre-created or symlinked by
    another local user). Returns None when no safe dir can be had."""
    import stat
    import tempfile

    d = os.path.join(tempfile.gettempdir(), f"sdtpu-{os.getuid()}")
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
        st = os.lstat(d)
        if (not stat.S_ISDIR(st.st_mode) or st.st_uid != os.getuid()
                or st.st_mode & 0o077):
            return None
    except OSError:
        return None
    return os.path.join(d, "h2d_probe.json")


def _h2d_probe_key() -> Optional[str]:
    """Cache key binding a probe result to the device set it measured:
    backend platform + device count. The on-disk cache outlives the
    process — without the key, a stale CPU-backend probe (a laptop
    run, a tier-1 test) would mis-calibrate the ring on a bench host
    for up to the TTL. None (jax unavailable) disables the disk cache
    rather than trusting an unkeyed entry."""
    try:
        import jax

        devs = jax.devices()
        return f"{devs[0].platform}:{len(devs)}"
    except Exception:
        return None


# Advisory last-writer-wins probe cache: a racing writer's value is
# as good as ours (same link, same hour) and a torn LOGICAL state is
# impossible — each write replaces the whole doc and a stale/invalid
# doc is simply re-probed.
# sdlint: ok[crash-atomicity]
def h2d_gbps() -> float:
    """Measured host→device link bandwidth, probed once per process and
    cached on disk for an hour (the probe itself costs a round trip, and
    every identifier-job init consults it).

    Sync is a FULL D2H fetch of the buffer: `block_until_ready` returns
    early on the axon platform, and a sliced fetch (`w[0]`) would compile
    a slice program remotely — seconds through the tunnel. The full
    round trip measures H2D+D2H; assuming a roughly symmetric link the
    per-direction rate is 2*nbytes/rt — the right go/no-go signal for
    the H2D-bound CAS pipeline.

    SDTPU_H2D_GBPS overrides (tests, benchmark pinning).
    """
    global _H2D_GBPS
    if _H2D_GBPS is not None:
        return _H2D_GBPS
    env = flags.get("SDTPU_H2D_GBPS")
    if env is not None:
        _H2D_GBPS = env
        return _H2D_GBPS
    import json
    import time

    cache = _h2d_cache_path()
    key = _h2d_probe_key() if cache is not None else None
    if cache is not None and key is not None:
        try:
            with open(cache) as f:
                saved = json.load(f)
            # Entries are only valid for the SAME backend + device set
            # that measured them (pre-key entries have no "key" and
            # re-probe once).
            if (time.time() - saved["t"] < _H2D_PROBE_TTL
                    and saved.get("key") == key):
                _H2D_GBPS = float(saved["gbps"])
                return _H2D_GBPS
        except Exception:
            pass
    ok = False
    try:
        import jax

        buf = np.zeros((8 << 20,), dtype=np.uint8)
        with jit_registry.io("staging.h2d_probe"):
            np.asarray(jax.device_put(buf))  # warm
            t0 = time.perf_counter()
            np.asarray(jax.device_put(buf))
            rt = time.perf_counter() - t0
        # Round trip moves the buffer twice; assuming a roughly
        # symmetric link, one direction runs at 2*nbytes/rt.
        _H2D_GBPS = 2 * buf.nbytes / rt / 1e9
        ok = True
    except Exception:
        _H2D_GBPS = 0.0
    if ok and cache is not None and key is not None:
        # Only successful probes are cached: a transient jax/device
        # failure must stay per-process, not poison an hour of runs.
        try:
            persist.atomic_write(
                "stage.h2d_cache", cache,
                json.dumps({"t": time.time(), "gbps": _H2D_GBPS,
                            "key": key}))
        except OSError:
            pass
    return _H2D_GBPS


def device_pipeline_worthwhile() -> bool:
    """True when staging→H2D→kernel beats the native CPU plane."""
    mode = flags.get("SDTPU_DEVICE_PIPELINE")
    if mode in ("force", "1"):
        return True
    if mode in ("off", "0"):
        return False
    try:
        import jax

        if jax.devices()[0].platform not in ("tpu", "axon"):
            return False
    except Exception:
        return False
    return h2d_gbps() > NATIVE_PLANE_GBPS


def auto_device_batch(orphan_count: int) -> Optional[int]:
    """Device step size for an identifier scan, or None to stay native.

    Engages the device for big scans (≥ AUTO_DEVICE_MIN_ORPHANS) when
    the link probe says the device pipeline wins (or is forced).
    """
    if orphan_count < AUTO_DEVICE_MIN_ORPHANS:
        return None
    if not device_pipeline_worthwhile():
        return None
    return AUTO_DEVICE_BATCH


def default_backend(batch_size: int = JAX_MIN_BATCH) -> str:
    """"jax" for device-worthy batches when jax is importable; below that
    the fused native C++ path when built, else batched numpy."""
    from .. import native as _native
    if batch_size < JAX_MIN_BATCH:
        return "native" if _native.available() else "numpy"
    try:
        import jax  # noqa: F401
        return "jax"
    except Exception:
        return "native" if _native.available() else "numpy"


def _cas_ids_native_fused(
    files: Sequence[Tuple[str, int]],
) -> Tuple[Dict[int, Optional[str]], Dict[int, str]]:
    """Fused native stage+hash — one C call for the whole batch."""
    from .. import native

    digests, status = native.cas_digests(
        [p for p, _ in files], np.array([s for _, s in files], np.uint64))
    ids: Dict[int, Optional[str]] = {}
    errors: Dict[int, str] = {}
    for i, st in enumerate(status):
        if st == native.OK:
            ids[i] = digests[i].tobytes().hex()[:16]
        elif st == native.ERR_EMPTY:
            ids[i] = None  # no CAS ID for empty files (mod.rs:86)
        else:
            errors[i] = (f"{files[i][0]}: "
                         f"{native.STATUS_MESSAGES.get(int(st), 'error')}")
    return ids, errors


def cas_ids_for_files(
    files: Sequence[Tuple[str, int]], backend: str = "auto",
) -> Tuple[Dict[int, Optional[str]], Dict[int, str]]:
    """(path, size) pairs → {index: cas_id | None for empty}, {index: error}.

    The identifier job's per-chunk kernel: stage + batch hash + format.
    """
    from ..telemetry import (
        IDENT_BATCHES,
        IDENT_BATCH_FILES,
        IDENT_BYTES_HASHED,
        IDENT_DEVICE_FALLBACK,
        IDENT_READ_ERRORS,
    )
    from ..tracing import device_span

    if backend == "auto":
        backend = default_backend(len(files))
        if backend == "jax" and not device_pipeline_worthwhile():
            # The CAS pipeline is H2D-bound: a device-worthy *batch size*
            # is not enough when the host→device link is slower than the
            # native plane hashes (compute-bound callers like phash make
            # their own call via default_backend directly).
            from .. import native as _native
            backend = "native" if _native.available() else "numpy"
            IDENT_DEVICE_FALLBACK.inc()
    IDENT_BATCHES.labels(backend=backend).inc()
    IDENT_BATCH_FILES.observe(len(files))
    # Payload-byte accounting (what the hashers actually consume): one
    # pass over the size list, ~ns/file against a ms/file pipeline.
    IDENT_BYTES_HASHED.inc(sum(
        cas.LARGE_PAYLOAD_SIZE if s > cas.MINIMUM_FILE_SIZE else s
        for _, s in files))
    chunk = _CHUNK_SEQ()
    if backend == "native":
        with device_span("cas_ids/native", batch=len(files)):
            t0 = time.perf_counter()
            ids, errors = _cas_ids_native_fused(files)
            # Fused stage+hash is one C call: one timeline event, on
            # the kernel lane (there is no separable stage phase).
            RECORDER.record(
                "kernel", batch=chunk, t0=t0, t1=time.perf_counter(),
                device="native", scope="identify",
                trace=tracing.current_trace_id(), files=len(files))
        if errors:
            IDENT_READ_ERRORS.inc(len(errors))
        return ids, errors
    # Staging (the file reads) belongs INSIDE the span on every backend
    # so cross-backend span timings stay comparable. The jax backend
    # additionally runs under the sanitizer's D2H transfer guard: the
    # only sanctioned fetch in this region is cas_ids_jax's declared
    # io("cas.ids") scope — anything else raises in tier-1.
    from contextlib import nullcontext

    guard = (jit_registry.device_scope(f"cas_ids/{backend}")
             if backend == "jax" else nullcontext())
    with device_span(f"cas_ids/{backend}", batch=len(files)), guard:
        trace = tracing.current_trace_id()
        t0 = time.perf_counter()
        large, small, empty_idx, errors = stage_files(files)
        t1 = time.perf_counter()
        ids: Dict[int, Optional[str]] = dict(
            _BACKENDS[backend](files, large, small))
        # Host-plane chunks get the same stage/kernel lanes as the
        # depth-N pipeline (scope "identify"): the exporter shows
        # hash-ahead chunk cadence next to the device ring's lanes.
        RECORDER.record("stage", batch=chunk, t0=t0, t1=t1,
                        device=backend, scope="identify", trace=trace,
                        files=len(files))
        RECORDER.record("kernel", batch=chunk, t0=t1,
                        t1=time.perf_counter(), device=backend,
                        scope="identify", trace=trace,
                        files=len(files))
    for idx in empty_idx:
        ids[idx] = None  # "We can't do shit with empty files" (mod.rs:86)
    for idx in errors:
        ids.pop(idx, None)
    if errors:
        IDENT_READ_ERRORS.inc(len(errors))
    return ids, errors
