"""Pure-Python streaming BLAKE3 (all three modes), from the public spec.

This is the correctness oracle for every other BLAKE3 implementation in the
framework (numpy batched, JAX batched, Pallas kernel, C++ native). The
environment ships no `blake3` wheel, so parity is established against the
official test vectors (input = repeating 0..250 byte pattern) plus
self-consistency between streaming and one-shot use.

Reference behavior being matched: the `blake3` crate as used by
/root/reference/core/src/object/cas.rs:23-62 (CAS IDs) and
/root/reference/core/src/object/validation/hash.rs:10-24 (full checksums).

All three modes are implemented: plain hash (the identification paths use
`Hasher::new()` only), keyed hash, and derive-key — the latter two are the
KDF primitives behind the crypto subsystem's key derivation, matching
`blake3::derive_key` as used by /root/reference/crates/crypto/src/keys.
"""

from __future__ import annotations

import struct

__all__ = [
    "Blake3", "blake3_hex", "blake3_digest", "blake3_keyed", "derive_key",
]

_MASK = 0xFFFFFFFF

IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)

MSG_PERMUTATION = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

BLOCK_LEN = 64
CHUNK_LEN = 1024

CHUNK_START = 1 << 0
CHUNK_END = 1 << 1
PARENT = 1 << 2
ROOT = 1 << 3
KEYED_HASH = 1 << 4
DERIVE_KEY_CONTEXT = 1 << 5
DERIVE_KEY_MATERIAL = 1 << 6


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & _MASK


def _g(s: list, a: int, b: int, c: int, d: int, mx: int, my: int) -> None:
    s[a] = (s[a] + s[b] + mx) & _MASK
    s[d] = _rotr(s[d] ^ s[a], 16)
    s[c] = (s[c] + s[d]) & _MASK
    s[b] = _rotr(s[b] ^ s[c], 12)
    s[a] = (s[a] + s[b] + my) & _MASK
    s[d] = _rotr(s[d] ^ s[a], 8)
    s[c] = (s[c] + s[d]) & _MASK
    s[b] = _rotr(s[b] ^ s[c], 7)


def compress(cv, block_words, counter: int, block_len: int, flags: int) -> list:
    """One BLAKE3 compression; returns the full 16-word output state.

    Words 0..8 are the new chaining value; words 8..16 only matter for
    extended output (not used by the framework, kept for spec completeness).
    """
    s = [
        cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
        IV[0], IV[1], IV[2], IV[3],
        counter & _MASK, (counter >> 32) & _MASK, block_len, flags,
    ]
    m = list(block_words)
    for r in range(7):
        _g(s, 0, 4, 8, 12, m[0], m[1])
        _g(s, 1, 5, 9, 13, m[2], m[3])
        _g(s, 2, 6, 10, 14, m[4], m[5])
        _g(s, 3, 7, 11, 15, m[6], m[7])
        _g(s, 0, 5, 10, 15, m[8], m[9])
        _g(s, 1, 6, 11, 12, m[10], m[11])
        _g(s, 2, 7, 8, 13, m[12], m[13])
        _g(s, 3, 4, 9, 14, m[14], m[15])
        if r < 6:
            m = [m[p] for p in MSG_PERMUTATION]
    return [
        s[0] ^ s[8], s[1] ^ s[9], s[2] ^ s[10], s[3] ^ s[11],
        s[4] ^ s[12], s[5] ^ s[13], s[6] ^ s[14], s[7] ^ s[15],
        s[8] ^ cv[0], s[9] ^ cv[1], s[10] ^ cv[2], s[11] ^ cv[3],
        s[12] ^ cv[4], s[13] ^ cv[5], s[14] ^ cv[6], s[15] ^ cv[7],
    ]


def _words_of_block(block: bytes) -> list:
    if len(block) < BLOCK_LEN:
        block = block + b"\x00" * (BLOCK_LEN - len(block))
    return list(struct.unpack("<16I", block))


class _ChunkState:
    __slots__ = ("cv", "counter", "buf", "blocks_compressed", "key", "base")

    def __init__(self, counter: int, key=IV, base_flags: int = 0):
        self.cv = list(key)
        self.key = key
        self.base = base_flags
        self.counter = counter
        self.buf = b""
        self.blocks_compressed = 0

    def _start_flag(self) -> int:
        return (CHUNK_START if self.blocks_compressed == 0 else 0) | self.base

    def length(self) -> int:
        return self.blocks_compressed * BLOCK_LEN + len(self.buf)

    def update(self, data: bytes) -> bytes:
        """Absorb up to a chunk's worth; returns unconsumed remainder."""
        while data:
            if len(self.buf) == BLOCK_LEN:
                # Only compress a full block once more input exists, so the
                # chunk's final block keeps its CHUNK_END flag available.
                out = compress(
                    self.cv, _words_of_block(self.buf), self.counter,
                    BLOCK_LEN, self._start_flag(),
                )
                self.cv = out[:8]
                self.blocks_compressed += 1
                self.buf = b""
            want = BLOCK_LEN - len(self.buf)
            take, data = data[:want], data[want:]
            self.buf += take
            if self.length() == CHUNK_LEN:
                break
        return data

    def output(self, extra_flags: int) -> list:
        flags = self._start_flag() | CHUNK_END | extra_flags
        out = compress(
            self.cv, _words_of_block(self.buf), self.counter,
            len(self.buf), flags,
        )
        return out[:8]


def _parent_words(left_cv, right_cv) -> list:
    return list(left_cv) + list(right_cv)


def _key_words(key: bytes) -> tuple:
    if len(key) != 32:
        raise ValueError("BLAKE3 key must be exactly 32 bytes")
    return struct.unpack("<8I", key)


class Blake3:
    """Streaming BLAKE3 hasher (hash, keyed-hash, and derive-key modes)."""

    def __init__(self, key: bytes | None = None, _flags: int = 0) -> None:
        if key is not None:
            self._key = _key_words(key)
            self._flags = _flags or KEYED_HASH
        else:
            self._key = IV
            self._flags = _flags
        self._chunk = _ChunkState(0, self._key, self._flags)
        self._cv_stack: list = []  # chaining values of completed subtrees

    def update(self, data: bytes) -> "Blake3":
        while data:
            if self._chunk.length() == CHUNK_LEN:
                # chunk complete and more input follows: finalize it as a
                # non-root leaf and fold the CV stack like a binary counter.
                cv = self._chunk.output(0)
                total = self._chunk.counter + 1
                while total & 1 == 0:
                    cv = compress(
                        self._key, _parent_words(self._cv_stack.pop(), cv),
                        0, BLOCK_LEN, PARENT | self._flags,
                    )[:8]
                    total >>= 1
                self._cv_stack.append(cv)
                self._chunk = _ChunkState(
                    self._chunk.counter + 1, self._key, self._flags)

            data = self._chunk.update(data)
        return self

    def digest(self, length: int = 32) -> bytes:
        if length > 64:
            raise ValueError("extended output beyond 64 bytes not implemented")
        if not self._cv_stack:
            out16 = compress(
                self._chunk.cv, _words_of_block(self._chunk.buf),
                self._chunk.counter, len(self._chunk.buf),
                self._chunk._start_flag() | CHUNK_END | ROOT,
            )
        else:
            cv = self._chunk.output(0)
            # Fold the stack top-down; the last (bottom-most) merge is root.
            for i in range(len(self._cv_stack) - 1, 0, -1):
                cv = compress(
                    self._key, _parent_words(self._cv_stack[i], cv),
                    0, BLOCK_LEN, PARENT | self._flags,
                )[:8]
            out16 = compress(
                self._key, _parent_words(self._cv_stack[0], cv),
                0, BLOCK_LEN, PARENT | ROOT | self._flags,
            )
        return struct.pack("<16I", *out16)[:length]

    def hexdigest(self, length: int = 32) -> str:
        return self.digest(length).hex()


def blake3_digest(data: bytes, length: int = 32) -> bytes:
    return Blake3().update(data).digest(length)


def blake3_hex(data: bytes, length: int = 32) -> str:
    return Blake3().update(data).hexdigest(length)


def blake3_keyed(key: bytes, data: bytes, length: int = 32) -> bytes:
    """Keyed-hash mode (MAC)."""
    return Blake3(key=key).update(data).digest(length)


def derive_key(context: str, key_material: bytes, length: int = 32) -> bytes:
    """BLAKE3 derive-key mode: hash the context string in
    DERIVE_KEY_CONTEXT mode to get a context key, then hash the key
    material keyed by it in DERIVE_KEY_MATERIAL mode — the KDF the
    reference's crypto crate invokes as ``blake3::derive_key`` with its
    fixed context strings (crates/crypto/src/primitives.rs:61-68)."""
    ctx_key = Blake3(_flags=DERIVE_KEY_CONTEXT).update(
        context.encode()).digest(32)
    return Blake3(
        key=ctx_key, _flags=DERIVE_KEY_MATERIAL,
    ).update(key_material).digest(length)
