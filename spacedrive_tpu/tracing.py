"""Structured tracing + device profiling.

The reference leans on the `tracing` ecosystem (rolling file logs, span
timing at debug level — SURVEY.md §5); the TPU-native equivalent is a
structured span log plus optional `jax.profiler` capture around device
batches:

- `span(name)` times a block and logs one structured line through the
  standard logging machinery (and the node event bus when attached).
  Spans are HIERARCHICAL: each carries a 64-bit trace id shared with
  every span under the same root, its own span id, and its parent's
  span id — propagated via a contextvar, so nesting survives
  `asyncio.to_thread` and task boundaries (both copy the context).
  Every finished span records `ok`/`error` (a body that raised is
  distinguishable in logs and the ring buffer) and lands in a bounded
  ring of recent spans queryable at runtime (`recent_spans`, served by
  the `node.spans` rspc query);
- when `SDTPU_PROFILE=/path` is set, `device_span(name)` additionally
  wraps the block in a jax profiler trace so device batches show up in
  TensorBoard/xprof with step markers;
- spans PROPAGATE across nodes: `traceparent()` renders the current
  (trace, span) as a compact wire field, `continue_trace(tp)` adopts a
  remote caller's ids so the first span opened inside becomes a child
  of the remote span — one trace id then covers a request end-to-end
  over the p2p/sync/rspc planes (the flight recorder's export path,
  spacedrive_tpu/flight.py, renders the merged timeline).

Span NAMES come from the central family registry at the bottom of this
module (`declare_span`): a span name is `<family>` or
`<family>/<variant>`, and the family must be declared — the sdlint
telemetry pass fails the build on an undeclared or fully-dynamic name,
the same scheme discipline metric families get.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import flags, telemetry

logger = logging.getLogger("spacedrive_tpu")

# (trace_id, span_id) of the innermost live span in this context.
_current_span: contextvars.ContextVar[Optional[Tuple[int, int]]] = \
    contextvars.ContextVar("sdtpu_current_span", default=None)

# Bounded ring of recently finished span records (newest last). The
# default 512 records × ~200 B is ~100 KB — queryable at runtime
# without ever growing with uptime; SDTPU_SPAN_RING resizes it (read
# once at import; configure_span_ring() is the re-read hook). Floored
# at 1 like the re-read path: 0/negative would disable the ring (or
# crash deque construction) instead of erroring usefully.
SPAN_RING_CAPACITY = max(1, int(flags.get("SDTPU_SPAN_RING")))
_span_ring: deque = deque(maxlen=SPAN_RING_CAPACITY)
_span_ring_lock = threading.Lock()
# Ids are sequential above a random 48-bit per-process base: cheap to
# mint under the lock, and two NODES (separate processes) joined by
# trace propagation cannot collide on trace ids.
_ID_BASE = (int.from_bytes(os.urandom(6), "big") << 14) + 1
_id_counter = iter(range(_ID_BASE, 1 << 63)).__next__
_id_lock = threading.Lock()

# Wall-clock anchor for span/timeline timestamps: perf_counter gives
# the monotone durations, this epoch aligns them to wall microseconds
# so two nodes' exported traces land on one comparable axis.
_EPOCH = time.time() - time.perf_counter()


def perf_to_us(t_perf: float) -> int:
    """A time.perf_counter() reading as wall-clock microseconds (the
    Chrome-trace `ts` unit)."""
    return int((_EPOCH + t_perf) * 1e6)


def _new_id() -> int:
    with _id_lock:
        return _id_counter()


def span_ring_capacity() -> int:
    return SPAN_RING_CAPACITY


def configure_span_ring() -> int:
    """Re-read SDTPU_SPAN_RING and rebuild the ring, keeping the newest
    records that fit. The flag is otherwise read once at import (the
    ring is module-global); tests and long-lived embedders that change
    the environment call this to apply it."""
    global SPAN_RING_CAPACITY, _span_ring
    cap = max(1, int(flags.get("SDTPU_SPAN_RING")))
    with _span_ring_lock:
        if cap != SPAN_RING_CAPACITY:
            SPAN_RING_CAPACITY = cap
            _span_ring = deque(_span_ring, maxlen=cap)
    return SPAN_RING_CAPACITY


# -- cross-node propagation -------------------------------------------------

def current_trace() -> Optional[Tuple[int, int]]:
    """(trace_id, span_id) of the innermost live span, or None."""
    return _current_span.get()


def current_trace_id() -> Optional[str]:
    """Hex trace id of the innermost live span, or None — what the
    flight recorder stamps on pipeline timeline events."""
    cur = _current_span.get()
    return f"{cur[0]:x}" if cur else None


def traceparent() -> Optional[str]:
    """The current span as a compact `<trace>-<span>` hex wire field —
    carried in p2p headers, sync pull frames, and rspc envelopes so the
    remote side's spans continue this trace instead of rooting a new
    one. None outside any span (the remote side then roots normally)."""
    cur = _current_span.get()
    return f"{cur[0]:x}-{cur[1]:x}" if cur else None


def parse_traceparent(tp: Any) -> Optional[Tuple[int, int]]:
    """Parse a wire traceparent; None for anything malformed — a
    hostile or stale peer field must degrade to a fresh root, never
    raise into the transport handler."""
    if not isinstance(tp, str) or "-" not in tp:
        return None
    trace_s, _, span_s = tp.partition("-")
    try:
        trace_id, span_id = int(trace_s, 16), int(span_s, 16)
    except ValueError:
        return None
    if not (0 < trace_id < 1 << 64 and 0 < span_id < 1 << 64):
        return None
    return trace_id, span_id


@contextlib.contextmanager
def continue_trace(tp: Any):
    """Adopt a remote caller's (trace, span) for the block: spans
    opened inside become children of the remote span, sharing its
    trace id across the wire. A missing/malformed `tp` is a no-op —
    the block's spans root locally as before."""
    parsed = parse_traceparent(tp)
    if parsed is None:
        yield
        return
    token = _current_span.set(parsed)
    try:
        yield
    finally:
        _current_span.reset(token)


def recent_spans(limit: int = 100,
                 trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Newest-last slice of the span ring buffer, optionally filtered
    to one trace. Records are JSON-safe dicts."""
    with _span_ring_lock:
        records = list(_span_ring)
    if trace_id is not None:
        records = [r for r in records if r.get("trace") == trace_id]
    limit = int(limit)
    return records[-limit:] if limit > 0 else []


def clear_span_ring() -> None:
    """Test hook: empty the ring buffer."""
    with _span_ring_lock:
        _span_ring.clear()


# -- trace-correlated structured logging (SDTPU_LOG_JSON) --------------------

class JsonLogFormatter(logging.Formatter):
    """One JSON object per log record, stamped with the CURRENT
    trace/span id at emit time. Emission is synchronous with the
    logging call and the span contextvar survives `asyncio.to_thread`
    and task boundaries, so a worker-side log line inside a span
    carries that span's trace id — log lines join node.spans and the
    flight-recorder export on one correlation key."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        cur = _current_span.get()
        if cur is not None:
            out["trace"] = f"{cur[0]:x}"
            out["span"] = f"{cur[1]:x}"
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


_json_handler: Optional[logging.Handler] = None
_json_handler_lock = threading.Lock()


def install_json_logging(force: bool = False, stream=None) -> bool:
    """Attach the JSON-line handler to the `spacedrive_tpu` logger
    when the SDTPU_LOG_JSON flag is on (or `force` is set). Idempotent
    — one handler per process no matter how many nodes boot. Returns
    whether the handler is installed afterwards."""
    global _json_handler
    with _json_handler_lock:
        if _json_handler is not None:
            return True
        if not force and not flags.get("SDTPU_LOG_JSON"):
            return False
        h = logging.StreamHandler(stream)
        h.setFormatter(JsonLogFormatter())
        logger.addHandler(h)
        _json_handler = h
    return True


def uninstall_json_logging() -> None:
    """Test/embedder hook: detach the JSON handler installed above."""
    global _json_handler
    with _json_handler_lock:
        if _json_handler is not None:
            logger.removeHandler(_json_handler)
            _json_handler = None


class LogRing(logging.Handler):
    """Bounded in-memory log ring: the recoverable copy of the
    process's recent log lines. Records are the same JSON-safe dicts
    the JSON formatter emits — ts/level/logger/msg plus the CURRENT
    trace/span id — held in the declared `tracing.logring` channel
    (shed_oldest), so the tail joins spans and the flight-recorder
    export on one correlation key and never grows with uptime. The
    incident observatory freezes `tail()` into every evidence bundle;
    stderr is write-only, this ring is what survives into a
    postmortem."""

    def __init__(self) -> None:
        super().__init__()
        from . import channels
        self.ring = channels.channel("tracing.logring")

    def emit(self, record: logging.LogRecord) -> None:
        try:
            out: Dict[str, Any] = {
                "ts": round(record.created, 3),
                "level": record.levelname,
                "logger": record.name,
                "msg": record.getMessage(),
            }
            cur = _current_span.get()
            if cur is not None:
                out["trace"] = f"{cur[0]:x}"
                out["span"] = f"{cur[1]:x}"
            if record.exc_info and record.exc_info[0] is not None:
                out["exc"] = record.exc_info[0].__name__
            self.ring.put_nowait(out)
        except Exception:
            self.handleError(record)

    def tail(self, limit: int = 100) -> List[Dict[str, Any]]:
        # All ring access is serialized by the handler lock: logging
        # holds it around every emit(), and tail() takes it here — the
        # ring needs no loop affinity of its own.
        with self.lock:
            records = [dict(r) for r in self.ring]
        limit = int(limit)
        return records[-limit:] if limit > 0 else []


_log_ring: Optional[LogRing] = None
_log_ring_lock = threading.Lock()


def install_log_ring(force: bool = False) -> bool:
    """Attach the LogRing handler to the `spacedrive_tpu` logger when
    the SDTPU_LOG_RING flag is on (or `force` is set). Idempotent —
    one ring per process no matter how many nodes boot. Returns
    whether the ring is installed afterwards."""
    global _log_ring
    with _log_ring_lock:
        if _log_ring is not None:
            return True
        if not force and not flags.get("SDTPU_LOG_RING"):
            return False
        h = LogRing()
        logger.addHandler(h)
        _log_ring = h
    return True


def uninstall_log_ring() -> None:
    """Test/embedder hook: detach the LogRing handler."""
    global _log_ring
    with _log_ring_lock:
        if _log_ring is not None:
            logger.removeHandler(_log_ring)
            _log_ring = None


def log_ring_tail(limit: int = 100) -> List[Dict[str, Any]]:
    """Newest-last tail of the installed log ring ([] when the ring
    is not installed) — the bundle-assembly entry point."""
    with _log_ring_lock:
        ring = _log_ring
    return ring.tail(limit) if ring is not None else []


# -- profiler (SDTPU_PROFILE) ----------------------------------------------

# Tri-state probe cache: None = not yet probed, False = profiling off
# (env unset/empty, or a start failure), True = trace running. Cached so
# the device_span hot path is a single attribute check instead of an
# os.environ read per call; reset_profiler_cache() is the documented
# hook for tests/hosts that toggle SDTPU_PROFILE after import.
_profiler_state: Optional[bool] = None
_profiler_lock = threading.Lock()


def reset_profiler_cache() -> None:
    """Forget the cached SDTPU_PROFILE probe so the next device_span
    re-reads the environment (does NOT stop a running trace)."""
    global _profiler_state
    with _profiler_lock:
        if not _profiler_state:
            _profiler_state = None


def _ensure_profiler() -> bool:
    """Start the jax trace once if SDTPU_PROFILE is set. The result —
    positive or negative — is cached; hosts that set the env var after
    import call reset_profiler_cache(). ANY profiling problem — no jax,
    unwritable path, double-start race — degrades to plain spans;
    device batches run from thread-pool workers, so the start is
    lock-guarded."""
    global _profiler_state
    state = _profiler_state
    if state is not None:
        return state
    with _profiler_lock:
        if _profiler_state is not None:
            return _profiler_state
        profile_dir = flags.get("SDTPU_PROFILE")
        if not profile_dir:
            _profiler_state = False
            return False
        try:
            import jax

            jax.profiler.start_trace(profile_dir)
        except Exception as e:
            _profiler_state = False
            logger.warning("SDTPU_PROFILE disabled: %s", e)
            return False
        _profiler_state = True
        import atexit

        # Process-scope flush. Deliberately NOT hooked into per-node
        # shutdown: the profiler is process-global and multiple nodes
        # share one process in tests.
        atexit.register(stop_profiler)
    return True


def stop_profiler() -> None:
    global _profiler_state
    if _profiler_state:
        import jax

        jax.profiler.stop_trace()
        _profiler_state = None


@contextlib.contextmanager
def span(name: str, events=None, **fields):
    """Time a block; emit one structured record at debug level (the
    reference's ad-hoc Instant deltas, job/mod.rs:592,638).

    The record carries `trace` (shared by all spans under one root),
    `id`, `parent` (absent for roots), and `ok`/`error` — a raising
    body produces ok=False plus the exception type, so failed phases
    are distinguishable downstream. `events` may be an object with an
    `.emit(dict)` method (the node EventBus) or a bare callable."""
    parent = _current_span.get()
    if parent is None:
        trace_id, parent_id = _new_id(), None
    else:
        trace_id, parent_id = parent[0], parent[1]
    span_id = _new_id()
    token = _current_span.set((trace_id, span_id))
    t0 = time.perf_counter()
    err: Optional[BaseException] = None
    try:
        yield
    except BaseException as e:
        err = e
        raise
    finally:
        _current_span.reset(token)
        ms = (time.perf_counter() - t0) * 1000
        record = {
            "span": name, "ms": round(ms, 2),
            # Start timestamp in wall microseconds: what the Chrome-
            # trace exporter uses as `ts` (dur comes from `ms`), and
            # what makes two nodes' rings mergeable on one axis.
            "ts_us": perf_to_us(t0),
            "trace": f"{trace_id:x}", "id": f"{span_id:x}",
            "ok": err is None,
            **fields,
        }
        if parent_id is not None:
            record["parent"] = f"{parent_id:x}"
        if err is not None:
            record["error"] = type(err).__name__
        telemetry.TRACE_SPANS.labels(
            ok="true" if err is None else "false").inc()
        with _span_ring_lock:
            _span_ring.append(record)
        logger.debug("span %s", record)
        if events is not None:
            emit_fn = getattr(events, "emit", events)
            emit_fn({"type": "TraceSpan", **record})


@contextlib.contextmanager
def device_span(name: str, events=None, **fields):
    """span() + named jax profiler trace context when profiling is on."""
    if _ensure_profiler():
        import jax

        with jax.profiler.TraceAnnotation(name):
            with span(name, events, **fields):
                yield
    else:
        with span(name, events, **fields):
            yield


# ---------------------------------------------------------------------------
# THE span-name namespace. A span name is `<family>` or
# `<family>/<variant>` (variants carry per-call detail: backend names,
# job names, rspc paths); the family before the first `/` must be
# declared here. Enforced by the sdlint telemetry pass: an undeclared
# family, a fully-dynamic name, or a declare_span() outside this module
# fails the build — span names stay a greppable, documented surface
# exactly like metric families.
# ---------------------------------------------------------------------------

# Import-time declaration registry (bounded by the source text, same
# contract as jobs.JOB_REGISTRY / store.MODELS).
SPAN_FAMILIES: Dict[str, str] = {}  # sdlint: ok[unbounded-growth]

_FAMILY_RE = re.compile(r"^[a-z0-9_.]+$")


def declare_span(family: str, doc: str = "") -> str:
    """Register a span family (tracing.py module bottom only — the
    telemetry pass flags declarations anywhere else)."""
    if not _FAMILY_RE.match(family):
        raise ValueError(
            f"span family {family!r} breaks the scheme "
            "(lowercase dotted, no slash — variants are per-call)")
    if family in SPAN_FAMILIES:
        raise ValueError(f"span family {family!r} declared twice")
    SPAN_FAMILIES[family] = doc
    return family


declare_span(
    "cas_ids",
    "One CAS hashing batch through ops/staging.cas_ids_for_files; the "
    "variant is the resolved backend (native/numpy/jax/oracle).")

declare_span(
    "fleet",
    "One fleet-observatory round (fleet.py); variants: poll (pull "
    "every paired peer's obs.health snapshot) and trace (distributed "
    "trace assembly across the fleet).")

declare_span(
    "job",
    "A job worker's whole run (jobs/worker.py); the variant is the "
    "job name. Root of the per-job trace; job.step spans nest under "
    "it.")

declare_span(
    "job.step",
    "One executed job step inside a job/<name> root span.")

declare_span(
    "p2p",
    "One inbound or outbound p2p exchange (p2p/manager.py); the "
    "variant is the header discriminator (ping/pair/spacedrop/file). "
    "Inbound spans continue the dialer's trace via the header's tp "
    "field.")

declare_span(
    "pipeline.run",
    "One depth-N identify pipeline run (ops/overlap.run_overlapped); "
    "the flight recorder's timeline events carry this span's trace "
    "id.")

declare_span(
    "rpc",
    "One rspc query/mutation dispatch on the API host (api/"
    "server.py); the variant is the procedure path. Continues the "
    "client's trace via the X-Sdtpu-Trace header / ws frame tp "
    "field.")

declare_span(
    "sync.pull",
    "The responder half of one sync stream (sync_net."
    "handle_sync_stream): the ingest pull loop, continuing the "
    "originator's trace from the new_ops header.")

declare_span(
    "sync.serve",
    "The originator half of one sync stream (sync_net._originate_one): "
    "announce + serve the peer's pull loop; root of the cross-node "
    "sync trace.")
