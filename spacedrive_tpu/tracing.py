"""Structured tracing + device profiling.

The reference leans on the `tracing` ecosystem (rolling file logs, span
timing at debug level — SURVEY.md §5); the TPU-native equivalent is a
structured span log plus optional `jax.profiler` capture around device
batches:

- `span(name)` times a block and logs one structured line through the
  standard logging machinery (and the node event bus when attached);
- when `SDTPU_PROFILE=/path` is set, `device_span(name)` additionally
  wraps the block in a jax profiler trace so device batches show up in
  TensorBoard/xprof with step markers.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Optional

logger = logging.getLogger("spacedrive_tpu")

import threading

_profiler_started = False
_profiler_failed = False
_profiler_lock = threading.Lock()


def _ensure_profiler() -> bool:
    """Start the jax trace once if SDTPU_PROFILE is set (read at call
    time so hosts can toggle it after import). ANY profiling problem —
    no jax, unwritable path, double-start race — degrades to plain
    spans; device batches run from thread-pool workers, so the start is
    lock-guarded."""
    global _profiler_started, _profiler_failed
    profile_dir = os.environ.get("SDTPU_PROFILE")
    if not profile_dir or _profiler_failed:
        return False
    if _profiler_started:
        return True
    with _profiler_lock:
        if _profiler_started:
            return True
        try:
            import jax

            jax.profiler.start_trace(profile_dir)
        except Exception as e:
            _profiler_failed = True
            logger.warning("SDTPU_PROFILE disabled: %s", e)
            return False
        _profiler_started = True
        import atexit

        # Process-scope flush. Deliberately NOT hooked into per-node
        # shutdown: the profiler is process-global and multiple nodes
        # share one process in tests.
        atexit.register(stop_profiler)
    return True


def stop_profiler() -> None:
    global _profiler_started
    if _profiler_started:
        import jax

        jax.profiler.stop_trace()
        _profiler_started = False


@contextlib.contextmanager
def span(name: str, events=None, **fields):
    """Time a block; emit one structured record at debug level (the
    reference's ad-hoc Instant deltas, job/mod.rs:592,638)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        ms = (time.perf_counter() - t0) * 1000
        record = {"span": name, "ms": round(ms, 2), **fields}
        logger.debug("span %s", record)
        if events is not None:
            events.emit({"type": "TraceSpan", **record})


@contextlib.contextmanager
def device_span(name: str, events=None, **fields):
    """span() + named jax profiler trace context when profiling is on."""
    if _ensure_profiler():
        import jax

        with jax.profiler.TraceAnnotation(name):
            with span(name, events, **fields):
                yield
    else:
        with span(name, events, **fields):
            yield
