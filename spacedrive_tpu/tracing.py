"""Structured tracing + device profiling.

The reference leans on the `tracing` ecosystem (rolling file logs, span
timing at debug level — SURVEY.md §5); the TPU-native equivalent is a
structured span log plus optional `jax.profiler` capture around device
batches:

- `span(name)` times a block and logs one structured line through the
  standard logging machinery (and the node event bus when attached).
  Spans are HIERARCHICAL: each carries a 64-bit trace id shared with
  every span under the same root, its own span id, and its parent's
  span id — propagated via a contextvar, so nesting survives
  `asyncio.to_thread` and task boundaries (both copy the context).
  Every finished span records `ok`/`error` (a body that raised is
  distinguishable in logs and the ring buffer) and lands in a bounded
  ring of recent spans queryable at runtime (`recent_spans`, served by
  the `node.spans` rspc query);
- when `SDTPU_PROFILE=/path` is set, `device_span(name)` additionally
  wraps the block in a jax profiler trace so device batches show up in
  TensorBoard/xprof with step markers.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import flags, telemetry

logger = logging.getLogger("spacedrive_tpu")

# (trace_id, span_id) of the innermost live span in this context.
_current_span: contextvars.ContextVar[Optional[Tuple[int, int]]] = \
    contextvars.ContextVar("sdtpu_current_span", default=None)

# Bounded ring of recently finished span records (newest last). 512
# records × ~200 B is ~100 KB — queryable at runtime without ever
# growing with uptime.
SPAN_RING_CAPACITY = 512
_span_ring: deque = deque(maxlen=SPAN_RING_CAPACITY)
_span_ring_lock = threading.Lock()
_id_counter = iter(range(1, 1 << 62)).__next__
_id_lock = threading.Lock()


def _new_id() -> int:
    with _id_lock:
        return _id_counter()


def recent_spans(limit: int = 100,
                 trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Newest-last slice of the span ring buffer, optionally filtered
    to one trace. Records are JSON-safe dicts."""
    with _span_ring_lock:
        records = list(_span_ring)
    if trace_id is not None:
        records = [r for r in records if r.get("trace") == trace_id]
    limit = int(limit)
    return records[-limit:] if limit > 0 else []


def clear_span_ring() -> None:
    """Test hook: empty the ring buffer."""
    with _span_ring_lock:
        _span_ring.clear()


# -- profiler (SDTPU_PROFILE) ----------------------------------------------

# Tri-state probe cache: None = not yet probed, False = profiling off
# (env unset/empty, or a start failure), True = trace running. Cached so
# the device_span hot path is a single attribute check instead of an
# os.environ read per call; reset_profiler_cache() is the documented
# hook for tests/hosts that toggle SDTPU_PROFILE after import.
_profiler_state: Optional[bool] = None
_profiler_lock = threading.Lock()


def reset_profiler_cache() -> None:
    """Forget the cached SDTPU_PROFILE probe so the next device_span
    re-reads the environment (does NOT stop a running trace)."""
    global _profiler_state
    with _profiler_lock:
        if not _profiler_state:
            _profiler_state = None


def _ensure_profiler() -> bool:
    """Start the jax trace once if SDTPU_PROFILE is set. The result —
    positive or negative — is cached; hosts that set the env var after
    import call reset_profiler_cache(). ANY profiling problem — no jax,
    unwritable path, double-start race — degrades to plain spans;
    device batches run from thread-pool workers, so the start is
    lock-guarded."""
    global _profiler_state
    state = _profiler_state
    if state is not None:
        return state
    with _profiler_lock:
        if _profiler_state is not None:
            return _profiler_state
        profile_dir = flags.get("SDTPU_PROFILE")
        if not profile_dir:
            _profiler_state = False
            return False
        try:
            import jax

            jax.profiler.start_trace(profile_dir)
        except Exception as e:
            _profiler_state = False
            logger.warning("SDTPU_PROFILE disabled: %s", e)
            return False
        _profiler_state = True
        import atexit

        # Process-scope flush. Deliberately NOT hooked into per-node
        # shutdown: the profiler is process-global and multiple nodes
        # share one process in tests.
        atexit.register(stop_profiler)
    return True


def stop_profiler() -> None:
    global _profiler_state
    if _profiler_state:
        import jax

        jax.profiler.stop_trace()
        _profiler_state = None


@contextlib.contextmanager
def span(name: str, events=None, **fields):
    """Time a block; emit one structured record at debug level (the
    reference's ad-hoc Instant deltas, job/mod.rs:592,638).

    The record carries `trace` (shared by all spans under one root),
    `id`, `parent` (absent for roots), and `ok`/`error` — a raising
    body produces ok=False plus the exception type, so failed phases
    are distinguishable downstream. `events` may be an object with an
    `.emit(dict)` method (the node EventBus) or a bare callable."""
    parent = _current_span.get()
    if parent is None:
        trace_id, parent_id = _new_id(), None
    else:
        trace_id, parent_id = parent[0], parent[1]
    span_id = _new_id()
    token = _current_span.set((trace_id, span_id))
    t0 = time.perf_counter()
    err: Optional[BaseException] = None
    try:
        yield
    except BaseException as e:
        err = e
        raise
    finally:
        _current_span.reset(token)
        ms = (time.perf_counter() - t0) * 1000
        record = {
            "span": name, "ms": round(ms, 2),
            "trace": f"{trace_id:x}", "id": f"{span_id:x}",
            "ok": err is None,
            **fields,
        }
        if parent_id is not None:
            record["parent"] = f"{parent_id:x}"
        if err is not None:
            record["error"] = type(err).__name__
        telemetry.TRACE_SPANS.labels(
            ok="true" if err is None else "false").inc()
        with _span_ring_lock:
            _span_ring.append(record)
        logger.debug("span %s", record)
        if events is not None:
            emit = getattr(events, "emit", events)
            emit({"type": "TraceSpan", **record})


@contextlib.contextmanager
def device_span(name: str, events=None, **fields):
    """span() + named jax profiler trace context when profiling is on."""
    if _ensure_profiler():
        import jax

        with jax.profiler.TraceAnnotation(name):
            with span(name, events, **fields):
                yield
    else:
        with span(name, events, **fields):
            yield
