"""Declarative dev-seed initializer.

Equivalent of the reference's debug initializer
(/root/reference/core/src/util/debug_initializer.rs:1): a JSON file in
the data dir (`init.json`) describes libraries and locations to create
at boot so a dev node comes up populated:

    {"libraries": [
        {"name": "dev", "reset_on_startup": false,
         "locations": [{"path": "/data/photos", "scan": true}]}
    ]}

Idempotent: existing libraries (by name) and locations (by path) are
reused, mirroring the reference's upsert behavior.
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Optional

INIT_FILE = "init.json"


def _load_config(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


async def apply_init_file(node, path: Optional[str] = None) -> int:
    """Apply the init config; returns the number of scans queued."""
    path = path or os.path.join(node.data_dir, INIT_FILE)
    if not os.path.exists(path):
        return 0
    config = await asyncio.to_thread(_load_config, path)
    scans = 0
    errors = []
    for lib_spec in config.get("libraries", []):
        try:
            scans += await _apply_library(node, lib_spec)
        except Exception as e:  # one bad entry must not block boot
            errors.append(f"{lib_spec.get('name', '?')}: {e}")
    for err in errors:
        node.events.emit({"type": "DebugInitError", "error": err})
    return scans


async def _apply_library(node, lib_spec: dict) -> int:
    name = lib_spec["name"]
    lib = next((c for c in node.libraries.list()
                if c.config.name == name), None)
    if lib is not None and lib_spec.get("reset_on_startup"):
        node.libraries.delete(lib.id)
        lib = None
    if lib is None:
        lib = node.create_library(name)
    scans = 0
    for loc_spec in lib_spec.get("locations", []):
        loc_path = os.path.abspath(loc_spec["path"])
        if not os.path.isdir(loc_path):
            continue
        row = await asyncio.to_thread(
            lib.db.query_one,
            "SELECT id FROM location WHERE path = ?", (loc_path,))
        if row is None:
            from .locations.manager import create_location

            loc_id = await asyncio.to_thread(create_location, lib, loc_path)
        else:
            loc_id = row["id"]
        if loc_spec.get("scan", True):
            from .locations.manager import scan_location

            await scan_location(node.jobs, lib, loc_id)
            scans += 1
    return scans
