"""Child process for the group-commit kill -9 durability storm: writes
a deterministic corpus of (object row + CRDT op-log row) pairs through
Database.write_tx from concurrent threads, with the declared
`store.group_commit` chaos fault stretching the pre-COMMIT window so
the parent's SIGKILL lands mid-group. Resumable: on start it computes
the missing indices and writes only those, so any number of kills
converges to the same final state. Run:

    python tests/_group_crash_child.py <db_path> <n_rows> <seed> <mode>

mode: "chaos" arms store.group_commit=delay (seeded); "plain" doesn't.
Prints WRITING when the storm begins and DONE <n> when the corpus is
complete.
"""

import hashlib
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from spacedrive_tpu import chaos  # noqa: E402
from spacedrive_tpu.store import Database  # noqa: E402

THREADS = 4


def pub(seed: int, i: int) -> bytes:
    return hashlib.sha256(f"{seed}:{i}".encode()).digest()[:16]


def payload(seed: int, i: int) -> bytes:
    return hashlib.sha256(f"{seed}:{i}:data".encode()).digest()


def main() -> None:
    db_path, n_rows = sys.argv[1], int(sys.argv[2])
    seed, mode = int(sys.argv[3]), sys.argv[4]
    if mode == "chaos":
        # Every group pauses 80 ms fully-written-but-uncommitted: the
        # widest possible torn-group window for the parent's SIGKILL.
        chaos.arm("store.group_commit=delay:80ms:1.0", seed=seed)

    db = Database(db_path)
    # One deterministic instance row for the op log's FK (idempotent
    # across restarts).
    inst_pub = pub(seed, -1)
    row = db.query_one("SELECT id FROM instance WHERE pub_id = ?",
                       (inst_pub,))
    if row is not None:
        inst_id = row["id"]
    else:
        inst_id = db.insert("instance", {
            "pub_id": inst_pub, "identity": b"\x00" * 16,
            "node_id": b"\x00" * 16, "node_name": "group-crash",
            "node_platform": 0, "last_seen": 0, "date_created": 0,
        })
    existing = {r["pub_id"] for r in db.query("SELECT pub_id FROM object")}
    missing = [i for i in range(n_rows) if pub(seed, i) not in existing]
    print("WRITING", len(missing), flush=True)

    it = iter(missing)
    it_lock = threading.Lock()
    errors = []

    def writer() -> None:
        while True:
            with it_lock:
                i = next(it, None)
            if i is None:
                return
            p = pub(seed, i)
            try:
                # Domain + op-log write in ONE batch: the crash
                # contract says they land together or not at all.
                with db.write_tx() as conn:
                    db.insert("object", {"pub_id": p}, conn=conn)
                    db.insert("shared_operation", {
                        "timestamp": i, "model": "object",
                        "record_id": p, "kind": "c",
                        "data": payload(seed, i), "instance_id": inst_id,
                    }, conn=conn)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    threads = [threading.Thread(target=writer) for _ in range(THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise SystemExit(f"writer failed: {errors[0]!r}")
    db.close()
    print("DONE", n_rows - len(existing), flush=True)


if __name__ == "__main__":
    main()
