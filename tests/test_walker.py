"""Walker tests with injected fake DB fetchers — the reference's main
dependency-injection seam (walk.rs:695-1071 passes `|_| Ok(vec![])` stubs so
the walker runs without any database)."""

import os
import time

from spacedrive_tpu.locations.rules import no_git, no_hidden, only_images
from spacedrive_tpu.locations.walker import ToWalkEntry, Walker


def _tree(tmp_path):
    """The reference's walker fixture shape (walk.rs:703-780)."""
    (tmp_path / "rust_project").mkdir()
    (tmp_path / "rust_project" / ".git").mkdir()
    (tmp_path / "rust_project" / ".git" / "config").write_bytes(b"cfg")
    (tmp_path / "rust_project" / "src").mkdir()
    (tmp_path / "rust_project" / "src" / "main.rs").write_bytes(b"fn main(){}")
    (tmp_path / "photos").mkdir()
    (tmp_path / "photos" / "photo1.png").write_bytes(b"\x89PNG")
    (tmp_path / "photos" / "photo2.jpg").write_bytes(b"\xff\xd8")
    (tmp_path / "photos" / "text.txt").write_bytes(b"text")
    (tmp_path / ".hidden_file").write_bytes(b"h")


def _rels(entries):
    return sorted(e.iso.relative_path for e in entries)


def test_walk_no_rules(tmp_path):
    _tree(tmp_path)
    w = Walker(1, str(tmp_path))
    res = w.walk()
    assert _rels(res.walked) == sorted([
        ".hidden_file", "photos", "photos/photo1.png", "photos/photo2.jpg",
        "photos/text.txt", "rust_project", "rust_project/.git",
        "rust_project/.git/config", "rust_project/src",
        "rust_project/src/main.rs",
    ])
    assert not res.to_update and not res.to_remove and not res.errors


def test_walk_no_hidden_no_git(tmp_path):
    _tree(tmp_path)
    w = Walker(1, str(tmp_path), rules=[no_hidden(), no_git()])
    res = w.walk()
    assert _rels(res.walked) == sorted([
        "photos", "photos/photo1.png", "photos/photo2.jpg",
        "photos/text.txt", "rust_project", "rust_project/src",
        "rust_project/src/main.rs",
    ])


def test_walk_only_images_indexes_ancestors(tmp_path):
    # Accept-globs skip dirs as entries, but ancestors of accepted files
    # are still indexed (walk.rs:617-660).
    _tree(tmp_path)
    w = Walker(1, str(tmp_path), rules=[only_images()])
    res = w.walk()
    assert _rels(res.walked) == sorted([
        "photos", "photos/photo1.png", "photos/photo2.jpg",
    ])


def test_walk_limit_defers_dirs(tmp_path):
    _tree(tmp_path)
    w = Walker(1, str(tmp_path))
    res = w.walk(limit=3)
    assert len(res.walked) >= 3
    # Un-walked dirs remain queued for a later step.
    assert len(res.to_walk) > 0
    # keep_walking drains one deferred dir at a time.
    more = w.keep_walking(res.to_walk.popleft())
    assert isinstance(more.walked, list)


def test_walk_single_dir_shallow(tmp_path):
    _tree(tmp_path)
    w = Walker(1, str(tmp_path))
    res = w.walk_single_dir(str(tmp_path / "photos"))
    assert _rels(res.walked) == sorted([
        "photos/photo1.png", "photos/photo2.jpg", "photos/text.txt"])
    assert not res.to_walk  # never descends


def test_symlinks_ignored(tmp_path):
    _tree(tmp_path)
    os.symlink(tmp_path / "photos", tmp_path / "photos_link")
    res = Walker(1, str(tmp_path)).walk()
    assert "photos_link" not in _rels(res.walked)


def test_dir_sizes(tmp_path):
    _tree(tmp_path)
    res = Walker(1, str(tmp_path)).walk()
    photos = str(tmp_path / "photos")
    assert res.paths_and_sizes[photos] == 4 + 2 + 4  # png+jpg+txt bytes
    # Root accumulates children totals.
    assert res.paths_and_sizes[str(tmp_path)] >= res.paths_and_sizes[photos]


def test_existing_rows_split_create_update(tmp_path):
    _tree(tmp_path)
    w = Walker(1, str(tmp_path))
    first = w.walk()
    photo = next(e for e in first.walked
                 if e.iso.relative_path == "photos/photo1.png")

    # Fake DB returning photo1 unchanged → not re-created, not updated.
    def fetcher(paths):
        m = photo.metadata
        return [{
            "pub_id": b"exists", "is_dir": 0,
            "materialized_path": photo.iso.materialized_path,
            "name": photo.iso.name, "extension": photo.iso.extension,
            "inode": m.inode.to_bytes(8, "big"),
            "date_modified": m.modified_at,
            "size_in_bytes_bytes": m.size_in_bytes.to_bytes(8, "big"),
        }]

    w2 = Walker(1, str(tmp_path), existing_paths_fetcher=fetcher)
    res = w2.walk()
    rels = _rels(res.walked)
    assert "photos/photo1.png" not in rels
    assert not res.to_update

    # Touch the file → appears in to_update with the DB pub_id.
    t = time.time() + 10
    os.utime(tmp_path / "photos" / "photo1.png", (t, t))
    res = w2.walk()
    assert [e.pub_id for e in res.to_update] == [b"exists"]


def test_to_remove_fetcher_called_per_dir(tmp_path):
    _tree(tmp_path)
    calls = []

    def to_remove(parent_iso, iso_paths):
        calls.append(parent_iso.relative_path)
        return [{"pub_id": b"stale"}] if parent_iso.relative_path == "photos" \
            else []

    res = Walker(1, str(tmp_path), to_remove_fetcher=to_remove).walk()
    assert "photos" in calls and "" in calls
    assert res.to_remove == [{"pub_id": b"stale"}]
