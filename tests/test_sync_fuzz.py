"""Sync convergence under adversarial interleaving (VERDICT r4 item 5).

Three nodes in an A↔B↔C line over the REAL TCP plane run a seeded
random schedule of concurrent multi-field updates, creates, deletes,
relation assigns/unassigns, and partition/heal cycles of the middle
node — then the suite asserts full convergence at quiescence: op logs
AND domain-table state identical on every node.

This generalizes the reference's two-instance `bruh` test
(/root/reference/core/crates/sync/tests/lib.rs:102-217) into the class
of schedules that caught the round-4 FK-delete-ordering divergence and
the round-5 watermark/cascade findings systematically.
"""

import asyncio
import os
import random

import pytest

from spacedrive_tpu.node import Node
from spacedrive_tpu.sync.manager import GetOpsArgs


def _run(coro):
    return asyncio.run(coro)


class Fuzzer:
    """One node's random actor: local domain write + op emission in the
    same shapes the API layer uses (tags/objects/assignments)."""

    def __init__(self, lib, rng: random.Random):
        self.lib = lib
        self.rng = rng

    def _tags(self):
        return self.lib.db.query("SELECT id, pub_id, name FROM tag")

    def _objects(self):
        return self.lib.db.query("SELECT id, pub_id FROM object")

    def create_tag(self):
        sync = self.lib.sync
        pub = os.urandom(16)
        name = f"t{self.rng.randrange(1_000_000)}"
        color = f"#{self.rng.randrange(0xFFFFFF):06x}"
        ops = sync.shared_create("tag", pub, {"name": name, "color": color})
        with sync.write_ops(ops) as conn:
            self.lib.db.insert("tag", {"pub_id": pub, "name": name,
                                       "color": color}, conn=conn)

    def create_object(self):
        sync = self.lib.sync
        pub = os.urandom(16)
        ops = sync.shared_create("object", pub, {"kind": 5})
        with sync.write_ops(ops) as conn:
            self.lib.db.insert("object", {"pub_id": pub, "kind": 5},
                               conn=conn)

    def update_tag(self):
        tags = self._tags()
        if not tags:
            return
        t = self.rng.choice(tags)
        sync = self.lib.sync
        if self.rng.random() < 0.5:  # multi-field (per-field LWW apply)
            vals = {"name": f"r{self.rng.randrange(1_000_000)}",
                    "color": f"#{self.rng.randrange(0xFFFFFF):06x}"}
            ops = [sync.shared_multi_update("tag", t["pub_id"], vals)]
        else:
            vals = {"name": f"s{self.rng.randrange(1_000_000)}"}
            ops = [sync.shared_update("tag", t["pub_id"], "name",
                                      vals["name"])]
        try:
            with sync.write_ops(ops) as conn:
                self.lib.db.update("tag", t["id"], vals, conn=conn)
        except Exception:
            pass  # tag vanished under a concurrent synced delete

    def delete_tag(self):
        tags = self._tags()
        if not tags:
            return
        t = self.rng.choice(tags)
        sync = self.lib.sync
        assigned = self.lib.db.query(
            "SELECT o.pub_id AS opub FROM tag_on_object tob "
            "JOIN object o ON o.id = tob.object_id WHERE tob.tag_id = ?",
            (t["id"],))
        # relation deletes FIRST — the API's FK-safe ordering
        ops = [sync.relation_delete("tag_on_object", r["opub"],
                                    t["pub_id"]) for r in assigned]
        ops.append(sync.shared_delete("tag", t["pub_id"]))
        try:
            with sync.write_ops(ops) as conn:
                conn.execute("DELETE FROM tag_on_object WHERE tag_id = ?",
                             (t["id"],))
                self.lib.db.delete("tag", t["id"], conn=conn)
        except Exception:
            pass

    def assign(self):
        tags, objs = self._tags(), self._objects()
        if not tags or not objs:
            return
        t, o = self.rng.choice(tags), self.rng.choice(objs)
        sync = self.lib.sync
        ops = sync.relation_create("tag_on_object", o["pub_id"],
                                   t["pub_id"])
        try:
            with sync.write_ops(ops) as conn:
                conn.execute(
                    "INSERT OR IGNORE INTO tag_on_object "
                    "(tag_id, object_id) VALUES (?, ?)",
                    (t["id"], o["id"]))
        except Exception:
            pass

    def unassign(self):
        rows = self.lib.db.query(
            "SELECT tob.tag_id, tob.object_id, t.pub_id AS tpub, "
            "o.pub_id AS opub FROM tag_on_object tob "
            "JOIN tag t ON t.id = tob.tag_id "
            "JOIN object o ON o.id = tob.object_id")
        if not rows:
            return
        r = self.rng.choice(rows)
        sync = self.lib.sync
        try:
            with sync.write_ops([sync.relation_delete(
                    "tag_on_object", r["opub"], r["tpub"])]) as conn:
                conn.execute(
                    "DELETE FROM tag_on_object WHERE tag_id = ? "
                    "AND object_id = ?", (r["tag_id"], r["object_id"]))
        except Exception:
            pass

    def act(self):
        # creation-heavy early mix keeps the pools populated; deletes
        # and relation churn provide the adversarial interleavings
        self.rng.choices(
            [self.create_tag, self.create_object, self.update_tag,
             self.delete_tag, self.assign, self.unassign],
            weights=[3, 2, 5, 2, 4, 2])[0]()


def _log(lib):
    ops = lib.sync.get_ops(GetOpsArgs(clocks=[], count=100_000))
    return sorted((o.timestamp, o.instance, o.typ.kind) for o in ops)


def _state(lib):
    tags = {r["pub_id"].hex(): (r["name"], r["color"]) for r in
            lib.db.query("SELECT pub_id, name, color FROM tag")}
    objs = {r["pub_id"].hex() for r in
            lib.db.query("SELECT pub_id FROM object")}
    rels = {(r["opub"].hex(), r["tpub"].hex()) for r in lib.db.query(
        "SELECT o.pub_id AS opub, t.pub_id AS tpub FROM tag_on_object "
        "tob JOIN tag t ON t.id = tob.tag_id "
        "JOIN object o ON o.id = tob.object_id")}
    return tags, objs, rels


from spacedrive_tpu import flags as _flags

_SEEDS = _flags.get("SDTPU_FUZZ_SEEDS")


def test_three_node_blob_relay_convergence(tmp_path):
    """Scaled-down 3-node convergence for the round-6 blob op-log
    write path: node A's history is written through the page-blob bulk
    encoder (native when built), B pulls from A, C pulls ONLY from B
    (A-authored ops relay through B's log). All three domain tables
    and logical op streams must converge. In-process managers rather
    than the TCP plane: this runtime lacks the `cryptography` package
    the p2p identity layer needs, and the semantics under test are the
    managers' — the wire is the same paged get_ops/ingest loop."""
    from conftest import drain_sync as drain
    from conftest import make_sync_manager

    from spacedrive_tpu.sync.manager import BLOB_MIN_OPS, GetOpsArgs

    def mk(name):
        return make_sync_manager(tmp_path, name)

    def domain(mgr):
        return {r["pub_id"].hex(): (r["kind"], r["date_created"],
                                    r["note"])
                for r in mgr.db.query(
                    "SELECT pub_id, kind, date_created, note FROM object")}

    def log(mgr):
        ops = mgr.get_ops(GetOpsArgs(clocks=[], count=100_000))
        return sorted((o.timestamp, o.instance, o.typ.kind,
                       o.typ.record_id) for o in ops)

    a, b, c = mk("a"), mk("b"), mk("c")
    n = BLOB_MIN_OPS + 17
    pubs = [os.urandom(16) for _ in range(n)]
    with a.db.tx() as conn:
        assert a.bulk_shared_ops(conn, "object", [
            (p, "c", None, None, {"kind": 5, "date_created": i})
            for i, p in enumerate(pubs)]) == n
        conn.executemany(
            "INSERT INTO object (pub_id, kind, date_created) "
            "VALUES (?, ?, ?)",
            [(p, 5, i) for i, p in enumerate(pubs)])
    assert a.db.query_one(
        "SELECT COUNT(*) AS n FROM shared_op_blob")["n"] == 1

    b.register_instance(a.instance)
    assert drain(a, b) == n
    # C pairs with B only; A's ops relay via B's log (auto-registered
    # placeholder instance on C).
    c.register_instance(b.instance)
    assert drain(b, c) == n

    # Second blob page AFTER the first relay: multi-field updates.
    with a.db.tx() as conn:
        assert a.bulk_shared_ops(conn, "object", [
            (p, "u:kind+note", None, None, {"kind": 6, "note": "v2"})
            for p in pubs]) == n
        conn.executemany(
            "UPDATE object SET kind = 6, note = 'v2' WHERE pub_id = ?",
            [(p,) for p in pubs])
    assert drain(a, b) == n
    assert drain(b, c) == n

    assert domain(a) == domain(b) == domain(c)
    assert len(domain(a)) == n
    da = domain(a)
    assert all(da[p.hex()] == (6, i, "v2") for i, p in enumerate(pubs))
    # Logical op streams converge byte-for-byte in (ts, instance,
    # kind, record) across ALL nodes — A still serving from blobs
    # (never ingested anything), B/C from exploded/ingested rows.
    assert log(a) == log(b) == log(c)
    assert a.db.query_one(
        "SELECT COUNT(*) AS n FROM shared_op_blob")["n"] == 2


@pytest.mark.parametrize("seed", _SEEDS)
def test_clone_during_churn_convergence(tmp_path, seed):
    """A fresh peer joins MID-write-storm: blob pages are still being
    appended while the clone stream drains, the fresh peer makes local
    writes mid-clone (flipping the batched apply across the
    pass-through ↔ per-op fallback boundary), and the origin finally
    pairs back (register_instance → solo off → its blobs explode on
    first ingest). Everything must converge byte-identically — domain
    tables AND logical op streams — against a per-op control replica.

    In-process managers rather than the TCP plane (this runtime lacks
    `cryptography`); the streams exercised are exactly the ones the
    wire carries."""
    from conftest import drain_sync as drain
    from conftest import make_sync_manager

    from spacedrive_tpu.sync.manager import BLOB_MIN_OPS, GetOpsArgs

    rng = random.Random(seed)
    a = make_sync_manager(tmp_path, "storm-origin")
    b = make_sync_manager(tmp_path, "fresh-peer")

    def blob_wave(mgr, n, note):
        pubs = [os.urandom(16) for _ in range(n)]
        with mgr.db.tx() as conn:
            mgr.bulk_shared_ops(conn, "object", [
                (p, "c", None, None, {"kind": 5, "note": note})
                for p in pubs])
            conn.executemany(
                "INSERT INTO object (pub_id, kind, note) "
                "VALUES (?, 5, ?)", [(p, note) for p in pubs])
        return pubs

    def local_tag(mgr, name):
        pub = os.urandom(16)
        ops = mgr.shared_create("tag", pub, {"name": name})
        with mgr.write_ops(ops) as conn:
            mgr.db.insert("tag", {"pub_id": pub, "name": name},
                          conn=conn)

    # two blob pages land before the peer exists
    wave1a = blob_wave(a, BLOB_MIN_OPS + rng.randrange(32), "w1a")
    wave1b = blob_wave(a, BLOB_MIN_OPS, "w1b")
    b.register_instance(a.instance)

    fast_pages = fallback_pages = 0
    stream = a.iter_clone_stream([(b.instance, 0)])
    consumed = 0
    for kind, item in stream:
        if kind == "ops":
            _n, errs = b.receive_crdt_operations(item)
            assert not errs, errs[:3]
        else:
            _n, errs, fast = b.receive_blob_pages([item])
            assert not errs, errs[:3]
            fast_pages += 1 if fast else 0
            fallback_pages += 0 if fast else 1
        consumed += 1
        if consumed == 1:
            # mid-clone: the storm continues on the origin (still
            # solo — the peer pulls without being registered there)...
            blob_wave(a, BLOB_MIN_OPS, "w2-mid-clone")
            # ...and the fresh peer writes locally. Its op-log
            # high-water is now NEWER than the second in-flight page
            # (its clock absorbed page 1's max_ts, so the local op
            # outstamps everything wave 1 minted) → the batched apply
            # must cross to the per-op fallback and still converge.
            local_tag(b, "mid-clone-local")

    assert fast_pages >= 1, "pass-through never engaged"
    assert fallback_pages >= 1, \
        "fallback boundary never crossed mid-clone"
    # wave 2 lands as a NEW stream attempt or the per-op tail — either
    # way the peer has history now, so pass-through must refuse
    assert list(a.iter_clone_stream(list(b.timestamps.items()))) == []
    drain(a, b)

    # the origin pairs back and ingests the peer's local writes: its
    # remaining blobs explode to rows on first ingest
    a.register_instance(b.instance)
    assert a.db.query_one(
        "SELECT COUNT(*) AS n FROM shared_op_blob")["n"] >= 1
    drain(b, a)
    assert a.db.query_one(
        "SELECT COUNT(*) AS n FROM shared_op_blob")["n"] == 0
    # post-pair churn in both directions, row-format now
    local_tag(a, "post-pair-a")
    wave3 = blob_wave(a, BLOB_MIN_OPS, "w3-post-pair")  # rows: not solo
    assert a.db.query_one(
        "SELECT COUNT(*) AS n FROM shared_op_blob")["n"] == 0
    local_tag(b, "post-pair-b")
    for _ in range(3):  # drain to quiescence both ways
        drain(a, b)
        drain(b, a)

    # per-op control replica pulled from the origin's (now exploded)
    # log must match both storm participants byte-for-byte
    c = make_sync_manager(tmp_path, "control")
    c.register_instance(a.instance)
    drain(a, c)

    def domain(mgr):
        objs = sorted((r["pub_id"].hex(), r["kind"], r["note"])
                      for r in mgr.db.query(
                          "SELECT pub_id, kind, note FROM object"))
        tags = sorted((r["pub_id"].hex(), r["name"]) for r in
                      mgr.db.query("SELECT pub_id, name FROM tag"))
        return objs, tags

    def log(mgr):
        ops = mgr.get_ops(GetOpsArgs(clocks=[], count=1_000_000))
        return sorted((o.timestamp, o.instance, o.id, o.typ.kind,
                       repr(o.typ.record_id)) for o in ops)

    assert domain(a) == domain(b) == domain(c)
    assert log(a) == log(b) == log(c)
    n_objects = len(domain(a)[0])
    assert n_objects == (len(wave1a) + len(wave1b) + BLOB_MIN_OPS
                         + len(wave3))


@pytest.mark.parametrize("seed", _SEEDS)
def test_three_node_adversarial_convergence(tmp_path, seed):
    rng = random.Random(seed)
    nodes = [Node(str(tmp_path / n)) for n in "abc"]
    a, b, c = nodes

    async def main():
        for n in nodes:
            await n.start()
        ports = [await n.start_p2p(host="127.0.0.1",
                                   enable_discovery=False)
                 for n in nodes]
        b.p2p.on_pairing_request = lambda peer, info: True
        c.p2p.on_pairing_request = lambda peer, info: True
        lib_a = a.create_library("fuzz")
        assert await a.p2p.pair("127.0.0.1", ports[1], lib_a)
        lib_b = b.libraries.list()[0]
        assert await b.p2p.pair("127.0.0.1", ports[2], lib_b)
        lib_c = c.libraries.list()[0]
        libs = [lib_a, lib_b, lib_c]
        actors = [Fuzzer(lib, random.Random(rng.randrange(2**30)))
                  for lib in libs]

        partitioned = False
        n_partitions = 0
        for step in range(90):
            actors[rng.randrange(3)].act()
            r = rng.random()
            # one guaranteed partition/heal cycle (steps 30-55) plus
            # whatever the seed adds randomly
            if not partitioned and (r < 0.06 or step == 30):
                await b.p2p.stop()  # partition the relay node
                partitioned = True
                n_partitions += 1
            elif partitioned and (r < 0.25 or step == 55):
                new_port = await b.start_p2p(host="127.0.0.1",
                                             enable_discovery=False)
                ident_b = b.p2p.identity.to_remote_identity()
                a.p2p.networked.set_route(ident_b, "127.0.0.1", new_port)
                c.p2p.networked.set_route(ident_b, "127.0.0.1", new_port)
                partitioned = False
            if rng.random() < 0.3:
                await asyncio.sleep(0.02)

        if partitioned:  # final heal
            new_port = await b.start_p2p(host="127.0.0.1",
                                         enable_discovery=False)
            ident_b = b.p2p.identity.to_remote_identity()
            a.p2p.networked.set_route(ident_b, "127.0.0.1", new_port)
            c.p2p.networked.set_route(ident_b, "127.0.0.1", new_port)

        # drain triggers: one trailing write per node re-announces so
        # every pull loop wakes with routes healed
        for actor in actors:
            actor.create_tag()

        deadline = 40.0
        stable = 0
        while deadline > 0:
            await asyncio.sleep(0.25)
            deadline -= 0.25
            states = [_state(lib) for lib in libs]
            if states[0] == states[1] == states[2]:
                stable += 1
                if stable >= 4:  # hold quiescence a moment
                    break
            else:
                stable = 0
        # THE CRDT invariant: domain state identical everywhere. (Op
        # logs are deliberately NOT byte-identical across replicas —
        # like the reference's ingest, a receiver skips LOGGING an op
        # already superseded by newer covering ops it holds, so two
        # replicas' logs agree only up to staleness-dropped ops.)
        states = [_state(lib) for lib in libs]
        assert states[0] == states[1] == states[2], (
            "domain state diverged:\n"
            + "\n".join(repr(s) for s in states))
        # Log sanity: nobody invents ops — every logged op was authored
        # somewhere, i.e. each log is a subset of the union.
        logs = [set(_log(lib)) for lib in libs]
        union = logs[0] | logs[1] | logs[2]
        for i, lg in enumerate(logs):
            assert lg <= union
        # And no parked/quarantined leftovers at quiescence.
        for lib in libs:
            assert lib.db.query_one(
                "SELECT COUNT(*) AS n FROM quarantined_op")["n"] == 0
        # Non-triviality: the schedule really exercised the op space —
        # survivors exist, and creates/updates/deletes all happened.
        tags, objs, rels = states[0]
        assert tags and objs, states[0]
        kinds = {k for _, _, k in union}
        assert "c" in kinds and "d" in kinds
        assert any(k.startswith("u:") for k in kinds)
        assert any("+" in k for k in kinds if k.startswith("u:")), \
            "no multi-field update ran"
        assert len(union) >= 60, len(union)
        assert n_partitions >= 1, "schedule never partitioned the relay"
        for n in nodes:
            await n.shutdown()

    _run(main())
