"""sd-images facade: dispatch, size guard, runtime gating."""

import os

import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from spacedrive_tpu.media.images import (  # noqa: E402
    GENERIC_EXTENSIONS,
    ImageHandlerError,
    UnsupportedFormat,
    convert_image,
    format_image,
    supported_extensions,
)


def test_generic_decode_and_convert(tmp_path):
    p = tmp_path / "a.png"
    Image.new("RGBA", (20, 10), (1, 2, 3, 255)).save(p)
    im = format_image(str(p))
    assert im.size == (20, 10)
    jpg = convert_image(str(p), "jpeg")
    assert jpg.mode == "RGB"  # alpha dropped for JPEG


def test_unknown_extension_rejected(tmp_path):
    p = tmp_path / "weird.xyz"
    p.write_bytes(b"not an image")
    with pytest.raises(UnsupportedFormat):
        format_image(str(p))
    with pytest.raises(UnsupportedFormat):
        convert_image(str(p), "xyz")


def test_size_guard(tmp_path, monkeypatch):
    import spacedrive_tpu.media.images as images

    monkeypatch.setattr(images, "MAXIMUM_FILE_SIZE", 50)
    p = tmp_path / "big.png"
    Image.new("RGB", (64, 64)).save(p)
    assert p.stat().st_size > 50
    with pytest.raises(ImageHandlerError):
        images.format_image(str(p))


def test_supported_extensions_contains_generics():
    exts = supported_extensions()
    assert GENERIC_EXTENSIONS <= set(exts) | {"jpg", "jpeg"}


def test_avmetadata_gates_without_ffmpeg(tmp_path):
    from spacedrive_tpu.media import avmetadata, video

    if video.available():
        pytest.skip("ffmpeg present")
    assert avmetadata.probe_media(str(tmp_path / "x.mp4")) is None
