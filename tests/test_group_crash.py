"""Group-commit durability storm: SIGKILL a node mid-group — inside
the seeded `store.group_commit` delay window, where the whole group is
written but uncommitted — restart cold, repeat, and require (a) no
torn group after ANY kill (an object row and its CRDT op-log row land
together or not at all), (b) committed work never regresses across a
kill (WAL recovery is monotone), and (c) the storm survivor converges
to the byte-identical canonical state of an unkilled control run —
domain table AND op log — under the raise-mode sanitizer with zero
violations. The subprocess + SIGKILL shape follows
test_crash_recovery.py; the seeded-chaos gating follows
test_load_bench.py."""

import hashlib
import os
import signal
import sqlite3
import subprocess
import sys
import time

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "_group_crash_child.py")
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_ROWS = 400
SEED = 1109
KILLS = 4


def _child_env():
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "SDTPU_SANITIZE": "1",
                "SDTPU_SANITIZE_MODE": "raise"})
    return env


def _spawn(db_path, mode):
    return subprocess.Popen(
        [sys.executable, CHILD, str(db_path), str(N_ROWS), str(SEED),
         mode],
        cwd=ROOT, env=_child_env(), stdout=subprocess.PIPE, text=True)


def _counts_and_tear(db_path):
    """(objects, ops, torn) read directly — opening the db replays
    whatever WAL state the SIGKILL left behind, exactly like the
    restarted node does."""
    conn = sqlite3.connect(db_path, timeout=30.0)
    try:
        n_obj = conn.execute("SELECT COUNT(*) FROM object").fetchone()[0]
        n_ops = conn.execute(
            "SELECT COUNT(*) FROM shared_operation").fetchone()[0]
        torn = conn.execute(
            "SELECT COUNT(*) FROM ("
            "  SELECT pub_id FROM object "
            "  EXCEPT SELECT record_id FROM shared_operation"
            ") ").fetchone()[0]
        torn += conn.execute(
            "SELECT COUNT(*) FROM ("
            "  SELECT record_id FROM shared_operation "
            "  EXCEPT SELECT pub_id FROM object"
            ") ").fetchone()[0]
        return n_obj, n_ops, torn
    finally:
        conn.close()


def _canonical_digest(db_path):
    """Order-independent byte digest of the logical state: every
    column except the autoincrement rowids (assignment order is thread
    interleaving, not state)."""
    conn = sqlite3.connect(db_path, timeout=30.0)
    try:
        h = hashlib.sha256()
        for row in conn.execute(
                "SELECT pub_id FROM object ORDER BY pub_id"):
            h.update(row[0])
        for row in conn.execute(
                "SELECT timestamp, model, record_id, kind, data, "
                "instance_id FROM shared_operation "
                "ORDER BY record_id, timestamp"):
            h.update(repr(row).encode())
        return h.hexdigest()
    finally:
        conn.close()


def test_group_commit_kill9_storm_converges(tmp_path):
    control_db = tmp_path / "control" / "lib.db"
    storm_db = tmp_path / "storm" / "lib.db"
    control_db.parent.mkdir()
    storm_db.parent.mkdir()

    # Unkilled control: same seed, same workload, no chaos.
    proc = _spawn(control_db, "plain")
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 0, out
    assert f"DONE {N_ROWS}" in out

    # The storm: kill -9 mid-group, cold-restart, repeat.
    prev_committed = 0
    interrupted = 0
    for round_no in range(KILLS):
        child = _spawn(storm_db, "chaos")
        try:
            assert child.stdout.readline().startswith("WRITING")
            time.sleep(0.10 + 0.07 * round_no)
            os.kill(child.pid, signal.SIGKILL)
            child.wait(timeout=10)
        finally:
            if child.poll() is None:  # pragma: no cover
                child.kill()
        n_obj, n_ops, torn = _counts_and_tear(storm_db)
        assert torn == 0, (
            f"round {round_no}: {torn} torn pair(s) — a group half-"
            "committed across the kill")
        assert n_obj == n_ops
        assert n_obj >= prev_committed, (
            f"round {round_no}: committed work regressed "
            f"{prev_committed} -> {n_obj}")
        if n_obj < N_ROWS:
            interrupted += 1
        prev_committed = n_obj
    assert interrupted >= 1, (
        "every storm round completed before the kill — the storm "
        "never actually interrupted a run; widen the fault window")

    # Cold restart, let it converge (chaos still armed, raise mode).
    child = _spawn(storm_db, "chaos")
    out, _ = child.communicate(timeout=120)
    assert child.returncode == 0, out
    assert "DONE" in out

    n_obj, n_ops, torn = _counts_and_tear(storm_db)
    assert (n_obj, n_ops, torn) == (N_ROWS, N_ROWS, 0)
    assert _canonical_digest(storm_db) == _canonical_digest(control_db)
