"""Child process for the real-crash recovery test: starts a node, kicks
off a slow job, then waits to be SIGKILLed. Run:
    python tests/_crash_child.py <data_dir> <corpus_dir>
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from spacedrive_tpu.jobs.job import StatefulJob, StepOutcome, register_job  # noqa: E402
from spacedrive_tpu.node import Node  # noqa: E402


@register_job
class SlowCountJob(StatefulJob):
    """Appends one line per step to progress.log — slow enough to be
    killed mid-run, observable enough to verify exactly-once effects."""

    NAME = "test_slow_count"

    def __init__(self, *, steps: int, log_path: str):
        super().__init__(steps=steps, log_path=log_path)
        self.steps = steps
        self.log_path = log_path

    async def init(self, ctx):
        return {}, list(range(self.steps))

    async def execute_step(self, ctx, data, step, step_number):
        await asyncio.sleep(0.05)
        with open(self.log_path, "a") as f:
            f.write(f"{step}\n")
        return StepOutcome()


async def main() -> None:
    data_dir, corpus = sys.argv[1], sys.argv[2]
    node = Node(data_dir)
    await node.start()
    lib = node.libraries.list()[0] if node.libraries.list() else \
        node.create_library("crash")
    job = SlowCountJob(steps=100,
                       log_path=os.path.join(corpus, "progress.log"))
    await node.jobs.ingest(lib, job)
    print("STARTED", flush=True)
    await asyncio.sleep(60)  # parent SIGKILLs us long before this


if __name__ == "__main__":
    asyncio.run(main())
