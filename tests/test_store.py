"""Store layer: DDL generation, CRUD, batching, constraint semantics."""

import sqlite3
import threading

import pytest

from spacedrive_tpu.store import Database, MODELS, SyncMode, uuid_bytes


@pytest.fixture
def db(tmp_path):
    return Database(tmp_path / "library.db")


def test_all_tables_created(db):
    names = {
        r["name"]
        for r in db.query("SELECT name FROM sqlite_master WHERE type='table'")
    }
    for model in MODELS:
        assert model in names


def test_file_path_unique_constraints(db):
    loc = db.insert("location", {"pub_id": uuid_bytes(), "name": "home",
                                 "path": "/home"})
    row = {
        "pub_id": uuid_bytes(), "location_id": loc,
        "materialized_path": "a/b/", "name": "f", "extension": "txt",
        "is_dir": 0,
    }
    db.insert("file_path", row)
    # same (location, path, name, ext) → reject, like schema.prisma:197
    dup = dict(row, pub_id=uuid_bytes())
    with pytest.raises(sqlite3.IntegrityError):
        db.insert("file_path", dup)
    assert db.insert_many("file_path", [dup], ignore_conflicts=True) == 0


def test_insert_many_and_query(db):
    loc = db.insert("location", {"pub_id": uuid_bytes(), "path": "/x"})
    rows = [
        {"pub_id": uuid_bytes(), "location_id": loc,
         "materialized_path": "", "name": f"f{i}", "extension": "bin"}
        for i in range(1000)
    ]
    assert db.insert_many("file_path", rows) == 1000
    n = db.query_one("SELECT COUNT(*) AS n FROM file_path")["n"]
    assert n == 1000


def test_atomic_tx_rollback(db):
    with pytest.raises(RuntimeError):
        with db.tx() as conn:
            db.insert("object", {"pub_id": uuid_bytes()}, conn=conn)
            raise RuntimeError("abort")
    assert db.query_one("SELECT COUNT(*) AS n FROM object")["n"] == 0


def test_upsert_preference(db):
    db.upsert("preference", {"key": "theme"}, {"value": b"dark"})
    db.upsert("preference", {"key": "theme"}, {"value": b"light"})
    rows = db.query("SELECT * FROM preference")
    assert len(rows) == 1 and rows[0]["value"] == b"light"


def test_concurrent_writers(db):
    """Write lock serializes threads; no SQLITE_BUSY surfacing."""
    errors = []

    def work(i):
        try:
            for j in range(20):
                db.insert("object", {"pub_id": uuid_bytes()})
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert db.query_one("SELECT COUNT(*) AS n FROM object")["n"] == 160


def test_sync_metadata_registry():
    fp = MODELS["file_path"]
    assert fp.sync is SyncMode.SHARED and fp.sync_id == ("pub_id",)
    assert MODELS["tag_on_object"].sync is SyncMode.RELATION
    assert MODELS["job"].sync is SyncMode.LOCAL
    # local_only fields never sync (location.instance_id)
    loc = MODELS["location"]
    assert "instance_id" not in [f.name for f in loc.synced_fields]


def test_additive_migration_of_pre_round5_library(tmp_path):
    """A library created BEFORE pending_relation_op grew its dedup/ref
    columns must still open: the additive migration ALTERs in the new
    plain-nullable columns (a UNIQUE op_id here bricked old libraries —
    round-5 review finding; SQLite cannot ADD a UNIQUE column)."""
    p = tmp_path / "old.db"
    conn = sqlite3.connect(p)
    conn.execute(
        "CREATE TABLE pending_relation_op ("
        "id INTEGER PRIMARY KEY AUTOINCREMENT, "
        "timestamp INTEGER NOT NULL, data BLOB NOT NULL)")
    conn.execute("INSERT INTO pending_relation_op (timestamp, data) "
                 "VALUES (1, x'00')")
    conn.commit()
    conn.close()
    db = Database(p)  # raises on a broken migration
    cols = {r["name"] for r in
            db.query("PRAGMA table_info(pending_relation_op)")}
    assert {"op_id", "item_model", "item_key",
            "group_model", "group_key"} <= cols
    # the pre-existing row survived
    assert db.query_one(
        "SELECT COUNT(*) AS n FROM pending_relation_op")["n"] == 1
