"""Dedup analytics: pHash properties, exact groups, near-dup job E2E."""

import asyncio
import os

import numpy as np
import pytest

from spacedrive_tpu.ops.phash import (
    dct_matrix,
    phash_files,
    phash_from_bytes,
    phash_numpy,
    phash_to_bytes,
)


def _img(path, seed, size=(256, 192), noise=0):
    from PIL import Image
    rng = np.random.default_rng(seed)
    # Smooth low-frequency image: noise-robust pHash needs structure.
    base = rng.normal(size=(12, 16))
    arr = np.kron(base, np.ones((16, 16)))[:size[1], :size[0]]
    arr = (arr - arr.min()) / (np.ptp(arr) + 1e-9) * 255
    if noise:
        arr = np.clip(arr + rng.normal(scale=noise, size=arr.shape), 0, 255)
    Image.fromarray(arr.astype(np.uint8), "L").convert("RGB").save(path)


def _dist(a, b):
    return int(np.unpackbits(
        (a ^ b).astype(">u4").view(np.uint8)).sum())


def test_dct_matrix_orthonormal():
    d = dct_matrix(32)
    assert np.allclose(d @ d.T, np.eye(32), atol=1e-5)


def test_phash_deterministic_and_discriminative(tmp_path):
    _img(tmp_path / "a.png", seed=1)
    _img(tmp_path / "a_copy.png", seed=1)
    _img(tmp_path / "a_noisy.png", seed=1, noise=6)
    _img(tmp_path / "b.png", seed=2)
    hashes, errors = phash_files([
        str(tmp_path / "a.png"), str(tmp_path / "a_copy.png"),
        str(tmp_path / "a_noisy.png"), str(tmp_path / "b.png"),
    ], backend="numpy")
    assert not errors and len(hashes) == 4
    assert _dist(hashes[0], hashes[1]) == 0          # identical
    assert _dist(hashes[0], hashes[2]) <= 10         # noisy variant near
    assert _dist(hashes[0], hashes[3]) > 16          # different image far


def test_phash_jax_matches_numpy(tmp_path):
    _img(tmp_path / "x.png", seed=5)
    from spacedrive_tpu.ops.phash import image_to_grid, phash_jax
    grid = image_to_grid(str(tmp_path / "x.png"))[None]
    a = phash_numpy(grid)
    b = phash_jax(grid)
    # Median thresholding can flip bits whose AC term sits exactly at the
    # median under float reordering; allow a tiny tolerance.
    assert _dist(a[0], b[0]) <= 2


def test_phash_blob_roundtrip(tmp_path):
    _img(tmp_path / "x.png", seed=3)
    hashes, _ = phash_files([str(tmp_path / "x.png")], backend="numpy")
    blob = phash_to_bytes(hashes[0])
    assert len(blob) == 8
    assert np.array_equal(phash_from_bytes(blob), hashes[0])


@pytest.fixture
def env(tmp_path):
    from spacedrive_tpu.node import Node
    corpus = tmp_path / "corpus"
    corpus.mkdir()
    _img(corpus / "photo.png", seed=1)
    _img(corpus / "photo_near.png", seed=1, noise=5)
    _img(corpus / "other.png", seed=9)
    # An exact duplicate pair (same bytes).
    (corpus / "dup1.bin").write_bytes(b"D" * 5000)
    (corpus / "dup2.bin").write_bytes(b"D" * 5000)
    node = Node(str(tmp_path / "data"))
    lib = node.create_library("t")
    return node, lib, str(corpus)


def test_exact_and_near_dup_jobs(env):
    node, lib, corpus = env
    from spacedrive_tpu.jobs.report import JobStatus
    from spacedrive_tpu.locations.manager import create_location, scan_location
    from spacedrive_tpu.objects.dedup import (
        NearDupDetectorJob,
        exact_duplicate_groups,
        near_duplicates,
    )

    async def main():
        loc = create_location(lib, corpus)
        await scan_location(node.jobs, lib, loc, backend="numpy")
        await node.jobs.wait_idle()
        jid = await node.jobs.ingest(lib, NearDupDetectorJob(
            location_id=loc, threshold=12, backend="numpy"))
        status = await node.jobs.wait(jid)
        assert status == JobStatus.COMPLETED, status
        return loc
    loc = asyncio.run(main())

    groups = exact_duplicate_groups(lib)
    assert len(groups) == 1
    assert groups[0]["count"] == 2
    assert groups[0]["reclaimable_bytes"] == 5000
    assert sorted(groups[0]["paths"]) == ["/dup1.bin", "/dup2.bin"]

    pairs = near_duplicates(lib)
    assert len(pairs) >= 1
    flat = {tuple(sorted((p["object_a"], p["object_b"]))) for p in pairs}
    # photo & photo_near are the near pair; other must not pair with them
    # at this threshold.
    rows = {r["name"]: r["object_id"] for r in lib.db.query(
        "SELECT name, object_id FROM file_path WHERE extension = 'png'")}
    expected = tuple(sorted((rows["photo"], rows["photo_near"])))
    assert expected in flat
    bad_a = tuple(sorted((rows["photo"], rows["other"])))
    assert bad_a not in flat

    # Re-running skips hashing (phashes persisted) and converges.
    async def rerun():
        jid = await node.jobs.ingest(lib, NearDupDetectorJob(
            location_id=loc, threshold=12, backend="numpy"))
        assert await node.jobs.wait(jid) == JobStatus.COMPLETED
    asyncio.run(rerun())
    assert len(near_duplicates(lib)) == len(pairs)
