"""Child process for the incident-store kill -9 WAL test: arms the
declared `incidents.write` delay fault — stretching BOTH write windows
(torn-tmp: half the body flushed; complete-tmp: fully written, not yet
renamed) — then fires a stream of distinct-fingerprint incidents so
the parent's SIGKILL lands mid-bundle-write. Run:

    python tests/_incident_crash_child.py <store_dir> <seed> <n>

Prints WRITING when the stream begins and DONE when it completes
(the unkilled convergence run). A killed child leaves the `.running`
crash marker behind — that is the point.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from spacedrive_tpu import chaos  # noqa: E402
from spacedrive_tpu.incidents import IncidentObservatory  # noqa: E402


def main() -> None:
    store_dir, seed, n = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    # Every bundle write pauses 60 ms half-flushed and 60 ms
    # complete-but-unrenamed: the widest possible torn/complete-tmp
    # windows for the parent's SIGKILL.
    chaos.arm("incidents.write=delay:60ms:1.0", seed=seed)
    obs = IncidentObservatory(dir_path=store_dir,
                              node_id="ic", node_name="incident-crash")
    print("WRITING", flush=True)
    for i in range(n):
        # Distinct resource per firing -> distinct fingerprint -> a
        # fresh durable write each time (no dedup collapse).
        obs.observe_give_up(f"obs.http.r{i}", 3)
    obs.close()
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
