"""P2P plane: pairing, sync-over-network, spacedrop, file requests.

Two full nodes in one process connected over loopback TCP — the network
analog of the reference's in-process two-instance sync test
(core/crates/sync/tests/lib.rs:102-217), but with the real transport.
"""

import asyncio
import os

import pytest

from spacedrive_tpu.node import Node


def _run(coro):
    return asyncio.run(coro)


@pytest.fixture
def two_nodes(tmp_path):
    a = Node(str(tmp_path / "a"))
    b = Node(str(tmp_path / "b"))
    yield a, b


async def _start_pair(a: Node, b: Node):
    """Start both p2p planes (no discovery: explicit routes) and pair
    a library from A into B. Returns (lib_a, lib_b)."""
    from conftest import pair_two_nodes

    return await pair_two_nodes(a, b, "shared")


def test_three_node_line_partition_heal(tmp_path):
    """A↔B↔C line over real TCP: ops relay transitively through B's op
    log (C never pairs with A), a partition of B stalls propagation,
    and healing converges every pair — rows AND op logs. The reference
    only ever ships two-instance sync tests
    (core/crates/sync/tests/lib.rs:102-217)."""
    from spacedrive_tpu.sync.manager import GetOpsArgs

    nodes = [Node(str(tmp_path / n)) for n in "abc"]
    a, b, c = nodes

    async def write_tag(lib, name):
        sync = lib.sync
        pub = os.urandom(16)
        ops = sync.shared_create("tag", pub, {"name": name})
        with sync.write_ops(ops) as conn:
            conn.execute("INSERT INTO tag (pub_id, name) VALUES (?, ?)",
                         (pub, name))
        return pub

    def tag_names(lib):
        return {r["name"] for r in lib.db.query("SELECT name FROM tag")}

    async def converge(libs, want, timeout=12.0):
        for _ in range(int(timeout / 0.05)):
            await asyncio.sleep(0.05)
            if all(tag_names(lib) == want for lib in libs):
                return True
        return False

    async def main():
        for n in nodes:
            await n.start()
        ports = [await n.start_p2p(host="127.0.0.1",
                                   enable_discovery=False) for n in nodes]
        # A shares its library into B; B shares the same library into C.
        b.p2p.on_pairing_request = lambda peer, info: True
        c.p2p.on_pairing_request = lambda peer, info: True
        lib_a = a.create_library("mesh")
        assert await a.p2p.pair("127.0.0.1", ports[1], lib_a)
        lib_b = b.libraries.list()[0]
        assert await b.p2p.pair("127.0.0.1", ports[2], lib_b)
        lib_c = c.libraries.list()[0]
        libs = [lib_a, lib_b, lib_c]

        # Transitive relay: a write on A must reach C (and C's reach A).
        await write_tag(lib_a, "from-a")
        await write_tag(lib_c, "from-c")
        assert await converge(libs, {"from-a", "from-c"}), \
            [sorted(tag_names(x)) for x in libs]

        # Partition: B's p2p goes down; A and C write concurrently.
        await b.p2p.stop()
        await write_tag(lib_a, "partition-a")
        await write_tag(lib_c, "partition-c")
        await asyncio.sleep(0.4)
        assert "partition-a" not in tag_names(lib_c)
        assert "partition-c" not in tag_names(lib_a)

        # Heal: B rebinds on a new port; peers re-learn the route (the
        # discovery plane's job in production; injected here) and the
        # next write on each side drains everything both ways.
        new_port = await b.start_p2p(host="127.0.0.1",
                                     enable_discovery=False)
        ident_b = b.p2p.identity.to_remote_identity()
        a.p2p.networked.set_route(ident_b, "127.0.0.1", new_port)
        c.p2p.networked.set_route(ident_b, "127.0.0.1", new_port)
        await write_tag(lib_a, "heal-a")
        await write_tag(lib_c, "heal-c")
        want = {"from-a", "from-c", "partition-a", "partition-c",
                "heal-a", "heal-c"}
        assert await converge(libs, want), \
            [sorted(tag_names(x)) for x in libs]

        # Op-log equivalence on every pair.
        logs = []
        for lib in libs:
            ops = lib.sync.get_ops(GetOpsArgs(clocks=[], count=10000))
            logs.append(sorted(
                (o.timestamp, o.instance, o.typ.kind) for o in ops))
        assert logs[0] == logs[1] == logs[2]
        for n in nodes:
            await n.shutdown()

    _run(main())


def test_sync_stream_refuses_mismatched_proto(two_nodes):
    """A peer announcing a different sync wire version is refused with a
    `done` frame before the pull loop starts — a v1 decoder would
    silently misread multi-field update ops as creates."""
    a, b = two_nodes

    class FakeTunnel:
        def __init__(self):
            self.sent = []

        async def send(self, frame):
            self.sent.append(frame)

        async def recv(self):
            raise AssertionError("pull loop must not start on mismatch")

    async def main():
        lib_a, lib_b = await _start_pair(a, b)
        t = FakeTunnel()
        await b.p2p.networked.handle_sync_stream(
            t, {"t": "sync", "kind": "new_ops",
                "library_id": str(lib_b.id), "proto": 1})
        assert t.sent == [{"kind": "done"}]

        # And the direction that matters: the originator must refuse to
        # SERVE a puller whose request frames lack/mismatch the proto —
        # a v1 decoder would misread multi-field ops as creates.
        class V1Puller:
            def __init__(self):
                self.sent = []
                self.frames = [  # a v1 pull request: no "proto" key
                    {"kind": "messages", "clocks": [], "count": 1000}]

            async def send(self, frame):
                self.sent.append(frame)

            async def recv(self):
                return self.frames.pop(0)

            def close(self):
                pass

        puller = V1Puller()

        async def fake_open_stream(*a, **k):
            return puller

        a.p2p.open_stream = fake_open_stream
        from spacedrive_tpu.p2p.identity import RemoteIdentity
        from spacedrive_tpu.p2p.sync_net import SYNC_PROTO
        await a.p2p.networked._originate_one(
            lib_a, RemoteIdentity(b"\x01" * 32), ("127.0.0.1", 1))
        # Header announced, then an empty terminal page — no ops served.
        assert puller.sent[0]["proto"] == SYNC_PROTO
        assert puller.sent[1] == {"ops": [], "has_more": False}

    _run(main())


def test_pair_then_sync_over_network(two_nodes, tmp_path):
    a, b = two_nodes

    async def main():
        lib_a, lib_b = await _start_pair(a, b)
        assert lib_b.config.name == "shared"

        # A write on A must arrive in B's DB via the originator →
        # responder pull loop.
        sync = lib_a.sync
        pub = os.urandom(16)
        ops = sync.shared_create("tag", pub,
                                 {"name": "from-a", "color": "#f00"})
        with sync.write_ops(ops) as conn:
            conn.execute(
                "INSERT INTO tag (pub_id, name, color) VALUES (?,?,?)",
                (pub, "from-a", "#f00"))
        for _ in range(100):
            await asyncio.sleep(0.05)
            row = lib_b.db.query_one(
                "SELECT * FROM tag WHERE pub_id = ?", (pub,))
            if row is not None:
                break
        assert row is not None and row["name"] == "from-a"

        # Op logs converge (ingested ops are re-logged on B).
        ops_a = lib_a.db.query_one(
            "SELECT COUNT(*) AS n FROM shared_operation")["n"]
        ops_b = lib_b.db.query_one(
            "SELECT COUNT(*) AS n FROM shared_operation")["n"]
        assert ops_a == ops_b > 0
        await a.shutdown()
        await b.shutdown()
    _run(main())


def test_spacedrop_interactive_accept(two_nodes, tmp_path):
    a, b = two_nodes
    payload = os.urandom(70_000)
    src = tmp_path / "gift.bin"
    src.write_bytes(payload)
    dst = tmp_path / "received.bin"

    async def main():
        await a.start()
        await b.start()
        await a.start_p2p(host="127.0.0.1", enable_discovery=False)
        pb = await b.start_p2p(host="127.0.0.1", enable_discovery=False)
        b.p2p.interactive_spacedrop = True

        offers, progress = [], []

        def on_event(e):
            if e.get("type") == "SpacedropRequest":
                offers.append(e)
                b.p2p.accept_spacedrop(e["id"], str(dst))
            elif e.get("type") == "SpacedropProgress":
                progress.append(e)
        b.events.subscribe(on_event)

        result = await a.p2p.spacedrop("127.0.0.1", pb, str(src))
        assert result == "sent"
        assert offers and offers[0]["name"] == "gift.bin"
        assert offers[0]["size"] == len(payload)
        assert dst.read_bytes() == payload
        # receiver emitted throttled progress, ending at the full size
        assert progress and progress[-1]["bytes"] == len(payload)
        assert progress[-1]["direction"] == "receive"
        await a.shutdown()
        await b.shutdown()
    _run(main())


def test_spacedrop_interactive_reject(two_nodes, tmp_path):
    a, b = two_nodes
    src = tmp_path / "gift.bin"
    src.write_bytes(b"data")

    async def main():
        await a.start()
        await b.start()
        await a.start_p2p(host="127.0.0.1", enable_discovery=False)
        pb = await b.start_p2p(host="127.0.0.1", enable_discovery=False)
        b.p2p.interactive_spacedrop = True
        b.events.subscribe(
            lambda e: e.get("type") == "SpacedropRequest"
            and b.p2p.reject_spacedrop(e["id"]))
        result = await a.p2p.spacedrop("127.0.0.1", pb, str(src))
        assert result == "rejected"
        await a.shutdown()
        await b.shutdown()
    _run(main())


def test_pairing_backfills_existing_data(two_nodes):
    """Data that existed BEFORE pairing reaches the new peer without any
    further writes on the originator."""
    a, b = two_nodes

    async def main():
        await a.start()
        await b.start()
        await a.start_p2p(host="127.0.0.1", enable_discovery=False)
        pb = await b.start_p2p(host="127.0.0.1", enable_discovery=False)
        lib_a = a.create_library("pre")
        # Write BEFORE pairing.
        pub = os.urandom(16)
        ops = lib_a.sync.shared_create("tag", pub, {"name": "pre-pair"})
        with lib_a.sync.write_ops(ops) as conn:
            conn.execute("INSERT INTO tag (pub_id, name) VALUES (?, ?)",
                         (pub, "pre-pair"))
        b.p2p.on_pairing_request = lambda peer, info: True
        assert await a.p2p.pair("127.0.0.1", pb, lib_a)
        lib_b = b.libraries.list()[0]
        row = None
        for _ in range(100):
            await asyncio.sleep(0.05)
            row = lib_b.db.query_one(
                "SELECT name FROM tag WHERE pub_id = ?", (pub,))
            if row is not None:
                break
        assert row is not None and row["name"] == "pre-pair"
        await a.shutdown()
        await b.shutdown()
    _run(main())


def test_relation_ops_sync_over_network(two_nodes):
    """Tag assignment (a RELATION CRDT op) flows to the peer, resolving
    pub_ids back to each side's local row ids."""
    a, b = two_nodes

    async def main():
        lib_a, lib_b = await _start_pair(a, b)
        sa = lib_a.sync
        tag_pub, obj_pub = os.urandom(16), os.urandom(16)
        ops = (sa.shared_create("tag", tag_pub, {"name": "red"})
               + sa.shared_create("object", obj_pub, {"kind": 5}))
        with sa.write_ops(ops) as conn:
            conn.execute("INSERT INTO tag (pub_id, name) VALUES (?, ?)",
                         (tag_pub, "red"))
            conn.execute(
                "INSERT INTO object (pub_id, kind) VALUES (?, ?)",
                (obj_pub, 5))
        ops = sa.relation_create("tag_on_object", obj_pub, tag_pub)
        with sa.write_ops(ops) as conn:
            ta = lib_a.db.query_one(
                "SELECT id FROM tag WHERE pub_id = ?", (tag_pub,))["id"]
            oa = lib_a.db.query_one(
                "SELECT id FROM object WHERE pub_id = ?", (obj_pub,))["id"]
            conn.execute(
                "INSERT INTO tag_on_object (tag_id, object_id) "
                "VALUES (?, ?)", (ta, oa))

        row = None
        for _ in range(100):
            await asyncio.sleep(0.05)
            row = lib_b.db.query_one(
                "SELECT t.name FROM tag_on_object tob "
                "JOIN tag t ON t.id = tob.tag_id "
                "JOIN object o ON o.id = tob.object_id "
                "WHERE o.pub_id = ?", (obj_pub,))
            if row is not None:
                break
        assert row is not None and row["name"] == "red"

        # Unassign on A → row disappears on B.
        ops = [sa.relation_delete("tag_on_object", obj_pub, tag_pub)]
        with sa.write_ops(ops) as conn:
            conn.execute(
                "DELETE FROM tag_on_object WHERE tag_id = ? AND "
                "object_id = ?", (ta, oa))
        for _ in range(100):
            await asyncio.sleep(0.05)
            if lib_b.db.query_one(
                    "SELECT 1 FROM tag_on_object tob JOIN object o "
                    "ON o.id = tob.object_id WHERE o.pub_id = ?",
                    (obj_pub,)) is None:
                break
        assert lib_b.db.query_one(
            "SELECT 1 FROM tag_on_object tob JOIN object o "
            "ON o.id = tob.object_id WHERE o.pub_id = ?",
            (obj_pub,)) is None
        await a.shutdown()
        await b.shutdown()
    _run(main())


def test_files_over_p2p_proxy(two_nodes, tmp_path):
    """B serves A's file through its own custom_uri by proxying over the
    mesh (custom_uri/mod.rs files_over_p2p_flag path)."""
    import aiohttp

    from spacedrive_tpu.api.server import ApiServer
    from spacedrive_tpu.jobs.report import JobStatus
    from spacedrive_tpu.locations.indexer_job import IndexerJob
    from spacedrive_tpu.locations.manager import create_location

    a, b = two_nodes
    src = tmp_path / "aloc"
    src.mkdir()
    payload = os.urandom(30_000)
    (src / "shared.bin").write_bytes(payload)

    async def main():
        lib_a, lib_b = await _start_pair(a, b)
        loc = create_location(lib_a, str(src))
        jid = await a.jobs.ingest(lib_a, IndexerJob(location_id=loc))
        assert await a.jobs.wait(jid) in (
            JobStatus.COMPLETED, JobStatus.COMPLETED_WITH_ERRORS)
        # Wait until B has ingested the location + file_path rows.
        for _ in range(100):
            row = lib_b.db.query_one(
                "SELECT * FROM file_path WHERE name = 'shared'")
            if row is not None:
                break
            await asyncio.sleep(0.05)
        assert row is not None
        loc_b = lib_b.db.query_one(
            "SELECT * FROM location WHERE id = ?", (row["location_id"],))
        assert loc_b["instance_id"] is not None  # owner attribution

        if "filesOverP2P" not in b.config.features:
            b.config.toggle_feature("filesOverP2P")
        srv = ApiServer(b)
        port = await srv.start("127.0.0.1", 0)
        url = (f"http://127.0.0.1:{port}/spacedrive/file/"
               f"{lib_b.id}/{row['location_id']}/{row['id']}")
        async with aiohttp.ClientSession() as s:
            async with s.get(url) as r:
                body = await r.read()
                assert r.status == 200, body[:100]
                assert r.headers.get("X-Served-Via") == "p2p"
                assert body == payload
        await srv.stop()
        await a.shutdown()
        await b.shutdown()
    _run(main())


def test_p2p_api_state_and_ping(two_nodes):
    a, b = two_nodes

    async def main():
        from spacedrive_tpu.api.router import mount_router

        await a.start()
        await b.start()
        await a.start_p2p(host="127.0.0.1", enable_discovery=False)
        pb = await b.start_p2p(host="127.0.0.1", enable_discovery=False)

        router = mount_router(a)
        state = await router.dispatch("p2p.state", {})
        assert state["enabled"] and state["port"] == a.p2p.port
        rtt = await router.dispatch(
            "p2p.debugPing", {"addr": "127.0.0.1", "port": pb})
        assert 0 < rtt < 5
        await a.shutdown()
        await b.shutdown()
    _run(main())
