"""Structured-concurrency supervisor (spacedrive_tpu/tasks.py): the
runtime twin of sdlint's task-lifecycle pass.

Covers the registry lifecycle (spawn → live → done-unregister), the
cancellation-safe stop idiom, ownership-tree reaps (deepest first,
orphan detection), violation wiring into the sanitizer, and the PR's
headline regression: a watcher dirty-scan surviving a forced
gc.collect() — the `locations/watcher.py:375` dropped-reference bug
where `asyncio.get_event_loop().create_task(scan())` held NO strong
reference and the collector could cancel a scan mid-flight.
"""

import asyncio
import gc
import os

import pytest

from spacedrive_tpu import sanitize, tasks
from spacedrive_tpu.sanitize import SanitizerViolation


def _run(coro):
    return asyncio.run(coro)


def _labels(owner=None):
    return sorted(f"{r.owner}/{r.name}" for r in tasks.live(owner))


# -- registry lifecycle ------------------------------------------------------

def test_spawn_registers_until_done():
    async def main():
        done = asyncio.Event()

        async def waiter():
            await done.wait()

        t = tasks.spawn("waiter", waiter(), owner="t1")
        assert "t1/waiter" in _labels("t1")
        done.set()
        await t
        await asyncio.sleep(0)
        assert _labels("t1") == []
    _run(main())


def test_spawn_without_loop_raises_and_closes_coro(recwarn):
    async def work():
        await asyncio.sleep(0)

    with pytest.raises(RuntimeError):
        tasks.spawn("no-loop", work(), owner="t2")
    gc.collect()
    # the coroutine was closed on failure: no "never awaited" warning
    assert not [w for w in recwarn.list
                if "never awaited" in str(w.message)]


def test_task_names_carry_the_sdtpu_prefix():
    async def main():
        async def idle():
            await asyncio.sleep(30)

        t = tasks.spawn("named", idle(), owner="t3/sub")
        assert t.get_name() == f"{tasks.TASK_NAME_PREFIX}t3/sub/named"
        await tasks.cancel_and_gather(t)
    _run(main())


def test_unique_owner_and_label_normalization():
    a = tasks.unique_owner("node")
    b = tasks.unique_owner("node")
    assert a != b and a.startswith("node#")
    assert tasks.owner_label(f"{a}/p2p/mdns") == "node/p2p/mdns"


# -- exception observation ---------------------------------------------------

def test_task_exception_is_recorded_as_violation():
    async def main():
        async def boom():
            raise ValueError("kaput")

        tasks.spawn("boom", boom(), owner="t4")
        await asyncio.sleep(0.05)
    _run(main())
    kinds = [v for v in sanitize.violations()
             if v["kind"] == "task_exception" and "kaput" in v["detail"]]
    assert kinds, sanitize.violations()[-3:]
    sanitize.reset_violations()  # deliberate trigger: keep tier-1 green


def test_cancelled_task_is_not_an_exception_violation():
    before = len(sanitize.violations())

    async def main():
        async def idle():
            await asyncio.sleep(30)

        t = tasks.spawn("idle", idle(), owner="t5")
        await tasks.cancel_and_gather(t)
    _run(main())
    assert sanitize.violations()[before:] == []


# -- cancel_and_gather -------------------------------------------------------

def test_cancel_and_gather_swallows_victim_cancellation_only():
    async def main():
        cleaned = []

        async def victim():
            try:
                await asyncio.sleep(30)
            finally:
                cleaned.append(True)

        t = tasks.spawn("victim", victim(), owner="t6")
        await asyncio.sleep(0)
        await tasks.cancel_and_gather(t, None)  # None entries tolerated
        assert cleaned == [True]
        assert t.cancelled()
    _run(main())


def test_cancel_and_gather_propagates_caller_cancellation():
    async def main():
        started = asyncio.Event()

        async def stubborn():
            # refuses the FIRST cancel so the gather stays pending
            # while the caller itself gets cancelled
            try:
                await asyncio.sleep(30)
            except asyncio.CancelledError:
                started.set()
                await asyncio.sleep(30)

        victim = tasks.spawn("stubborn", stubborn(), owner="t7")
        await asyncio.sleep(0)

        async def caller():
            await tasks.cancel_and_gather(victim)

        c = asyncio.ensure_future(caller())
        await started.wait()
        c.cancel()
        with pytest.raises(asyncio.CancelledError):
            await c
        victim.cancel()  # second cancel lands; clean up
        await asyncio.gather(victim, return_exceptions=True)
    _run(main())


# -- reap --------------------------------------------------------------------

def test_reap_cancels_subtree_children_first():
    async def main():
        order = []

        def ender(tag):
            async def run():
                try:
                    await asyncio.sleep(30)
                finally:
                    order.append(tag)
            return run()

        tasks.spawn("parent", ender("parent"), owner="n1")
        tasks.spawn("child", ender("child"), owner="n1/p2p")
        tasks.spawn("grandchild", ender("grand"), owner="n1/p2p/mdns")
        tasks.spawn("other", ender("other"), owner="n2")
        await asyncio.sleep(0)
        reaped = await tasks.reap("n1", grace_s=2.0)
        assert set(reaped) == {"n1/parent", "n1/p2p/child",
                               "n1/p2p/mdns/grandchild"}
        # deepest owners die before their parents
        assert order.index("grand") < order.index("child") < \
            order.index("parent")
        assert _labels("n1") == []
        assert _labels("n2") == ["n2/other"]  # untouched sibling tree
        await tasks.reap("n2", grace_s=2.0)
    _run(main())


def test_reap_raises_on_orphaned_task():
    async def main():
        release = asyncio.Event()

        async def immortal():
            while not release.is_set():
                try:
                    await asyncio.sleep(30)
                except asyncio.CancelledError:
                    pass  # ignores cancellation: the orphan shape

        tasks.spawn("immortal", immortal(), owner="n3")
        await asyncio.sleep(0)
        with pytest.raises(SanitizerViolation, match="task_orphaned"):
            await tasks.reap("n3", grace_s=0.1)
        release.set()
        for rec in tasks.live("n3"):
            rec.task.cancel()
        await asyncio.sleep(0.05)
    _run(main())
    sanitize.reset_violations()  # deliberate trigger


def test_reap_zero_grace_cancels_before_declaring_orphans():
    """grace_s=0 means "cancel, just don't wait" — never "leave
    everything running": the cancel pass is unconditional, only the
    wait is grace-bounded."""
    async def main():
        async def idle():
            await asyncio.sleep(30)

        t = tasks.spawn("idle", idle(), owner="n5")
        await asyncio.sleep(0)
        with pytest.raises(SanitizerViolation, match="task_orphaned"):
            await tasks.reap("n5", grace_s=0.0)
        # the cancel was still delivered: the task dies at its next
        # suspension instead of running on against closed DBs
        await asyncio.gather(t, return_exceptions=True)
        assert t.cancelled()
    _run(main())
    sanitize.reset_violations()  # deliberate trigger


def test_reap_sweeps_tasks_spawned_during_the_reap():
    """A callback queued before shutdown can spawn under the owner
    WHILE the reap awaits (threadsafe originate_soon, ws-emit,
    watcher on_dirty): a one-shot snapshot would let it escape both
    cancellation and the orphan report."""
    async def main():
        late_done = []

        async def late():
            try:
                await asyncio.sleep(30)
            finally:
                late_done.append(True)

        async def spawner():
            try:
                await asyncio.sleep(30)
            except asyncio.CancelledError:
                tasks.spawn("late", late(), owner="n6")
                raise

        tasks.spawn("spawner", spawner(), owner="n6")
        await asyncio.sleep(0)
        reaped = await tasks.reap("n6", grace_s=2.0)
        assert "n6/late" in reaped
        assert late_done == [True]
        await asyncio.sleep(0)
        assert _labels("n6") == []
    _run(main())


def test_reap_observes_cancel_latency_metric():
    from spacedrive_tpu.telemetry import TASK_CANCEL_LATENCY

    before = TASK_CANCEL_LATENCY.count

    async def main():
        async def idle():
            await asyncio.sleep(30)

        tasks.spawn("idle", idle(), owner="n4")
        await asyncio.sleep(0)
        await tasks.reap("n4", grace_s=2.0)
    _run(main())
    assert TASK_CANCEL_LATENCY.count == before + 1


# -- the watcher GC regression (satellite #1) --------------------------------

def test_supervised_fire_and_forget_survives_gc():
    """The supervisor holds the ONLY strong reference: a spawn whose
    result is discarded must survive aggressive collection (the loop
    itself keeps tasks weakly — asyncio docs require callers to hold
    a reference, which the registry now does for everyone)."""
    async def main():
        hit = asyncio.Event()

        async def scan():
            await asyncio.sleep(0.05)
            hit.set()

        tasks.spawn("gc-scan", scan(), owner="t8")  # reference dropped
        for _ in range(10):
            gc.collect()
            await asyncio.sleep(0.02)
        assert hit.is_set()
    _run(main())


def _has_cryptography() -> bool:
    try:
        import cryptography  # noqa: F401
        return True
    except ImportError:
        return False


def test_watcher_on_dirty_scan_survives_forced_gc(tmp_path, monkeypatch):
    """The watcher.py:375 code path itself, crypto-free: drive the
    on_dirty closure directly (shallow's heavy import chain stubbed)
    and assert the supervised scan task completes under gc pressure —
    the old dropped-reference spawn could be collected mid-scan."""
    import sys
    import types

    from spacedrive_tpu.locations.watcher import Locations
    from spacedrive_tpu.node import Node

    scans = []
    stub = types.ModuleType("spacedrive_tpu.locations.shallow")

    def light_scan_location(lib, loc, sub, backend):
        scans.append((loc, sub))
        return {"saved": 0}
    stub.light_scan_location = light_scan_location
    monkeypatch.setitem(sys.modules,
                        "spacedrive_tpu.locations.shallow", stub)

    src = tmp_path / "src"
    src.mkdir()
    node = Node(str(tmp_path / "data"))
    lib = node.create_library("t")
    lib.db.insert("location", {
        "pub_id": os.urandom(16), "name": "src", "path": str(src),
        "date_created": 0})

    async def main():
        monkeypatch.setenv("SDTPU_WATCHER", "poll")
        locations = Locations(node, backend="numpy")
        loc_id = lib.db.query_one("SELECT id FROM location")["id"]
        assert locations.watch_location(lib, loc_id)
        (src / "new.bin").write_bytes(b"x" * 64)
        for _ in range(60):
            gc.collect()  # the old dropped-reference spawn died here
            await asyncio.sleep(0.1)
            if scans:
                break
        else:
            raise AssertionError("dirty-scan never ran under gc "
                                 "pressure")
        locations.close()
        await node.close()
    _run(main())


@pytest.mark.skipif(not os.path.exists("/proc"), reason="linux only")
@pytest.mark.skipif(not _has_cryptography(),
                    reason="cryptography missing (environmental)")
def test_watcher_dirty_scan_survives_forced_gc(tmp_path, monkeypatch):
    """End-to-end regression for locations/watcher.py:375: the dirty-
    scan task spawned by a watch event used the deprecated
    `asyncio.get_event_loop().create_task(scan())` and dropped the
    reference — GC was free to destroy the scan mid-flight. Routed
    through the supervisor, the scan must index the new file while
    gc.collect() hammers every poll tick."""
    monkeypatch.setenv("SDTPU_WATCHER", "poll")
    from spacedrive_tpu.locations.manager import create_location
    from spacedrive_tpu.locations.watcher import Locations, PollingWatcher
    from spacedrive_tpu.node import Node

    src = tmp_path / "src"
    src.mkdir()
    (src / "seed.txt").write_bytes(b"seed")
    node = Node(str(tmp_path / "data"))
    lib = node.create_library("t")

    async def main():
        from spacedrive_tpu.locations.indexer_job import IndexerJob

        sid = create_location(lib, str(src))
        j = await node.jobs.ingest(lib, IndexerJob(location_id=sid))
        await node.jobs.wait(j)
        locations = Locations(node, backend="numpy")
        assert locations.watch_location(lib, sid)
        assert isinstance(locations.watchers[(lib.id, sid)],
                          PollingWatcher)
        with open(src / "ghost.bin", "wb") as f:
            f.write(b"gc-bait" * 64)
        for _ in range(120):
            gc.collect()  # the old dropped-reference spawn died here
            await asyncio.sleep(0.1)
            row = lib.db.query_one(
                "SELECT object_id FROM file_path WHERE name='ghost'")
            if row is not None and row["object_id"] is not None:
                break
        else:
            raise AssertionError(
                "dirty-scan never indexed the new file under gc "
                "pressure")
        locations.close()
        await node.close()
    _run(main())
