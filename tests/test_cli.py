"""CLI host: header inspection + one-shot encrypt/decrypt."""

import pytest

from spacedrive_tpu.cli import main


@pytest.fixture(autouse=True)
def _tiny_balloon_costs(monkeypatch):
    from spacedrive_tpu.crypto import hashing
    from spacedrive_tpu.crypto.hashing import HashingAlgorithm, Params

    monkeypatch.setattr(hashing, "_BALLOON_COSTS", {
        Params.STANDARD: (16, 1),
        Params.HARDENED: (32, 1),
        Params.PARANOID: (64, 1),
    })
    # CLI defaults to argon2id; keep the test fast by defaulting balloon.
    import spacedrive_tpu.crypto.header as header_mod

    monkeypatch.setattr(
        header_mod.encrypt_file, "__defaults__",
        (header_mod.Algorithm.XCHACHA20_POLY1305,
         HashingAlgorithm.BALLOON_BLAKE3, Params.STANDARD, None, None,
         None))


def test_encrypt_header_decrypt_roundtrip(tmp_path, capsys):
    src = tmp_path / "plain.bin"
    src.write_bytes(b"cli secret" * 50)

    assert main(["encrypt", str(src), "-p", "pw"]) == 0
    sealed = str(src) + ".sdtpu"

    assert main(["header", sealed]) == 0
    out = capsys.readouterr().out
    assert "Header version: 1" in out
    assert "XChaCha20Poly1305" in out
    assert "Keyslot 0:" in out

    dst = tmp_path / "roundtrip.bin"
    assert main(["decrypt", sealed, "-o", str(dst), "-p", "pw"]) == 0
    assert dst.read_bytes() == src.read_bytes()


def test_header_rejects_plain_file(tmp_path, capsys):
    p = tmp_path / "not_encrypted.txt"
    p.write_bytes(b"hello world")
    assert main(["header", str(p)]) == 1
    assert "error" in capsys.readouterr().err


def test_decrypt_wrong_password(tmp_path):
    src = tmp_path / "a.bin"
    src.write_bytes(b"x" * 100)
    assert main(["encrypt", str(src), "-p", "right"]) == 0
    out = tmp_path / "out.bin"
    assert main(["decrypt", str(src) + ".sdtpu", "-o", str(out),
                 "-p", "wrong"]) == 1
    assert not out.exists()  # failed decrypt leaves nothing behind


def test_encrypt_refuses_existing_output(tmp_path, capsys):
    src = tmp_path / "a.bin"
    src.write_bytes(b"x")
    (tmp_path / "a.bin.sdtpu").write_bytes(b"occupied")
    assert main(["encrypt", str(src), "-p", "pw"]) == 1
    assert "already exists" in capsys.readouterr().err
    assert (tmp_path / "a.bin.sdtpu").read_bytes() == b"occupied"
