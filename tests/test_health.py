"""Health observatory (spacedrive_tpu/health.py): telemetry delta
snapshots (exact under concurrency, cumulative families untouched),
windowed bucket-delta percentiles, the sampler's bounded rings, the
saturation engine's attribution — including the three-saturation
scenario gates (wedged ws consumer / held store write lock /
sim-link-throttled depth-N run) — the node.health query +
subscription surfaces, the sd_top CLI self-check, and the
SDTPU_LOG_JSON trace-correlated logging satellite."""

import asyncio
import io
import json
import logging
import os
import subprocess
import sys
import threading
import time

import pytest

from spacedrive_tpu import channels, health, telemetry, tracing
from spacedrive_tpu.telemetry import MetricsRegistry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

try:
    # Seed the objects package: in runtimes without `cryptography` the
    # first attempt fails but leaves the non-crypto submodules cached,
    # after which mount_router imports cleanly (container quirk; no-op
    # where the dependency exists).
    import spacedrive_tpu.objects  # noqa: F401
except ModuleNotFoundError:
    pass


def _run(coro):
    return asyncio.run(coro)


# -- delta snapshots (satellite 1) -------------------------------------------

def test_counter_snapshot_delta_telescopes():
    reg = MetricsRegistry()
    c = reg.counter("sd_jobs_hd_total")
    c.inc(3)
    d1 = c.snapshot_delta()
    assert d1["value"] == 3
    c.inc(2)
    d2 = c.snapshot_delta(d1["cursor"])
    assert d2["value"] == 2
    # cumulative value untouched by any number of delta readers
    assert c.value == 5
    # registry reset mid-window: the delta restarts, never negative
    c._zero()
    c.inc(1)
    d3 = c.snapshot_delta(d2["cursor"])
    assert d3["value"] == 1


def test_histogram_snapshot_delta_windows():
    reg = MetricsRegistry()
    h = reg.histogram("sd_jobs_hdh_seconds", buckets=(0.1, 1.0, 10.0))
    h.observe(0.05)
    h.observe(5.0)
    d1 = h.snapshot_delta()
    assert d1["count"] == 2 and d1["counts"] == [1, 0, 1, 0]
    h.observe(0.5)
    d2 = h.snapshot_delta(d1["cursor"])
    # ONLY the window's observations, per bucket, exactly
    assert d2["count"] == 1 and d2["counts"] == [0, 1, 0, 0]
    assert abs(d2["sum"] - 0.5) < 1e-9
    # the cumulative family never changed meaning: totals monotone
    s = h.snapshot_value()
    assert s["count"] == 3 and s["buckets"][-1] == ["+Inf", 3]
    # reset mid-window restarts the delta instead of going negative
    h._zero()
    h.observe(0.05)
    d3 = h.snapshot_delta(d2["cursor"])
    assert d3["count"] == 1 and d3["counts"][0] == 1


def test_delta_snapshots_exact_totals_under_concurrency():
    """Writers hammer a histogram + counter while a reader takes
    windowed deltas mid-flight: the windows must telescope to the
    exact totals (nothing lost, nothing double-counted) — and the
    race recorder is armed suite-wide, so the declared
    telemetry.Histogram ownership contract audits every write."""
    reg = MetricsRegistry()
    h = reg.histogram("sd_jobs_hdc_seconds", buckets=(0.5,))
    c = reg.counter("sd_jobs_hdc_total")
    n_threads, per = 8, 2000
    stop = threading.Event()
    got = {"count": 0, "buckets": [0, 0], "value": 0.0}
    hcur = ccur = None

    def drain():
        nonlocal hcur, ccur
        dh = h.snapshot_delta(hcur)
        hcur = dh["cursor"]
        dc = c.snapshot_delta(ccur)
        ccur = dc["cursor"]
        got["count"] += dh["count"]
        got["buckets"][0] += dh["counts"][0]
        got["buckets"][1] += dh["counts"][1]
        got["value"] += dc["value"]

    def reader():
        while not stop.is_set():
            drain()

    def writer(i):
        for k in range(per):
            h.observe(0.25 if k % 2 else 0.75)
            c.inc()

    r = threading.Thread(target=reader)
    r.start()
    ws = [threading.Thread(target=writer, args=(i,))
          for i in range(n_threads)]
    for w in ws:
        w.start()
    for w in ws:
        w.join()
    stop.set()
    r.join()
    drain()  # the residual window after the last mid-flight read
    total = n_threads * per
    assert got["count"] == total
    assert got["buckets"] == [total // 2, total // 2]
    assert got["value"] == total
    # cumulative untouched by the windowed reader
    assert h.count == total and c.value == total


def test_windowed_quantile_interpolation():
    buckets = (0.1, 1.0, 10.0)
    assert health.windowed_quantile(buckets, [0, 0, 0, 0], 0.99) is None
    # one observation in (0.1, 1.0]: interpolates inside that bucket
    p50 = health.windowed_quantile(buckets, [0, 1, 0, 0], 0.5)
    assert 0.1 < p50 <= 1.0
    # uniform mass: p50 lands mid-scale, p99 near the top bucket
    p50 = health.windowed_quantile(buckets, [10, 10, 10, 0], 0.5)
    assert abs(p50 - 0.55) < 1e-9  # halfway into the middle bucket
    # +Inf observations clamp to the top finite bound
    assert health.windowed_quantile(buckets, [0, 0, 0, 5], 0.99) == 10.0


# -- sampler + rings ---------------------------------------------------------

def test_sampler_windows_and_bounded_rings():
    mon = health.HealthMonitor(interval_s=0.05)
    c = telemetry.REGISTRY.counter("sd_jobs_hsr_total")
    cap = channels.capacity("health.series")
    for _ in range(5):
        c.inc(10)
        time.sleep(0.002)
        snap = mon.sample()
    rec = snap["window"]["sd_jobs_hsr_total"]
    assert rec["kind"] == "counter" and rec["delta"] == 10
    assert rec["rate"] > 0
    # every ring stays within the declared health.series capacity
    for _ in range(cap + 20):
        mon.sample()
    assert mon._series, "sampler built no series rings"
    assert all(len(ring) <= channels.capacity("health.series")
               for ring in mon._series.values())
    # the state gauge family is live for every base subsystem
    g = telemetry.REGISTRY.get("sd_health_state")
    for sub in health.BASE_SUBSYSTEMS:
        child = g.labels(subsystem=sub)
        assert child.value in (0.0, 1.0, 2.0)


def test_health_monitor_emits_periodic_snapshots():
    from spacedrive_tpu.node import EventBus

    async def main():
        bus = EventBus()
        got = []
        bus.subscribe(got.append)
        mon = health.HealthMonitor(bus, interval_s=0.05)
        mon.start()
        await asyncio.sleep(0.3)
        mon.stop()
        snaps = [e for e in got if e["type"] == "HealthSnapshot"]
        assert snaps, "no HealthSnapshot events emitted"
        assert health.validate_health_snapshot(snaps[0]["health"]) == []
        assert snaps[0]["health"]["window_s"] is not None
    _run(main())


def test_sheds_expected_contracts():
    """History rings age by design: their sheds are not saturation
    evidence (the health engine skips them), and the contract is
    declared, not engine-hardcoded."""
    for name in ("health.series", "health.snapshots",
                 "ops.pipeline.timeline", "jobs.worker.commands"):
        assert channels.CHANNELS[name].sheds_expected, name
    for name in ("api.ws", "jobs.manager.queue", "media.thumbs"):
        assert not channels.CHANNELS[name].sheds_expected, name


# -- the three-saturation scenario gates (acceptance criteria) ---------------

def test_scenario_wedged_ws_consumer_attributed_to_api_ws():
    """A websocket subscriber that stops reading: the api.ws channel
    fills to its declared capacity and sheds — node.health must
    attribute the api subsystem's saturation to `api.ws` by its
    declared name within one sampling interval."""
    from spacedrive_tpu.api.server import WsSubscriptionPump

    async def main():
        mon = health.HealthMonitor(interval_s=0.05)
        stall = asyncio.Event()

        async def stalled_send(payload):
            await stall.wait()

        pump = WsSubscriptionPump(stalled_send, owner="test-health-ws")
        cap = pump.chan.capacity
        # distinct (un-coalescible) events, synchronously — the
        # wedged drainer never gets scheduled in between
        for i in range(3 * cap):
            pump.offer({"id": 1, "type": "event",
                        "data": {"type": "Notification", "n": i}})
        assert len(pump.chan) == cap
        snap = mon.sample()  # ONE sampling interval
        assert snap["states"]["api"] == "saturated"
        top = snap["attribution"]["api"][0]
        assert top["resource"] == "api.ws"
        assert top["owner"] == channels.CHANNELS["api.ws"].owner
        key = "sd_chan_depth{name=api.ws}"
        assert top["evidence"][key] == cap
        assert top["evidence"]["capacity"] == cap
        assert top["evidence"]["sd_chan_shed_total{name=api.ws}"] > 0
        # evidence series inline: the depth ring tail rides along
        assert key in top["points"] and top["points"][key]
        stall.set()
        await pump.stop()
    _run(main())


def test_scenario_held_write_lock_attributed_to_store(tmp_path):
    """A held store write lock: concurrent writers observe long
    sd_store_write_lock_wait waits — the store subsystem saturates,
    attributed to store.db.write_lock, while the CUMULATIVE histogram
    keeps its meaning (monotone totals, never reset) and the windowed
    p99 moves back down once the contention passes."""
    from spacedrive_tpu.store.db import Database

    db = Database(str(tmp_path / "lock.db"))
    hist = telemetry.REGISTRY.get("sd_store_write_lock_wait_seconds")
    cum_before = hist.count
    mon = health.HealthMonitor(interval_s=0.05)
    release = threading.Event()

    def holder():
        with db.tx() as conn:
            conn.execute(
                "INSERT INTO tag (pub_id, name) VALUES (?, ?)",
                (os.urandom(16), "held"))
            release.wait(timeout=10)

    def waiter():
        with db.tx() as conn:
            conn.execute(
                "INSERT INTO tag (pub_id, name) VALUES (?, ?)",
                (os.urandom(16), "waited"))

    t1 = threading.Thread(target=holder)
    t1.start()
    time.sleep(0.15)  # the holder owns the write lock
    t2 = threading.Thread(target=waiter)
    t2.start()
    time.sleep(0.6)
    release.set()
    t1.join(timeout=10)
    t2.join(timeout=10)
    assert not t2.is_alive()

    snap = mon.sample()  # within one sampling interval of the wait
    assert snap["states"]["store"] == "saturated"
    top = snap["attribution"]["store"][0]
    assert top["resource"] == "store.db.write_lock"
    assert top["doc"]  # named by its declared registry doc
    p99 = snap["window"]["sd_store_write_lock_wait_seconds"]["p99"]
    assert p99 is not None and p99 >= health.LOCK_WAIT_SATURATED_S
    # cumulative family unchanged in meaning: totals only grew
    assert hist.count > cum_before
    cum_after = hist.count
    # an idle window later: the WINDOWED p99 empties out while the
    # cumulative count stands — exactly what cumulative-forever
    # histograms could not express
    time.sleep(0.05)
    snap2 = mon.sample()
    assert snap2["window"][
        "sd_store_write_lock_wait_seconds"]["p99"] is None
    assert snap2["states"]["store"] == "ok"
    assert hist.count == cum_after
    db.close()


def test_scenario_simlink_pipeline_attributed_to_h2d(tmp_path,
                                                     monkeypatch):
    """A sim-link-throttled depth-N run: H2D dominates every batch
    window, the retirer starves — the ops subsystem degrades with the
    bound attributed to ops.pipeline.h2d (cross-read from the flight
    recorder's per-batch bound attribution)."""
    from spacedrive_tpu.ops import overlap
    from tools.overlap_bench import _cheap_kernel

    # Warm the cheap kernel at the measured batch shape OUTSIDE the
    # window so a cold jit compile cannot dilute the stall rates.
    warm_dir = tmp_path / "warm"
    warm_dir.mkdir()
    warm = overlap.make_sparse_corpus(str(warm_dir), 512, 120_000, 512)
    overlap.run_overlapped(warm, kernel=_cheap_kernel, depth=1,
                           calibrate_every=8)

    # B=512 @ 0.125 GB/s: ~490 ms of simulated H2D per batch, an
    # order of magnitude over this container's staging cost — the
    # same corpus shape the PR 13 sim-link gate pins (a 32-file batch
    # is genuinely STAGE-bound here, which is correct attribution but
    # the wrong scenario).
    monkeypatch.setenv("SDTPU_SIM_LINK_GBPS", "0.125")
    corpus_dir = tmp_path / "corpus"
    corpus_dir.mkdir()
    corpus = overlap.make_sparse_corpus(
        str(corpus_dir), 512 * 4, 120_000, 512)
    mon = health.HealthMonitor(interval_s=0.05)
    _res, _stats = overlap.run_overlapped(
        corpus, kernel=_cheap_kernel, depth=3,
        calibrate_every=len(corpus))
    snap = mon.sample()  # one sampling interval after the run
    assert snap["states"]["ops"] in ("degraded", "saturated"), \
        snap["states"]
    top = snap["attribution"]["ops"][0]
    assert top["resource"] == "ops.pipeline.h2d", top
    assert "sd_pipeline_retire_stall_seconds_total" in top["evidence"]
    assert health.validate_health_snapshot(snap) == []


# -- surfaces ----------------------------------------------------------------

def test_node_health_query_and_subscription(tmp_path):
    from spacedrive_tpu.api.router import mount_router
    from spacedrive_tpu.node import Node

    node = Node(str(tmp_path / "data"))
    router = mount_router(node)
    # the path is BOTH a query and a subscription (split namespaces)
    assert "node.health" in router.procedures
    assert "node.health" in router.subscriptions

    async def main():
        snap = await router.dispatch("node.health")
        assert health.validate_health_snapshot(snap) == []
        assert set(health.BASE_SUBSYSTEMS) <= set(snap["states"])
        got = []
        unsub = await router.subscribe("node.health", None, got.append)
        # one immediately on subscribe, validated payload
        assert got and got[0]["type"] == "HealthSnapshot"
        assert health.validate_health_snapshot(got[0]["health"]) == []
        unsub()
    _run(main())
    _run(node.shutdown())


def test_ws_pump_coalesces_health_snapshots_newest_wins():
    from spacedrive_tpu.api.server import WsSubscriptionPump

    async def main():
        stall = asyncio.Event()

        async def stalled_send(payload):
            await stall.wait()

        pump = WsSubscriptionPump(stalled_send, owner="test-health-co")
        for seq in (1, 2, 3):
            pump.offer({"id": 1, "type": "event",
                        "data": {"type": "HealthSnapshot", "seq": seq}})
        assert len(pump.chan) == 1  # coalesced
        frame = pump.chan.get_nowait()
        assert frame["data"]["seq"] == 3  # newest wins
        stall.set()
        await pump.stop()
    _run(main())


def test_health_state_served_on_metrics_endpoint(tmp_path):
    """GET /metrics carries the sd_health_state{subsystem} gauges a
    scraper alerts on."""
    mon = health.HealthMonitor(interval_s=0.05)
    mon.sample()
    text = telemetry.render_prometheus()
    assert "# TYPE sd_health_state gauge" in text
    assert 'sd_health_state{subsystem="store"}' in text


def test_sd_top_cli_self_check(tmp_path):
    """`python -m tools.sd_top --json` is the tier-1 gate: exit 0 +
    a schema-valid artifact whose three induced saturations are
    attributed to the right declared resources; a corrupted artifact
    fed back through --input exits non-zero naming the violation."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "tools.sd_top", "--json"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["metric"] == "sd_top"
    assert health.validate_health_snapshot(doc["health"]) == []
    assert doc["health"]["states"]["store"] == "saturated"

    # corrupt: state/severity consistency broken
    doc["health"]["states"]["store"] = "ok"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(doc))
    out2 = subprocess.run(
        [sys.executable, "-m", "tools.sd_top", "--input", str(bad)],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=120)
    assert out2.returncode == 1
    assert "inconsistent" in out2.stderr


def test_sd_top_live_url_fetch(tmp_path):
    """The operator path: sd_top's fetchers pull node.health AND
    node.metrics from a live API host over rspc HTTP and render one
    frame with the cumulative context in the header."""
    from spacedrive_tpu.api.server import ApiServer
    from spacedrive_tpu.node import Node
    from tools.sd_top import fetch_health, fetch_metrics, render_top

    async def main():
        node = Node(str(tmp_path / "data"))
        server = ApiServer(node)
        port = await server.start(port=0)
        try:
            url = f"http://127.0.0.1:{port}"
            snap = await asyncio.to_thread(fetch_health, url)
            assert health.validate_health_snapshot(snap) == []
            metrics = await asyncio.to_thread(fetch_metrics, url)
            frame = render_top(snap, source=url, metrics=metrics)
            assert "SUBSYSTEM" in frame and "families=" in frame
        finally:
            await server.stop()
            await node.shutdown()
    _run(main())


def test_render_top_frame():
    from tools.sd_top import render_top

    mon = health.HealthMonitor(interval_s=0.05)
    time.sleep(0.02)
    snap = mon.sample()
    frame = render_top(snap, source="unit-test")
    assert "SUBSYSTEM" in frame and "unit-test" in frame
    for sub in health.BASE_SUBSYSTEMS:
        assert sub in frame


def test_overlap_bench_health_flow():
    """The bench embedding flow (cursors before the sweep, one sample
    after) produces a schema-clean health section — the shape
    overlap_bench --json and perf_smoke --telemetry ship."""
    mon = health.HealthMonitor(interval_s=0.05)
    time.sleep(0.02)
    snap = mon.sample()
    section = {"window_s": snap["window_s"], "states": snap["states"],
               "attribution": snap["attribution"]}
    assert health.validate_health_snapshot(snap) == []
    assert json.dumps(section)  # JSON-safe artifact body


# -- SDTPU_LOG_JSON (satellite 2) -------------------------------------------

def test_json_log_formatter_stamps_span_trace_id():
    buf = io.StringIO()
    assert tracing.install_json_logging(force=True, stream=buf)
    try:
        logger = logging.getLogger("spacedrive_tpu")
        with tracing.span("rpc/log-probe"):
            expected = tracing.current_trace_id()
            logger.warning("inside span %d", 7)
        rec = json.loads(buf.getvalue().strip().splitlines()[-1])
        assert rec["msg"] == "inside span 7"
        assert rec["level"] == "WARNING"
        assert rec["trace"] == expected
        assert "span" in rec
        logger.warning("outside any span")
        rec2 = json.loads(buf.getvalue().strip().splitlines()[-1])
        assert "trace" not in rec2
    finally:
        tracing.uninstall_json_logging()


def test_json_log_trace_survives_to_thread():
    buf = io.StringIO()
    assert tracing.install_json_logging(force=True, stream=buf)
    try:
        logger = logging.getLogger("spacedrive_tpu")

        async def main():
            with tracing.span("job/log-thread-probe"):
                expected = tracing.current_trace_id()
                await asyncio.to_thread(
                    logger.warning, "from a worker thread")
            return expected

        expected = _run(main())
        rec = json.loads(buf.getvalue().strip().splitlines()[-1])
        assert rec["trace"] == expected
    finally:
        tracing.uninstall_json_logging()


def test_json_logging_flag_gate(monkeypatch):
    monkeypatch.setenv("SDTPU_LOG_JSON", "0")
    assert not tracing.install_json_logging()
    monkeypatch.setenv("SDTPU_LOG_JSON", "1")
    assert tracing.install_json_logging()
    assert tracing.install_json_logging()  # idempotent
    tracing.uninstall_json_logging()


# -- perf_smoke embeds a health stage (satellite 4) --------------------------

def _has_cryptography() -> bool:
    try:
        import cryptography  # noqa: F401
        return True
    except ModuleNotFoundError:
        return False


@pytest.mark.skipif(
    not _has_cryptography(),
    reason="perf_smoke imports objects.dedup, whose package init "
           "needs the cryptography module")
def test_perf_smoke_embeds_health_stage(tmp_path):
    from tools.perf_smoke import run as smoke_run

    out = tmp_path / "smoke.json"
    _run(smoke_run(files=40, backend="auto", images=0,
                   keep=str(tmp_path / "work"),
                   with_telemetry=True, json_out=str(out)))
    doc = json.loads(out.read_text())
    stages = {s["stage"]: s for s in doc["stages"]}
    assert "health" in stages, sorted(stages)
    h = stages["health"]
    assert set(health.BASE_SUBSYSTEMS) <= set(h["states"])
    assert h["window_s"] and h["window_s"] > 0
    assert all(v in health.STATES for v in h["states"].values())
