"""Runtime SQL auditor (store/sqlaudit.py) + statement registry.

The dynamic half of the round-16 store passes: contract matching on
every executed statement, the autocommit-write and undeclared-
statement violations, the ad-hoc read allowance, the per-tx statement
histogram, shape matching with registry-identifier validation, and
the read-path/write-path split regression (reads must not serialize
behind the write lock)."""

import threading
import time

import pytest

from spacedrive_tpu import sanitize
from spacedrive_tpu.store import sqlaudit, statements
from spacedrive_tpu.store.db import Database
from spacedrive_tpu.telemetry import snapshot


@pytest.fixture
def db(tmp_path):
    d = Database(str(tmp_path / "lib.db"))
    yield d
    d.close()


def _metric(name, label=None):
    fam = snapshot().get(name)
    if fam is None:
        return 0.0
    if label is None:
        return fam.get("value", 0.0)
    for child in fam.get("labeled", []):
        if child["labels"].get("name") == label:
            return child["value"]
    return 0.0


# -- registry round-trip -----------------------------------------------------

def test_registry_round_trip():
    st = statements.get("api.tag.by_id")
    assert st.verb == "read"
    assert st.cardinality == "one"
    assert st.tables == ("tag",)
    assert statements.lookup_sql(st.sql) is st
    # whitespace never changes identity
    assert statements.lookup_sql(
        "SELECT  *\n FROM tag   WHERE id = ?;") is st


def test_registry_validation_raises():
    E = statements.SqlContractError
    with pytest.raises(E):
        statements.get("no.such.statement")
    with pytest.raises(E):  # name discipline
        statements.declare_stmt("NotDotted", "SELECT 1 FROM tag",
                                verb="read", cardinality="one")
    with pytest.raises(E):  # verb vs leading keyword
        statements.declare_stmt(
            "fixture.verb_clash", "DELETE FROM tag WHERE id = ?",
            verb="read", tables=("tag",), cardinality="one")
    with pytest.raises(E):  # unknown table at declare time
        statements.declare_stmt(
            "fixture.ghost", "SELECT 1 FROM warp_core",
            verb="read", tables=("warp_core",), cardinality="one")
    with pytest.raises(E):  # duplicate SQL must reuse the name
        statements.declare_stmt(
            "fixture.duplicate", "SELECT * FROM tag WHERE id = ?",
            verb="read", tables=("tag",), cardinality="one")


def test_shape_matching_validates_registry_identifiers():
    # a real helper-shaped INSERT matches...
    assert statements.lookup_sql(
        "INSERT INTO tag (pub_id, name) VALUES (?, ?)"
    ).name == "bench.tag_insert"  # exact beats shape
    assert statements.lookup_sql(
        "INSERT INTO tag (pub_id, name, color) VALUES (?, ?, ?)"
    ).name == "store.helper.insert"
    # ...but an off-registry table does NOT (the `{i}` slot check)
    assert statements.lookup_sql(
        "INSERT INTO warp_core (pub_id) VALUES (?)") is None
    assert statements.lookup_sql(
        "UPDATE tag SET name = ? WHERE id = ?"
    ).name == "store.helper.update"
    assert statements.lookup_sql(
        "UPDATE warp_core SET name = ? WHERE id = ?") is None


def test_sql_table_renders_every_statement():
    md = statements.sql_table_markdown()
    for st in statements.all_statements():
        assert f"`{st.name}`" in md
    assert "| read |" in md and "| write |" in md


# -- armed behavior ----------------------------------------------------------
# conftest installs the sanitizer in raise mode, so the auditor is
# armed for every Database this suite constructs.

def test_declared_statements_flow_and_count(db):
    tid = db.insert("tag", {"pub_id": b"t" * 16, "name": "x"})
    before = _metric("sd_sql_statements_total", "api.tag.by_id")
    row = db.run("api.tag.by_id", (tid,))
    assert row["name"] == "x"
    assert _metric("sd_sql_statements_total", "api.tag.by_id") == \
        before + 1
    assert _metric("sd_sql_rows_total", "api.tag.by_id") >= 1


def test_run_cardinalities(db):
    db.insert("tag", {"pub_id": b"u" * 16, "name": "y"})
    assert db.run("store.init.instance_count") == 0  # scalar
    rows = db.run("api.tag.all")                     # many
    assert isinstance(rows, list) and len(rows) == 1
    assert db.run("api.tag.by_id", (999,)) is None   # one


def test_undeclared_statement_raises(db):
    with pytest.raises(sanitize.SanitizerViolation,
                       match="sql_undeclared"):
        db._conn().execute("SELECT 1 FROM tag WHERE rowid > 3")
    sanitize.reset_violations()


def test_adhoc_allowance_covers_reads_not_writes(db):
    # db.query IS the ad-hoc diagnostic surface
    assert db.query("SELECT name FROM tag") == []
    assert _metric("sd_sql_statements_total", "_adhoc") >= 1
    # the allowance never excuses a write
    with pytest.raises(sanitize.SanitizerViolation,
                       match="sql_undeclared"):
        with sqlaudit.adhoc():
            db._conn().execute(
                "UPDATE tag SET color = 'x' WHERE name = 'nope'")
    sanitize.reset_violations()


def test_autocommit_write_raises(db):
    tid = db.insert("tag", {"pub_id": b"v" * 16, "name": "z"})
    with pytest.raises(sanitize.SanitizerViolation,
                       match="sql_autocommit_write"):
        db._conn().execute(statements.get("node.object_delete").sql,
                           (tid,))
    sanitize.reset_violations()
    # the same statement inside tx() is the sanctioned path
    with db.tx() as conn:
        db.run("api.tag.clear_assignments", (tid,), conn=conn)


def test_write_without_conn_refused(db):
    with pytest.raises(statements.SqlContractError,
                       match="tx_required|pass conn"):
        db.run("node.object_delete", (1,))
    with pytest.raises(statements.SqlContractError):
        db.run_many("identifier.link_paths", [("c", 1, 1)])
    # run_tx is the single-statement sugar
    db.run_tx("api.notification.dismiss_all")


def test_tx_statement_histogram_observes(db):
    before = snapshot().get("sd_sql_tx_statements", {}).get(
        "count", 0)
    with db.tx() as conn:
        for i in range(5):
            db.insert("tag", {"pub_id": bytes([i]) * 16,
                              "name": f"t{i}"}, conn=conn)
    fam = snapshot()["sd_sql_tx_statements"]
    assert fam["count"] == before + 1
    # 5 inserts counted into the committed tx's bucket


def test_explain_sampling_counts_scans(tmp_path, monkeypatch):
    monkeypatch.setenv("SDTPU_SQL_EXPLAIN", "1")
    sqlaudit.disarm()
    sqlaudit.arm("raise", sanitize.record)
    try:
        d = Database(str(tmp_path / "scan.db"))
        before = _metric("sd_sql_scan_total", "bench.file_count")
        # is_dir filter over file_path has no index — EXPLAIN flags it
        d.run("bench.file_count")
        assert _metric("sd_sql_scan_total", "bench.file_count") == \
            before + 1
        # an indexed probe is NOT a scan
        before_ok = _metric("sd_sql_scan_total", "api.file_path.by_id")
        d.run("api.file_path.by_id", (1,))
        assert _metric("sd_sql_scan_total",
                       "api.file_path.by_id") == before_ok
        d.close()
    finally:
        monkeypatch.setenv("SDTPU_SQL_EXPLAIN", "0")
        sqlaudit.disarm()
        sqlaudit.arm("raise", sanitize.record)


def test_executed_names_feeds_drift_surface(db):
    db.run("store.object_count")
    assert sqlaudit.executed_names().get("store.object_count", 0) >= 1


# -- satellite regressions ---------------------------------------------------

def test_reads_do_not_take_the_write_lock(db):
    """The Database.execute split: a writer holding the write lock in
    a long transaction must NOT block run()'s read path (the old
    wrapper serialized every read behind BEGIN IMMEDIATE)."""
    db.insert("tag", {"pub_id": b"w" * 16, "name": "held"})
    in_tx = threading.Event()
    release = threading.Event()

    def long_writer():
        with db.tx() as conn:
            db.insert("tag", {"pub_id": b"x" * 16, "name": "w2"},
                      conn=conn)
            in_tx.set()
            release.wait(timeout=10)

    t = threading.Thread(target=long_writer)
    t.start()
    try:
        assert in_tx.wait(timeout=10)
        t0 = time.perf_counter()
        rows = db.run("api.tag.all")
        dt = time.perf_counter() - t0
        assert any(r["name"] == "held" for r in rows)
        # a read behind the old write-wrapping execute would block
        # until `release` — bound it well under the writer's hold
        assert dt < 2.0, f"read serialized behind the write lock ({dt:.2f}s)"
    finally:
        release.set()
        t.join(timeout=10)


def test_lazy_index_drop_failure_is_counted(tmp_path, monkeypatch):
    """Satellite: the init-time lazy-index drop must not swallow
    errors silently — it logs at debug and counts into
    sd_store_init_warnings_total."""
    from spacedrive_tpu.store import db as db_mod

    before = _metric("sd_store_init_warnings_total")
    real_get = statements.get

    class _Boom:
        # DDL head passes the auditor untouched; sqlite rejects the
        # missing table — exactly the corrupt-library error class
        sql = "CREATE INDEX idx_boom ON no_such_table_anywhere (x)"

    def fake_get(name):
        if name == "store.init.instance_count":
            return _Boom
        return real_get(name)

    monkeypatch.setattr(db_mod.statements, "get", fake_get)
    d = Database(str(tmp_path / "warn.db"))  # probe fails, open survives
    d.close()
    assert _metric("sd_store_init_warnings_total") == before + 1
