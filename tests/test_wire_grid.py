"""The malformed-frame grid, as a tier-1 gate.

tools/wire_grid.py feeds EVERY declared wire message every applicable
malformed shape (drop-required, truncate, type-flip, unknown-kind,
oversize, version-skew) through both entry points — `wire.unpack` and
the armed tunnel-seam auditor — and asserts reject-without-crash per
cell. Systematic, not sampled: a new `declare_message` is covered the
moment it lands, with zero new test code. Subprocess shape follows
test_crash_grid.py."""

import json
import os
import subprocess
import sys

from spacedrive_tpu.p2p import wire

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
GRID = os.path.join(ROOT, "tools", "wire_grid.py")


def _child_env():
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "SDTPU_SANITIZE": "1",
                "SDTPU_SANITIZE_MODE": "raise"})
    return env


def test_full_grid_passes():
    """Every declared message rejects every malformed shape without
    crashing, at both seams — the acceptance gate itself."""
    proc = subprocess.run(
        [sys.executable, GRID, "--json", "-"],
        cwd=ROOT, env=_child_env(), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["pass"] is True
    assert doc["failures"] == []
    # every declared message gets a row the moment it is declared
    assert doc["messages"] == sorted(wire.MESSAGES)
    by_message = {}
    for r in doc["rounds"]:
        by_message.setdefault(r["message"], set()).add(r["mutation"])
    for name, msg in wire.MESSAGES.items():
        muts = by_message[name]
        # universal cells: a clean control and an oversize mutant
        assert {"control", "oversize"} <= muts, (name, muts)
        if msg.values is not None:
            assert {"truncate", "type-flip", "unknown-kind"} <= muts
        elif msg.binary:
            assert "type-flip" in muts
        else:
            assert "drop-required" in muts, (name, muts)
        if any(f.is_proto for f in msg.fields):
            assert "version-skew" in muts, (name, muts)
    # the grid really went through the auditor: mutants record
    # violations on the same census production dashboards read
    violated = [r for r in doc["rounds"]
                if r["mutation"] != "control" and r["violations"]]
    assert len(violated) >= doc["mutations"] - len(doc["unaudited"])
