"""sdlint fixture — jit-stability KNOWN POSITIVES."""

import functools

import jax
import numpy as np

from spacedrive_tpu.ops import jit_registry


@jax.jit
def unregistered(x):
    # jit entry point with no tracked(...) binding
    return x + 1


@jit_registry.tracked("no.such.contract")
@jax.jit
def unknown_name(x):
    # tracked, but the contract does not exist in the registry
    return x * 2


@jit_registry.tracked("hamming.near_mask")
@functools.partial(jax.jit, static_argnames=("tile",))
def drifted_static(x, y, tile: int = 4):
    # declared static_argnames is ("threshold",) — site drifted
    return x[:tile] ^ y[:tile]


@jit_registry.tracked("hamming.tile")
@functools.partial(jax.jit, static_argnums=(1,))
def positional_static(x, n):
    # static_argnums instead of static_argnames
    return x * n


def call_time(fn, words, lengths):
    # the overlap.py:166 shape: a fresh jit wrapper per invocation
    jfn = jax.jit(fn)
    return jfn(words, lengths)


def jit_per_batch(fn, batches):
    out = []
    for batch in batches:
        jfn = jax.jit(fn)  # strictly worse: one compile per iteration
        out.append(jfn(batch))
    return out


@jit_registry.tracked("hamming.near_mask")
@functools.partial(jax.jit, static_argnames=("threshold",))
def mask(x, y, threshold: int = 2):
    # correctly bound — the bad call sites below abuse it
    return (x ^ y) <= threshold


@jit_registry.tracked("hamming.tile")
@functools.partial(jax.jit, donate_argnums=(0,))
def donates_undeclared(x, y):
    # hamming.tile's contract declares no donate_argnums: consuming the
    # caller's x is an undeclared semantic change
    return x ^ y


def unhashable_static(x, y):
    return mask(x, y, threshold=[1, 2])


def raw_len_shape(xs, d):
    # Python-value-dependent shape built at the jit boundary
    return mask(np.zeros((len(xs), 2), dtype=np.uint32), d, threshold=2)
