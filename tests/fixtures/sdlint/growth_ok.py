# sdlint-scope: growth
"""unbounded-growth known-NEGATIVES: eviction paths, bounded deques,
registry channels/caches, fixed-slot state, and short-lived classes."""

from collections import deque

from spacedrive_tpu import channels

_STATE = [0, 0]                 # fixed-slot list: writes, not growth


def bump(ms):
    _STATE[0] = ms


class BoundedActor:
    def __init__(self):
        self.recent = deque(maxlen=16)
        self.pending = {}
        self.inbox = channels.channel("sync.ingest.events")
        self.routes = channels.bounded_dict("p2p.route_cache")

    async def run(self):
        while True:
            self.pending[1] = 2
            self.pending.pop(1, None)
            self.recent.append(1)
            self.routes[b"k"] = ("addr", 1)


class Unsubscribable:
    """The eviction path may live in a nested closure (unsubscribe)."""

    def start(self):
        pass

    def stop(self):
        pass

    def __init__(self):
        self.subs = []

    def subscribe(self, cb):
        self.subs.append(cb)
        return lambda: self.subs.remove(cb)


class ShortLived:
    """No while-True/spawn/start+stop: request-scoped accumulation
    is bounded by the request's lifetime."""

    def __init__(self):
        self.items = []

    def add(self, x):
        self.items.append(x)
