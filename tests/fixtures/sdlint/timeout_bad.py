# sdlint-scope: net
"""timeout-discipline known-POSITIVES (scope opted in above)."""

import asyncio

from spacedrive_tpu.timeouts import with_timeout


async def pull(tunnel):
    req = await tunnel.recv()            # no-timeout
    await tunnel.send({"ok": True})      # no-timeout
    return req


async def raw_read(reader):
    return await reader.readexactly(4)   # no-timeout


async def literal_budget(tunnel):
    # unnamed-timeout: the budget must come from the registry.
    return await asyncio.wait_for(tunnel.recv(), 5.0)


async def unknown_name(tunnel):
    # undeclared-timeout: not in timeouts.py.
    return await with_timeout("not.a.real.budget", tunnel.recv())


async def computed_name(tunnel, which):
    # dynamic-timeout-name: the table must stay static.
    return await with_timeout(which, tunnel.recv())
