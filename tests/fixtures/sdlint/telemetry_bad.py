"""sdlint fixture — telemetry-pass KNOWN POSITIVE: a metric family
registered outside the central registry."""

from spacedrive_tpu.telemetry import counter

ROGUE = counter("sd_rogue_things_total",
                "registered outside spacedrive_tpu/telemetry.py")
