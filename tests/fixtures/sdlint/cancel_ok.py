"""cancellation-safety known-NEGATIVES."""

import asyncio


async def reap_idiom(task):
    # lone CancelledError after an explicit cancel: legitimate.
    task.cancel()
    try:
        await task
    except asyncio.CancelledError:
        pass


async def reraise_base(conn):
    # BaseException with a re-raise (store/db.py's rollback shape).
    try:
        await conn.run()
    except BaseException:
        await_nothing = None  # noqa: F841
        raise
    finally:
        await asyncio.shield(conn.aclose())  # shielded cleanup: fine


async def narrow_handler(q):
    # except Exception does NOT catch CancelledError (py3.8+): fine.
    try:
        await q.get()
    except Exception:
        return None


async def bounded_loop(q):
    while True:  # has a cancellation point AND an exit
        item = await q.get()
        if item is None:
            break


def observing_callback(task, mgr):
    # the task parameter is used: outcome reaches the handler.
    task.add_done_callback(lambda t: mgr.on_done(t))
