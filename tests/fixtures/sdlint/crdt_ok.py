"""sdlint fixture — crdt-parity KNOWN NEGATIVES (all clean)."""


def tag_create_synced(db, sync, values, pub_id):
    ops = sync.shared_create("tag", pub_id, values)
    with sync.write_ops(ops) as conn:
        db.insert("tag", {"pub_id": pub_id, **values}, conn=conn)


def bulk_synced(db, sync, conn, specs, rows):
    db.insert_many("file_path", rows, conn=conn)
    sync.bulk_shared_ops(conn, "file_path", specs)


def local_table_write(db):
    # volume is a LOCAL model — never synced, no ops required
    with db.tx() as conn:
        conn.execute("INSERT INTO volume (name) VALUES (?)", ("v",))
