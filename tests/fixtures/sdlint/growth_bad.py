# sdlint-scope: growth
"""unbounded-growth known-POSITIVES."""

SEEN_GLOBAL: dict = {}      # module-level grow-only


def remember(key):
    SEEN_GLOBAL[key] = True


class LeakyActor:
    """Long-lived (actor loop) with grow-only instance collections."""

    def __init__(self):
        self.seen = {}          # grow-only (subscript writes)
        self.log = []           # grow-only (append)

    async def run(self):
        while True:
            self.seen[object()] = 1
            self.log.append(1)
