"""sdlint fixture — host-transfer KNOWN POSITIVES."""

import jax
import numpy as np

from spacedrive_tpu.ops import jit_registry


@jax.jit
def kernel(x):
    return x + 1


def undeclared_fetch(x):
    out = kernel(x)
    return np.asarray(out)             # stray D2H, no io(...) scope


def implicit_sync(x):
    r = kernel(x)
    if r:                              # hidden __bool__ → full D2H sync
        return float(r)                # hidden __float__ → D2H sync
    return 0.0


def blocking_idioms(x):
    out = kernel(x)
    out.block_until_ready()            # undeclared sync
    first = kernel(x)[0].item()        # undeclared .item() fetch
    return jax.device_get(out), first  # undeclared explicit fetch


def rogue_io_scope(x):
    out = kernel(x)
    with jit_registry.io("not.a.contract"):  # name never declared
        return np.asarray(out)
