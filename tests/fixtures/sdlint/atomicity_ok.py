# sdlint-scope: persist
"""crash-atomicity known-NEGATIVES."""

import json
import threading

from spacedrive_tpu import persist

_lock = threading.Lock()


def single_commit(path, doc):
    persist.atomic_write("node.config", path, json.dumps(doc))


def same_artifact_twice(old_path, new_path, doc):
    # one NAME = one recovery story; two paths of it are fine
    persist.atomic_write("library.config", old_path, doc)
    persist.atomic_write("library.config", new_path, doc)


def guarded_bump(path):
    with _lock:
        with open(path) as f:
            doc = json.load(f)
        doc["generation"] = doc.get("generation", 0) + 1
        persist.atomic_write("crypto.keyring", path, json.dumps(doc))
