"""cancellation-safety known-POSITIVES."""

import asyncio


async def swallow_bare(q):
    try:
        await q.get()
    except:  # noqa: E722 — swallow-cancel (bare)
        pass


async def swallow_base(q):
    try:
        await q.get()
    except BaseException:  # swallow-cancel (no re-raise)
        return None


async def conflated_reap(task):
    # the pre-PR mdns/discovery stop() shape: CancelledError lumped
    # with Exception in one silencing handler.
    task.cancel()
    try:
        await task
    except (asyncio.CancelledError, Exception):  # swallow-cancel
        pass


async def unshielded_cleanup(conn):
    try:
        await conn.run()
    finally:
        await conn.aclose()  # await-in-finally


async def spin(counter):
    while True:  # no-cancel-point: no await, no break
        counter += 1


def drops_outcome(task, pending):
    # container-method callback: the exception is never retrieved.
    task.add_done_callback(pending.discard)
    # lambda that ignores its task argument: same black hole.
    task.add_done_callback(lambda t: print("done"))
