"""sdlint fixture — blocking-async KNOWN POSITIVES.

Not imported by anything; tests/test_sdlint.py lints this file and
asserts each shape below is flagged.
"""

import time


async def direct_sqlite(db):
    # sqlite on the event loop
    return db.query("SELECT 1")


async def direct_sleep():
    time.sleep(0.1)  # time.sleep on the event loop


def helper(store):
    return store.db.query_one("SELECT 1")


async def reaches_through_helper(store):
    # interprocedural: helper() blocks, and this call is not wrapped
    # (the argument is not itself a db handle, so only the call-graph
    # walk can see the violation)
    return helper(store)


async def passes_db_handle(report, library):
    # passing a live Database into a writer helper
    report.update(library.db)
