"""sdlint fixture — jit-stability KNOWN NEGATIVES (all clean)."""

import functools

import jax
import numpy as np

from spacedrive_tpu.ops import jit_registry


@jit_registry.tracked("hamming.tile")
@jax.jit
def bound_tile(x, y):
    return x ^ y


@jit_registry.tracked("hamming.near_mask")
@functools.partial(jax.jit, static_argnames=("threshold",))
def bound_mask(x, y, threshold: int):
    return (x ^ y) <= threshold


def _body(words, lengths):
    return words[:, 0] + lengths


bound_assign = jit_registry.tracked("blake3.jnp")(jax.jit(_body))


def _donating_body(words, lengths):
    return words[:, 0] + lengths, words, lengths


# donation matching the contract's declared donate_argnums is clean
bound_donated = jit_registry.tracked("blake3.donated")(
    jax.jit(_donating_body, donate_argnums=(0, 1)))


def caller(d):
    pre = np.zeros((8, 2), dtype=np.uint32)  # bucketed, not len()-shaped
    mask = bound_mask(pre, d, threshold=6)   # hashable static arg
    return bound_tile(mask, mask)
