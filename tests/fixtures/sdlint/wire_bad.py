# sdlint-scope: wire
"""wire-discipline known-POSITIVES.

Every way a frame can dodge the wire registry
(spacedrive_tpu/p2p/wire.py): hand-built discriminator dicts, dynamic
and undeclared pack names, bare verdict literals at a send, and a
declaration the static side cannot see.
"""

from spacedrive_tpu.p2p import wire

KIND = "fx." + "computed"

# computed-declaration: invisible to every static consumer
wire.declare_message(KIND, "p2p", "both", {"t": "=fx"},
                     size_cap=4096, timeout_budget="p2p.ping")


def hand_built_frame():
    # raw-kind-literal: pack() fills discriminators itself
    return {"t": "ping", "tp": None}


async def dynamic_name(tunnel, kind):
    # dynamic-kind: the inventory/grid/drift checks must see the name
    await tunnel.send(wire.pack(kind))


def undeclared_name(raw):
    # undeclared-kind: no such declaration
    return wire.unpack("fx.no.such.message", raw)


def undeclared_group():
    # undeclared-kind: no such proto group in PROTO_VERSIONS
    return wire.proto("fxgroup")


async def bare_verdict(tunnel):
    # raw-value-literal: 'ok' is spaceblock.verdict's declared value —
    # sending it raw bypasses the values contract
    await tunnel.send("ok")
