"""sdlint fixture — blocking-async KNOWN NEGATIVES (all clean)."""

import asyncio


def helper(db):
    return db.query_one("SELECT 1")


async def wrapped_everywhere(db):
    rows = await asyncio.to_thread(db.query, "SELECT 1")
    one = await asyncio.to_thread(helper, db)
    await asyncio.sleep(0.01)  # asyncio.sleep is awaited → fine
    return rows, one


async def sync_callback_not_executed(db):
    # a nested def is only DEFINED here; its body runs on a worker
    def work():
        return db.query("SELECT 1")

    return await asyncio.to_thread(work)
