"""sdlint fixture — dtype-discipline KNOWN NEGATIVES (all clean)."""

import jax.numpy as jnp
import numpy as np


def explicit_creations(words):
    idx = jnp.arange(8, dtype=jnp.int32)
    pad = jnp.zeros((4,), jnp.uint32)
    carry = jnp.zeros_like(words)            # dtype-preserving
    arr = jnp.asarray(words)                 # dtype-preserving
    return idx, pad, carry, arr


def same_sign_arith():
    lo = jnp.uint32(1)
    hi = jnp.uint32(2)
    counter = lo + hi                        # uint32 + uint32
    steps = jnp.arange(4, dtype=jnp.int32)
    return counter, steps - jnp.int32(1)     # int32 - int32


def explicit_casts(x):
    as_words = x.astype(jnp.uint32)
    host = np.asarray([1, 2], dtype=np.uint32)
    return as_words, host
