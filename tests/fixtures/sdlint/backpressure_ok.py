"""backpressure known-NEGATIVES: budgeted puts, shed policies, and
windowed bursts with a drain point."""

from spacedrive_tpu import channels
from spacedrive_tpu.timeouts import with_timeout


class Producer:
    def __init__(self):
        self.requests = channels.channel("sync.ingest.requests")
        self.events = channels.channel("sync.ingest.events")

    async def push(self, item):
        # block policy: put() waits under the contract's declared
        # sync.ingest.backlog budget — the sanctioned shape.
        await self.requests.put(item)

    def poke(self):
        # coalesce policy: put_nowait never blocks, overflow sheds.
        self.events.put_nowait(("notification", None),
                               key="notification")


async def windowed_burst(tunnel, pages):
    inflight = 0
    for page in pages:
        tunnel.send_nowait(page)
        inflight += 1
        if inflight >= 4:
            # the drain point that closes the window
            await with_timeout("sync.clone.drain", tunnel.drain())
            inflight = 0
    await with_timeout("sync.clone.drain", tunnel.drain())


def fan_out_calls(subs, event):
    # calling subscribers is fine — the rule is about unbounded
    # per-subscriber BUFFER writes.
    for sub in subs:
        sub(event)
