"""sdlint fixture — flag-registry KNOWN POSITIVES."""

import os


def read_undeclared():
    # typo'd / never-declared flag: silently returns None at runtime
    return os.environ.get("SDTPU_NOT_A_REAL_FLAG")


def read_outside_registry():
    # declared flag, but read around the registry
    return os.environ.get("SDTPU_TELEMETRY", "on")


def subscript_read_outside_registry():
    return os.environ["SDTPU_PROFILE"]
