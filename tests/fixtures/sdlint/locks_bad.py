"""sdlint fixture — lock-discipline KNOWN POSITIVES.

`Pr1Database` preserves the shape `store/db.py` had BEFORE PR 1's fix,
the deadlock that motivated this pass: connection REGISTRATION
serialized on the WRITE lock (`_conn`), while a writer holds that same
lock across a cross-thread wait on reader futures (`commit_group`).
A reader thread opening its first connection blocks on `_write_lock`;
the writer never releases it because it is waiting on that reader.
The pass must flag the `fut.result()` under `_write_lock`
(wait-under-lock) — the encoded regression test for the PR 1 bug.
"""

import threading


class Pr1Database:
    def __init__(self):
        self._write_lock = threading.RLock()
        self._all_conns = []

    def _conn(self):
        with self._write_lock:  # registration under the WRITE lock
            conn = object()
            self._all_conns.append(conn)
            return conn

    def commit_group(self, prefetch_futures):
        with self._write_lock:
            for fut in prefetch_futures:
                rows = fut.result()  # waits on readers that need _conn()
                self._write(rows)

    def _write(self, rows):
        pass


a_lock = threading.Lock()
b_lock = threading.Lock()


def take_ab():
    with a_lock:
        with b_lock:
            pass


def take_ba():  # opposite order → AB/BA cycle
    with b_lock:
        with a_lock:
            pass


async def suspended_critical_section(db):
    with db._write_lock:
        await asyncio_notify()  # coroutine parks while holding the lock


async def asyncio_notify():
    pass


def nested_transaction(db, rows):
    with db.tx() as conn:
        db.insert("job", {"id": 1})  # opens a SECOND tx inside the first
