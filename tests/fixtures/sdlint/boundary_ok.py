"""Known-NEGATIVE fixture for the thread-boundary pass: the sanctioned
shapes — loop-side channel use, the hardened call_threadsafe hand-off
from worker code, and channel methods in ambient sync drivers."""

import asyncio

from spacedrive_tpu import channels, tasks, threadctx


async def _noop() -> None:
    pass


class Pump:
    def __init__(self, events):
        self.inbox = channels.channel("media.thumbs")
        self.events = events

    def worker_offer(self, loop, item) -> None:
        # The sanctioned hand-off: post the loop-affine work through
        # the hardened helper; the callback runs ON the loop, and a
        # loop closed mid-shutdown is counted, not crashed into.
        threadctx.call_threadsafe(loop, self.inbox.put_nowait, item)

    async def on_loop(self, item) -> None:
        # Loop context: channel methods and spawns are home here.
        self.inbox.put_nowait(item)
        await self.inbox.put(item)
        self.events.emit({"type": "x"})
        tasks.spawn("fanout", _noop(), owner="fixture")

    async def run(self, pool) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(pool, self.worker_offer, loop, 1)


def sync_driver() -> None:
    # Ambient single-threaded construction path (the jobs run-queue
    # shape): no worker context, so the sync surface is fine.
    q = channels.channel("media.thumbs")
    q.put_nowait(1)
