# sdlint-scope: wire
"""schema-drift known-NEGATIVES: field traffic inside the contract."""

from spacedrive_tpu.p2p import wire


def full_pack():
    return wire.pack("p2p.pair.request", library_id="x",
                     library_name="y", listen_port=7373, instance={})


def optional_omitted():
    # optional fields ('?') and consts are pack()'s to fill
    return wire.pack("sync.pull.request", clocks=[], count=100)


def splat_pack(fields):
    # **kwargs packs are statically unknowable — the runtime check
    # owns them
    return wire.pack("p2p.pair.request", **fields)


def declared_reads(raw):
    page = wire.unpack("sync.pull.page", raw)
    return page.get("ops"), page["has_more"]


def reassigned_var(raw, store):
    # once the name stops holding the unpacked frame, its reads are
    # the new value's business, not the schema's
    page = wire.unpack("sync.pull.page", raw)
    ops = page.get("ops")
    page = store.lookup(ops)
    return page["anything_at_all"]
