"""queue-discipline known-POSITIVES."""

import asyncio
from asyncio import Queue
from collections import deque

from spacedrive_tpu import channels


class Actor:
    def __init__(self):
        self.inbox = asyncio.Queue()        # bare-queue
        self.backlog = deque()              # unbounded-deque-channel
        self.spare = Queue()                # bare-queue (from-import)

    def produce(self, item):
        self.inbox.put_nowait(item)         # unregistered-put
        self.backlog.append(item)

    async def consume(self):
        self.backlog.popleft()
        return await self.inbox.get()


class Sender:
    def send_nowait(self, msg):             # unregistered-send-buffer
        self._buf.append(msg)


def local_channel():
    q = asyncio.Queue()                     # bare-queue
    q.put_nowait(1)                         # unregistered-put (local)
    return q


def undeclared():
    return channels.channel("not.a.real.channel")   # undeclared-channel


def dynamic(name):
    return channels.channel(name)           # dynamic-channel-name
