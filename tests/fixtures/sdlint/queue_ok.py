"""queue-discipline known-NEGATIVES: registry channels, bounded
deques, and plain work lists are all sanctioned."""

from collections import deque

from spacedrive_tpu import channels


class Actor:
    def __init__(self):
        self.inbox = channels.channel("sync.ingest.events")
        self.recent = deque(maxlen=64)      # bounded: not a channel

    def produce(self, item):
        self.inbox.put_nowait(item)         # registered

    async def consume(self):
        return await self.inbox.get()


class Tunnelish:
    def __init__(self):
        self._frames = channels.window("p2p.tunnel.frames")

    def send_nowait(self, msg):
        self._frames.note_put()


class Cache:
    def __init__(self):
        self.routes = channels.bounded_dict("p2p.route_cache")


def scratch():
    # function-local deque: a work list, not a cross-task channel
    work = deque()
    work.append(1)
    return work.popleft()
