"""sdlint fixture — dtype-discipline KNOWN POSITIVES."""

import jax.numpy as jnp


def x64_dependent_creations(n):
    a = jnp.arange(8)            # implicit dtype: int32 or int64 by flag
    b = jnp.zeros((4,))          # implicit dtype
    c = jnp.asarray(123)         # dtype chosen by VALUE under x64
    return a, b, c, n


def builtin_casts(x):
    lanes = jnp.zeros((4,), int)     # Python-builtin dtype
    return x.astype(int) + lanes     # .astype(int) width follows x64


def mixed_direct():
    idx = jnp.arange(8, dtype=jnp.int32)
    mask = jnp.uint32(7)
    return idx & mask            # int32/uint32 in one op


def _wrap_mask():
    return jnp.uint32(0xFFFF)


def mixed_via_helper():
    base = jnp.arange(4, dtype=jnp.int32)
    return base + _wrap_mask()   # interprocedural int32/uint32 mix
