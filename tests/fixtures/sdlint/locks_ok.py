"""sdlint fixture — lock-discipline KNOWN NEGATIVES (all clean).

`FixedDatabase` is the post-PR 1 shape: registration has its own leaf
lock, and commit groups drain their futures BEFORE taking the write
lock. Lock order is consistent everywhere (write → conns).
"""

import threading


class FixedDatabase:
    def __init__(self):
        self._write_lock = threading.RLock()
        self._conns_lock = threading.Lock()
        self._all_conns = []

    def _conn(self):
        with self._conns_lock:  # leaf lock, never the write lock
            conn = object()
            self._all_conns.append(conn)
            return conn

    def commit_group(self, prefetch_futures):
        batches = [fut.result() for fut in prefetch_futures]  # lock-free
        with self._write_lock:
            for rows in batches:
                self._write(rows)

    def teardown(self):
        with self._write_lock:
            with self._conns_lock:  # same order as everywhere else
                self._all_conns.clear()

    def _write(self, rows):
        pass


def tx_with_passed_conn(db, sync, rows, ops):
    with sync.write_ops(ops) as conn:
        db.insert("job", {"id": 1}, conn=conn)  # reuses the open tx


async def lock_released_before_await(db):
    with db._write_lock:
        value = 1
    await asyncio_notify()
    return value


async def asyncio_notify():
    pass
