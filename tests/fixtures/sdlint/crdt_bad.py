"""sdlint fixture — crdt-parity KNOWN POSITIVES.

Both functions write SHARED model tables (tag, object — real names
from store/models.py) inside a plain tx with no op emission in scope:
the silent-divergence bug the pass exists to catch.
"""

import time


def tag_create_silent(db, values):
    with db.tx() as conn:
        conn.execute(
            "INSERT INTO tag (pub_id, name) VALUES (?, ?)",
            (values["pub_id"], values["name"]))


def object_update_silent(db, oid):
    with db.tx() as conn:
        conn.execute(
            "UPDATE object SET date_accessed = ? WHERE id = ?",
            (int(time.time()), oid))
