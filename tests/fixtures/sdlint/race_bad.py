"""Known-POSITIVE fixture for the shared-mutation pass.

Every contract kind broken once, plus the two registry-enforcement
codes. The `h2d_bytes` bare `+=` from a run_in_executor target is the
encoded PR 8 PipelineStats lost-update shape — the pass must keep
catching it. The fixture self-declares its contracts (declare_owner is
parsed from project files as well as the central registry, exactly so
fixtures can do this)."""

import asyncio
import threading

from spacedrive_tpu.threadctx import (
    atomic_counter,
    declare_owner,
    guarded_by,
    immutable_after_init,
    loop_only,
    single_thread,
)

declare_owner(
    "fixture.RaceStats",
    "tests/fixtures/sdlint/race_bad.py::RaceStats",
    {
        "h2d_bytes": guarded_by("_lock"),
        "events": loop_only(),
        "wall_s": single_thread(),
        "ticks": atomic_counter(),
        "shape": immutable_after_init(),
    })


class RaceStats:
    def __init__(self):
        self._lock = threading.Lock()
        self.h2d_bytes = 0
        self.events = []
        self.wall_s = 0.0
        self.ticks = 0
        self.shape = (0, 0)


def _transfer(stats: RaceStats) -> None:
    # BAD unguarded-write: the PipelineStats shape — a per-device
    # executor stream bumping a guarded counter with no lock held.
    stats.h2d_bytes += 57344


def _report(stats: RaceStats) -> None:
    stats.events.append("done")   # BAD wrong-context-write (loop_only)
    stats.shape = (2, 2)          # BAD post-init-write (immutable)
    stats.ticks = 0               # BAD non-atomic-write (rebind)


def _finish(stats: RaceStats) -> None:
    stats.wall_s = 2.0            # BAD multi-thread-write (with drive)
    stats.extra = 1               # BAD undeclared-attr


async def drive(stats: RaceStats, pool) -> None:
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(pool, _transfer, stats)
    await asyncio.to_thread(_report, stats)
    await asyncio.to_thread(_finish, stats)
    stats.wall_s = 1.0            # loop-side half of the wall_s pair


class BareShared:
    """No declare_owner, mutated from loop AND worker contexts —
    the undeclared-class code."""

    def __init__(self):
        self.seen = {}

    def record(self, k) -> None:
        self.seen[k] = True


def _pump(b: BareShared) -> None:
    b.record("z")


async def uses(b: BareShared) -> None:
    b.record("x")
    await asyncio.to_thread(_pump, b)
