# sdlint-scope: persist
"""io-durability known-POSITIVES (scope opted in above)."""

import json
import os

from spacedrive_tpu import persist


def bare_config_save(path, doc):
    with open(path, "w") as f:          # bare-write
        json.dump(doc, f)


def promote_by_rename(src, dst):
    os.rename(src, dst)                 # rename-no-tmp (no tmp token)


def replace_without_flush(doc_tmp, doc):
    os.replace(doc_tmp, doc)            # replace-no-fsync (tmp ok)


def writes_unknown_artifact(path):
    persist.atomic_write("nope.not_declared", path, b"x")


def writes_computed_name(which, path):
    persist.atomic_write(f"cfg.{which}", path, b"x")  # artifact-dynamic
