"""Known-POSITIVE fixture for the guard-consistency pass: attributes
protected at one site and bare (or under a different lock) at another
— the RacerD inconsistent-lock-protection smell."""

import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self.entries = {}
        self.hits = 0

    def put(self, k, v) -> None:
        with self._lock:
            self.entries[k] = v
            self.hits += 1

    def evict(self, k) -> None:
        if k in self.entries:
            del self.entries[k]   # BAD: bare vs the guarded put

    def reset(self) -> None:
        self.hits = 0             # BAD: bare vs the guarded increment


class TwoLocks:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self.state = []

    def one(self) -> None:
        with self._a_lock:
            self.state.append(1)

    def two(self) -> None:
        with self._b_lock:
            self.state.append(2)  # BAD: disjoint lock from one()
