"""sdlint fixture — tx-shape KNOWN NEGATIVES.

One tx around the loop with per-row statements riding it, run_many
batching, blocking work hoisted BEFORE the tx, and a helper that
rides the caller's connection instead of opening its own.
"""


def one_tx_around_loop(db, items):
    with db.tx() as conn:
        for item in items:
            db.run("node.object_delete", (item,), conn=conn)


def batched(db, rows):
    with db.tx() as conn:
        db.run_many("identifier.link_paths", rows, conn=conn)


def helper_rides_conn(db, conn, row):
    db.insert("tag", row, conn=conn)


def helpers_in_loop_on_one_tx(db, rows):
    with db.tx() as conn:
        for row in rows:
            db.insert("tag", row, conn=conn)


def blocking_before_tx(db, path):
    data = open(path).read()
    with db.tx() as conn:
        db.run("node.object_delete", (len(data),), conn=conn)


async def await_outside_tx(db, fetch):
    row = await fetch()
    with db.tx() as conn:
        db.run("node.object_delete", (row,), conn=conn)
