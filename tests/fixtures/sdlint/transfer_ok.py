"""sdlint fixture — host-transfer KNOWN NEGATIVES (all clean)."""

import asyncio

import jax
import numpy as np

from spacedrive_tpu.ops import jit_registry


@jax.jit
def kernel(x):
    return x + 1


def declared_fetch(x):
    out = kernel(x)
    with jit_registry.io("cas.ids"):   # declared host_transfer contract
        return np.asarray(out)


def input_prep(rows):
    # np.asarray feeding the jit boundary is H2D staging, not a fetch
    return kernel(np.asarray(rows, dtype=np.uint32))


async def offloaded(x):
    out = kernel(x)
    return await asyncio.to_thread(np.asarray, out)


def host_only(rows):
    # no jit call in sight: numpy conversions here are host work
    return np.asarray(rows).sum()
