# sdlint-scope: persist
"""io-durability known-NEGATIVES: the blessed write shapes."""

import json
import os

from spacedrive_tpu import persist


def declared_save(path, doc):
    persist.atomic_write("library.config", path, json.dumps(doc))


def sealed_stream(part_path, target):
    persist.seal("object.sealed", part_path, target)


def read_only(path):
    with open(path, "rb") as f:
        return f.read()


def flushed_replace(doc_tmp, doc, fd):
    os.fsync(fd)
    os.replace(doc_tmp, doc)            # fsync present, tmp source
