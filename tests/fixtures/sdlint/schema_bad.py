"""sdlint fixture — schema-parity KNOWN POSITIVES.

Declarations whose SQL has drifted from store/models.py: an unknown
table, an unknown column on a real table (bare and alias-qualified),
a tables= set disagreeing with the SQL, and an unindexed filter over
a registered large table. The declare calls carry sql-discipline
waivers — central-registry placement is that pass's concern, not this
fixture's.
"""

from spacedrive_tpu.store.statements import declare_stmt


def declare_bad():
    declare_stmt(  # sdlint: ok[sql-discipline]
        "fixture.ghost_table",
        "SELECT * FROM warp_core WHERE dilithium = ?",
        verb="read", tables=(), cardinality="one")

    declare_stmt(  # sdlint: ok[sql-discipline]
        "fixture.ghost_column",
        "SELECT flux_capacitance FROM tag WHERE id = ?",
        verb="read", tables=("tag",), cardinality="one")

    declare_stmt(  # sdlint: ok[sql-discipline]
        "fixture.ghost_qualified",
        "SELECT t.wormhole FROM tag t WHERE t.id = ?",
        verb="read", tables=("tag",), cardinality="one")

    declare_stmt(  # sdlint: ok[sql-discipline]
        "fixture.drifted_tables",
        "SELECT id FROM object WHERE id = ?",
        verb="read", tables=("location",), cardinality="one")

    declare_stmt(  # sdlint: ok[sql-discipline]
        "fixture.sequential_scan",
        "SELECT id FROM file_path WHERE date_modified = ?",
        verb="read", tables=("file_path",), cardinality="many")
