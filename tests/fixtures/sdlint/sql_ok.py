"""sdlint fixture — sql-discipline KNOWN NEGATIVES.

The sanctioned forms: run() with registry names (reads bare, writes
with conn= or run_tx), dynamic SQL bound to a declared shape,
registry-pulled SQL text on a bare connection, and non-SQL strings at
methods that happen to be called execute/run.
"""


def declared_read(db, oid):
    return db.run("api.object.by_id", (oid,))


def declared_write(db, oid):
    with db.tx() as conn:
        db.run("node.object_delete", (oid,), conn=conn)


def declared_write_sugar(db, oid):
    db.run_tx("node.object_delete", (oid,))


def declared_many(db, conn, rows):
    db.run_many("identifier.link_paths", rows, conn=conn)


def shape_bound(conn, table, col):
    # binds the declared store.helper.update shape
    conn.execute(f"UPDATE {table} SET {col} = ? WHERE id = ?", (1, 2))


def registry_sql_on_conn(conn, scratch_id):
    from spacedrive_tpu.store import statements

    conn.execute(statements.get("jobs.scratch.delete").sql,
                 (scratch_id,))


def not_sql(runner, job):
    # .run()/.execute() on non-database receivers are out of scope
    runner.run(job)
    job.execute("not a sql string at all")


def subprocess_run():
    import subprocess

    subprocess.run(["true"], check=False)
