"""Known-NEGATIVE fixture for the shared-mutation pass: every contract
kind obeyed, plus the sanctioned unregistered single-context class."""

import asyncio
import threading

from spacedrive_tpu.threadctx import (
    atomic_counter,
    declare_owner,
    guarded_by,
    immutable_after_init,
    loop_only,
    single_thread,
)

declare_owner(
    "fixture.CleanStats",
    "tests/fixtures/sdlint/race_ok.py::CleanStats",
    {
        "h2d_bytes": guarded_by("_lock"),
        "events": loop_only(),
        "wall_s": single_thread(),
        "ticks": atomic_counter(),
        "shape": immutable_after_init(),
    })


class CleanStats:
    def __init__(self):
        self._lock = threading.Lock()
        self.h2d_bytes = 0
        self.events = []
        self.wall_s = 0.0
        self.ticks = 0
        self.shape = (8, 57344)   # immutable: bound here, never again


def _transfer(stats: CleanStats) -> None:
    # guarded_by honored: the executor stream takes the declared lock.
    with stats._lock:
        stats.h2d_bytes += 57344
    # atomic_counter: bare augmented update is the declared waiver.
    stats.ticks += 1


async def drive(stats: CleanStats, pool) -> None:
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(pool, _transfer, stats)
    stats.events.append("done")   # loop_only attr, loop context only
    stats.wall_s = 1.0            # single_thread: one writer context


class LoopLocal:
    """Unregistered, but every mutation is loop-context: no contract
    needed and no finding."""

    def __init__(self):
        self.seen = {}

    def record(self, k) -> None:
        self.seen[k] = True


async def uses(b: LoopLocal) -> None:
    b.record("x")


class WorkList:
    """Unregistered and mutated only from ambient (unlabeled) sync
    drivers — single-threaded by construction, no finding."""

    def __init__(self):
        self.items = []

    def push(self, item) -> None:
        self.items.append(item)
