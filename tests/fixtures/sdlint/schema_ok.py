"""sdlint fixture — schema-parity KNOWN NEGATIVES.

Registry-faithful declarations: real tables and columns, aliases and
result aliases, an indexed filter on a large table, a shape with open
identifier slots, and SQLite internals (rowid, sqlite_master,
functions).
"""

from spacedrive_tpu.store.statements import declare_shape, declare_stmt


def declare_ok():
    declare_stmt(  # sdlint: ok[sql-discipline]
        "fixture.ok_read",
        "SELECT t.id, t.name AS tag_name FROM tag t "
        "JOIN tag_on_object tob ON tob.tag_id = t.id "
        "WHERE tob.object_id = ?",
        verb="read", tables=("tag", "tag_on_object"),
        cardinality="many")

    declare_stmt(  # sdlint: ok[sql-discipline]
        "fixture.ok_indexed_filter",
        "SELECT COUNT(*) AS n FROM file_path WHERE cas_id = ?",
        verb="read", tables=("file_path",), cardinality="one")

    declare_stmt(  # sdlint: ok[sql-discipline]
        "fixture.ok_write",
        "UPDATE tag SET name = ?, date_modified = ? WHERE id = ?",
        verb="write", tables=("tag",), tx_required=True)

    declare_stmt(  # sdlint: ok[sql-discipline]
        "fixture.ok_internal",
        "SELECT name FROM sqlite_master WHERE rowid = ?",
        verb="read", tables=("sqlite_master",), cardinality="one")

    declare_shape(  # sdlint: ok[sql-discipline]
        "fixture.ok_shape",
        "SELECT id FROM {i} WHERE {i} = ? ORDER BY id LIMIT ?",
        verb="read", cardinality="many")
