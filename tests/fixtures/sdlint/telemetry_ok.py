"""sdlint fixture — telemetry-pass KNOWN NEGATIVE: importing and using
a centrally-defined family is the sanctioned idiom."""

from spacedrive_tpu.telemetry import JOBS_INGESTED


def record():
    JOBS_INGESTED.inc()
