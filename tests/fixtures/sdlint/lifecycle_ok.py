"""task-lifecycle known-NEGATIVES: all sanctioned spawn shapes."""

import asyncio

from spacedrive_tpu import tasks


async def work():
    await asyncio.sleep(0)


class Actor:
    def start(self):
        # stored on an owner: the actor cancels it in stop().
        self._task = asyncio.get_running_loop().create_task(work())

    def start_supervised(self):
        # supervised fire-and-forget: the registry holds the reference.
        tasks.spawn("actor", work(), owner="fixture")

    def stop(self):
        self._task.cancel()


async def awaited_directly():
    t = asyncio.ensure_future(work())
    await t


async def bounded_in_loop(items):
    # worker.py's step/command shape: spawned in a loop but awaited
    # (via asyncio.wait) inside the same function.
    for _ in items:
        step = asyncio.ensure_future(work())
        cmd = asyncio.ensure_future(work())
        await asyncio.wait({step, cmd},
                           return_when=asyncio.FIRST_COMPLETED)
        await tasks.cancel_and_gather(step, cmd)
