# sdlint-scope: net
"""timeout-discipline known-NEGATIVES."""

import asyncio

from spacedrive_tpu.timeouts import deadline, with_timeout


async def pull(tunnel):
    req = await with_timeout("p2p.header_recv", tunnel.recv())
    await with_timeout("p2p.frame_send", tunnel.send({"ok": True}))
    return req


async def handshake(reader, writer):
    # block-scoped budget covers every await inside.
    async with deadline("p2p.handshake"):
        await writer.drain()
        return await reader.readexactly(4)


async def local_work(db):
    # not a network root: no budget required.
    return await asyncio.to_thread(db.query, "SELECT 1")


async def server_read_loop(ws):
    # async-for over a websocket is exempt by design: a client owns
    # its own idle cadence.
    async for msg in ws:
        if msg is None:
            break
