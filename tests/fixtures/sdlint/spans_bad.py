"""sdlint fixture — telemetry-pass span-name KNOWN POSITIVES: an
undeclared family, fully-dynamic names, and a declaration outside the
central registry."""

import spacedrive_tpu.tracing as tr
from spacedrive_tpu.tracing import declare_span, device_span
from spacedrive_tpu.tracing import span as trace_span


def undeclared_literal():
    with trace_span("totally.rogue.family"):
        pass


def undeclared_via_module_alias():
    # the review-round bypass: an aliased module import must not dodge
    # the family check
    with tr.span("rogue.via.alias"):
        pass


def undeclared_via_full_path():
    import spacedrive_tpu.tracing

    with spacedrive_tpu.tracing.span("rogue.via.dotted"):
        pass


def undeclared_via_relative_alias():
    # pure-relative import (ast module=None) — the second review-round
    # bypass; fixtures are parsed, never imported, so this is legal
    from .. import tracing as trc

    with trc.span("rogue.via.relative"):
        pass


def undeclared_variant(backend):
    with device_span(f"rogue_family/{backend}"):
        pass


def dynamic_name(name):
    with trace_span(name):  # no constant family at all
        pass


def dynamic_prefix(name):
    with device_span(f"{name}/suffix"):  # family itself is dynamic
        pass


ROGUE = declare_span("declared.in.the.wrong.place")
