"""sdlint fixture — flag-registry KNOWN NEGATIVES (all clean)."""

import os

from spacedrive_tpu import flags


def read_via_registry():
    return flags.get("SDTPU_TELEMETRY")


def writes_are_allowed():
    os.environ["SDTPU_TELEMETRY"] = "off"
    os.environ.setdefault("SDTPU_SHARDED_CAS", "off")
    os.environ.pop("SDTPU_TELEMETRY", None)
