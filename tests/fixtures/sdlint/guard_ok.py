"""Known-NEGATIVE fixture for the guard-consistency pass: consistent
guards (including supersets and the tx-implies-write-lock model),
init-time bare writes, never-guarded work lists, and registered
classes (owned by the shared-mutation contract instead)."""

import threading

from spacedrive_tpu.threadctx import declare_owner, guarded_by

declare_owner(
    "fixture.OwnedElsewhere",
    "tests/fixtures/sdlint/guard_ok.py::OwnedElsewhere",
    {
        "count": guarded_by("_lock"),
    })


class Consistent:
    def __init__(self):
        self._lock = threading.Lock()
        self._aux_lock = threading.Lock()
        self.entries = {}       # bare here: __init__ is exempt
        self.hits = 0

    def put(self, k, v) -> None:
        with self._lock:
            self.entries[k] = v
            self.hits += 1

    def evict(self, k) -> None:
        with self._lock:
            with self._aux_lock:
                # Superset of the common guard is still consistent.
                self.entries.pop(k, None)
                self.hits -= 1


class TxGuarded:
    """`with db.tx():` holds the database write lock — the model the
    pass shares with lock-discipline — so mixing it with an explicit
    `with self._write_lock:` site is consistent."""

    def __init__(self, db):
        self.db = db
        self._write_lock = threading.Lock()
        self.pending = []

    def in_tx(self) -> None:
        with self.db.tx():
            self.pending.append(1)

    def direct(self) -> None:
        with self._write_lock:
            self.pending.append(2)


class NeverGuarded:
    """No site claims protection: a single-threaded work list, out of
    scope by design (the shared-mutation context derivation decides
    whether it NEEDS protection)."""

    def __init__(self):
        self.items = []

    def push(self, item) -> None:
        self.items.append(item)


class OwnedElsewhere:
    """Registered in the ownership registry: guard enforcement belongs
    to the shared-mutation contract, not this heuristic — even though
    one site here is bare (it would be a shared-mutation finding if
    its context were multi-threaded)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self) -> None:
        with self._lock:
            self.count += 1

    def lazy_reset(self) -> None:
        self.count = 0
