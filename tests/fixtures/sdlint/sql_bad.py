"""sdlint fixture — sql-discipline KNOWN POSITIVES.

Every way SQL can dodge the statement contract registry
(store/statements.py): raw literals at execute methods (direct and
via a local variable), dynamic SQL matching no declared shape, opaque
expressions, unknown/dynamic run() names, un-tx'd writes, the removed
Database.execute surface, and an out-of-central declaration.
"""


def literal_select(db, oid):
    # sql-literal: raw DML literal at an execute method
    return db.query_one("SELECT * FROM object WHERE id = ?", (oid,))


def literal_insert(conn, pub):
    # sql-literal: raw write literal on a connection
    conn.execute("INSERT INTO tag (pub_id) VALUES (?)", (pub,))


def literal_via_variable(db):
    # sql-literal: the literal hides behind a local name
    sql = "SELECT id FROM location"
    return db.query(sql)


def dynamic_unmatched(conn, table):
    # sql-dynamic: f-string matching NO declared shape
    conn.execute(f"UPDATE {table} SET kind = 7 WHERE kind IS NULL")


def opaque(conn, mystery_sql):
    # sql-opaque: the pass cannot see what runs
    conn.execute(mystery_sql)


def unknown_name(db):
    # run-unknown: not in the registry
    db.run("store.totally.unknown_statement")


def dynamic_name(db, which):
    # run-dynamic-name: registry linkage must be literal
    db.run(which)


def write_without_conn(db, oid):
    # write-no-conn: a write-verb statement with no tx connection
    db.run("node.object_delete", (oid,))


def read_on_write_path(library):
    # read-via-write-path: the removed write-wrapping execute surface
    library.db.execute("DELETE FROM tag")


def rogue_declare():
    # sql-central: declaring outside store/statements.py
    from spacedrive_tpu.store.statements import declare_stmt

    declare_stmt(
        "rogue.statement", "SELECT 1 FROM tag",
        verb="read", tables=("tag",), cardinality="one")
