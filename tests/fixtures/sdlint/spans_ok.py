"""sdlint fixture — telemetry-pass span-name KNOWN NEGATIVES: declared
families, literal and with dynamic variants, through both import
spellings."""

from spacedrive_tpu import tracing
from spacedrive_tpu.tracing import device_span
from spacedrive_tpu.tracing import span as trace_span


def literal_family():
    with trace_span("job.step", step=1):
        pass


def declared_variant(backend):
    with device_span(f"cas_ids/{backend}", batch=4):
        pass


def qualified_call(path):
    with tracing.span(f"rpc/{path}"):
        pass


def aliased_module_call():
    import spacedrive_tpu.tracing as tr

    with tr.span("job.step"):
        pass


def unrelated_span_function():
    def span(name):  # a local def named span is NOT the span surface
        return name

    return span(object())
