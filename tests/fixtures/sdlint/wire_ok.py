# sdlint-scope: wire
"""wire-discipline known-NEGATIVES: the blessed frame shapes."""

from spacedrive_tpu.p2p import wire

SYNC_PROTO = wire.proto("sync")


async def declared_pack(tunnel):
    await tunnel.send(wire.pack("p2p.ping", tp=None))


def declared_unpack(raw):
    return wire.unpack("p2p.pong", raw)


async def declared_verdict(tunnel):
    # the values contract: the verdict goes through pack, so the
    # declared set is enforced
    verdict = wire.pack("spaceblock.verdict", value="ok")
    await tunnel.send(verdict)


def undeclared_discriminator():
    # a dict with a t/kind value NO declaration claims is not a frame
    return {"kind": "fixture-local-state", "tp": None}
