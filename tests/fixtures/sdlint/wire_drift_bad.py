# sdlint-scope: wire
"""schema-drift known-POSITIVES.

Field traffic off the declared schema on both sides of an exchange:
packs that smuggle or omit fields, reads of fields no declaration
carries, and a hand-built discriminator frame missing a required
field.
"""

from spacedrive_tpu.p2p import wire


def smuggled_pack():
    # smuggled-field: 'extra' is not in p2p.pair.request's schema
    return wire.pack("p2p.pair.request", library_id="x",
                     library_name="y", listen_port=7373,
                     instance={}, extra=1)


def incomplete_pack():
    # missing-field: library_name / listen_port / instance omitted —
    # the call raises WireSchemaError at runtime
    return wire.pack("p2p.pair.request", library_id="x")


def phantom_read(raw):
    # unknown-field-read: no declaration of sync.pull.request carries
    # a 'cursor' field — no peer ever sends it
    req = wire.unpack("sync.pull.request", raw)
    return req.get("cursor")


def phantom_subscript(raw):
    # unknown-field-read: subscript form
    page = wire.unpack("sync.pull.page", raw)
    return page["total"]


def hand_built_incomplete():
    # missing-field: a literal clone.ack frame without 'fast'
    # (also wire-discipline's raw-kind-literal — different pass)
    return {"kind": "ack", "ts": 4}
