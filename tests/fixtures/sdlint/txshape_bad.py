"""sdlint fixture — tx-shape KNOWN POSITIVES.

The commit-per-item shape in every spelling (lexical with-tx, run_tx,
helper-without-conn, and an interprocedural opener in a loop), a
blocking call and an await inside an open tx, a nested-tx call chain,
and a per-iteration single-row write where executemany exists.
"""

import time


def tx_per_item(db, items):
    for item in items:
        with db.tx() as conn:  # the PR 1 identifier shape
            db.run("node.object_delete", (item,), conn=conn)


def run_tx_per_item(db, items):
    for item in items:
        db.run_tx("node.object_delete", (item,))


def helper_per_item(db, rows):
    for row in rows:
        db.insert("tag", row)


def _opens_tx(db, row):
    with db.tx() as conn:
        db.run("node.object_delete", (row,), conn=conn)


def opener_in_loop(db, rows):
    for row in rows:
        _opens_tx(db, row)


def blocking_inside_tx(db, path):
    with db.tx() as conn:
        time.sleep(0.5)
        data = open(path).read()
        db.run("node.object_delete", (len(data),), conn=conn)


async def await_inside_tx(db, fetch):
    with db.tx() as conn:
        row = await fetch()
        db.run("node.object_delete", (row,), conn=conn)


def nested_chain(db, rows):
    with db.tx() as conn:
        db.run("node.object_delete", (1,), conn=conn)
        _opens_tx(db, rows)  # transitively BEGINs inside our tx


def row_at_a_time(db, conn, rows):
    for a, b in rows:
        db.run("identifier.link_paths", (a, b, 1), conn=conn)


def write_tx_per_item(db, items):
    # the same commit-per-item shape through the group-commit seam
    for item in items:
        with db.write_tx() as conn:
            db.run("node.object_delete", (item,), conn=conn)
