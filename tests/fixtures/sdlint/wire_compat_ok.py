# sdlint-scope: wire
"""proto-compat known-NEGATIVES: compat events handled by the book."""

from spacedrive_tpu.p2p import wire

WIRE_BASELINE = {
    # unchanged shape, same version: nothing to report
    "fx.ok.msg": {
        "proto": "p2p", "version": 1, "size_cap": 4096,
        "schema": {"kind": "=fxok", "a": "str"},
    },
    # schema changed WITH a bump: the entry records the old version,
    # the registry's group moved on — the diff is satisfied
    "fx.ok.bumped": {
        "proto": "p2p", "version": 0, "size_cap": 4096,
        "schema": {"kind": "=fxbumped", "old": "str"},
    },
}

wire.declare_message(
    "fx.ok.msg", "p2p", "both",
    {"kind": "=fxok", "a": "str"},
    size_cap=4096, timeout_budget="p2p.ping")

wire.declare_message(
    "fx.ok.bumped", "p2p", "both",
    {"kind": "=fxbumped", "renamed": "str"},
    size_cap=4096, timeout_budget="p2p.ping")


def registry_version_gate(header):
    # the declared idiom: unpack refuses skew itself
    return wire.unpack("sync.announce", header)
