"""backpressure known-POSITIVES."""

from spacedrive_tpu import channels


class Producer:
    def __init__(self):
        # sync.ingest.requests is block-policy in the real registry
        self.requests = channels.channel("sync.ingest.requests")

    def push(self, item):
        self.requests.put_nowait(item)      # nowait-on-block


def fan_out(subs, event):
    for sub in subs:
        sub.buffer.append(event)            # unbounded-fanout


async def burst(tunnel, pages):
    for page in pages:
        tunnel.send_nowait(page)            # burst-without-drain
