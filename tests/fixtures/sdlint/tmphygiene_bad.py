"""tmp-hygiene known-POSITIVES."""

import os
import shutil
import tempfile


def forgets_entirely(n):
    tmp = tempfile.mkdtemp(prefix="leaky-")     # tmp-no-cleanup
    for i in range(n):
        with open(os.path.join(tmp, f"{i}.bin"), "wb") as f:
            f.write(b"x")
    return tmp


def happy_path_only(build):
    tmp = tempfile.mkdtemp(prefix="fragile-")   # tmp-leak-on-error
    build(tmp)                                  # a raise here leaks
    shutil.rmtree(tmp, ignore_errors=True)


def keeps_named_file(data):
    f = tempfile.NamedTemporaryFile(delete=False)  # tmp-no-cleanup
    f.write(data)
    f.close()
    return f.name
