# sdlint-scope: wire
"""proto-compat known-POSITIVES.

The compat events the snapshot diff must catch: a schema change with
no version bump, a declaration missing from the snapshot, a snapshot
entry whose message is gone, and a hand-rolled proto-field compare.
The expected snapshot rides along as a WIRE_BASELINE literal
(fixture entries win over the committed file).
"""

from spacedrive_tpu.p2p import wire

WIRE_BASELINE = {
    # schema-no-bump: the declaration below grew field 'b' but 'p2p'
    # is still the version this entry recorded
    "fx.compat.msg": {
        "proto": "p2p", "version": 1, "size_cap": 4096,
        "schema": {"kind": "=fxmsg", "a": "str"},
    },
    # removed-message: nothing declares this any more
    "fx.compat.ghost": {
        "proto": "p2p", "version": 1, "size_cap": 4096,
        "schema": {"kind": "=fxghost"},
    },
}

wire.declare_message(
    "fx.compat.msg", "p2p", "both",
    {"kind": "=fxmsg", "a": "str", "b": "int"},
    size_cap=4096, timeout_budget="p2p.ping")

# missing-snapshot: declared, no baseline entry anywhere
wire.declare_message(
    "fx.compat.unsnapshotted", "p2p", "both",
    {"kind": "=fxnew"},
    size_cap=4096, timeout_budget="p2p.ping")


def adhoc_version_gate(frame):
    # adhoc-version-check: wire.unpack IS the version check
    return frame.get("proto") == 3
