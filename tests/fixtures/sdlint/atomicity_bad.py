# sdlint-scope: persist
"""crash-atomicity known-POSITIVES (scope opted in above)."""

import json

from spacedrive_tpu import persist


def restore_pair(cfg_path, node_path, doc):
    # two artifacts, no declared ordering -> multi-commit
    persist.atomic_write("library.config", cfg_path, doc)
    persist.atomic_write("node.config", node_path, doc)


class Creator:
    def create(self, db, cfg_path, doc):
        # artifact + DB row -> multi-commit
        db.insert("library", {"pub_id": b"x"})
        persist.atomic_write("library.config", cfg_path, doc)


def bump_generation(path):
    # read-modify-write with no lock -> rmw-unguarded
    with open(path) as f:
        doc = json.load(f)
    doc["generation"] = doc.get("generation", 0) + 1
    persist.atomic_write("crypto.keyring", path, json.dumps(doc))
