"""tmp-hygiene known-NEGATIVES: cleanup by construction."""

import shutil
import tempfile

from spacedrive_tpu import persist


def guarded(build):
    tmp = tempfile.mkdtemp(prefix="guarded-")
    try:
        build(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def context_managed(build):
    with tempfile.TemporaryDirectory() as tmp:
        build(tmp)


def declared_scratch(build):
    with persist.scratch("bench.workdir") as tmp:
        build(tmp)


def auto_deleting_file(data):
    with tempfile.NamedTemporaryFile() as f:    # delete=True default
        f.write(data)
