"""task-lifecycle known-POSITIVES: every shape below is a finding."""

import asyncio


async def work():
    await asyncio.sleep(0)


def fire_and_forget(loop):
    # dropped-task: the loop holds tasks weakly; nothing owns this.
    loop.create_task(work())


def old_loop_spawn():
    # deprecated-get-event-loop AND dropped-task — the exact
    # locations/watcher.py:375 shape (dynamic receiver chain).
    asyncio.get_event_loop().create_task(work())


def just_the_loop():
    # deprecated-get-event-loop alone.
    loop = asyncio.get_event_loop()
    return loop


async def storm(items, registry):
    # spawn-in-loop: stored, registered... but never awaited anywhere
    # in this function — an unbounded task pile-up.
    for _ in items:
        t = asyncio.ensure_future(work())
        registry.append(t)
