"""Known-POSITIVE fixture for the thread-boundary pass: loop-affine
calls (task spawn, registry-channel methods, EventBus emit) from
executor-context code, plus the raw threadsafe hand-off primitive."""

import asyncio

from spacedrive_tpu import channels, tasks


async def _noop() -> None:
    pass


class Pump:
    def __init__(self, events):
        self.inbox = channels.channel("media.thumbs")
        self.events = events

    def worker_offer(self, item) -> None:
        # All four BAD: this method is submitted to the pool below, so
        # these loop-affine calls run on an executor thread.
        self.inbox.put_nowait(item)
        self.events.emit({"type": "x"})
        tasks.spawn("leak", _noop(), owner="fixture")
        asyncio.ensure_future(_noop())

    def legacy_post(self, loop, item) -> None:
        # BAD raw-threadsafe-handoff: the raw primitive crashes the
        # posting thread when the loop closed mid-shutdown.
        loop.call_soon_threadsafe(self.inbox.put_nowait, item)

    async def run(self, pool) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(pool, self.worker_offer, 1)


def _drain_local() -> None:
    q = channels.channel("media.thumbs")
    q.put_nowait(1)   # BAD: local registry channel, worker context


async def kick() -> None:
    await asyncio.to_thread(_drain_local)
