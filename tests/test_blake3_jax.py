"""JAX backend parity vs oracle, incl. sharded execution on a CPU mesh."""

import os
import random

import numpy as np

from spacedrive_tpu.ops.blake3_jax import (
    blake3_words,
    build_cas_messages,
    cas_ids_jax,
    digests_to_cas_ids,
    digests_to_hex,
    make_sharded_blake3,
)
from spacedrive_tpu.ops.blake3_batch import pack_messages
from spacedrive_tpu.ops.blake3_ref import blake3_hex
from spacedrive_tpu.ops import cas
from spacedrive_tpu.parallel import batch_mesh


def test_jax_matches_oracle_edge_lengths():
    lengths = [0, 1, 64, 1024, 1025, 2048, 3071, 57352]
    msgs = [os.urandom(n) for n in lengths]
    words, lens = pack_messages(msgs)
    digests = blake3_words(words, lens)
    for m, hexd in zip(msgs, digests_to_hex(digests)):
        assert hexd == blake3_hex(m), f"len={len(m)}"


def test_cas_pipeline_large_mode_matches_oracle(tmp_path):
    """Fixed-shape large-file mode: sampled payloads → CAS IDs on device."""
    rng = random.Random(5)
    B = 4
    paths, sizes = [], []
    for i in range(B):
        size = rng.randrange(cas.MINIMUM_FILE_SIZE + 1, 400_000)
        p = tmp_path / f"f{i}"
        p.write_bytes(os.urandom(size))
        paths.append(p)
        sizes.append(size)

    payloads = np.zeros((B, cas.LARGE_PAYLOAD_SIZE), dtype=np.uint8)
    for i, (p, size) in enumerate(zip(paths, sizes)):
        with open(p, "rb") as f:
            payloads[i] = np.frombuffer(
                cas.read_sampled_payload(f, size), dtype=np.uint8
            )
    got = cas_ids_jax(payloads, np.array(sizes, dtype=np.uint64))
    want = [cas.generate_cas_id(p) for p in paths]
    assert got == want


def test_cas_pipeline_small_mode(tmp_path):
    """Variable-length small files padded into one grid."""
    sizes = [0, 1, 5000, cas.MINIMUM_FILE_SIZE]
    B = len(sizes)
    payloads = np.zeros((B, cas.MINIMUM_FILE_SIZE), dtype=np.uint8)
    paths = []
    for i, size in enumerate(sizes):
        p = tmp_path / f"s{i}"
        data = os.urandom(size)
        p.write_bytes(data)
        paths.append(p)
        payloads[i, :size] = np.frombuffer(data, dtype=np.uint8)
    got = cas_ids_jax(
        payloads,
        np.array(sizes, dtype=np.uint64),
        payload_lens=np.array(sizes, dtype=np.int32),
    )
    want = [cas.generate_cas_id(p) for p in paths]
    assert got == want


def test_cas_dispatch_routes_donated_entry(monkeypatch):
    """cas_ids_jax dispatch plumbing for SDTPU_DONATE_BUFFERS: with the
    flag on (the production default; conftest pins it off suite-wide
    for compile cost) the single-device path hashes through the donated
    entry — `_donated_local` over the `blake3.donated` contract — and
    the CAS IDs come out unchanged. The stand-in delegates to the
    already-compiled undonated program, keeping this a pure plumbing
    test; the donated program's real consume-at-dispatch semantics are
    pinned by test_overlap.py's footprint test over a cheap kernel."""
    from spacedrive_tpu.ops import blake3_jax as bj

    sizes = [0, 77, 4096]
    B = len(sizes)
    payloads = np.zeros((B, cas.MINIMUM_FILE_SIZE), dtype=np.uint8)
    for i, size in enumerate(sizes):
        payloads[i, :size] = np.frombuffer(os.urandom(size), np.uint8)
    lens = np.array(sizes, dtype=np.int32)
    want = cas_ids_jax(payloads, np.array(sizes, np.uint64),
                       payload_lens=lens)

    calls = []

    def fake_donated(words, lengths):
        calls.append(tuple(words.shape))
        return bj.blake3_words(words, lengths)

    monkeypatch.setenv("SDTPU_DONATE_BUFFERS", "on")
    monkeypatch.setattr(bj, "_donated_local", fake_donated)
    got = cas_ids_jax(payloads, np.array(sizes, np.uint64),
                      payload_lens=lens)
    assert calls, "donated entry was not dispatched with the flag on"
    assert got == want
    # the suite-wide off pin really does route the undonated program
    calls.clear()
    monkeypatch.setenv("SDTPU_DONATE_BUFFERS", "off")
    got_off = cas_ids_jax(payloads, np.array(sizes, np.uint64),
                          payload_lens=lens)
    assert not calls and got_off == want


def test_sharded_blake3_on_cpu_mesh(cpu_devices):
    mesh = batch_mesh(cpu_devices)
    assert len(cpu_devices) == 8, "conftest should provide 8 virtual CPU devices"
    B = 16  # divisible by mesh size
    msgs = [os.urandom(3000) for _ in range(B)]
    words, lens = pack_messages(msgs, max_chunks=3)
    sharded = make_sharded_blake3(mesh)
    digests = sharded(words, lens)
    for m, hexd in zip(msgs, digests_to_hex(digests)):
        assert hexd == blake3_hex(m)


def test_identifier_sharded_dispatch_on_cpu_mesh(tmp_path, monkeypatch):
    """The PRODUCTION multi-device path: with >1 local device (and the
    suite's compile-saving gate reopened) cas_ids_for_files
    backend="jax" auto-routes through the mesh-sharded program with
    pad-to-devices batching — CAS IDs byte-equal the streaming oracle.
    This is the dispatch a real pod slice uses; dryrun_multichip
    stage 6 proves the same thing under the driver."""
    import random

    from spacedrive_tpu.ops import blake3_jax as bj
    from spacedrive_tpu.ops import staging
    from spacedrive_tpu.ops.cas import generate_cas_id

    monkeypatch.setenv("SDTPU_SHARDED_CAS", "auto")
    monkeypatch.setattr(bj, "_SHARDED", None)
    rng = random.Random(4)
    files = []
    for i in range(9):  # deliberately not a devices multiple
        size = 1500 + 701 * i
        p = tmp_path / f"f{i}.bin"
        p.write_bytes(rng.randbytes(size))
        files.append((str(p), size))
    hasher, n_dev = bj.sharded_hasher()
    assert hasher is not None and n_dev == 8
    ids, errs = staging.cas_ids_for_files(files, backend="jax")
    assert not errs
    for i, (p, size) in enumerate(files):
        assert ids[i] == generate_cas_id(p, size), i
