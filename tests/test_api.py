"""API surface tests: router dispatch, procedures across namespaces, and
the websocket/HTTP server host with custom_uri file serving."""

import asyncio
import json
import os
import uuid

import aiohttp
import pytest

from spacedrive_tpu.api.router import RpcError, mount_router
from spacedrive_tpu.node import Node


def _run(coro):
    return asyncio.run(coro)


def _corpus(root):
    os.makedirs(f"{root}/docs", exist_ok=True)
    with open(f"{root}/docs/hello.txt", "wb") as f:
        f.write(b"hello world " * 400)
    from PIL import Image
    Image.new("RGB", (80, 60), (10, 120, 200)).save(f"{root}/pic.png")


@pytest.fixture
def env(tmp_path):
    corpus = tmp_path / "corpus"
    corpus.mkdir()
    _corpus(str(corpus))
    node = Node(str(tmp_path / "data"))
    router = mount_router(node)
    return node, router, str(corpus)


def test_router_basics(env):
    node, router, corpus = env

    async def main():
        info = await router.dispatch("buildInfo")
        assert info["version"]
        state = await router.dispatch("nodeState")
        assert state["name"]
        with pytest.raises(RpcError):
            await router.dispatch("nope.nope")
        # library-scoped without library_id
        with pytest.raises(RpcError):
            await router.dispatch("locations.list", {})
    _run(main())


def test_full_api_flow(env):
    node, router, corpus = env

    async def main():
        lib = await router.dispatch("library.create", {"name": "api-lib"})
        lid = lib["uuid"]
        libs = await router.dispatch("library.list")
        assert [x["uuid"] for x in libs] == [lid]

        # invalidation events fired for mutations
        events = []
        node.events.subscribe(events.append)

        loc_id = await router.dispatch("locations.create", {
            "library_id": lid, "path": corpus, "dry_run": True})
        assert isinstance(loc_id, int)
        # full rescan via jobs
        await router.dispatch("locations.fullRescan",
                              {"library_id": lid, "location_id": loc_id})
        await node.jobs.wait_idle()

        paths = await router.dispatch("search.paths", {"library_id": lid})
        names = {p["name"] for p in paths["items"]}
        assert {"hello", "pic", "docs"} <= names
        count = await router.dispatch(
            "search.pathsCount", {"library_id": lid})
        assert count == len(paths["items"])

        objs = await router.dispatch("search.objects", {"library_id": lid})
        assert len(objs["items"]) == 2
        cats = await router.dispatch("categories.list", {"library_id": lid})
        assert cats["Image"] == 1 and cats["Text"] == 1

        # tags roundtrip
        tag = await router.dispatch("tags.create", {
            "library_id": lid, "name": "important", "color": "#f00"})
        obj_id = objs["items"][0]["id"]
        await router.dispatch("tags.assign", {
            "library_id": lid, "tag_id": tag["id"], "object_id": obj_id})
        got = await router.dispatch("tags.getForObject", {
            "library_id": lid, "object_id": obj_id})
        assert [t["name"] for t in got] == ["important"]
        await router.dispatch("tags.assign", {
            "library_id": lid, "tag_id": tag["id"], "object_id": obj_id,
            "unassign": True})
        assert await router.dispatch("tags.getForObject", {
            "library_id": lid, "object_id": obj_id}) == []

        # files procedures
        fp = next(p for p in paths["items"] if p["name"] == "hello")
        full = await router.dispatch("files.getPath", {
            "library_id": lid, "id": fp["id"]})
        assert full.endswith("docs/hello.txt")
        await router.dispatch("files.setFavorite", {
            "library_id": lid, "id": fp["object_id"], "favorite": True})
        favs = await router.dispatch("search.objects", {
            "library_id": lid, "filter": {"favorite": True}})
        assert len(favs["items"]) == 1

        # rename + DB consistency
        await router.dispatch("files.renameFile", {
            "library_id": lid, "file_path_id": fp["id"],
            "new_name": "renamed.txt"})
        assert os.path.exists(f"{corpus}/docs/renamed.txt")

        # jobs reports exist; statistics aggregate
        reports = await router.dispatch("jobs.reports", {"library_id": lid})
        assert any(r["name"] == "indexer" for r in reports)
        stats = await router.dispatch(
            "library.statistics", {"library_id": lid})
        assert stats["total_object_count"] == 2

        # volumes + ephemeral
        vols = await router.dispatch("volumes.list")
        assert any(v["mount_point"] == "/" for v in vols)
        eph = await router.dispatch("search.ephemeralPaths", {
            "path": corpus})
        assert any(e["name"] == "pic" for e in eph)

        # preferences
        await router.dispatch("preferences.update", {
            "library_id": lid, "values": {"theme": "dark"}})
        prefs = await router.dispatch(
            "preferences.get", {"library_id": lid})
        assert prefs["theme"] == "dark"

        # invalidation events were emitted for the mutations above
        keys = {e.get("key") for e in events
                if e.get("type") == "InvalidateOperation"}
        assert "tags.list" in keys and "locations.list" in keys
    _run(main())


def test_backup_restore_roundtrip(env):
    node, router, corpus = env

    async def main():
        lib = await router.dispatch("library.create", {"name": "bk"})
        lid = lib["uuid"]
        await router.dispatch("tags.create", {
            "library_id": lid, "name": "keepme"})
        backup_id = await router.dispatch(
            "backups.backup", {"library_id": lid})
        assert (await router.dispatch("backups.getAll"))[0]["id"] == backup_id
        # destroy the tag, then restore
        lib_obj = node.libraries.get(uuid.UUID(lid))
        for r in lib_obj.db.query("SELECT id FROM tag"):
            lib_obj.db.delete("tag", r["id"])
        assert await router.dispatch("tags.list", {"library_id": lid}) == []
        await router.dispatch("backups.restore", {"backup_id": backup_id})
        tags = await router.dispatch("tags.list", {"library_id": lid})
        assert [t["name"] for t in tags] == ["keepme"]
        assert await router.dispatch("backups.delete",
                                     {"backup_id": backup_id})
    _run(main())


def test_server_ws_and_custom_uri(env, tmp_path):
    node, router, corpus = env

    async def main():
        from spacedrive_tpu.api.server import ApiServer
        server = ApiServer(node, router)
        port = await server.start(port=0)
        base = f"http://127.0.0.1:{port}"
        async with aiohttp.ClientSession() as http:
            # health + embedded web explorer + one-shot HTTP rpc
            async with http.get(f"{base}/health") as resp:
                assert resp.status == 200
            async with http.get(f"{base}/") as resp:
                assert resp.status == 200
                page = await resp.text()
                assert "spacedrive-tpu" in page
                assert "/static/app.js" in page  # split-asset entry
            async with http.get(f"{base}/static/app.js") as resp:
                assert resp.status == 200
                js = await resp.text()
                # the app drives the same /rspc ws protocol
                assert "/rspc" in js and "jobs.progress" in js
            async with http.post(f"{base}/rspc/library.create",
                                 json={"name": "ws-lib"}) as resp:
                lid = (await resp.json())["result"]["uuid"]

            # websocket: subscription + mutation + query
            async with http.ws_connect(f"{base}/rspc") as ws:
                await ws.send_json({"id": 1, "type": "subscription",
                                    "path": "invalidation.listen"})
                assert (await ws.receive_json())["type"] == "response"
                await ws.send_json({
                    "id": 2, "type": "mutation",
                    "path": "locations.create",
                    "input": {"library_id": lid, "path": corpus,
                              "dry_run": True}})
                got_invalidate = got_response = False
                loc_id = None
                for _ in range(4):
                    frame = await asyncio.wait_for(
                        ws.receive_json(), timeout=5)
                    if frame["type"] == "event" and \
                            frame["data"]["key"] == "locations.list":
                        got_invalidate = True
                    if frame["type"] == "response" and frame["id"] == 2:
                        got_response = True
                        loc_id = frame["result"]
                    if got_invalidate and got_response:
                        break
                assert got_invalidate and got_response

                await ws.send_json({
                    "id": 3, "type": "mutation",
                    "path": "locations.fullRescan",
                    "input": {"library_id": lid, "location_id": loc_id}})
                while (await ws.receive_json()).get("id") != 3:
                    pass
            await node.jobs.wait_idle()

            # custom_uri: original file with Range
            lib = node.libraries.get(uuid.UUID(lid))
            fp = lib.db.query_one(
                "SELECT id, location_id FROM file_path WHERE name='hello'")
            url = (f"{base}/spacedrive/file/{lid}/"
                   f"{fp['location_id']}/{fp['id']}")
            async with http.get(url) as resp:
                assert resp.status == 200
                body = await resp.read()
                assert body.startswith(b"hello world ")
            async with http.get(
                    url, headers={"Range": "bytes=6-10"}) as resp:
                assert resp.status == 206
                assert await resp.read() == b"world"
                assert resp.headers["Content-Range"].startswith("bytes 6-10/")

            # thumbnail plane
            from spacedrive_tpu.media.thumbnail import generate_thumbnail
            pic = lib.db.query_one(
                "SELECT cas_id FROM file_path WHERE name='pic'")
            generate_thumbnail(f"{corpus}/pic.png", node.data_dir,
                               pic["cas_id"])
            async with http.get(
                    f"{base}/spacedrive/thumbnail/"
                    f"{pic['cas_id']}.webp") as resp:
                assert resp.status == 200
                assert (await resp.read())[:4] == b"RIFF"
        await server.stop()
    _run(main())


def test_ws_teardown_reaps_every_subscription_pump(env):
    """A client holding SEVERAL subscriptions must get all of them
    torn down on disconnect — every EventBus callback unsubscribed and
    every pump drainer reaped — whether the handler exits via a close
    frame or is cancelled by server shutdown. (Regression: teardown
    used to await each pump stop inside the unsub loop, so a
    cancellation mid-loop stranded the remaining subscriptions'
    callbacks and drainers for the node's lifetime.)"""
    node, router, corpus = env

    async def main():
        from spacedrive_tpu import tasks
        from spacedrive_tpu.api.server import ApiServer
        server = ApiServer(node, router)
        port = await server.start(port=0)
        base = f"http://127.0.0.1:{port}"
        subs_before = len(node.events._subs)

        async def open_three(ws):
            for mid in (1, 2, 3):
                await ws.send_json({"id": mid, "type": "subscription",
                                    "path": "invalidation.listen"})
                assert (await asyncio.wait_for(
                    ws.receive_json(), 5))["type"] == "response"
            assert len(node.events._subs) == subs_before + 3

        async def assert_torn_down():
            # the client side races ahead of the server handler's
            # finally, and supervisor records prune in a done-callback
            # — poll briefly before asserting
            for _ in range(100):
                pumps = [r for r in tasks.live(server._owner)
                         if r.name == "ws-pump"]
                if not pumps and len(node.events._subs) == subs_before:
                    break
                await asyncio.sleep(0.05)
            assert len(node.events._subs) == subs_before
            assert not [r for r in tasks.live(server._owner)
                        if r.name == "ws-pump"]

        async with aiohttp.ClientSession() as http:
            # clean close frame
            async with http.ws_connect(f"{base}/rspc") as ws:
                await open_three(ws)
                # duplicate mid is rejected, NOT silently overwritten
                # (an overwrite would strand the first unsub + pump)
                await ws.send_json({"id": 1, "type": "subscription",
                                    "path": "invalidation.listen"})
                frame = await asyncio.wait_for(ws.receive_json(), 5)
                assert frame["type"] == "error"
                assert len(node.events._subs) == subs_before + 3
                # explicit stop tears down that one subscription
                await ws.send_json({"id": 2, "type": "subscriptionStop"})
                for _ in range(100):
                    if len(node.events._subs) == subs_before + 2:
                        break
                    await asyncio.sleep(0.05)
                assert len(node.events._subs) == subs_before + 2
            await assert_torn_down()

            # handler cancelled by server shutdown with the client
            # still connected and holding three subscriptions
            ws = await http.ws_connect(f"{base}/rspc")
            await open_three(ws)
            await server.stop()
            await assert_torn_down()
            await ws.close()
    _run(main())


def test_ts_client_generator_covers_every_procedure():
    """packages/client parity: the generated TS client exposes one
    method per registered procedure with its metadata as JSDoc."""
    from spacedrive_tpu.api.procedures import register_all
    from spacedrive_tpu.api.router import Router
    from tools.gen_ts_client import generate

    router = Router(node=None)
    register_all(router)
    code = generate()
    n_scoped = 0
    for name, proc in list(router.procedures.items()) \
            + list(router.subscriptions.items()):
        assert f"'{name}'" in code, name
        if proc.library_scoped:
            n_scoped += 1
    # a path registered as both query and subscription (node.health)
    # vends two methods, the subscription one suffixed
    assert "node.health" in router.procedures
    assert "node.health" in router.subscriptions
    assert "healthSubscribe" in code
    # every library-scoped procedure carries the JSDoc contract marker
    assert code.count("library-scoped (input.library_id required)") \
        == n_scoped
    assert code.count("this.call") + code.count("this.subscribe") \
        >= len(router.procedures)
    assert "export class SpacedriveClient" in code


def test_auth_device_flow(env):
    """The RFC 8628 state machine (core/src/api/auth.rs:36-174):
    loginSession streams Start{user_code}, polls pending, the user
    approves at the issuer, the token persists into node config,
    auth.me reflects the identity (surviving a config reload), logout
    clears it; a denied session errors without persisting anything."""
    node, router, corpus = env
    from spacedrive_tpu import auth as auth_mod

    async def main():
        with pytest.raises(RpcError):  # logged out
            await router.dispatch("auth.me")

        events = []
        unsub = await router.subscribe(
            "auth.loginSession", {"poll_interval": 0.02}, events.append)
        for _ in range(100):
            await asyncio.sleep(0.01)
            if events:
                break
        assert events and events[0]["state"] == "Start"
        user_code = events[0]["user_code"]
        assert "?user_code=" in events[0]["verification_url_complete"]

        # Polls keep coming back authorization_pending until approval.
        await asyncio.sleep(0.08)
        assert len(events) == 1

        assert node.auth_issuer.approve(user_code, "user-1", "u@x.test")
        for _ in range(200):
            await asyncio.sleep(0.01)
            if len(events) > 1:
                break
        assert events[-1]["state"] == "Complete"
        unsub()

        me = await router.dispatch("auth.me")
        assert me == {"id": "user-1", "email": "u@x.test"}
        # Token persisted: a FRESH config object reads it from disk.
        from spacedrive_tpu.node import NodeConfig
        reloaded = NodeConfig(node.config.path)
        assert reloaded.raw.get("auth_token")["access_token"] == \
            node.config.raw["auth_token"]["access_token"]

        await router.dispatch("auth.logout")
        with pytest.raises(RpcError):
            await router.dispatch("auth.me")
        assert node.config.raw.get("auth_token") is None

        # Denied session → Error, nothing persisted.
        events2 = []
        unsub2 = await router.subscribe(
            "auth.loginSession", {"poll_interval": 0.02}, events2.append)
        for _ in range(100):
            await asyncio.sleep(0.01)
            if events2:
                break
        assert node.auth_issuer.deny(events2[0]["user_code"])
        for _ in range(200):
            await asyncio.sleep(0.01)
            if len(events2) > 1:
                break
        assert events2[-1]["state"] == "Error"
        unsub2()
        with pytest.raises(RpcError):
            await router.dispatch("auth.me")

        # Issuer-side protocol details (expiry + bad grant).
        iss = auth_mod.DeviceFlowIssuer(ttl=0.0)
        dev = iss.device_code("c")
        status, body = iss.access_token(
            auth_mod.DEVICE_CODE_URN, dev["device_code"], "c")
        assert (status, body["error"]) == (400, "expired_token")
        assert iss.access_token("password", "x", "c")[1]["error"] \
            == "unsupported_grant_type"

    _run(main())


def test_orphan_remover_cascades_membership_rows(tmp_path):
    """An orphan object holding tag/album/space memberships must still
    be removed — the raw DELETE FROM object FK-failed on any membership
    row and one failure aborted the WHOLE cleanup batch (round-5 review
    finding on the new album/space tables; tag_on_object had the same
    latent bug)."""
    import uuid as _uuid

    from spacedrive_tpu.node import Node, OrphanRemover

    node = Node(str(tmp_path / "n"))
    lib = node.create_library("orph")
    oid = lib.db.insert("object", {"pub_id": _uuid.uuid4().bytes,
                                   "kind": 5})
    tag = lib.db.insert("tag", {"pub_id": _uuid.uuid4().bytes,
                                "name": "t"})
    lib.db.insert("tag_on_object", {"tag_id": tag, "object_id": oid})
    alb = lib.db.insert("album", {"pub_id": _uuid.uuid4().bytes,
                                  "name": "a"})
    lib.db.insert("object_in_album", {"album_id": alb, "object_id": oid})
    sp = lib.db.insert("space", {"pub_id": _uuid.uuid4().bytes,
                                 "name": "s"})
    lib.db.insert("object_in_space", {"space_id": sp, "object_id": oid})

    removed = OrphanRemover(lib).invoke()
    assert removed == 1
    assert lib.db.query_one("SELECT COUNT(*) AS n FROM object")["n"] == 0
    for t in ("tag_on_object", "object_in_album", "object_in_space"):
        assert lib.db.query_one(
            f"SELECT COUNT(*) AS n FROM {t}")["n"] == 0, t
    # the grouping/tag rows themselves survive
    assert lib.db.query_one("SELECT COUNT(*) AS n FROM album")["n"] == 1


def test_search_objects_windows(tmp_path):
    """search.objects serves absolute skip/take windows with
    server-side order, mirroring search.paths (virtualized views)."""
    import asyncio
    import uuid as _uuid

    from spacedrive_tpu.api.router import mount_router
    from spacedrive_tpu.node import Node

    node = Node(str(tmp_path / "n"))
    router = mount_router(node)
    lib = node.create_library("ow")
    with lib.db.tx() as conn:
        conn.executemany(
            "INSERT INTO object (pub_id, kind, date_created) "
            "VALUES (?, ?, ?)",
            [(_uuid.uuid4().bytes, i % 7, 1_700_000_000 + i)
             for i in range(500)])

    async def go():
        lid = str(lib.id)
        r = await router.dispatch("search.objects", {
            "library_id": lid, "skip": 490, "take": 10})
        assert len(r["items"]) == 10 and r["skip"] == 490
        r2 = await router.dispatch("search.objects", {
            "library_id": lid, "skip": 0, "take": 5,
            "order": {"field": "date_created", "desc": True}})
        assert r2["items"][0]["date_created"] == 1_700_000_499
        n = await router.dispatch("search.objectsCount",
                                  {"library_id": lid, "filter": {}})
        assert n == 500
    asyncio.run(go())
