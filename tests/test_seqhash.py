"""Sequence-parallel single-file BLAKE3 vs the streaming oracle."""

import os

import numpy as np
import pytest

from spacedrive_tpu.ops.blake3_ref import blake3_hex
from spacedrive_tpu.ops.seqhash import make_sharded_checksum
from spacedrive_tpu.parallel.mesh import batch_mesh


@pytest.fixture(scope="module")
def mesh():
    return batch_mesh()


def test_sharded_matches_oracle_across_boundaries(mesh):
    # 8 devices × 4 chunks = 32-chunk capacity; lengths straddle shard
    # and chunk boundaries, including the partial-tail cases.
    fn = make_sharded_checksum(mesh, shard_chunks=4)
    for n in [4097, 8192, 8193, 12288, 20000, 32760, 32768]:
        data = bytes(i % 251 for i in range(n))
        assert fn(data).hex() == blake3_hex(data), f"len={n}"


def test_small_input_falls_back(mesh):
    fn = make_sharded_checksum(mesh, shard_chunks=4)
    for n in [0, 1, 1024, 4096]:  # ≤ one shard
        data = os.urandom(n)
        assert fn(data).hex() == blake3_hex(data), f"len={n}"


def test_capacity_guard(mesh):
    fn = make_sharded_checksum(mesh, shard_chunks=4)
    with pytest.raises(ValueError):
        fn(b"x" * (8 * 4 * 1024 + 1))


def test_shard_chunks_must_be_pow2(mesh):
    with pytest.raises(ValueError):
        make_sharded_checksum(mesh, shard_chunks=3)


def test_multi_megabyte_vs_numpy_reference(mesh):
    """A ~1.5 MiB payload: compare against the (vector-validated) numpy
    batched path rather than the slow pure-Python oracle."""
    from spacedrive_tpu.ops.blake3_batch import blake3_batch_np

    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=1_500_000, dtype=np.uint8).tobytes()
    fn = make_sharded_checksum(mesh, shard_chunks=256)  # 8×256 KiB
    assert fn(data) == blake3_batch_np([data])[0]
