"""Sequence-parallel single-file BLAKE3 vs the streaming oracle."""

import os

import numpy as np
import pytest

from spacedrive_tpu.ops.blake3_ref import blake3_hex
from spacedrive_tpu.ops.seqhash import make_sharded_checksum
from spacedrive_tpu.parallel.mesh import batch_mesh


@pytest.fixture(scope="module")
def mesh():
    return batch_mesh()


def test_sharded_matches_oracle_across_boundaries(mesh):
    # 8 devices × 4 chunks = 32-chunk capacity; lengths straddle shard
    # and chunk boundaries, including the partial-tail cases.
    fn = make_sharded_checksum(mesh, shard_chunks=4)
    for n in [4097, 8192, 8193, 12288, 20000, 32760, 32768]:
        data = bytes(i % 251 for i in range(n))
        assert fn(data).hex() == blake3_hex(data), f"len={n}"


def test_small_input_falls_back(mesh):
    fn = make_sharded_checksum(mesh, shard_chunks=4)
    for n in [0, 1, 1024, 4096]:  # ≤ one shard
        data = os.urandom(n)
        assert fn(data).hex() == blake3_hex(data), f"len={n}"


def test_capacity_guard(mesh):
    fn = make_sharded_checksum(mesh, shard_chunks=4)
    with pytest.raises(ValueError):
        fn(b"x" * (8 * 4 * 1024 + 1))


def test_shard_chunks_must_be_pow2(mesh):
    with pytest.raises(ValueError):
        make_sharded_checksum(mesh, shard_chunks=3)


def test_multi_megabyte_vs_numpy_reference(mesh):
    """A ~1.5 MiB payload: compare against the (vector-validated) numpy
    batched path rather than the slow pure-Python oracle."""
    from spacedrive_tpu.ops.blake3_batch import blake3_batch_np

    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=1_500_000, dtype=np.uint8).tobytes()
    fn = make_sharded_checksum(mesh, shard_chunks=256)  # 8×256 KiB
    assert fn(data) == blake3_batch_np([data])[0]

# -- streaming windows (VERDICT r1 item 7) ----------------------------------


def test_streaming_multi_window_matches_oracle(mesh):
    """A stream spanning several windows (8 dev × 2 chunks = 16 KiB
    window) hashes oracle-exact while never buffering more than one
    window; odd tail, chunk-unaligned."""
    from spacedrive_tpu.ops.seqhash import StreamingShardedChecksum

    data = bytes((i * 7 + 3) % 256 for i in range(5 * 16384 + 777))
    h = StreamingShardedChecksum(mesh, shard_chunks=2)
    # Feed in awkward increments to exercise buffering.
    for off in range(0, len(data), 10_000):
        h.update(data[off:off + 10_000])
        assert len(h._buf) <= h._window_bytes
    assert h.hexdigest() == blake3_hex(data)


@pytest.mark.parametrize("n_windows,extra", [
    (1, 0),       # exactly one window → single-call ROOT path
    (2, 0),       # ends exactly on a window boundary
    (2, 1),       # one byte into the third window
    (3, 1024),    # chunk-aligned tail
    (4, 0),       # power-of-two windows, boundary end
    (5, 16383),   # nearly-full tail window
])
def test_streaming_boundary_cases(mesh, n_windows, extra):
    from spacedrive_tpu.ops.seqhash import StreamingShardedChecksum

    window = 8 * 2 * 1024  # mesh D=8, shard_chunks=2
    data = bytes(i % 251 for i in range(n_windows * window + extra))
    h = StreamingShardedChecksum(mesh, shard_chunks=2)
    h.update(data)
    assert h.hexdigest() == blake3_hex(data)


def test_streaming_small_stream_falls_back(mesh):
    from spacedrive_tpu.ops.seqhash import StreamingShardedChecksum

    for n in [0, 1, 4096]:
        data = os.urandom(n)
        h = StreamingShardedChecksum(mesh, shard_chunks=2)
        h.update(data)
        assert h.hexdigest() == blake3_hex(data)


def test_streaming_counter_bases_are_global(mesh):
    """Two same-bytes windows must produce different tops (chunk counters
    differ) — a regression guard for the counter_base plumbing."""
    from spacedrive_tpu.ops.seqhash import StreamingShardedChecksum

    window = 8 * 2 * 1024
    block = os.urandom(window)
    h = StreamingShardedChecksum(mesh, shard_chunks=2)
    h.update(block + block + b"tail")
    assert h.hexdigest() == blake3_hex(block + block + b"tail")


def test_streaming_file_checksum_bounded_memory(mesh, tmp_path):
    """sharded_file_checksum streams a file bigger than one window."""
    from spacedrive_tpu.ops.seqhash import sharded_file_checksum
    from spacedrive_tpu.ops.blake3_batch import blake3_batch_np

    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=3 * 8 * 256 * 1024 + 12345,
                        dtype=np.uint8).tobytes()  # > 3 windows @ 2 MiB
    p = tmp_path / "big.bin"
    p.write_bytes(data)
    got = sharded_file_checksum(mesh, str(p), shard_chunks=256)
    assert got == blake3_batch_np([data])[0].hex()


def test_validator_jax_backend_streams_checksums(tmp_path):
    """ObjectValidatorJob backend="jax": full-file checksums computed by
    the sequence-sharded streaming path over the CPU mesh, identical to
    the oracle and accepted by verify mode."""
    import asyncio

    import numpy as np

    from spacedrive_tpu.locations.indexer_job import IndexerJob
    from spacedrive_tpu.locations.manager import create_location
    from spacedrive_tpu.node import Node
    from spacedrive_tpu.objects.identifier import FileIdentifierJob
    from spacedrive_tpu.objects.validator import ObjectValidatorJob
    from spacedrive_tpu.ops.blake3_batch import blake3_batch_np

    corpus = tmp_path / "c"
    corpus.mkdir()
    rng = np.random.default_rng(21)
    blobs = {}
    # small.bin + multi.bin are under SMALL_FILE_CAP → the round-5
    # BATCHED dispatch path; huge.bin exceeds the cap, so the
    # sequence-sharded streaming path really runs too.
    for name, size in [("small.bin", 3_000), ("multi.bin", 1_300_000),
                       ("huge.bin", (4 << 20) + 70_000)]:
        data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
        (corpus / name).write_bytes(data)
        blobs[name] = data

    async def scenario():
        node = Node(str(tmp_path / "data"))
        await node.start()
        try:
            lib = node.create_library("v")
            loc = create_location(lib, str(corpus))
            await node.jobs.wait(await node.jobs.ingest(
                lib, IndexerJob(location_id=loc)))
            await node.jobs.wait(await node.jobs.ingest(
                lib, FileIdentifierJob(location_id=loc)))
            await node.jobs.wait(await node.jobs.ingest(
                lib, ObjectValidatorJob(location_id=loc, backend="jax")))
            rows = lib.db.query(
                "SELECT name, extension, integrity_checksum "
                "FROM file_path WHERE is_dir = 0")
            return {f"{r['name']}.{r['extension']}":
                    r["integrity_checksum"] for r in rows}
        finally:
            await node.shutdown()

    got = asyncio.run(scenario())
    for name, data in blobs.items():
        assert got[name] == blake3_batch_np([data])[0].hex(), name
