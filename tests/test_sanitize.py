"""Runtime sanitizer (spacedrive_tpu/sanitize.py): the dynamic half of
sdlint. Tier-1 runs the whole suite under SDTPU_SANITIZE=1 (conftest);
these tests exercise each detector deliberately and then reset the
violation list so the autouse zero-violations fixture stays green.
"""

import asyncio
import threading
import time

import pytest

from spacedrive_tpu import sanitize, telemetry
from spacedrive_tpu.telemetry import SANITIZE_VIOLATIONS


@pytest.fixture
def clean_violations():
    yield
    sanitize.reset_violations()


def test_installed_by_conftest():
    assert sanitize.installed()


def test_tracked_locks_back_the_store(tmp_path):
    from spacedrive_tpu.store.db import Database

    db = Database(str(tmp_path / "t.db"))
    assert getattr(db._write_lock, "name", None) == "db._write_lock"
    assert getattr(db._conns_lock, "name", None) == "db._conns_lock"
    with db.tx():
        assert "db._write_lock" in sanitize.held_tracked_locks()
    assert "db._write_lock" not in sanitize.held_tracked_locks()
    db.close()


def test_lock_order_cycle_raises(clean_violations):
    a = sanitize.tracked_rlock("test_cycle_a")
    b = sanitize.tracked_rlock("test_cycle_b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(sanitize.SanitizerViolation):
            with a:
                pass


def test_cross_instance_same_name_cycle_detected(clean_violations):
    """Two locks SHARING a name (every Database names its write lock
    db._write_lock) are distinct graph nodes: opposite acquisition
    orders across instances is a real AB/BA deadlock and must raise."""
    a = sanitize.tracked_rlock("test_same_name")
    b = sanitize.tracked_rlock("test_same_name")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(sanitize.SanitizerViolation):
            with a:
                pass


def test_reentrant_rlock_is_not_a_cycle():
    lk = sanitize.tracked_rlock("test_reentrant")
    with lk:
        with lk:
            assert sanitize.held_tracked_locks().count("test_reentrant") == 2


def test_lock_across_await_detected(clean_violations):
    lk = sanitize.tracked_lock("test_across_await")

    async def bad():
        lk.acquire()
        try:
            # Two suspension points in one held episode: the detector
            # must report the lock ONCE, not once per loop callback.
            await asyncio.sleep(0.01)
            await asyncio.sleep(0.01)
        finally:
            lk.release()

    asyncio.run(bad())
    hits = [v for v in sanitize.violations()
            if v["kind"] == "lock_across_await"
            and "test_across_await" in v["detail"]]
    assert len(hits) == 1, hits


def test_loop_stall_detected(clean_violations, monkeypatch):
    monkeypatch.setattr(sanitize, "_stall_s", 0.05)
    before = SANITIZE_VIOLATIONS.labels(kind="loop_stall").value

    async def stall():
        time.sleep(0.12)  # blocks the loop past the tightened threshold

    asyncio.run(stall())
    assert any(v["kind"] == "loop_stall" for v in sanitize.violations())
    if telemetry.enabled():
        assert SANITIZE_VIOLATIONS.labels(
            kind="loop_stall").value > before


def test_no_stall_below_threshold():
    before = len(sanitize.violations())

    async def fine():
        await asyncio.sleep(0.01)

    asyncio.run(fine())
    assert len(sanitize.violations()) == before


def test_cross_thread_lock_tracking_is_per_thread():
    lk = sanitize.tracked_lock("test_thread_local")
    seen = []

    def worker():
        seen.append(sanitize.held_tracked_locks())

    with lk:
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen == [[]]  # the other thread holds nothing


# -- device-contract guards (round 10: ops/jit_registry.py) -----------------

def test_jit_registry_armed_by_conftest():
    from spacedrive_tpu.ops import jit_registry

    assert jit_registry.armed()


def test_retrace_budget_counts_and_raises(clean_violations):
    """A registered jit exceeding its declared trace budget is a
    sanitizer violation at the call that crossed it, and every trace
    lands in sd_jit_retraces_total / sd_jit_cache_size."""
    import jax
    import jax.numpy as jnp

    from spacedrive_tpu.ops import jit_registry
    from spacedrive_tpu.telemetry import JIT_CACHE_SIZE, JIT_RETRACES

    with jit_registry.temporary_contract("test.retrace", max_traces=1):

        @jit_registry.tracked("test.retrace")
        @jax.jit
        def f(x):
            return x + 1

        f(jnp.ones(3))                      # trace 1: within budget
        f(jnp.ones(3))                      # cache hit: no new trace
        assert jit_registry.trace_counts()["test.retrace"] == 1
        with pytest.raises(sanitize.SanitizerViolation):
            f(jnp.ones(4))                  # trace 2: budget exceeded
        assert jit_registry.trace_counts()["test.retrace"] == 2
        if telemetry.enabled():
            assert JIT_RETRACES.labels(fn="test.retrace").value == 2
            assert JIT_CACHE_SIZE.labels(fn="test.retrace").value == 2
        hits = [v for v in sanitize.violations()
                if v["kind"] == "jit_retrace_budget"]
        assert hits and "test.retrace" in hits[0]["detail"]


def test_tracked_requires_declared_contract():
    from spacedrive_tpu.ops import jit_registry

    with pytest.raises(KeyError):
        jit_registry.tracked("never.declared.anywhere")


def test_undeclared_io_scope_raises(clean_violations):
    from spacedrive_tpu.ops import jit_registry

    with pytest.raises(sanitize.SanitizerViolation):
        with jit_registry.io("never.declared.anywhere"):
            pass


def test_device_scope_arms_d2h_guard_and_io_lifts_it(monkeypatch):
    """raise mode: device_scope enters JAX's D2H guard at `disallow`;
    a declared io scope re-enters at `allow` and counts the declared
    transfer. (The CPU backend's D2H is zero-copy and never trips the
    real guard, so the wiring is pinned via the cm seam.)"""
    from contextlib import contextmanager

    import jax

    from spacedrive_tpu.ops import jit_registry
    from spacedrive_tpu.telemetry import JIT_DECLARED_TRANSFERS

    levels = []

    @contextmanager
    def fake_guard(level):
        levels.append(level)
        yield

    monkeypatch.setattr(jax, "transfer_guard_device_to_host", fake_guard)
    before = JIT_DECLARED_TRANSFERS.labels(fn="cas.ids").value
    with jit_registry.device_scope("test"):
        pass
    with jit_registry.io("cas.ids"):
        pass
    assert levels == ["disallow", "allow"]
    if telemetry.enabled():
        assert JIT_DECLARED_TRANSFERS.labels(
            fn="cas.ids").value == before + 1


def test_device_scope_records_transfer_guard_error(clean_violations):
    """A transfer-guard error escaping a device scope is recorded as a
    host_transfer violation and re-raised with the original traceback
    (the offending fetch stays visible)."""
    from spacedrive_tpu.ops import jit_registry

    with pytest.raises(RuntimeError, match="transfer"):
        with jit_registry.device_scope("test"):
            raise RuntimeError(
                "Disallowed device-to-host transfer: f32[8]")
    hits = [v for v in sanitize.violations()
            if v["kind"] == "host_transfer"]
    assert hits and "device scope test" in hits[0]["detail"]


def test_transfer_guard_flag_off_disables_scopes(monkeypatch,
                                                 clean_violations):
    from spacedrive_tpu.ops import jit_registry

    monkeypatch.setenv("SDTPU_TRANSFER_GUARD", "off")
    # no jax cm entered, no violation recorded on the error path either
    with pytest.raises(ValueError):
        with jit_registry.device_scope("test"):
            raise ValueError("unrelated")
    assert not [v for v in sanitize.violations()
                if v["kind"] == "host_transfer"]


def test_retrace_guard_flag_off_disables_counting(monkeypatch):
    import jax
    import jax.numpy as jnp

    from spacedrive_tpu.ops import jit_registry

    monkeypatch.setenv("SDTPU_RETRACE_GUARD", "off")
    with jit_registry.temporary_contract("test.retrace_off",
                                         max_traces=1):

        @jit_registry.tracked("test.retrace_off")
        @jax.jit
        def f(x):
            return x * 2

        f(jnp.ones(2))
        f(jnp.ones(5))  # over budget, but the guard is off
        assert "test.retrace_off" not in jit_registry.trace_counts()


def test_violations_surface_in_metrics_snapshot(clean_violations):
    """sd_sanitize_* families are part of the node-wide namespace:
    a recorded violation shows up in telemetry.snapshot() and the
    Prometheus rendering (the production `count`-mode wiring)."""
    if not telemetry.enabled():
        pytest.skip("telemetry disabled in this environment")
    before = SANITIZE_VIOLATIONS.labels(kind="loop_stall").value
    sanitize._record("loop_stall", "synthetic (test)", may_raise=False)
    assert SANITIZE_VIOLATIONS.labels(
        kind="loop_stall").value == before + 1
    snap = telemetry.snapshot()
    assert "sd_sanitize_violations_total" in snap
    assert "sd_sanitize_violations_total" in telemetry.render_prometheus()
