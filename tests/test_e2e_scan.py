"""End-to-end VDFS slice: node → library → location → indexer →
identifier → media processor, with CAS parity against the oracle.

This is SURVEY.md §7's "minimum end-to-end slice" as a test. Hashing uses
the batched numpy backend (same algorithm as the device path; the jax
backend is exercised in test_blake3_jax.py / the driver's compile check).
"""

import asyncio
import os
import uuid

import pytest

from spacedrive_tpu.jobs.report import JobStatus
from spacedrive_tpu.locations.manager import (
    LocationError,
    create_location,
    delete_location,
    scan_location,
)
from spacedrive_tpu.node import Node
from spacedrive_tpu.ops.cas import generate_cas_id
from spacedrive_tpu.files import ObjectKind


def _corpus(root):
    os.makedirs(f"{root}/docs", exist_ok=True)
    os.makedirs(f"{root}/photos", exist_ok=True)
    rng = __import__("random").Random(7)
    # small file (oracle whole-file path)
    with open(f"{root}/docs/small.txt", "wb") as f:
        f.write(bytes(rng.randrange(256) for _ in range(5000)))
    # large file (sampled path) — >100 KiB
    with open(f"{root}/docs/large.bin", "wb") as f:
        f.write(bytes(rng.randrange(256) for _ in range(150_000)))
    # exact duplicate of the large file in another dir
    with open(f"{root}/photos/large_copy.bin", "wb") as f:
        with open(f"{root}/docs/large.bin", "rb") as src:
            f.write(src.read())
    # an empty file (no cas_id, still gets an object)
    open(f"{root}/docs/empty", "wb").close()
    # a real png for the media pass
    from PIL import Image
    Image.new("RGB", (64, 48), (200, 10, 10)).save(f"{root}/photos/red.png")


def _run(coro):
    return asyncio.run(coro)


@pytest.fixture
def env(tmp_path):
    corpus = tmp_path / "corpus"
    corpus.mkdir()
    _corpus(str(corpus))
    node = Node(str(tmp_path / "data"))
    lib = node.create_library("test")
    return node, lib, str(corpus)


def test_full_scan_chain(env):
    node, lib, corpus = env

    async def main():
        loc_id = create_location(lib, corpus)
        await scan_location(node.jobs, lib, loc_id, backend="numpy")
        await node.jobs.wait_idle()
        return loc_id

    loc_id = _run(main())
    db = lib.db

    # All five files indexed (+ 2 dirs + root-less entries).
    files = db.query("SELECT * FROM file_path WHERE is_dir = 0")
    assert len(files) == 5

    # CAS parity with the oracle on every non-empty file.
    for r in files:
        rel = f"{r['materialized_path'][1:]}{r['name']}" + (
            f".{r['extension']}" if r["extension"] else "")
        full = os.path.join(corpus, rel)
        size = os.path.getsize(full)
        if size == 0:
            assert r["cas_id"] is None
        else:
            assert r["cas_id"] == generate_cas_id(full, size), rel

    # Every file got an object; duplicates share one.
    assert all(r["object_id"] is not None for r in files)
    large = db.query_one(
        "SELECT object_id FROM file_path WHERE name = 'large'")
    copy = db.query_one(
        "SELECT object_id FROM file_path WHERE name = 'large_copy'")
    assert large["object_id"] == copy["object_id"]
    objects = db.query("SELECT * FROM object")
    assert len(objects) == 4  # 5 files, 2 sharing one object

    # Kinds resolved: png → IMAGE, txt → TEXT.
    png = db.query_one(
        "SELECT o.kind FROM object o JOIN file_path fp ON fp.object_id=o.id "
        "WHERE fp.name = 'red'")
    assert png["kind"] == int(ObjectKind.IMAGE)
    txt = db.query_one(
        "SELECT o.kind FROM object o JOIN file_path fp ON fp.object_id=o.id "
        "WHERE fp.name = 'small'")
    assert txt["kind"] == int(ObjectKind.TEXT)

    # Media pass: media_data row + sharded webp thumbnail for the png.
    md = db.query_one("SELECT * FROM media_data")
    assert md is not None
    png_row = db.query_one("SELECT cas_id FROM file_path WHERE name='red'")
    from spacedrive_tpu.media.thumbnail import thumbnail_path
    assert os.path.exists(thumbnail_path(node.data_dir, png_row["cas_id"]))

    # Sync ops were emitted for every write path.
    n_ops = db.query_one("SELECT COUNT(*) AS n FROM shared_operation")["n"]
    assert n_ops > len(files)

    # Statistics aggregate.
    stats = lib.statistics()
    assert stats["total_object_count"] == 4
    assert int(stats["total_bytes_used"]) > int(stats["total_unique_bytes"])


def test_rescan_is_idempotent(env):
    node, lib, corpus = env

    async def main():
        loc_id = create_location(lib, corpus)
        await scan_location(node.jobs, lib, loc_id, backend="numpy",
                            with_media=False)
        await node.jobs.wait_idle()
        counts1 = (
            lib.db.query_one("SELECT COUNT(*) AS n FROM file_path")["n"],
            lib.db.query_one("SELECT COUNT(*) AS n FROM object")["n"],
        )
        # Second scan: indexer EarlyFinishes (or no-ops), identifier finds
        # no orphans, nothing duplicates.
        await scan_location(node.jobs, lib, loc_id, backend="numpy",
                            with_media=False)
        await node.jobs.wait_idle()
        counts2 = (
            lib.db.query_one("SELECT COUNT(*) AS n FROM file_path")["n"],
            lib.db.query_one("SELECT COUNT(*) AS n FROM object")["n"],
        )
        assert counts1 == counts2
    _run(main())


def test_validator_job(env):
    node, lib, corpus = env

    async def main():
        loc_id = create_location(lib, corpus)
        await scan_location(node.jobs, lib, loc_id, backend="numpy",
                            with_media=False)
        await node.jobs.wait_idle()
        from spacedrive_tpu.objects.validator import ObjectValidatorJob
        jid = await node.jobs.ingest(
            lib, ObjectValidatorJob(location_id=loc_id))
        status = await node.jobs.wait(jid)
        assert status == JobStatus.COMPLETED
    _run(main())

    from spacedrive_tpu.ops.cas import file_checksum
    rows = lib.db.query(
        "SELECT * FROM file_path WHERE is_dir = 0")
    for r in rows:
        rel = f"{r['materialized_path'][1:]}{r['name']}" + (
            f".{r['extension']}" if r["extension"] else "")
        assert r["integrity_checksum"] == \
            file_checksum(os.path.join(corpus, rel))


def test_orphan_remover(env):
    node, lib, corpus = env

    async def main():
        loc_id = create_location(lib, corpus)
        await scan_location(node.jobs, lib, loc_id, backend="numpy",
                            with_media=False)
        await node.jobs.wait_idle()
        return loc_id
    loc_id = _run(main())
    # Delete the location → file_paths cascade → objects orphaned.
    delete_location(lib, loc_id)
    assert lib.db.query_one("SELECT COUNT(*) AS n FROM file_path")["n"] == 0
    remover = node.orphan_removers[lib.id]
    removed = remover.invoke()
    assert removed == 4
    assert lib.db.query_one("SELECT COUNT(*) AS n FROM object")["n"] == 0


def test_location_overlap_rejected(env):
    node, lib, corpus = env
    create_location(lib, corpus)
    with pytest.raises(LocationError):
        create_location(lib, corpus)
    with pytest.raises(LocationError):
        create_location(lib, os.path.join(corpus, "docs"))


def test_cold_resume_after_kill(env):
    """Pause the identifier mid-run, rebuild node, cold-resume, converge."""
    node, lib, corpus = env

    async def phase1():
        loc_id = create_location(lib, corpus)
        # Index only first.
        from spacedrive_tpu.locations.indexer_job import IndexerJob
        jid = await node.jobs.ingest(lib, IndexerJob(location_id=loc_id))
        await node.jobs.wait(jid)
        # Start identifier and immediately shut down (pauses it).
        from spacedrive_tpu.objects.identifier import FileIdentifierJob
        jid2 = await node.jobs.ingest(
            lib, FileIdentifierJob(location_id=loc_id, backend="numpy"))
        await node.jobs.shutdown()
        return loc_id, jid2

    loc_id, jid2 = _run(phase1())

    # "Process death": fresh Node over the same data dir.
    node2 = Node(node.data_dir)

    async def phase2():
        await node2.start()
        lib2 = node2.libraries.list()[0]
        await node2.jobs.wait_idle()
        return lib2

    lib2 = _run(phase2())
    files = lib2.db.query("SELECT * FROM file_path WHERE is_dir = 0")
    assert len(files) == 5
    assert all(r["object_id"] is not None for r in files)


def test_indexer_spools_steps_and_resumes(tmp_path, monkeypatch):
    """Step payloads live in job_scratch, not in the checkpoint blob
    (SURVEY §7 hard part 3): pausing a big index leaves a SMALL job.data
    (step descriptors only — inline rows measured ~200 MB at 1M files)
    plus scratch rows that survive the pause and are swept on finalize;
    the resumed job completes exactly."""
    import time as _time

    from spacedrive_tpu.locations import indexer_job as ij
    monkeypatch.setattr(ij, "BATCH_SIZE", 100)  # many steps, small corpus
    # Slow each save just enough that the pause deterministically lands
    # mid-run (30 steps x >=10 ms >> the 0.15 s pause delay) — without
    # this, a fast machine can finish before the pause and silently skip
    # the assertions this test exists for.
    real_save = ij.save_file_path_rows

    def slow_save(*a, **kw):
        _time.sleep(0.01)
        return real_save(*a, **kw)

    monkeypatch.setattr(ij, "save_file_path_rows", slow_save)
    corpus = tmp_path / "corpus"
    n_files = 3000
    for d in range(10):
        os.makedirs(corpus / f"d{d}", exist_ok=True)
    for i in range(n_files):
        (corpus / f"d{i % 10}" / f"f{i}.bin").write_bytes(
            i.to_bytes(4, "big") * 50)
    node = Node(str(tmp_path / "data"))

    async def main():
        await node.start()
        lib = node.create_library("spool")
        loc = create_location(lib, str(corpus))
        jid = await node.jobs.ingest(lib, ij.IndexerJob(location_id=loc))
        # Let a couple of steps run, then pause between steps.
        await asyncio.sleep(0.15)
        node.jobs.pause(jid)
        status = await node.jobs.wait(jid)
        assert status == JobStatus.PAUSED  # slow_save guarantees mid-run
        row = lib.db.query_one("SELECT data FROM job WHERE id = ?",
                               (jid,))
        # Descriptors only: ~30 steps x ~30 B, far under the rows'
        # ~500 KB — the bound proves no payload rides the blob.
        assert row["data"] is not None
        assert len(row["data"]) < 50_000, len(row["data"])
        scratch = lib.db.query_one(
            "SELECT COUNT(*) AS n FROM job_scratch WHERE job_id = ?",
            (jid,))["n"]
        assert scratch > 0  # payloads survive the pause for resume
        await node.jobs.resume(lib, jid)
        status = await node.jobs.wait(jid)
        assert status in (JobStatus.COMPLETED,
                          JobStatus.COMPLETED_WITH_ERRORS)
        n = lib.db.query_one(
            "SELECT COUNT(*) AS n FROM file_path WHERE is_dir = 0")["n"]
        assert n == n_files
        left = lib.db.query_one(
            "SELECT COUNT(*) AS n FROM job_scratch")["n"]
        assert left == 0  # consumed per step + swept at finalize
        await node.shutdown()

    _run(main())


def test_cancel_paused_index_sweeps_scratch(tmp_path, monkeypatch):
    """Cancelling a PAUSED job never reaches the worker's cleanup hook —
    the manager must sweep the spooled payloads itself or a cancelled
    paused index leaks its scratch blobs until the job row is cleared."""
    import time as _time

    from spacedrive_tpu.locations import indexer_job as ij
    monkeypatch.setattr(ij, "BATCH_SIZE", 100)
    real_save = ij.save_file_path_rows

    def slow_save(*a, **kw):
        _time.sleep(0.01)
        return real_save(*a, **kw)

    monkeypatch.setattr(ij, "save_file_path_rows", slow_save)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus)
    for i in range(3000):
        (corpus / f"f{i}.bin").write_bytes(i.to_bytes(4, "big") * 10)
    node = Node(str(tmp_path / "data"))

    async def main():
        await node.start()
        lib = node.create_library("sweep")
        loc = create_location(lib, str(corpus))
        jid = await node.jobs.ingest(lib, ij.IndexerJob(location_id=loc))
        await asyncio.sleep(0.15)
        node.jobs.pause(jid)
        assert await node.jobs.wait(jid) == JobStatus.PAUSED
        assert lib.db.query_one(
            "SELECT COUNT(*) AS n FROM job_scratch WHERE job_id = ?",
            (jid,))["n"] > 0
        node.jobs.cancel(jid)
        assert lib.db.query_one(
            "SELECT COUNT(*) AS n FROM job_scratch WHERE job_id = ?",
            (jid,))["n"] == 0
        await node.shutdown()

    _run(main())


def test_mass_removals_spool_to_scratch_not_checkpoint(tmp_path, monkeypatch):
    """Deferred removals ride job_scratch (keyed by job_id, consumed in
    finalize), NOT data['pending_removals']: a mass-removal rescan must
    not regrow the crash-checkpoint blob toward the inline-rows problem
    the spooling fixed for save/update steps (ADVICE r5). The paused
    checkpoint carries only scratch-row ids; finalize applies the
    removals and consumes the rows."""
    import time as _time

    import msgpack

    from spacedrive_tpu.locations import indexer_job as ij
    monkeypatch.setattr(ij, "BATCH_SIZE", 100)
    real_save = ij.save_file_path_rows

    def slow_save(*a, **kw):
        _time.sleep(0.01)
        return real_save(*a, **kw)

    monkeypatch.setattr(ij, "save_file_path_rows", slow_save)
    corpus = tmp_path / "corpus"
    os.makedirs(corpus / "doomed")
    os.makedirs(corpus / "kept")
    for i in range(1200):
        (corpus / "doomed" / f"f{i}.bin").write_bytes(
            i.to_bytes(4, "big") * 10)
    node = Node(str(tmp_path / "data"))

    async def main():
        await node.start()
        lib = node.create_library("removals")
        loc = create_location(lib, str(corpus))
        jid = await node.jobs.ingest(
            lib, ij.IndexerJob(location_id=loc))
        assert await node.jobs.wait(jid) in (
            JobStatus.COMPLETED, JobStatus.COMPLETED_WITH_ERRORS)
        assert lib.db.query_one(
            "SELECT COUNT(*) AS n FROM file_path WHERE is_dir = 0"
        )["n"] == 1200

        # rm -rf the subtree, add fresh files (so the rescan has save
        # steps to pause inside), rescan and pause mid-run.
        import shutil
        shutil.rmtree(corpus / "doomed")
        for i in range(1200):
            (corpus / "kept" / f"g{i}.bin").write_bytes(
                i.to_bytes(4, "big") * 10)
        jid2 = await node.jobs.ingest(
            lib, ij.IndexerJob(location_id=loc))
        await asyncio.sleep(0.15)
        node.jobs.pause(jid2)
        assert await node.jobs.wait(jid2) == JobStatus.PAUSED
        state = msgpack.unpackb(
            lib.db.query_one("SELECT data FROM job WHERE id = ?",
                             (jid2,))["data"], raw=False)
        # The checkpoint carries scratch IDS, not removal payloads.
        assert state["data"]["pending_removals"] == []
        sids = state["data"]["removal_scratch"]
        assert sids and all(isinstance(s, int) for s in sids)
        n_payload = lib.db.query_one(
            "SELECT COUNT(*) AS n FROM job_scratch WHERE job_id = ?",
            (jid2,))["n"]
        assert n_payload >= len(sids)

        await node.jobs.resume(lib, jid2)
        assert await node.jobs.wait(jid2) in (
            JobStatus.COMPLETED, JobStatus.COMPLETED_WITH_ERRORS)
        # finalize applied the removals and consumed the scratch rows
        assert lib.db.query_one(
            "SELECT COUNT(*) AS n FROM file_path WHERE is_dir = 0"
        )["n"] == 1200
        assert lib.db.query_one(
            "SELECT COUNT(*) AS n FROM file_path "
            "WHERE materialized_path LIKE '/doomed/%'")["n"] == 0
        assert lib.db.query_one(
            "SELECT COUNT(*) AS n FROM job_scratch")["n"] == 0
        await node.shutdown()

    _run(main())


def test_pure_removal_rescan_still_removes(tmp_path):
    """A rescan whose ONLY work is removals (nothing new to index) must
    not EarlyFinish past finalize — the spooled removals apply and the
    stale rows go away."""
    corpus = tmp_path / "corpus"
    os.makedirs(corpus / "doomed")
    (corpus / "keep.bin").write_bytes(b"k" * 256)
    for i in range(30):
        (corpus / "doomed" / f"f{i}.bin").write_bytes(b"x" * 64)
    node = Node(str(tmp_path / "data"))

    async def main():
        from spacedrive_tpu.locations.indexer_job import IndexerJob
        await node.start()
        lib = node.create_library("pure-removal")
        loc = create_location(lib, str(corpus))
        jid = await node.jobs.ingest(lib, IndexerJob(location_id=loc))
        assert await node.jobs.wait(jid) in (
            JobStatus.COMPLETED, JobStatus.COMPLETED_WITH_ERRORS)
        import shutil
        shutil.rmtree(corpus / "doomed")
        jid2 = await node.jobs.ingest(lib, IndexerJob(location_id=loc))
        assert await node.jobs.wait(jid2) in (
            JobStatus.COMPLETED, JobStatus.COMPLETED_WITH_ERRORS)
        rows = lib.db.query(
            "SELECT materialized_path, name, is_dir FROM file_path "
            "WHERE is_dir = 0")
        assert [(r["materialized_path"], r["name"]) for r in rows] == \
            [("/", "keep")]
        assert lib.db.query_one(
            "SELECT COUNT(*) AS n FROM job_scratch")["n"] == 0
        await node.shutdown()

    _run(main())
