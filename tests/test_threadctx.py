"""threadctx: the ownership registry's runtime twin + the threaded
stress suite (round 13).

Covers: the armed write recorder (seeded cross-thread race caught,
guarded/sanctioned shapes quiet), container wraps, the hardened
call_threadsafe hand-off, static↔runtime registry drift, and the
satellite stress tests — N-thread telemetry increments with exact
totals and concurrent shed-channel puts with a monotone per-NAME
high-water (the PR 7 peak-fix regression)."""

import ast
import asyncio
import os
import threading

import pytest

from spacedrive_tpu import channels, sanitize, telemetry, threadctx
from spacedrive_tpu.telemetry import (
    CHAN_HIGH_WATER,
    RACE_CANDIDATES,
    RACE_HANDOFF_CLOSED,
    RACE_TRACKED_WRITES,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_violations():
    yield
    sanitize.reset_violations()


def test_armed_by_conftest():
    assert threadctx.armed()
    names = {c.__name__ for c in threadctx.armed_classes()}
    assert {"PipelineStats", "Counter", "Histogram", "Database",
            "SyncManager", "HLC"} <= names, names


# -- the seeded race: a real cross-thread unguarded += is caught ------------

class _Seeded:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0


def test_seeded_unguarded_race_raises(clean_violations):
    """The PR 8 shape at runtime: two threads bumping a guarded attr
    with no lock — empty lockset intersection → data_race."""
    with threadctx.temporary_owner(
            _Seeded, n=threadctx.guarded_by("_lock")):
        obj = _Seeded()
        obj.n += 1  # single-thread rebind: tracked, quiet
        caught = []
        # Barrier: both writers must be ALIVE concurrently — a thread
        # that exits before the other starts can hand its pthread
        # ident to the successor, and the recorder (correctly) sees
        # one thread.
        barrier = threading.Barrier(2)

        def bump():
            try:
                barrier.wait()
                for _ in range(50):
                    obj.n += 1
            except sanitize.SanitizerViolation as e:
                caught.append(e)

        threads = [threading.Thread(target=bump) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert caught, "cross-thread bare += must raise data_race"
        assert "data_race" in str(caught[0])
    hits = [v for v in sanitize.violations()
            if v["kind"] == "data_race" and "_Seeded.n" in v["detail"]]
    assert hits
    if telemetry.enabled():
        assert RACE_CANDIDATES.labels(
            cls_attr="_Seeded.n").value >= 1


def test_guarded_writes_from_threads_are_quiet():
    """The same shape done right — every writer holds the declared
    guard — records tracked writes and raises nothing."""
    with threadctx.temporary_owner(
            _Seeded, n=threadctx.guarded_by("_lock")):
        obj = _Seeded()
        before = RACE_TRACKED_WRITES.value

        def bump():
            for _ in range(200):
                with obj._lock:
                    obj.n += 1

        threads = [threading.Thread(target=bump) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert obj.n == 800  # 4 threads x 200: nothing lost
        if telemetry.enabled():
            assert RACE_TRACKED_WRITES.value > before
    assert not [v for v in sanitize.violations()
                if v["kind"] == "data_race"]


class _LoopOwned:
    def __init__(self):
        self.state = "idle"


def test_second_thread_on_single_thread_attr_raises(clean_violations):
    with threadctx.temporary_owner(
            _LoopOwned, state=threadctx.single_thread()):
        obj = _LoopOwned()
        obj.state = "main"  # first rebind: owner thread established

        def other():
            try:
                obj.state = "intruder"
            except sanitize.SanitizerViolation:
                other.caught = True

        other.caught = False
        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert other.caught


class _Frozen:
    def __init__(self):
        self.shape = (1, 2)


def test_immutable_after_init_write_raises(clean_violations):
    with threadctx.temporary_owner(
            _Frozen, shape=threadctx.immutable_after_init()):
        obj = _Frozen()
        with pytest.raises(sanitize.SanitizerViolation):
            obj.shape = (3, 4)


class _Tally:
    def __init__(self):
        self.hits = 0


def test_atomic_counter_multi_thread_is_waived():
    """atomic_counter is the declared, visible waiver: counted, never
    raised — a lost update skews a statistic, not state."""
    with threadctx.temporary_owner(
            _Tally, hits=threadctx.atomic_counter()):
        obj = _Tally()
        obj.hits += 1

        def bump():
            for _ in range(100):
                obj.hits += 1

        threads = [threading.Thread(target=bump) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not [v for v in sanitize.violations()
                if v["kind"] == "data_race"]


class _Listy:
    def __init__(self):
        self._lock = threading.Lock()
        self.samples = []


def test_container_mutations_are_recorded(clean_violations):
    """Declared list attrs are wrapped: bare .append from two threads
    is a data_race even though __setattr__ never fires."""
    with threadctx.temporary_owner(
            _Listy, samples=threadctx.guarded_by("_lock")):
        obj = _Listy()
        assert type(obj.samples).__name__ == "_TrackedList"
        caught = []
        barrier = threading.Barrier(2)  # overlap: see the seeded test

        def push():
            try:
                barrier.wait()
                for i in range(50):
                    obj.samples.append(i)
            except sanitize.SanitizerViolation as e:
                caught.append(e)

        threads = [threading.Thread(target=push) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert caught, "bare container mutation must be recorded"


# -- call_threadsafe: the hardened hand-off ---------------------------------

def test_call_threadsafe_posts_to_live_loop():
    hits = []

    async def main():
        loop = asyncio.get_running_loop()

        def from_thread():
            assert threadctx.call_threadsafe(loop, hits.append, 1)

        t = threading.Thread(target=from_thread)
        t.start()
        t.join()
        await asyncio.sleep(0.05)

    asyncio.run(main())
    assert hits == [1]


def test_call_threadsafe_tolerates_closed_loop():
    loop = asyncio.new_event_loop()
    loop.close()
    before = RACE_HANDOFF_CLOSED.value
    assert threadctx.call_threadsafe(loop, lambda: None) is False
    assert threadctx.call_threadsafe(None, lambda: None) is False
    if telemetry.enabled():
        assert RACE_HANDOFF_CLOSED.value == before + 2


def test_call_threadsafe_reraises_other_runtime_errors():
    class _FakeLoop:
        def is_closed(self):
            return False

        def call_soon_threadsafe(self, cb, *args):
            raise RuntimeError("something else entirely")

    with pytest.raises(RuntimeError, match="something else"):
        threadctx.call_threadsafe(_FakeLoop(), lambda: None)


# -- static <-> runtime drift -----------------------------------------------

def test_registry_static_runtime_drift():
    """The AST-parsed owner table and the runtime registry cannot
    drift: same names, same sites, same attr kinds and locks (the
    jit/channel/timeout drift check, for ownership)."""
    from tools.sdlint.passes._threads import declared_owners_from_tree

    central = os.path.join(ROOT, "spacedrive_tpu", "threadctx.py")
    static = declared_owners_from_tree(
        ast.parse(open(central, encoding="utf-8").read()))
    assert set(static) == set(threadctx.CONTRACTS)
    for name, spec in static.items():
        runtime = threadctx.CONTRACTS[name]
        assert spec["site"] == runtime.site, name
        static_attrs = {a: kind_lock
                       for a, kind_lock in spec["attrs"].items()}
        runtime_attrs = {a: (c.kind, c.lock)
                         for a, c in runtime.attrs.items()}
        assert static_attrs == runtime_attrs, name


def test_every_declared_class_is_constructed_and_armed():
    """Contracts must point at live code: every declared site resolves
    to a class the sanitizer actually WRAPPED at install, and that
    class (or a subclass) is constructed somewhere in the tree — a
    dead contract is a silently-unchecked contract."""
    from tools.sdlint.core import dotted, load_project

    armed_names = {c.__name__ for c in threadctx.armed_classes()}
    project = load_project(ROOT)
    constructed = set()
    subclasses = {}
    for src in project.files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is not None:
                    constructed.add(d.rsplit(".", 1)[-1])
                # factory idiom: `_get_or_create(Counter, ...)`
                # constructs via the class ARGUMENT
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    ad = dotted(arg)
                    if ad is not None:
                        constructed.add(ad.rsplit(".", 1)[-1])
            elif isinstance(node, ast.ClassDef):
                for b in node.bases:
                    bd = dotted(b)
                    if bd is not None:
                        subclasses.setdefault(
                            bd.rsplit(".", 1)[-1], set()).add(node.name)

    def constructed_somewhere(cls_name, seen=None):
        seen = seen or set()
        if cls_name in seen:
            return False
        seen.add(cls_name)
        if cls_name in constructed:
            return True
        return any(constructed_somewhere(sub, seen)
                   for sub in subclasses.get(cls_name, ()))

    for name, oc in threadctx.CONTRACTS.items():
        cls_name = oc.site.split("::", 1)[1]
        assert cls_name in armed_names, (
            f"contract {name!r}: class {cls_name!r} not armed")
        assert constructed_somewhere(cls_name), (
            f"contract {name!r}: {cls_name!r} (and no subclass) is "
            "ever constructed in the tree — prune or adopt it")


# -- satellite stress: telemetry exact totals under threads -----------------

def test_telemetry_counter_exact_totals_under_threads():
    """N threads x M increments land exactly — the per-metric leaf
    lock loses nothing — and the armed race recorder stays quiet
    (the autouse conftest fixture asserts zero new violations)."""
    c = telemetry.REGISTRY.counter("sd_race_stress_counter_total")
    h = telemetry.REGISTRY.histogram(
        "sd_race_stress_hist_seconds", buckets=(0.5, 1.5, 2.5))
    n_threads, n_iters = 8, 2000
    barrier = threading.Barrier(n_threads)

    def work():
        barrier.wait()
        for _ in range(n_iters):
            c.inc()
            h.observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if telemetry.enabled():
        assert c.value == n_threads * n_iters
        assert h.count == n_threads * n_iters
        assert h.sum == float(n_threads * n_iters)
        # every observation landed in the 1.5 bucket exactly
        sample = h._sample()
        assert sample["buckets"][1] == [1.5, n_threads * n_iters]


# -- satellite stress: shed channel under concurrent put_nowait -------------

def test_shed_channel_concurrent_put_accounting():
    """Concurrent put_nowait on a shed_new channel: delivered + shed
    == attempts exactly, and the per-NAME high-water gauge is monotone
    across the storm AND across instance churn (the PR 7 peak fix)."""
    chan = channels.channel("bench.shed")
    shed_before = chan.shed_total
    n_threads, n_iters = 6, 500
    delivered = [0] * n_threads
    barrier = threading.Barrier(n_threads)
    hw_samples = []
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            hw_samples.append(
                CHAN_HIGH_WATER.labels(name="bench.shed").value)

    def work(idx):
        barrier.wait()
        for i in range(n_iters):
            if chan.put_nowait((idx, i)):
                delivered[idx] += 1

    sam = threading.Thread(target=sampler)
    sam.start()
    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    sam.join()

    attempts = n_threads * n_iters
    shed = chan.shed_total - shed_before
    if telemetry.enabled():
        assert sum(delivered) + shed == attempts
        assert len(chan) == sum(delivered)
        # monotone while sampled mid-storm
        assert all(a <= b for a, b in zip(hw_samples, hw_samples[1:]))
        # instance churn cannot regress the per-NAME peak
        peak = CHAN_HIGH_WATER.labels(name="bench.shed").value
        assert peak >= len(chan)
        fresh = channels.channel("bench.shed")
        fresh.put_nowait("tiny")
        assert CHAN_HIGH_WATER.labels(
            name="bench.shed").value == peak


def test_overlap_stats_guarded_increment_quiet():
    """The real PipelineStats contract end-to-end: cross-thread
    guarded increments record quietly; the declared samples list is
    container-tracked."""
    from spacedrive_tpu.ops.overlap import PipelineStats

    stats = PipelineStats()
    assert type(stats.samples).__name__ == "_TrackedList"

    def stream():
        for _ in range(100):
            with stats._lock:
                stats.h2d_bytes += 4096
                stats.samples.append((0.1, 0.2, 0.3))

    threads = [threading.Thread(target=stream) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert stats.h2d_bytes == 3 * 100 * 4096
    assert len(stats.samples) == 300
    assert not [v for v in sanitize.violations()
                if v["kind"] == "data_race"]
