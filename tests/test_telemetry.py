"""Node-wide telemetry: registry semantics, thread safety, overhead
budget, Prometheus exposition, /metrics endpoint, span hierarchy over a
real job run, snapshot events, and the namespace lint."""

import asyncio
import concurrent.futures
import os
import sys
import threading
import time

import pytest

from spacedrive_tpu import telemetry
from spacedrive_tpu.store import Database
from spacedrive_tpu.telemetry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

try:
    # Seed the objects package: in runtimes without `cryptography` the
    # first attempt fails but leaves the non-crypto submodules cached,
    # after which mount_router imports cleanly (container quirk; no-op
    # where the dependency exists).
    import spacedrive_tpu.objects  # noqa: F401
except ModuleNotFoundError:
    pass


def _run(coro):
    return asyncio.run(coro)


# -- registry semantics ------------------------------------------------------

def test_get_or_create_and_collisions():
    reg = MetricsRegistry()
    c1 = reg.counter("sd_store_x_total", "help")
    assert reg.counter("sd_store_x_total") is c1  # same spec: same object
    with pytest.raises(ValueError):
        reg.gauge("sd_store_x_total")  # kind collision
    with pytest.raises(ValueError):
        reg.counter("sd_store_x_total", labelnames=("a",))  # label collision


def test_labels_vend_cached_children():
    reg = MetricsRegistry()
    c = reg.counter("sd_jobs_l_total", labelnames=("status",))
    a = c.labels(status="done")
    assert c.labels(status="done") is a
    a.inc(3)
    c.labels(status="failed").inc()
    snap = c.snapshot_value()
    by = {e["labels"]["status"]: e["value"] for e in snap["labeled"]}
    assert by == {"done": 3, "failed": 1}
    with pytest.raises(ValueError):
        c.labels(nope="x")


def test_histogram_buckets_and_sum():
    reg = MetricsRegistry()
    h = reg.histogram("sd_store_h_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    s = h.snapshot_value()
    assert s["count"] == 4 and abs(s["sum"] - 55.55) < 1e-6
    assert s["buckets"] == [[0.1, 1], [1.0, 2], [10.0, 3], ["+Inf", 4]]


# -- thread safety (satellite: no lost updates, no deadlock) -----------------

def test_concurrent_increments_no_lost_updates():
    reg = MetricsRegistry()
    c = reg.counter("sd_jobs_conc_total")
    h = reg.histogram("sd_jobs_conc_seconds", buckets=(0.5,))
    n_threads, per_thread = 8, 5000

    def work():
        for _ in range(per_thread):
            c.inc()
            h.observe(0.25)

    with concurrent.futures.ThreadPoolExecutor(n_threads) as pool:
        list(pool.map(lambda _: work(), range(n_threads)))
    assert c.value == n_threads * per_thread
    assert h.count == n_threads * per_thread
    assert h.snapshot_value()["buckets"][0][1] == n_threads * per_thread


def test_increments_inside_store_write_lock_no_deadlock(tmp_path):
    """Thread-pool workers increment metrics while holding the store
    write lock (exactly what instrumented job steps do); a snapshot
    reader runs concurrently. Must finish without deadlock or loss."""
    db = Database(tmp_path / "t.db")
    c = telemetry.REGISTRY.counter("sd_store_locktest_total")
    base = c.value
    stop = threading.Event()

    def snapshot_reader():
        while not stop.is_set():
            telemetry.snapshot()
            telemetry.render_prometheus()

    def writer(i):
        for k in range(20):
            with db.tx() as conn:
                conn.execute(
                    "INSERT INTO tag (pub_id, name) VALUES (?, ?)",
                    (os.urandom(16), f"t{i}-{k}"))
                c.inc()

    reader = threading.Thread(target=snapshot_reader, daemon=True)
    reader.start()
    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "writer deadlocked"
    stop.set()
    reader.join(timeout=10)
    assert c.value - base == 6 * 20
    db.close()


# -- overhead budget (satellite regression test) -----------------------------

def test_disabled_path_overhead_budget():
    """The disabled hot path must stay one flag check — budget 5 µs/call
    (typical ~0.1 µs; the budget absorbs container scheduling noise while
    still catching a regression to per-call env reads or lock grabs)."""
    c = telemetry.REGISTRY.counter("sd_jobs_budget_total")
    n = 100_000
    telemetry.set_enabled(False)
    try:
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
        per_call = (time.perf_counter() - t0) / n
        assert c.value == 0  # disabled increments are dropped
    finally:
        telemetry.set_enabled(True)
    assert per_call < 5e-6, f"disabled inc() costs {per_call * 1e6:.2f} µs"
    c.inc()
    assert c.value == 1  # re-enabled path records again


# -- Prometheus exposition ---------------------------------------------------

def test_render_prometheus_golden():
    reg = MetricsRegistry()
    reg.counter("sd_api_g_total", "requests").inc(3)
    lab = reg.counter("sd_jobs_g_total", labelnames=("status",))
    lab.labels(status="completed").inc(2)
    g = reg.gauge("sd_jobs_g_running")
    g.set(1.5)
    h = reg.histogram("sd_store_g_seconds", "lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(3.0)
    assert reg.render_prometheus() == (
        "# HELP sd_api_g_total requests\n"
        "# TYPE sd_api_g_total counter\n"
        "sd_api_g_total 3\n"
        "# TYPE sd_jobs_g_running gauge\n"
        "sd_jobs_g_running 1.5\n"
        "# TYPE sd_jobs_g_total counter\n"
        'sd_jobs_g_total{status="completed"} 2\n'
        "# HELP sd_store_g_seconds lat\n"
        "# TYPE sd_store_g_seconds histogram\n"
        'sd_store_g_seconds_bucket{le="0.1"} 1\n'
        'sd_store_g_seconds_bucket{le="1"} 1\n'
        'sd_store_g_seconds_bucket{le="+Inf"} 2\n'
        "sd_store_g_seconds_sum 3.05\n"
        "sd_store_g_seconds_count 2\n"
    )


def test_metrics_endpoint_content_type_and_format(tmp_path):
    """GET /metrics serves the process registry in Prometheus text
    format with the exposition content type, covering every subsystem
    the acceptance criteria name (p2p arrives via central registration
    even when the tunnel's crypto dependency is absent)."""
    import aiohttp

    from spacedrive_tpu.api.server import ApiServer
    from spacedrive_tpu.node import Node

    async def main():
        node = Node(str(tmp_path / "data"))
        node.create_library("metrics")  # guarantees live tx() traffic
        server = ApiServer(node)
        port = await server.start(port=0)
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        f"http://127.0.0.1:{port}/metrics") as resp:
                    assert resp.status == 200
                    assert resp.headers["Content-Type"] == \
                        "text/plain; version=0.0.4; charset=utf-8"
                    body = await resp.text()
        finally:
            await server.stop()
            await node.shutdown()
        assert "# TYPE sd_store_tx_total counter" in body
        for family in ("sd_jobs_ingested_total",
                       "sd_identifier_batches_total",
                       "sd_sync_ops_encoded_total",
                       "sd_p2p_tunnel_bytes_sent_total",
                       "sd_store_commit_seconds_bucket",
                       "sd_api_requests_total"):
            assert family in body, family
        # The store booted this node's DB, so tx count is live already.
        line = [ln for ln in body.splitlines()
                if ln.startswith("sd_store_tx_total ")][0]
        assert float(line.split()[1]) > 0
    _run(main())


def test_node_metrics_and_spans_queries(tmp_path):
    from spacedrive_tpu.api.router import mount_router
    from spacedrive_tpu.node import Node
    from spacedrive_tpu.tracing import span

    node = Node(str(tmp_path / "data"))
    router = mount_router(node)

    async def main():
        snap = await router.dispatch("node.metrics")
        assert snap["sd_store_tx_total"]["kind"] == "counter"
        assert snap["sd_store_tx_total"]["value"] > 0
        with span("rspc.probe"):
            pass
        spans = await router.dispatch("node.spans", {"limit": 5})
        assert any(s["span"] == "rspc.probe" and s["ok"] for s in spans)
    _run(main())
    _run(node.shutdown())


# -- span hierarchy across a real job run (satellite test) -------------------

def test_trace_propagates_across_job_run(tmp_path):
    from spacedrive_tpu.jobs import (
        JobManager,
        StatefulJob,
        StepOutcome,
        register_job,
    )
    from spacedrive_tpu.tracing import clear_span_ring, recent_spans, span

    @register_job
    class TelemetryProbeJob(StatefulJob):
        NAME = "telemetry_probe"

        async def init(self, ctx):
            return {}, [1, 2, 3]

        async def execute_step(self, ctx, data, step, step_number):
            with span("probe.work", step=step):
                if step == 2:
                    raise ValueError("boom")  # non-fatal step error
            return StepOutcome()

    class FakeLibrary:
        def __init__(self, db):
            self.db = db

    lib = FakeLibrary(Database(tmp_path / "lib.db"))
    clear_span_ring()

    async def main():
        m = JobManager()
        jid = await m.ingest(lib, TelemetryProbeJob())
        await m.wait(jid)
    _run(main())

    spans = recent_spans(limit=100)
    roots = [s for s in spans if s["span"] == "job/telemetry_probe"]
    assert len(roots) == 1 and roots[0]["ok"] and "parent" not in roots[0]
    root = roots[0]
    steps = [s for s in spans if s["span"] == "job.step"]
    assert len(steps) == 3
    for s in steps:
        # every step nests under the SAME trace, parented on the root —
        # across ensure_future and the job driver's select loop
        assert s["trace"] == root["trace"]
        assert s["parent"] == root["id"]
    works = [s for s in spans if s["span"] == "probe.work"]
    assert len(works) == 3
    by_step = {s["step"]: s for s in works}
    assert by_step[1]["ok"] and by_step[3]["ok"]
    # the raising body is distinguishable (satellite bugfix: ok/error)
    assert not by_step[2]["ok"] and by_step[2]["error"] == "ValueError"
    assert all(s["parent"] in {x["id"] for x in steps} for s in works)


# -- snapshot events ---------------------------------------------------------

def test_telemetry_reporter_emits_snapshots():
    from spacedrive_tpu.node import EventBus, TelemetryReporter

    async def main():
        bus = EventBus()
        got = []
        bus.subscribe(got.append)
        rep = TelemetryReporter(bus, interval_s=0.05)
        rep.start()
        await asyncio.sleep(0.25)
        rep.stop()
        snaps = [e for e in got if e["type"] == "TelemetrySnapshot"]
        assert snaps, "no TelemetrySnapshot events emitted"
        assert snaps[0]["metrics"]["sd_store_tx_total"]["kind"] == "counter"
    _run(main())


# -- namespace lint (CI satellite) -------------------------------------------

def test_telemetry_lint_package_clean():
    from tools.telemetry_lint import run_lint

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "spacedrive_tpu")
    assert run_lint(pkg) == []


def test_telemetry_lint_catches_violations(tmp_path):
    from tools.telemetry_lint import run_lint

    pkg = tmp_path / "fakepkg"
    pkg.mkdir()
    (pkg / "telemetry.py").write_text(
        "def counter(name, help=''):\n    return None\n\n\n"
        "A = counter('sd_jobs_a_total')\n"
        "B = counter('sd_jobs_a_total')\n"      # collision
        "C = counter('bad_name_total')\n"       # scheme violation
        "NAME = 'sd_jobs_dyn_total'\n"
        "D = counter(NAME)\n")                  # non-literal
    (pkg / "rogue.py").write_text(
        "from .telemetry import counter\n"
        "from spacedrive_tpu.telemetry import Counter\n"
        "R = counter('sd_jobs_rogue_total')\n"  # outside central registry
        "S = Counter('sd_jobs_raw_total')\n")   # direct instantiation
    (pkg / "innocent.py").write_text(
        "def counter():\n    return 1\n\n\n"
        "x = counter()\n")                      # unrelated local counter()
    problems = run_lint(str(pkg))
    text = "\n".join(problems)
    assert "collision" in text
    assert "naming scheme" in text
    assert "string literal" in text
    assert text.count("outside the central registry") == 2
    assert "innocent.py" not in text


# -- metric classes stay importable for tooling ------------------------------

def test_metric_kinds():
    assert Counter("sd_api_k_total").kind == "counter"
    assert Gauge("sd_api_k_g").kind == "gauge"
    assert Histogram("sd_api_k_h", buckets=(1,)).kind == "histogram"
