"""MJPEG-AVI container plane: the video-thumbnail path executing for
real (VERDICT r1 missing #5 — the ffmpeg path had never run in this
image; MJPEG needs no codec, only RIFF parsing: media/mjpeg.py)."""

import io
import os

import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from spacedrive_tpu.media.mjpeg import (  # noqa: E402
    frame_at_fraction, index_frames, write_mjpeg_avi)


def _clip(tmp_path, n=20, size=(320, 240)):
    frames = [Image.new("RGB", size, (i * 12, 60, max(0, 200 - i * 8)))
              for i in range(n)]
    p = tmp_path / "clip.avi"
    write_mjpeg_avi(str(p), frames, fps=10)
    return p


def test_writer_reader_roundtrip(tmp_path):
    p = _clip(tmp_path)
    idx = index_frames(str(p))
    assert len(idx) == 20
    # every frame is a standalone JPEG PIL can decode
    with open(p, "rb") as f:
        for off, size in idx:
            f.seek(off)
            with Image.open(io.BytesIO(f.read(size))) as im:
                assert im.size == (320, 240)


def test_frame_at_ten_percent_matches_reference_seek(tmp_path):
    """thumbnailer.rs seeks 10% of the stream; frame 2 of 20 carries the
    planted color ramp value."""
    p = _clip(tmp_path)
    j = frame_at_fraction(str(p), 0.10)
    with Image.open(io.BytesIO(j)) as im:
        assert abs(im.getpixel((10, 10))[0] - 24) < 16  # i=2 → r=24


def test_thumbnail_pipeline_executes_video(tmp_path):
    from spacedrive_tpu.media.thumbnail import (
        THUMBNAILABLE_EXTENSIONS, generate_thumbnail)

    assert "avi" in THUMBNAILABLE_EXTENSIONS
    p = _clip(tmp_path)
    out = generate_thumbnail(str(p), str(tmp_path / "data"),
                             "aa" + "1" * 14)
    assert out is not None and out.endswith(".webp")
    with Image.open(out) as t:
        assert t.format == "WEBP" and t.size == (320, 240)


def test_non_mjpeg_avi_degrades(tmp_path):
    """A RIFF/AVI whose frame payloads are unreadable by EVERY backend
    (cv2's resilient mjpeg decoder included — wiping just the SOI is no
    longer enough since the cv2 chain landed) yields None, like the
    reference's MovieDecoder error path."""
    from spacedrive_tpu.media.thumbnail import generate_thumbnail

    p = _clip(tmp_path, n=5)
    raw = bytearray(p.read_bytes())
    for off, size in index_frames(str(p)):
        raw[off:off + size] = b"\x00" * size  # zero the whole payload
    p.write_bytes(bytes(raw))
    assert frame_at_fraction(str(p)) is None
    assert generate_thumbnail(str(p), str(tmp_path / "d"),
                              "bb" + "2" * 14) is None


def test_not_an_avi_raises(tmp_path):
    p = tmp_path / "x.avi"
    p.write_bytes(b"MZ garbage")
    with pytest.raises(ValueError):
        index_frames(str(p))
