"""Fault injection: the reference has no such framework (SURVEY §5);
this framework tests its failure semantics deliberately — flaky steps,
pause mid-device-batch, concurrent-write convergence."""

import asyncio
import os
import random


from spacedrive_tpu.jobs.job import (
    EarlyFinish,
    StatefulJob,
    StepOutcome,
    register_job,
)
from spacedrive_tpu.jobs.report import JobStatus
from spacedrive_tpu.locations.indexer_job import IndexerJob
from spacedrive_tpu.locations.manager import create_location
from spacedrive_tpu.node import Node
from spacedrive_tpu.objects.identifier import FileIdentifierJob


def _run(coro):
    return asyncio.run(coro)


@register_job
class FlakyJob(StatefulJob):
    """Steps fail at a configured rate — non-fatal (JobRunErrors)."""

    NAME = "test_flaky"

    def __init__(self, *, steps: int, fail_every: int):
        super().__init__(steps=steps, fail_every=fail_every)
        self.steps = steps
        self.fail_every = fail_every

    async def init(self, ctx):
        return {"done": 0}, list(range(self.steps))

    async def execute_step(self, ctx, data, step, step_number):
        data["done"] += 1
        if step % self.fail_every == 0:
            return StepOutcome(errors=[f"injected failure at step {step}"])
        return StepOutcome()


def test_flaky_steps_complete_with_errors(tmp_path):
    node = Node(str(tmp_path / "data"))
    lib = node.create_library("t")

    async def main():
        jid = await node.jobs.ingest(lib, FlakyJob(steps=20, fail_every=5))
        status = await node.jobs.wait(jid)
        assert status == JobStatus.COMPLETED_WITH_ERRORS
        row = lib.db.query_one("SELECT * FROM job WHERE id = ?", (jid,))
        assert row["errors_text"] and "injected" in row["errors_text"]
        await node.shutdown()
    _run(main())


def test_identifier_pause_resume_device_batch_exact(tmp_path):
    """Hard part 3 (SURVEY §7): pause across a device-batch boundary and
    resume — every file identified exactly once, none skipped."""
    src = tmp_path / "corpus"
    src.mkdir()
    rng = random.Random(0)
    for i in range(300):
        (src / f"f{i}.bin").write_bytes(rng.randbytes(600))
    node = Node(str(tmp_path / "data"))
    lib = node.create_library("t")

    async def main():
        loc = create_location(lib, str(src))
        jid = await node.jobs.ingest(lib, IndexerJob(location_id=loc))
        await node.jobs.wait(jid)

        job = FileIdentifierJob(location_id=loc, device_batch=64)
        jid = await node.jobs.ingest(lib, job)
        # Pause as soon as it starts making progress, then resume.
        for _ in range(200):
            await asyncio.sleep(0.005)
            done = lib.db.query_one(
                "SELECT COUNT(*) AS n FROM file_path "
                "WHERE cas_id IS NOT NULL")["n"]
            if done > 0:
                break
        from spacedrive_tpu.jobs.manager import JobManagerError

        try:
            node.jobs.pause(jid)
            for _ in range(200):
                await asyncio.sleep(0.01)
                if jid not in node.jobs.running:
                    break
            await node.jobs.resume(lib, jid)
        except JobManagerError:
            pass  # job outran the pause on a fast machine — still valid:
            # the invariants below must hold either way
        status = await node.jobs.wait(jid)
        assert status == JobStatus.COMPLETED
        orphans = lib.db.query_one(
            "SELECT COUNT(*) AS n FROM file_path "
            "WHERE object_id IS NULL AND is_dir = 0")["n"]
        assert orphans == 0
        # exactly one object per unique content
        n_obj = lib.db.query_one("SELECT COUNT(*) AS n FROM object")["n"]
        n_cas = lib.db.query_one(
            "SELECT COUNT(DISTINCT cas_id) AS n FROM file_path "
            "WHERE cas_id IS NOT NULL")["n"]
        assert n_obj == n_cas == 300
        await node.shutdown()
    _run(main())


def test_two_node_concurrent_writes_converge(tmp_path):
    """LWW convergence over the real network: both nodes update the same
    record concurrently; both settle on the same winner."""
    from spacedrive_tpu.node import Node as _Node

    a = _Node(str(tmp_path / "a"))
    b = _Node(str(tmp_path / "b"))

    async def main():
        from conftest import pair_two_nodes

        lib_a, lib_b = await pair_two_nodes(a, b, "shared")

        pub = os.urandom(16)
        ops = lib_a.sync.shared_create("tag", pub, {"name": "base"})
        with lib_a.sync.write_ops(ops) as conn:
            conn.execute(
                "INSERT INTO tag (pub_id, name) VALUES (?, ?)",
                (pub, "base"))
        for _ in range(100):
            await asyncio.sleep(0.05)
            if lib_b.db.query_one(
                    "SELECT 1 FROM tag WHERE pub_id = ?", (pub,)):
                break

        # Concurrent conflicting updates on both sides.
        for lib, val in ((lib_a, "from-a"), (lib_b, "from-b")):
            op = lib.sync.shared_update("tag", pub, "name", val)
            with lib.sync.write_ops([op]) as conn:
                conn.execute(
                    "UPDATE tag SET name = ? WHERE pub_id = ?", (val, pub))

        async def settled():
            va = lib_a.db.query_one(
                "SELECT name FROM tag WHERE pub_id = ?", (pub,))["name"]
            vb = lib_b.db.query_one(
                "SELECT name FROM tag WHERE pub_id = ?", (pub,))["name"]
            return va, vb
        for _ in range(100):
            await asyncio.sleep(0.05)
            va, vb = await settled()
            if va == vb and va in ("from-a", "from-b"):
                break
        va, vb = await settled()
        assert va == vb and va in ("from-a", "from-b"), (va, vb)
        await a.shutdown()
        await b.shutdown()
    _run(main())






def test_identifier_cancel_restores_bulk_dropped_indexes(
        tmp_path, monkeypatch):
    """Big scans drop file_path's cas_id/object_id indexes for the run;
    a CANCELLED job never reaches finalize, so the cleanup() hook must
    restore them (VERDICT-class invariant: reads stay indexed for the
    life of the process)."""
    monkeypatch.setattr(FileIdentifierJob, "BULK_DROP_MIN_ORPHANS", 50)
    src = tmp_path / "corpus"
    src.mkdir()
    rng = random.Random(5)
    for i in range(400):
        (src / f"f{i}.bin").write_bytes(rng.randbytes(500))
    node = Node(str(tmp_path / "data"))
    lib = node.create_library("t")

    def idx_names():
        return {r["name"] for r in lib.db.query(
            "SELECT name FROM sqlite_master WHERE type='index' "
            "AND tbl_name='file_path'")}

    async def main():
        loc = create_location(lib, str(src))
        jid = await node.jobs.ingest(lib, IndexerJob(location_id=loc))
        await node.jobs.wait(jid)
        assert "idx_file_path_cas_id" in idx_names()

        job = FileIdentifierJob(location_id=loc, device_batch=16,
                                backend="numpy")
        jid = await node.jobs.ingest(lib, job)
        for _ in range(400):
            await asyncio.sleep(0.002)
            done = lib.db.query_one(
                "SELECT COUNT(*) AS n FROM file_path "
                "WHERE cas_id IS NOT NULL")["n"]
            if done:
                break
        # init dropped them (50-orphan threshold, 400 orphans)
        node.jobs.cancel(jid)
        status = await node.jobs.wait(jid)
        # Whichever end state won the race (cancel's cleanup() or a
        # photo-finish completion's finalize), the indexes must be back.
        assert status in (JobStatus.CANCELED, JobStatus.COMPLETED,
                          JobStatus.COMPLETED_WITH_ERRORS)
        assert {"idx_file_path_cas_id",
                "idx_file_path_object_id"} <= idx_names()
        await node.shutdown()
    _run(main())
