"""Packed native staging (native/sdio.cpp sd_stage_batch → ops/staging
stage_batch_native): byte parity with the classic Python path, the
per-file and whole-batch degradation ladders, pooled-page recycling,
and the chaos seam — all CPU-only tier-1.

The acceptance shape: native digests must be bit-identical to the
Python CAS oracle across the WHOLE degradation matrix — healthy rows,
fallback rows, and scrubbed error rows alike — because the kernel
consumes whatever bytes staging hands it.
"""

import os
import struct
import subprocess

import numpy as np
import pytest

from spacedrive_tpu import chaos, flags, native
from spacedrive_tpu.ops import cas, staging

requires_native = pytest.mark.skipif(
    not native.available(), reason="native libsdio unavailable")


def _write(path: str, data: bytes) -> int:
    with open(path, "wb") as f:
        f.write(data)
    return len(data)


def _pattern(n: int, seed: int = 0) -> bytes:
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, n, dtype=np.uint8).tobytes()


def _oracle_payload(path: str, declared: int) -> bytes:
    """The Python reader's payload for one file — the CAS oracle's
    input bytes."""
    if declared <= cas.MINIMUM_FILE_SIZE:
        with open(path, "rb") as f:
            return f.read()
    out = np.zeros(cas.LARGE_PAYLOAD_SIZE, np.uint8)
    staging._read_large(path, declared, out)
    return out.tobytes()


def _expect_row(declared: int, payload: bytes, stride: int) -> bytes:
    row = struct.pack("<Q", declared) + payload
    return row + b"\x00" * (stride - len(row))


@requires_native
def test_make_stage_selftest():
    """Satellite: `make -C native stage` builds and runs the C-level
    self-test (layout, statuses, sampled offsets, pooled-page
    scrubbing) with no Python in the loop."""
    native_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native")
    res = subprocess.run(["make", "-C", native_dir, "stage"],
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert "sd_stage_batch self-test: OK" in res.stdout


@requires_native
def test_byte_parity_across_split(tmp_path, monkeypatch):
    """Byte-for-byte parity with the classic path across the
    large/small boundary (102399 / 102400 / 102401) and a deep-sample
    large file: prefix, payload, and zero tail per packed row."""
    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "on")
    sizes = [102399, cas.MINIMUM_FILE_SIZE, 102401, 150000, 4242]
    files = []
    for i, n in enumerate(sizes):
        p = str(tmp_path / f"f{i}.bin")
        files.append((p, _write(p, _pattern(n, seed=i))))

    staged = staging.stage_batch_native(files)
    assert staged is not None
    try:
        assert staged.errors == {} and staged.empty_rows == []
        assert staged.fallback_files == 0
        stride = staged.lease.arr.shape[1]
        for r, (p, declared) in enumerate(files):
            payload = _oracle_payload(p, declared)
            assert int(staged.lengths[r]) == 8 + len(payload)
            got = staged.lease.arr[r].tobytes()
            assert got == _expect_row(declared, payload, stride), \
                f"row {r} ({declared}B) diverges from the oracle"
        # words is a zero-copy view over the SAME pooled page
        assert staged.words.base is not None
        assert np.shares_memory(staged.words, staged.lease.arr)
    finally:
        staged.release()


@requires_native
def test_digest_parity_with_cas_oracle(tmp_path, monkeypatch):
    """CAS IDs computed from the packed rows equal the pure-Python
    oracle's — the end contract every staging backend must meet."""
    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "on")
    files = []
    for i, n in enumerate((300, 102400, 120000, 103000)):
        p = str(tmp_path / f"d{i}.bin")
        files.append((p, _write(p, _pattern(n, seed=10 + i))))
    staged = staging.stage_batch_native(files)
    assert staged is not None
    try:
        for r, (p, declared) in enumerate(files):
            payload = staged.lease.arr[
                r, 8:int(staged.lengths[r])].tobytes()
            assert cas.cas_id_of_payload(declared, payload) == \
                cas.cas_id_of_payload(declared, _oracle_payload(p, declared))
    finally:
        staged.release()


@requires_native
def test_per_file_degradation_matrix(tmp_path, monkeypatch):
    """One batch, every ladder rung at once: a healthy row stays
    native, a vanished file (ENOENT) and a truncated file (short read)
    fail BOTH readers into `errors` with their rows scrubbed to the
    8-byte prefix, an empty file lands in `empty_rows`, and a grown
    file (real bytes past the declared size) is refused by both."""
    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "on")
    ok = str(tmp_path / "ok.bin")
    _write(ok, _pattern(120000, seed=1))
    gone = str(tmp_path / "gone.bin")
    short = str(tmp_path / "short.bin")
    _write(short, _pattern(4096, seed=2))  # declared 150000: truncated
    empty = str(tmp_path / "empty.bin")
    _write(empty, b"")
    grew = str(tmp_path / "grew.bin")
    # declared small (5000) but the real bytes crossed the small-class
    # cap — the only grow the whole-file reader can (and must) refuse,
    # exactly like the classic path's MINIMUM+1 sentinel read
    _write(grew, _pattern(cas.MINIMUM_FILE_SIZE + 600, seed=3))

    files = [(ok, 120000), (gone, 120000), (short, 150000),
             (empty, 0), (grew, 5000)]
    staged = staging.stage_batch_native(files)
    assert staged is not None
    try:
        assert sorted(staged.errors) == [1, 2, 4]
        assert staged.empty_rows == [3]
        assert staged.fallback_files == 0
        # the healthy row is untouched by its neighbors' failures
        payload = staged.lease.arr[0, 8:int(staged.lengths[0])].tobytes()
        assert payload == _oracle_payload(ok, 120000)
        # failed + empty rows: prefix only, tail scrubbed (the kernel
        # hashes full blocks — stale residue would corrupt digests)
        for r in (1, 2, 3, 4):
            assert int(staged.lengths[r]) == 8
            assert not staged.lease.arr[r, 8:].any()
        # error parity with the classic path: same rows, same classes
        _l, _s, empty_idx, perrors = staging.stage_files(files)
        assert sorted(perrors) == sorted(staged.errors)
        assert empty_idx == staged.empty_rows
    finally:
        staged.release()


@requires_native
def test_chaos_injected_eio_falls_back_per_file(tmp_path, monkeypatch):
    """Satellite: the declared stage.native.read fault point. A
    probability-1.0 error storm marks every native row failed; the
    per-file Python ladder re-reads them all into the SAME pooled rows
    and digest parity still holds (fallback is invisible to the
    kernel)."""
    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "on")
    files = []
    for i, n in enumerate((120000, 50000, 102401)):
        p = str(tmp_path / f"c{i}.bin")
        files.append((p, _write(p, _pattern(n, seed=20 + i))))
    chaos.arm("stage.native.read=error:1.0", seed=11)
    try:
        staged = staging.stage_batch_native(files)
        assert staged is not None
        try:
            assert staged.errors == {}
            assert staged.fallback_files == len(files)
            for r, (p, declared) in enumerate(files):
                payload = staged.lease.arr[
                    r, 8:int(staged.lengths[r])].tobytes()
                assert payload == _oracle_payload(p, declared)
        finally:
            staged.release()
    finally:
        chaos.disarm()
    assert not chaos.armed_point("stage.native.read")


def test_whole_batch_fallback_flag_off(tmp_path, monkeypatch):
    """SDTPU_STAGE_NATIVE=off declines the packed path entirely — the
    fail-closed ladder's top rung."""
    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "off")
    p = str(tmp_path / "x.bin")
    files = [(p, _write(p, _pattern(120000)))]
    assert staging.stage_batch_native(files) is None


def test_whole_batch_fallback_so_missing(tmp_path, monkeypatch):
    """A missing shared object degrades the WHOLE batch, silently and
    correctly, whatever the flag says."""
    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "on")
    monkeypatch.setattr(native, "available", lambda: False)
    p = str(tmp_path / "x.bin")
    files = [(p, _write(p, _pattern(120000)))]
    assert staging.stage_batch_native(files) is None


@requires_native
def test_pool_exhaustion_degrades_not_grows(tmp_path, monkeypatch):
    """The pool is a declared bounded resource: with every page checked
    out, stage_batch_native returns None (degrade to Python) instead of
    allocating past the bound; a release makes it available again."""
    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "on")
    monkeypatch.setenv("SDTPU_STAGE_POOL_BUFFERS", "1")
    pool = staging.StagePool()
    p = str(tmp_path / "x.bin")
    files = [(p, _write(p, _pattern(120000)))]
    held = pool.acquire(4, 58368)
    assert held is not None
    assert staging.stage_batch_native(files, pool=pool) is None
    held.release()
    staged = staging.stage_batch_native(files, pool=pool)
    assert staged is not None
    staged.release()


@requires_native
def test_pool_recycles_pages_and_scrubs_residue(tmp_path, monkeypatch):
    """Recycled pages are reused (bounded allocation) and every packed
    row's tail is rewritten — batch B staged into batch A's dirty page
    must not inherit A's bytes."""
    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "on")
    pool = staging.StagePool()
    big = str(tmp_path / "big.bin")
    big2 = str(tmp_path / "big2.bin")
    # two large rows (2 x 58368B) so the page fits batch B's one
    # small-grid row (103424B) and MUST be reused, not reallocated
    files_a = [(big, _write(big, _pattern(150000, seed=5))),
               (big2, _write(big2, _pattern(150000, seed=7)))]
    small = str(tmp_path / "small.bin")
    files_b = [(small, _write(small, _pattern(600, seed=6)))]

    a = staging.stage_batch_native(files_a, pool=pool)
    assert a is not None
    page_a = id(a.lease.buf)
    a.release()
    assert pool._total == 1 and len(pool._free) == 1

    b = staging.stage_batch_native(files_b, pool=pool)
    assert b is not None
    try:
        # same pooled page, reshaped for the small grid
        assert id(b.lease.buf) == page_a
        assert pool._total == 1
        assert int(b.lengths[0]) == 8 + 600
        assert not b.lease.arr[0, 8 + 600:].any(), \
            "stale residue from the previous batch survived the scrub"
        payload = b.lease.arr[0, 8:608].tobytes()
        assert payload == _oracle_payload(small, 600)
    finally:
        b.release()


@requires_native
def test_overlap_pipeline_digest_parity_and_pool_drain(tmp_path,
                                                       monkeypatch):
    """End to end through the depth-N ring: native and Python staging
    produce identical digests for the same corpus, the run reports its
    backend, and every pooled page is back on the free list when the
    pipeline drains (recycling is keyed to batch retirement)."""
    from tools.overlap_bench import _cheap_kernel

    from spacedrive_tpu.ops import overlap

    root = str(tmp_path / "corpus")
    batches = overlap.make_sparse_corpus(root, 12, 120000, 4)
    pool = staging.stage_buffer_pool()

    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "on")
    r_native, s_native = overlap.run_overlapped(
        batches, kernel=_cheap_kernel, depth=3, calibrate_every=99)
    monkeypatch.setenv("SDTPU_STAGE_NATIVE", "off")
    r_python, s_python = overlap.run_overlapped(
        batches, kernel=_cheap_kernel, depth=3, calibrate_every=99)

    assert s_native.staging_backend == "native"
    assert s_native.stage_native_batches > 0
    assert s_python.staging_backend == "python"
    assert s_python.stage_native_batches == 0
    for a, b in zip(r_native, r_python):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # retirement returned every lease: nothing checked out
    with pool._lock:
        assert pool._total == len(pool._free)
    assert len(pool._win) == 0


@requires_native
def test_stage_native_flag_modes(tmp_path, monkeypatch):
    """auto (default) and on both engage when the .so is present; the
    off spellings all decline."""
    p = str(tmp_path / "x.bin")
    files = [(p, _write(p, _pattern(120000)))]
    for mode in ("auto", "on", "1"):
        monkeypatch.setenv("SDTPU_STAGE_NATIVE", mode)
        staged = staging.stage_batch_native(files)
        assert staged is not None, mode
        staged.release()
    for mode in ("off", "0", "no", "false"):
        monkeypatch.setenv("SDTPU_STAGE_NATIVE", mode)
        assert staging.stage_batch_native(files) is None, mode
