"""Web-UI drive test: the embedded explorer's procedure surface,
regression-tested over the real websocket (VERDICT r2 item 3).

Two guards:
1. Every procedure name the UI's JS references (q("…") / mut("…") /
   subscription paths) must exist in the mounted router — a rename in
   api/procedures.py that would silently break the UI fails here.
2. A real server is booted and ≥ 30 procedures are driven through the
   SAME JSON frames webui.js sends (rpc(): {"id", "type", "path",
   "input"}), covering explorer listing, inspector mutations, search,
   tags, dup/near-dup views, job spawn/pause/resume/cancel, settings,
   keys, backups, and a live subscription round trip.

Reference shape: packages/client's rspc websocket usage, which the
reference UI depends on (packages/client/src/rspc.tsx).
"""

import asyncio
import json
import os
import re

import aiohttp
import pytest

from spacedrive_tpu.api.router import mount_router
from spacedrive_tpu.api.server import ApiServer
from spacedrive_tpu.api.webui import asset_path


def _ui_js() -> str:
    with open(asset_path("app.js"), encoding="utf-8") as f:
        return f.read()
from spacedrive_tpu.node import Node


def _run(coro):
    return asyncio.run(coro)


def _corpus(root: str) -> None:
    os.makedirs(f"{root}/docs", exist_ok=True)
    for i in range(6):
        with open(f"{root}/docs/file{i}.txt", "wb") as f:
            f.write(f"content {i} ".encode() * 300)
    # one duplicate pair for the dup view
    with open(f"{root}/dup_a.bin", "wb") as f:
        f.write(b"same bytes " * 500)
    with open(f"{root}/dup_b.bin", "wb") as f:
        f.write(b"same bytes " * 500)
    from PIL import Image

    Image.new("RGB", (64, 48), (200, 40, 10)).save(f"{root}/pic.png")
    Image.new("RGB", (64, 48), (201, 41, 11)).save(f"{root}/pic2.png")
    # bulk dir: enough steps that a pause frame can land mid-job
    os.makedirs(f"{root}/bulk", exist_ok=True)
    for i in range(400):
        with open(f"{root}/bulk/b{i}.dat", "wb") as f:
            f.write(os.urandom(64) * 64)


def test_ui_procedure_names_resolve():
    """Guard 1: every procedure name the UI JS carries exists in the
    router, and the surfaced census covers >= 80 of the full registry
    (the round-4 breadth bar; round 3 was ~66)."""
    js = _ui_js()
    # explicit call sites…
    names = set(re.findall(r'\b(?:q|mut|sub)\(\s*"([A-Za-z0-9._]+)"', js))
    # …plus any other string literal shaped like a namespaced procedure
    # (ternaries like `cut ? "files.cutFiles" : "files.copyFiles"` and
    # the keys mount/unmount toggle build names conditionally)
    literals = set(re.findall(
        r'"([A-Za-z][A-Za-z0-9]*(?:\.[A-Za-z0-9_]+)+)"', js))
    # dynamic job-control calls are built as "jobs." + verb
    names |= {"jobs.pause", "jobs.resume", "jobs.cancel", "jobs.clear"}
    names = {n for n in names if not n.endswith(".")}
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        node = Node(os.path.join(d, "data"))
        router = mount_router(node)
        known = set(router.procedures) | set(router.subscriptions)
        missing = sorted(n for n in names if n not in known)
        assert not missing, f"UI references unknown procedures: {missing}"
        referenced = (names | (literals & known)) & known
        assert len(referenced) >= 80, (
            f"UI surfaces only {len(referenced)} of {len(known)} "
            f"procedures; missing: {sorted(known - referenced)}")


class _Ws:
    """Minimal client speaking the exact frames webui.js rpc() sends."""

    def __init__(self, ws):
        self.ws = ws
        self._id = 0

    async def call(self, type_, path, input_=None):
        self._id += 1
        rid = self._id
        await self.ws.send_json(
            {"id": rid, "type": type_, "path": path, "input": input_ or {}})
        while True:
            msg = await asyncio.wait_for(self.ws.receive(), timeout=30)
            assert msg.type == aiohttp.WSMsgType.TEXT, msg
            frame = json.loads(msg.data)
            if frame.get("id") != rid:
                continue  # stray subscription event
            if frame["type"] == "error":
                raise RuntimeError(f"{path}: {frame}")
            return frame.get("result")

    async def q(self, path, input_=None):
        return await self.call("query", path, input_)

    async def m(self, path, input_=None):
        return await self.call("mutation", path, input_)


@pytest.fixture
def served(tmp_path):
    corpus = tmp_path / "corpus"
    corpus.mkdir()
    _corpus(str(corpus))
    node = Node(str(tmp_path / "data"))
    return node, str(corpus)


def test_drive_ui_procedures(served):
    node, corpus = served
    driven = set()

    async def main():
        await node.start()
        server = ApiServer(node)
        port = await server.start(port=0)
        async with aiohttp.ClientSession() as http:
            # the explorer page itself serves
            async with http.get(f"http://127.0.0.1:{port}/") as resp:
                assert resp.status == 200
                page = await resp.text()
                # the served shell must actually be the app (round-4's
                # `or True` here let ANY page pass — it was even hiding
                # that the title says "spacedrive-tpu", not "Spacedrive")
                assert "app.js" in page and "spacedrive-tpu" in page, \
                    page[:200]
            async with http.ws_connect(
                    f"http://127.0.0.1:{port}/rspc") as ws_raw:
                ws = _Ws(ws_raw)

                async def q(path, input_=None):
                    driven.add(path)
                    return await ws.q(path, input_)

                async def m(path, input_=None):
                    driven.add(path)
                    return await ws.m(path, input_)

                # ---- onboarding: create library → location → scan ----
                info = await q("buildInfo")
                assert info["version"]
                lib = await m("library.create", {"name": "ui-lib"})
                lid = lib["uuid"]
                assert [x["uuid"] for x in await q("library.list")] == [lid]
                loc = await m("locations.create",
                              {"library_id": lid, "path": corpus,
                               "dry_run": True})
                await m("locations.fullRescan",
                        {"library_id": lid, "location_id": loc})
                await node.jobs.wait_idle()
                locs = await q("locations.list", {"library_id": lid})
                assert len(locs) == 1

                # ---- explorer listing + search ----
                paths = await q("search.paths",
                                {"library_id": lid, "take": 500})
                assert {"dup_a", "dup_b", "docs"} <= {
                    p["name"] for p in paths["items"]}
                n = await q("search.pathsCount", {"library_id": lid})
                assert n == len(paths["items"])
                objs = await q("search.objects", {"library_id": lid})
                assert objs["items"]
                stats = await q("library.statistics", {"library_id": lid})
                assert stats["total_object_count"] > 0

                # ---- inspector mutations on one object ----
                target = next(p for p in paths["items"]
                              if p["name"] == "dup_a")
                oid = target["object_id"]
                obj = await q("files.get", {"library_id": lid, "id": oid})
                assert obj["file_paths"]
                await m("files.setFavorite",
                        {"library_id": lid, "id": oid, "favorite": True})
                await m("files.setNote",
                        {"library_id": lid, "id": oid, "note": "from ui"})
                obj = await q("files.get", {"library_id": lid, "id": oid})
                assert obj["favorite"] == 1 and obj["note"] == "from ui"
                await q("files.getMediaData", {"library_id": lid, "id": oid})
                await m("files.renameFile",
                        {"library_id": lid,
                         "file_path_id": target["id"],
                         "new_name": "dup_renamed.bin"})

                # ---- file ops driving real jobs ----
                some_txt = next(p for p in paths["items"]
                                if p["name"] == "file0")
                await m("files.duplicateFiles",
                        {"library_id": lid, "location_id": loc,
                         "file_path_ids": [some_txt["id"]]})
                await node.jobs.wait_idle()
                await m("files.deleteFiles",
                        {"library_id": lid, "location_id": loc,
                         "file_path_ids": [some_txt["id"]]})
                await node.jobs.wait_idle()

                # ---- per-location settings + indexer-rule editor ----
                lw = await q("locations.getWithRules",
                             {"library_id": lid, "location_id": loc})
                assert lw["path"] == corpus
                rule_id = await m("locations.indexer_rules.create",
                                  {"library_id": lid, "name": "no logs",
                                   "rules": [[1, ["**/*.log"]]]})
                await m("locations.update",
                        {"library_id": lid, "id": loc,
                         "name": "Main", "indexer_rules_ids": [rule_id]})
                lw = await q("locations.getWithRules",
                             {"library_id": lid, "location_id": loc})
                assert lw["name"] == "Main"
                assert [x["id"] for x in lw["indexer_rules"]] == [rule_id]
                rules = await q("locations.indexer_rules.list",
                                {"library_id": lid})
                assert any(x["id"] == rule_id for x in rules)
                await m("locations.indexer_rules.delete",
                        {"library_id": lid, "id": rule_id})

                # ---- explorer copy/cut (the context-menu paste path) ----
                os.makedirs(os.path.join(corpus, "dest"), exist_ok=True)
                await m("locations.fullRescan",
                        {"library_id": lid, "location_id": loc})
                await node.jobs.wait_idle()
                paths2 = await q("search.paths",
                                 {"library_id": lid, "take": 500})
                src = next(p for p in paths2["items"]
                           if p["name"] == "file1")
                await m("files.copyFiles",
                        {"library_id": lid, "source_location_id": loc,
                         "sources_file_path_ids": [src["id"]],
                         "target_location_id": loc,
                         "target_location_relative_directory_path":
                             "dest/"})
                await node.jobs.wait_idle()
                assert os.path.exists(
                    os.path.join(corpus, "dest", "file1.txt"))
                await m("files.cutFiles",
                        {"library_id": lid, "source_location_id": loc,
                         "sources_file_path_ids": [src["id"]],
                         "target_location_id": loc,
                         "target_location_relative_directory_path":
                             "dest/"})
                await node.jobs.wait_idle()

                # ---- dup + near-dup views ----
                dups = await q("search.duplicates", {"library_id": lid})
                assert any(g["count"] >= 2 for g in dups), dups
                await m("jobs.nearDupDetector",
                        {"library_id": lid, "id": loc, "threshold": 10})
                await node.jobs.wait_idle()
                await q("search.nearDuplicates", {"library_id": lid})

                # ---- tags ----
                tag = await m("tags.create",
                              {"library_id": lid, "name": "red",
                               "color": "#ff0000"})
                tags = await q("tags.list", {"library_id": lid})
                assert [t["name"] for t in tags] == ["red"]
                await m("tags.assign", {"library_id": lid,
                                        "tag_id": tag["id"],
                                        "object_id": oid})
                got = await q("tags.getForObject",
                              {"library_id": lid, "object_id": oid})
                assert [t["name"] for t in got] == ["red"]
                await m("tags.delete",
                        {"library_id": lid, "id": tag["id"]})

                # ---- job spawn / pause / resume / cancel ----
                # fill checksums first so verify-mode has rows to walk
                # (it EarlyFinishes on a library with no checksums).
                await m("jobs.objectValidator",
                        {"library_id": lid, "id": loc, "mode": "fill"})
                await node.jobs.wait_idle()
                # verify-mode validator re-hashes every file, so it can
                # be respawned; pause can race completion on a tiny
                # corpus — retry until the pause actually lands.
                paused = False
                for _ in range(5):
                    jid = await m("jobs.objectValidator",
                                  {"library_id": lid, "id": loc,
                                   "mode": "verify"})
                    try:
                        await m("jobs.pause",
                                {"library_id": lid, "id": jid})
                    except RuntimeError:
                        await node.jobs.wait_idle()
                        continue  # job outran the pause frame
                    for _ in range(50):
                        reports = await q("jobs.reports",
                                          {"library_id": lid})
                        rep = next(r for r in reports if r["id"] == jid)
                        if rep["status"] not in (0, 1):  # left QUEUED/RUNNING
                            break
                        await asyncio.sleep(0.02)
                    if rep["status"] == 5:  # PAUSED
                        paused = True
                        await m("jobs.resume",
                                {"library_id": lid, "id": jid})
                        break
                    await node.jobs.wait_idle()
                assert paused, "pause never landed before completion"
                await node.jobs.wait_idle()
                # identify is a no-op here (everything already has an
                # object) — drives the procedure + EarlyFinish path.
                await m("jobs.identifyUniqueFiles",
                        {"library_id": lid, "id": loc})
                await node.jobs.wait_idle()
                # cancel races completion the same way pause does
                cancelled = False
                for _ in range(5):
                    jid2 = await m("jobs.objectValidator",
                                   {"library_id": lid, "id": loc,
                                    "mode": "verify"})
                    try:
                        await m("jobs.cancel",
                                {"library_id": lid, "id": jid2})
                        cancelled = True
                        break
                    except RuntimeError:
                        await node.jobs.wait_idle()
                assert cancelled, "cancel never landed before completion"
                await node.jobs.wait_idle()
                reports = await q("jobs.reports", {"library_id": lid})
                assert reports
                assert await q("jobs.isActive", {"library_id": lid}) is False
                await m("jobs.clearAll", {"library_id": lid})

                # ---- settings: preferences / keys / backups / misc ----
                await m("preferences.update",
                        {"library_id": lid,
                         "values": {"explorer_view": "media"}})
                prefs = await q("preferences.get", {"library_id": lid})
                assert prefs.get("explorer_view") == "media"
                assert await q("keys.isSetup") is False
                await m("keys.setup", {"password": "hunter2hunter2"})
                await m("keys.lock")
                await m("keys.unlock", {"password": "hunter2hunter2"})
                assert await q("keys.isUnlocked") is True
                await q("keys.list")
                b = await m("backups.backup", {"library_id": lid})
                assert b
                all_b = await q("backups.getAll")
                assert all_b
                await q("volumes.list")
                await q("categories.list", {"library_id": lid})

                # ---- ephemeral (non-indexed) browsing ----
                eph = await q("search.ephemeralPaths", {"path": corpus})
                assert any(e["name"] == "docs" and e["is_dir"]
                           for e in eph)

                # ---- new folder + secure erase + encrypt/decrypt ----
                await m("files.createFolder",
                        {"library_id": lid, "location_id": loc,
                         "sub_path": "/", "name": "made_by_ui"})
                assert os.path.isdir(os.path.join(corpus, "made_by_ui"))
                paths3 = await q("search.paths",
                                 {"library_id": lid, "take": 500})
                victim = next(p for p in paths3["items"]
                              if p["name"] == "file2")
                await m("files.eraseFiles",
                        {"library_id": lid, "location_id": loc,
                         "file_path_ids": [victim["id"]], "passes": 1})
                await node.jobs.wait_idle()
                assert not os.path.exists(
                    os.path.join(corpus, "docs", "file2.txt"))
                enc_target = next(p for p in paths3["items"]
                                  if p["name"] == "file3")
                await m("files.encryptFiles",
                        {"library_id": lid, "location_id": loc,
                         "file_path_ids": [enc_target["id"]],
                         "password": "pw-ui-test"})
                await node.jobs.wait_idle()
                enc_path = os.path.join(corpus, "docs", "file3.txt.sdtpu")
                assert os.path.exists(enc_path), os.listdir(
                    os.path.join(corpus, "docs"))

                # ---- backup delete + restore round trip ----
                bid = (all_b[0] if isinstance(all_b, list)
                       else all_b["backups"][0])["id"]
                b2 = await m("backups.backup", {"library_id": lid})
                await m("backups.delete", {"backup_id": bid})
                left = await q("backups.getAll")
                left_ids = [x["id"] for x in (
                    left if isinstance(left, list) else left["backups"])]
                assert bid not in left_ids
                await m("backups.restore", {"backup_id": b2 if isinstance(
                    b2, str) else b2["id"]})
                assert [x["uuid"] for x in await q("library.list")] \
                    == [lid]
                n_after = await q("search.pathsCount",
                                  {"library_id": lid})
                assert n_after > 0
                await q("p2p.state")

                # ---- overview landing page (round 4) ----
                nstate = await q("nodeState")
                assert nstate["name"]
                online = await q("locations.online", {"library_id": lid})
                assert loc in online
                nlocs = await q("nodes.listLocations", {"library_id": lid})
                assert len(nlocs) == 1
                n_obj = await q("search.objectsCount",
                                {"library_id": lid, "filter": {}})
                assert n_obj > 0

                # ---- quick preview path (round 4) ----
                paths4 = await q("search.paths",
                                 {"library_id": lid, "take": 500})
                pv = next(p for p in paths4["items"]
                          if p["name"] == "pic" and not p["is_dir"])
                full = await q("files.getPath",
                               {"library_id": lid, "id": pv["id"]})
                assert full and full.endswith("pic.png")
                await m("files.updateAccessTime",
                        {"library_id": lid, "ids": [pv["object_id"]]})
                row = await q("files.get",
                              {"library_id": lid, "id": pv["object_id"]})
                assert row["date_accessed"]
                await m("files.removeAccessTime",
                        {"library_id": lid, "ids": [pv["object_id"]]})

                # ---- convert image (context menu, round 4) ----
                exts = await q("files.getConvertableImageExtensions")
                assert "webp" in exts
                await m("files.convertImage",
                        {"library_id": lid, "file_path_id": pv["id"],
                         "to_extension": "webp"})
                assert os.path.exists(os.path.join(corpus, "pic.webp"))

                # ---- node / library settings cards (round 4) ----
                await m("nodes.edit", {"name": "ui-node"})
                assert (await q("nodeState"))["name"] == "ui-node"
                await m("toggleFeatureFlag", {"feature": "filesOverP2P"})
                assert "filesOverP2P" in (await q("nodeState"))["features"]
                await m("library.edit", {"id": lid, "name": "renamed-ui"})
                libs2 = await q("library.list")
                assert libs2[0]["config"]["name"] == "renamed-ui"
                ops = await q("sync.messages", {"library_id": lid})
                assert ops, "op log should not be empty after a scan"

                # ---- location extras (round 4) ----
                lrow = await q("locations.get",
                               {"library_id": lid, "location_id": loc})
                assert lrow["path"] == corpus
                await m("locations.createDirectory",
                        {"library_id": lid, "location_id": loc,
                         "sub_path": "made_by_settings"})
                assert os.path.isdir(
                    os.path.join(corpus, "made_by_settings"))
                await m("locations.subPathRescan",
                        {"library_id": lid, "location_id": loc,
                         "sub_path": "/"})
                await node.jobs.wait_idle()
                await m("locations.relink",
                        {"library_id": lid, "location_id": loc,
                         "path": corpus})
                rid2 = await m("locations.indexer_rules.create",
                               {"library_id": lid, "name": "tmp rule",
                                "rules": [[1, ["**/*.bak"]]]})
                got_rule = await q("locations.indexer_rules.get",
                                   {"library_id": lid, "id": rid2})
                assert got_rule["name"] == "tmp rule"
                await m("locations.update",
                        {"library_id": lid, "id": loc,
                         "indexer_rules_ids": [rid2]})
                for_loc = await q("locations.indexer_rules.listForLocation",
                                  {"library_id": lid, "location_id": loc})
                assert [x["id"] for x in for_loc] == [rid2]
                lib2 = await m("library.create", {"name": "second"})
                await m("locations.addLibrary",
                        {"library_id": lib2["uuid"], "path": corpus})
                await node.jobs.wait_idle()
                assert await q("locations.list",
                               {"library_id": lib2["uuid"]})

                # ---- tags: counts + edit (round 4) ----
                tag2 = await m("tags.create", {"library_id": lid,
                               "name": "blue", "color": "#00f"})
                await m("tags.assign", {"library_id": lid,
                        "tag_id": tag2["id"], "object_id": oid})
                with_obj = await q("tags.getWithObjects",
                                   {"library_id": lid})
                blue = next(t for t in with_obj if t["name"] == "blue")
                assert oid in blue["object_ids"]
                await m("tags.update", {"library_id": lid,
                        "id": tag2["id"], "name": "navy", "color": "#009"})
                assert (await q("tags.get", {"library_id": lid,
                        "id": tag2["id"]}))["name"] == "navy"

                # ---- labels (net-new surface over the Label model) ----
                lbl = await m("labels.create",
                              {"library_id": lid, "name": "project-x"})
                await m("labels.assign", {"library_id": lid,
                        "label_id": lbl["id"], "object_id": oid})
                for_obj = await q("labels.getForObject",
                                  {"library_id": lid, "object_id": oid})
                assert [x["name"] for x in for_obj] == ["project-x"]
                lbls = await q("labels.list", {"library_id": lid})
                assert lbls[0]["object_count"] == 1
                await m("labels.assign", {"library_id": lid,
                        "label_id": lbl["id"], "object_id": oid,
                        "unassign": True})
                assert (await q("labels.list",
                                {"library_id": lid}))[0]["object_count"] == 0
                await m("labels.delete",
                        {"library_id": lid, "id": lbl["id"]})
                assert await q("labels.list", {"library_id": lid}) == []

                # ---- albums / spaces (net-new groupings, round 5) ----
                alb = await m("albums.create",
                              {"library_id": lid, "name": "trip"})
                await m("albums.addObjects",
                        {"library_id": lid, "id": alb["id"],
                         "object_ids": [oid]})
                albs = await q("albums.list", {"library_id": lid})
                assert next(a for a in albs
                            if a["id"] == alb["id"])["object_count"] == 1
                got_alb = await q("albums.get",
                                  {"library_id": lid, "id": alb["id"]})
                assert got_alb["object_ids"] == [oid]
                # the explorer filter drives the same windows the UI uses
                in_alb = await q("search.paths",
                                 {"library_id": lid, "skip": 0,
                                  "take": 50,
                                  "filter": {"album_id": alb["id"]}})
                assert any(p["object_id"] == oid
                           for p in in_alb["items"])
                await m("albums.update", {"library_id": lid,
                        "id": alb["id"], "name": "trip-2024",
                        "is_hidden": 1})
                albs = await q("albums.list", {"library_id": lid})
                a_row = next(a for a in albs if a["id"] == alb["id"])
                assert a_row["name"] == "trip-2024" \
                    and a_row["is_hidden"] == 1
                await m("albums.removeObjects",
                        {"library_id": lid, "id": alb["id"],
                         "object_ids": [oid]})
                assert (await q("albums.get", {"library_id": lid,
                        "id": alb["id"]}))["object_ids"] == []
                await m("albums.delete",
                        {"library_id": lid, "id": alb["id"]})
                assert all(a["id"] != alb["id"] for a in
                           await q("albums.list", {"library_id": lid}))

                sp = await m("spaces.create",
                             {"library_id": lid, "name": "work",
                              "description": "projects"})
                await m("spaces.addObjects",
                        {"library_id": lid, "id": sp["id"],
                         "object_ids": [oid]})
                sps = await q("spaces.list", {"library_id": lid})
                s_row = next(s for s in sps if s["id"] == sp["id"])
                assert s_row["object_count"] == 1 \
                    and s_row["description"] == "projects"
                in_sp = await q("search.paths",
                                {"library_id": lid, "skip": 0,
                                 "take": 50,
                                 "filter": {"space_id": sp["id"]}})
                assert any(p["object_id"] == oid
                           for p in in_sp["items"])
                await m("spaces.removeObjects",
                        {"library_id": lid, "id": sp["id"],
                         "object_ids": [oid]})
                await m("spaces.delete",
                        {"library_id": lid, "id": sp["id"]})
                assert all(s["id"] != sp["id"] for s in
                           await q("spaces.list", {"library_id": lid}))

                # ---- saved searches (preferences-backed, round 4) ----
                await m("preferences.update", {"library_id": lid,
                        "values": {"saved_searches":
                                   '{"big docs": {"q": "file", '
                                   '"tag": null, "kind": null}}'}})
                prefs2 = await q("preferences.get", {"library_id": lid})
                assert "big docs" in prefs2["saved_searches"]

                # ---- ephemeral extras (round 4) ----
                await m("files.createEphemeralFolder",
                        {"path": corpus, "name": "eph_made"})
                assert os.path.isdir(os.path.join(corpus, "eph_made"))
                md = await q("files.getEphemeralMediaData",
                             {"path": os.path.join(corpus, "pic.png")})
                assert md is None or isinstance(md, dict)

                # ---- auth device flow (round 4) ----
                auth_id = 7001
                await ws_raw.send_json(
                    {"id": auth_id, "type": "subscription",
                     "path": "auth.loginSession",
                     "input": {"poll_interval": 0.02}})
                driven.add("auth.loginSession")
                start_ev = None
                for _ in range(60):
                    msg = await asyncio.wait_for(
                        ws_raw.receive(), timeout=10)
                    frame = json.loads(msg.data)
                    if (frame.get("id") == auth_id
                            and frame.get("type") == "event"):
                        start_ev = frame["data"]
                        break
                assert start_ev and start_ev["state"] == "Start"
                node.auth_issuer.approve(
                    start_ev["user_code"], "ui-user", "ui@x.test")
                done_ev = None
                for _ in range(200):
                    msg = await asyncio.wait_for(
                        ws_raw.receive(), timeout=10)
                    frame = json.loads(msg.data)
                    if (frame.get("id") == auth_id
                            and frame.get("type") == "event"
                            and frame["data"].get("state") != "Start"):
                        done_ev = frame["data"]
                        break
                assert done_ev and done_ev["state"] == "Complete"
                me = await q("auth.me")
                assert me["email"] == "ui@x.test"
                await m("auth.logout")

                # ---- keys mount/unmount/delete (round 4) ----
                kid = await m("keys.add", {"key": "extra-key-pw"})
                keys_now = await q("keys.list")
                target_key = next(k for k in keys_now
                                  if (k.get("uuid") or k.get("id")) == kid)
                ku = target_key.get("uuid") or target_key.get("id")
                await m("keys.unmount", {"uuid": ku})
                await m("keys.mount", {"uuid": ku})
                await m("keys.delete", {"uuid": ku})
                assert all((k.get("uuid") or k.get("id")) != ku
                           for k in await q("keys.list"))

                # ---- round 5: decrypt, thumbs, rescans, deletes, ----
                # ---- dismiss-one, clear-one, live subscriptions ----
                await m("locations.quickRescan",
                        {"library_id": lid, "location_id": loc})
                paths5 = await q("search.paths",
                                 {"library_id": lid, "take": 500})
                enc_fp = next(p for p in paths5["items"]
                              if p["extension"] == "sdtpu")
                await m("files.decryptFiles",
                        {"library_id": lid, "location_id": loc,
                         "file_path_ids": [enc_fp["id"]],
                         "password": "pw-ui-test"})
                await node.jobs.wait_idle()
                dec_path = os.path.join(corpus, "docs", "file3.txt")
                assert os.path.exists(dec_path), os.listdir(
                    os.path.join(corpus, "docs"))
                with open(dec_path, "rb") as f:
                    assert f.read(9) == b"content 3"

                # thumbs job + its newThumbnail feed
                thumb_id = 7100
                await ws_raw.send_json(
                    {"id": thumb_id, "type": "subscription",
                     "path": "jobs.newThumbnail", "input": {}})
                driven.add("jobs.newThumbnail")
                prog_id = 7101
                await ws_raw.send_json(
                    {"id": prog_id, "type": "subscription",
                     "path": "jobs.progress", "input": {}})
                driven.add("jobs.progress")
                await m("jobs.generateThumbsForLocation",
                        {"library_id": lid, "id": loc})
                await node.jobs.wait_idle()
                thumb_dir = os.path.join(str(node.data_dir), "thumbnails")
                webps = [os.path.join(r, f)
                         for r, _, fs in os.walk(thumb_dir) for f in fs
                         if f.endswith(".webp")]
                assert webps, "thumbs job produced no thumbnails"
                got_thumb_ev = got_prog_ev = False
                for _ in range(100):
                    if got_thumb_ev and got_prog_ev:
                        break
                    try:
                        msg = await asyncio.wait_for(
                            ws_raw.receive(), timeout=1)
                    except asyncio.TimeoutError:
                        break
                    frame = json.loads(msg.data)
                    if frame.get("type") != "event":
                        continue
                    if frame.get("id") == thumb_id:
                        got_thumb_ev = True
                    elif frame.get("id") == prog_id:
                        got_prog_ev = True
                assert got_thumb_ev, "no jobs.newThumbnail event"
                for sid in (thumb_id, prog_id):
                    await ws_raw.send_json(
                        {"id": sid, "type": "subscriptionStop"})

                # invalidation feed: an invalidating mutation must push
                # its key so the UI refetches
                inv_id = 7102
                await ws_raw.send_json(
                    {"id": inv_id, "type": "subscription",
                     "path": "invalidation.listen", "input": {}})
                driven.add("invalidation.listen")
                tag3 = await m("tags.create",
                               {"library_id": lid, "name": "inv-probe"})
                got_inv = None
                for _ in range(40):
                    msg = await asyncio.wait_for(ws_raw.receive(),
                                                 timeout=10)
                    frame = json.loads(msg.data)
                    if (frame.get("id") == inv_id
                            and frame.get("type") == "event"
                            and frame["data"].get("key") == "tags.list"):
                        got_inv = frame["data"]
                        break
                assert got_inv, "no invalidation event for tags.list"
                await ws_raw.send_json(
                    {"id": inv_id, "type": "subscriptionStop"})
                await m("tags.delete", {"library_id": lid,
                                        "id": tag3["id"]})

                # sync.newMessage fires on local op-log writes
                sync_id = 7103
                await ws_raw.send_json(
                    {"id": sync_id, "type": "subscription",
                     "path": "sync.newMessage",
                     "input": {"library_id": lid}})
                driven.add("sync.newMessage")
                await m("files.setNote",
                        {"library_id": lid, "id": oid, "note": "sync ev"})
                got_sync = False
                for _ in range(40):
                    msg = await asyncio.wait_for(ws_raw.receive(),
                                                 timeout=10)
                    frame = json.loads(msg.data)
                    if (frame.get("id") == sync_id
                            and frame.get("type") == "event"):
                        got_sync = True
                        break
                assert got_sync, "no sync.newMessage event"
                await ws_raw.send_json(
                    {"id": sync_id, "type": "subscriptionStop"})

                # notifications: library variant + dismiss ONE
                await m("notifications.testLibrary", {"library_id": lid})
                notifs = await q("notifications.get")
                assert any(n["library_id"] == lid for n in notifs)
                first = next(n for n in notifs if n["library_id"] == lid)
                await m("notifications.dismiss",
                        {"library_id": lid, "id": first["id"]})
                after = await q("notifications.get")
                assert next(n for n in after
                            if n["id"] == first["id"])["read"] == 1

                # clear ONE job report, keep the rest
                reports5 = await q("jobs.reports", {"library_id": lid})
                done = next(r for r in reports5 if r["status"] == 2)
                await m("jobs.clear", {"library_id": lid,
                                       "id": done["id"]})
                left5 = await q("jobs.reports", {"library_id": lid})
                assert all(r["id"] != done["id"] for r in left5)

                # second location lifecycle: create → delete
                extra_dir = os.path.join(corpus, "..", "extra-loc")
                os.makedirs(extra_dir, exist_ok=True)
                with open(os.path.join(extra_dir, "z.txt"), "w") as f:
                    f.write("z")
                loc2 = await m("locations.create",
                               {"library_id": lid, "path": extra_dir,
                                "dry_run": True})
                await m("locations.delete",
                        {"library_id": lid, "location_id": loc2})
                locs5 = await q("locations.list", {"library_id": lid})
                assert all(x["id"] != loc2 for x in locs5)

                # library lifecycle: delete the second library
                await m("library.delete", {"id": lib2["uuid"]})
                assert all(x["uuid"] != lib2["uuid"]
                           for x in await q("library.list"))

                # ---- subscription round trip (notifications panel) ----
                sub_id = 9001
                await ws_raw.send_json({"id": sub_id, "type": "subscription",
                                        "path": "notifications.listen",
                                        "input": {}})
                driven.add("notifications.listen")
                await m("notifications.test")
                got_event = None
                for _ in range(20):
                    msg = await asyncio.wait_for(
                        ws_raw.receive(), timeout=10)
                    frame = json.loads(msg.data)
                    if (frame.get("id") == sub_id
                            and frame.get("type") == "event"):
                        got_event = frame
                        break
                assert got_event, "no notification event arrived"
                await ws_raw.send_json(
                    {"id": sub_id, "type": "subscriptionStop"})
                await q("notifications.get")
                await m("notifications.dismissAll")

        await server.stop()
        await node.shutdown()

    _run(main())
    assert len(driven) >= 80, (
        f"only {len(driven)} procedures driven: {sorted(driven)}")


def test_virtual_explorer_windows_100k(tmp_path):
    """The explorer is VIRTUALIZED (VERDICT r4 item 2): the engine
    handles 1M-file libraries, so its UI must browse past the first
    window. This drives the exact windowed RPC sequence the virtual
    grid issues (vgFetch: search.paths skip/take + server-side order)
    against a generated 100k-file library and asserts scroll-to-end
    reaches the last row with bounded per-window latency.

    Static guards pin the JS to the windowed renderer: the old
    `take: 400` full-fetch is gone, the window size respects the
    server's take cap, and every server-side narrowing the windows
    rely on (favorite/extensions/order) is sent by the client."""
    import time
    import uuid as uuidlib

    js = _ui_js()
    assert "take: 400" not in js, "explorer regressed to full fetch"
    assert "vgFetch" in js and "skip:" in js
    m = re.search(r"const VWIN = (\d+)", js)
    assert m and int(m.group(1)) <= 500, "window exceeds server take cap"
    for token in ("filter.favorite", "filter.extensions", "order:"):
        assert token.replace("order:", "order") in js.replace(
            "order:", "order"), token

    node = Node(str(tmp_path / "data"))
    lib = node.create_library("big")
    loc_id = lib.db.insert("location", {
        "pub_id": uuidlib.uuid4().bytes, "name": "synthetic",
        "path": str(tmp_path / "root")})
    with lib.db.tx() as conn:
        exts = ["txt", "jpg", "png", "pdf", "mp4", "py", ""]
        conn.executemany(
            "INSERT INTO file_path (pub_id, location_id,"
            " materialized_path, name, extension, is_dir,"
            " date_modified) VALUES (?, ?, ?, ?, ?, 0, ?)",
            [(uuidlib.uuid4().bytes, loc_id, "/", f"file-{i:06d}",
              exts[i % len(exts)], 1_700_000_000 + i)
             for i in range(100_000)])

    async def main():
        server = ApiServer(node)
        port = await server.start(port=0)
        async with aiohttp.ClientSession() as http:
            async with http.ws_connect(
                    f"http://127.0.0.1:{port}/rspc") as ws_raw:
                ws = _Ws(ws_raw)
                lid = (await ws.q("library.list"))[0]["uuid"]
                filt = {"location_id": loc_id,
                        "materialized_path": "/"}
                n = await ws.q("search.pathsCount",
                               {"library_id": lid, "filter": filt})
                assert n == 100_000
                # scroll-to-end: the windows the virtual grid fetches
                # on a jump to the bottom, plus spot windows on the way
                worst = 0.0
                for skip in (0, 37_800, 50_000, 99_800):
                    t0 = time.monotonic()
                    r = await ws.q("search.paths",
                                   {"library_id": lid, "filter": filt,
                                    "skip": skip, "take": 200})
                    worst = max(worst, time.monotonic() - t0)
                    assert len(r["items"]) == 200
                    assert r["items"][0]["name"] == f"file-{skip:06d}"
                assert r["items"][-1]["name"] == "file-099999", \
                    "scroll-to-end did not reach the last row"
                assert worst < 0.25, f"window latency {worst:.3f}s"
                # server-side sort: deep window under the sorted order
                t0 = time.monotonic()
                r = await ws.q("search.paths",
                               {"library_id": lid, "filter": filt,
                                "skip": 99_995, "take": 5,
                                "order": {"field": "name",
                                          "desc": True}})
                assert time.monotonic() - t0 < 1.5
                assert r["items"][-1]["name"] == "file-000000"
                # server-side extension filter keeps indices stable
                n_img = await ws.q(
                    "search.pathsCount",
                    {"library_id": lid,
                     "filter": {**filt, "extensions": ["jpg", "png"]}})
                r = await ws.q(
                    "search.paths",
                    {"library_id": lid,
                     "filter": {**filt, "extensions": ["jpg", "png"]},
                     "skip": n_img - 2, "take": 2})
                assert len(r["items"]) == 2 and all(
                    x["extension"] in ("jpg", "png") for x in r["items"])
        await server.stop()

    _run(main())
