"""FS ops jobs + shallow scan + watcher tests (fs/{copy,cut,delete,erase}.rs
behavior; watcher mirrors the reference's real-watcher tempdir tests,
core/src/location/manager/watcher/mod.rs:352-728)."""

import asyncio
import os

import pytest

from spacedrive_tpu.jobs.report import JobStatus
from spacedrive_tpu.locations.manager import create_location
from spacedrive_tpu.node import Node
from spacedrive_tpu.objects.fs_ops import (
    FileCopierJob,
    FileCutterJob,
    FileDeleterJob,
    FileEraserJob,
    append_digit_to_filename,
    find_available_filename_for_duplicate,
)


def _run(coro):
    return asyncio.run(coro)


@pytest.fixture
def env(tmp_path):
    src = tmp_path / "src"
    dst = tmp_path / "dst"
    (src / "sub").mkdir(parents=True)
    dst.mkdir()
    (src / "a.txt").write_bytes(b"alpha")
    (src / "sub" / "b.txt").write_bytes(b"beta")
    node = Node(str(tmp_path / "data"))
    lib = node.create_library("t")

    async def setup():
        from spacedrive_tpu.locations.indexer_job import IndexerJob
        sid = create_location(lib, str(src))
        did = create_location(lib, str(dst))
        j = await node.jobs.ingest(lib, IndexerJob(location_id=sid))
        await node.jobs.wait(j)
        j = await node.jobs.ingest(lib, IndexerJob(location_id=did))
        assert await node.jobs.wait(j) in (
            JobStatus.COMPLETED, JobStatus.COMPLETED_WITH_ERRORS)
        return sid, did
    sid, did = _run(setup())
    return node, lib, str(src), str(dst), sid, did


def _fp_id(lib, name):
    return lib.db.query_one(
        "SELECT id FROM file_path WHERE name = ?", (name,))["id"]


def test_append_digit():
    assert append_digit_to_filename("report", "pdf", 2) == "report (2).pdf"
    assert append_digit_to_filename("report (1)", "pdf", 2) == "report (2).pdf"
    assert append_digit_to_filename("dir", None, 1) == "dir (1)"


def test_find_available(tmp_path):
    (tmp_path / "f.txt").write_text("x")
    (tmp_path / "f (1).txt").write_text("x")
    avail = find_available_filename_for_duplicate(str(tmp_path / "f.txt"))
    assert avail == str(tmp_path / "f (2).txt")


def test_copy_file_and_dir(env):
    node, lib, src, dst, sid, did = env

    async def main():
        job = FileCopierJob(
            location_id=sid,
            file_path_ids=[_fp_id(lib, "a"), _fp_id(lib, "sub")],
            target_location_id=did)
        jid = await node.jobs.ingest(lib, job)
        assert await node.jobs.wait(jid) == JobStatus.COMPLETED
    _run(main())
    assert open(f"{dst}/a.txt").read() == "alpha"
    assert open(f"{dst}/sub/b.txt").read() == "beta"
    # Re-copying identical content is an idempotent no-op (replay
    # semantics), NOT a " (1)" duplicate.
    _run(main())
    assert not os.path.exists(f"{dst}/a (1).txt")
    # But a changed source under the same name dedup-names.
    with open(f"{dst}/a.txt", "w") as f:
        f.write("different")
    _run(main())
    assert open(f"{dst}/a (1).txt").read() == "alpha"


def test_duplicate_in_same_dir(env):
    node, lib, src, dst, sid, did = env

    async def main():
        job = FileCopierJob(
            location_id=sid, file_path_ids=[_fp_id(lib, "a")],
            target_location_id=sid)  # same location, same dir
        jid = await node.jobs.ingest(lib, job)
        assert await node.jobs.wait(jid) == JobStatus.COMPLETED
    _run(main())
    assert open(f"{src}/a (1).txt").read() == "alpha"


def test_cut(env):
    node, lib, src, dst, sid, did = env

    async def main():
        job = FileCutterJob(
            location_id=sid, file_path_ids=[_fp_id(lib, "a")],
            target_location_id=did)
        jid = await node.jobs.ingest(lib, job)
        assert await node.jobs.wait(jid) == JobStatus.COMPLETED
    _run(main())
    assert not os.path.exists(f"{src}/a.txt")
    assert open(f"{dst}/a.txt").read() == "alpha"


def test_delete(env):
    node, lib, src, dst, sid, did = env

    async def main():
        job = FileDeleterJob(
            location_id=sid,
            file_path_ids=[_fp_id(lib, "a"), _fp_id(lib, "sub")])
        jid = await node.jobs.ingest(lib, job)
        assert await node.jobs.wait(jid) == JobStatus.COMPLETED
    _run(main())
    assert not os.path.exists(f"{src}/a.txt")
    assert not os.path.exists(f"{src}/sub")


def test_erase_overwrites_then_removes(env):
    node, lib, src, dst, sid, did = env

    async def main():
        job = FileEraserJob(
            location_id=sid, file_path_ids=[_fp_id(lib, "sub")], passes=2)
        jid = await node.jobs.ingest(lib, job)
        assert await node.jobs.wait(jid) == JobStatus.COMPLETED
    _run(main())
    assert not os.path.exists(f"{src}/sub")


def test_shallow_light_scan(env):
    node, lib, src, dst, sid, did = env
    from spacedrive_tpu.locations.shallow import light_scan_location
    # New file appears; light scan of its dir picks it up + identifies it.
    with open(f"{src}/sub/new.bin", "wb") as f:
        f.write(b"fresh-content" * 10)
    res = light_scan_location(lib, sid, "sub", backend="numpy")
    assert res["saved"] == 1 and res["created"] >= 1
    row = lib.db.query_one(
        "SELECT cas_id, object_id FROM file_path WHERE name='new'")
    assert row["cas_id"] is not None and row["object_id"] is not None
    # File vanishes; rescan removes the row.
    os.remove(f"{src}/sub/new.bin")
    res = light_scan_location(lib, sid, "sub", backend="numpy")
    assert res["removed"] == 1
    assert lib.db.query_one(
        "SELECT * FROM file_path WHERE name='new'") is None


@pytest.mark.skipif(not os.path.exists("/proc"), reason="linux only")
def test_watcher_detects_create_and_delete(env):
    node, lib, src, dst, sid, did = env

    async def main():
        from spacedrive_tpu.locations.watcher import Locations
        locations = Locations(node, backend="numpy")
        assert locations.watch_location(lib, sid)
        # Create a file and wait for the debounce + scan.
        with open(f"{src}/watched.bin", "wb") as f:
            f.write(b"watch-me" * 50)
        for _ in range(50):
            await asyncio.sleep(0.1)
            row = lib.db.query_one(
                "SELECT object_id FROM file_path WHERE name='watched'")
            if row is not None and row["object_id"] is not None:
                break
        else:
            raise AssertionError("watcher never indexed the new file")
        os.remove(f"{src}/watched.bin")
        for _ in range(50):
            await asyncio.sleep(0.1)
            if lib.db.query_one(
                    "SELECT * FROM file_path WHERE name='watched'") is None:
                break
        else:
            raise AssertionError("watcher never removed the deleted file")
        locations.close()
    _run(main())


def test_cross_directory_move_repaths_by_inode(env):
    """mv A/f B/f between rescans: the row is re-pathed in place (inode
    match), keeping its object link — not dropped on the unique
    constraint."""
    node, lib, src, dst, sid, did = env

    async def main():
        from spacedrive_tpu.locations.indexer_job import IndexerJob
        from spacedrive_tpu.objects.identifier import FileIdentifierJob

        with open(f"{src}/moveme.bin", "wb") as f:
            f.write(b"move-payload" * 40)
        for job in (IndexerJob(location_id=sid),
                    FileIdentifierJob(location_id=sid)):
            jid = await node.jobs.ingest(lib, job)
            await node.jobs.wait(jid)
        before = lib.db.query_one(
            "SELECT pub_id, object_id, cas_id, inode FROM file_path "
            "WHERE name='moveme'")
        assert before["object_id"] is not None

        os.rename(f"{src}/moveme.bin", f"{src}/sub/moveme.bin")
        jid = await node.jobs.ingest(lib, IndexerJob(location_id=sid))
        await node.jobs.wait(jid)

        rows = lib.db.query(
            "SELECT * FROM file_path WHERE name='moveme'")
        assert len(rows) == 1, [dict(r) for r in rows]
        after = rows[0]
        assert after["pub_id"] == before["pub_id"]  # same row, re-pathed
        assert after["materialized_path"] == "/sub/"
        assert after["object_id"] == before["object_id"]
        assert after["cas_id"] == before["cas_id"]
    _run(main())


@pytest.mark.skipif(not os.path.exists("/proc"), reason="linux only")
def test_watcher_detects_rename(env):
    """Cookie-paired MOVED_FROM/MOVED_TO: the old name disappears, the
    new name appears with the same content identity."""
    node, lib, src, dst, sid, did = env

    async def main():
        from spacedrive_tpu.locations.watcher import Locations
        locations = Locations(node, backend="numpy")
        assert locations.watch_location(lib, sid)
        with open(f"{src}/before.bin", "wb") as f:
            f.write(b"rename-me" * 60)
        for _ in range(50):
            await asyncio.sleep(0.1)
            row = lib.db.query_one(
                "SELECT cas_id FROM file_path WHERE name='before'")
            if row is not None and row["cas_id"]:
                break
        else:
            raise AssertionError("watcher never indexed the file")
        old_cas = row["cas_id"]
        os.rename(f"{src}/before.bin", f"{src}/after.bin")
        for _ in range(50):
            await asyncio.sleep(0.1)
            new = lib.db.query_one(
                "SELECT cas_id FROM file_path WHERE name='after'")
            gone = lib.db.query_one(
                "SELECT 1 FROM file_path WHERE name='before'") is None
            if new is not None and new["cas_id"] and gone:
                break
        else:
            raise AssertionError("rename not reflected")
        assert new["cas_id"] == old_cas  # same bytes → same identity
        locations.close()
    _run(main())


def test_polling_watcher_fallback_detects_changes(env, monkeypatch):
    """The polling fallback (platforms without inotify) must deliver
    the same create/delete → light-scan behavior. Forced on Linux via
    SDTPU_WATCHER=poll — round 4 shipped the fallback claim with no
    implementation behind it."""
    node, lib, src, dst, sid, did = env
    monkeypatch.setenv("SDTPU_WATCHER", "poll")

    async def main():
        from spacedrive_tpu.locations.watcher import (Locations,
                                                      PollingWatcher)
        locations = Locations(node, backend="numpy")
        assert locations.watch_location(lib, sid)
        w = locations.watchers[(lib.id, sid)]
        assert isinstance(w, PollingWatcher), type(w)
        with open(f"{src}/polled.bin", "wb") as f:
            f.write(b"poll-me" * 50)
        for _ in range(80):
            await asyncio.sleep(0.1)
            row = lib.db.query_one(
                "SELECT object_id FROM file_path WHERE name='polled'")
            if row is not None and row["object_id"] is not None:
                break
        else:
            raise AssertionError("polling watcher never indexed")
        os.remove(f"{src}/polled.bin")
        for _ in range(80):
            await asyncio.sleep(0.1)
            if lib.db.query_one(
                    "SELECT * FROM file_path WHERE name='polled'") is None:
                break
        else:
            raise AssertionError("polling watcher never removed")
        locations.close()
    _run(main())
