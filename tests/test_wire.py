"""Declared wire contracts (spacedrive_tpu/p2p/wire.py).

Unit coverage for the registry itself — pack/unpack semantics, frame
classification, the tunnel-seam auditor and its arming switch — plus
the transport-level regression tests the contracts promise:

- oversize refusal at every seam a frame enters (the transport's
  MAX_FRAME bound, `unpack(size=)`, the binary scalar check);
- the AEAD tunnel round-trips every protocol family raise-clean with
  the conftest-armed auditor watching both directions (skipped where
  the container lacks `cryptography` — the registry itself imports
  without it by design);
- a two-node stub-transport load_bench smoke that must finish with a
  zero wire-violation census while real clone frames flow.

tools/wire_grid.py (gated by test_wire_grid.py) owns the systematic
message x mutation matrix; this file owns the semantics the grid
builds on.
"""

import asyncio
import contextlib
import json
import os
import socket
import struct
import subprocess
import sys

import pytest

from spacedrive_tpu import timeouts
from spacedrive_tpu.p2p import wire
from spacedrive_tpu.telemetry import WIRE_FRAMES, WIRE_VIOLATIONS

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def _labeled(counter):
    """{labels-tuple: value} snapshot of one labeled counter family."""
    return {tuple(sorted(labels.items())): metric.value
            for labels, metric in counter.samples() if labels}


def _delta(before, after):
    return {k: after[k] - before.get(k, 0.0) for k in after
            if after[k] != before.get(k, 0.0)}


@contextlib.contextmanager
def _auditor():
    """Arm the frame auditor with a collecting recorder; restore the
    session's arming (conftest installs raise mode) on exit."""
    prev = (wire._armed, wire._mode, wire._recorder)
    seen = []
    wire.arm("count",
             lambda kind, detail, may_raise: seen.append((kind, detail)))
    try:
        yield seen
    finally:
        wire._armed, wire._mode, wire._recorder = prev


# -- the registry itself -----------------------------------------------------

def test_registry_inventory_invariants():
    """Every declaration is internally coherent: name prefix == proto
    group, version == the group's PROTO_VERSIONS entry, caps bounded
    by MAX_FRAME, budgets declared in timeouts.py, exactly one payload
    family."""
    assert len(wire.MESSAGES) >= 26
    for name, msg in wire.MESSAGES.items():
        assert name.split(".")[0] == msg.group
        assert msg.version == wire.PROTO_VERSIONS[msg.group]
        assert 0 < msg.size_cap <= wire.MAX_FRAME
        assert msg.timeout_budget in timeouts.TIMEOUTS
        families = (msg.values is not None, msg.binary, bool(msg.fields))
        assert sum(families) == 1, (name, families)
        assert msg.doc


def test_registry_lookups_refuse_unknowns():
    with pytest.raises(wire.WireError, match="undeclared"):
        wire.message("nope.frame")
    with pytest.raises(KeyError, match="unknown wire proto group"):
        wire.proto("nope")
    with pytest.raises(KeyError, match="declares no slice_cap"):
        wire.slice_cap("p2p.ping")
    assert wire.slice_cap("obs.trace") == \
        wire.MESSAGES["obs.trace"].slice_cap


def test_module_constants_are_registry_reads():
    """Satellite: the old per-module literals (TRACE_SLICE_LIMIT, the
    obs proto rev) are now reads off the declarations — static and
    runtime cannot drift."""
    from spacedrive_tpu.p2p import obs

    assert obs.TRACE_SLICE_LIMIT == wire.slice_cap("obs.trace")
    assert obs.INCIDENT_SLICE_LIMIT == wire.slice_cap("obs.incidents")
    assert obs.OBS_PROTO == wire.proto("obs")


def test_sync_proto_is_a_registry_read():
    pytest.importorskip("cryptography")
    from spacedrive_tpu.p2p import sync_net

    assert sync_net.SYNC_PROTO == wire.proto("sync")
    # sync and clone version together: the clone fast path is a
    # sync-stream answer
    assert wire.proto("clone") == wire.proto("sync")


# -- pack --------------------------------------------------------------------

def test_pack_fills_consts_and_version_fields():
    frame = wire.pack("sync.announce", library_id="lib")
    assert frame == {"t": "sync", "kind": "new_ops",
                     "library_id": "lib",
                     "proto": wire.proto("sync")}


def test_pack_name_is_positional_only():
    """spaceblock.request legitimately declares a schema field called
    `name` — pack's own name parameter must not collide with it."""
    frame = wire.pack("spaceblock.request", name="f.bin", size=10)
    assert frame["name"] == "f.bin" and frame["size"] == 10


def test_pack_refuses_drift():
    with pytest.raises(wire.WireSchemaError, match="not in the declared"):
        wire.pack("p2p.pair.request", extra=1)
    with pytest.raises(wire.WireSchemaError, match="missing"):
        wire.pack("p2p.pair.request", library_id="only")
    with pytest.raises(wire.WireSchemaError, match="must be str"):
        wire.pack("sync.announce", library_id=7)
    # bools are not ints, even though Python says so
    with pytest.raises(wire.WireSchemaError, match="must be int"):
        wire.pack("clone.ack", ts=True, fast=False)
    with pytest.raises(wire.WireSchemaError, match="const field"):
        wire.pack("p2p.ping", t="pong")


def test_pack_optional_semantics():
    assert wire.pack("p2p.ping") == {"t": "ping"}
    # an explicit optional None rides along (peers see the key)
    assert wire.pack("p2p.ping", tp=None) == {"t": "ping", "tp": None}
    # float fields tolerate ints (msgpack peers send both)
    assert wire.pack("obs.response", status="ok", ts=3)["ts"] == 3


def test_pack_values_and_binary_frames():
    assert wire.pack("p2p.spacedrop.verdict", value="accept") == "accept"
    assert wire.pack("spaceblock.chunk", value=b"\x01") == b"\x01"
    with pytest.raises(wire.WireSchemaError, match="not in declared"):
        wire.pack("p2p.spacedrop.verdict", value="maybe")
    with pytest.raises(wire.WireSchemaError, match="empty binary"):
        wire.pack("spaceblock.chunk", value=b"")
    with pytest.raises(wire.WireSchemaError, match="exactly one kwarg"):
        wire.pack("spaceblock.chunk", data=b"\x01")


# -- unpack ------------------------------------------------------------------

def test_unpack_tolerates_unknown_inbound_fields():
    """Forward compatibility: a newer peer may send more than we know."""
    frame = {"t": "ping", "tp": "abc", "novel_field": 42}
    assert wire.unpack("p2p.ping", frame) is frame


def test_unpack_refuses_schema_drift():
    with pytest.raises(wire.WireSchemaError, match="missing"):
        wire.unpack("clone.ack", {"kind": "ack", "fast": True})
    with pytest.raises(wire.WireSchemaError, match="is None"):
        wire.unpack("clone.ack", {"kind": "ack", "ts": None, "fast": True})
    with pytest.raises(wire.WireSchemaError, match="const field"):
        wire.unpack("p2p.ping", {"t": "pong"})
    with pytest.raises(wire.WireSchemaError, match="must be int"):
        wire.unpack("clone.ack", {"kind": "ack", "ts": "7", "fast": True})
    with pytest.raises(wire.WireSchemaError, match="map frame"):
        wire.unpack("p2p.ping", ["t", "ping"])


def test_unpack_version_discipline():
    ours = wire.proto("sync")
    good = wire.pack("sync.announce", library_id="lib")
    assert wire.unpack("sync.announce", good) is good
    skewed = dict(good, proto=ours + 1)
    with pytest.raises(wire.WireVersionError, match="peer wire proto"):
        wire.unpack("sync.announce", skewed)
    # obs.response REQUIRES its version const; absence is a skew too
    with pytest.raises(wire.WireVersionError, match="missing"):
        wire.unpack("obs.response", {"status": "ok"})
    # "=proto?" tolerates absence but still rejects a present mismatch
    assert wire.unpack("obs.metrics", {"t": "obs.metrics"})
    with pytest.raises(wire.WireVersionError):
        wire.unpack("obs.metrics",
                    {"t": "obs.metrics", "proto": wire.proto("obs") + 1})


def test_unpack_enforces_declared_size_caps():
    cap = wire.MESSAGES["p2p.ping"].size_cap
    frame = wire.pack("p2p.ping")
    assert wire.unpack("p2p.ping", frame, size=cap) is frame
    with pytest.raises(wire.WireSizeError, match="over the declared"):
        wire.unpack("p2p.ping", frame, size=cap + 1)


def test_binary_frames_carry_their_own_cap():
    cap = wire.MESSAGES["spaceblock.chunk"].size_cap
    with pytest.raises(wire.WireSizeError):
        wire.unpack("spaceblock.chunk", b"\x00" * (cap + 1))
    with pytest.raises(wire.WireSchemaError, match="raw bytes"):
        wire.unpack("spaceblock.chunk", "not-bytes")


# -- classify ----------------------------------------------------------------

def test_classify_by_discriminator_value_and_shape():
    assert wire.classify({"t": "ping"}) == ("p2p.ping",)
    assert wire.classify(
        wire.pack("sync.announce", library_id="l")) == ("sync.announce",)
    assert wire.classify("accept") == ("p2p.spacedrop.verdict",)
    assert wire.classify("ok") == ("spaceblock.verdict",)
    assert wire.classify(b"\x01") == ("spaceblock.chunk",)
    assert wire.classify("zork") == ()
    assert wire.classify({"zork": 1}) == ()
    assert wire.classify(3.14) == ()


def test_classify_structural_fallback_is_deterministic():
    """The const-less status envelopes are structurally identical —
    classification returns ALL of them, alphabetically, and the
    auditor tries each until one unpacks clean."""
    assert wire.classify({"status": "ok"}) == (
        "obs.response", "p2p.file.response", "p2p.pair.response")


# -- the tunnel-seam auditor -------------------------------------------------

def test_audit_frame_census_and_violation_flow():
    with _auditor() as seen:
        frames_before = _labeled(WIRE_FRAMES)
        control = wire.pack("p2p.ping")
        assert wire.audit_frame(control, "in", 16) == "p2p.ping"
        assert seen == []
        grew = _delta(frames_before, _labeled(WIRE_FRAMES))
        assert grew == {(("dir", "in"), ("name", "p2p.ping")): 1.0}

        viols_before = _labeled(WIRE_VIOLATIONS)
        assert wire.audit_frame({"t": "ping", "tp": 7}, "in", 16) is None
        assert [kind for kind, _ in seen] == ["wire_violation"]
        assert "p2p.ping" in seen[0][1]
        grew = _delta(viols_before, _labeled(WIRE_VIOLATIONS))
        assert grew == {(("kind", "schema"),): 1.0}


def test_audit_frame_subkind_attribution():
    cases = [
        (dict(wire.pack("sync.announce", library_id="l"),
              proto=wire.proto("sync") + 1), None, "proto_skew"),
        (wire.pack("p2p.ping"),
         wire.MESSAGES["p2p.ping"].size_cap + 1, "size_cap"),
        ({"t": "no_such_kind"}, 8, "undeclared"),
    ]
    for frame, nbytes, want in cases:
        with _auditor() as seen:
            before = _labeled(WIRE_VIOLATIONS)
            assert wire.audit_frame(frame, "out", nbytes) is None
            assert len(seen) == 1
            grew = _delta(before, _labeled(WIRE_VIOLATIONS))
            assert grew == {(("kind", want),): 1.0}, (frame, grew)


def test_audit_frame_disarmed_is_inert():
    prev = (wire._armed, wire._mode, wire._recorder)
    try:
        wire.disarm()
        before = _labeled(WIRE_FRAMES)
        assert wire.audit_frame(wire.pack("p2p.ping"), "in", 8) is None
        assert _delta(before, _labeled(WIRE_FRAMES)) == {}
    finally:
        wire._armed, wire._mode, wire._recorder = prev


def test_wire_audit_off_flag_skips_arming(monkeypatch):
    prev = (wire._armed, wire._mode, wire._recorder)
    try:
        wire.disarm()
        monkeypatch.setenv("SDTPU_WIRE_AUDIT", "off")
        wire.arm("raise", lambda kind, detail, may_raise: None)
        assert not wire.armed()
        # pack/unpack still validate with the auditor off
        with pytest.raises(wire.WireSchemaError):
            wire.pack("p2p.ping", bogus=1)
        monkeypatch.delenv("SDTPU_WIRE_AUDIT")
        wire.arm("count", lambda kind, detail, may_raise: None)
        assert wire.armed()
    finally:
        wire._armed, wire._mode, wire._recorder = prev


# -- transports --------------------------------------------------------------

def test_transport_frame_cap_is_the_registry_bound():
    pytest.importorskip("cryptography")
    from spacedrive_tpu.p2p import proto

    assert proto.MAX_FRAME is wire.MAX_FRAME

    async def oversized_header():
        reader = asyncio.StreamReader()
        reader.feed_data(struct.pack(">I", wire.MAX_FRAME + 1))
        reader.feed_eof()
        with pytest.raises(proto.ProtoError, match="frame too large"):
            await proto.read_frame(reader)

    asyncio.run(oversized_header())


def test_aead_tunnel_round_trips_raise_clean():
    """Every protocol family crosses a real ChaCha20-Poly1305 tunnel
    pair — ping, pairing, sync, clone, plus the bare-string and raw
    chunk shapes — with the conftest-armed raise-mode auditor watching
    both directions: any contract breach tears the test down."""
    pytest.importorskip("cryptography")
    from spacedrive_tpu.p2p.proto import Tunnel

    frames = [
        ("p2p.ping", wire.pack("p2p.ping", tp="t1")),
        ("p2p.pong", wire.pack("p2p.pong")),
        ("p2p.pair.request", wire.pack(
            "p2p.pair.request", library_id="lib", library_name="Lib",
            listen_port=7373, instance={"pub_id": "aa"})),
        ("p2p.pair.response", wire.pack(
            "p2p.pair.response", status="accepted",
            instance={"pub_id": "bb"})),
        ("sync.announce", wire.pack("sync.announce", library_id="lib")),
        ("sync.pull.request", wire.pack(
            "sync.pull.request", clocks=[], count=64)),
        ("sync.pull.page", wire.pack(
            "sync.pull.page", ops=[], has_more=False)),
        ("sync.done", wire.pack("sync.done")),
        ("clone.stream", wire.pack("clone.stream", window=4)),
        ("clone.page", wire.pack(
            "clone.page", model="file_path", instance=b"\x01",
            min_ts=1, max_ts=2, n_ops=1, data=b"\x02")),
        ("clone.ack", wire.pack("clone.ack", ts=2, fast=True)),
        ("clone.done", wire.pack("clone.done")),
        ("p2p.spacedrop.verdict",
         wire.pack("p2p.spacedrop.verdict", value="accept")),
    ]

    async def round_trip():
        s1, s2 = socket.socketpair()
        r1, w1 = await asyncio.open_connection(sock=s1)
        r2, w2 = await asyncio.open_connection(sock=s2)
        k1, k2 = os.urandom(32), os.urandom(32)
        a = Tunnel(r1, w1, send_key=k1, recv_key=k2, remote=None)
        b = Tunnel(r2, w2, send_key=k2, recv_key=k1, remote=None)
        try:
            for name, frame in frames:
                await a.send(frame)
                got = await b.recv()
                assert wire.unpack(name, got) == frame
                # and back the other way, via the pipelined path
                b.send_nowait(frame)
                await b.drain()
                assert wire.unpack(name, await a.recv()) == frame
            # the raw-bytes shape (spaceblock chunks) has its own seam
            await a.send_raw(wire.pack("spaceblock.chunk", value=b"\x07"))
            assert await b.recv_raw() == b"\x07"
        finally:
            a.close()
            b.close()
            await asyncio.sleep(0)

    asyncio.run(round_trip())


def test_two_node_load_bench_smoke_zero_wire_violations(tmp_path):
    """A two-peer stub-transport fleet (clone fast path end to end)
    must finish with an EMPTY wire-violation census while real clone
    frames flow through the audited stub seam — the production-posture
    twin of the raise-mode tier-1 suite."""
    artifact = tmp_path / "smoke.json"
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "SDTPU_SANITIZE": "1",
                "SDTPU_SANITIZE_MODE": "count"})
    proc = subprocess.run(
        [sys.executable, "-m", "tools.load_bench", "--peers", "2",
         "--waves", "1", "--ops-per-wave", "256", "--events", "20",
         "--requests", "2", "--ops-per-peer", "8", "--chaos", "",
         "--json", str(artifact)],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(artifact.read_text())
    assert doc["violations"] == []
    counters = doc["counters"]
    assert counters["sd_wire_violations_total"]["labeled"] == []
    census = {(r["labels"]["name"], r["labels"]["dir"]): r["value"]
              for r in counters["sd_wire_frames_total"]["labeled"]}
    # the clone burst really crossed the audited stub wire
    assert census.get(("clone.done", "in"), 0) > 0
    assert census.get(("clone.done", "out"), 0) > 0
    assert census.get(("clone.page", "in"), 0) > 0
