"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the
multichip path). XLA_FLAGS must be set before jax initializes a backend;
platform selection must go through jax.config because the axon TPU
plugin overrides the JAX_PLATFORMS env var at interpreter start.
"""

import os

import pytest

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon TPU plugin registers at interpreter start (sitecustomize) and
# sets jax_platforms="axon,cpu", so merely calling jax.devices() would
# initialize the TPU tunnel (slow, single-client). Tests never need the
# real chip: restrict platforms to cpu BEFORE any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def cpu_devices():
    return jax.devices("cpu")
