"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the
multichip path). XLA_FLAGS must be set before jax initializes a backend;
platform selection must go through jax.config because the axon TPU
plugin overrides the JAX_PLATFORMS env var at interpreter start.
"""

import os

import pytest

from spacedrive_tpu.xla_env import ensure_host_device_count

ensure_host_device_count(8)

# The identifier's auto mesh-sharded CAS dispatch would compile a fresh
# shard_map program (~50 s on the CPU mesh) per batch grid across the
# whole suite; tests pin the single-device program and the sharded
# dispatch is covered by test_blake3_jax's dedicated case (which flips
# this back) plus the driver's dryrun_multichip stage 6.
os.environ.setdefault("SDTPU_SHARDED_CAS", "off")

# The axon TPU plugin registers at interpreter start (sitecustomize) and
# sets jax_platforms="axon,cpu", so merely calling jax.devices() would
# initialize the TPU tunnel (slow, single-client). Tests never need the
# real chip: restrict platforms to cpu BEFORE any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture
def cpu_devices():
    return jax.devices("cpu")


async def pair_two_nodes(a, b, library_name: str = "shared"):
    """Start both nodes' p2p planes (no discovery) and pair a library
    from A into B. Pairing itself records the sync routes both ways
    (initiator: the dialed address; responder: socket IP + announced
    listen port), so no manual set_route wiring is needed. Returns
    (lib_a, lib_b). Shared by the p2p/fault/live-loop suites."""
    await a.start()
    await b.start()
    await a.start_p2p(host="127.0.0.1", enable_discovery=False)
    pb = await b.start_p2p(host="127.0.0.1", enable_discovery=False)
    lib_a = a.create_library(library_name)
    b.p2p.on_pairing_request = lambda peer, info: True
    assert await a.p2p.pair("127.0.0.1", pb, lib_a)
    lib_b = b.libraries.list()[0]
    return lib_a, lib_b
