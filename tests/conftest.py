"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the
multichip path). XLA_FLAGS must be set before jax initializes a backend;
platform selection must go through jax.config because the axon TPU
plugin overrides the JAX_PLATFORMS env var at interpreter start.
"""

import os

import pytest

from spacedrive_tpu.xla_env import ensure_host_device_count

ensure_host_device_count(8)

# The identifier's auto mesh-sharded CAS dispatch would compile a fresh
# shard_map program (~50 s on the CPU mesh) per batch grid across the
# whole suite; tests pin the single-device program and the sharded
# dispatch is covered by test_blake3_jax's dedicated case (which flips
# this back) plus the driver's dryrun_multichip stage 6.
os.environ.setdefault("SDTPU_SHARDED_CAS", "off")

# Same compile-cost hygiene for the depth-N overlap pipeline: donated
# kernel twins and per-device programs each cost a fresh ~45 s BLAKE3
# compile on CPU for zero extra coverage of the REAL kernel (identity
# pass-through donation cannot change digests). The suite pins the
# undonated single-device programs; the dedicated donation/multi-device
# tests in test_overlap.py flip these back over cheap kernels, and
# test_blake3_jax pins the donated CAS dispatch plumbing.
os.environ.setdefault("SDTPU_DONATE_BUFFERS", "off")
os.environ.setdefault("SDTPU_PIPELINE_DEVICES", "1")

# Tier-1 runs SANITIZED (spacedrive_tpu/sanitize.py): every asyncio
# callback is timed (loop-stall detector), the store's locks record
# acquisition order (cycle check raises), and a lock held across an
# await is a violation. `raise` mode surfaces lock-order cycles as
# exceptions at the acquire; asynchronous detections (stalls,
# held-across-await) are asserted ZERO per test by the autouse fixture
# below. Install BEFORE any Database is constructed so its locks come
# from the sanitizer.
os.environ.setdefault("SDTPU_SANITIZE", "1")
os.environ.setdefault("SDTPU_SANITIZE_MODE", "raise")
# CI containers run 2 cores over a 9p filesystem with ±40% IO weather;
# the production 1.0s stall threshold false-positives there on genuine
# thread-pool contention. 2.5s flaked twice across tier-1 rounds on
# weather-side Task.task_wakeup stalls (3.49s, then 4.498s — each with
# no code on the loop), so the CI margin sits at 6.0s; real loop hogs —
# the class the detector exists for — measured 1.5s+ of pure compute,
# which the 1.0s production threshold flags on real hosts regardless.
os.environ.setdefault("SDTPU_SANITIZE_STALL_S", "6.0")
from spacedrive_tpu import sanitize  # noqa: E402

sanitize.install()

# The axon TPU plugin registers at interpreter start (sitecustomize) and
# sets jax_platforms="axon,cpu", so merely calling jax.devices() would
# initialize the TPU tunnel (slow, single-client). Tests never need the
# real chip: restrict platforms to cpu BEFORE any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from tier-1 (`-m 'not slow'`) — benchmark-scale "
        "cases like the full-library clone")


@pytest.fixture
def cpu_devices():
    return jax.devices("cpu")


@pytest.fixture(autouse=True)
def _sanitizer_clean():
    """Every test must finish with zero NEW sanitizer violations —
    the runtime half of the sdlint acceptance gate. Tests that
    deliberately trigger violations (test_sanitize.py) reset the list
    before returning, so this stays green for them too."""
    before = len(sanitize.violations())
    yield
    fresh = sanitize.violations()[before:]
    assert not fresh, (
        "sanitizer violations during test: "
        + "; ".join(f"{v['kind']}: {v['detail']}" for v in fresh[:3]))


async def pair_two_nodes(a, b, library_name: str = "shared"):
    """Start both nodes' p2p planes (no discovery) and pair a library
    from A into B. Pairing itself records the sync routes both ways
    (initiator: the dialed address; responder: socket IP + announced
    listen port), so no manual set_route wiring is needed. Returns
    (lib_a, lib_b). Shared by the p2p/fault/live-loop suites."""
    await a.start()
    await b.start()
    await a.start_p2p(host="127.0.0.1", enable_discovery=False)
    pb = await b.start_p2p(host="127.0.0.1", enable_discovery=False)
    lib_a = a.create_library(library_name)
    b.p2p.on_pairing_request = lambda peer, info: True
    assert await a.p2p.pair("127.0.0.1", pb, lib_a)
    lib_b = b.libraries.list()[0]
    return lib_a, lib_b


def mk_instance(db, pub_id: bytes) -> int:
    """Insert a bare instance row (the sync suites' fixture shape)."""
    return db.insert("instance", {
        "pub_id": pub_id, "identity": b"", "node_id": b"",
        "node_name": "test", "node_platform": 0,
        "last_seen": 0, "date_created": 0,
    })


def make_sync_manager(tmp_path, name="solo", others=()):
    """A SyncManager over a fresh library DB holding its own instance
    row plus `others` — with no others this is the SOLO configuration
    the page-blob op-log format targets. Shared by the blob-format and
    fuzz suites so the two never drift."""
    import uuid

    from spacedrive_tpu.store.db import Database
    from spacedrive_tpu.sync.manager import SyncManager

    pub = uuid.uuid4().bytes
    db = Database(str(tmp_path / f"{name}.db"))
    mk_instance(db, pub)
    for other in others:
        mk_instance(db, other)
    return SyncManager(db, pub)


def drain_sync(src, dst) -> int:
    """Paged pull-loop drain src → dst through the real
    get_ops/receive_crdt_operations path (the in-process analog of the
    TCP pull loop); returns ops applied, asserts no ingest errors."""
    from spacedrive_tpu.sync.manager import GetOpsArgs

    applied = 0
    while True:
        clocks = dict(dst.timestamps)
        clocks[dst.instance] = max(dst.clock.last,
                                   clocks.get(dst.instance, 0))
        page = src.get_ops(GetOpsArgs(clocks=list(clocks.items()),
                                      count=1000))
        page = [op for op in page if op.instance != dst.instance]
        if not page:
            return applied
        n, errs = dst.receive_crdt_operations(page)
        assert not errs, errs[:3]
        applied += n
