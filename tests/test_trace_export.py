"""Flight recorder: pipeline timeline, cross-node trace propagation,
and the Chrome-trace exporter (spacedrive_tpu/flight.py +
tools/trace_export.py).

Pins the PR's acceptance shapes on CPU:
- a depth-3 sim-link identify run exports a schema-valid Chrome-trace
  JSON with per-device stage/H2D/kernel/retire lanes and per-batch
  bound attribution, race-recorder-clean (the autouse sanitizer
  fixture asserts the zero-violations half);
- the exporter's golden schema: required keys, monotone ts, and a
  named process/thread for every pid/tid;
- a two-node sync pull produces ONE trace id whose spans include both
  the serving (sync.serve) and the ingesting (sync.pull) node
  [skipif-cryptography, like the rest of the TCP p2p plane];
- `python -m tools.trace_export --json` self-checks in tier-1 and
  exits non-zero on a schema violation.
"""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from spacedrive_tpu import flight, tracing

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    # Seed the objects package: in runtimes without `cryptography` the
    # first attempt fails but leaves the non-crypto submodules cached,
    # after which mount_router imports cleanly (container quirk; no-op
    # where the dependency exists — same idiom as test_telemetry).
    import spacedrive_tpu.objects  # noqa: F401
except ModuleNotFoundError:
    pass


def _has_cryptography() -> bool:
    try:
        import cryptography  # noqa: F401
        return True
    except ImportError:
        return False


# -- recorder unit surface --------------------------------------------------

def test_recorder_window_bound_attribution():
    """A retired batch emits one `window` event naming the binding
    component of max(stage, h2d, kernel) and the margin over the
    runner-up."""
    rec = flight.FlightRecorder()
    run = flight.new_run_token()
    t = 100.0
    rec.record("stage", batch=7, t0=t, t1=t + 0.020, stream=1, run=run)
    rec.record("h2d", batch=7, t0=t + 0.020, t1=t + 0.070, device="0",
               run=run)
    rec.record("kernel", batch=7, t0=t + 0.070, t1=t + 0.080,
               device="0", run=run)
    rec.record("retire", batch=7, t0=t + 0.080, t1=t + 0.085, run=run)
    snap = rec.snapshot()
    assert [e["lane"] for e in snap] == [
        "stage", "h2d", "kernel", "retire", "window"]
    win = snap[-1]
    assert win["batch"] == 7
    assert win["binding"] == "h2d"
    # the window inherits the batch's DEVICE stream (h2d/kernel carry
    # it; the shared retire pool does not) so attribution names which
    # stream was bound
    assert win["device"] == "0"
    # margin = h2d (50 ms) - stage (20 ms), in µs with rounding slack
    assert win["margin_us"] == pytest.approx(30_000, abs=200)
    assert set(win["phases_us"]) == {"stage", "h2d", "kernel", "retire"}
    # the whole-batch window spans first stage start → retire end
    assert win["dur_us"] == pytest.approx(85_000, abs=200)


def test_recorder_contract_quiet_under_threads():
    """The threadctx half of the timeline ring: a post-arm recorder's
    _open dict is container-tracked, and concurrent record() storms
    from worker threads — every mutation under the declared _lock —
    stay data_race-quiet (the autouse sanitizer fixture asserts zero
    violations) while every batch still closes to exactly one window."""
    import threading

    from spacedrive_tpu import threadctx

    rec = flight.FlightRecorder()
    if threadctx.armed():
        assert type(rec._open).__name__ == "_TrackedDict"
    run = flight.new_run_token()

    def work(base):
        for i in range(50):
            b = base + i
            rec.record("stage", batch=b, t0=1.0, t1=2.0, run=run)
            rec.record("h2d", batch=b, t0=2.0, t1=3.0, device="0",
                       run=run)
            rec.record("kernel", batch=b, t0=3.0, t1=3.5, device="0",
                       run=run)
            rec.record("retire", batch=b, t0=3.5, t1=4.0, run=run)

    threads = [threading.Thread(target=work, args=(k * 1000,))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wins = [e for e in rec.snapshot() if e["lane"] == "window"]
    assert len(wins) == 200
    assert rec._open == {}  # every batch's window closed at retire


def test_recorder_runless_scopes_never_accumulate_windows():
    """Scopes that never retire (identify host-plane chunks pass no
    run token) are pure lane events: thousands of them must leave the
    open-window map EMPTY — the review-round leak regression (a
    long-running node hashes chunks forever)."""
    rec = flight.FlightRecorder()
    for i in range(1000):
        rec.record("stage", batch=i, t0=1.0, t1=2.0, scope="identify")
        rec.record("kernel", batch=i, t0=2.0, t1=3.0,
                   scope="identify")
    assert rec._open == {}
    # and even WITH run tokens, abandoned windows stop at the cap
    for i in range(flight._OPEN_CAP * 2):
        rec.record("stage", batch=0, t0=1.0, t1=2.0,
                   run=flight.new_run_token())
    assert len(rec._open) == flight._OPEN_CAP


def test_recorder_runs_do_not_collide_on_batch_numbers():
    """Two runs both dispatching a 'batch 3' keep separate windows:
    each retire closes ITS run's phases (the review-round collision
    regression — mixing runs corrupted one window and dropped the
    other)."""
    rec = flight.FlightRecorder()
    r1, r2 = flight.new_run_token(), flight.new_run_token()
    rec.record("stage", batch=3, t0=1.0, t1=1.1, run=r1)
    rec.record("stage", batch=3, t0=2.0, t1=2.5, run=r2)
    rec.record("h2d", batch=3, t0=1.1, t1=1.2, device="0", run=r1)
    rec.record("h2d", batch=3, t0=2.5, t1=2.6, device="0", run=r2)
    rec.record("kernel", batch=3, t0=1.2, t1=1.25, device="0", run=r1)
    rec.record("kernel", batch=3, t0=2.6, t1=2.65, device="0", run=r2)
    rec.record("retire", batch=3, t0=1.25, t1=1.3, run=r1)
    rec.record("retire", batch=3, t0=2.65, t1=2.7, run=r2)
    wins = [e for e in rec.snapshot() if e["lane"] == "window"]
    assert len(wins) == 2
    # run 2's stage (500 ms) binds; run 1's (100 ms) binds too — and
    # neither window spans the other run's timestamps
    assert all(w["binding"] == "stage" for w in wins)
    assert wins[0]["dur_us"] == pytest.approx(300_000, abs=200)
    assert wins[1]["dur_us"] == pytest.approx(700_000, abs=200)
    assert rec._open == {}


def test_recorder_ring_is_bounded_and_clearable():
    """History ages out oldest-first at the declared channel capacity;
    clear() empties the ring (the per-run artifact hygiene hook)."""
    from spacedrive_tpu import channels

    cap = channels.capacity("ops.pipeline.timeline")
    rec = flight.FlightRecorder()
    for i in range(cap + 10):
        rec.record("stage", batch=i, t0=float(i), t1=float(i) + 0.5)
    snap = rec.snapshot()
    assert len(snap) == cap
    assert snap[0]["batch"] == 10  # oldest 10 aged out
    rec.clear()
    assert rec.snapshot() == []


# -- golden exporter schema -------------------------------------------------

def _synthetic_doc():
    rec = flight.FlightRecorder()
    run = flight.new_run_token()
    t = 10.0
    for batch in (1, 2):
        b = t + batch * 0.1
        rec.record("stage", batch=batch, t0=b, t1=b + 0.03,
                   stream=batch % 2, trace="feed", run=run)
        rec.record("h2d", batch=batch, t0=b + 0.03, t1=b + 0.05,
                   device=str(batch % 2), trace="feed", run=run)
        rec.record("kernel", batch=batch, t0=b + 0.05, t1=b + 0.06,
                   device=str(batch % 2), trace="feed", run=run)
        rec.record("retire", batch=batch, t0=b + 0.06, t1=b + 0.07,
                   trace="feed", run=run)
    spans = [
        {"span": "job/x", "ms": 50.0, "ts_us": 1_000_000,
         "trace": "aa", "id": "1", "ok": True},
        {"span": "job.step", "ms": 10.0, "ts_us": 1_010_000,
         "trace": "aa", "id": "2", "parent": "1", "ok": False,
         "error": "KeyError"},
    ]
    return flight.chrome_trace(spans=spans, timeline=rec.snapshot(),
                               node_name="golden")


def test_chrome_trace_golden_schema():
    doc = _synthetic_doc()
    assert flight.validate_chrome_trace(doc) == []
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    xs = [e for e in evs if e["ph"] == "X"]
    # required keys on every complete event
    for e in xs:
        assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
    # monotone ts over the complete events
    tss = [e["ts"] for e in xs]
    assert tss == sorted(tss)
    # pid mapping: both processes named, every (pid, tid) named
    named_pids = {e["pid"] for e in meta if e["name"] == "process_name"}
    assert named_pids == {flight.PID_SPANS, flight.PID_TIMELINE}
    named_tids = {(e["pid"], e["tid"]) for e in meta
                  if e["name"] == "thread_name"}
    assert {(e["pid"], e["tid"]) for e in xs} <= named_tids
    # the per-device lanes and the bound-attribution lane exist
    lane_names = {e["args"]["name"] for e in meta
                  if e["name"] == "thread_name"}
    assert {"dev0 h2d", "dev1 h2d", "dev0 kernel", "dev1 kernel",
            "retire", "dev0 window", "dev1 window"} <= lane_names
    assert any(n.startswith("stage/w") for n in lane_names)
    # span events carry trace/id lineage in args
    span_evs = [e for e in xs if e["pid"] == flight.PID_SPANS]
    assert {e["name"] for e in span_evs} == {"job/x", "job.step"}
    child = next(e for e in span_evs if e["name"] == "job.step")
    assert child["args"]["parent"] == "1"
    assert child["args"]["error"] == "KeyError"


def test_validator_rejects_seeded_violations():
    """Each schema rule actually fires: missing keys, unsorted ts,
    unnamed pid/tid, unknown ph, bad top level."""
    assert flight.validate_chrome_trace([]) != []
    assert flight.validate_chrome_trace({"traceEvents": "nope"}) != []

    def broken(mutate):
        doc = json.loads(json.dumps(_synthetic_doc()))
        mutate(doc["traceEvents"])
        return flight.validate_chrome_trace(doc)

    xs_at = lambda evs: [i for i, e in enumerate(evs)  # noqa: E731
                         if e["ph"] == "X"]

    probs = broken(lambda evs: evs[xs_at(evs)[0]].pop("dur"))
    assert any("missing keys" in p for p in probs)
    probs = broken(lambda evs: evs.insert(
        len(evs), {"ph": "X", "name": "late", "ts": -5, "dur": 1,
                   "pid": flight.PID_SPANS, "tid": 1}))
    assert any("non-negative" in p for p in probs)
    probs = broken(lambda evs: evs.reverse())
    assert any("sorted" in p for p in probs)
    probs = broken(lambda evs: evs.append(
        {"ph": "X", "name": "orphan", "ts": 10**12, "dur": 1,
         "pid": 99, "tid": 1}))
    assert any("no process_name" in p for p in probs)
    probs = broken(lambda evs: evs.append({"ph": "Q"}))
    assert any("unknown ph" in p for p in probs)


# -- the depth-3 acceptance shape -------------------------------------------

def test_depth3_sim_link_run_exports_valid_trace(tmp_path, monkeypatch):
    """A depth-3 sim-link identify run over two device streams exports
    a schema-valid Chrome trace with per-device stage/H2D/kernel/
    retire lanes and per-batch bound attribution — and the multi-
    stream timeline writes are race-recorder-clean (the autouse
    sanitizer fixture + the armed threadctx recorder assert that
    half)."""
    import jax

    from spacedrive_tpu.ops import overlap
    from tools.overlap_bench import _cheap_kernel

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs the multi-device virtual mesh")
    monkeypatch.setenv("SDTPU_SIM_LINK_GBPS", "0.02")
    flight.RECORDER.clear()
    corpus = overlap.make_sparse_corpus(str(tmp_path), 32 * 8,
                                        120_000, 32)
    _res, stats = overlap.run_overlapped(
        corpus, kernel=_cheap_kernel, depth=3, devices=devs[:2],
        calibrate_every=len(corpus))
    snap = flight.RECORDER.snapshot()

    # every measured batch got all four phases + a window
    measured = len(corpus) - 1
    by_lane = {}
    for ev in snap:
        by_lane.setdefault(ev["lane"], []).append(ev)
    for lane in ("stage", "h2d", "kernel", "retire", "window"):
        assert len(by_lane[lane]) == measured, (
            lane, {k: len(v) for k, v in by_lane.items()})
    # both device streams carried h2d/kernel work
    assert {e["device"] for e in by_lane["h2d"]} == {"0", "1"}
    # all events share the pipeline.run span's trace id
    traces = {e.get("trace") for e in snap}
    assert len(traces) == 1 and None not in traces
    ring = tracing.recent_spans(limit=tracing.span_ring_capacity())
    run_spans = [r for r in ring if r["span"] == "pipeline.run"]
    assert run_spans and run_spans[-1]["trace"] in traces
    # bound attribution: with the simulated link binding, h2d windows
    # dominate; every window names a real component with real phases
    # and the device stream it was bound on
    for win in by_lane["window"]:
        assert win["binding"] in ("stage", "h2d", "kernel")
        assert win["phases_us"]["h2d"] > 0
        assert win["device"] in ("0", "1")
    assert any(w["binding"] == "h2d" for w in by_lane["window"])
    assert {w["device"] for w in by_lane["window"]} == {"0", "1"}

    # and the export is schema-valid with the per-device lanes visible
    doc = flight.chrome_trace(node_name="depth3")
    assert flight.validate_chrome_trace(doc) == []
    lane_names = {e["args"]["name"] for e in doc["traceEvents"]
                  if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"dev0 h2d", "dev1 h2d", "dev0 kernel", "dev1 kernel",
            "retire", "dev0 window", "dev1 window"} <= lane_names
    out = tmp_path / "trace.json"
    out.write_text(json.dumps(doc))
    assert json.loads(out.read_text())["otherData"]["node"] == "depth3"


def test_identify_host_plane_records_timeline(tmp_path):
    """The host hashing planes get the same lanes (scope=identify):
    one stage + one kernel event per cas_ids_for_files chunk."""
    from spacedrive_tpu.ops.staging import cas_ids_for_files

    flight.RECORDER.clear()
    p = tmp_path / "f.bin"
    p.write_bytes(b"y" * 5000)
    ids, errors = cas_ids_for_files([(str(p), 5000)], backend="numpy")
    assert not errors and ids[0]
    snap = [e for e in flight.RECORDER.snapshot()
            if e.get("scope") == "identify"]
    assert [e["lane"] for e in snap] == ["stage", "kernel"]
    assert all(e["device"] == "numpy" for e in snap)
    assert snap[0]["batch"] == snap[1]["batch"]
    doc = flight.chrome_trace(node_name="identify")
    assert flight.validate_chrome_trace(doc) == []


# -- cross-node propagation -------------------------------------------------

def test_traceparent_round_trip_and_malformed():
    assert tracing.traceparent() is None
    assert tracing.parse_traceparent(None) is None
    assert tracing.parse_traceparent("") is None
    assert tracing.parse_traceparent("zz-qq") is None
    assert tracing.parse_traceparent("12345") is None
    assert tracing.parse_traceparent("0-0") is None
    with tracing.span("p2p/probe"):
        tp = tracing.traceparent()
        assert tracing.parse_traceparent(tp) == tracing.current_trace()
    # malformed tp degrades to a local root, never raises
    with tracing.continue_trace("not-a-trace"):
        with tracing.span("p2p/local-root"):
            pass
    rec = tracing.recent_spans(limit=1)[-1]
    assert "parent" not in rec


def test_continue_trace_parents_remote_span():
    """The cross-node contract in one process: a span opened under
    continue_trace(tp) carries the remote trace id and the remote span
    as its parent — and the adoption survives asyncio.to_thread, the
    hand-off job steps actually use."""
    with tracing.span("sync.serve"):
        tp = tracing.traceparent()
    serve_trace, serve_span = tp.split("-")

    def worker_span():
        with tracing.span("job.step"):
            pass

    async def remote_side():
        with tracing.continue_trace(tp):
            with tracing.span("sync.pull"):
                # context flows into to_thread workers too
                await asyncio.to_thread(worker_span)

    asyncio.run(remote_side())
    ring = tracing.recent_spans(limit=20)
    pull = next(r for r in reversed(ring) if r["span"] == "sync.pull")
    assert pull["trace"] == serve_trace
    assert pull["parent"] == serve_span
    step = next(r for r in reversed(ring) if r["span"] == "job.step")
    assert step["trace"] == serve_trace
    assert step["parent"] == pull["id"]


@pytest.mark.skipif(not _has_cryptography(),
                    reason="p2p TCP plane needs the cryptography module")
def test_two_node_sync_pull_shares_one_trace(tmp_path):
    """The tentpole's cross-node acceptance: a write on node A fans
    out over real loopback TCP, and the resulting sync stream is ONE
    trace — A's sync.serve span and B's sync.pull span (plus B's
    ingest spans under it) share a trace id carried in the new_ops
    header's tp field."""
    from spacedrive_tpu.node import Node

    a = Node(str(tmp_path / "a"))
    b = Node(str(tmp_path / "b"))

    async def main():
        from conftest import pair_two_nodes

        lib_a, lib_b = await pair_two_nodes(a, b, "traced")
        tracing.clear_span_ring()
        sync = lib_a.sync
        pub = os.urandom(16)
        ops = sync.shared_create("tag", pub, {"name": "traced-tag"})
        with sync.write_ops(ops) as conn:
            conn.execute("INSERT INTO tag (pub_id, name) VALUES (?, ?)",
                         (pub, "traced-tag"))
        row = None
        for _ in range(200):
            await asyncio.sleep(0.05)
            row = lib_b.db.query_one(
                "SELECT * FROM tag WHERE pub_id = ?", (pub,))
            if row is not None:
                ring = tracing.recent_spans(limit=512)
                if any(r["span"] == "sync.serve" for r in ring) and \
                        any(r["span"] == "sync.pull" for r in ring):
                    break
        assert row is not None and row["name"] == "traced-tag"
        ring = tracing.recent_spans(limit=512)
        serves = [r for r in ring if r["span"] == "sync.serve"]
        pulls = [r for r in ring if r["span"] == "sync.pull"]
        assert serves and pulls, [r["span"] for r in ring]
        serve = serves[-1]
        same_trace = [p for p in pulls if p["trace"] == serve["trace"]]
        assert same_trace, (serve, pulls)
        # the pull span is a CHILD of the serving node's span — the
        # traceparent crossed the wire, not just a coincidental id
        assert any(p.get("parent") == serve["id"] for p in same_trace)
        await a.shutdown()
        await b.shutdown()

    asyncio.run(main())


# -- rspc route + CLI -------------------------------------------------------

def test_node_trace_export_route(tmp_path):
    """node.trace.export serves a schema-valid document over rspc."""
    from spacedrive_tpu.api.router import mount_router
    from spacedrive_tpu.node import Node

    node = Node(str(tmp_path / "data"))
    router = mount_router(node)

    async def main():
        with tracing.span("rpc/warmup"):
            pass
        doc = await router.dispatch("node.trace.export")
        assert flight.validate_chrome_trace(doc) == []
        assert doc["otherData"]["node"] == node.config.name
        assert any(e.get("ph") == "X" for e in doc["traceEvents"])

    asyncio.run(main())


def test_trace_export_cli_self_check(tmp_path):
    """`python -m tools.trace_export --json` is the tier-1 schema
    gate: exit 0 + a valid document on stdout; a corrupted artifact
    fed back through --input exits non-zero naming the violation."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "tools.trace_export", "--json"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert flight.validate_chrome_trace(doc) == []

    # corrupt it: drop a thread_name metadata event
    doc["traceEvents"] = [
        e for e in doc["traceEvents"]
        if not (e.get("ph") == "M" and e.get("name") == "thread_name")]
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(doc))
    out = subprocess.run(
        [sys.executable, "-m", "tools.trace_export", "--input",
         str(bad)],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert "thread_name" in out.stderr


def test_overlap_bench_trace_flag(tmp_path, monkeypatch):
    """`overlap_bench --trace` ships a schema-valid trace artifact
    next to the sweep JSON (exercised in-process via run_sweep + the
    same export path the flag drives)."""
    from tools import overlap_bench

    flight.RECORDER.clear()
    rows = overlap_bench.run_sweep(
        depths=[3], links=[0.125], batch=64, batches=4,
        cheap_kernel=True, calibrate_every=4)
    assert rows and rows[0]["measured_files_per_sec"] > 0
    doc = flight.chrome_trace(node_name="overlap_bench")
    assert flight.validate_chrome_trace(doc) == []
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert any(n.endswith("window") for n in lanes), lanes
