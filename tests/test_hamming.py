"""Dedup analytics: Hamming all-pairs, exact groups, LSH bands."""

import numpy as np

import jax

from spacedrive_tpu.ops.hamming import (
    exact_dup_groups,
    hamming_tile,
    make_sharded_hamming,
    near_dup_pairs,
    phash_bands,
)
from spacedrive_tpu.parallel.mesh import tile_mesh


def _popcount64(v: int) -> int:
    return bin(v).count("1")


def _digests_from_u64(vals):
    a = np.asarray(vals, dtype=np.uint64)
    return np.stack(
        [(a & np.uint64(0xFFFFFFFF)).astype(np.uint32),
         (a >> np.uint64(32)).astype(np.uint32)], axis=1
    )


def test_hamming_tile_matches_popcount():
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 2**63, size=32, dtype=np.uint64)
    d = _digests_from_u64(vals)
    dist = np.asarray(hamming_tile(d, d))
    for i in range(0, 32, 7):
        for j in range(0, 32, 5):
            assert dist[i, j] == _popcount64(int(vals[i]) ^ int(vals[j]))


def test_near_dup_pairs_small_tiles():
    base = 0b1111000011110000
    vals = [base, base ^ 0b1, base ^ 0b11, 0x0F0F0F0F0F0F0F0F]
    d = _digests_from_u64(vals)
    pairs = near_dup_pairs(d, threshold=2, tile=2)  # force multi-tile path
    assert (0, 1) in pairs and (0, 2) in pairs and (1, 2) in pairs
    assert not any(3 in p for p in pairs)


def test_sharded_hamming_matches_single_device():
    mesh = tile_mesh(jax.devices("cpu"))
    r, c = mesh.devices.shape
    N = 8 * r * c
    rng = np.random.default_rng(2)
    d = rng.integers(0, 2**32, size=(N, 2), dtype=np.uint64).astype(np.uint32)
    dist_sharded = np.asarray(make_sharded_hamming(mesh)(d, d))
    dist_local = np.asarray(hamming_tile(d, d))
    assert (dist_sharded == dist_local).all()


def test_exact_dup_groups():
    ids = ["aa", "bb", "aa", "cc", "bb", "aa"]
    g = exact_dup_groups(ids)
    assert g == {"aa": [0, 2, 5], "bb": [1, 4]}


def test_phash_bands_bucket_near_dups():
    rng = np.random.default_rng(3)
    a = rng.integers(0, 2**63, dtype=np.uint64)
    b = int(a) ^ 0b1  # 1-bit neighbor: must share >= 1 of 4 16-bit bands
    far = rng.integers(0, 2**63, dtype=np.uint64)
    d = _digests_from_u64([a, b, far])
    buckets = phash_bands(d, n_bands=4)
    assert any(set(v) >= {0, 1} for v in buckets.values())
